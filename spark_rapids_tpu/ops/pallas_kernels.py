"""Hand-written Pallas TPU kernels for the engine's hottest device ops.

This is the L0 native-kernel layer (SURVEY §1 L0): where the reference
ships CUDA kernels inside cudf (hashing, stream compaction), the TPU
analog is a Pallas kernel compiled for the VPU.  XLA already fuses most
of this engine's elementwise work well; Pallas earns its keep where the
access pattern defeats XLA's fusion heuristics — the Spark-parity
string hash is the canonical case: ~W/4 block-mix steps plus W masked
tail steps over an (N, W) byte matrix, which XLA lowers as ~1.25*W
full-width masked vector passes over HBM, while the kernel below walks
the byte matrix ONCE per VMEM-resident row block.

Kernels are bit-compatible with the jnp reference implementations in
exprs/hashing.py (the same mix functions are imported), and every
kernel has a jnp fallback: pallas.enabled=false, a non-TPU backend, or
an awkward shape routes to the reference path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from spark_rapids_tpu.config import get_conf, register

PALLAS_ENABLED = register(
    "spark.rapids.tpu.sql.pallas.enabled", True,
    "Use hand-written Pallas TPU kernels for hot ops (string murmur3) "
    "instead of the XLA-fused jnp reference implementations.  Only "
    "takes effect on a TPU backend; other backends always use jnp.  "
    "Read at program-compile time: changing it mid-session does not "
    "affect pipelines already in the compile cache.")

_BLOCK_N = 1024  # rows per grid step: (8, 128) row tiles; W*1KB << VMEM
#: widest string column the kernel accepts: the per-grid-step working
#: set is ~5KB per byte of width (chars tile + widened u32 copy), so
#: wider columns would overrun the kernel's VMEM budget — they take
#: the jnp path instead
_MAX_WIDTH = 128


def pallas_available() -> bool:
    if not get_conf().get(PALLAS_ENABLED):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _hash_string_kernel(chars_ref, lengths_ref, seed_ref, out_ref):
    """One (B/128, 128, W) tile of Spark hashUnsafeBytes: aligned
    4-byte little-endian blocks through mixK1/mixH1, then each tail
    byte sign-extended — identical math to
    exprs.hashing.hash_string_bytes.

    Rows are laid out (group, byte, lane) — the byte index is a SUBLANE
    coordinate, so plane selection chars[:, j, :] is a cheap sublane
    slice and every mix step is a full (groups, 128) vector op on the
    VPU.  (Byte-in-lane layouts force a cross-lane relayout per plane —
    measured ~8.5MB of scoped VMEM on v5e.)"""
    from spark_rapids_tpu.exprs.hashing import _fmix, _mix_h1, _mix_k1

    chars = chars_ref[:]  # (G, W, 128) uint8, VMEM-resident
    lengths = lengths_ref[:].astype(jnp.int32)  # (G, 128)
    h1 = seed_ref[:].astype(jnp.uint32)  # (G, 128)
    s_rows, width, lanes = chars.shape
    four = jnp.asarray(4, jnp.int32)
    aligned = lengths - jnp.remainder(lengths, four)
    # widen THEN mask: Mosaic's u8 widening sign-extends bytes >= 128
    c32 = (chars.astype(jnp.int32)
           & jnp.asarray(0xFF, jnp.int32)).astype(jnp.uint32)
    nblocks = (width + 3) // 4
    # little-endian word assembly via MULTIPLIES: Mosaic miscompiles
    # vector shifts of byte-widened uint32 planes (verified on v5e),
    # while multiplies by 2^8k are exact
    scales = (jnp.asarray(0x100, jnp.uint32),
              jnp.asarray(0x10000, jnp.uint32),
              jnp.asarray(0x1000000, jnp.uint32))
    for b in range(nblocks):
        j = b * 4

        def byte(off):
            if j + off < width:
                return c32[:, j + off, :]
            return jnp.zeros((s_rows, lanes), jnp.uint32)

        word = (byte(0) + byte(1) * scales[0] + byte(2) * scales[1]
                + byte(3) * scales[2])
        in_block = jnp.asarray(j + 4, jnp.int32) <= aligned
        h1 = jnp.where(in_block, _mix_h1(h1, _mix_k1(word)), h1)
    c128 = jnp.asarray(128, jnp.int32)
    c256 = jnp.asarray(256, jnp.int32)
    for j in range(width):
        jj = jnp.asarray(j, jnp.int32)
        is_tail = (jj >= aligned) & (jj < lengths)
        b32 = c32[:, j, :].astype(jnp.int32)
        signed = jnp.where(b32 >= c128, b32 - c256, b32)
        h1 = jnp.where(is_tail,
                       _mix_h1(h1, _mix_k1(signed.astype(jnp.uint32))),
                       h1)
    out_ref[:] = _fmix(h1, lengths.astype(jnp.uint32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_hash_string(chars: jax.Array, lengths: jax.Array,
                       seeds: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """Spark murmur3 of a fixed-width string column via a Pallas grid
    over row blocks.  chars (N, W) uint8; lengths/seeds (N,); -> (N,)
    uint32.  Caller guarantees N % _BLOCK_N == 0
    (maybe_pallas_hash_string pads off-multiple shapes up first)."""
    from jax.experimental import pallas as pl

    n, width = chars.shape
    sub = _BLOCK_N // 128
    grid = (n // _BLOCK_N,)

    def blk3(i):
        # under jax_enable_x64 a literal 0 would trace as i64, which
        # Mosaic's index-map legalization rejects — derive 0 from i
        return (i, i * 0, i * 0)

    def blk2(i):
        return (i, i * 0)

    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        # the default VMEM budget (16MB) plus XLA's scoped overhead
        # overruns the 16MB space; the kernel's working set per grid
        # step is tiny, so cap it well below
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=2 * 1024 * 1024)
    out = pl.pallas_call(
        _hash_string_kernel,
        out_shape=jax.ShapeDtypeStruct((n // 128, 128), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((sub, width, 128), blk3),
            pl.BlockSpec((sub, 128), blk2),
            pl.BlockSpec((sub, 128), blk2),
        ],
        out_specs=pl.BlockSpec((sub, 128), blk2),
        interpret=interpret,
        **kwargs,
    )(chars.reshape(n // 128, 128, width).transpose(0, 2, 1),
      lengths.reshape(n // 128, 128).astype(jnp.int32),
      seeds.reshape(n // 128, 128).astype(jnp.uint32))
    return out.reshape(n)


def maybe_pallas_hash_string(chars, lengths, seeds):
    """Route to the Pallas kernel when available and the shape fits;
    None means 'use the jnp reference path'.

    Off-multiple batches pad into WIDE kernel blocks: any capacity
    that is not a _BLOCK_N multiple — ragged scan tails and small
    partials below one block, and the 3*pow2/2 occupancy buckets above
    it (1536, 3·2^k for k < 10: capacity.policy=pow2x3,
    docs/occupancy.md) — pads its rows up to the next block multiple
    and slices the result back, instead of falling to the
    width-specialized jnp path.  Shapes are static (capacities come
    from pad_capacity), so the pad/slice fuse into the surrounding
    program; the win is program-count, not FLOPs — every distinct
    jnp-path shape used to mint its own ~1.25*W-pass lowering per
    (capacity, width), while the padded form shares the one
    grid-blocked kernel per width with every batch, including the
    multi-batch blocks a TpuCoalesceBatchesExec feeds in.  The grid
    covers ceil(n / _BLOCK_N) row blocks — sized to the live region of
    the padded matrix — and the pad tail is masked by construction:
    padding rows hash garbage nobody reads (length 0 -> fmix of an
    empty string); the slice drops them inside the same program."""
    n, width = chars.shape
    if width > _MAX_WIDTH or not pallas_available():
        return None
    if n % _BLOCK_N != 0:
        pad = -n % _BLOCK_N
        chars = jnp.concatenate(
            [chars, jnp.zeros((pad, width), chars.dtype)], axis=0)
        lengths = jnp.concatenate(
            [lengths, jnp.zeros((pad,), lengths.dtype)], axis=0)
        seeds = jnp.concatenate(
            [seeds, jnp.zeros((pad,), seeds.dtype)], axis=0)
        return pallas_hash_string(chars, lengths, seeds)[:n]
    return pallas_hash_string(chars, lengths, seeds)
