"""Sort-based segmented group-by aggregation.

TPU counterpart of cudf's `Table.groupBy(...).aggregate(...)` as used by
GpuHashAggregateExec (ref: sql-plugin/.../aggregate.scala:240,366).  cudf
uses a device hash table; the XLA-idiomatic design is sort-based:

    sort rows by key -> mark segment starts -> segment_{sum,min,max}

which is one fused program of static shape: the output batch has the same
capacity as the input with `num_groups` live rows (traced scalar).
Aggregations are expressed as (update, merge) pairs the way Spark
aggregate modes are (Partial -> PartialMerge/Final), so multi-batch and
post-shuffle merging reuse the same kernels on the partial-result columns.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    AnyColumn,
    Column,
    StringColumn,
    pad_capacity,
)
from spark_rapids_tpu.ops.sort import SortOrder, sort_permutation


def _keys_equal_adjacent(col: AnyColumn) -> jax.Array:
    """row i equal to row i-1 under SQL grouping (NULL == NULL)."""
    if isinstance(col, StringColumn):
        chars_eq = jnp.all(col.chars == jnp.roll(col.chars, 1, axis=0), axis=1)
        len_eq = col.lengths == jnp.roll(col.lengths, 1)
        data_eq = chars_eq & len_eq
    else:
        data_eq = col.data == jnp.roll(col.data, 1)
        if isinstance(col.dtype, (T.FloatType, T.DoubleType)):
            # NaN == NaN for grouping; -0.0 groups with 0.0 via pre-normalize
            both_nan = jnp.isnan(col.data) & jnp.isnan(jnp.roll(col.data, 1))
            data_eq = data_eq | both_nan
    valid_eq = col.validity == jnp.roll(col.validity, 1)
    null_pair = (~col.validity) & (~jnp.roll(col.validity, 1))
    return valid_eq & (data_eq | null_pair)


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregation over a value ordinal.  `op` in
    {sum, count, count_star, min, max, first, last}; avg is planned as
    sum+count and finalized by the exec (the way the reference splits
    GpuAverage into update/merge expressions, AggregateFunctions.scala)."""

    op: str
    ordinal: int  # ignored for count_star
    out_dtype: Optional[T.DataType] = None


def _sum_dtype(dt: T.DataType) -> T.DataType:
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        return T.DOUBLE
    if isinstance(dt, T.DecimalType):
        return T.DecimalType(min(dt.precision + 10, T.DecimalType.MAX_PRECISION),
                             dt.scale)
    return T.LONG


def agg_output_dtype(spec: AggSpec, value_dtype: Optional[T.DataType]
                     ) -> T.DataType:
    if spec.out_dtype is not None:
        return spec.out_dtype
    if spec.op in ("count", "count_star"):
        return T.LONG
    if spec.op == "sum":
        assert value_dtype is not None
        return _sum_dtype(value_dtype)
    assert value_dtype is not None
    return value_dtype


#: widest combined (dict ++ NULL) key domain the coded fast path takes;
#: past this the padded segment arrays outgrow the win over sorting.
#: 2^17 keeps the segment matrix a few MB (trivial next to a
#: multi-hundred-ms device lexsort of the input rows) while admitting
#: e.g. a (store x item) TPC-DS grouping of ~18K combined domain.
MAX_CODED_DOMAIN = 1 << 17


def _coded_key_domains(key_cols: Sequence[AnyColumn]) -> Optional[list[int]]:
    """Per-key dictionary sizes when EVERY key column carries the wire
    dict sidecar (codes + device dictionary) and the combined domain is
    small, else None.  Static decision: dict sizes are array shapes.
    Both string ("sdict") and fixed-width numeric ("dict") sidecars
    qualify."""
    ks: list[int] = []
    total = 1
    for kc in key_cols:
        if getattr(kc, "codes", None) is None:
            return None
        if isinstance(kc, StringColumn):
            padded = int(kc.dict_chars.shape[0])
        else:
            if isinstance(kc.dtype, (T.FloatType, T.DoubleType)):
                # a Parquet dictionary may hold -0.0 and 0.0 (or two
                # NaN payloads) as distinct entries; raw codes would
                # split groups SQL merges.  Float keys take the sort
                # path, whose keys normalize both.
                return None
            padded = int(kc.dict_values.shape[0])
        # the wire pads the dictionary to its pow2 capacity bucket; a
        # tight (16-bucketed) bound on the true entry count rides in
        # dict_len — using the padded capacity would overestimate the
        # combined domain (compounding per key), spuriously exceeding
        # MAX_CODED_DOMAIN and padding the segment matrix
        k = kc.dict_len if kc.dict_len is not None else padded
        ks.append(k)
        total *= k + 1  # +1: the NULL group rides past the dictionary
        if total > MAX_CODED_DOMAIN:
            return None
    return ks


def _coded_groupby(batch: ColumnarBatch, key_ordinals: Sequence[int],
                   ks: list[int], aggs: Sequence[AggSpec],
                   out_schema: T.Schema,
                   live_mask=None) -> ColumnarBatch:
    """Sort-free group-by over dictionary codes (the analog of cudf's
    hash groupby for low-cardinality keys, ref: aggregate.scala:240-430):
    each row's combined code IS its dense group id, so the whole
    aggregation is segment reductions over a static code domain — no
    O(n log n) lexsort of the key bytes.

    Kernel-budget design (the tunneled backend charges ~10ms per
    non-fusable kernel launch once any D2H fetch has happened, so
    LAUNCH COUNT, not FLOPs, is the cost): every sum/count-family
    aggregate packs into ONE (rows, m) matrix reduced by a single N-D
    segment_sum; compaction is a cumsum + one gather (no scatters);
    only min/max/first/last fall back to per-spec segment ops.  Output
    is compact (capacity = padded domain size), orders of magnitude
    below the input bucket."""
    from spark_rapids_tpu.columnar.column import MIN_CAPACITY

    cap = batch.capacity
    live = batch.row_mask()
    if live_mask is not None:
        live = live & live_mask
    key_cols = [batch.columns[o] for o in key_ordinals]

    K = 1
    for k in ks:
        K *= k + 1
    seg = jnp.zeros((cap,), jnp.int32)
    for kc, k in zip(key_cols, ks):
        pid = jnp.where(kc.validity, jnp.clip(kc.codes.astype(jnp.int32),
                                              0, k - 1), k)
        seg = seg * (k + 1) + pid
    seg = jnp.where(live, seg, K)  # dead rows drop out of segment ops

    # pack the sum/count family into one f64 matrix (and one i64 matrix
    # for integer-typed sums, whose wrap-on-overflow semantics f64
    # cannot reproduce); slot 0 = live-ones: count_star AND occupancy
    f64_cols: list = [jnp.where(live, 1.0, 0.0)]
    i64_cols: list = []
    slots: list = []  # per spec: ("f64"/"i64", value_slot, nvalid_slot)
    for spec in aggs:
        if spec.op == "count_star":
            slots.append(("star",))
            continue
        vcol = batch.columns[spec.ordinal]
        valid = vcol.validity & live
        if spec.op == "count":
            f64_cols.append(valid.astype(jnp.float64))
            slots.append(("count", len(f64_cols) - 1))
            continue
        if spec.op == "sum" and isinstance(vcol, Column):
            out_dtype = agg_output_dtype(spec, vcol.dtype)
            phys = np.dtype(T.to_numpy_dtype(out_dtype))
            f64_cols.append(valid.astype(jnp.float64))
            nv = len(f64_cols) - 1
            if phys.kind == "f":
                f64_cols.append(jnp.where(
                    valid, vcol.data.astype(jnp.float64), 0.0))
                slots.append(("f64", len(f64_cols) - 1, nv, out_dtype))
            else:
                i64_cols.append(jnp.where(
                    valid, vcol.data.astype(jnp.int64),
                    jnp.asarray(0, jnp.int64)))
                slots.append(("i64", len(i64_cols) - 1, nv, out_dtype))
            continue
        slots.append(("segop",))

    S = jax.ops.segment_sum(jnp.stack(f64_cols, axis=1), seg,
                            num_segments=K)
    Si = (jax.ops.segment_sum(jnp.stack(i64_cols, axis=1), seg,
                              num_segments=K)
          if i64_cols else None)

    occ = S[:, 0] > 0.0
    ranks = jnp.cumsum(occ.astype(jnp.int32))
    num_groups = ranks[-1]
    out_cap = max(MIN_CAPACITY, pad_capacity(K))
    # inv[g] = segment id of the g-th occupied segment (binary search of
    # the rank prefix — one gather-free kernel, no scatter)
    inv = jnp.clip(
        jnp.searchsorted(ranks, jnp.arange(out_cap, dtype=jnp.int32) + 1,
                         side="left").astype(jnp.int32), 0, K - 1)
    group_live = jnp.arange(out_cap, dtype=jnp.int32) < num_groups
    Sc = jnp.take(S, inv, axis=0)
    Sic = jnp.take(Si, inv, axis=0) if Si is not None else None

    need_segop = any(s[0] == "segop" for s in slots)
    if need_segop:
        dest = jnp.where(occ, ranks - 1, out_cap)
        row_seg = jnp.take(
            jnp.concatenate([dest, jnp.full((1,), out_cap, jnp.int32)]),
            jnp.minimum(seg, K))

    # keys: decode each compact slot's segment id back to its dict entry
    out_cols: list[AnyColumn] = []
    key_ids: list[jax.Array] = []
    sid = inv
    for k in reversed(ks):
        key_ids.append(sid % (k + 1))
        sid = sid // (k + 1)
    key_ids.reverse()
    for kc, k, kid in zip(key_cols, ks, key_ids):
        valid_g = (kid < k) & group_live
        if isinstance(kc, StringColumn):
            dchars = jnp.concatenate(
                [kc.dict_chars,
                 jnp.zeros((1, kc.dict_chars.shape[1]), jnp.uint8)])
            dlens = jnp.concatenate(
                [kc.dict_lens.astype(jnp.int32),
                 jnp.zeros((1,), jnp.int32)])
            chars = jnp.take(dchars, kid, axis=0) \
                * valid_g[:, None].astype(jnp.uint8)
            lengths = jnp.take(dlens, kid) * valid_g.astype(jnp.int32)
            out_cols.append(StringColumn(chars, lengths, valid_g))
        else:
            dvals = jnp.concatenate(
                [kc.dict_values,
                 jnp.zeros((1,), kc.dict_values.dtype)])
            out_cols.append(Column(jnp.take(dvals, kid), valid_g,
                                   kc.dtype))

    for spec, slot in zip(aggs, slots):
        if slot[0] == "star":
            out_cols.append(Column(Sc[:, 0].astype(jnp.int64),
                                   group_live, T.LONG))
        elif slot[0] == "count":
            out_cols.append(Column(Sc[:, slot[1]].astype(jnp.int64),
                                   group_live, T.LONG))
        elif slot[0] == "f64":
            _, vs, nv, out_dtype = slot
            out_cols.append(Column(
                Sc[:, vs].astype(T.to_numpy_dtype(out_dtype)),
                group_live & (Sc[:, nv] > 0), out_dtype))
        elif slot[0] == "i64":
            _, vs, nv, out_dtype = slot
            out_cols.append(Column(
                Sic[:, vs].astype(T.to_numpy_dtype(out_dtype)),
                group_live & (Sc[:, nv] > 0), out_dtype))
        else:
            out_cols.append(_eval_agg(spec, batch, row_seg, live,
                                      group_live, out_cap, cap))
    assert len(out_schema) == len(key_cols) + len(aggs)
    return ColumnarBatch(out_cols, num_groups, out_schema)


def groupby_aggregate(batch: ColumnarBatch, key_ordinals: Sequence[int],
                      aggs: Sequence[AggSpec],
                      out_schema: T.Schema,
                      live_mask=None) -> ColumnarBatch:
    """One-batch group-by.  Output columns = keys ++ aggs, prefix-compact
    with num_groups live rows.  Traceable (fixed shapes throughout).
    `live_mask` further restricts the live rows (a fused WHERE: the
    aggregate masks filtered rows instead of paying a compaction)."""
    ks = _coded_key_domains([batch.columns[o] for o in key_ordinals])
    if ks is not None:
        return _coded_groupby(batch, key_ordinals, ks, aggs, out_schema,
                              live_mask)
    cap = batch.capacity
    live = batch.row_mask()
    if live_mask is not None:
        live = live & live_mask
    orders = [SortOrder(o) for o in key_ordinals]
    perm = sort_permutation(batch, orders, live=live)
    sorted_batch = batch.gather(perm, batch.num_rows)
    live_sorted = jnp.take(live, perm)

    key_cols = [sorted_batch.columns[o] for o in key_ordinals]
    same_as_prev = jnp.ones((cap,), bool)
    for kc in key_cols:
        same_as_prev = same_as_prev & _keys_equal_adjacent(kc)
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_start = live_sorted & ((idx == 0) | ~same_as_prev)
    seg_id = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    # dead rows -> out-of-range segment (dropped by segment_* ops)
    seg_id = jnp.where(live_sorted, seg_id, cap)
    num_groups = jnp.sum(is_start.astype(jnp.int32))

    out_cols: list[AnyColumn] = []
    # keys: value at each segment start, scattered to [0, num_groups)
    start_dest = jnp.where(is_start, seg_id, cap)
    group_live = idx < num_groups
    for kc in key_cols:
        if isinstance(kc, StringColumn):
            chars = jnp.zeros_like(kc.chars).at[start_dest].set(
                kc.chars, mode="drop")
            lengths = jnp.zeros_like(kc.lengths).at[start_dest].set(
                kc.lengths, mode="drop")
            valid = jnp.zeros_like(kc.validity).at[start_dest].set(
                kc.validity, mode="drop") & group_live
            out_cols.append(StringColumn(chars, lengths, valid))
        else:
            data = jnp.zeros_like(kc.data).at[start_dest].set(
                kc.data, mode="drop")
            valid = jnp.zeros_like(kc.validity).at[start_dest].set(
                kc.validity, mode="drop") & group_live
            out_cols.append(Column(data, valid, kc.dtype))

    for spec in aggs:
        out_cols.append(_eval_agg(spec, sorted_batch, seg_id, live_sorted,
                                  group_live, cap, cap))
    n_keys = len(key_cols)
    assert len(out_schema) == n_keys + len(aggs)
    return ColumnarBatch(out_cols, num_groups, out_schema)


def _minmax_sentinel(phys, op: str):
    """Identity element masking NULL slots for min/max reductions."""
    if jnp.issubdtype(phys, jnp.floating):
        return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, phys)
    info = jnp.iinfo(phys)
    return jnp.asarray(info.max if op == "min" else info.min, phys)


def _firstlast_pos(valid: jax.Array, op: str, cap: int) -> jax.Array:
    """Per-row candidate position for first/last non-null selection."""
    idx = jnp.arange(cap, dtype=jnp.int32)
    return jnp.where(valid, idx, cap if op == "first" else -1)


def _eval_agg(spec: AggSpec, sorted_batch: ColumnarBatch, seg_id: jax.Array,
              live_sorted: jax.Array, group_live: jax.Array,
              num_segments: int, row_cap: int) -> Column:
    """One aggregation as segment reductions.  `seg_id[row_cap]` maps
    each row to its output segment in [0, num_segments) (out-of-range =
    dropped); output arrays have length `num_segments`.  The sort path
    passes num_segments == row_cap; the coded path a compact domain."""
    if spec.op == "count_star":
        ones = live_sorted.astype(jnp.int64)
        counts = jax.ops.segment_sum(ones, seg_id,
                                     num_segments=num_segments)
        return Column(counts, group_live, T.LONG)

    vcol = sorted_batch.columns[spec.ordinal]
    valid = vcol.validity & live_sorted
    nvalid = jax.ops.segment_sum(valid.astype(jnp.int64), seg_id,
                                 num_segments=num_segments)

    if spec.op == "count":  # validity-only: works for ANY column kind
        return Column(nvalid, group_live, T.LONG)
    assert isinstance(vcol, Column), f"agg over {vcol.dtype} unsupported"

    out_dtype = agg_output_dtype(spec, vcol.dtype)
    phys = T.to_numpy_dtype(out_dtype)
    if spec.op == "sum":
        vals = jnp.where(valid, vcol.data.astype(phys), jnp.asarray(0, phys))
        sums = jax.ops.segment_sum(vals, seg_id, num_segments=num_segments)
        return Column(sums, group_live & (nvalid > 0), out_dtype)
    if spec.op in ("min", "max"):
        vals = jnp.where(valid, vcol.data.astype(phys),
                         _minmax_sentinel(phys, spec.op))
        f = jax.ops.segment_min if spec.op == "min" else jax.ops.segment_max
        if jnp.issubdtype(jnp.dtype(phys), jnp.floating):
            # Spark float total order: NaN is GREATEST.  segment_max's
            # IEEE NaN propagation already realizes that; min must
            # instead IGNORE NaN unless the whole group is NaN (then
            # the answer is NaN, not NULL).
            isnan = valid & jnp.isnan(vcol.data)
            if spec.op == "min":
                vals = jnp.where(isnan, _minmax_sentinel(phys, "min"),
                                 vals)
            n_nan = jax.ops.segment_sum(isnan.astype(jnp.int64), seg_id,
                                        num_segments=num_segments)
            out = f(vals, seg_id, num_segments=num_segments)
            if spec.op == "min":
                out = jnp.where(n_nan == nvalid,
                                jnp.asarray(jnp.nan, phys), out)
            return Column(out, group_live & (nvalid > 0), out_dtype)
        out = f(vals, seg_id, num_segments=num_segments)
        return Column(out, group_live & (nvalid > 0), out_dtype)
    if spec.op in ("first", "last"):
        # first/last non-null within the segment, in sorted-batch order
        pos = _firstlast_pos(valid, spec.op, row_cap)
        f = jax.ops.segment_min if spec.op == "first" else jax.ops.segment_max
        sel = f(pos, seg_id, num_segments=num_segments)
        sel_clipped = jnp.clip(sel, 0, row_cap - 1)
        out = jnp.take(vcol.data, sel_clipped).astype(phys)
        return Column(out, group_live & (nvalid > 0), out_dtype)
    if spec.op in ("first_any", "last_any"):
        # Spark default (ignoreNulls=false): first/last LIVE row of the
        # segment regardless of validity; a NULL first value stays NULL
        base = "first" if spec.op == "first_any" else "last"
        pos = _firstlast_pos(live_sorted, base, row_cap)
        f = jax.ops.segment_min if base == "first" else jax.ops.segment_max
        sel = f(pos, seg_id, num_segments=num_segments)
        sel_clipped = jnp.clip(sel, 0, row_cap - 1)
        out = jnp.take(vcol.data, sel_clipped).astype(phys)
        sel_valid = jnp.take(vcol.validity, sel_clipped)
        return Column(out, group_live & sel_valid, out_dtype)
    raise ValueError(f"unknown agg op {spec.op}")


def reduce_aggregate(batch: ColumnarBatch, aggs: Sequence[AggSpec],
                     out_schema: T.Schema,
                     live_mask=None) -> ColumnarBatch:
    """Grand aggregate (no keys): one output row.  Separate path because
    there is no sort: plain masked reductions."""
    cap = batch.capacity
    live = batch.row_mask()
    if live_mask is not None:
        live = live & live_mask
    out_cols: list[AnyColumn] = []
    one_live = jnp.arange(cap, dtype=jnp.int32) < 1
    for spec in aggs:
        if spec.op == "count_star":
            n = jnp.sum(live.astype(jnp.int64))
            out_cols.append(Column(jnp.zeros(cap, jnp.int64).at[0].set(n),
                                   one_live, T.LONG))
            continue
        vcol = batch.columns[spec.ordinal]
        valid = vcol.validity & live
        nvalid = jnp.sum(valid.astype(jnp.int64))
        if spec.op == "count":  # validity-only: any column kind
            out_cols.append(Column(
                jnp.zeros(cap, jnp.int64).at[0].set(nvalid), one_live, T.LONG))
            continue
        assert isinstance(vcol, Column)
        out_dtype = agg_output_dtype(spec, vcol.dtype)
        phys = T.to_numpy_dtype(out_dtype)
        if spec.op == "sum":
            s = jnp.sum(jnp.where(valid, vcol.data.astype(phys),
                                  jnp.asarray(0, phys)))
        elif spec.op in ("min", "max"):
            vals = jnp.where(valid, vcol.data.astype(phys),
                             _minmax_sentinel(phys, spec.op))
            if jnp.issubdtype(jnp.dtype(phys), jnp.floating):
                # Spark float total order (see _eval_agg): max keeps
                # IEEE NaN propagation (NaN greatest); min ignores NaN
                # unless every valid value is NaN
                isnan = valid & jnp.isnan(vcol.data)
                if spec.op == "min":
                    vals = jnp.where(
                        isnan, _minmax_sentinel(phys, "min"), vals)
                    s = jnp.where(jnp.sum(isnan.astype(jnp.int64))
                                  == nvalid,
                                  jnp.asarray(jnp.nan, phys),
                                  jnp.min(vals))
                else:
                    s = jnp.max(vals)
            else:
                s = jnp.min(vals) if spec.op == "min" else jnp.max(vals)
        elif spec.op in ("first", "last"):
            pos = _firstlast_pos(valid, spec.op, cap)
            sel = jnp.min(pos) if spec.op == "first" else jnp.max(pos)
            s = jnp.take(vcol.data, jnp.clip(sel, 0, cap - 1)).astype(phys)
        elif spec.op in ("first_any", "last_any"):
            base = "first" if spec.op == "first_any" else "last"
            pos = _firstlast_pos(live, base, cap)
            sel = jnp.min(pos) if base == "first" else jnp.max(pos)
            sel_c = jnp.clip(sel, 0, cap - 1)
            s = jnp.take(vcol.data, sel_c).astype(phys)
            sel_ok = jnp.take(vcol.validity, sel_c)
            data = jnp.zeros(cap, phys).at[0].set(s)
            out_cols.append(Column(
                data, one_live & sel_ok & (jnp.sum(live) > 0), out_dtype))
            continue
        else:
            raise ValueError(f"unknown agg op {spec.op}")
        data = jnp.zeros(cap, phys).at[0].set(s.astype(phys))
        out_cols.append(Column(data, one_live & (nvalid > 0), out_dtype))
    return ColumnarBatch(out_cols, 1, out_schema)
