"""Sort-based segmented group-by aggregation.

TPU counterpart of cudf's `Table.groupBy(...).aggregate(...)` as used by
GpuHashAggregateExec (ref: sql-plugin/.../aggregate.scala:240,366).  cudf
uses a device hash table; the XLA-idiomatic design is sort-based:

    sort rows by key -> mark segment starts -> segment_{sum,min,max}

which is one fused program of static shape: the output batch has the same
capacity as the input with `num_groups` live rows (traced scalar).
Aggregations are expressed as (update, merge) pairs the way Spark
aggregate modes are (Partial -> PartialMerge/Final), so multi-batch and
post-shuffle merging reuse the same kernels on the partial-result columns.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn
from spark_rapids_tpu.ops.sort import SortOrder, sort_permutation


def _keys_equal_adjacent(col: AnyColumn) -> jax.Array:
    """row i equal to row i-1 under SQL grouping (NULL == NULL)."""
    if isinstance(col, StringColumn):
        chars_eq = jnp.all(col.chars == jnp.roll(col.chars, 1, axis=0), axis=1)
        len_eq = col.lengths == jnp.roll(col.lengths, 1)
        data_eq = chars_eq & len_eq
    else:
        data_eq = col.data == jnp.roll(col.data, 1)
        if isinstance(col.dtype, (T.FloatType, T.DoubleType)):
            # NaN == NaN for grouping; -0.0 groups with 0.0 via pre-normalize
            both_nan = jnp.isnan(col.data) & jnp.isnan(jnp.roll(col.data, 1))
            data_eq = data_eq | both_nan
    valid_eq = col.validity == jnp.roll(col.validity, 1)
    null_pair = (~col.validity) & (~jnp.roll(col.validity, 1))
    return valid_eq & (data_eq | null_pair)


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregation over a value ordinal.  `op` in
    {sum, count, count_star, min, max, first, last}; avg is planned as
    sum+count and finalized by the exec (the way the reference splits
    GpuAverage into update/merge expressions, AggregateFunctions.scala)."""

    op: str
    ordinal: int  # ignored for count_star
    out_dtype: Optional[T.DataType] = None


def _sum_dtype(dt: T.DataType) -> T.DataType:
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        return T.DOUBLE
    if isinstance(dt, T.DecimalType):
        return T.DecimalType(min(dt.precision + 10, T.DecimalType.MAX_PRECISION),
                             dt.scale)
    return T.LONG


def agg_output_dtype(spec: AggSpec, value_dtype: Optional[T.DataType]
                     ) -> T.DataType:
    if spec.out_dtype is not None:
        return spec.out_dtype
    if spec.op in ("count", "count_star"):
        return T.LONG
    if spec.op == "sum":
        assert value_dtype is not None
        return _sum_dtype(value_dtype)
    assert value_dtype is not None
    return value_dtype


def groupby_aggregate(batch: ColumnarBatch, key_ordinals: Sequence[int],
                      aggs: Sequence[AggSpec],
                      out_schema: T.Schema) -> ColumnarBatch:
    """One-batch group-by.  Output columns = keys ++ aggs, prefix-compact
    with num_groups live rows.  Traceable (fixed shapes throughout)."""
    cap = batch.capacity
    live = batch.row_mask()
    orders = [SortOrder(o) for o in key_ordinals]
    perm = sort_permutation(batch, orders)
    sorted_batch = batch.gather(perm, batch.num_rows)
    live_sorted = jnp.take(live, perm)

    key_cols = [sorted_batch.columns[o] for o in key_ordinals]
    same_as_prev = jnp.ones((cap,), bool)
    for kc in key_cols:
        same_as_prev = same_as_prev & _keys_equal_adjacent(kc)
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_start = live_sorted & ((idx == 0) | ~same_as_prev)
    seg_id = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    # dead rows -> out-of-range segment (dropped by segment_* ops)
    seg_id = jnp.where(live_sorted, seg_id, cap)
    num_groups = jnp.sum(is_start.astype(jnp.int32))

    out_cols: list[AnyColumn] = []
    # keys: value at each segment start, scattered to [0, num_groups)
    start_dest = jnp.where(is_start, seg_id, cap)
    group_live = idx < num_groups
    for kc in key_cols:
        if isinstance(kc, StringColumn):
            chars = jnp.zeros_like(kc.chars).at[start_dest].set(
                kc.chars, mode="drop")
            lengths = jnp.zeros_like(kc.lengths).at[start_dest].set(
                kc.lengths, mode="drop")
            valid = jnp.zeros_like(kc.validity).at[start_dest].set(
                kc.validity, mode="drop") & group_live
            out_cols.append(StringColumn(chars, lengths, valid))
        else:
            data = jnp.zeros_like(kc.data).at[start_dest].set(
                kc.data, mode="drop")
            valid = jnp.zeros_like(kc.validity).at[start_dest].set(
                kc.validity, mode="drop") & group_live
            out_cols.append(Column(data, valid, kc.dtype))

    for spec in aggs:
        out_cols.append(_eval_agg(spec, sorted_batch, seg_id, live_sorted,
                                  group_live, cap))
    n_keys = len(key_cols)
    assert len(out_schema) == n_keys + len(aggs)
    return ColumnarBatch(out_cols, num_groups, out_schema)


def _minmax_sentinel(phys, op: str):
    """Identity element masking NULL slots for min/max reductions."""
    if jnp.issubdtype(phys, jnp.floating):
        return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, phys)
    info = jnp.iinfo(phys)
    return jnp.asarray(info.max if op == "min" else info.min, phys)


def _firstlast_pos(valid: jax.Array, op: str, cap: int) -> jax.Array:
    """Per-row candidate position for first/last non-null selection."""
    idx = jnp.arange(cap, dtype=jnp.int32)
    return jnp.where(valid, idx, cap if op == "first" else -1)


def _eval_agg(spec: AggSpec, sorted_batch: ColumnarBatch, seg_id: jax.Array,
              live_sorted: jax.Array, group_live: jax.Array,
              cap: int) -> Column:
    if spec.op == "count_star":
        ones = live_sorted.astype(jnp.int64)
        counts = jax.ops.segment_sum(ones, seg_id, num_segments=cap)
        return Column(counts, group_live, T.LONG)

    vcol = sorted_batch.columns[spec.ordinal]
    valid = vcol.validity & live_sorted
    nvalid = jax.ops.segment_sum(valid.astype(jnp.int64), seg_id,
                                 num_segments=cap)

    if spec.op == "count":  # validity-only: works for ANY column kind
        return Column(nvalid, group_live, T.LONG)
    assert isinstance(vcol, Column), f"agg over {vcol.dtype} unsupported"

    out_dtype = agg_output_dtype(spec, vcol.dtype)
    phys = T.to_numpy_dtype(out_dtype)
    if spec.op == "sum":
        vals = jnp.where(valid, vcol.data.astype(phys), jnp.asarray(0, phys))
        sums = jax.ops.segment_sum(vals, seg_id, num_segments=cap)
        return Column(sums, group_live & (nvalid > 0), out_dtype)
    if spec.op in ("min", "max"):
        vals = jnp.where(valid, vcol.data.astype(phys),
                         _minmax_sentinel(phys, spec.op))
        f = jax.ops.segment_min if spec.op == "min" else jax.ops.segment_max
        if jnp.issubdtype(jnp.dtype(phys), jnp.floating):
            # Spark float total order: NaN is GREATEST.  segment_max's
            # IEEE NaN propagation already realizes that; min must
            # instead IGNORE NaN unless the whole group is NaN (then
            # the answer is NaN, not NULL).
            isnan = valid & jnp.isnan(vcol.data)
            if spec.op == "min":
                vals = jnp.where(isnan, _minmax_sentinel(phys, "min"),
                                 vals)
            n_nan = jax.ops.segment_sum(isnan.astype(jnp.int64), seg_id,
                                        num_segments=cap)
            out = f(vals, seg_id, num_segments=cap)
            if spec.op == "min":
                out = jnp.where(n_nan == nvalid,
                                jnp.asarray(jnp.nan, phys), out)
            return Column(out, group_live & (nvalid > 0), out_dtype)
        out = f(vals, seg_id, num_segments=cap)
        return Column(out, group_live & (nvalid > 0), out_dtype)
    if spec.op in ("first", "last"):
        # first/last non-null within the segment, in sorted-batch order
        pos = _firstlast_pos(valid, spec.op, cap)
        f = jax.ops.segment_min if spec.op == "first" else jax.ops.segment_max
        sel = f(pos, seg_id, num_segments=cap)
        sel_clipped = jnp.clip(sel, 0, cap - 1)
        out = jnp.take(vcol.data, sel_clipped).astype(phys)
        return Column(out, group_live & (nvalid > 0), out_dtype)
    if spec.op in ("first_any", "last_any"):
        # Spark default (ignoreNulls=false): first/last LIVE row of the
        # segment regardless of validity; a NULL first value stays NULL
        base = "first" if spec.op == "first_any" else "last"
        pos = _firstlast_pos(live_sorted, base, cap)
        f = jax.ops.segment_min if base == "first" else jax.ops.segment_max
        sel = f(pos, seg_id, num_segments=cap)
        sel_clipped = jnp.clip(sel, 0, cap - 1)
        out = jnp.take(vcol.data, sel_clipped).astype(phys)
        sel_valid = jnp.take(vcol.validity, sel_clipped)
        return Column(out, group_live & sel_valid, out_dtype)
    raise ValueError(f"unknown agg op {spec.op}")


def reduce_aggregate(batch: ColumnarBatch, aggs: Sequence[AggSpec],
                     out_schema: T.Schema) -> ColumnarBatch:
    """Grand aggregate (no keys): one output row.  Separate path because
    there is no sort: plain masked reductions."""
    cap = batch.capacity
    live = batch.row_mask()
    out_cols: list[AnyColumn] = []
    one_live = jnp.arange(cap, dtype=jnp.int32) < 1
    for spec in aggs:
        if spec.op == "count_star":
            n = jnp.sum(live.astype(jnp.int64))
            out_cols.append(Column(jnp.zeros(cap, jnp.int64).at[0].set(n),
                                   one_live, T.LONG))
            continue
        vcol = batch.columns[spec.ordinal]
        valid = vcol.validity & live
        nvalid = jnp.sum(valid.astype(jnp.int64))
        if spec.op == "count":  # validity-only: any column kind
            out_cols.append(Column(
                jnp.zeros(cap, jnp.int64).at[0].set(nvalid), one_live, T.LONG))
            continue
        assert isinstance(vcol, Column)
        out_dtype = agg_output_dtype(spec, vcol.dtype)
        phys = T.to_numpy_dtype(out_dtype)
        if spec.op == "sum":
            s = jnp.sum(jnp.where(valid, vcol.data.astype(phys),
                                  jnp.asarray(0, phys)))
        elif spec.op in ("min", "max"):
            vals = jnp.where(valid, vcol.data.astype(phys),
                             _minmax_sentinel(phys, spec.op))
            if jnp.issubdtype(jnp.dtype(phys), jnp.floating):
                # Spark float total order (see _eval_agg): max keeps
                # IEEE NaN propagation (NaN greatest); min ignores NaN
                # unless every valid value is NaN
                isnan = valid & jnp.isnan(vcol.data)
                if spec.op == "min":
                    vals = jnp.where(
                        isnan, _minmax_sentinel(phys, "min"), vals)
                    s = jnp.where(jnp.sum(isnan.astype(jnp.int64))
                                  == nvalid,
                                  jnp.asarray(jnp.nan, phys),
                                  jnp.min(vals))
                else:
                    s = jnp.max(vals)
            else:
                s = jnp.min(vals) if spec.op == "min" else jnp.max(vals)
        elif spec.op in ("first", "last"):
            pos = _firstlast_pos(valid, spec.op, cap)
            sel = jnp.min(pos) if spec.op == "first" else jnp.max(pos)
            s = jnp.take(vcol.data, jnp.clip(sel, 0, cap - 1)).astype(phys)
        elif spec.op in ("first_any", "last_any"):
            base = "first" if spec.op == "first_any" else "last"
            pos = _firstlast_pos(live, base, cap)
            sel = jnp.min(pos) if base == "first" else jnp.max(pos)
            sel_c = jnp.clip(sel, 0, cap - 1)
            s = jnp.take(vcol.data, sel_c).astype(phys)
            sel_ok = jnp.take(vcol.validity, sel_c)
            data = jnp.zeros(cap, phys).at[0].set(s)
            out_cols.append(Column(
                data, one_live & sel_ok & (jnp.sum(live) > 0), out_dtype))
            continue
        else:
            raise ValueError(f"unknown agg op {spec.op}")
        data = jnp.zeros(cap, phys).at[0].set(s.astype(phys))
        out_cols.append(Column(data, one_live & (nvalid > 0), out_dtype))
    return ColumnarBatch(out_cols, 1, out_schema)
