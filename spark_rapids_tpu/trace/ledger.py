"""Per-program device-utilization ledger: which compiled program burns
the chip's time, and how close each runs to its roofline.

ROADMAP open item #2 is judged on ``hbm_roofline_fraction``, but until
this module that number was a single coarse quotient in bench.py
(wall-clock rows/s x row bytes / HBM bandwidth) — nothing could say
WHICH XLA program the time went to, how much of a query was dispatch
overhead versus device compute, or what a program's achieved bytes/s
and FLOPs/s are against the chip peaks.  The reference stack leans on
exactly this attribution (per-exec GpuMetrics feeding the Profiling /
Qualification tools); this is the XLA analog:

- every compiled program already flows through ONE chokepoint —
  :func:`spark_rapids_tpu.execs.jit_cache.cached_jit` — keyed by a
  structural program key.  The cache wraps each jitted callable with a
  ledger hook: when the ledger is ON, each dispatch bumps an invocation
  counter and hands the program's output to a settlement worker (the
  metric-reaper pattern: poll ``is_ready`` off the critical path, then
  credit the dispatch its EXCLUSIVE busy interval — completion stamps
  are monotone across the settle queue, so overlapping async-dispatch
  windows never double-count the one chip and the per-query sum is a
  true device-busy time bounded by the wall);
- on a program's FIRST ledger-observed dispatch, XLA's own cost model
  is captured (``fn.lower(*args).compile().cost_analysis()`` on the
  settlement worker): flops and bytes accessed per execution;
- from (dispatches, device wall, cost model) the ledger computes the
  ATTRIBUTED roofline per program — achieved bytes/s and flops/s
  against the chip peaks — plus dispatch-overhead ratios, surfaced in
  ``explain("analyze")`` (per-operator roofline column + top-program
  footer), bench.py (``q*_device_busy_ms`` / ``q*_roofline_attributed``
  / top-program fields), the event log (the per-query ``programs``
  section) and ``tools/history`` (per-program compare deltas, health
  rules HC010/HC011).

Cost discipline: with ``spark.rapids.tpu.trace.ledger.enabled=false``
(the default) the per-dispatch cost is ONE attribute read in the
cached_jit wrapper — no entry exists, no lock is taken, behavior is
bit-identical.  Enabled, the hot loop pays one counter bump under a
per-entry lock; everything else (completion wait, cost analysis)
settles on the ledger's worker thread.  Docs: ``docs/device_ledger.md``.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
import weakref
from typing import Any, Optional

from spark_rapids_tpu.config import register

LEDGER_ENABLED = register(
    "spark.rapids.tpu.trace.ledger.enabled", False,
    "Enable the per-program device-utilization ledger: every program "
    "dispatched through the jit cache records invocation count, "
    "device wall time (settled off the critical path) and XLA's own "
    "cost model (flops, bytes accessed), from which per-program and "
    "per-operator ATTRIBUTED roofline fractions are computed — "
    "surfaced in explain('analyze'), bench.py and the event log's "
    "per-query `programs` section (docs/device_ledger.md).  Off (the "
    "default) the only per-dispatch cost is one attribute read.")

LEDGER_HBM_BYTES_PER_S = register(
    "spark.rapids.tpu.trace.ledger.hbmBytesPerSec", 819e9,
    "HBM bandwidth roofline of the chip (bytes/s; default: TPU v5e "
    "~819 GB/s).  The single source of the roofline denominator: "
    "bench.py's coarse hbm_roofline_fraction and the ledger's "
    "attributed per-program fractions both divide by this, so the "
    "constant cannot drift between them.",
    check=lambda v: v > 0)

LEDGER_PEAK_FLOPS = register(
    "spark.rapids.tpu.trace.ledger.peakFlopsPerSec", 197e12,
    "Compute roofline of the chip (FLOPs/s; default: TPU v5e bf16 "
    "~197 TFLOP/s) — denominator of the ledger's attributed "
    "flops-side roofline fraction.",
    check=lambda v: v > 0)

LEDGER_ROOFLINE_FLOOR = register(
    "spark.rapids.tpu.trace.ledger.health.rooflineFloor", 0.001,
    "HC011 health-rule budget: a query whose ATTRIBUTED roofline "
    "fraction (device-time-weighted, from the event log's per-query "
    "programs section) falls below this while its programs burned "
    "real device time is flagged — the chip ran far under its "
    "roofline for that plan (docs/device_ledger.md).",
    check=lambda v: 0 <= v <= 1)

LEDGER_OCCUPANCY_FLOOR = register(
    "spark.rapids.tpu.trace.ledger.health.occupancyFloor", 0.5,
    "HC015 health-rule budget: a query whose aggregate live-rows over "
    "padded-capacity ratio (from the event log's per-query programs "
    "section) falls below this while its programs burned real device "
    "time is flagged — the chip mostly processed padding; coalesce "
    "small batches (sql.coalesce.enabled) or switch the capacity "
    "policy (sql.capacity.policy=pow2x3) to densify "
    "(docs/occupancy.md).",
    check=lambda v: 0 <= v <= 1)

#: the conf default, importable without a conf in hand (bench.py's
#: module-level docs reference the same number the conf carries)
DEFAULT_HBM_BYTES_PER_S = float(LEDGER_HBM_BYTES_PER_S.default)


def roofline_fraction(bytes_per_s: float,
                      hbm_bytes_per_s: Optional[float] = None) -> float:
    """THE roofline formula: achieved bytes/s over the chip's HBM
    bandwidth.  One definition shared by bench.py's coarse cold/warm
    quotients and the ledger's per-program attribution, so the formula
    and the constant cannot drift apart."""
    if hbm_bytes_per_s is None:
        from spark_rapids_tpu.config import get_conf

        hbm_bytes_per_s = float(get_conf().get(LEDGER_HBM_BYTES_PER_S))
    return bytes_per_s / hbm_bytes_per_s


def key_tag(key: Any) -> str:
    """THE key-tag rule: the leading string of a structural jit key
    (every cached_jit key starts with one), "prog" otherwise.  One
    definition shared by ProgramEntry, program_key_str and
    jit_cache.program_census — the census the fusion smoke diffs and
    the ledger footer must bucket keys identically or key churn gets
    pinned to the wrong tag."""
    return key[0] if isinstance(key, tuple) and key \
        and isinstance(key[0], str) else "prog"


def program_key_str(key: Any) -> str:
    """Stable, compact cross-run identity for a structural jit key:
    the key's leading tag (every cached_jit key starts with one) plus a
    hash of the full structural serialization.  Structural keys contain
    only expression trees / capacities / schemas — no addresses — so
    the same program hashes identically across runs, which is what
    lets tools/history line programs up between event logs."""
    h = hashlib.sha256(repr(key).encode()).hexdigest()[:12]
    return f"{key_tag(key)}#{h}"


class ProgramEntry:
    """Cumulative counters for one compiled program (one jit key)."""

    __slots__ = ("key_str", "tag", "op", "gen", "donated", "meta",
                 "dispatches", "dispatch_ns", "device_ns", "flops",
                 "bytes_accessed", "live_rows", "capacity_rows",
                 "cost_state", "lock")

    #: cost_state values
    COST_NONE, COST_PENDING, COST_DONE = 0, 1, 2

    def __init__(self, key: Any, op: Optional[str], gen: int,
                 donated: bool = False,
                 meta: Optional[dict] = None):
        self.key_str = program_key_str(key)
        self.tag = key_tag(key)
        self.op = op
        self.gen = gen
        self.donated = donated
        #: static program attributes from the compile site — a
        #: PARTITIONED (SPMD) program records its mesh device count
        #: (`devices`) and in-program collective round count
        #: (`rounds`), so snapshots can attribute per-device busy time
        #: (device_ms spans the whole mesh: the per-device figure IS
        #: device_ms, and the mesh burns devices x device_ms of chip
        #: capacity) and the multichip bench can report how many
        #: exchange rounds each stage folded into one dispatch
        self.meta = dict(meta) if meta else None
        self.dispatches = 0     # guard: lock
        self.dispatch_ns = 0    # guard: lock (host-side dispatch wall)
        self.device_ns = 0      # guard: lock (exclusive busy, settled)
        self.flops = 0.0        # guard: lock (XLA cost analysis)
        self.bytes_accessed = 0.0  # guard: lock (per execution)
        self.live_rows = 0      # guard: lock (occupancy accounting)
        self.capacity_rows = 0  # guard: lock (occupancy accounting)
        self.cost_state = self.COST_NONE  # guard: lock
        self.lock = threading.Lock()


# ------------------------------------------------------------------ #
# Occupancy accounting (live rows vs padded capacity per dispatch)
# ------------------------------------------------------------------ #

#: per-thread occupancy hint: dispatch sites whose batch row counts are
#: device-resident (the fused pipelines promote num_rows to a device
#: scalar before dispatch) state the host-known live/capacity pair just
#: before calling the wrapped program; the very next ledger dispatch on
#: that thread consumes it.  Sites that don't hint fall back to the
#: argument scan below.
_OCC_TLS = threading.local()

#: batch classes recognized by the argument scan; resolved lazily (the
#: columnar package imports config only, but ledger loads very early)
_BATCH_TYPES: Optional[tuple] = None


def note_occupancy(live_rows: Any, capacity_rows: Any) -> None:
    """Record the live/capacity row counts for the NEXT cached_jit
    dispatch on this thread.  No-op when the ledger is off (one
    attribute read), so call sites need no guard of their own; counts
    that aren't host ints (traced values) are ignored."""
    if not LEDGER.enabled:
        return
    try:
        live, cap = int(live_rows), int(capacity_rows)
    except Exception:
        return
    if cap > 0:
        _OCC_TLS.occ = (live, cap)


def _batch_types() -> Optional[tuple]:
    global _BATCH_TYPES
    if _BATCH_TYPES is None:
        try:
            from spark_rapids_tpu.columnar.batch import ColumnarBatch
            from spark_rapids_tpu.columnar.transfer import EncodedBatch

            _BATCH_TYPES = (ColumnarBatch, EncodedBatch)
        except Exception:
            return None
    return _BATCH_TYPES


def observe_occupancy(args: tuple) -> tuple[int, int]:
    """(live_rows, capacity_rows) summed over every batch argument
    whose row count is host-known.  Batches carrying device-resident
    counts are skipped (reading them would force a sync on the hot
    path) — their dispatch sites use :func:`note_occupancy` instead.
    Scans one level of tuple/list nesting, bounded, never throws."""
    types_ = _batch_types()
    if types_ is None:
        return (0, 0)
    batch_cls, encoded_cls = types_
    live = cap = 0
    stack = list(args)
    budget = 64
    while stack and budget > 0:
        budget -= 1
        a = stack.pop()
        try:
            if isinstance(a, batch_cls):
                n = a.num_rows
                if type(n) is int:
                    live += n
                    cap += a.capacity
            elif isinstance(a, encoded_cls):
                if a.num_rows is not None:
                    live += int(a.num_rows)
                    cap += int(a.capacity)
            elif isinstance(a, (tuple, list)):
                stack.extend(a)
        except Exception:
            continue
    return (live, cap)


def derive_sentinels(out: Any) -> list:
    """Zero-row sentinel slices for every live device-array leaf of a
    program output pytree (the sentinel's completion implies the
    program finished — data dependency + in-order device execution —
    and the settle worker exclusively owns it, so polling never races
    the spill store's .delete()).

    PER-LEAF fault isolation: under buffer donation a fused program's
    output can mix live leaves with leaves the caller already consumed
    (donated into the next program, or passed through from a donated
    input) — one dead leaf must not throw away every usable sentinel,
    or the donated fused program silently settles \"as host\" and its
    device-busy time vanishes from the ledger (the warm-roofline
    number ROADMAP #2 is judged on).  The retained leaves still bound
    the program's completion: the device runs programs in order, so
    ANY output leaf's readiness implies the whole program retired."""
    import jax

    try:
        leaves = jax.tree_util.tree_leaves(out)
    except Exception:
        return []
    sentinels = []
    for x in leaves:
        if not isinstance(x, jax.Array):
            continue
        try:
            sentinels.append(x[:0] if x.ndim > 0
                             else x.reshape((1,))[:0])
        except Exception:
            continue  # this leaf is gone; the survivors still settle
    return sentinels


class _SettleWorker:
    """Off-critical-path settlement, mirroring the metric reaper:
    dispatch sites derive zero-row SENTINELS from the program output on
    the producing thread (the sentinel's completion implies the
    program finished; polling the output arrays themselves would race
    the spill store's .delete()) and this daemon polls readiness, then
    credits dispatch-to-completion time to the entry.  Cost-analysis
    capture (lower+compile+cost_analysis, once per program) also runs
    here — it can take tens of ms and must never sit on the hot
    loop."""

    def __init__(self) -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._unfinished = 0    # guard: _cv
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        #: completion stamp of the previously settled dispatch: each
        #: dispatch is credited its EXCLUSIVE interval
        #: [max(t0, prev_done), done] — async dispatch lets
        #: dispatch-to-completion windows overlap (program k+1 is
        #: launched while k still runs), and crediting overlapping
        #: wall to both would double-count one chip.  The device runs
        #: programs in order, the worker settles them in order, so the
        #: credited intervals are disjoint and their sum is a true
        #: BUSY time, bounded by the query wall (the run_ledger_smoke
        #: acceptance bound) — queue wait inherited from the previous
        #: program is excluded by construction.
        self._last_done_ns = 0

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="tpu-ledger-settle", daemon=True)
            self._thread.start()

    def submit(self, entry: ProgramEntry, t0: int, out: Any,
               cost_req: Optional[tuple]) -> None:
        sentinels = derive_sentinels(out)
        with self._cv:
            self._ensure_thread()
            self._unfinished += 1
        self._q.put((entry, t0, sentinels, cost_req))

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait (bounded) until every submitted dispatch has settled;
        returns False on timeout.  Bounded because callers sit at query
        boundaries — a wedged settle must degrade the ledger, not hang
        the query epilogue."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cv:
            while self._unfinished:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    def _task_done(self) -> None:
        with self._cv:
            self._unfinished -= 1
            if not self._unfinished:
                self._cv.notify_all()

    def _run(self) -> None:
        while True:
            entry, t0, sentinels, cost_req = self._q.get()
            try:
                for x in sentinels:
                    while not x.is_ready():
                        time.sleep(0.001)
                done = time.perf_counter_ns()
                start = max(t0, self._last_done_ns)
                self._last_done_ns = done
                with entry.lock:
                    entry.device_ns += max(0, done - start)
                if cost_req is not None:
                    self._capture_cost(entry, cost_req)
            except Exception:
                pass  # diagnostics must never take the engine down
            finally:
                self._task_done()

    @staticmethod
    def _capture_cost(entry: ProgramEntry, cost_req: tuple) -> None:
        """XLA cost model for one program: lower+compile at the first
        observed argument signature, read flops / bytes accessed.  A
        backend without cost analysis (or an unlowerable signature)
        marks the entry DONE with zeros — retried never."""
        fn, args, kwargs = cost_req
        flops = nbytes = 0.0
        try:
            compiled = fn.lower(*args, **kwargs).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if isinstance(ca, dict):
                flops = max(0.0, float(ca.get("flops", 0.0) or 0.0))
                nbytes = max(0.0, float(
                    ca.get("bytes accessed", 0.0) or 0.0))
        except Exception:
            pass
        with entry.lock:
            entry.flops = flops
            entry.bytes_accessed = nbytes
            entry.cost_state = ProgramEntry.COST_DONE


class DeviceLedger:
    """Process-wide program ledger.  ``enabled`` is THE fast-path
    guard (the cached_jit wrapper reads this one attribute and does
    nothing else when the ledger is off); ``forced`` marks a
    programmatic :func:`enable` that :func:`sync_conf` must not
    override — the tracer's ownership discipline exactly."""

    def __init__(self) -> None:
        self.enabled = False
        self.forced = False
        self.gen = 0  # bumped by reset(); stale wrapper cells re-key
        self._entries: dict[Any, ProgramEntry] = {}  # guard: _lock
        self._lock = threading.Lock()
        self._enabled_by: Optional[weakref.ref] = None
        self._settle = _SettleWorker()

    # -- recording (fed by the cached_jit wrapper) ------------------- #

    def entry(self, key: Any, op: Optional[str],
              donated: bool = False,
              meta: Optional[dict] = None) -> ProgramEntry:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = ProgramEntry(key, op, self.gen,
                                                      donated, meta)
            elif e.op is None and op is not None:
                e.op = op
            return e

    def wrap(self, key: Any, fn, op: Optional[str] = None,
             donated: bool = False, meta: Optional[dict] = None):
        """Wrap one jitted callable with ledger accounting.  The
        disabled path is one attribute read + the passthrough call —
        bit-identical results either way (the wrapper never touches
        arguments or output).  `donated` marks programs compiled with
        buffer donation so snapshots/footers can say which programs
        reuse input HBM; `meta` carries static partitioned-program
        attributes (mesh devices, in-program collective rounds)."""
        cell: list = [None]
        ledger = self

        def dispatch(*args, **kwargs):
            if not ledger.enabled:
                return fn(*args, **kwargs)
            e = cell[0]
            if e is None or e.gen != ledger.gen:
                e = cell[0] = ledger.entry(key, op, donated, meta)
            occ = getattr(_OCC_TLS, "occ", None)
            if occ is not None:
                _OCC_TLS.occ = None
            t0 = time.perf_counter_ns()
            out = fn(*args, **kwargs)
            t1 = time.perf_counter_ns()
            if occ is None:
                occ = observe_occupancy(args)
            cost_req = None
            with e.lock:
                e.dispatches += 1
                e.dispatch_ns += t1 - t0
                if occ[1] > 0:
                    e.live_rows += occ[0]
                    e.capacity_rows += occ[1]
                if e.cost_state == ProgramEntry.COST_NONE:
                    e.cost_state = ProgramEntry.COST_PENDING
                    # args are immutable jax values: safe to hold for
                    # the worker's one-time lower+compile
                    cost_req = (fn, args, kwargs)
            ledger._settle.submit(e, t0, out, cost_req)
            return out

        dispatch.__wrapped__ = fn
        return dispatch

    # -- lifecycle --------------------------------------------------- #

    def enable(self, forced: bool = True) -> None:
        self.enabled = True
        self.forced = forced

    def disable(self) -> None:
        self.enabled = False
        self.forced = False
        self._enabled_by = None

    def reset(self) -> None:
        """Drop every entry (bench resets between queries, tests
        between cases).  Wrapper cells holding stale entries re-key on
        their next dispatch via the generation check."""
        with self._lock:
            self.gen += 1
            self._entries = {}

    def flush(self, timeout: Optional[float] = None) -> bool:
        return self._settle.flush(timeout)

    # -- reading ----------------------------------------------------- #

    def snapshot(self) -> dict[str, dict]:
        """Point-in-time cumulative counters per program (key_str ->
        plain dict).  Callers wanting per-query figures snapshot
        before/after and :func:`delta`."""
        with self._lock:
            entries = list(self._entries.values())
        out: dict[str, dict] = {}
        for e in entries:
            with e.lock:
                rec = {
                    "tag": e.tag,
                    "op": e.op,
                    "donated": e.donated,
                    "dispatches": e.dispatches,
                    "dispatch_ms": round(e.dispatch_ns / 1e6, 3),
                    "device_ms": round(e.device_ns / 1e6, 3),
                    "flops": e.flops,
                    "bytes_accessed": e.bytes_accessed,
                    "live_rows": e.live_rows,
                    "capacity_rows": e.capacity_rows,
                    "live_capacity_ratio": round(
                        e.live_rows / e.capacity_rows, 4)
                    if e.capacity_rows else None,
                }
                if e.meta:
                    # partitioned-program attribution: device_ms spans
                    # the mesh, so per-device busy IS device_ms and the
                    # stage burned devices x device_ms of chip capacity
                    rec.update(e.meta)
                out[e.key_str] = rec
        return out


#: THE process-wide ledger; the cached_jit wrapper guards on
#: ``LEDGER.enabled``
LEDGER = DeviceLedger()


def is_enabled() -> bool:
    return LEDGER.enabled


def enable() -> None:
    """Force the ledger on (tests, bench): survives sync_conf."""
    LEDGER.enable(forced=True)


def disable() -> None:
    LEDGER.disable()


def reset_stats() -> None:
    LEDGER.reset()


def snapshot() -> dict[str, dict]:
    return LEDGER.snapshot()


def sync_conf(conf=None) -> None:
    """Align the ledger with the session conf at a query boundary —
    same ownership rule as the tracer: a programmatic enable() wins,
    and only the conf that ENABLED the ledger may turn it off (a
    concurrent session's defaults-only conf must not kill another
    session's capture mid-query)."""
    if LEDGER.forced:
        return
    from spark_rapids_tpu.config import get_conf

    conf = conf or get_conf()
    want = bool(conf.get(LEDGER_ENABLED))
    if want:
        if not LEDGER.enabled:
            LEDGER.enable(forced=False)
        LEDGER._enabled_by = weakref.ref(conf)
    elif LEDGER.enabled and LEDGER._enabled_by is not None \
            and LEDGER._enabled_by() is conf:
        LEDGER.disable()


# ------------------------------------------------------------------ #
# Analytics over snapshots
# ------------------------------------------------------------------ #


def delta(before: dict[str, dict],
          after: dict[str, dict]) -> dict[str, dict]:
    """Per-query attribution: after - before on the monotonic
    counters, cost-model fields carried from `after` (they are
    per-execution constants).  Programs that did not dispatch in the
    window are dropped."""
    out: dict[str, dict] = {}
    for k, a in after.items():
        b = before.get(k, {})
        d = a["dispatches"] - b.get("dispatches", 0)
        if d <= 0:
            continue
        live = a.get("live_rows", 0) - b.get("live_rows", 0)
        cap = a.get("capacity_rows", 0) - b.get("capacity_rows", 0)
        rec = {
            "tag": a["tag"],
            "op": a["op"],
            "donated": a.get("donated", False),
            "dispatches": d,
            "dispatch_ms": round(
                a["dispatch_ms"] - b.get("dispatch_ms", 0.0), 3),
            "device_ms": round(
                a["device_ms"] - b.get("device_ms", 0.0), 3),
            "flops": a["flops"],
            "bytes_accessed": a["bytes_accessed"],
            "live_rows": live,
            "capacity_rows": cap,
            "live_capacity_ratio": round(live / cap, 4)
            if cap > 0 else None,
        }
        for mk in ("devices", "rounds"):
            if mk in a:
                rec[mk] = a[mk]
        out[k] = rec
    return out


def summarize(programs: dict[str, dict], top_n: int = 5,
              hbm_bytes_per_s: Optional[float] = None,
              peak_flops: Optional[float] = None) -> dict:
    """Enrich a snapshot/delta with attributed rooflines and totals —
    the ``programs`` section the event log persists and bench/analyze
    render.  Per program: achieved bytes/s and flops/s (cost model x
    dispatches over settled device time) against the chip peaks, and
    the dispatch-overhead ratio (host dispatch ms per device ms).
    Totals: device-time totals, a device-time-WEIGHTED roofline
    fraction, and the top-N programs by device time with their
    share."""
    from spark_rapids_tpu.config import get_conf

    conf = get_conf()
    if hbm_bytes_per_s is None:
        hbm_bytes_per_s = float(conf.get(LEDGER_HBM_BYTES_PER_S))
    if peak_flops is None:
        peak_flops = float(conf.get(LEDGER_PEAK_FLOPS))
    enriched: dict[str, dict] = {}
    total_device_ms = 0.0
    total_dispatch_ms = 0.0
    total_dispatches = 0
    total_live = 0
    total_capacity = 0
    weighted_roofline = 0.0
    weighted_known_ms = 0.0
    for k, p in programs.items():
        device_s = p["device_ms"] / 1e3
        e = dict(p)
        total_live += p.get("live_rows", 0)
        total_capacity += p.get("capacity_rows", 0)
        if device_s > 0 and p["bytes_accessed"] > 0:
            bps = p["bytes_accessed"] * p["dispatches"] / device_s
            fps = p["flops"] * p["dispatches"] / device_s
            e["bytes_per_s"] = round(bps, 1)
            e["flops_per_s"] = round(fps, 1)
            e["roofline"] = round(
                roofline_fraction(bps, hbm_bytes_per_s), 6)
            e["flops_fraction"] = round(fps / peak_flops, 9)
            weighted_roofline += e["roofline"] * p["device_ms"]
            weighted_known_ms += p["device_ms"]
        else:
            e["bytes_per_s"] = e["flops_per_s"] = None
            e["roofline"] = e["flops_fraction"] = None
        e["dispatch_overhead"] = round(
            p["dispatch_ms"] / p["device_ms"], 3) \
            if p["device_ms"] > 0 else None
        enriched[k] = e
        total_device_ms += p["device_ms"]
        total_dispatch_ms += p["dispatch_ms"]
        total_dispatches += p["dispatches"]
    top = sorted(enriched.items(),
                 key=lambda kv: -kv[1]["device_ms"])[:top_n]
    totals = {
        "programs": len(enriched),
        "dispatches": total_dispatches,
        "dispatch_ms": round(total_dispatch_ms, 3),
        "device_ms": round(total_device_ms, 3),
        "roofline": round(weighted_roofline / weighted_known_ms, 6)
        if weighted_known_ms else None,
        "live_rows": total_live,
        "capacity_rows": total_capacity,
        "live_capacity_ratio": round(total_live / total_capacity, 4)
        if total_capacity else None,
        "top": [{
            "key": k,
            "op": p["op"],
            "dispatches": p["dispatches"],
            "device_ms": p["device_ms"],
            "share": round(p["device_ms"] / total_device_ms, 3)
            if total_device_ms else 0.0,
            "live_capacity_ratio": p.get("live_capacity_ratio"),
        } for k, p in top],
    }
    return {"programs": enriched, "totals": totals}


def per_op(programs: dict[str, dict],
           hbm_bytes_per_s: Optional[float] = None) -> dict[str, dict]:
    """Aggregate an (un-enriched or enriched) program delta by the
    operator that compiled it (cached_jit's `op=`), for the
    explain('analyze') per-operator roofline column: per op —
    dispatches, device_ms, and the attributed roofline over the op's
    own device time (cost-model bytes x dispatches / device time)."""
    from spark_rapids_tpu.config import get_conf

    if hbm_bytes_per_s is None:
        hbm_bytes_per_s = float(
            get_conf().get(LEDGER_HBM_BYTES_PER_S))
    acc: dict[str, dict] = {}
    for p in programs.values():
        op = p.get("op")
        if not op:
            continue
        a = acc.setdefault(op, {"dispatches": 0, "device_ms": 0.0,
                                "bytes_total": 0.0, "live_rows": 0,
                                "capacity_rows": 0})
        a["dispatches"] += p["dispatches"]
        a["device_ms"] += p["device_ms"]
        a["bytes_total"] += p["bytes_accessed"] * p["dispatches"]
        a["live_rows"] += p.get("live_rows", 0)
        a["capacity_rows"] += p.get("capacity_rows", 0)
    out: dict[str, dict] = {}
    for op, a in acc.items():
        device_s = a["device_ms"] / 1e3
        roof = None
        if device_s > 0 and a["bytes_total"] > 0:
            roof = round(roofline_fraction(
                a["bytes_total"] / device_s, hbm_bytes_per_s), 6)
        out[op] = {"dispatches": a["dispatches"],
                   "device_ms": round(a["device_ms"], 3),
                   "roofline": roof,
                   "live_capacity_ratio": round(
                       a["live_rows"] / a["capacity_rows"], 4)
                   if a["capacity_rows"] else None}
    return out
