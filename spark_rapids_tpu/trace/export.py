"""Trace exporters and span analytics.

- :func:`chrome_trace` / :func:`export_chrome_trace` — Chrome Trace
  Format JSON (the ``traceEvents`` array form) from the process trace
  buffer.  Loads in Perfetto (ui.perfetto.dev) or chrome://tracing,
  side by side with the XPlane capture ``device_trace()`` produces —
  the nsys-timeline analog of the reference's NVTX workflow.
- :func:`span_stats` — per-operator busy/wall/overlap aggregation over
  ``exec.*`` spans, the ``df.explain("analyze")`` feed: *busy* is the
  summed span time (across threads/partitions), *wall* the union of
  the intervals (span-derived self-time: how long the operator was
  running anywhere), and *overlap* = busy - wall (time at least two of
  its spans ran concurrently — proof the pipeline actually overlapped).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

from spark_rapids_tpu.trace import TraceEvent, snapshot


def chrome_trace(events: Optional[Sequence[TraceEvent]] = None) -> dict:
    """Chrome Trace Format dict (JSON Object Format with a
    ``traceEvents`` array; timestamps in microseconds)."""
    if events is None:
        events = snapshot()
    pid = os.getpid()
    out: list[dict] = []
    named: set[int] = set()
    for ev in events:
        if ev.tid not in named:
            named.add(ev.tid)
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": ev.tid,
                        "args": {"name": ev.thread_name}})
        rec = {"name": ev.name, "ph": ev.ph, "pid": pid, "tid": ev.tid,
               "ts": ev.ts_ns / 1e3, "cat": "engine",
               "args": dict(ev.attrs)}
        if ev.ph == "X":
            rec["dur"] = ev.dur_ns / 1e3
        elif ev.ph == "C":
            # counter sample (the telemetry tracks): args ARE the
            # series values; Perfetto stacks them into a counter track
            pass
        else:
            rec["s"] = "t"  # thread-scoped instant
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str,
                        events: Optional[Sequence[TraceEvent]] = None
                        ) -> str:
    """Write the Chrome-trace JSON; returns the path."""
    doc = chrome_trace(events)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def merge_wire_trace(doc: dict, client_spans: Sequence[dict],
                     label: str = "connect-client") -> dict:
    """Fold a connect client's wire spans (plain dicts —
    ``ConnectClient.trace_spans``, engine-free by design) into a
    :func:`chrome_trace` document IN PLACE and return it: both sides
    stamp ``perf_counter_ns``, so for an in-process loopback (the
    tests' and bench's shape) client send/first-byte/last-byte spans
    and the server's trace_id-tagged engine spans land on ONE
    timeline.  Client spans get their own named thread track.  For a
    genuinely remote client the clocks are unrelated — align
    externally before merging (docs/ops_plane.md)."""
    events = doc.setdefault("traceEvents", [])
    if not client_spans:
        return doc
    pid = os.getpid()
    tid = max((e.get("tid", 0) for e in events
               if isinstance(e.get("tid", 0), int)), default=0) + 1
    events.append({"ph": "M", "name": "thread_name", "pid": pid,
                   "tid": tid, "args": {"name": label}})
    for sp in client_spans:
        events.append({
            "name": sp["name"], "ph": sp.get("ph", "X"), "pid": pid,
            "tid": tid, "ts": sp["ts_ns"] / 1e3,
            "dur": sp.get("dur_ns", 0) / 1e3, "cat": "wire",
            "args": dict(sp.get("attrs") or {}),
        })
    return doc


def _union_ns(intervals: list[tuple[int, int]]) -> int:
    intervals.sort()
    total = 0
    cs, ce = intervals[0]
    for s, e in intervals[1:]:
        if s > ce:
            total += ce - cs
            cs, ce = s, e
        else:
            ce = max(ce, e)
    return total + (ce - cs)


def span_stats(events: Sequence[TraceEvent],
               query_id: Optional[int] = None,
               attr: str = "op") -> dict[str, dict]:
    """Aggregate spans by an attribute (default: the exec spans' `op`),
    optionally restricted to one query id.  Per key:
    ``{"spans", "busy_ns", "wall_ns", "overlap_ns"}`` (see module doc
    for the busy/wall/overlap semantics)."""
    per: dict[str, list[tuple[int, int]]] = {}
    for ev in events:
        if ev.ph != "X":
            continue
        key = ev.attrs.get(attr)
        if key is None:
            continue
        if query_id is not None \
                and ev.attrs.get("query_id") != query_id:
            continue
        per.setdefault(str(key), []).append((ev.ts_ns, ev.end_ns))
    out: dict[str, dict] = {}
    for key, iv in per.items():
        busy = sum(e - s for s, e in iv)
        wall = _union_ns(iv)
        out[key] = {"spans": len(iv), "busy_ns": busy, "wall_ns": wall,
                    "overlap_ns": busy - wall}
    return out
