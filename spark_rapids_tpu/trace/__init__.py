"""Unified structured tracing: one correlated timeline across threads.

The reference engine gets its timeline from NVTX: every hot operator
runs inside an ``NvtxWithMetrics`` range and nsys stitches the ranges
from all threads/streams into one view (ref: NvtxWithMetrics.scala:25,
SURVEY §5.1 nvtx_profiling.md).  This engine runs work on several
thread families — the calling session thread, prefetch stage producers
(``tpu-pipe-*``), the exchange map-task pool, the metric reaper — and
the per-exec ``TpuMetric`` aggregates cannot answer *where a specific
query's wall time went* or *whether stages actually overlapped*.

This module is the NVTX analog:

- :func:`span` — a context manager recording a named interval on the
  current thread's ring buffer;
- :func:`event` — an instant marker;
- :func:`trace_context` / :func:`current_context` /
  :func:`attach_context` — correlation attributes (``query_id``,
  stage, batch index) that explicitly *cross thread hops*: thread-locals
  do not follow work onto a prefetch stage or pool thread, so the
  dispatching side captures its context and the receiving thread
  attaches it;
- per-thread ring buffers: recording is lock-free on the hot path (one
  enabled-flag read when tracing is off, a list append when on) and
  bounded by ``spark.rapids.tpu.trace.bufferSize`` events per thread —
  a long-running process can leave tracing on without growing without
  bound (oldest events are evicted).

Export lives in :mod:`spark_rapids_tpu.trace.export` (Chrome Trace
Format JSON, viewable in Perfetto next to a ``device_trace()`` XPlane
capture) and feeds ``df.explain("analyze")``.  Docs:
``docs/observability.md``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import threading
import time
import weakref
from typing import Any, Iterator, Optional

from spark_rapids_tpu.config import register

TRACE_ENABLED = register(
    "spark.rapids.tpu.trace.enabled", False,
    "Enable the unified structured tracer: spans/events from the "
    "session, execs, pipeline stages, spill store, shuffle manager and "
    "JIT cache are recorded to per-thread ring buffers, correlated by "
    "query id across thread hops, and exportable as Chrome Trace JSON "
    "(session.export_trace / python -m spark_rapids_tpu.tools.trace). "
    "Off (the default) the only cost per potential span is one "
    "attribute read.")

TRACE_BUFFER_SIZE = register(
    "spark.rapids.tpu.trace.bufferSize", 65536,
    "Ring-buffer capacity (events) PER THREAD for the structured "
    "tracer; the oldest events are evicted when a thread's buffer is "
    "full, so long-running processes can trace continuously at bounded "
    "memory.",
    check=lambda v: v >= 16)


@dataclasses.dataclass(slots=True)
class TraceEvent:
    """One recorded span ("X") or instant ("i")."""

    name: str
    ph: str  # "X" complete span | "i" instant
    ts_ns: int  # perf_counter_ns at span start / instant time
    dur_ns: int  # 0 for instants
    tid: int
    thread_name: str
    attrs: dict

    @property
    def end_ns(self) -> int:
        return self.ts_ns + self.dur_ns


#: process-unique track ids for rings.  NOT the OS thread ident:
#: CPython recycles idents after thread death, and per-query pipeline /
#: pool threads would then merge onto one mislabeled Perfetto track.
_RING_IDS = itertools.count(1)

#: dead-thread rings (owner exited, events still current) retained for
#: export; oldest beyond this are dropped so a long-running traced
#: process stays bounded even across many short-lived stage threads
_MAX_DEAD_RINGS = 256


class _Ring:
    """Per-thread fixed-capacity event ring.  STRICTLY single-writer:
    only the owning thread ever mutates buf/pos (appends are lock-free;
    a clear()/resize from another thread only bumps the tracer's
    generation, and the owner lazily resets on its next append —
    cross-thread mutation of buf would race `buf[pos] = ev`).  Readers
    snapshot under the tracer lock and skip stale-generation rings,
    which is fine for a diagnostics buffer."""

    __slots__ = ("cap", "buf", "pos", "dropped", "tid", "thread_name",
                 "gen", "owner")

    def __init__(self, cap: int, thread: threading.Thread, gen: int):
        self.cap = cap
        self.buf: list[TraceEvent] = []
        self.pos = 0
        self.dropped = 0
        self.tid = next(_RING_IDS)
        self.thread_name = thread.name
        self.gen = gen
        #: weakref so the ring never keeps a finished Thread alive;
        #: a dead owner can no longer append, which makes pruning safe
        self.owner = weakref.ref(thread)

    def append(self, ev: TraceEvent) -> None:
        if len(self.buf) < self.cap:
            self.buf.append(ev)
        else:
            self.buf[self.pos] = ev
            self.pos = (self.pos + 1) % self.cap
            self.dropped += 1

    def ordered(self) -> list[TraceEvent]:
        return self.buf[self.pos:] + self.buf[:self.pos]

    def reset(self, cap: Optional[int] = None) -> None:
        """Owner-thread only (see class doc)."""
        if cap is not None:
            self.cap = cap
        self.buf = []
        self.pos = 0
        self.dropped = 0


class Tracer:
    """Process-wide trace collector.

    ``enabled`` is THE fast-path guard: every instrumentation site
    reads this one attribute and does nothing else when tracing is
    off.  ``forced`` marks a programmatic :func:`enable` (tests, the
    tools.trace CLI) that :func:`sync_conf` must not override."""

    def __init__(self) -> None:
        self.enabled = False
        self.forced = False
        self.buffer_size = TRACE_BUFFER_SIZE.default
        #: bumped by clear()/resize; rings lazily self-reset when their
        #: gen falls behind, so only the OWNER thread mutates a ring
        self._gen = 0
        #: perf_counter_ns of the last clear()/resize: any event whose
        #: interval STARTED before it belongs to the discarded capture
        #: (covers spans and caller-timed record_complete alike)
        self._gen_ts = 0
        #: weakref to the conf that last enabled via sync_conf — only
        #: that conf's "off" may disable (another session's conf must
        #: not kill a concurrent session's capture mid-query; a
        #: weakref, not id(), because a recycled address would hand the
        #: kill switch to an unrelated conf)
        self._enabled_by: Optional[weakref.ref] = None
        self._rings: list[_Ring] = []
        self._lock = threading.Lock()
        self._tls = threading.local()

    # -- recording (hot path) ------------------------------------------ #

    def _ring(self) -> _Ring:
        ring = getattr(self._tls, "ring", None)
        if ring is None:
            ring = _Ring(self.buffer_size, threading.current_thread(),
                         self._gen)
            with self._lock:
                self._rings.append(ring)
                self._prune_locked()
            self._tls.ring = ring
        elif ring.gen != self._gen:
            # a clear()/resize happened since this thread last wrote:
            # apply it here, on the owning thread
            ring.reset(self.buffer_size)
            ring.gen = self._gen
        return ring

    def record(self, name: str, ts_ns: int, dur_ns: int,
               attrs: Optional[dict], ph: str = "X") -> None:
        if not self.enabled:
            return  # a span may outlive a disable(): drop, don't bleed
        if ts_ns < self._gen_ts:
            return  # interval predates a clear(): that capture was
            # discarded — applies to spans and pre-timed
            # record_complete (reaper settle, pipeline waits) alike
        ring = self._ring()
        ctx = getattr(self._tls, "ctx", None)
        if ctx:
            attrs = {**ctx, **attrs} if attrs else dict(ctx)
        ring.append(TraceEvent(name, ph, ts_ns, dur_ns, ring.tid,
                               ring.thread_name, attrs or {}))

    # -- lifecycle ------------------------------------------------------ #

    def enable(self, buffer_size: Optional[int] = None,
               forced: bool = True) -> None:
        with self._lock:
            if buffer_size is not None \
                    and int(buffer_size) != self.buffer_size:
                # an actual RESIZE resets (lazily per owner); a mere
                # re-enable at the same size preserves prior events
                self.buffer_size = int(buffer_size)
                self._gen += 1
                self._gen_ts = time.perf_counter_ns()
            self.enabled = True
            self.forced = forced

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self.forced = False
            self._enabled_by = None

    def clear(self) -> None:
        with self._lock:
            self._gen += 1
            self._gen_ts = time.perf_counter_ns()
            self._prune_locked()

    def _prune_locked(self) -> None:
        """Drop rings no snapshot can ever use again: dead-owner rings
        whose content is stale (owner can't lazily reset them), and the
        oldest dead-but-current rings past _MAX_DEAD_RINGS.  A dead
        owner cannot append, so dropping its ring is race-free; live
        rings are never touched from here."""
        kept: list[_Ring] = []
        dead_current: list[_Ring] = []
        for r in self._rings:
            o = r.owner()
            if o is not None and o.is_alive():
                kept.append(r)
            elif r.gen == self._gen:
                dead_current.append(r)  # events still exportable
            # dead + stale generation: unreferenced garbage — drop
        if len(dead_current) > _MAX_DEAD_RINGS:
            dead_current = dead_current[-_MAX_DEAD_RINGS:]
        self._rings = kept + dead_current

    def _live_rings(self) -> list[_Ring]:
        """Rings whose content survives the latest clear/resize (a
        stale ring's owner has not written since, so its buffered
        events predate the clear)."""
        return [r for r in self._rings if r.gen == self._gen]

    def snapshot(self) -> list[TraceEvent]:
        with self._lock:
            out: list[TraceEvent] = []
            for r in self._live_rings():
                out.extend(r.ordered())
        out.sort(key=lambda e: e.ts_ns)
        return out

    def dropped(self) -> int:
        with self._lock:
            return sum(r.dropped for r in self._live_rings())


#: THE process-wide tracer; instrumentation guards on ``TRACER.enabled``
TRACER = Tracer()


def is_enabled() -> bool:
    return TRACER.enabled


def enable(buffer_size: Optional[int] = None) -> None:
    """Force tracing on (tests / the tools.trace CLI): survives
    :func:`sync_conf` calls made by collect()."""
    TRACER.enable(buffer_size, forced=True)


def disable() -> None:
    TRACER.disable()


def clear() -> None:
    TRACER.clear()


def snapshot() -> list[TraceEvent]:
    """All recorded events (every thread), in timestamp order."""
    return TRACER.snapshot()


def sync_conf(conf=None) -> None:
    """Align the tracer with the session conf at a query boundary (the
    conf is a thread-local snapshot; the tracer is process-global, so
    the query entry point does one explicit sync).  A programmatic
    :func:`enable` wins over the conf, and only the conf that ENABLED
    tracing may turn it off — another session whose conf merely
    defaults to off must not kill a concurrently tracing session's
    capture mid-query."""
    if TRACER.forced:
        return
    from spark_rapids_tpu.config import get_conf

    conf = conf or get_conf()
    want = bool(conf.get(TRACE_ENABLED))
    if want:
        if not TRACER.enabled:
            TRACER.enable(int(conf.get(TRACE_BUFFER_SIZE)),
                          forced=False)
        TRACER._enabled_by = weakref.ref(conf)
    elif TRACER.enabled and TRACER._enabled_by is not None \
            and TRACER._enabled_by() is conf:
        TRACER.disable()


# ------------------------------------------------------------------ #
# Span / event API
# ------------------------------------------------------------------ #


class _NoopSpan:
    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        # a clear() between enter and exit discards this span: record()
        # drops any interval starting before the clear stamp
        TRACER.record(self.name, self.t0,
                      time.perf_counter_ns() - self.t0, self.attrs)
        return False


def span(name: str, **attrs: Any):
    """Context manager recording a named interval on this thread; the
    thread's correlation context (query_id, ...) merges into `attrs`.
    A single shared no-op object when tracing is off."""
    if not TRACER.enabled:
        return _NOOP
    return _Span(name, attrs)


def event(name: str, **attrs: Any) -> None:
    """Record an instant marker."""
    if not TRACER.enabled:
        return
    TRACER.record(name, time.perf_counter_ns(), 0, attrs, ph="i")


def record_complete(name: str, t0_ns: int, dur_ns: int,
                    **attrs: Any) -> None:
    """Record a span whose interval the caller already measured (sites
    like MetricTimer and the pipeline wait counters, which time their
    region anyway — no second clock read)."""
    if not TRACER.enabled:
        return
    TRACER.record(name, t0_ns, dur_ns, attrs)


# ------------------------------------------------------------------ #
# Cross-thread correlation context
# ------------------------------------------------------------------ #


@contextlib.contextmanager
def trace_context(**attrs: Any) -> Iterator[None]:
    """Push correlation attributes onto this thread's context; every
    span/event recorded inside carries them."""
    tls = TRACER._tls
    prev = getattr(tls, "ctx", None)
    tls.ctx = {**prev, **attrs} if prev else attrs
    try:
        yield
    finally:
        tls.ctx = prev


def current_context() -> dict:
    """Snapshot of this thread's correlation context — capture it where
    work is dispatched, and :func:`attach_context` it on the thread
    that executes (thread-locals do not cross the hop)."""
    ctx = getattr(TRACER._tls, "ctx", None)
    return dict(ctx) if ctx else {}


@contextlib.contextmanager
def attach_context(ctx: Optional[dict]) -> Iterator[None]:
    """Install a captured context on the current (receiving) thread for
    the duration of the block."""
    tls = TRACER._tls
    prev = getattr(tls, "ctx", None)
    tls.ctx = dict(ctx) if ctx else None
    try:
        yield
    finally:
        tls.ctx = prev
