"""Live serving telemetry: a conf-gated sampling thread snapshotting
the engine's occupancy gauges into Chrome-trace counter tracks and
periodic event-log records.

The PR8 serving tier made the process multi-tenant, but every signal
so far is per-QUERY (spans, settled metrics, counter deltas) — nothing
shows what the fleet looks like BETWEEN query boundaries: how full the
device store is while six sessions contend, how many semaphore permits
are held, how deep the admission queue runs, whether the pipeline
stages sit full or starved.  This module is that view:

- :func:`sample_now` — one consistent gauge snapshot: device-store
  bytes by tier (device/host/disk), device-semaphore permits in use,
  serving admission queue occupancy (running/waiting), and pipeline
  stage occupancy (item-weighted, bench.py's formula);
- :class:`TelemetrySampler` — a daemon thread sampling at
  ``spark.rapids.tpu.telemetry.hz``; each sample is recorded as
  Chrome-trace COUNTER events (``ph="C"``) when the tracer is on —
  Perfetto renders them as stacked counter tracks above the span
  timeline — and every ``telemetry.eventLogEvery``-th sample appends a
  ``telemetry`` record to each attached session's event log, so
  ``tools/history`` can replay fleet load offline;
- ownership mirrors the tracer: a programmatic :func:`start` (tests)
  survives :func:`sync_conf`; conf-driven starts are owned by the
  enabling conf, and only that conf's "off" stops the thread —
  concurrent sessions attach their event-log writers to the ONE
  process sampler instead of racing thread lifecycles.

Cost discipline: disabled (the default), the per-query cost is one
enabled-flag read plus one conf read in :func:`sync_conf`; no thread
exists.  Docs: ``docs/device_ledger.md`` (live telemetry section).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Optional

from spark_rapids_tpu import trace as _tr
from spark_rapids_tpu.config import register

TELEMETRY_ENABLED = register(
    "spark.rapids.tpu.telemetry.enabled", False,
    "Enable the live telemetry sampler: a background thread "
    "snapshotting device-store bytes by tier, semaphore permits in "
    "use, serving admission queue depth and pipeline stage occupancy "
    "at telemetry.hz — into Chrome-trace counter tracks (when tracing "
    "is on) and periodic `telemetry` event-log records "
    "(docs/device_ledger.md).  Off (the default) no thread exists.")

TELEMETRY_HZ = register(
    "spark.rapids.tpu.telemetry.hz", 4.0,
    "Sampling frequency of the live telemetry thread (samples per "
    "second).  Each sample is a handful of in-process gauge reads — "
    "no device traffic — so tens of Hz are safe; the default stays "
    "low because the event-log records accumulate.",
    check=lambda v: 0.1 <= v <= 1000)

TELEMETRY_LOG_EVERY = register(
    "spark.rapids.tpu.telemetry.eventLogEvery", 4,
    "Append a `telemetry` event-log record every Nth sample (per "
    "attached session log).  Counter tracks in the trace buffer get "
    "EVERY sample; the persisted record rate is divided so long runs "
    "do not bloat their logs.",
    check=lambda v: v >= 1)


def sample_now() -> dict:
    """One flat gauge snapshot (all host-side reads, no device sync):
    the fleet-monitoring surface the sampler records.  Usable directly
    by tests and ad-hoc probes; keys are stable (the event-log
    `telemetry` record persists exactly this dict)."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.memory.store import peek_store
    from spark_rapids_tpu.parallel.pipeline import (
        live_stage_threads,
        stage_snapshot,
    )
    from spark_rapids_tpu.serving import cancel as _cancel
    from spark_rapids_tpu.serving import work_share as _ws
    from spark_rapids_tpu.serving.scheduler import queue_gauges

    # peek, never create: the singleton store snapshots budgets + the
    # spill codec from the CONSTRUCTING thread's conf, and this may be
    # the sampler thread holding defaults
    store = peek_store()
    ss = store.spill_stats() if store is not None else {
        "device_used": 0, "host_used": 0, "disk_used": 0}
    sem = TpuSemaphore.usage_now()
    adm = queue_gauges()
    weighted = items = 0.0
    for s in stage_snapshot().values():
        n = s.get("items", 0)
        if n:
            weighted += s.get("occupancy_fraction", 0.0) * n
            items += n
    return {
        "store.device_bytes": ss["device_used"],
        "store.host_bytes": ss["host_used"],
        "store.disk_bytes": ss["disk_used"],
        "semaphore.permits": sem["permits"],
        "semaphore.in_use": sem["in_use"],
        "admission.running": adm["running"],
        "admission.waiting": adm["waiting"],
        "pipeline.occupancy": round(weighted / items, 3)
        if items else 0.0,
        "pipeline.items": int(items),
        # the cancellation tier's live-serving gauges: in-flight
        # tokens, live stage producer threads and in-flight shared
        # scans — all must return to baseline after a cancellation
        # storm (docs/robustness.md)
        "cancel.active": _cancel.active_count(),
        "pipeline.stage_threads": live_stage_threads(),
        "scan.inflight": _ws.SCAN_REGISTRY.inflight(),
        # the ops-plane surfaces (docs/ops_plane.md): live registered
        # queries (0 whenever the obs plane is off — REGISTRY.count()
        # is a plain len, no lock, no conf read) and the shared
        # result-cache residency the /metrics scrape reports
        "queries.in_flight": _obs_inflight(),
        "result_cache.bytes": _ws.RESULT_CACHE.bytes_used(),
        # warm-start disk-cache footprint (docs/warm_start.md): 0 with
        # no dir walk when persistence never activated in this process
        "persist_cache.bytes": _persist_bytes(),
    }


def _obs_inflight() -> int:
    from spark_rapids_tpu.obs import REGISTRY
    return REGISTRY.count()


def _persist_bytes() -> int:
    from spark_rapids_tpu.persist import cache_bytes
    return cache_bytes()


#: Chrome counter TRACKS: one ph="C" event per track per sample, the
#: series within a track stacked by Perfetto (name -> sample keys)
_COUNTER_TRACKS = (
    ("telemetry.store_bytes", (("device", "store.device_bytes"),
                               ("host", "store.host_bytes"),
                               ("disk", "store.disk_bytes"))),
    ("telemetry.semaphore", (("in_use", "semaphore.in_use"),)),
    ("telemetry.admission", (("running", "admission.running"),
                             ("waiting", "admission.waiting"))),
    ("telemetry.pipeline_occupancy",
     (("occupancy", "pipeline.occupancy"),)),
    ("telemetry.queries", (("in_flight", "queries.in_flight"),)),
    ("telemetry.result_cache_bytes",
     (("bytes", "result_cache.bytes"),)),
    ("telemetry.persist_cache_bytes",
     (("bytes", "persist_cache.bytes"),)),
)


class TelemetrySampler:
    """The process sampler (see module doc).  ``enabled`` is the
    fast-path guard; writers are held by WEAKREF so a session going
    away never leaks its log into the sampler."""

    def __init__(self) -> None:
        self.enabled = False
        self.forced = False
        self.hz = float(TELEMETRY_HZ.default)
        self.log_every = int(TELEMETRY_LOG_EVERY.default)
        self.samples = 0
        self._enabled_by: Optional[weakref.ref] = None
        self._writers: list[weakref.ref] = []
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------- #

    def start(self, hz: Optional[float] = None,
              log_every: Optional[int] = None,
              forced: bool = True) -> None:
        with self._lock:
            if hz is not None:
                self.hz = float(hz)
            if log_every is not None:
                self.log_every = int(log_every)
            self.forced = self.forced or forced
            if self.enabled:
                return
            self.enabled = True
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._run, args=(self._stop_evt,),
                name="tpu-telemetry", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        """Stop and JOIN the sampler thread — leak-free by contract:
        after stop() returns, no telemetry thread exists (the
        concurrent-sessions test counts threads across start/stop
        cycles)."""
        with self._lock:
            if not self.enabled:
                self.forced = False
                self._enabled_by = None
                return
            self.enabled = False
            self.forced = False
            self._enabled_by = None
            evt, t = self._stop_evt, self._thread
            self._thread = None
        evt.set()
        if t is not None:
            t.join()

    def attach_writer(self, writer) -> None:
        """Register one session's event-log writer for periodic
        `telemetry` records (weakref; dedup; dead refs pruned on each
        emit)."""
        if writer is None:
            return
        with self._lock:
            for r in self._writers:
                if r() is writer:
                    return
            self._writers.append(weakref.ref(writer))

    # -- sampling loop ----------------------------------------------- #

    def _run(self, stop_evt: threading.Event) -> None:
        n = 0
        while not stop_evt.wait(1.0 / max(self.hz, 0.1)):
            try:
                sample = sample_now()
            except Exception:
                continue  # a torn gauge read must not kill the thread
            n += 1
            with self._lock:
                self.samples += 1
            self._emit_counters(sample)
            if n % max(1, self.log_every) == 0:
                self._emit_eventlog(sample)

    @staticmethod
    def _emit_counters(sample: dict) -> None:
        if not _tr.TRACER.enabled:
            return
        ts = time.perf_counter_ns()
        for track, series in _COUNTER_TRACKS:
            _tr.TRACER.record(
                track, ts, 0,
                {label: sample[key] for label, key in series},
                ph="C")

    def _emit_eventlog(self, sample: dict) -> None:
        with self._lock:
            refs = list(self._writers)
        live = []
        for r in refs:
            w = r()
            if w is None:
                continue
            live.append(r)
            try:
                w.log_telemetry(sample)
            except Exception:
                pass  # a full disk must not kill the sampler
        if len(live) != len(refs):
            with self._lock:
                self._writers = [r for r in self._writers
                                 if r() is not None]


#: THE process sampler
SAMPLER = TelemetrySampler()


def is_enabled() -> bool:
    return SAMPLER.enabled


def start(hz: Optional[float] = None,
          log_every: Optional[int] = None) -> None:
    """Force the sampler on (tests): survives sync_conf."""
    SAMPLER.start(hz=hz, log_every=log_every, forced=True)


def stop() -> None:
    SAMPLER.stop()


def sync_conf(conf=None, writer=None) -> None:
    """Query-boundary alignment with the session conf (tracer
    ownership discipline): the conf that enables the sampler owns it;
    another session's defaults-only conf cannot stop it mid-flight; a
    forced start() wins over confs entirely.  `writer` (the session's
    event-log writer, may be None) is attached so the sampler's
    periodic `telemetry` records land in every enabled session's
    log."""
    from spark_rapids_tpu.config import get_conf

    conf = conf or get_conf()
    if SAMPLER.forced:
        if SAMPLER.enabled:
            SAMPLER.attach_writer(writer)
        return
    want = bool(conf.get(TELEMETRY_ENABLED))
    if want:
        if not SAMPLER.enabled:
            SAMPLER.start(hz=float(conf.get(TELEMETRY_HZ)),
                          log_every=int(conf.get(TELEMETRY_LOG_EVERY)),
                          forced=False)
        SAMPLER._enabled_by = weakref.ref(conf)
        SAMPLER.attach_writer(writer)
    elif SAMPLER.enabled and SAMPLER._enabled_by is not None \
            and SAMPLER._enabled_by() is conf:
        SAMPLER.stop()
