"""Connect client: submit serialized plans over TCP, receive Arrow.

The client half of the Spark Connect-style ingress (docs/connect.md).
This module deliberately imports NOTHING from the engine — stdlib
sockets plus pyarrow only — so a client process stays engine-free: no
session, no planner, no execs, no device runtime (the wire-parity test
asserts exactly that on a subprocess, and
``python -m spark_rapids_tpu.tools.connect_client`` is the packaged
stand-alone entry point).  The server (connect/server.py) imports the
framing helpers from HERE, so both ends share one wire contract.

Wire format (one frame):

    <u64 little-endian length> <1-byte tag> <payload>

``length`` counts the tag byte plus the payload and is clamped against
a maximum BEFORE any allocation on both ends (tpulint SRC014 enforces
the server side).  Tags: ``J`` = JSON control, ``A`` = one Arrow IPC
stream carrying one record batch.  A request is one J frame; the
response is a J header, zero or more A frames (one per device batch —
socket backpressure propagates straight into the engine's bounded
prefetch queue), and a J trailer carrying rows/batches or the error.

Trace propagation (docs/ops_plane.md): a request MAY carry an optional
``"trace": {"trace_id": <16 hex>, "span_id": <id>}`` object — the
client mints the trace id (:func:`mint_trace_id`, still engine-free)
and the server installs it as correlation context around the query, so
every server-side span of that query is tagged with the client's id.
Servers ignore the field when absent; old servers ignore it entirely
(it is just one more JSON key), so the frame stays wire-compatible.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import struct
import time
from typing import Iterator, Optional, Union

#: default frame clamp, mirroring spark.rapids.tpu.connect.maxFrameBytes
DEFAULT_MAX_FRAME_BYTES = 64 << 20

TAG_JSON = b"J"
TAG_ARROW = b"A"


class ConnectError(RuntimeError):
    """Protocol-level failure (framing, transport, server rejection).
    ``kind`` carries the server's error class when one was reported
    (e.g. ``translate_error``, ``deadline_exceeded``,
    ``admission_rejected``)."""

    def __init__(self, message: str, kind: str = "protocol"):
        super().__init__(message)
        self.kind = kind


# ------------------------------------------------------------------ #
# Framing (shared with the server)
# ------------------------------------------------------------------ #


def send_frame(sock: socket.socket, tag: bytes, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload) + 1) + tag + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket,
               max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES
               ) -> tuple[bytes, bytes]:
    """Read one ``(tag, payload)`` frame.  The length is validated
    against ``max_frame_bytes`` BEFORE any payload allocation — an
    oversized or nonsensical length costs 8 bytes of read, never a
    giant bytearray (the SRC014 contract)."""
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    if n < 1 or n > max_frame_bytes:
        raise ConnectError(
            f"frame length {n} outside (0, {max_frame_bytes}] — "
            "oversized or corrupt frame")
    body = _recv_exact(sock, n)
    return body[:1], body[1:]


def recv_json(sock: socket.socket,
              max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES) -> dict:
    tag, payload = recv_frame(sock, max_frame_bytes)
    if tag != TAG_JSON:
        raise ConnectError(f"expected JSON frame, got tag {tag!r}")
    try:
        out = json.loads(payload.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ConnectError(f"malformed JSON frame: {e}") from None
    if not isinstance(out, dict):
        raise ConnectError("JSON frame must carry an object")
    return out


def mint_trace_id() -> str:
    """A fresh 16-hex-char wire trace id (engine-free: os.urandom, no
    engine tracer import).  The client stamps it on each request's
    optional ``trace`` field; the server installs it as correlation
    context, so both sides' spans merge onto one timeline
    (trace/export.merge_wire_trace; docs/ops_plane.md)."""
    return os.urandom(8).hex()


def table_digest(tbl) -> str:
    """Engine-free mirror of eventlog.table_digest: sha256 of the
    combined table's Arrow IPC stream bytes, truncated to 16 hex
    chars — the two ends agree bit-for-bit exactly when the results
    do."""
    import pyarrow as pa

    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        for b in tbl.combine_chunks().to_batches():
            w.write_batch(b)
    return hashlib.sha256(memoryview(sink.getvalue())).hexdigest()[:16]


# ------------------------------------------------------------------ #
# Client
# ------------------------------------------------------------------ #


class ConnectClient:
    """One connection to a ConnectServer.  Requests are sequential per
    connection (the Spark Connect ExecutePlan shape); reconnect or open
    more clients for concurrency.  Usable as a context manager."""

    def __init__(self, host: str, port: int,
                 tenant: str = "default",
                 timeout: float = 120.0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 trace: bool = False):
        self.tenant = tenant
        self._max_frame = max_frame_bytes
        #: wire trace propagation (docs/ops_plane.md): with trace=True
        #: every request carries {"trace": {"trace_id", "span_id"}} and
        #: the client records send / first-byte / last-byte spans into
        #: ``trace_spans`` as plain dicts — perf_counter_ns timestamps,
        #: the engine tracer's clock, so an in-process loopback merges
        #: onto ONE Chrome-trace timeline (export.merge_wire_trace)
        self.trace_id: Optional[str] = mint_trace_id() if trace \
            else None
        self.trace_spans: list[dict] = []
        self._span_seq = 0
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)

    # -- lifecycle -- #

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ConnectClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- requests -- #

    def ping(self) -> bool:
        send_frame(self._sock, TAG_JSON,
                   json.dumps({"op": "ping"}).encode())
        return bool(recv_json(self._sock, self._max_frame).get("pong"))

    def execute_plan(self, plan: Union[str, dict], **kw):
        """Submit a Substrait plan (JSON text or dict); returns the
        full result as one pyarrow Table.  Keywords: ``conf`` (session
        conf overrides), ``deadline_ms`` (becomes
        spark.rapids.tpu.serving.deadlineMs server-side), and
        ``batch_rows``."""
        import pyarrow as pa

        tbls = list(self.execute_plan_stream(plan, **kw))
        if not tbls:
            return pa.table({})
        # concat (not from_batches): a 0-row frame still carries the
        # result schema, and the reassembled table must keep it
        return pa.concat_tables(tbls)

    def execute_plan_stream(self, plan: Union[str, dict],
                            conf: Optional[dict] = None,
                            params: Optional[dict] = None,
                            deadline_ms: Optional[float] = None,
                            batch_rows: Optional[int] = None,
                            sql: Optional[str] = None) -> Iterator:
        """Stream the result: yields one pyarrow Table per response
        Arrow frame (= one device batch).  ``plan`` may be None when
        ``sql`` text is given instead."""
        req: dict = {"op": "execute_plan", "tenant": self.tenant}
        if plan is not None:
            req["plan"] = plan
        if sql is not None:
            req["sql"] = sql
        if conf:
            req["conf"] = dict(conf)
        if params:
            req["params"] = dict(params)
        if deadline_ms is not None:
            req["deadline_ms"] = float(deadline_ms)
        if batch_rows is not None:
            req["batch_rows"] = int(batch_rows)
        span_attrs = None
        if self.trace_id is not None:
            self._span_seq += 1
            span_id = f"{self.trace_id}.{self._span_seq}"
            req["trace"] = {"trace_id": self.trace_id,
                            "span_id": span_id}
            span_attrs = {"trace_id": self.trace_id,
                          "span_id": span_id}
        t0 = time.perf_counter_ns()
        send_frame(self._sock, TAG_JSON, json.dumps(req).encode())
        t_sent = time.perf_counter_ns()
        head = recv_json(self._sock, self._max_frame)
        t_first = time.perf_counter_ns()
        if span_attrs is not None:
            # client-side wire spans: request serialization+send, then
            # time-to-first-byte (the server's admit+translate+first
            # batch sit inside it on the merged timeline)
            self.trace_spans.append(
                {"name": "connect.client.send", "ph": "X",
                 "ts_ns": t0, "dur_ns": t_sent - t0,
                 "attrs": dict(span_attrs)})
            self.trace_spans.append(
                {"name": "connect.client.first_byte", "ph": "X",
                 "ts_ns": t_sent, "dur_ns": t_first - t_sent,
                 "attrs": dict(span_attrs)})
        if not head.get("ok"):
            raise ConnectError(head.get("error", "server error"),
                               kind=head.get("kind", "server"))
        import pyarrow as pa

        try:
            while True:
                tag, payload = recv_frame(self._sock, self._max_frame)
                if tag == TAG_ARROW:
                    with pa.ipc.open_stream(
                            pa.py_buffer(payload)) as rd:
                        yield rd.read_all()
                    continue
                if tag != TAG_JSON:
                    raise ConnectError(
                        f"unexpected frame tag {tag!r}")
                trailer = json.loads(payload.decode())
                if not trailer.get("ok"):
                    raise ConnectError(
                        trailer.get("error", "stream failed"),
                        kind=trailer.get("kind", "server"))
                return
        finally:
            if span_attrs is not None:
                t_last = time.perf_counter_ns()
                self.trace_spans.append(
                    {"name": "connect.client.last_byte", "ph": "X",
                     "ts_ns": t_first, "dur_ns": t_last - t_first,
                     "attrs": dict(span_attrs)})

    def execute_sql(self, sql: str, **kw):
        """SQL-text convenience: same wire op with ``sql`` instead of a
        Substrait plan (``params`` binds :name placeholders)."""
        return self.execute_plan(None, sql=sql, **kw)
