"""Connect server: the wire-facing plan ingress (docs/connect.md).

A ThreadingTCPServer on the shuffle/net.py length-prefixed framing
idiom (framing shared with the engine-free client, connect/client.py).
One connection = one sequential request loop (the Spark Connect
ExecutePlan shape); each connection gets its own engine session per
(tenant, conf-override) combination, so concurrent tenants ride the
process-wide serving substrate — weighted-fair admission, the
prepared-plan cache keyed by the wire plan's structural key, the
cross-tenant result/scan caches — while never sharing a mutable conf.

Serving-seam discipline (tpulint SRC014): nothing here calls
``DataFrame.collect()``; every query drains through
``PreparedQuery.execute_stream`` → ``_stream_tpu`` — admission,
cancellation, sharing, history and the event log all engage exactly as
for an in-process query, and the per-query record carries a ``connect``
section (peer, wire_bytes, translate_ms).

Failure contract:

- translate errors (bad Substrait / SQL outside the subset), admission
  rejection, quarantine and deadline expiry are reported as error
  frames; the connection stays usable (and the server certainly
  survives);
- malformed or oversized frames get an error frame and close ONLY that
  connection — the length clamp runs before any allocation;
- a dropped client connection cancels the in-flight query via its
  CancelToken, so the engine unwinds cooperatively (admission slot
  released, partial metrics recorded as a cancelled outcome).
"""

from __future__ import annotations

import contextlib
import json
import socketserver
import threading
import time
from typing import Optional

from spark_rapids_tpu.connect import (
    BATCH_ROWS,
    MAX_FRAME_BYTES,
    SEND_BUFFER_BYTES,
    SOCKET_TIMEOUT_S,
)
from spark_rapids_tpu.connect.client import (
    TAG_ARROW,
    TAG_JSON,
    ConnectError,
    recv_frame,
    send_frame,
)


class _SessionState:
    """Per-connection engine state for one (tenant, conf-overrides)
    combination: the Substrait and SQL frontends share one TpuSession
    (one plan cache, one event log, one tenant identity)."""

    def __init__(self, catalog: dict, base_conf: dict,
                 overrides: dict, tenant: str):
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.frontends.sql import SqlSession
        from spark_rapids_tpu.frontends.substrait import (
            SubstraitFrontend,
        )

        conf = TpuConf()
        # pin the value set to the server's frozen snapshot + the
        # request overrides — NOT whatever the registry holds at this
        # connection's construction time.  Confs register lazily
        # (including per-expression kill-switches minted at tagging),
        # so two otherwise-identical sessions built before/after the
        # first query would fingerprint differently and fork every
        # fingerprint-keyed cache (plan cache, cross-tenant result
        # cache).  Unregistered keys fall back to registry defaults
        # through TpuConf.get.
        conf._values = dict(base_conf)
        for k, v in overrides.items():
            conf.set(k, v)
        self.conf = conf
        self.substrait = SubstraitFrontend(conf)
        self.session = self.substrait._session
        self.session.tenant = tenant
        self.sql = SqlSession(session=self.session)
        for name, source in catalog.items():
            self.substrait.register_table(name, source)
            self._register_sql(name, source)

    def _register_sql(self, name: str, source) -> None:
        import pyarrow as pa

        if isinstance(source, pa.Table):
            self.sql.register_table(name, source)
        else:
            paths = [source] if isinstance(source, str) else list(source)
            self.sql.register_parquet(name, *paths)


class _ConnectHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        srv = self.server
        conf = srv.base_tpu_conf  # type: ignore[attr-defined]
        max_frame = conf.get(MAX_FRAME_BYTES)
        self.request.settimeout(conf.get(SOCKET_TIMEOUT_S))
        sndbuf = conf.get(SEND_BUFFER_BYTES)
        if sndbuf:
            import socket as _socket

            self.request.setsockopt(_socket.SOL_SOCKET,
                                    _socket.SO_SNDBUF, int(sndbuf))
        peer = "%s:%s" % self.client_address[:2]
        states: dict[tuple, _SessionState] = {}
        while True:
            try:
                tag, payload = recv_frame(self.request, max_frame)
                if tag != TAG_JSON:
                    raise ConnectError(
                        f"expected JSON frame, got tag {tag!r}")
                try:
                    req = json.loads(payload.decode())
                except (UnicodeDecodeError,
                        json.JSONDecodeError) as je:
                    raise ConnectError(
                        f"malformed JSON frame: {je}") from None
                if not isinstance(req, dict):
                    raise ConnectError("JSON frame must carry an "
                                       "object")
            except ConnectError as e:
                # EOF mid-length-read is a normal disconnect; anything
                # else (oversized length, bad tag, bad JSON) gets a
                # best-effort error frame — either way only THIS
                # connection closes
                if "closed mid-frame" not in str(e):
                    self._reply_error(str(e), "bad_frame")
                return
            except OSError:
                return
            op = req.get("op")
            if op == "ping":
                self._reply({"ok": True, "pong": True})
                continue
            if op != "execute_plan":
                self._reply_error(f"unknown op {op!r}", "bad_request")
                continue
            try:
                # the exact request bytes as framed on the wire (the
                # length recv_frame already validated), not a re-dump
                self._execute(srv, states, req, peer, len(payload))
            except OSError:
                return  # client gone; _execute already cancelled

    # -- replies ----------------------------------------------------- #

    def _reply(self, obj: dict) -> None:
        send_frame(self.request, TAG_JSON, json.dumps(obj).encode())

    def _reply_error(self, message: str, kind: str) -> None:
        try:
            self._reply({"ok": False, "error": message, "kind": kind})
        except OSError:
            pass

    # -- the one query path ------------------------------------------ #

    def _execute(self, srv, states: dict, req: dict, peer: str,
                 wire_bytes: int) -> None:
        from spark_rapids_tpu.frontends.sql import SqlError
        from spark_rapids_tpu.frontends.substrait import SubstraitError
        from spark_rapids_tpu.serving.cancel import DEADLINE_MS

        tenant = str(req.get("tenant") or "default")
        overrides = dict(req.get("conf") or {})
        key = (tenant, tuple(sorted(
            (str(k), str(v)) for k, v in overrides.items())))
        state = states.get(key)
        if state is None:
            state = states[key] = _SessionState(
                srv.catalog, srv.base_conf_values, overrides, tenant)
        # the wire deadline becomes serving.deadlineMs for THIS request
        # (restored to the PRE-REQUEST value right after — which may
        # itself be a session-level conf override; requests on one
        # connection are sequential, and restoring the prior value
        # restores the constructed conf fingerprint)
        deadline = req.get("deadline_ms")
        if deadline is not None:
            prev_deadline = state.conf.get(DEADLINE_MS)
            state.conf.set(DEADLINE_MS.key, float(deadline))
        try:
            t0 = time.perf_counter()
            try:
                if req.get("sql") is not None:
                    pq = state.sql.prepare(str(req["sql"]))
                    params = self._decode_params(req.get("params"))
                else:
                    plan = req.get("plan")
                    if plan is None:
                        raise ConnectError(
                            "execute_plan needs 'plan' or 'sql'",
                            kind="bad_request")
                    df = state.substrait.dataframe(plan)
                    pq = state.session.prepare(df)
                    params = None
            except (SubstraitError, SqlError, ConnectError,
                    KeyError, TypeError, ValueError) as e:
                self._reply_error(
                    f"{type(e).__name__}: {e}", "translate_error")
                return
            translate_ms = (time.perf_counter() - t0) * 1e3
            batch_rows = int(req.get("batch_rows")
                             or state.conf.get(BATCH_ROWS) or 0) or None
            facts = {"connect": {
                "peer": peer, "wire_bytes": wire_bytes,
                "translate_ms": round(translate_ms, 3)}}
            # wire trace propagation (docs/ops_plane.md): install the
            # client-minted trace id as correlation context around the
            # drain — _stream_tpu's trace_context(query_id=...) MERGES
            # onto this, so every server span of the query carries the
            # inbound id and the two sides join on one timeline.  The
            # id also rides the record's connect section.
            trace = req.get("trace")
            tctx = {}
            if isinstance(trace, dict) and trace.get("trace_id"):
                tctx = {"trace_id": str(trace["trace_id"])}
                if trace.get("span_id"):
                    tctx["parent_span_id"] = str(trace["span_id"])
                facts["connect"]["trace_id"] = tctx["trace_id"]
            from spark_rapids_tpu import trace as _trace

            with (_trace.trace_context(**tctx) if tctx
                  else contextlib.nullcontext()):
                self._stream_result(pq, params, batch_rows, facts)
        finally:
            if deadline is not None:
                state.conf.set(DEADLINE_MS.key, prev_deadline)

    @staticmethod
    def _decode_params(raw: Optional[dict]) -> Optional[dict]:
        """JSON carries no date type: ``{"name": {"date":
        "2001-01-02"}}`` binds a date parameter; everything else binds
        as-is."""
        if not raw:
            return None
        import datetime as _dt

        out = {}
        for k, v in raw.items():
            if isinstance(v, dict) and set(v) == {"date"}:
                v = _dt.date.fromisoformat(v["date"])
            out[k] = v
        return out

    def _stream_result(self, pq, params, batch_rows: Optional[int],
                       facts: dict) -> None:
        """Drain one prepared query to the socket: J header, one A
        frame per record batch off the engine's streaming fetch path
        (socket backpressure stalls the producer, not the process), J
        trailer.  A send failure = the client dropped — cancel the
        in-flight query via its CancelToken and let it unwind
        cooperatively before closing."""
        import pyarrow as pa

        from spark_rapids_tpu.serving.cancel import (
            QueryCancelled,
            TenantQuarantined,
        )
        from spark_rapids_tpu.serving.scheduler import AdmissionRejected

        gen = pq.execute_stream(params=params, batch_rows=batch_rows,
                                extra_facts=facts)
        rows = 0
        batches = 0
        sent_header = False
        try:
            while True:
                try:
                    rb = next(gen)
                except StopIteration:
                    break
                except QueryCancelled as e:
                    self._reply_error(str(e), e.reason)
                    return
                except (TenantQuarantined, AdmissionRejected) as e:
                    self._reply_error(str(e), "admission_rejected")
                    return
                except Exception as e:  # noqa: BLE001 — wire boundary:
                    # the engine already classified/recorded; the
                    # client gets the terminal error frame
                    self._reply_error(
                        f"{type(e).__name__}: {e}", "execution_error")
                    return
                if not sent_header:
                    self._reply({"ok": True})
                    sent_header = True
                try:
                    sink = pa.BufferOutputStream()
                    with pa.ipc.new_stream(sink, rb.schema) as w:
                        w.write_batch(rb)
                    send_frame(self.request, TAG_ARROW,
                               sink.getvalue().to_pybytes())
                except OSError:
                    # client dropped mid-stream: cancel via the
                    # token, then drain to the cancellation point so
                    # the engine records the cancelled outcome and
                    # releases its admission slot (already-produced
                    # batches yield without a checkpoint; the token
                    # raises at the next production checkpoint), then
                    # propagate the disconnect
                    pq.cancel(reason="cancelled")
                    try:
                        for _ in gen:
                            pass
                    except QueryCancelled:
                        pass
                    raise
                rows += rb.num_rows
                batches += 1
            if not sent_header:
                self._reply({"ok": True})
            if batches == 0:
                # an empty result still carries its SCHEMA: one empty
                # Arrow frame, so the client reassembles a
                # schema-bearing 0-row table bit-identical to an
                # in-process collect (not a columnless placeholder)
                from spark_rapids_tpu.columnar.arrow import (
                    schema_to_arrow,
                )

                entry, _hit = pq._resolve(params)  # cached
                aschema = schema_to_arrow(entry.exec_.schema)
                empty = pa.RecordBatch.from_arrays(
                    [pa.array([], type=f.type) for f in aschema],
                    schema=aschema)
                sink = pa.BufferOutputStream()
                with pa.ipc.new_stream(sink, aschema) as w:
                    w.write_batch(empty)
                send_frame(self.request, TAG_ARROW,
                           sink.getvalue().to_pybytes())
                batches = 1
            self._reply({"ok": True, "rows": rows, "batches": batches})
        finally:
            gen.close()


class ConnectServer:
    """The wire front door: register tables, start, take queries.

    ``conf`` seeds every connection session (per-request overrides
    layer on top); ``catalog`` entries are pyarrow Tables or parquet
    path(s), registered under their name for both the Substrait
    (namedTable) and SQL frontends of every connection."""

    def __init__(self, conf=None, host: str = "127.0.0.1",
                 port: int = 0):
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.tools.gen_docs import (
            load_conf_registrars,
        )

        # complete the conf registry BEFORE any session conf is
        # snapshotted: a lazily-registered conf appearing between two
        # connections would fork their fingerprints and split every
        # fingerprint-keyed cache (plan cache, result cache) across
        # tenants issuing identical queries
        load_conf_registrars()
        self.base_conf = conf if conf is not None else TpuConf()
        self.catalog: dict = {}
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _ConnectHandler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.base_tpu_conf = self.base_conf
        # raw (key, value) overrides a _SessionState reconstructs its
        # TpuConf from: the base conf's non-default values
        self._srv.base_conf_values = dict(self.base_conf._values)
        self._srv.catalog = self.catalog
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="tpu-connect-server")

    def register_table(self, name: str, source) -> None:
        """``source``: pa.Table, or parquet path(s).  Takes effect for
        connections opened after the call."""
        self.catalog[name.lower()] = source

    @property
    def address(self) -> tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "ConnectServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
