"""Spark Connect-style plan ingress: the engine's wire front door.

The reference's defining capability is "the user's job, unchanged" — a
real Spark hands its plans to the plugin seam (ref: SQLPlugin.scala:
26-31) and the plugin accelerates whatever Catalyst produced.  The
TPU-idiomatic mirror (ROADMAP #5, VERDICT missing #1) is this package:
an external process serializes a plan, ships it over TCP, and the FULL
serving stack executes it —

- ``connect/server.py``: length-prefixed framed TCP server (the
  shuffle/net.py idiom) accepting ExecutePlan-style requests — a
  Substrait plan (JSON or dict form) or SQL text, plus session conf
  overrides, SQL parameter bindings, a tenant id, and an optional
  deadline — translated through the existing frontends
  (frontends/substrait.py, frontends/sql.py) and routed through
  admission control + weighted-fair queuing, the prepared-plan cache
  keyed by the wire plan's structural key, cross-tenant result/scan
  sharing, and cancellation/deadline propagation: a dropped client
  connection cancels the in-flight query via its CancelToken, and a
  wire deadline becomes ``spark.rapids.tpu.serving.deadlineMs``
  (enforced from the admission queue — expiry while queued sheds with
  zero device work);
- ``connect/client.py``: the engine-free client (stdlib + pyarrow
  ONLY) plus the shared framing helpers; results stream back as Arrow
  IPC frames, one per device batch, backpressured by the socket;
- ``python -m spark_rapids_tpu.tools.connect_client``: the stand-alone
  CLI client.

Auth posture: none — the server binds loopback by default and trusts
its network, like the reference's shuffle transport (docs/connect.md).
"""

from __future__ import annotations

from spark_rapids_tpu.config import register

MAX_FRAME_BYTES = register(
    "spark.rapids.tpu.connect.maxFrameBytes", 64 << 20,
    "Upper bound on one connect wire frame (request JSON or response "
    "Arrow IPC batch).  The length prefix is validated against this "
    "BEFORE any payload allocation on both ends (tpulint SRC014), so "
    "a corrupt or hostile length costs 8 bytes of read, never a giant "
    "allocation; oversized requests are rejected with an error frame "
    "and the connection closed, without killing the server.",
    check=lambda v: v >= 1024)

BATCH_ROWS = register(
    "spark.rapids.tpu.connect.batchRows", 0,
    "Row cap per response Arrow frame (0 = the engine's device batch "
    "size as produced by the streaming fetch path).  A wire request's "
    "batch_rows field overrides per query.",
    check=lambda v: v >= 0)

SEND_BUFFER_BYTES = register(
    "spark.rapids.tpu.connect.sendBufferBytes", 0,
    "SO_SNDBUF for response streaming on the server side (0 = OS "
    "default).  Smaller buffers tighten the backpressure loop — the "
    "engine's bounded prefetch stalls as soon as the CLIENT stops "
    "reading, instead of after megabytes of kernel buffering — at "
    "the cost of more syscalls; the disconnect-cancellation tests "
    "pin it low to make client-drop detection deterministic.",
    check=lambda v: v >= 0)

SOCKET_TIMEOUT_S = register(
    "spark.rapids.tpu.connect.socketTimeoutSeconds", 120.0,
    "Per-connection socket timeout on the server (reads of the next "
    "request and writes of response frames).  A stalled or vanished "
    "client trips this, the handler cancels any in-flight query via "
    "its CancelToken and the connection closes; other connections are "
    "unaffected.",
    check=lambda v: v > 0)
