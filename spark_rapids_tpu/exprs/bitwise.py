"""Bitwise and shift expressions (ref: sql-plugin/.../bitwise.scala).

Shift semantics follow Java/Spark: the shift amount is masked by the
value's bit width (x << 65 == x << 1 for longs), and >>> zero-fills."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    broadcast_validity,
)


@dataclasses.dataclass(repr=False)
class BitwiseBinary(Expression):
    left: Expression
    right: Expression

    fn = staticmethod(jnp.bitwise_and)

    @property
    def dtype(self) -> T.DataType:
        ct = T.common_type(self.left.dtype, self.right.dtype)
        if ct is None or not isinstance(ct, T.IntegralType):
            raise TypeError("bitwise op requires integral operands")
        return ct

    def eval(self, ctx: EvalContext) -> AnyColumn:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        phys = T.to_numpy_dtype(self.dtype)
        out = type(self).fn(l.data.astype(phys), r.data.astype(phys))
        return Column(out, broadcast_validity(l, r), self.dtype)


class BitwiseAnd(BitwiseBinary):
    fn = staticmethod(jnp.bitwise_and)


class BitwiseOr(BitwiseBinary):
    fn = staticmethod(jnp.bitwise_or)


class BitwiseXor(BitwiseBinary):
    fn = staticmethod(jnp.bitwise_xor)


@dataclasses.dataclass(repr=False)
class BitwiseNot(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(~c.data, c.validity, self.dtype)


@dataclasses.dataclass(repr=False)
class ShiftLeft(Expression):
    left: Expression
    right: Expression  # shift amount (int)

    @property
    def dtype(self) -> T.DataType:
        return self.left.dtype

    def _bits(self) -> int:
        return 64 if isinstance(self.left.dtype, T.LongType) else 32

    def _shift(self, ld, amount):
        return ld << amount

    def eval(self, ctx: EvalContext) -> AnyColumn:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        bits = self._bits()
        phys = jnp.int64 if bits == 64 else jnp.int32
        amount = r.data.astype(phys) & (bits - 1)  # Java masks the shift
        out = self._shift(l.data.astype(phys), amount)
        return Column(out, broadcast_validity(l, r), self.dtype)


class ShiftRight(ShiftLeft):
    def _shift(self, ld, amount):
        return ld >> amount  # arithmetic (sign-propagating)


class ShiftRightUnsigned(ShiftLeft):
    def _shift(self, ld, amount):
        u = jnp.uint64 if ld.dtype == jnp.int64 else jnp.uint32
        return (ld.astype(u) >> amount.astype(u)).astype(ld.dtype)
