"""Spark-compatible Murmur3 hashing, vectorized for XLA.

Counterpart of the reference's HashFunctions.scala (GpuMurmur3Hash) whose
whole purpose is *bit-for-bit parity with Spark CPU hash partitioning*
(ref: sql-plugin/.../org/apache/spark/sql/rapids/HashFunctions.scala and
GpuHashPartitioning.scala).  Spark's hash is Murmur3 x86_32 with Spark's
own quirks (from `Murmur3_x86_32.hashUnsafeBytes` in spark-catalyst):

- ints/smaller + float + boolean + date hash as a single 4-byte block;
- longs + double + timestamp hash as two 4-byte blocks (low word first);
- strings hash their UTF-8 bytes: each aligned 4-byte little-endian block
  through mixK1/mixH1, then *each remaining tail byte individually*
  (sign-extended!) through mixK1/mixH1 — this differs from canonical
  murmur3's tail handling and is required for parity;
- NULL columns leave the running seed untouched;
- multi-column hash chains: seed of column i+1 = hash of column i;
  default initial seed is 42.

All arithmetic is uint32 with wrap-around, which XLA vectorizes cleanly on
the VPU; the string path is a static unroll over the fixed byte-matrix
width (W/4 block steps + W masked tail steps).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Union

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn
from spark_rapids_tpu.exprs.base import EvalContext, Expression

# plain ints (weak-typed: uint32 math stays uint32) so kernels that
# import the mix functions don't capture device constants
_C1 = 0xCC9E2D51
_C2 = 0x1B873593

DEFAULT_SEED = 42


def _u32(x) -> jax.Array:
    return jnp.asarray(x).astype(jnp.uint32)


def _rotl(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1: jax.Array) -> jax.Array:
    k1 = k1 * _C1
    k1 = _rotl(k1, 15)
    return k1 * _C2


def _mix_h1(h1: jax.Array, k1: jax.Array) -> jax.Array:
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1: jax.Array, length: Union[int, jax.Array]) -> jax.Array:
    h1 = h1 ^ _u32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> 16)
    return h1


def hash_int32_block(word: jax.Array, seed: jax.Array) -> jax.Array:
    """Murmur3 of a single 4-byte value (Spark hashInt)."""
    h1 = _mix_h1(_u32(seed), _mix_k1(_u32(word)))
    return _fmix(h1, 4)


# --------------------------------------------------------------------- #
# Host (numpy) mirrors of the fixed-width block hashes.
#
# The runtime-filter subsystem (plan/runtime_filter.py) builds its Bloom
# bitset ON DEVICE from build-side join keys and probes it ON HOST
# against freshly decoded scan columns — before any byte crosses the
# host->device link.  Both sides must agree bit-for-bit, so the host
# probe mirrors the jax functions above in pure numpy uint32 arithmetic
# (numpy integer ops wrap exactly like XLA's).  Any edit to the device
# functions must be mirrored here; test_runtime_filter.py pins parity
# on randomized keys.
# --------------------------------------------------------------------- #


def np_hash_int32_block(word, seed):
    """numpy mirror of :func:`hash_int32_block`: uint32[n] hashes of
    int32-block values (int/short/byte/date/bool lanes)."""
    import numpy as np

    k1 = np.asarray(word).astype(np.uint32)
    k1 = k1 * np.uint32(_C1)
    k1 = (k1 << np.uint32(15)) | (k1 >> np.uint32(17))
    k1 = k1 * np.uint32(_C2)
    h1 = np.asarray(seed).astype(np.uint32) ^ k1
    h1 = (h1 << np.uint32(13)) | (h1 >> np.uint32(19))
    h1 = h1 * np.uint32(5) + np.uint32(0xE6546B64)
    return _np_fmix(h1, 4)


def np_hash_int64_blocks(value, seed):
    """numpy mirror of :func:`hash_int64_blocks`: uint32[n] hashes of
    8-byte values, low word first (long/timestamp lanes)."""
    import numpy as np

    v = np.asarray(value).astype(np.int64)
    low = (v & np.int64(0xFFFFFFFF)).astype(np.uint32)
    high = ((v >> np.int64(32)) & np.int64(0xFFFFFFFF)).astype(np.uint32)
    h1 = np.asarray(seed).astype(np.uint32)
    for k1 in (low, high):
        k1 = k1 * np.uint32(_C1)
        k1 = (k1 << np.uint32(15)) | (k1 >> np.uint32(17))
        k1 = k1 * np.uint32(_C2)
        h1 = h1 ^ k1
        h1 = (h1 << np.uint32(13)) | (h1 >> np.uint32(19))
        h1 = h1 * np.uint32(5) + np.uint32(0xE6546B64)
    return _np_fmix(h1, 8)


def _np_fmix(h1, length: int):
    import numpy as np

    h1 = h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> np.uint32(16))
    return h1


def hash_int64_blocks(value: jax.Array, seed: jax.Array) -> jax.Array:
    """Murmur3 of an 8-byte value, low 32-bit word first (Spark hashLong)."""
    v = value.astype(jnp.int64)
    low = _u32(v & jnp.int64(0xFFFFFFFF))
    high = _u32((v >> 32) & jnp.int64(0xFFFFFFFF))
    h1 = _mix_h1(_u32(seed), _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, 8)


def _float_to_bits(x: jax.Array) -> jax.Array:
    """Java floatToIntBits: canonical NaN 0x7fc00000, else raw IEEE bits."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)
    return jnp.where(jnp.isnan(x), jnp.int32(0x7FC00000), bits)


def _double_to_bits(x: jax.Array) -> jax.Array:
    """Java doubleToLongBits: canonical NaN 0x7ff8000000000000."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.int64)
    return jnp.where(jnp.isnan(x), jnp.int64(0x7FF8000000000000), bits)


def hash_string_bytes(chars: jax.Array, lengths: jax.Array,
                      seed: jax.Array) -> jax.Array:
    """Spark hashUnsafeBytes over a fixed-width (n, W) uint8 byte matrix.

    Aligned blocks are little-endian ints; tail bytes are processed one at
    a time *sign-extended* (Platform.getByte is a signed read).

    On a TPU backend this routes to the Pallas kernel
    (ops/pallas_kernels.py) — bit-identical, but walks the byte matrix
    once per VMEM-resident row block instead of ~1.25*W masked
    full-width passes.
    """
    n, width = chars.shape
    seeds = jnp.broadcast_to(_u32(seed), (n,))
    from spark_rapids_tpu.ops.pallas_kernels import (
        maybe_pallas_hash_string,
    )

    fast = maybe_pallas_hash_string(chars, lengths.astype(jnp.int32),
                                    seeds)
    if fast is not None:
        return fast
    h1 = seeds
    lengths = lengths.astype(jnp.int32)
    aligned = lengths - (lengths % 4)
    c32 = chars.astype(jnp.uint32)
    nblocks = (width + 3) // 4
    for b in range(nblocks):
        j = b * 4

        def byte(off):
            if j + off < width:
                return c32[:, j + off]
            return jnp.zeros((n,), jnp.uint32)

        word = (byte(0) | (byte(1) << 8) | (byte(2) << 16) | (byte(3) << 24))
        in_block = jnp.int32(j + 4) <= aligned
        h1 = jnp.where(in_block, _mix_h1(h1, _mix_k1(word)), h1)
    # tail: each byte beyond the aligned prefix, sign-extended to int
    for j in range(width):
        is_tail = (jnp.int32(j) >= aligned) & (jnp.int32(j) < lengths)
        signed = chars[:, j].astype(jnp.int8).astype(jnp.int32)
        h1 = jnp.where(is_tail, _mix_h1(h1, _mix_k1(_u32(signed))), h1)
    return _fmix(h1, _u32(lengths))


def hash_column(col: AnyColumn, seed: jax.Array) -> jax.Array:
    """Hash one column into a running uint32 seed array; NULL rows keep
    the incoming seed (Spark semantics)."""
    if isinstance(col, StringColumn):
        h = hash_string_bytes(col.chars, col.lengths, seed)
        return jnp.where(col.validity, h, seed)
    dt = col.dtype
    if isinstance(dt, (T.BooleanType,)):
        h = hash_int32_block(col.data.astype(jnp.int32), seed)
    elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.DateType)):
        h = hash_int32_block(col.data.astype(jnp.int32), seed)
    elif isinstance(dt, (T.LongType, T.TimestampType, T.DecimalType)):
        h = hash_int64_blocks(col.data, seed)
    elif isinstance(dt, T.FloatType):
        # Spark normalizes -0.0f to 0.0f before hashing
        x = col.data.astype(jnp.float32)
        x = jnp.where(x == 0.0, jnp.float32(0.0), x)
        h = hash_int32_block(_float_to_bits(x), seed)
    elif isinstance(dt, T.DoubleType):
        x = col.data.astype(jnp.float64)
        x = jnp.where(x == 0.0, jnp.float64(0.0), x)
        h = hash_int64_blocks(_double_to_bits(x), seed)
    else:
        raise TypeError(f"murmur3 unsupported for {dt}")
    return jnp.where(col.validity, h, seed)


def hash_columns(cols: Sequence[AnyColumn], capacity: int,
                 seed: int = DEFAULT_SEED) -> jax.Array:
    """Chained multi-column Spark hash -> int32 array (Spark `hash(...)`)."""
    h = jnp.full((capacity,), seed, jnp.uint32)
    for c in cols:
        h = hash_column(c, h)
    return h.astype(jnp.int32)


@dataclasses.dataclass(repr=False)
class Murmur3Hash(Expression):
    """SQL hash(exprs...) (ref: HashFunctions.scala GpuMurmur3Hash)."""

    exprs: tuple[Expression, ...]
    seed: int = DEFAULT_SEED

    def __init__(self, *exprs: Expression, seed: int = DEFAULT_SEED):
        self.exprs = tuple(exprs)
        self.seed = seed

    def with_children(self, children):
        return type(self)(*children, seed=self.seed)

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cols = [e.eval(ctx) for e in self.exprs]
        h = hash_columns(cols, ctx.batch.capacity, self.seed)
        return Column(h, ctx.row_mask, T.INT)


def partition_ids(cols: Sequence[AnyColumn], capacity: int,
                  num_partitions: int) -> jax.Array:
    """Spark hash-partitioning: pmod(hash(keys), numPartitions)
    (ref: GpuHashPartitioning.scala).  Returns int32 in [0, n)."""
    h = hash_columns(cols, capacity)
    m = h % jnp.int32(num_partitions)
    return jnp.where(m < 0, m + jnp.int32(num_partitions), m)


# --------------------------------------------------------------------- #
# MD5 (ref: HashFunctions.scala GpuMd5 -> cudf md5; Spark md5() returns
# the lowercase hex digest of the UTF-8 bytes)
# --------------------------------------------------------------------- #

_MD5_K = tuple(int(abs(__import__("math").sin(i + 1)) * (1 << 32))
               & 0xFFFFFFFF for i in range(64))
_MD5_S = (7, 12, 17, 22) * 4 + (5, 9, 14, 20) * 4 \
    + (4, 11, 16, 23) * 4 + (6, 10, 15, 21) * 4
_MD5_INIT = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476)
_HEX = tuple(b"0123456789abcdef")


def _rotl32(x: jax.Array, s: int) -> jax.Array:
    return (x << jnp.uint32(s)) | (x >> jnp.uint32(32 - s))


def md5_string_bytes(chars: jax.Array, lengths: jax.Array,
                     cap: int) -> tuple[jax.Array, jax.Array]:
    """Per-row MD5 over the fixed-width chars matrix.

    Rows of different byte lengths need different block counts; every
    row runs the full (static) block schedule but only folds a block
    into its state while the block index is below the row's own block
    count — branch-free lockstep on the VPU, the TPU shape of cudf's
    warp-per-row md5 kernel.  Returns (hex_chars[cap, 32],
    lengths[cap] == 32)."""
    w = int(chars.shape[1])
    msg_len = ((w + 9 + 63) // 64) * 64
    nblocks = msg_len // 64
    L = lengths.astype(jnp.int32)
    msg = jnp.concatenate(
        [chars, jnp.zeros((cap, msg_len - w), jnp.uint8)], axis=1)
    cols = jnp.arange(msg_len, dtype=jnp.int32)[None, :]
    msg = jnp.where(cols == L[:, None], jnp.uint8(0x80), msg)
    # per-row trailer: 64-bit little-endian BIT length at the end of
    # the row's LAST block
    row_blocks = (L + 9 + 63) // 64
    len_pos = row_blocks * 64 - 8
    bitlen = (L.astype(jnp.int64) * 8)
    for k in range(8):
        byte_k = ((bitlen >> (8 * k)) & 0xFF).astype(jnp.uint8)
        msg = jnp.where(cols == (len_pos + k)[:, None],
                        byte_k[:, None], msg)
    # bytes -> little-endian u32 words: (cap, nblocks, 16)
    bw = msg.reshape(cap, nblocks, 16, 4).astype(jnp.uint32)
    words = (bw[..., 0] | (bw[..., 1] << 8) | (bw[..., 2] << 16)
             | (bw[..., 3] << 24))

    a0 = jnp.full((cap,), _MD5_INIT[0], jnp.uint32)
    b0 = jnp.full((cap,), _MD5_INIT[1], jnp.uint32)
    c0 = jnp.full((cap,), _MD5_INIT[2], jnp.uint32)
    d0 = jnp.full((cap,), _MD5_INIT[3], jnp.uint32)
    # g-schedule per round is static; the BLOCK loop is a fori_loop so
    # the compiled graph is 64 rounds regardless of string width
    gidx = []
    for i in range(64):
        if i < 16:
            gidx.append(i)
        elif i < 32:
            gidx.append((5 * i + 1) % 16)
        elif i < 48:
            gidx.append((3 * i + 5) % 16)
        else:
            gidx.append((7 * i) % 16)

    def body(blk, state):
        a0, b0, c0, d0 = state
        active = blk < row_blocks
        m = jax.lax.dynamic_index_in_dim(words, blk, axis=1,
                                         keepdims=False)
        a, b, c, d = a0, b0, c0, d0
        for i in range(64):
            if i < 16:
                f = (b & c) | (~b & d)
            elif i < 32:
                f = (d & b) | (~d & c)
            elif i < 48:
                f = b ^ c ^ d
            else:
                f = c ^ (b | ~d)
            tmp = d
            d = c
            c = b
            rot = a + f + jnp.uint32(_MD5_K[i]) + m[:, gidx[i]]
            b = b + _rotl32(rot, _MD5_S[i])
            a = tmp
        return (jnp.where(active, a0 + a, a0),
                jnp.where(active, b0 + b, b0),
                jnp.where(active, c0 + c, c0),
                jnp.where(active, d0 + d, d0))

    a0, b0, c0, d0 = jax.lax.fori_loop(0, nblocks, body,
                                       (a0, b0, c0, d0))

    digest = jnp.stack([a0, b0, c0, d0], axis=1)  # (cap, 4) LE words
    dbytes = jnp.stack(
        [(digest >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)
         for k in range(4)], axis=2).reshape(cap, 16).astype(jnp.uint8)
    hex_lut = jnp.asarray(_HEX, jnp.uint8)
    hi = jnp.take(hex_lut, (dbytes >> 4).astype(jnp.int32))
    lo = jnp.take(hex_lut, (dbytes & 0xF).astype(jnp.int32))
    hex_chars = jnp.stack([hi, lo], axis=2).reshape(cap, 32)
    return hex_chars, jnp.full((cap,), 32, jnp.int32)


@dataclasses.dataclass(repr=False)
class Md5(Expression):
    """SQL md5(string) -> lowercase hex digest (ref:
    HashFunctions.scala GpuMd5)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def eval(self, ctx: EvalContext) -> AnyColumn:
        from spark_rapids_tpu.columnar.column import StringColumn

        col = self.child.eval(ctx)
        assert isinstance(col, StringColumn), "md5 over non-string"
        cap = ctx.batch.capacity
        hex_chars, lens = md5_string_bytes(col.chars, col.lengths, cap)
        valid = col.validity
        return StringColumn(hex_chars * valid[:, None].astype(jnp.uint8),
                            lens * valid.astype(jnp.int32), valid)
