"""Date/time expressions.

TPU counterparts of datetimeExpressions.scala (845 LoC).  DATE is int32
days since epoch; TIMESTAMP is int64 microseconds UTC (UTC-only, like
the reference: GpuOverrides.scala:439).  Civil-calendar field extraction
uses Howard Hinnant's civil_from_days algorithm — branch-free integer
arithmetic that XLA vectorizes cleanly (vs cudf's datetime kernels)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    broadcast_validity,
)

US_PER_DAY = 86_400_000_000
US_PER_HOUR = 3_600_000_000
US_PER_MINUTE = 60_000_000
US_PER_SECOND = 1_000_000


def civil_from_days(z: jax.Array):
    """days-since-epoch -> (year, month [1,12], day [1,31]).

    Hinnant's algorithm (public domain), int32-safe for the SQL date
    range."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)  # [1, 12]
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y: jax.Array, m: jax.Array, d: jax.Array) -> jax.Array:
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _leap(y):
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


_DAYS_IN_MONTH = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30,
                              31], jnp.int32)


@dataclasses.dataclass(repr=False)
class _DateField(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    def _field(self, days: jax.Array) -> jax.Array:
        raise NotImplementedError

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        days = c.data.astype(jnp.int32)
        if isinstance(self.child.dtype, T.TimestampType):
            days = (c.data.astype(jnp.int64) // US_PER_DAY).astype(jnp.int32)
        return Column(self._field(days), c.validity, T.INT)


class Year(_DateField):
    def _field(self, days):
        y, _, _ = civil_from_days(days)
        return y


class Month(_DateField):
    def _field(self, days):
        _, m, _ = civil_from_days(days)
        return m


class DayOfMonth(_DateField):
    def _field(self, days):
        _, _, d = civil_from_days(days)
        return d


class Quarter(_DateField):
    def _field(self, days):
        _, m, _ = civil_from_days(days)
        return (m - 1) // 3 + 1


class DayOfWeek(_DateField):
    """Spark: Sunday=1 .. Saturday=7.  1970-01-01 was a Thursday."""

    def _field(self, days):
        return ((((days.astype(jnp.int64) + 4) % 7 + 7) % 7 + 1)
                .astype(jnp.int32))


class WeekDay(_DateField):
    """Spark weekday(): Monday=0 .. Sunday=6."""

    def _field(self, days):
        return (((days.astype(jnp.int64) + 3) % 7 + 7) % 7).astype(jnp.int32)


class DayOfYear(_DateField):
    def _field(self, days):
        y, _, _ = civil_from_days(days)
        jan1 = days_from_civil(y, jnp.full_like(y, 1), jnp.full_like(y, 1))
        return days - jan1 + 1


@dataclasses.dataclass(repr=False)
class LastDay(Expression):
    """Last day of the input date's month -> DATE."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.DATE

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        days = c.data.astype(jnp.int32)
        y, m, _ = civil_from_days(days)
        dim = jnp.take(_DAYS_IN_MONTH, m - 1)
        dim = jnp.where((m == 2) & _leap(y), 29, dim)
        return Column(days_from_civil(y, m, dim), c.validity, T.DATE)


@dataclasses.dataclass(repr=False)
class AddMonths(Expression):
    """add_months(date, n) — calendar month shift with end-of-month
    clamping (ref: GpuAddMonths, datetimeExpressions.scala): Jan 31 +
    1 month = Feb 28 (29 in leap years).  Proleptic Gregorian on
    device via Hinnant's civil conversions, so pre-1582 dates shift
    exactly like Python's datetime does — the month/year arm of the
    SQL frontend's date-column interval arithmetic lowers here."""

    child: Expression
    months: int

    @property
    def dtype(self) -> T.DataType:
        return T.DATE

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    @property
    def name(self) -> str:
        return f"add_months({self.child.name}, {self.months})"

    @property
    def children(self) -> tuple:
        return (self.child,)

    def with_children(self, children):
        return AddMonths(children[0], self.months)

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.DateType):
            raise TypeError("AddMonths needs a date input")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        days = c.data.astype(jnp.int32)
        y, m, d = civil_from_days(days)
        mi = y.astype(jnp.int64) * 12 + (m - 1) + jnp.int64(self.months)
        # floor divmod keeps pre-year-1 months correct
        y2 = (jnp.where(mi >= 0, mi, mi - 11) // 12).astype(jnp.int32)
        m2 = (mi - y2.astype(jnp.int64) * 12).astype(jnp.int32) + 1
        dim = jnp.take(_DAYS_IN_MONTH, m2 - 1)
        dim = jnp.where((m2 == 2) & _leap(y2), 29, dim)
        d2 = jnp.minimum(d, dim)
        return Column(days_from_civil(y2, m2, d2), c.validity, T.DATE)


@dataclasses.dataclass(repr=False)
class _TimeField(Expression):
    child: Expression

    divisor = US_PER_HOUR
    modulus = 24

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        us = c.data.astype(jnp.int64)
        # floor-mod keeps pre-epoch timestamps correct
        day_us = ((us % US_PER_DAY) + US_PER_DAY) % US_PER_DAY
        out = (day_us // self.divisor) % self.modulus
        return Column(out.astype(jnp.int32), c.validity, T.INT)


class Hour(_TimeField):
    divisor = US_PER_HOUR
    modulus = 24


class Minute(_TimeField):
    divisor = US_PER_MINUTE
    modulus = 60


class Second(_TimeField):
    divisor = US_PER_SECOND
    modulus = 60


@dataclasses.dataclass(repr=False)
class DateAdd(Expression):
    """date_add(date, days) -> DATE (ref: GpuDateAdd)."""

    left: Expression
    right: Expression

    _sign = 1

    @property
    def dtype(self) -> T.DataType:
        return T.DATE

    def eval(self, ctx: EvalContext) -> AnyColumn:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        out = l.data.astype(jnp.int32) + \
            self._sign * r.data.astype(jnp.int32)
        return Column(out, broadcast_validity(l, r), T.DATE)


class DateSub(DateAdd):
    _sign = -1


@dataclasses.dataclass(repr=False)
class DateDiff(Expression):
    """datediff(end, start) -> INT days."""

    left: Expression
    right: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    def eval(self, ctx: EvalContext) -> AnyColumn:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        out = l.data.astype(jnp.int32) - r.data.astype(jnp.int32)
        return Column(out, broadcast_validity(l, r), T.INT)


@dataclasses.dataclass(repr=False)
class UnixTimestampFromTs(Expression):
    """to_unix_timestamp(timestamp) -> LONG seconds (floor)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        us = c.data.astype(jnp.int64)
        return Column(us // US_PER_SECOND, c.validity, T.LONG)


_SUPPORTED_FORMATS = ("yyyy-MM-dd HH:mm:ss", "yyyy-MM-dd")


def _format_chars(days, sec_of_day, fmt: str, cap: int):
    """Device-side date formatting: civil fields -> a uint8 char matrix
    (one fixed-width program per supported format — the GpuOverrides
    regexp-style policy: refuse exotic formats at tagging instead of
    producing wrong output)."""
    from spark_rapids_tpu.columnar.column import pad_width

    y, m, d = civil_from_days(days)
    fields = {
        "yyyy": (y, 4), "MM": (m, 2), "dd": (d, 2),
        "HH": (sec_of_day // 3600, 2),
        "mm": ((sec_of_day // 60) % 60, 2),
        "ss": (sec_of_day % 60, 2),
    }
    out_len = len(fmt)
    width = pad_width(out_len)
    chars = jnp.zeros((cap, width), jnp.uint8)
    i = 0
    pos = 0
    while i < len(fmt):
        for token, (val, nd) in fields.items():
            if fmt.startswith(token, i):
                v = val.astype(jnp.int64)
                for k in range(nd):
                    digit = (v // (10 ** (nd - 1 - k))) % 10
                    chars = chars.at[:, pos + k].set(
                        (digit + ord("0")).astype(jnp.uint8))
                i += len(token)
                pos += nd
                break
        else:
            chars = chars.at[:, pos].set(jnp.uint8(ord(fmt[i])))
            i += 1
            pos += 1
    return chars, out_len


@dataclasses.dataclass(repr=False)
class FromUnixTime(Expression):
    """from_unixtime(seconds, fmt) -> formatted UTC string
    (ref: GpuFromUnixTime, datetimeExpressions.scala)."""

    child: Expression
    fmt: str = "yyyy-MM-dd HH:mm:ss"

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def check_supported(self) -> None:
        if self.fmt not in _SUPPORTED_FORMATS:
            raise TypeError(
                f"from_unixtime format {self.fmt!r} not supported "
                f"(supported: {', '.join(_SUPPORTED_FORMATS)})")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        from spark_rapids_tpu.columnar.column import StringColumn

        c = self.child.eval(ctx)
        secs = c.data.astype(jnp.int64)
        days = secs // 86400  # jnp // floors, negatives included
        sod = secs - days * 86400
        chars, out_len = _format_chars(days, sod, self.fmt,
                                       ctx.batch.capacity)
        return StringColumn(
            chars, jnp.full((ctx.batch.capacity,), out_len, jnp.int32),
            c.validity & ctx.row_mask)


@dataclasses.dataclass(repr=False)
class DateFormatClass(Expression):
    """date_format(ts, fmt) -> formatted UTC string
    (ref: GpuDateFormatClass)."""

    child: Expression
    fmt: str = "yyyy-MM-dd"

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def check_supported(self) -> None:
        if self.fmt not in _SUPPORTED_FORMATS:
            raise TypeError(
                f"date_format format {self.fmt!r} not supported "
                f"(supported: {', '.join(_SUPPORTED_FORMATS)})")
        if not isinstance(self.child.dtype,
                          (T.DateType, T.TimestampType)):
            raise TypeError("date_format needs a date/timestamp input")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        from spark_rapids_tpu.columnar.column import StringColumn

        c = self.child.eval(ctx)
        if isinstance(self.child.dtype, T.DateType):
            days = c.data.astype(jnp.int64)
            sod = jnp.zeros_like(days)
        else:
            us = c.data.astype(jnp.int64)
            days = us // US_PER_DAY  # floor division, negatives included
            sod = (us - days * US_PER_DAY) // US_PER_SECOND
        chars, out_len = _format_chars(days, sod, self.fmt,
                                       ctx.batch.capacity)
        return StringColumn(
            chars, jnp.full((ctx.batch.capacity,), out_len, jnp.int32),
            c.validity & ctx.row_mask)


@dataclasses.dataclass(repr=False)
class CalendarInterval:
    """A literal calendar interval (months, days, microseconds) — the
    Spark CalendarIntervalType value TimeAdd/DateAddInterval consume
    (ref: TimeSub/TimeAdd in datetimeExpressions.scala)."""

    months: int = 0
    days: int = 0
    microseconds: int = 0


@dataclasses.dataclass(repr=False)
class TimeAdd(Expression):
    """timestamp + interval (ref: GpuTimeAdd/GpuTimeSub,
    datetimeExpressions.scala).  Month components are calendar-
    dependent and fall back (matching the reference, which rejects
    intervals with months)."""

    child: Expression
    interval: CalendarInterval
    _sign = 1

    @property
    def dtype(self) -> T.DataType:
        return T.TIMESTAMP

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    @property
    def name(self) -> str:
        iv = self.interval
        return (f"{self.child.name} + interval({iv.months}m "
                f"{iv.days}d {iv.microseconds}us)")

    @property
    def children(self) -> tuple:
        return (self.child,)

    def with_children(self, children):
        out = type(self)(children[0], self.interval)
        return out

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.TimestampType):
            raise TypeError("TimeAdd needs a timestamp input")
        if self.interval.months:
            raise TypeError(
                "interval months are calendar-dependent — CPU fallback "
                "(the reference rejects them too)")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        delta = (self.interval.days * US_PER_DAY
                 + self.interval.microseconds) * self._sign
        return Column(c.data.astype(jnp.int64) + jnp.int64(delta),
                      c.validity, T.TIMESTAMP)


class TimeSub(TimeAdd):
    _sign = -1


@dataclasses.dataclass(repr=False)
class DateAddInterval(Expression):
    """date + interval -> DATE (ref: GpuDateAddInterval,
    datetimeExpressions.scala: microseconds must be a whole number of
    days in practice; Spark truncates toward zero)."""

    child: Expression
    interval: CalendarInterval

    @property
    def dtype(self) -> T.DataType:
        return T.DATE

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    @property
    def name(self) -> str:
        iv = self.interval
        return f"{self.child.name} + interval({iv.days}d)"

    @property
    def children(self) -> tuple:
        return (self.child,)

    def with_children(self, children):
        return DateAddInterval(children[0], self.interval)

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.DateType):
            raise TypeError("DateAddInterval needs a date input")
        if self.interval.months:
            raise TypeError("interval months fall back")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        days = self.interval.days + int(
            self.interval.microseconds / US_PER_DAY)
        return Column(c.data.astype(jnp.int32) + jnp.int32(days),
                      c.validity, T.DATE)
