"""Window expressions: functions + specs + frames.

Counterpart of the reference's GpuWindowExpression / GpuWindowSpecDefinition
/ GpuSpecifiedWindowFrame family (ref: GpuWindowExpression.scala:174,
207-296,856) and the ranking/offset functions Lead/Lag/RowNumber from
Appendix A.  A WindowExpression is an Expression for planning purposes
(dtype, tagging, explain) but never evaluates inline — the planner routes
it to TpuWindowExec, which computes all window columns of a projection in
one segmented-scan program (ops.window).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.aggregates import (
    AggregateFunction,
    Average,
    Count,
    CountStar,
    Max,
    Min,
    Sum,
)
from spark_rapids_tpu.exprs.base import Expression, bind_references
from spark_rapids_tpu.execs.sort import SortKey

#: offset value meaning "unbounded" in a frame bound
UNBOUNDED = None
CURRENT_ROW = 0


@dataclasses.dataclass(frozen=True)
class WindowFrame:
    """ROWS/RANGE frame with offsets relative to the current row
    (negative = preceding, None = unbounded on that side).  Spark default
    with an ORDER BY: RANGE UNBOUNDED PRECEDING .. CURRENT ROW; without:
    the whole partition."""

    mode: str = "range"  # "rows" | "range"
    start: Optional[int] = UNBOUNDED
    end: Optional[int] = CURRENT_ROW

    def __post_init__(self):
        assert self.mode in ("rows", "range"), self.mode

    def describe(self) -> str:
        def b(v, side):
            if v is None:
                return f"unbounded {side}"
            if v == 0:
                return "current row"
            return f"{-v} preceding" if v < 0 else f"{v} following"

        return (f"{self.mode} between {b(self.start, 'preceding')} "
                f"and {b(self.end, 'following')}")


WHOLE_PARTITION = WindowFrame("rows", UNBOUNDED, UNBOUNDED)
DEFAULT_ORDERED = WindowFrame("range", UNBOUNDED, CURRENT_ROW)


@dataclasses.dataclass(repr=False)
class WindowSpec:
    partition_by: tuple = ()
    order_by: tuple = ()  # of SortKey
    frame: Optional[WindowFrame] = None  # None = Spark default

    def resolved_frame(self) -> WindowFrame:
        if self.frame is not None:
            return self.frame
        return DEFAULT_ORDERED if self.order_by else WHOLE_PARTITION

    def describe(self) -> str:
        ps = ", ".join(e.name for e in self.partition_by)
        os_ = ", ".join(
            f"{k.expr.name}{' DESC' if k.descending else ''}"
            for k in self.order_by)
        return (f"partition by [{ps}] order by [{os_}] "
                f"{self.resolved_frame().describe()}")


class Window:
    """pyspark-shaped WindowSpec builder:
    Window.partition_by("k").order_by("ts").rows_between(-3, 0)"""

    @staticmethod
    def partition_by(*cols) -> "WindowSpecBuilder":
        return WindowSpecBuilder().partition_by(*cols)

    @staticmethod
    def order_by(*keys) -> "WindowSpecBuilder":
        return WindowSpecBuilder().order_by(*keys)


class WindowSpecBuilder:
    def __init__(self):
        self._partition: list[Expression] = []
        self._order: list[SortKey] = []
        self._frame: Optional[WindowFrame] = None

    def partition_by(self, *cols) -> "WindowSpecBuilder":
        from spark_rapids_tpu.exprs.base import ColumnReference

        for c in cols:
            self._partition.append(
                ColumnReference(c) if isinstance(c, str) else c)
        return self

    def order_by(self, *keys, desc: bool = False) -> "WindowSpecBuilder":
        from spark_rapids_tpu.exprs.base import ColumnReference

        for k in keys:
            if isinstance(k, SortKey):
                self._order.append(k)
            else:
                e = ColumnReference(k) if isinstance(k, str) else k
                self._order.append(SortKey(e, descending=desc,
                                           nulls_last=desc))
        return self

    def rows_between(self, start: Optional[int],
                     end: Optional[int]) -> "WindowSpecBuilder":
        self._frame = WindowFrame("rows", start, end)
        return self

    def range_between(self, start: Optional[int],
                      end: Optional[int]) -> "WindowSpecBuilder":
        self._frame = WindowFrame("range", start, end)
        return self

    def build(self) -> WindowSpec:
        return WindowSpec(tuple(self._partition), tuple(self._order),
                          self._frame)


def _spec(s: Union[WindowSpec, WindowSpecBuilder]) -> WindowSpec:
    return s.build() if isinstance(s, WindowSpecBuilder) else s


@dataclasses.dataclass(repr=False)
class WindowExpression(Expression):
    """fn over spec.  Never evaluated inline — planned into
    TpuWindowExec."""

    fn: "WindowFunction"
    spec: WindowSpec

    def __post_init__(self):
        # query-invalidity (vs device-capability) errors surface at
        # construction, like Spark's AnalysisException — they must NOT
        # become CPU fallbacks that silently compute degenerate results
        self.fn.check_analysis(self.spec)

    @property
    def dtype(self) -> T.DataType:
        return self.fn.dtype

    @property
    def nullable(self) -> bool:
        return self.fn.nullable

    @property
    def name(self) -> str:
        return f"{self.fn.describe()} over ({self.spec.describe()})"

    @property
    def children(self):
        return tuple(self.fn.inputs()) + tuple(self.spec.partition_by) \
            + tuple(k.expr for k in self.spec.order_by)

    def bind(self, schema: T.Schema) -> "WindowExpression":
        spec = WindowSpec(
            tuple(bind_references(e, schema)
                  for e in self.spec.partition_by),
            tuple(SortKey(bind_references(k.expr, schema), k.descending,
                          k.nulls_last) for k in self.spec.order_by),
            self.spec.frame)
        return WindowExpression(self.fn.bind(schema), spec)

    def check_supported(self) -> None:
        self.fn.check_supported(self.spec)


class WindowFunction:
    """Base for functions usable over a window."""

    @property
    def dtype(self) -> T.DataType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return True

    def inputs(self) -> list[Expression]:
        return []

    def bind(self, schema: T.Schema) -> "WindowFunction":
        return self

    def describe(self) -> str:
        return type(self).__name__.lower()

    def check_analysis(self, spec: WindowSpec) -> None:
        """Query-validity checks (raise = invalid query, both engines)."""

    def check_supported(self, spec: WindowSpec) -> None:
        """Device-capability checks (raise = CPU fallback)."""

    def over(self, spec) -> WindowExpression:
        return WindowExpression(self, _spec(spec))


class _RankingFunction(WindowFunction):
    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    @property
    def nullable(self) -> bool:
        return False

    def check_analysis(self, spec: WindowSpec) -> None:
        if not spec.order_by:
            raise ValueError(
                f"{self.describe()}() requires a window ORDER BY")


class RowNumber(_RankingFunction):
    pass


class Rank(_RankingFunction):
    pass


class DenseRank(_RankingFunction):
    def describe(self) -> str:
        return "dense_rank"


@dataclasses.dataclass(repr=False)
class Lead(WindowFunction):
    """lead(expr, offset, default): value `offset` rows after the current
    row within the partition (lag = negative direction)."""

    child: Expression
    offset: int = 1
    default: Optional[Expression] = None

    _sign = 1

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def inputs(self) -> list[Expression]:
        return [self.child] + ([self.default] if self.default is not None
                               else [])

    def bind(self, schema: T.Schema) -> "Lead":
        return type(self)(
            bind_references(self.child, schema), self.offset,
            bind_references(self.default, schema)
            if self.default is not None else None)

    def describe(self) -> str:
        return f"{type(self).__name__.lower()}({self.child.name}, " \
               f"{self.offset})"

    def check_analysis(self, spec: WindowSpec) -> None:
        if not spec.order_by:
            raise ValueError(
                f"{type(self).__name__.lower()}() requires a window "
                "ORDER BY")

    def check_supported(self, spec: WindowSpec) -> None:
        if self.default is None:
            return
        try:
            dt = self.child.dtype
        except RuntimeError:  # unbound reference; planner re-checks bound
            return
        if isinstance(dt, T.StringType):
            raise TypeError(
                "lead/lag with a default over STRING is not supported on "
                "TPU (string defaults need a width-merged select)")

    @property
    def shift(self) -> int:
        return self._sign * self.offset


class Lag(Lead):
    _sign = -1


@dataclasses.dataclass(repr=False)
class WindowAgg(WindowFunction):
    """An aggregate function evaluated over the window frame."""

    agg: AggregateFunction

    _SUPPORTED = (Sum, Count, CountStar, Min, Max, Average)

    @property
    def dtype(self) -> T.DataType:
        return self.agg.dtype

    @property
    def nullable(self) -> bool:
        return self.agg.nullable

    def inputs(self) -> list[Expression]:
        return self.agg.inputs()

    def bind(self, schema: T.Schema) -> "WindowAgg":
        return WindowAgg(self.agg.bind(schema))

    def describe(self) -> str:
        ins = ", ".join(e.name for e in self.agg.inputs())
        return f"{self.agg.name}({ins})"

    def check_supported(self, spec: WindowSpec) -> None:
        if not isinstance(self.agg, self._SUPPORTED):
            raise TypeError(
                f"aggregate {self.agg.name} is not supported over a "
                "window on TPU")
        for e in self.agg.inputs():
            try:
                dt = e.dtype
            except RuntimeError:  # unbound; planner re-checks bound
                continue
            if isinstance(dt, T.StringType):
                raise TypeError(
                    "window aggregates over STRING are not supported on "
                    "TPU (falls back)")
        frame = spec.resolved_frame()
        if frame.mode == "range" and (frame.start is not UNBOUNDED or
                                      frame.end not in (CURRENT_ROW,
                                                        UNBOUNDED)):
            # bounded value-based range frame (ref:
            # GpuWindowExpression.scala:207-296): needs exactly one
            # numeric/date order key for the device bisection kernel
            if len(spec.order_by) != 1:
                raise TypeError(
                    "bounded RANGE frames need exactly one order-by "
                    "key on TPU")
            okdt = None
            try:
                okdt = spec.order_by[0].expr.dtype
            except RuntimeError:
                pass  # unbound; planner re-checks bound
            if okdt is not None and not isinstance(
                    okdt, (T.ByteType, T.ShortType, T.IntegerType,
                           T.LongType, T.FloatType, T.DoubleType,
                           T.DateType, T.TimestampType)):
                raise TypeError(
                    "bounded RANGE frames need a numeric/date order "
                    "key on TPU")
        if isinstance(self.agg, (Min, Max)):
            if frame.start is not UNBOUNDED and frame.end is not UNBOUNDED:
                raise TypeError(
                    "min/max window frames must be unbounded on one side "
                    "on TPU (bounded-both-sides falls back)")


# Give every AggregateFunction an .over() so session aggregates compose:
# sum_("v").over(Window.partition_by("k"))
AggregateFunction.over = (  # type: ignore[attr-defined]
    lambda self, spec: WindowExpression(WindowAgg(self), _spec(spec)))


def row_number() -> RowNumber:
    return RowNumber()


def rank() -> Rank:
    return Rank()


def dense_rank() -> DenseRank:
    return DenseRank()


def lead(e, offset: int = 1, default=None) -> Lead:
    from spark_rapids_tpu.exprs.base import ColumnReference

    e = ColumnReference(e) if isinstance(e, str) else e
    return Lead(e, offset, default)


def lag(e, offset: int = 1, default=None) -> Lag:
    from spark_rapids_tpu.exprs.base import ColumnReference

    e = ColumnReference(e) if isinstance(e, str) else e
    return Lag(e, offset, default)
