"""Partition-context and nondeterministic expressions.

TPU analogs of the reference's task-context expressions
(ref: GpuOverrides.scala Rand/MonotonicallyIncreasingID/
SparkPartitionID rules; sql/rapids/catalyst/expressions/
GpuRandomExpressions.scala:34 GpuRand).

Design: expressions carrying the `PartitionAware` marker read
`partition_index` / `row_offset` from the EvalContext; the fused
pipeline threads those in as DEVICE scalars (no per-partition
recompile), and pipelines without such expressions keep today's
single-argument signature — zero overhead for the common case.

Rand uses counter-based hashing (threefry via jax.random.fold_in on
the GLOBAL row index) instead of the reference's sequential
XORShiftRandom: same statistical contract, but the value of row i is
independent of batch boundaries — the right construction for an
engine whose batch sizes are a tuning knob, and the reason the CPU
oracle can mirror it bit-exactly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.exprs.base import EvalContext, Expression


class PartitionAware:
    """Marker: eval() reads ctx.partition_index / ctx.row_offset."""


def tree_is_partition_aware(e: Expression) -> bool:
    if isinstance(e, PartitionAware):
        return True
    return any(tree_is_partition_aware(c) for c in e.children)


@dataclasses.dataclass(repr=False)
class SparkPartitionID(Expression, PartitionAware):
    """spark_partition_id() (ref: GpuSparkPartitionID)."""

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cap = ctx.batch.capacity
        pid = jnp.asarray(ctx.partition_index, jnp.int32)
        return Column(jnp.broadcast_to(pid, (cap,)), ctx.row_mask, T.INT)


@dataclasses.dataclass(repr=False)
class MonotonicallyIncreasingID(Expression, PartitionAware):
    """monotonically_increasing_id(): partition index in the upper 31
    bits, per-partition row position in the lower 33
    (ref: GpuMonotonicallyIncreasingID)."""

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cap = ctx.batch.capacity
        pid = jnp.asarray(ctx.partition_index, jnp.int64)
        off = jnp.asarray(ctx.row_offset, jnp.int64)
        ids = (pid << 33) + off + jnp.arange(cap, dtype=jnp.int64)
        return Column(ids, ctx.row_mask, T.LONG)


def _rand_uniform(seed: int, partition, global_idx) -> jax.Array:
    """Counter-based uniform doubles in [0,1): threefry keyed on
    (seed, partition), hashed per global row index.  The int64 index
    folds in as two 32-bit halves so the counter stays injective over
    the full index range (a single uint32 fold would repeat the stream
    every 2^32 rows)."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), partition)

    def one(i):
        hi = (i >> 32).astype(jnp.uint32)
        lo = (i & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        return jax.random.uniform(
            jax.random.fold_in(jax.random.fold_in(key, hi), lo),
            dtype=jnp.float64)

    return jax.vmap(one)(global_idx)


@dataclasses.dataclass(repr=False)
class Rand(Expression, PartitionAware):
    """rand(seed) (ref: GpuRand, GpuRandomExpressions.scala:34).  Values
    are deterministic per (seed, partition, global row index) and
    independent of batch boundaries."""

    seed: int = 0

    @property
    def dtype(self) -> T.DataType:
        return T.DOUBLE

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cap = ctx.batch.capacity
        idx = jnp.asarray(ctx.row_offset, jnp.int64) \
            + jnp.arange(cap, dtype=jnp.int64)
        vals = _rand_uniform(self.seed,
                             jnp.asarray(ctx.partition_index, jnp.int32),
                             idx)
        return Column(vals, ctx.row_mask, T.DOUBLE)


@dataclasses.dataclass(repr=False)
class InputFileName(Expression):
    """input_file_name() (ref: GpuInputFileName, GpuOverrides.scala).

    The planner rewrites this to a hidden per-file constant column the
    scan appends (the ColumnarPartitionReaderWithPartitionValues
    mechanism); un-rewritten occurrences (no file scan below, or a
    widening operator between) evaluate to Spark's no-context default
    on the CPU engine."""

    #: Spark's value when no file context exists
    DEFAULT = ""
    HIDDEN = "__input_file_name"

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    @property
    def nullable(self) -> bool:
        return False

    @property
    def name(self) -> str:
        return "input_file_name()"

    def check_supported(self) -> None:
        raise TypeError(
            "input_file_name() is only supported directly above a file "
            "scan (project/filter chain) — CPU fallback")

    def eval(self, ctx: EvalContext):
        raise AssertionError("rewritten by the planner before eval")


@dataclasses.dataclass(repr=False)
class InputFileBlockStart(InputFileName):
    """input_file_block_start() (ref: GpuInputFileBlockStart): whole
    files are read as one split, so the start is 0."""

    DEFAULT = -1
    HIDDEN = "__input_file_block_start"

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    @property
    def name(self) -> str:
        return "input_file_block_start()"


@dataclasses.dataclass(repr=False)
class InputFileBlockLength(InputFileName):
    """input_file_block_length() (ref: GpuInputFileBlockLength): the
    split is the whole file, so the length is the file size."""

    DEFAULT = -1
    HIDDEN = "__input_file_block_length"

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    @property
    def name(self) -> str:
        return "input_file_block_length()"
