"""Expression tree base classes.

TPU re-design of the reference's GpuExpression contract
(ref: sql-plugin/.../GpuExpressions.scala:110-134 `columnarEval` returning a
GpuColumnVector or scalar) and reference binding
(ref: GpuBoundAttribute.scala, used at basicPhysicalOperators.scala:114).

Key difference from the reference: `eval` here runs *inside a JAX trace* —
the whole expression tree of an operator (or a fused pipeline of operators)
becomes one XLA program, so there is no per-expression kernel-launch cost
to optimize and literals can simply broadcast (XLA folds them).  Every
`eval` returns a Column/StringColumn of the batch's capacity; SQL NULLs
travel in the validity array.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn


@dataclasses.dataclass
class EvalContext:
    """Evaluation context handed down an expression tree: the input batch
    plus its live-row mask (rows past num_rows must stay NULL).

    `partition_index` / `row_offset` serve PartitionAware expressions
    (Rand, MonotonicallyIncreasingID, ...); they are device scalars when
    the fused pipeline threads them in (so programs stay shared across
    partitions) and plain 0 everywhere partition context is
    meaningless (sort keys, join keys, aggregates — where Spark forbids
    nondeterministic expressions too)."""

    batch: ColumnarBatch
    row_mask: jax.Array
    partition_index: object = 0  # int or jax i32 scalar
    row_offset: object = 0  # int or jax i64 scalar

    @staticmethod
    def for_batch(batch: ColumnarBatch) -> "EvalContext":
        pi = getattr(_PINFO, "v", None) or (0, 0)
        return EvalContext(batch, batch.row_mask(), pi[0], pi[1])


_PINFO = threading.local()


@contextlib.contextmanager
def partition_info(partition_index, row_offset):
    """Scope PartitionAware context for expression evaluation: the fused
    pipeline sets TRACED device scalars here while tracing, so compiled
    programs stay shared across partitions."""
    prev = getattr(_PINFO, "v", None)
    _PINFO.v = (partition_index, row_offset)
    try:
        yield
    finally:
        _PINFO.v = prev


# ------------------------------------------------------------------ #
# ANSI mode (ref: GpuCast.scala:166 ANSI cast matrix + the ANSI
# arithmetic overflow gating in arithmetic.scala).  XLA programs can't
# raise, so error conditions trace as per-row flags collected into one
# int32 error-code scalar the EXEC polls after the program runs —
# the host-side throw the reference gets synchronously from cudf.
# ------------------------------------------------------------------ #

def _register_ansi_conf():
    from spark_rapids_tpu.config import register

    return register(
        "spark.rapids.tpu.sql.ansi.enabled", False,
        "ANSI SQL mode (the spark.sql.ansi.enabled analog): overflow "
        "in Add/Subtract/Multiply and invalid/overflowing casts RAISE "
        "instead of wrapping/NULLing (ref: GpuCast.scala:166 ANSI "
        "matrix; CheckOverflow).")


ANSI_ENABLED = _register_ansi_conf()


class AnsiError(RuntimeError):
    """org.apache.spark.SparkArithmeticException analog."""


def ansi_enabled() -> bool:
    from spark_rapids_tpu.config import get_conf

    return get_conf().get(ANSI_ENABLED)


_ANSI_CAPTURE = threading.local()
_ANSI_MESSAGES: dict[int, str] = {}
_ANSI_CODES: dict[str, int] = {}
_ansi_lock = threading.Lock()


def ansi_code(message: str) -> int:
    """Stable small int code for an error message (trace-time)."""
    with _ansi_lock:
        code = _ANSI_CODES.get(message)
        if code is None:
            code = _ANSI_CODES[message] = len(_ANSI_CODES) + 1
            _ANSI_MESSAGES[code] = message
        return code


@contextlib.contextmanager
def ansi_capture():
    """Scope an ANSI flag accumulator around a traced pipeline; yields
    the list the trace appends (code, any-flag scalar) pairs into."""
    flags: list = []
    prev = getattr(_ANSI_CAPTURE, "v", None)
    _ANSI_CAPTURE.v = flags
    try:
        yield flags
    finally:
        _ANSI_CAPTURE.v = prev


def ansi_active() -> bool:
    """True while a capture is open (the pipeline only opens one when
    ANSI mode is on, so expressions check this, not the conf)."""
    return getattr(_ANSI_CAPTURE, "v", None) is not None


def ansi_report(flag, message: str) -> None:
    """Record a per-row error condition (traced bool array)."""
    cap = getattr(_ANSI_CAPTURE, "v", None)
    if cap is not None:
        cap.append((ansi_code(message), jnp.any(flag)))


def fold_ansi_flags(flags: list) -> jax.Array:
    """(code, flag) pairs -> one int32 scalar (0 = no error)."""
    err = jnp.int32(0)
    for code, f in flags:
        err = jnp.maximum(err, jnp.where(f, jnp.int32(code),
                                         jnp.int32(0)))
    return err


def raise_if_ansi_error(err) -> None:
    code = int(err)
    if code:
        raise AnsiError(
            _ANSI_MESSAGES.get(code, f"ANSI error {code}")
            + ". If necessary set spark.rapids.tpu.sql.ansi.enabled "
            "to false to bypass this error.")


class Expression:
    """Base expression. Subclasses define `dtype`, `nullable` and `eval`.

    `children` is derived automatically from dataclass fields that hold
    Expressions (or tuples of Expressions), in field order, so eval()'s
    named fields (self.left, self.child, ...) can never go stale against
    the child list during tree rewrites.  Variadic/irregular nodes
    (CaseWhen's branch pairs) override both `children` and
    `with_children`.
    """

    @property
    def children(self) -> tuple["Expression", ...]:
        if not dataclasses.is_dataclass(self):
            return ()
        out: list[Expression] = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Expression):
                out.append(v)
            elif isinstance(v, tuple) and v and all(
                    isinstance(x, Expression) for x in v):
                out.extend(v)
        return tuple(out)

    @property
    def dtype(self) -> T.DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, ctx: EvalContext) -> AnyColumn:
        raise NotImplementedError(type(self).__name__)

    # -- tree utilities -------------------------------------------------- #

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        """Rebuild this node with new children (for binding/rewrites)."""
        children = list(children)
        if not children:
            return self
        assert dataclasses.is_dataclass(self), type(self).__name__
        updates: dict[str, Any] = {}
        i = 0
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Expression):
                updates[f.name] = children[i]
                i += 1
            elif isinstance(v, tuple) and v and all(
                    isinstance(x, Expression) for x in v):
                updates[f.name] = tuple(children[i:i + len(v)])
                i += len(v)
        assert i == len(children), f"arity mismatch in {type(self).__name__}"
        return dataclasses.replace(self, **updates)

    def transform_up(self, fn) -> "Expression":
        node = self
        if self.children:
            node = self.with_children(
                [c.transform_up(fn) for c in self.children])
        return fn(node)

    @property
    def name(self) -> str:
        return type(self).__name__

    def __repr__(self) -> str:
        if self.children:
            return f"{self.name}({', '.join(map(repr, self.children))})"
        return self.name

    # convenience builders (mirrors the Column DSL of DataFrame frontends)
    def __add__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Add

        return Add(_expr(self), _expr(other))

    def __sub__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Subtract

        return Subtract(_expr(self), _expr(other))

    def __mul__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Multiply

        return Multiply(_expr(self), _expr(other))

    def __truediv__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Divide

        return Divide(_expr(self), _expr(other))

    def __mod__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Remainder

        return Remainder(_expr(self), _expr(other))

    def __neg__(self):
        from spark_rapids_tpu.exprs.arithmetic import UnaryMinus

        return UnaryMinus(_expr(self))

    def __and__(self, other):
        from spark_rapids_tpu.exprs.predicates import And

        return And(_expr(self), _expr(other))

    def __or__(self, other):
        from spark_rapids_tpu.exprs.predicates import Or

        return Or(_expr(self), _expr(other))

    def __invert__(self):
        from spark_rapids_tpu.exprs.predicates import Not

        return Not(_expr(self))

    def _cmp(self, other, cls):
        return cls(_expr(self), _expr(other))

    def __lt__(self, other):
        from spark_rapids_tpu.exprs.predicates import LessThan

        return self._cmp(other, LessThan)

    def __le__(self, other):
        from spark_rapids_tpu.exprs.predicates import LessThanOrEqual

        return self._cmp(other, LessThanOrEqual)

    def __gt__(self, other):
        from spark_rapids_tpu.exprs.predicates import GreaterThan

        return self._cmp(other, GreaterThan)

    def __ge__(self, other):
        from spark_rapids_tpu.exprs.predicates import GreaterThanOrEqual

        return self._cmp(other, GreaterThanOrEqual)

    def eq(self, other):
        from spark_rapids_tpu.exprs.predicates import EqualTo

        return self._cmp(other, EqualTo)

    def ne(self, other):
        from spark_rapids_tpu.exprs.predicates import EqualTo, Not

        return Not(EqualTo(_expr(self), _expr(other)))

    def get_field(self, name: str):
        """struct field access: col("s").get_field("x")."""
        from spark_rapids_tpu.exprs.complex import GetStructField

        return GetStructField(self, name)

    def element_at(self, key):
        """element_at(array, 1-based index) / element_at(map, key)."""
        from spark_rapids_tpu.exprs.complex import ElementAt

        return ElementAt(self, _expr(key))

    def get_map_value(self, key):
        from spark_rapids_tpu.exprs.complex import GetMapValue

        return GetMapValue(self, _expr(key))

    def is_null(self):
        from spark_rapids_tpu.exprs.predicates import IsNull

        return IsNull(self)

    def is_not_null(self):
        from spark_rapids_tpu.exprs.predicates import IsNotNull

        return IsNotNull(self)

    def cast(self, dtype: T.DataType):
        from spark_rapids_tpu.exprs.cast import Cast

        return Cast(self, dtype)

    def alias(self, name: str):
        return Alias(self, name)


def _expr(v) -> Expression:
    return v if isinstance(v, Expression) else Literal.of(v)


def lit(v) -> "Literal":
    return Literal.of(v)


@dataclasses.dataclass(repr=False)
class ColumnReference(Expression):
    """Unresolved reference by column name; resolved against a schema into
    a BoundReference before execution (analysis step)."""

    col_name: str
    _dtype: Optional[T.DataType] = None
    _nullable: bool = True

    @property
    def dtype(self) -> T.DataType:
        if self._dtype is None:
            raise RuntimeError(f"unresolved reference {self.col_name}")
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    @property
    def name(self) -> str:
        return self.col_name

    def eval(self, ctx: EvalContext) -> AnyColumn:
        raise RuntimeError(
            f"unbound reference {self.col_name}; bind_references first")


@dataclasses.dataclass(repr=False)
class BoundReference(Expression):
    """Reference bound to an input-batch ordinal (ref: the reference's
    GpuBoundReference in GpuBoundAttribute.scala)."""

    ordinal: int
    _dtype: T.DataType = dataclasses.field(default_factory=lambda: T.LONG)
    _nullable: bool = True
    col_name: str = ""

    @property
    def dtype(self) -> T.DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    @property
    def name(self) -> str:
        return self.col_name or f"input[{self.ordinal}]"

    def eval(self, ctx: EvalContext) -> AnyColumn:
        col = ctx.batch.columns[self.ordinal]
        # mask out padding rows so downstream reductions can trust validity
        return col.with_validity(col.validity & ctx.row_mask)


@dataclasses.dataclass(repr=False)
class Literal(Expression):
    """A scalar literal, broadcast to the batch capacity at eval
    (ref: literals.scala GpuLiteral/GpuScalar)."""

    value: Any
    _dtype: T.DataType = dataclasses.field(default_factory=lambda: T.LONG)

    @property
    def dtype(self) -> T.DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    @property
    def name(self) -> str:
        return repr(self.value)

    @staticmethod
    def of(v, dtype: Optional[T.DataType] = None) -> "Literal":
        if dtype is None:
            if v is None:
                dtype = T.NULL
            elif isinstance(v, bool):
                dtype = T.BOOLEAN
            elif isinstance(v, (int, np.integer)):
                dtype = T.LONG
            elif isinstance(v, (float, np.floating)):
                dtype = T.DOUBLE
            elif isinstance(v, str):
                dtype = T.STRING
            else:
                raise TypeError(f"cannot infer literal type of {v!r}")
        return Literal(v, dtype)

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cap = ctx.batch.capacity
        if isinstance(self._dtype, T.StringType):
            b = (self.value or "").encode("utf-8")
            w = max(len(b), 1)
            chars = jnp.broadcast_to(
                jnp.asarray(np.frombuffer(b.ljust(w, b"\0"), np.uint8)),
                (cap, w))
            lengths = jnp.full(cap, len(b), jnp.int32)
            valid = jnp.full(cap, self.value is not None) & ctx.row_mask
            return StringColumn(chars, lengths, valid)
        phys = T.to_numpy_dtype(self._dtype)
        v = self.value if self.value is not None else 0
        data = jnp.full(cap, v, dtype=phys)
        valid = jnp.full(cap, self.value is not None) & ctx.row_mask
        return Column(data, valid, self._dtype)


@dataclasses.dataclass(repr=False)
class Alias(Expression):
    child: Expression
    out_name: str

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    @property
    def name(self) -> str:
        return self.out_name

    def eval(self, ctx: EvalContext) -> AnyColumn:
        return self.child.eval(ctx)


def bind_references(expr: Expression, schema: T.Schema) -> Expression:
    """Resolve ColumnReferences against `schema` into BoundReferences
    (ref: GpuBindReferences.bindGpuReferences)."""

    def rewrite(e: Expression) -> Expression:
        if isinstance(e, ColumnReference):
            idx = schema.index_of(e.col_name)
            f = schema.fields[idx]
            return BoundReference(idx, f.dtype, f.nullable, f.name)
        return e

    return expr.transform_up(rewrite)


# ---------------------------------------------------------------------- #
# Shared eval helpers
# ---------------------------------------------------------------------- #

def broadcast_validity(*cols: AnyColumn) -> jax.Array:
    v = cols[0].validity
    for c in cols[1:]:
        v = v & c.validity
    return v


def result_numeric_type(left: T.DataType, right: T.DataType,
                        div: bool = False) -> T.DataType:
    if div:
        return T.DOUBLE
    ct = T.common_type(left, right)
    if ct is None:
        raise TypeError(f"incompatible types {left} / {right}")
    return ct
