"""Decimal precision-management expressions.

Analogs of the reference's decimal plumbing (ref:
sql-plugin/.../decimalExpressions.scala — GpuPromotePrecision,
GpuCheckOverflow): Spark's analyzer wraps decimal arithmetic as
CheckOverflow(op(PromotePrecision(cast l), PromotePrecision(cast r))),
and the physical layer works on UNSCALED integer values.  Our decimals
are int64-backed (precision <= 18), so:

- `PromotePrecision` rescales the unscaled value to the target scale
  (one integer multiply by a power of ten — exact while the target
  precision fits int64);
- `CheckOverflow` re-asserts the declared precision after an
  operation: values whose magnitude reaches 10^precision become NULL
  (Spark's default nullOnOverflow=true; ANSI raise mode is a planner
  fallback, like the reference's ansiEnabled tagging).

Same-type decimal Add/Subtract themselves are exact unscaled int64
adds, enabled in the arithmetic TypeSig when wrapped this way.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.exprs.base import EvalContext, Expression


@dataclasses.dataclass(repr=False)
class PromotePrecision(Expression):
    """Rescale a decimal child to the target precision/scale (ref:
    decimalExpressions.scala GpuPromotePrecision)."""

    child: Expression
    target: T.DecimalType

    @property
    def dtype(self) -> T.DataType:
        return self.target

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def check_supported(self) -> None:
        cdt = self.child.dtype
        if not isinstance(cdt, T.DecimalType):
            raise TypeError("PromotePrecision over non-decimal input")
        if self.target.scale < cdt.scale:
            raise TypeError(
                "PromotePrecision cannot reduce scale (would round)")
        if self.target.precision > T.DecimalType.MAX_PRECISION:
            raise TypeError("decimal precision beyond int64 falls back")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, Column)
        diff = self.target.scale - self.child.dtype.scale
        data = c.data * jnp.int64(10 ** diff) if diff else c.data
        return Column(data, c.validity, self.target)


@dataclasses.dataclass(repr=False)
class CheckOverflow(Expression):
    """NULL out values exceeding the declared precision (ref:
    decimalExpressions.scala GpuCheckOverflow, nullOnOverflow=true)."""

    child: Expression
    target: T.DecimalType
    null_on_overflow: bool = True

    @property
    def dtype(self) -> T.DataType:
        return self.target

    @property
    def nullable(self) -> bool:
        return True

    def check_supported(self) -> None:
        if not self.null_on_overflow:
            raise TypeError(
                "ANSI overflow (exception mode) falls back, like the "
                "reference's ansiEnabled tagging")
        if self.target.precision > T.DecimalType.MAX_PRECISION:
            raise TypeError("decimal precision beyond int64 falls back")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, Column)
        cdt = self.child.dtype
        assert isinstance(cdt, T.DecimalType)
        diff = cdt.scale - self.target.scale
        data = c.data
        if diff > 0:
            # scale down with HALF_UP (away from zero) rounding —
            # Spark's toPrecision: round on |v|, restore the sign
            p = jnp.int64(10 ** diff)
            half = p // 2
            mag = (jnp.abs(data) + half) // p
            data = jnp.where(data < 0, -mag, mag)
            bound = jnp.int64(10 ** self.target.precision)
            ok = (data > -bound) & (data < bound)
        elif diff < 0:
            # guard BEFORE scaling up: int64 wraparound could land a
            # huge value back inside the bound and return a wrong
            # non-null result — the exact rows this exists to NULL
            mult = 10 ** (-diff)
            limit = jnp.int64((10 ** self.target.precision - 1) // mult)
            ok = (data >= -limit) & (data <= limit)
            data = data * jnp.int64(mult)
        else:
            bound = jnp.int64(10 ** self.target.precision)
            ok = (data > -bound) & (data < bound)
        return Column(data, c.validity & ok, self.target)


@dataclasses.dataclass(repr=False)
class UnscaledValue(Expression):
    """decimal -> LONG unscaled backing value (ref:
    decimalExpressions.scala GpuUnscaledValue) — zero-copy here: the
    device representation IS the unscaled int64."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    @property
    def name(self) -> str:
        return f"unscaled({self.child.name})"

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.DecimalType):
            raise TypeError("UnscaledValue needs a decimal input")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(c.data.astype(jnp.int64), c.validity, T.LONG)


@dataclasses.dataclass(repr=False)
class MakeDecimal(Expression):
    """LONG unscaled -> decimal(p, s) (ref: GpuMakeDecimal): values
    beyond the declared precision become NULL (nullOnOverflow)."""

    child: Expression
    precision: int
    scale: int

    @property
    def dtype(self) -> T.DataType:
        return T.DecimalType(self.precision, self.scale)

    @property
    def nullable(self) -> bool:
        return True

    @property
    def name(self) -> str:
        return (f"make_decimal({self.child.name}, "
                f"{self.precision}, {self.scale})")

    @property
    def children(self) -> tuple:
        return (self.child,)

    def with_children(self, children):
        return MakeDecimal(children[0], self.precision, self.scale)

    def check_supported(self) -> None:
        from spark_rapids_tpu import types as _T

        if not isinstance(self.child.dtype, _T.IntegralType):
            raise TypeError("MakeDecimal needs an integral input")
        if self.precision > T.DecimalType.MAX_PRECISION:
            raise TypeError("decimal precision beyond int64 falls back")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        data = c.data.astype(jnp.int64)
        bound = jnp.int64(10 ** self.precision)
        ok = (data > -bound) & (data < bound)
        return Column(data, c.validity & ok, self.dtype)
