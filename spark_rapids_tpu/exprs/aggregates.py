"""Declarative aggregate functions.

TPU counterpart of the reference's GpuAggregateFunction hierarchy
(ref: sql-plugin/.../org/apache/spark/sql/rapids/AggregateFunctions.scala,
704 LoC: Sum/Count/Min/Max/Average/First/Last/Pivot) which decomposes
every SQL aggregate into *update* expressions (per input batch),
*merge* expressions (combining partial results, e.g. post-shuffle), and
a *finalize* projection (e.g. avg = sum / count).  The same decomposition
drives three placements here: single-batch complete aggregation,
multi-batch streaming re-merge, and distributed partial->exchange->final
plans (aggregate.scala:240's mode handling).

Each function maps its update/merge phases onto the segmented-reduce
kernel ops in ops.groupby (AggSpec)."""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import BoundReference, Expression
from spark_rapids_tpu.ops.groupby import AggSpec, agg_output_dtype


@dataclasses.dataclass(repr=False)
class AggregateFunction:
    """Base: child input expression(s) + phase decomposition."""

    child: Optional[Expression]

    @property
    def name(self) -> str:
        return type(self).__name__.lower()

    def bind(self, schema: T.Schema) -> "AggregateFunction":
        """Resolve the child expression against the pre-aggregation input
        schema (required before dtype/partial_dtypes are meaningful)."""
        if self.child is None:
            return self
        from spark_rapids_tpu.exprs.base import bind_references

        return type(self)(bind_references(self.child, schema))

    def inputs(self) -> list[Expression]:
        """Expressions projected out of the child batch before update."""
        return [self.child] if self.child is not None else []

    def n_partials(self) -> int:
        return 1

    def update_ops(self) -> list[str]:
        """AggSpec ops over this function's input columns (one per
        partial)."""
        raise NotImplementedError

    def merge_ops(self) -> list[str]:
        """AggSpec ops over this function's partial columns."""
        raise NotImplementedError

    def partial_dtypes(self) -> list[T.DataType]:
        ops = self.update_ops()
        in_dt = self.child.dtype if self.child is not None else None
        return [agg_output_dtype(AggSpec(op, 0), in_dt) for op in ops]

    def finalize_expr(self, partial_refs: list[Expression]) -> Expression:
        """Expression over the partial columns producing the SQL result."""
        return partial_refs[0]

    @property
    def dtype(self) -> T.DataType:
        return self.partial_dtypes()[0]

    @property
    def nullable(self) -> bool:
        return True


class Sum(AggregateFunction):
    def update_ops(self):
        return ["sum"]

    def merge_ops(self):
        return ["sum"]


class Count(AggregateFunction):
    """count(expr): counts non-null rows; count(*) via CountStar."""

    def update_ops(self):
        return ["count"]

    def merge_ops(self):
        return ["sum"]

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    @property
    def nullable(self) -> bool:
        return False

    def finalize_expr(self, partial_refs):
        from spark_rapids_tpu.exprs.predicates import Coalesce
        from spark_rapids_tpu.exprs.base import Literal

        # the merge phase SUMs counts; over an empty grand aggregate that
        # sum is NULL but SQL count() must be 0
        return Coalesce(partial_refs[0], Literal.of(0))


class CountDistinct(AggregateFunction):
    """count(DISTINCT x) marker: never executes directly — the session
    frontend rewrites it into a two-level aggregate (group-by-x dedupe
    then count), the single-distinct specialization of Spark's
    RewriteDistinctAggregates rule."""

    @property
    def name(self) -> str:
        return "count_distinct"


class CountStar(AggregateFunction):
    def __init__(self):
        super().__init__(None)

    def update_ops(self):
        return ["count_star"]

    def merge_ops(self):
        return ["sum"]

    def partial_dtypes(self):
        return [T.LONG]

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    @property
    def nullable(self) -> bool:
        return False

    def finalize_expr(self, partial_refs):
        from spark_rapids_tpu.exprs.predicates import Coalesce
        from spark_rapids_tpu.exprs.base import Literal

        # merge-sum of counts is NULL only for an empty global aggregate
        return Coalesce(partial_refs[0], Literal.of(0))


class Min(AggregateFunction):
    def update_ops(self):
        return ["min"]

    def merge_ops(self):
        return ["min"]


class Max(AggregateFunction):
    def update_ops(self):
        return ["max"]

    def merge_ops(self):
        return ["max"]


@dataclasses.dataclass(repr=False)
class First(AggregateFunction):
    """first(expr[, ignoreNulls]) — Spark defaults ignoreNulls to FALSE:
    a group whose first value is NULL returns NULL (ref: GpuFirst,
    AggregateFunctions.scala).  Deterministic only after an explicit
    sort, as in Spark."""

    ignore_nulls: bool = False

    def bind(self, schema: T.Schema) -> "First":
        from spark_rapids_tpu.exprs.base import bind_references

        return type(self)(bind_references(self.child, schema),
                          self.ignore_nulls)

    def _op(self) -> str:
        base = type(self).__name__.lower()
        return base if self.ignore_nulls else f"{base}_any"

    def update_ops(self):
        return [self._op()]

    def merge_ops(self):
        return [self._op()]


class Last(First):
    pass


class Average(AggregateFunction):
    """avg = sum / count, decomposed exactly like the reference's
    GpuAverage (AggregateFunctions.scala): partials [sum, count],
    merge [sum, sum], finalize sum/count (NULL when count == 0 — Divide
    by zero yields NULL, matching Spark's null-safe average)."""

    def n_partials(self) -> int:
        return 2

    def update_ops(self):
        return ["sum", "count"]

    def merge_ops(self):
        return ["sum", "sum"]

    def partial_dtypes(self):
        return [T.DOUBLE, T.LONG]

    @property
    def dtype(self) -> T.DataType:
        return T.DOUBLE

    def finalize_expr(self, partial_refs):
        from spark_rapids_tpu.exprs.arithmetic import Divide

        return Divide(partial_refs[0], partial_refs[1])


@dataclasses.dataclass
class NamedAgg:
    """An aggregate function with its output column name."""

    fn: AggregateFunction
    out_name: str

    def output_field(self) -> T.Field:
        return T.Field(self.out_name, self.fn.dtype, self.fn.nullable)


class CollectList(AggregateFunction):
    """collect_list(expr): non-null inputs gathered into an array per
    group (ref: AggregateFunctions.scala GpuCollectList; element order
    is unspecified, as in Spark).  Executes on a dedicated two-phase
    dense-list exec (ops/collect.py); multi-partition plans fall back."""

    collect_kind = "list"

    def update_ops(self):
        return ["collect"]

    def merge_ops(self):
        return ["collect"]

    @property
    def dtype(self) -> T.DataType:
        cdt = self.child.dtype
        if isinstance(cdt, T.ListType):
            # nested arrays have no logical type in this engine —
            # a query-construction error, not a fallback (documented
            # divergence: the reference supports array<array<T>>)
            raise TypeError(
                f"{self.name} over an array column is not supported "
                "by this engine (no nested array type)")
        return T.ListType(cdt)

    @property
    def nullable(self) -> bool:
        return False  # empty group -> empty list, never NULL

    def check_supported(self) -> None:
        dt = self.child.dtype
        if isinstance(dt, (T.StringType, T.DecimalType)):
            raise TypeError(
                f"{self.name} over {dt.name} input runs on the CPU "
                "engine (device lists hold fixed-width elements only)")


class CollectSet(CollectList):
    """collect_set(expr): distinct non-null inputs per group (total
    order equality: NaN == NaN dedups)."""

    collect_kind = "set"


@dataclasses.dataclass(repr=False)
class PivotFirst(AggregateFunction):
    """pivot aggregate marker (ref: GpuPivotFirst,
    AggregateFunctions.scala): first value of `child` per group per
    pivot value.  Expanded at aggregate construction into one masked
    First per pivot value — PivotFirst(p, v, [a, b]) becomes
    first(if(p = a, v, null) ignore nulls), first(if(p = b, ...)) —
    the exact per-slot semantics the reference's array-building kernel
    computes, laid out straight into the output columns."""

    pivot: Expression = None  # type: ignore[assignment]
    pivot_values: tuple = ()

    @property
    def name(self) -> str:
        return (f"pivot_first({self.pivot.name}, {self.child.name}, "
                f"{list(self.pivot_values)})")

    def inputs(self):
        return (self.child, self.pivot)

    def bind(self, schema):
        from spark_rapids_tpu.exprs.base import bind_references

        return PivotFirst(bind_references(self.child, schema),
                          bind_references(self.pivot, schema),
                          tuple(self.pivot_values))

    def expand(self, out_name: str) -> list["NamedAgg"]:
        """The masked-First expansion (one output column per value)."""
        return expand_pivot_aggs(
            self.pivot, self.pivot_values,
            [NamedAgg(First(self.child, ignore_nulls=True), out_name)],
            single=out_name == "__pivot")


def expand_pivot_aggs(pcol, values, named: list["NamedAgg"],
                      single: bool) -> list["NamedAgg"]:
    """Masked-aggregate pivot expansion shared by PivotFirst and
    GroupedData.pivot(): F(v) becomes F(if(p <=> val, v, null)) per
    pivot value.  A None pivot value matches NULL keys (null-safe);
    First/Last flip to ignore_nulls so masked-out rows never win the
    slot (the reference's PivotFirst updates only on a pivot match)."""
    import dataclasses as _dc

    from spark_rapids_tpu.exprs.base import Literal
    from spark_rapids_tpu.exprs.predicates import EqualTo, If, IsNull

    out = []
    for v in values:
        for na in named:
            ins = na.fn.inputs()
            if len(ins) != 1:
                raise ValueError(
                    f"pivot over {na.fn.name} is not supported")
            child = ins[0]
            try:
                null_dt = child.dtype  # bound children know their type
            except RuntimeError:
                null_dt = None  # unbound: NULL literal widens in If
            cond = IsNull(pcol) if v is None \
                else EqualTo(pcol, Literal.of(v))
            masked = If(cond, child, Literal.of(None, null_dt))
            fn2 = _dc.replace(na.fn, child=masked)
            if isinstance(fn2, First) and not fn2.ignore_nulls:
                # non-null-ignoring First/Last would treat masked-out
                # rows as candidate values — Spark's pivot only
                # considers matching rows
                fn2 = _dc.replace(fn2, ignore_nulls=True)
            name = str(v) if single else f"{v}_{na.out_name}"
            out.append(NamedAgg(fn2, name))
    return out
