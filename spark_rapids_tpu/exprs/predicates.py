"""Predicate and null-handling expressions.

TPU counterparts of the reference's predicates and null expressions
(ref: sql-plugin/.../org/apache/spark/sql/rapids/predicates.scala, 631 LoC;
com/nvidia/spark/rapids/nullExpressions.scala, conditionalExpressions.scala,
GpuInSet.scala) with Spark SQL three-valued logic: And/Or are Kleene
(false AND NULL = false, true OR NULL = true), comparisons propagate NULL,
EqualNullSafe/IsNull/IsNotNull/IsNaN never return NULL.

Floating-point comparisons implement Spark's total order for NaN:
NaN = NaN is true and NaN sorts greater than every other value.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    broadcast_validity,
)


def _string_cmp(lc: StringColumn, rc: StringColumn):
    """Lexicographic byte comparison of two string columns.
    Returns (lt, eq) boolean arrays.  Widths may differ; compare on the
    common padded width (zero padding sorts first, which matches byte-wise
    UTF-8 ordering on the unpadded strings)."""
    w = max(lc.width, rc.width)
    lchars = jnp.pad(lc.chars, ((0, 0), (0, w - lc.width)))
    rchars = jnp.pad(rc.chars, ((0, 0), (0, w - rc.width)))
    diff = lchars.astype(jnp.int16) - rchars.astype(jnp.int16)
    nz = diff != 0
    any_nz = jnp.any(nz, axis=1)
    first_nz = jnp.argmax(nz, axis=1)
    first_diff = jnp.take_along_axis(diff, first_nz[:, None], axis=1)[:, 0]
    lt = any_nz & (first_diff < 0)
    eq_bytes = ~any_nz
    # zero padding makes "a" and "a\0" byte-equal; break ties on length so
    # embedded-NUL strings compare correctly (shorter prefix sorts first)
    lt = lt | (eq_bytes & (lc.lengths < rc.lengths))
    eq = eq_bytes & (lc.lengths == rc.lengths)
    return lt, eq


def _ordered_cmp(ld, rd):
    """(lt, eq) under Spark's total order: for floats, NaN == NaN and NaN
    is greater than everything else."""
    if jnp.issubdtype(ld.dtype, jnp.floating):
        lnan = jnp.isnan(ld)
        rnan = jnp.isnan(rd)
        eq = (ld == rd) | (lnan & rnan)
        lt = (ld < rd) | (~lnan & rnan)
        return lt, eq
    return ld < rd, ld == rd


@dataclasses.dataclass(repr=False)
class BinaryComparison(Expression):
    left: Expression
    right: Expression

    symbol = "?"

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def _cmp_columns(self, lc: AnyColumn, rc: AnyColumn):
        if isinstance(lc, StringColumn) or isinstance(rc, StringColumn):
            return _string_cmp(lc, rc)
        ct = T.common_type(self.left.dtype, self.right.dtype) \
            or self.left.dtype
        phys = T.to_numpy_dtype(ct)
        return _ordered_cmp(lc.data.astype(phys), rc.data.astype(phys))

    def eval(self, ctx: EvalContext) -> AnyColumn:
        lc = self.left.eval(ctx)
        rc = self.right.eval(ctx)
        valid = broadcast_validity(lc, rc)
        lt, eq = self._cmp_columns(lc, rc)
        return Column(self.compare_ordered(lt, eq), valid, T.BOOLEAN)

    def compare_ordered(self, lt, eq):
        raise NotImplementedError


class EqualTo(BinaryComparison):
    symbol = "="

    def compare_ordered(self, lt, eq):
        return eq


class LessThan(BinaryComparison):
    symbol = "<"

    def compare_ordered(self, lt, eq):
        return lt


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def compare_ordered(self, lt, eq):
        return lt | eq


class GreaterThan(BinaryComparison):
    symbol = ">"

    def compare_ordered(self, lt, eq):
        return ~(lt | eq)


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def compare_ordered(self, lt, eq):
        return ~lt


class EqualNullSafe(BinaryComparison):
    """<=>: never NULL; NULL <=> NULL is true."""

    symbol = "<=>"

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        lc = self.left.eval(ctx)
        rc = self.right.eval(ctx)
        _, eq = self._cmp_columns(lc, rc)
        both_null = ~lc.validity & ~rc.validity
        both_valid = lc.validity & rc.validity
        data = (both_null | (both_valid & eq)) & ctx.row_mask
        return Column(data, ctx.row_mask, T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class And(Expression):
    left: Expression
    right: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def eval(self, ctx: EvalContext) -> AnyColumn:
        lc = self.left.eval(ctx)
        rc = self.right.eval(ctx)
        lval = lc.data.astype(bool)
        rval = rc.data.astype(bool)
        false_wins = (lc.validity & ~lval) | (rc.validity & ~rval)
        valid = (lc.validity & rc.validity) | false_wins
        return Column(lval & rval & lc.validity & rc.validity,
                      valid, T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class Or(Expression):
    left: Expression
    right: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def eval(self, ctx: EvalContext) -> AnyColumn:
        lc = self.left.eval(ctx)
        rc = self.right.eval(ctx)
        lval = lc.data.astype(bool) & lc.validity
        rval = rc.data.astype(bool) & rc.validity
        true_wins = lval | rval
        valid = (lc.validity & rc.validity) | true_wins
        return Column(true_wins, valid, T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class Not(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(~c.data.astype(bool), c.validity, T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class IsNull(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(~c.validity & ctx.row_mask, ctx.row_mask, T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class IsNotNull(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(c.validity & ctx.row_mask, ctx.row_mask, T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class IsNaN(Expression):
    """Spark IsNaN: non-nullable; NULL input -> false."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(jnp.isnan(c.data) & c.validity, ctx.row_mask,
                      T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class In(Expression):
    """value IN (literals...) (ref: GpuInSet.scala). NULL semantics: if the
    value is NULL -> NULL; if no match and the list contains NULL -> NULL."""

    child: Expression
    values: tuple

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        has_null = any(v is None for v in self.values)
        vals = [v for v in self.values if v is not None]
        if isinstance(c, StringColumn):
            from spark_rapids_tpu.exprs.base import Literal

            match = jnp.zeros(c.capacity, bool)
            for v in vals:
                litcol = Literal.of(v, T.STRING).eval(ctx)
                _, eq = _string_cmp(c, litcol)
                match = match | eq
        else:
            phys = c.data.dtype
            match = jnp.zeros(c.data.shape[0], bool)
            for v in vals:
                match = match | (c.data == jnp.asarray(v, phys))
        valid = c.validity & (match | (~jnp.asarray(has_null)))
        return Column(match, valid, T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class Coalesce(Expression):
    """First non-null value (ref: nullExpressions.scala GpuCoalesce)."""

    exprs: tuple[Expression, ...]

    def __init__(self, *exprs: Expression):
        self.exprs = tuple(exprs)

    def with_children(self, children):
        return Coalesce(*children)

    @property
    def dtype(self) -> T.DataType:
        from spark_rapids_tpu.exprs.arithmetic import _widen

        if isinstance(self.exprs[0].dtype, T.StringType):
            return T.STRING
        return _widen([e.dtype for e in self.exprs])

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cols = [e.eval(ctx) for e in self.exprs]
        if isinstance(cols[0], StringColumn):
            w = max(c.width for c in cols)
            chars = jnp.zeros((cols[0].capacity, w), jnp.uint8)
            lengths = jnp.zeros(cols[0].capacity, jnp.int32)
            taken = jnp.zeros(cols[0].capacity, bool)
            for c in cols:
                pc = jnp.pad(c.chars, ((0, 0), (0, w - c.width)))
                use = c.validity & ~taken
                chars = jnp.where(use[:, None], pc, chars)
                lengths = jnp.where(use, c.lengths, lengths)
                taken = taken | c.validity
            return StringColumn(chars, lengths, taken)
        phys = T.to_numpy_dtype(self.dtype)
        data = jnp.zeros(cols[0].data.shape[0], phys)
        taken = jnp.zeros(cols[0].data.shape[0], bool)
        for c in cols:
            use = c.validity & ~taken
            data = jnp.where(use, c.data.astype(phys), data)
            taken = taken | c.validity
        return Column(data, taken, self.dtype)


@dataclasses.dataclass(repr=False)
class If(Expression):
    """if(cond, a, b) (ref: conditionalExpressions.scala GpuIf).
    Branch types widen to a common numeric type."""

    pred: Expression
    then: Expression
    otherwise: Expression

    @property
    def dtype(self) -> T.DataType:
        if isinstance(self.then.dtype, T.StringType) or isinstance(
                self.otherwise.dtype, T.StringType):
            return T.STRING
        from spark_rapids_tpu.exprs.arithmetic import _widen

        return _widen([self.then.dtype, self.otherwise.dtype])

    def eval(self, ctx: EvalContext) -> AnyColumn:
        p = self.pred.eval(ctx)
        a = self.then.eval(ctx)
        b = self.otherwise.eval(ctx)
        take_a = p.data.astype(bool) & p.validity
        if isinstance(a, StringColumn):
            w = max(a.width, b.width)
            ac = jnp.pad(a.chars, ((0, 0), (0, w - a.width)))
            bc = jnp.pad(b.chars, ((0, 0), (0, w - b.width)))
            return StringColumn(
                jnp.where(take_a[:, None], ac, bc),
                jnp.where(take_a, a.lengths, b.lengths),
                jnp.where(take_a, a.validity, b.validity))
        phys = T.to_numpy_dtype(self.dtype)
        return Column(
            jnp.where(take_a, a.data.astype(phys), b.data.astype(phys)),
            jnp.where(take_a, a.validity, b.validity),
            self.dtype)


@dataclasses.dataclass(repr=False)
class CaseWhen(Expression):
    """CASE WHEN ... (ref: conditionalExpressions.scala GpuCaseWhen)."""

    branches: tuple[tuple[Expression, Expression], ...]
    else_value: Expression

    @property
    def children(self):
        kids = []
        for c, v in self.branches:
            kids += [c, v]
        kids.append(self.else_value)
        return tuple(kids)

    def with_children(self, children):
        n = len(self.branches)
        branches = tuple(
            (children[2 * i], children[2 * i + 1]) for i in range(n))
        return CaseWhen(branches, children[2 * n])

    @property
    def dtype(self) -> T.DataType:
        vals = [v for _, v in self.branches] + [self.else_value]
        if any(isinstance(v.dtype, T.StringType) for v in vals):
            return T.STRING
        from spark_rapids_tpu.exprs.arithmetic import _widen

        return _widen([v.dtype for v in vals])

    def eval(self, ctx: EvalContext) -> AnyColumn:
        expr: Expression = self.else_value
        for cond, val in reversed(self.branches):
            expr = If(cond, val, expr)
        return expr.eval(ctx)


@dataclasses.dataclass(repr=False)
class AtLeastNNonNulls(Expression):
    n: int
    exprs: tuple[Expression, ...]

    def __init__(self, n: int, exprs: Sequence[Expression]):
        self.n = n
        self.exprs = tuple(exprs)

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cols = [e.eval(ctx) for e in self.exprs]
        count = None
        for c in cols:
            v = c.validity
            if not isinstance(c, StringColumn):
                if jnp.issubdtype(c.data.dtype, jnp.floating):
                    v = v & ~jnp.isnan(c.data)
            x = v.astype(jnp.int32)
            count = x if count is None else count + x
        return Column(count >= self.n, ctx.row_mask, T.BOOLEAN)
