"""Struct/map extractor and constructor expressions.

TPU counterparts of the reference's complex-type expressions (ref:
org/apache/spark/sql/rapids/complexTypeExtractors.scala GpuGetStructField
/ GpuGetMapValue / GpuElementAt, complexTypeCreator.scala
GpuCreateNamedStruct).  The struct-of-columns layout makes field access
zero-copy (validity AND); the twin-matrix map layout makes key lookup
one vectorized compare + first-match gather."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    Column,
    ListColumn,
    MapColumn,
    StructColumn,
)
from spark_rapids_tpu.exprs.base import EvalContext, Expression, Literal

#: map/list element types the device kernels handle (fixed-width
#: physical); strings inside maps fall back to the CPU engine
_FIXED = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
          T.LongType, T.FloatType, T.DoubleType, T.DateType,
          T.TimestampType, T.DecimalType)


@dataclasses.dataclass(repr=False)
class GetStructField(Expression):
    """struct.field — child column with parent-validity AND (ref:
    GpuGetStructField, complexTypeExtractors.scala)."""

    child: Expression
    field_name: str

    @property
    def dtype(self) -> T.DataType:
        dt = self.child.dtype
        if isinstance(dt, T.StructType):
            try:
                return dt.fields[dt.field_index(self.field_name)].dtype
            except KeyError:
                return T.NULL
        return T.NULL

    @property
    def name(self) -> str:
        return f"{self.child.name}.{self.field_name}"

    def check_supported(self) -> None:
        dt = self.child.dtype
        if not isinstance(dt, T.StructType):
            raise TypeError("getField requires a struct input")
        dt.field_index(self.field_name)  # raises KeyError if absent

    def eval(self, ctx: EvalContext):
        sc = self.child.eval(ctx)
        assert isinstance(sc, StructColumn), type(sc).__name__
        dt = self.child.dtype
        c = sc.children[dt.field_index(self.field_name)]
        return c.with_validity(c.validity & sc.validity)


@dataclasses.dataclass(repr=False)
class CreateNamedStruct(Expression):
    """named_struct(n1, v1, ...) (ref: GpuCreateNamedStruct,
    complexTypeCreator.scala) — always-valid struct rows over the
    evaluated children."""

    names: tuple
    values: tuple  # of Expression

    @property
    def dtype(self) -> T.DataType:
        return T.StructType([T.Field(n, v.dtype, True)
                             for n, v in zip(self.names, self.values)])

    @property
    def name(self) -> str:
        inner = ", ".join(f"{n}: {v.name}"
                          for n, v in zip(self.names, self.values))
        return f"named_struct({inner})"

    @property
    def children(self) -> tuple:
        return tuple(self.values)

    def with_children(self, children):
        return CreateNamedStruct(self.names, tuple(children))

    def check_supported(self) -> None:
        if len(self.names) != len(self.values) or not self.values:
            raise TypeError("named_struct needs matching names/values")

    def eval(self, ctx: EvalContext) -> StructColumn:
        kids = tuple(v.eval(ctx) for v in self.values)
        cap = kids[0].capacity
        return StructColumn(kids, jnp.ones(cap, bool), self.dtype)


def _map_lookup(mc: MapColumn, key_value, value_dtype: T.DataType
                ) -> Column:
    """First-match lookup: NULL when the key is absent or the row is
    NULL (ref: GpuGetMapValue)."""
    slot = jnp.arange(mc.max_len, dtype=jnp.int32)[None, :]
    in_len = slot < mc.lengths[:, None].astype(jnp.int32)
    kphys = mc.keys.dtype
    eq = (mc.keys == jnp.asarray(key_value, kphys)) & in_len
    found = jnp.any(eq, axis=1)
    idx = jnp.argmax(eq, axis=1)
    rows = jnp.arange(mc.capacity)
    vals = mc.values[rows, idx]
    evalid = mc.entry_validity[rows, idx]
    return Column(vals.astype(T.to_numpy_dtype(value_dtype)),
                  mc.validity & found & evalid, value_dtype)


def _check_map_device(dt: T.MapType) -> None:
    if not isinstance(dt.key, _FIXED) or not isinstance(dt.value,
                                                        _FIXED):
        raise TypeError(
            f"map {dt.name} has non-fixed-width key/value (device "
            "lookup supports primitives; CPU fallback handles the rest)")


@dataclasses.dataclass(repr=False)
class GetMapValue(Expression):
    """map[key] with a literal key (ref: GpuGetMapValue)."""

    child: Expression
    key: Expression  # Literal

    @property
    def dtype(self) -> T.DataType:
        dt = self.child.dtype
        return dt.value if isinstance(dt, T.MapType) else T.NULL

    @property
    def name(self) -> str:
        return f"{self.child.name}[{self.key.name}]"

    def check_supported(self) -> None:
        dt = self.child.dtype
        if not isinstance(dt, T.MapType):
            raise TypeError("getMapValue requires a map input")
        if not isinstance(self.key, Literal) or self.key.value is None:
            raise TypeError("getMapValue key must be a non-null literal")
        _check_map_device(dt)

    def eval(self, ctx: EvalContext) -> Column:
        mc = self.child.eval(ctx)
        assert isinstance(mc, MapColumn), type(mc).__name__
        return _map_lookup(mc, self.key.value, self.dtype)


@dataclasses.dataclass(repr=False)
class ElementAt(Expression):
    """element_at(array, i) (1-based, negative from the end) or
    element_at(map, key) (ref: GpuElementAt; Spark rejects index 0
    outright, out-of-bounds yields NULL in non-ANSI mode)."""

    child: Expression
    index: Expression  # Literal

    @property
    def dtype(self) -> T.DataType:
        dt = self.child.dtype
        if isinstance(dt, T.ListType):
            return dt.element
        if isinstance(dt, T.MapType):
            return dt.value
        return T.NULL

    @property
    def name(self) -> str:
        return f"element_at({self.child.name}, {self.index.name})"

    def check_supported(self) -> None:
        dt = self.child.dtype
        if not isinstance(dt, (T.ListType, T.MapType)):
            raise TypeError("element_at requires an array or map input")
        if not isinstance(self.index, Literal) \
                or self.index.value is None:
            raise TypeError("element_at index must be a non-null literal")
        if isinstance(dt, T.ListType):
            if int(self.index.value) == 0:
                raise ValueError("SQL array indices start at 1")
        else:
            _check_map_device(dt)

    def eval(self, ctx: EvalContext) -> Column:
        dt = self.child.dtype
        if isinstance(dt, T.MapType):
            mc = self.child.eval(ctx)
            assert isinstance(mc, MapColumn)
            return _map_lookup(mc, self.index.value, self.dtype)
        c = self.child.eval(ctx)
        assert isinstance(c, ListColumn), type(c).__name__
        k = int(self.index.value)
        lens = c.lengths.astype(jnp.int32)
        # 1-based; negative counts back from the end
        pos = jnp.where(jnp.int32(k) > 0, jnp.int32(k - 1),
                        lens + jnp.int32(k))
        in_bounds = (pos >= 0) & (pos < lens)
        safe = jnp.clip(pos, 0, max(c.max_len - 1, 0))
        rows = jnp.arange(c.capacity)
        vals = c.values[rows, safe]
        evalid = c.elem_validity[rows, safe]
        return Column(vals.astype(T.to_numpy_dtype(self.dtype)),
                      c.validity & in_bounds & evalid, self.dtype)
