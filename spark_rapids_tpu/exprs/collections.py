"""Collection (array) expressions over dense list matrices.

TPU counterparts of collectionOperations.scala / complexTypeExtractors
(ref: sql-plugin/.../sql/rapids/collectionOperations.scala,
GpuGetArrayItem in complexTypeExtractors.scala, GpuExplode in
GpuGenerateExec.scala:378).  cudf walks offsets+child buffers; a
ListColumn is a dense (rows, max_len) element matrix + lengths, so
every op here is one vectorized program."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import Column, ListColumn
from spark_rapids_tpu.exprs.base import EvalContext, Expression, Literal


def _as_list(col) -> ListColumn:
    assert isinstance(col, ListColumn), \
        f"collection op over non-list column {type(col).__name__}"
    return col


@dataclasses.dataclass(repr=False)
class Size(Expression):
    """size(array) — number of elements, NULL for NULL input
    (ref: GpuSize, collectionOperations.scala; legacy -1-for-null mode
    not implemented)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    @property
    def name(self) -> str:
        return f"size({self.child.name})"

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.ListType):
            raise TypeError(f"size() requires an array, got "
                            f"{self.child.dtype.name}")

    def eval(self, ctx: EvalContext) -> Column:
        c = _as_list(self.child.eval(ctx))
        return Column(c.lengths.astype(jnp.int32), c.validity, T.INT)


@dataclasses.dataclass(repr=False)
class GetArrayItem(Expression):
    """array[i] with a literal index — NULL when out of bounds or the
    element is NULL (ref: GpuGetArrayItem,
    complexTypeExtractors.scala)."""

    child: Expression
    index: Expression  # Literal int

    @property
    def dtype(self) -> T.DataType:
        dt = self.child.dtype
        # non-list child: keep schema derivation alive so tagging can
        # report the real reason via check_supported
        return dt.element if isinstance(dt, T.ListType) else T.NULL

    @property
    def name(self) -> str:
        return f"{self.child.name}[{self.index.name}]"

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.ListType):
            raise TypeError("getItem requires an array input")
        if not isinstance(self.index, Literal):
            raise TypeError("getItem index must be a literal")

    def eval(self, ctx: EvalContext) -> Column:
        c = _as_list(self.child.eval(ctx))
        k = int(self.index.value)  # type: ignore[union-attr]
        if k < 0 or k >= c.max_len:
            phys = T.to_numpy_dtype(self.dtype)
            return Column(jnp.zeros(c.capacity, phys),
                          jnp.zeros(c.capacity, bool), self.dtype)
        in_bounds = jnp.int32(k) < c.lengths
        return Column(c.values[:, k],
                      c.validity & in_bounds & c.elem_validity[:, k],
                      self.dtype)


@dataclasses.dataclass(repr=False)
class ArrayContains(Expression):
    """array_contains(arr, literal) with Spark NULL semantics: true if
    found; NULL if not found but the array has NULL elements; else
    false (ref: GpuArrayContains, collectionOperations.scala)."""

    child: Expression
    value: Expression  # Literal

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    @property
    def name(self) -> str:
        return f"array_contains({self.child.name}, {self.value.name})"

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.ListType):
            raise TypeError("array_contains requires an array input")
        if not isinstance(self.value, Literal) \
                or self.value.value is None:
            raise TypeError("array_contains value must be a non-null "
                            "literal")

    def eval(self, ctx: EvalContext) -> Column:
        c = _as_list(self.child.eval(ctx))
        elem_dt = c.dtype.element  # type: ignore[union-attr]
        phys = T.to_numpy_dtype(elem_dt)
        v = jnp.asarray(self.value.value, phys)  # type: ignore[union-attr]
        pos = jnp.arange(c.max_len, dtype=jnp.int32)[None, :]
        in_len = pos < c.lengths[:, None]
        hit = in_len & c.elem_validity & (c.values == v)
        found = jnp.any(hit, axis=1)
        has_null = jnp.any(in_len & ~c.elem_validity, axis=1)
        return Column(found, c.validity & (found | ~has_null), T.BOOLEAN)


@dataclasses.dataclass(repr=False)
class Explode(Expression):
    """Generator marker: one output row per array element.  Never
    evaluates inline — the session frontend extracts it into a Generate
    node (ref: GpuExplode/GpuPosExplode, GpuGenerateExec.scala:378);
    `outer` emits a NULL-element row for empty/NULL arrays
    (explode_outer)."""

    child: Expression
    pos: bool = False
    outer: bool = False

    @property
    def dtype(self) -> T.DataType:
        dt = self.child.dtype
        return dt.element if isinstance(dt, T.ListType) else T.NULL

    @property
    def name(self) -> str:
        base = "posexplode" if self.pos else "explode"
        return f"{base}{'_outer' if self.outer else ''}({self.child.name})"

    def check_supported(self) -> None:
        if not isinstance(self.child.dtype, T.ListType):
            raise TypeError(f"{self.name} requires an array input")

    def eval(self, ctx: EvalContext):
        raise TypeError("explode must appear at the top level of a "
                        "select list")


@dataclasses.dataclass(repr=False)
class CreateArray(Expression):
    """array(e1, e2, ...) — fixed-length list per row from N element
    expressions (ref: GpuCreateArray, complexTypeCreator.scala).  The
    dense matrix is just a stack: max_len == N for every row."""

    exprs: tuple[Expression, ...]

    def __init__(self, *exprs: Expression):
        if not exprs:
            raise TypeError("array() needs at least one element "
                            "(empty arrays are not supported)")
        self.exprs = tuple(exprs)

    def with_children(self, children):
        return type(self)(*children)

    @property
    def dtype(self) -> T.DataType:
        from spark_rapids_tpu.exprs.arithmetic import _widen

        return T.ListType(_widen([e.dtype for e in self.exprs]))

    @property
    def nullable(self) -> bool:
        return False

    def check_supported(self) -> None:
        for e in self.exprs:
            if isinstance(e.dtype, (T.StringType, T.ListType)):
                raise TypeError(
                    "array() of string/nested elements is not supported")

    def eval(self, ctx: EvalContext) -> ListColumn:
        elem_t = self.dtype.element
        phys = T.to_numpy_dtype(elem_t)
        cols = [e.eval(ctx) for e in self.exprs]
        values = jnp.stack([c.data.astype(phys) for c in cols], axis=1)
        evalid = jnp.stack([c.validity for c in cols], axis=1)
        n = len(cols)
        cap = ctx.batch.capacity
        return ListColumn(values,
                          jnp.full((cap,), n, jnp.int32),
                          evalid, ctx.row_mask, T.ListType(elem_t))
