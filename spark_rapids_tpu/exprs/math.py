"""Math expressions.

TPU counterparts of the reference's mathExpressions.scala (447 LoC).
Spark semantics preserved where they differ from IEEE/numpy defaults:
log-family functions return NULL (not NaN/-inf) for out-of-domain
inputs, ceil/floor of doubles return LONG, round is HALF_UP while bround
is HALF_EVEN (ref: GpuCeil/GpuFloor/GpuRound in mathExpressions.scala)."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    broadcast_validity,
)


@dataclasses.dataclass(repr=False)
class UnaryMath(Expression):
    """double -> double elementwise function."""

    child: Expression

    fn = staticmethod(lambda d: d)

    @property
    def dtype(self) -> T.DataType:
        return T.DOUBLE

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        d = c.data.astype(jnp.float64)
        return Column(type(self).fn(d), c.validity, T.DOUBLE)


class Sqrt(UnaryMath):
    fn = staticmethod(jnp.sqrt)  # sqrt(neg) = NaN, as Spark


class Cbrt(UnaryMath):
    fn = staticmethod(jnp.cbrt)


class Exp(UnaryMath):
    fn = staticmethod(jnp.exp)


class Expm1(UnaryMath):
    fn = staticmethod(jnp.expm1)


class Sin(UnaryMath):
    fn = staticmethod(jnp.sin)


class Cos(UnaryMath):
    fn = staticmethod(jnp.cos)


class Tan(UnaryMath):
    fn = staticmethod(jnp.tan)


class Cot(UnaryMath):
    fn = staticmethod(lambda d: 1.0 / jnp.tan(d))


class Asin(UnaryMath):
    fn = staticmethod(jnp.arcsin)


class Acos(UnaryMath):
    fn = staticmethod(jnp.arccos)


class Atan(UnaryMath):
    fn = staticmethod(jnp.arctan)


class Sinh(UnaryMath):
    fn = staticmethod(jnp.sinh)


class Cosh(UnaryMath):
    fn = staticmethod(jnp.cosh)


class Tanh(UnaryMath):
    fn = staticmethod(jnp.tanh)


class Asinh(UnaryMath):
    fn = staticmethod(jnp.arcsinh)


class Acosh(UnaryMath):
    fn = staticmethod(jnp.arccosh)


class Atanh(UnaryMath):
    fn = staticmethod(jnp.arctanh)


class Rint(UnaryMath):
    fn = staticmethod(jnp.rint)


class Signum(UnaryMath):
    fn = staticmethod(jnp.sign)


class ToDegrees(UnaryMath):
    fn = staticmethod(jnp.degrees)


class ToRadians(UnaryMath):
    fn = staticmethod(jnp.radians)


class _LogBase(UnaryMath):
    """Spark log family: NULL for input <= 0 (ref: GpuLog et al apply an
    is-not-<=0 mask — Spark returns NULL where math would give NaN/-inf)."""

    _shift = 0.0  # log1p domain is > -1

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        d = c.data.astype(jnp.float64)
        bad = d <= -self._shift if self._shift else d <= 0.0
        safe = jnp.where(bad, 1.0, d)
        return Column(type(self).fn(safe), c.validity & ~bad, T.DOUBLE)

    @property
    def nullable(self) -> bool:
        return True


class Log(_LogBase):
    fn = staticmethod(jnp.log)


class Log10(_LogBase):
    fn = staticmethod(jnp.log10)


class Log2(_LogBase):
    fn = staticmethod(jnp.log2)


class Log1p(_LogBase):
    fn = staticmethod(jnp.log1p)
    _shift = 1.0


@dataclasses.dataclass(repr=False)
class Logarithm(Expression):
    """log(base, x); NULL when x <= 0 or base <= 0."""

    base: Expression
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.DOUBLE

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, ctx: EvalContext) -> AnyColumn:
        b = self.base.eval(ctx)
        c = self.child.eval(ctx)
        bd = b.data.astype(jnp.float64)
        cd = c.data.astype(jnp.float64)
        bad = (cd <= 0.0) | (bd <= 0.0)
        out = jnp.log(jnp.where(cd <= 0, 1.0, cd)) / \
            jnp.log(jnp.where(bd <= 0, 2.0, bd))
        return Column(out, broadcast_validity(b, c) & ~bad, T.DOUBLE)


@dataclasses.dataclass(repr=False)
class Pow(Expression):
    left: Expression
    right: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.DOUBLE

    def eval(self, ctx: EvalContext) -> AnyColumn:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        out = jnp.power(l.data.astype(jnp.float64),
                        r.data.astype(jnp.float64))
        return Column(out, broadcast_validity(l, r), T.DOUBLE)


@dataclasses.dataclass(repr=False)
class Ceil(Expression):
    """ceil(double) -> LONG (Spark), identity on integral types."""

    child: Expression

    _fn = staticmethod(jnp.ceil)

    @property
    def dtype(self) -> T.DataType:
        if isinstance(self.child.dtype, (T.FloatType, T.DoubleType)):
            return T.LONG
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        from spark_rapids_tpu.exprs.cast import saturating_float_to_integral

        c = self.child.eval(ctx)
        if not isinstance(self.child.dtype, (T.FloatType, T.DoubleType)):
            return c
        r = type(self)._fn(c.data.astype(jnp.float64))
        # ceil/floor already produce integral values; the shared
        # conversion contributes NaN -> 0 and Long.MIN/MAX saturation
        # (Spark's java (long) cast), where a raw astype is backend-defined
        out = saturating_float_to_integral(r, jnp.int64)
        return Column(out, c.validity, T.LONG)


class Floor(Ceil):
    _fn = staticmethod(jnp.floor)


@dataclasses.dataclass(repr=False)
class Round(Expression):
    """round(x, scale) HALF_UP (Spark GpuRound); bround is HALF_EVEN."""

    child: Expression
    scale: int = 0

    half_even = False

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        dt = self.child.dtype
        if isinstance(dt, (T.FloatType, T.DoubleType)):
            d = c.data.astype(jnp.float64)
            p = 10.0 ** self.scale
            scaled = d * p
            if self.half_even:
                r = jnp.rint(scaled)
            else:
                # HALF_UP: away from zero at .5
                r = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
            out = (r / p).astype(
                jnp.float32 if isinstance(dt, T.FloatType) else jnp.float64)
            return Column(out, c.validity, dt)
        if self.scale >= 0:
            return c
        p = 10 ** (-self.scale)
        d = c.data.astype(jnp.int64)
        if self.half_even:
            # floor-based: rem in [0, p) makes HALF_EVEN symmetric
            q0 = d // p
            rem = d - q0 * p
            up = (rem * 2 > p) | ((rem * 2 == p) & (q0 % 2 != 0))
            out = (q0 + up.astype(jnp.int64)) * p
        else:
            out = jnp.where(d >= 0, (d + p // 2) // p,
                            -((-d + p // 2) // p)) * p
        return Column(out.astype(c.data.dtype), c.validity, dt)


class BRound(Round):
    half_even = True


@dataclasses.dataclass(repr=False)
class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN, else a (ref: GpuNaNvl,
    mathExpressions.scala)."""

    left: Expression
    right: Expression

    @property
    def dtype(self) -> T.DataType:
        return self.left.dtype  # registered for float/double only

    def eval(self, ctx: EvalContext) -> AnyColumn:
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        take_b = jnp.isnan(a.data.astype(jnp.float64)) & a.validity
        phys = T.to_numpy_dtype(self.dtype)
        return Column(
            jnp.where(take_b, b.data.astype(phys), a.data.astype(phys)),
            jnp.where(take_b, b.validity, a.validity), self.dtype)


@dataclasses.dataclass(repr=False)
class NormalizeNaNAndZero(Expression):
    """Canonicalize NaN bit patterns and -0.0 -> +0.0 so float GROUP BY
    / join keys compare equal (ref: GpuNormalizeNaNAndZero,
    normalizedExpressions GpuOverrides.scala)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        d = c.data
        d = jnp.where(jnp.isnan(d), jnp.asarray(float("nan"), d.dtype), d)
        d = d + jnp.zeros((), d.dtype)  # -0.0 + 0.0 == +0.0
        return Column(d, c.validity, self.dtype)


@dataclasses.dataclass(repr=False)
class KnownFloatingPointNormalized(Expression):
    """Analyzer marker: input is already normalized; identity
    (ref: GpuKnownFloatingPointNormalized)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        return self.child.eval(ctx)
