"""Cast expression.

TPU counterpart of GpuCast.scala (1,296 LoC).  Non-ANSI Spark cast
semantics for the supported matrix:

- numeric -> narrower integral: bit truncation (Java semantics);
- float/double -> integral: truncate toward zero; NaN -> 0; +/-inf and
  out-of-range saturate to the target MIN/MAX (Java `(long) d`);
- numeric -> boolean: != 0; boolean -> numeric: 1/0;
- date -> timestamp: midnight UTC; timestamp -> date: floor to day;
- timestamp <-> long: seconds (Spark casts ts to epoch *seconds*);
- integral -> string: device-side digit expansion;
- string -> integral: device-side parse, NULL on malformed (non-ANSI);
- decimal -> wider decimal: int64 unscaled rescale (widening shapes
  only — scale and integral digits both non-decreasing);
- integral -> decimal (when every source digit fits) and
  decimal -> float/double.

Unsupported pairs raise at construction; the planner turns that into a
will-not-work reason and falls back (the reference gates the same way
through TypeSig checks, GpuCast.scala:166)."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn
from spark_rapids_tpu.exprs.base import EvalContext, Expression

_INTEGRAL = (T.ByteType, T.ShortType, T.IntegerType, T.LongType)
_FLOATING = (T.FloatType, T.DoubleType)
_NUMERIC = _INTEGRAL + _FLOATING

#: max decimal digits for int64 -> string expansion
_MAX_DIGITS = 20


def cast_supported(src: T.DataType, dst: T.DataType) -> bool:
    if src == dst:
        return True
    ts, td = type(src), type(dst)
    if ts in _NUMERIC and td in _NUMERIC:
        return True
    if ts in _NUMERIC and td is T.BooleanType:
        return True
    if ts is T.BooleanType and td in _NUMERIC:
        return True
    if (ts, td) in ((T.DateType, T.TimestampType),
                    (T.TimestampType, T.DateType)):
        return True
    if ts is T.TimestampType and td is T.LongType:
        return True
    if ts is T.LongType and td is T.TimestampType:
        return True
    if ts in _INTEGRAL + (T.BooleanType,) and td is T.StringType:
        return True
    if ts is T.StringType and td in _INTEGRAL:
        return True
    if ts is T.DecimalType and td is T.DecimalType:
        # pure widening only (no value can overflow the int64 unscaled
        # backing): integral digits and scale both non-decreasing.  This
        # is the shape UNION member coercion produces; a narrowing
        # decimal cast (overflow -> NULL/ANSI raise) is future work.
        return (dst.scale >= src.scale
                and dst.precision - dst.scale >= src.precision - src.scale)
    if ts in _INTEGRAL and td is T.DecimalType:
        # widening only: the target must hold every integral digit the
        # source type can produce
        return (dst.precision - dst.scale
                >= T.INTEGRAL_DECIMAL_DIGITS[ts])
    if ts is T.DecimalType and td in _FLOATING:
        return True
    return False


@dataclasses.dataclass(repr=False)
class Cast(Expression):
    child: Expression
    to: T.DataType

    @property
    def dtype(self) -> T.DataType:
        return self.to

    def check_supported(self) -> None:
        """Raises for unsupported pairs.  Called after reference binding
        (construction may hold unresolved ColumnReferences); the planner
        turns the raise into a will-not-work reason -> CPU fallback."""
        if not cast_supported(self.child.dtype, self.to):
            raise TypeError(
                f"cast {self.child.dtype} -> {self.to} not supported")

    @property
    def name(self) -> str:
        return f"cast({self.child.name} as {self.to.name})"

    def eval(self, ctx: EvalContext) -> AnyColumn:
        from spark_rapids_tpu.exprs.base import ansi_active, ansi_report

        self.check_supported()
        src = self.child.dtype
        dst = self.to
        c = self.child.eval(ctx)
        if src == dst:
            return c
        ts, td = type(src), type(dst)
        if ts is T.StringType:
            out = _parse_integral(c, dst)
            if ansi_active():
                # ANSI: malformed input RAISES instead of NULLing
                # (ref: GpuCast ANSI matrix, GpuCast.scala:166)
                ansi_report(
                    c.validity & ~out.validity,
                    f"invalid input syntax for type {dst.name} "
                    "(ANSI cast)")
            return out
        if td is T.StringType:
            return _integral_to_string(c, src, ctx)
        d = c.data
        valid = c.validity
        if td is T.BooleanType:
            return Column(d != 0, valid, dst)
        if ts is T.BooleanType:
            return Column(d.astype(T.to_numpy_dtype(dst)), valid, dst)
        if ts is T.DecimalType and td is T.DecimalType:
            # rescale the int64 unscaled value; cast_supported admits
            # only widening shapes, so the shift is >= 0 and the result
            # provably fits MAX_PRECISION digits (no overflow check)
            shift = dst.scale - src.scale
            return Column(d.astype(jnp.int64) * (10 ** shift), valid, dst)
        if ts in _INTEGRAL and td is T.DecimalType:
            # widening only (cast_supported): value * 10^scale fits the
            # MAX_PRECISION-digit unscaled int64
            return Column(d.astype(jnp.int64) * (10 ** dst.scale),
                          valid, dst)
        if ts is T.DecimalType and td in _FLOATING:
            out = d.astype(jnp.float64) / (10.0 ** src.scale)
            return Column(out.astype(T.to_numpy_dtype(dst)), valid, dst)
        if (ts, td) == (T.DateType, T.TimestampType):
            from spark_rapids_tpu.exprs.datetime import US_PER_DAY

            return Column(d.astype(jnp.int64) * US_PER_DAY, valid, dst)
        if (ts, td) == (T.TimestampType, T.DateType):
            from spark_rapids_tpu.exprs.datetime import US_PER_DAY

            us = d.astype(jnp.int64)
            return Column((us // US_PER_DAY).astype(jnp.int32), valid, dst)
        if ts is T.TimestampType and td is T.LongType:
            return Column(d.astype(jnp.int64) // 1_000_000, valid, dst)
        if ts is T.LongType and td is T.TimestampType:
            return Column(d.astype(jnp.int64) * 1_000_000, valid, dst)
        phys = T.to_numpy_dtype(dst)
        if ts in _FLOATING and td in _INTEGRAL:
            if ansi_active():
                f = d.astype(jnp.float64)
                info = jnp.iinfo(phys)
                t = jnp.trunc(f)
                bad = valid & (jnp.isnan(f)
                               | (t > float(info.max))
                               | (t < float(info.min)))
                ansi_report(
                    bad, f"value out of range for {dst.name} "
                    "(ANSI cast overflow)")
            return Column(saturating_float_to_integral(d, phys), valid, dst)
        out_data = d.astype(phys)
        if ansi_active() and ts in _INTEGRAL and td in _INTEGRAL \
                and jnp.dtype(phys).itemsize < d.dtype.itemsize:
            # narrowing truncation that loses value = ANSI overflow
            ansi_report(valid & (out_data.astype(d.dtype) != d),
                        f"value out of range for {dst.name} "
                        "(ANSI cast overflow)")
        return Column(out_data, valid, dst)


def saturating_float_to_integral(d, phys):
    """Java (long)(double) semantics: truncate toward zero, NaN -> 0,
    +/-inf and out-of-range saturate at target MIN/MAX.  Saturation is by
    threshold compare: float64 cannot represent INT64_MAX, so
    clip-then-astype would convert 2^63 out of range.  Shared by Cast and
    Ceil/Floor (whose double -> LONG results must saturate identically)."""
    f = d.astype(jnp.float64)
    info = jnp.iinfo(phys)
    hi_f = float(info.max) + 1.0  # exact power of two
    lo_f = float(info.min)
    t = jnp.trunc(jnp.where(jnp.isnan(f), 0.0, f))
    interior = (t > lo_f) & (t < hi_f)
    out = jnp.where(interior, t, 0.0).astype(phys)
    out = jnp.where(t >= hi_f, info.max, out)
    out = jnp.where(t <= lo_f, info.min, out)
    return out


def _integral_to_string(c: Column, src: T.DataType,
                        ctx: EvalContext) -> StringColumn:
    """Digit expansion on device: int64 -> fixed-width decimal bytes."""
    if isinstance(src, T.BooleanType):
        n = c.data.shape[0]
        true_b = jnp.asarray(
            [116, 114, 117, 101, 0], jnp.uint8)  # "true"
        false_b = jnp.asarray(
            [102, 97, 108, 115, 101], jnp.uint8)  # "false"
        b = c.data.astype(bool)
        chars = jnp.where(b[:, None], true_b[None, :], false_b[None, :])
        lengths = jnp.where(b, 4, 5).astype(jnp.int32)
        return StringColumn(chars, lengths, c.validity)
    v = c.data.astype(jnp.int64)
    neg = v < 0
    # abs via where (INT64_MIN-safe: uint arithmetic)
    u = jnp.where(neg, (-(v + 1)).astype(jnp.uint64) + 1,
                  v.astype(jnp.uint64))
    digits = []
    for i in range(_MAX_DIGITS):
        digits.append((u % 10).astype(jnp.uint8))
        u = u // 10
    digs = jnp.stack(digits[::-1], axis=1)  # most significant first
    ndig = jnp.maximum(
        _MAX_DIGITS - jnp.sum(jnp.cumsum(digs != 0, axis=1) == 0, axis=1),
        1).astype(jnp.int32)
    length = ndig + neg.astype(jnp.int32)
    width = _MAX_DIGITS + 1
    # layout: optional '-' then digits left-aligned
    pos = jnp.arange(width, dtype=jnp.int32)[None, :]
    digit_idx = pos - neg.astype(jnp.int32)[:, None] \
        + (_MAX_DIGITS - ndig)[:, None]
    digit_idx_c = jnp.clip(digit_idx, 0, _MAX_DIGITS - 1)
    dig_chars = jnp.take_along_axis(digs, digit_idx_c, axis=1) + 48
    chars = jnp.where((pos == 0) & neg[:, None], 45, dig_chars)  # '-'
    in_range = pos < length[:, None]
    chars = jnp.where(in_range, chars, 0).astype(jnp.uint8)
    return StringColumn(chars, length, c.validity)


def _parse_integral(c: StringColumn, dst: T.DataType) -> Column:
    """String -> integral parse; NULL on malformed (non-ANSI Spark).
    Accepts optional sign + digits + surrounding ASCII whitespace (Spark
    trims UTF8 whitespace before parsing)."""
    chars = c.chars.astype(jnp.int32)
    lengths = c.lengths
    n, w = chars.shape
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_str = pos < lengths[:, None]
    is_space = in_str & ((chars == 32) | ((chars >= 9) & (chars <= 13)))
    # leading/trailing whitespace bounds
    lead = jnp.sum(jnp.cumprod(is_space, axis=1), axis=1)
    rev_space = is_space[:, ::-1] | ~in_str[:, ::-1]
    trail_plus_pad = jnp.sum(jnp.cumprod(rev_space, axis=1), axis=1)
    end = w - trail_plus_pad
    start = lead.astype(jnp.int32)
    end = jnp.maximum(end.astype(jnp.int32), start)
    has_sign = in_str & (pos == start[:, None]) & (
        (chars == 45) | (chars == 43))
    sign_neg = jnp.any(has_sign & (chars == 45), axis=1)
    dstart = start + jnp.any(has_sign, axis=1).astype(jnp.int32)
    payload = (pos >= dstart[:, None]) & (pos < end[:, None])
    is_digit = (chars >= 48) & (chars <= 57)
    # Spark accepts a fractional tail and TRUNCATES toward zero
    # (cast('3.5' as int) = 3, cast('-3.5' as int) = -3): digits up to
    # an optional single '.', digits after it ignored for the value
    dot = payload & (chars == 46)
    any_dot = jnp.any(dot, axis=1)
    first_dot = jnp.where(any_dot,
                          jnp.argmax(dot, axis=1).astype(jnp.int32),
                          end)
    int_pos = payload & (pos < first_dot[:, None])
    frac_pos = payload & (pos > first_dot[:, None])
    n_digits = jnp.sum((int_pos | frac_pos) & is_digit, axis=1)
    ok = jnp.all(~int_pos | is_digit, axis=1) \
        & jnp.all(~frac_pos | is_digit, axis=1) \
        & (n_digits > 0)
    is_digit_pos = int_pos
    digit_vals = jnp.where(is_digit_pos & is_digit, chars - 48, 0)
    # Horner in uint64 magnitude with overflow detection (19-digit
    # values can exceed INT64_MAX and must become NULL, not wrap)
    acc = jnp.zeros((n,), jnp.uint64)
    overflow = jnp.zeros((n,), bool)
    safe_mul = jnp.uint64((2**64 - 1) // 10)
    for j in range(w):
        dj = digit_vals[:, j].astype(jnp.uint64)
        use = is_digit_pos[:, j]
        overflow = overflow | (use & (acc > safe_mul))
        nxt = acc * jnp.uint64(10)
        overflow = overflow | (use & (nxt > nxt + dj))  # add wrapped
        acc = jnp.where(use, nxt + dj, acc)
    bound = jnp.where(sign_neg, jnp.uint64(2**63), jnp.uint64(2**63 - 1))
    ok = ok & ~overflow & (acc <= bound)
    mag = acc.astype(jnp.int64)  # -2^63 wraps correctly under negation
    val = jnp.where(sign_neg, -mag, mag)
    phys = T.to_numpy_dtype(dst)
    if not isinstance(dst, T.LongType):
        info = jnp.iinfo(phys)
        ok = ok & (val >= info.min) & (val <= info.max)
    return Column(val.astype(phys), c.validity & ok, dst)
