"""Arithmetic expressions.

TPU counterparts of the reference's arithmetic expression library
(ref: sql-plugin/.../org/apache/spark/sql/rapids/arithmetic.scala, 676 LoC)
with Spark SQL semantics: NULL-propagating binary ops, NULL (not error) on
divide/modulo by zero in non-ANSI mode, Java-style truncating integer
division, and Spark's pmod definition (sign handling follows
`r = a % n; if (r < 0) (r + n) % n else r` with Java `%`).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    broadcast_validity,
    result_numeric_type,
)


def _java_divmod(ld, rd):
    """Java's truncate-toward-zero (quotient, remainder) for integer
    arrays.  jnp's // rounds toward -inf; Java truncates toward zero."""
    qi = ld // rd
    rem = ld - qi * rd
    fix = (rem != 0) & ((ld < 0) != (rd < 0))
    qtrunc = jnp.where(fix, qi + 1, qi)
    return qtrunc, ld - qtrunc * rd


def _java_mod(ld, rd):
    return _java_divmod(ld, rd)[1]


@dataclasses.dataclass(repr=False)
class BinaryArithmetic(Expression):
    left: Expression
    right: Expression

    symbol = "?"

    @property
    def dtype(self) -> T.DataType:
        return result_numeric_type(self.left.dtype, self.right.dtype)

    @property
    def nullable(self) -> bool:
        return self.left.nullable or self.right.nullable

    def _phys(self):
        return T.to_numpy_dtype(self.dtype)

    def eval(self, ctx: EvalContext) -> AnyColumn:
        from spark_rapids_tpu.exprs.base import ansi_active

        lc = self.left.eval(ctx)
        rc = self.right.eval(ctx)
        phys = self._phys()
        ld = lc.data.astype(phys)
        rd = rc.data.astype(phys)
        valid = broadcast_validity(lc, rc)
        pre_valid = valid
        data, valid = self.compute(ld, rd, valid)
        if ansi_active():
            self._ansi_check(ld, rd, data, pre_valid, phys)
        return Column(data, valid, self.dtype)

    def _ansi_check(self, ld, rd, data, valid, phys) -> None:
        """Per-op ANSI error detection (overflow / division by zero);
        `valid` is the PRE-compute row validity."""

    def compute(self, ld, rd, valid):
        raise NotImplementedError


class _DecimalAddSub(BinaryArithmetic):
    """Shared decimal path for +/-: Spark's analyzer result type
    (p = max integral digits + max scale + 1, s = max scale, ref:
    decimalExpressions.scala / DecimalPrecision) with operands rescaled
    to the result scale — exact unscaled int64 math while the result
    precision fits MAX_PRECISION; wider falls back."""

    def _decimal_result(self, l: T.DecimalType,
                        r: T.DecimalType) -> T.DecimalType:
        s = max(l.scale, r.scale)
        p = max(l.precision - l.scale, r.precision - r.scale) + s + 1
        return T.DecimalType(min(p, T.DecimalType.MAX_PRECISION), s)

    @property
    def dtype(self) -> T.DataType:
        l, r = self.left.dtype, self.right.dtype
        if isinstance(l, T.DecimalType) and isinstance(r, T.DecimalType):
            return self._decimal_result(l, r)
        return result_numeric_type(l, r)

    def check_supported(self) -> None:
        try:
            l, r = self.left.dtype, self.right.dtype
        except RuntimeError:
            return  # unbound; the planner re-checks after binding
        ldec = isinstance(l, T.DecimalType)
        rdec = isinstance(r, T.DecimalType)
        if ldec != rdec:
            raise TypeError("decimal +/- with a non-decimal operand "
                            "falls back")
        if ldec:
            s = max(l.scale, r.scale)
            p = max(l.precision - l.scale, r.precision - r.scale) + s + 1
            if p > T.DecimalType.MAX_PRECISION:
                raise TypeError(
                    "decimal +/- beyond precision 18 falls back "
                    "(unscaled int64 math would overflow)")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        out = self.dtype
        if not isinstance(out, T.DecimalType):
            return super().eval(ctx)
        import jax.numpy as jnp

        lc = self.left.eval(ctx)
        rc = self.right.eval(ctx)
        ls = out.scale - self.left.dtype.scale
        rs = out.scale - self.right.dtype.scale
        ld = lc.data * jnp.int64(10 ** ls) if ls else lc.data
        rd = rc.data * jnp.int64(10 ** rs) if rs else rc.data
        valid = broadcast_validity(lc, rc)
        data, valid = self.compute(ld, rd, valid)
        return Column(data, valid, out)


def _overflow_message(phys) -> str:
    # java.lang.Math.addExact wording (what Spark surfaces)
    return "long overflow" if jnp.dtype(phys).itemsize == 8 \
        else "integer overflow"


class Add(_DecimalAddSub):
    symbol = "+"

    def compute(self, ld, rd, valid):
        return ld + rd, valid

    def _ansi_check(self, ld, rd, data, valid, phys) -> None:
        from spark_rapids_tpu.exprs.base import ansi_report

        if not jnp.issubdtype(phys, jnp.integer):
            return
        # two same-sign operands whose sum flips sign overflowed
        ovf = valid & ((ld >= 0) == (rd >= 0)) \
            & ((data >= 0) != (ld >= 0))
        ansi_report(ovf, _overflow_message(phys))


class Subtract(_DecimalAddSub):
    symbol = "-"

    def compute(self, ld, rd, valid):
        return ld - rd, valid

    def _ansi_check(self, ld, rd, data, valid, phys) -> None:
        from spark_rapids_tpu.exprs.base import ansi_report

        if not jnp.issubdtype(phys, jnp.integer):
            return
        # mixed-sign operands whose difference flips sign overflowed
        ovf = valid & ((ld >= 0) != (rd >= 0)) \
            & ((data >= 0) != (ld >= 0))
        ansi_report(ovf, _overflow_message(phys))


class Multiply(BinaryArithmetic):
    symbol = "*"

    @property
    def dtype(self) -> T.DataType:
        l, r = self.left.dtype, self.right.dtype
        if isinstance(l, T.DecimalType) and isinstance(r, T.DecimalType):
            # Spark DecimalPrecision: scale adds, precision p1+p2+1 —
            # the declared type the CPU fallback must produce (device
            # multiply over decimals is not supported; TypeSig refuses)
            return T.DecimalType(
                min(l.precision + r.precision + 1,
                    T.DecimalType.MAX_PRECISION),
                min(l.scale + r.scale, T.DecimalType.MAX_PRECISION))
        return result_numeric_type(l, r)

    def compute(self, ld, rd, valid):
        return ld * rd, valid

    def _ansi_check(self, ld, rd, data, valid, phys) -> None:
        from spark_rapids_tpu.exprs.base import ansi_report

        if not jnp.issubdtype(phys, jnp.integer):
            return
        # multiplicative overflow: the product no longer divides back
        # to the left operand (Math.multiplyExact's check), plus the
        # MIN_VALUE * -1 corner
        info = jnp.iinfo(phys)
        back = jnp.where(rd != 0, _java_divmod(data, jnp.where(
            rd != 0, rd, 1))[0], 0)
        ovf = valid & (rd != 0) & (back != ld)
        ovf = ovf | (valid & (ld == info.min) & (rd == -1))
        ansi_report(ovf, _overflow_message(phys))


class _DivByZeroAnsi(BinaryArithmetic):
    """Shared ANSI division-by-zero detection for the divide family
    (both-valid gating; Spark's right-only rule differs on the
    (NULL, 0) corner — documented engine behavior)."""

    def _ansi_check(self, ld, rd, data, valid, phys) -> None:
        from spark_rapids_tpu.exprs.base import ansi_report

        ansi_report(valid & (rd == 0), "Division by zero")


class Divide(_DivByZeroAnsi):
    """Double division; x/0 -> NULL per Spark non-ANSI Divide semantics."""

    symbol = "/"

    @property
    def nullable(self) -> bool:
        return True  # introduces NULL on zero divisor (Spark: always true)

    @property
    def dtype(self) -> T.DataType:
        return T.DOUBLE

    def compute(self, ld, rd, valid):
        zero = rd == 0.0
        safe = jnp.where(zero, 1.0, rd)
        return ld / safe, valid & ~zero



class IntegralDivide(_DivByZeroAnsi):
    """`div`: long division truncated toward zero; x div 0 -> NULL."""

    symbol = "div"

    @property
    def nullable(self) -> bool:
        return True

    @property
    def dtype(self) -> T.DataType:
        return T.LONG

    def compute(self, ld, rd, valid):
        zero = rd == 0
        safe = jnp.where(zero, 1, rd)
        # integer arithmetic (no float round-trip: big longs lose precision)
        qi, _ = _java_divmod(ld, safe)
        return qi, valid & ~zero



class Remainder(_DivByZeroAnsi):
    """`%` with Java semantics (sign of dividend); x % 0 -> NULL."""

    symbol = "%"

    @property
    def nullable(self) -> bool:
        return True

    def compute(self, ld, rd, valid):
        if jnp.issubdtype(ld.dtype, jnp.floating):
            zero = rd == 0.0
            safe = jnp.where(zero, 1.0, rd)
            return jnp.fmod(ld, safe), valid & ~zero
        zero = rd == 0
        safe = jnp.where(zero, 1, rd)
        return _java_mod(ld, safe), valid & ~zero



class Pmod(_DivByZeroAnsi):
    """Spark pmod: `r = a % n; if (r < 0) (r + n) % n else r` with Java `%`
    (ref: arithmetic.scala GpuPmod).  Note pmod(-7, -3) = -1, not 2."""

    symbol = "pmod"

    @property
    def nullable(self) -> bool:
        return True

    def compute(self, ld, rd, valid):
        if jnp.issubdtype(ld.dtype, jnp.floating):
            zero = rd == 0.0
            safe = jnp.where(zero, 1.0, rd)
            r = jnp.fmod(ld, safe)
            r = jnp.where(r < 0, jnp.fmod(r + safe, safe), r)
            return r, valid & ~zero
        zero = rd == 0
        safe = jnp.where(zero, 1, rd)
        r = _java_mod(ld, safe)
        r = jnp.where(r < 0, _java_mod(r + safe, safe), r)
        return r, valid & ~zero



@dataclasses.dataclass(repr=False)
class UnaryMinus(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(-c.data, c.validity, self.dtype)


@dataclasses.dataclass(repr=False)
class UnaryPositive(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        return self.child.eval(ctx)


@dataclasses.dataclass(repr=False)
class Abs(Expression):
    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return self.child.dtype

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        return Column(jnp.abs(c.data), c.validity, self.dtype)


def _widen(dtypes) -> T.DataType:
    out = dtypes[0]
    for dt in dtypes[1:]:
        ct = T.common_type(out, dt)
        if ct is None:
            raise TypeError(f"incompatible types {out} / {dt}")
        out = ct
    return out


@dataclasses.dataclass(repr=False)
class Least(Expression):
    """least(...) ignoring NULLs (ref: arithmetic.scala GpuLeast).

    Selection runs on integer *total-order keys* (the sort-key transform
    from ops.sort) rather than the float values themselves, which gets
    Spark's ordering contract for free: NaN counts as the greatest value
    (least(NaN, 1.0) = 1.0, greatest(NaN, 1.0) = NaN).  NULL slots are
    excluded by validity-aware selection, not sentinel keys, so extreme
    valid values (LONG_MAX, +/-inf) are handled exactly."""

    exprs: tuple[Expression, ...]


    def __init__(self, *exprs: Expression):
        self.exprs = tuple(exprs)

    def with_children(self, children):
        return type(self)(*children)

    @property
    def dtype(self) -> T.DataType:
        return _widen([e.dtype for e in self.exprs])

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cols = [e.eval(ctx) for e in self.exprs]
        phys = T.to_numpy_dtype(self.dtype)
        is_float = jnp.issubdtype(phys, jnp.floating)
        acc_val = acc_key = acc_valid = None
        for c in cols:
            d = c.data.astype(phys)
            # floats: Spark total order with NaN largest, realized by
            # canonicalizing NaN to +inf plus an is-NaN tiebreak INSIDE
            # _take_new (a 64-bit bitcast to order bits would not
            # compile through the TPU X64 rewriter); exact f64
            # comparisons are preserved
            key = (jnp.where(jnp.isnan(d), jnp.inf, d), jnp.isnan(d)) \
                if is_float else d
            if acc_val is None:
                acc_val, acc_key, acc_valid = d, key, c.validity
            else:
                # validity-aware select: no NULL sentinel key, so a valid
                # LONG_MAX/LONG_MIN can never collide with a NULL slot
                take = c.validity & (~acc_valid
                                     | self._take_new(key, acc_key))
                acc_val = jnp.where(take, d, acc_val)
                if isinstance(key, tuple):
                    acc_key = tuple(jnp.where(take, k, a)
                                    for k, a in zip(key, acc_key))
                else:
                    acc_key = jnp.where(take, key, acc_key)
                acc_valid = acc_valid | c.validity
        return Column(acc_val, acc_valid, self.dtype)

    @staticmethod
    def _lt(a, b):
        """Total-order less-than over plain or (value, is_nan) keys."""
        if isinstance(a, tuple):
            (va, na), (vb, nb) = a, b
            return (va < vb) | ((va == vb) & ~na & nb)
        return a < b

    def _take_new(self, k, acc_k):
        return self._lt(k, acc_k)


class Greatest(Least):
    def _take_new(self, k, acc_k):
        return self._lt(acc_k, k)
