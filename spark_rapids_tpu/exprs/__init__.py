from spark_rapids_tpu.exprs.base import (  # noqa: F401
    Alias,
    BoundReference,
    ColumnReference,
    EvalContext,
    Expression,
    Literal,
    bind_references,
)
from spark_rapids_tpu.exprs import arithmetic, predicates  # noqa: F401
