"""Scalar subqueries (ref: GpuScalarSubquery in the reference's misc
support, SURVEY §2.17): a single-row single-column child query used as
a scalar value.

Execution model: the planner's prepass runs the subplan ONCE per
plan_query and splices the result in as a Literal — the XLA-friendly
shape (no data-dependent control flow inside compiled programs), and
the same eager-broadcast the reference performs on the driver."""

from __future__ import annotations

import dataclasses

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import Expression


@dataclasses.dataclass(repr=False)
class ScalarSubquery(Expression):
    """Placeholder replaced by the planner prepass (TPU path) or
    evaluated eagerly by the CPU engine."""

    plan: object  # L.LogicalPlan (1 row x 1 column)

    @property
    def dtype(self) -> T.DataType:
        return self.plan.schema.fields[0].dtype

    @property
    def name(self) -> str:
        return "scalar_subquery"

    def eval(self, ctx):  # pragma: no cover - replaced before eval
        raise NotImplementedError(
            "ScalarSubquery must be rewritten by the planner prepass")


def subquery_value(plan, conf):
    """Run the subplan and return its scalar (Python value)."""
    from spark_rapids_tpu.config import SQL_ENABLED

    if conf.get(SQL_ENABLED):
        from spark_rapids_tpu.plan.planner import (
            collect_exec,
            plan_query,
        )

        exec_, _ = plan_query(plan, conf)
        tbl = collect_exec(exec_)
    else:
        from spark_rapids_tpu.cpu.engine import execute_cpu

        tbl = execute_cpu(plan)
    if tbl.num_rows != 1 or tbl.num_columns != 1:
        raise ValueError(
            f"scalar subquery must return 1x1, got "
            f"{tbl.num_rows}x{tbl.num_columns}")
    return tbl.column(0)[0].as_py()
