"""String expressions over fixed-width byte matrices.

TPU counterparts of stringFunctions.scala (976 LoC).  cudf walks ragged
offset+chars buffers; here every op is a dense (rows, width) vectorized
program:

- char-indexed ops (length, substring) derive a per-byte *character
  index* from UTF-8 start-byte detection (one cumsum);
- byte re-layout ops (substring, concat, trim, pad) build output via
  take_along_axis index arithmetic or a stable per-row argsort on a
  drop flag — the row-local analog of the batch compaction trick;
- case mapping decodes UTF-8 to codepoints and maps through a BMP
  lookup table (built once from Python's casing rules).  Codepoints
  whose case-mapped UTF-8 byte length differs (e.g. 'ß' -> 'SS') map to
  themselves — a documented divergence, mirroring the reference's
  unicode caveats (docs/compatibility.md "unicode case-change edge
  cases"; the reference ships an incompatibleOps flag for the same
  reason).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    AnyColumn,
    Column,
    StringColumn,
    pad_width,
)
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    Literal,
    broadcast_validity,
)


def _is_char_start(chars: jax.Array) -> jax.Array:
    """True for bytes that start a UTF-8 character (not 0b10xxxxxx)."""
    return (chars & 0xC0) != 0x80


def char_length(col: StringColumn) -> jax.Array:
    pos = jnp.arange(col.width, dtype=jnp.int32)[None, :]
    in_str = pos < col.lengths[:, None]
    return jnp.sum((_is_char_start(col.chars) & in_str).astype(jnp.int32),
                   axis=1)


@dataclasses.dataclass(repr=False)
class Length(Expression):
    """character_length (ref: GpuLength — char count, not bytes)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        return Column(char_length(c), c.validity, T.INT)


# ---------------------------------------------------------------------- #
# Case mapping
# ---------------------------------------------------------------------- #

@lru_cache(maxsize=2)
def _case_table(upper: bool) -> np.ndarray:
    """BMP codepoint -> cased codepoint, restricted to mappings that
    preserve UTF-8 byte length (others map to themselves)."""
    tbl = np.arange(0x10000, dtype=np.int32)
    for cp in range(0x10000):
        if 0xD800 <= cp <= 0xDFFF:  # surrogates are not characters
            continue
        ch = chr(cp)
        m = ch.upper() if upper else ch.lower()
        if len(m) == 1 and ord(m) < 0x10000:
            if len(m.encode("utf-8")) == len(ch.encode("utf-8")):
                tbl[cp] = ord(m)
    return tbl


def _decode_codepoints(chars: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-byte (codepoint_of_its_char, is_start).  3-byte max (BMP);
    4-byte sequences pass through unmapped."""
    c = chars.astype(jnp.int32)
    start = _is_char_start(chars)
    b0 = c
    b1 = jnp.pad(c[:, 1:], ((0, 0), (0, 1)))
    b2 = jnp.pad(c[:, 2:], ((0, 0), (0, 2)))
    cp1 = b0
    cp2 = ((b0 & 0x1F) << 6) | (b1 & 0x3F)
    cp3 = ((b0 & 0x0F) << 12) | ((b1 & 0x3F) << 6) | (b2 & 0x3F)
    cp = jnp.where(b0 < 0x80, cp1,
                   jnp.where(b0 < 0xE0, cp2,
                             jnp.where(b0 < 0xF0, cp3, -1)))
    return jnp.where(start, cp, -1), start


def _encode_inplace(chars: jax.Array, mapped_cp: jax.Array,
                    start: jax.Array) -> jax.Array:
    """Re-encode mapped codepoints over the same byte layout (same-length
    mappings only, enforced by the table)."""
    c = chars.astype(jnp.int32)
    one = (mapped_cp >= 0) & (mapped_cp < 0x80) & start
    two = (mapped_cp >= 0x80) & (mapped_cp < 0x800) & start
    three = (mapped_cp >= 0x800) & start
    out = c
    out = jnp.where(one, mapped_cp, out)
    out = jnp.where(two, 0xC0 | (mapped_cp >> 6), out)
    out = jnp.where(three, 0xE0 | (mapped_cp >> 12), out)
    # continuation bytes: recompute from *this* char's codepoint.  Chars
    # with no mapping (4-byte sequences, cp == -1) carry the -2 marker so
    # their continuation bytes pass through untouched — a plain
    # last-valid-value scan would leak the previous char's codepoint
    # into them and corrupt the UTF-8
    tag = jnp.where(start,
                    jnp.where(mapped_cp >= 0, mapped_cp, -2), -3)
    cp_here = jax.lax.associative_scan(
        lambda a, b: jnp.where(b != -3, b, a), tag, axis=1)
    pos = jnp.arange(chars.shape[1], dtype=jnp.int32)[None, :]
    start_pos = jax.lax.associative_scan(
        jnp.maximum, jnp.where(start, pos, -1), axis=1)
    off = pos - start_pos
    cont1 = (~start) & (off == 1)
    cont2 = (~start) & (off == 2)
    is3 = cp_here >= 0x800
    out = jnp.where(cont1 & is3, 0x80 | ((cp_here >> 6) & 0x3F), out)
    out = jnp.where(cont1 & ~is3 & (cp_here >= 0x80),
                    0x80 | (cp_here & 0x3F), out)
    out = jnp.where(cont2 & is3, 0x80 | (cp_here & 0x3F), out)
    return out.astype(jnp.uint8)


@dataclasses.dataclass(repr=False)
class Upper(Expression):
    child: Expression

    _upper = True

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        tbl = jnp.asarray(_case_table(self._upper))
        cp, start = _decode_codepoints(c.chars)
        safe_cp = jnp.clip(cp, 0, 0xFFFF)
        mapped = jnp.where((cp >= 0) & (cp < 0x10000),
                           jnp.take(tbl, safe_cp), cp)
        chars = _encode_inplace(c.chars, mapped, start)
        # zero out padding bytes again
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        chars = jnp.where(pos < c.lengths[:, None], chars, 0)
        return StringColumn(chars, c.lengths, c.validity)


class Lower(Upper):
    _upper = False


# ---------------------------------------------------------------------- #
# Search (literal needles, like the reference's lit-only TypeSigs)
# ---------------------------------------------------------------------- #

def _needle_bytes(e: Expression) -> bytes:
    assert isinstance(e, Literal), "needle must be a literal"
    return (e.value or "").encode("utf-8")


@dataclasses.dataclass(repr=False)
class StartsWith(Expression):
    left: Expression
    right: Expression  # literal

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def _match(self, c: StringColumn, nb: bytes) -> jax.Array:
        m = len(nb)
        if m == 0:
            return jnp.ones((c.capacity,), bool)
        if m > c.width:
            return jnp.zeros((c.capacity,), bool)
        needle = jnp.asarray(np.frombuffer(nb, np.uint8))
        return (c.lengths >= m) & jnp.all(
            c.chars[:, :m] == needle[None, :], axis=1)

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.left.eval(ctx)
        r = self.right.eval(ctx)
        out = self._match(c, _needle_bytes(self.right))
        return Column(out, broadcast_validity(c, r), T.BOOLEAN)


class EndsWith(StartsWith):
    def _match(self, c: StringColumn, nb: bytes) -> jax.Array:
        m = len(nb)
        if m == 0:
            return jnp.ones((c.capacity,), bool)
        if m > c.width:
            return jnp.zeros((c.capacity,), bool)
        needle = jnp.asarray(np.frombuffer(nb, np.uint8))
        # gather the last m bytes of each row
        start = jnp.maximum(c.lengths - m, 0)
        idx = start[:, None] + jnp.arange(m, dtype=jnp.int32)[None, :]
        tail = jnp.take_along_axis(
            c.chars, jnp.clip(idx, 0, c.width - 1), axis=1)
        return (c.lengths >= m) & jnp.all(tail == needle[None, :], axis=1)


class Contains(StartsWith):
    def _match(self, c: StringColumn, nb: bytes) -> jax.Array:
        m = len(nb)
        if m == 0:
            return jnp.ones((c.capacity,), bool)
        if m > c.width:
            return jnp.zeros((c.capacity,), bool)
        needle = jnp.asarray(np.frombuffer(nb, np.uint8))
        # compare all windows (W - m + 1 shifted equality tests, fused)
        hit = jnp.zeros((c.capacity,), bool)
        for off in range(c.width - m + 1):
            w = c.chars[:, off:off + m]
            hit = hit | ((c.lengths >= off + m)
                         & jnp.all(w == needle[None, :], axis=1))
        return hit


@dataclasses.dataclass(repr=False)
class Like(Expression):
    """SQL LIKE for simple patterns (%x, x%, %x%, exact, and
    'a%b' prefix+suffix).  Patterns with '_' or more embedded '%'s fail
    check_supported() and the planner falls back to the CPU engine's
    full match_like (the reference likewise refuses regex-like patterns,
    GpuOverrides.scala:440-473)."""

    left: Expression
    pattern: str

    def check_supported(self) -> None:
        p = self.pattern
        if "_" in p:
            raise TypeError("LIKE with '_' not supported on TPU")
        if "\\" in p:
            raise TypeError("LIKE with escapes not supported on TPU")
        inner = p.strip("%")
        if "%" in inner and len(inner.split("%")) != 2:
            raise TypeError(f"LIKE pattern {p!r} not supported on TPU")

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def eval(self, ctx: EvalContext) -> AnyColumn:
        self.check_supported()
        c = self.left.eval(ctx)
        assert isinstance(c, StringColumn)
        p = self.pattern
        lead = p.startswith("%")
        trail = p.endswith("%")
        inner = p.strip("%")
        lit_ = Literal.of(inner, T.STRING)
        if "%" in inner:  # 'a%b': prefix + suffix, lengths must fit
            pre, suf = inner.split("%")
            m1 = StartsWith(self.left, Literal.of(pre, T.STRING))._match(
                c, pre.encode())
            m2 = EndsWith(self.left, Literal.of(suf, T.STRING))._match(
                c, suf.encode())
            fit = c.lengths >= len(pre.encode()) + len(suf.encode())
            out = m1 & m2 & fit
        elif lead and trail:
            out = Contains(self.left, lit_)._match(c, inner.encode())
        elif trail:
            out = StartsWith(self.left, lit_)._match(c, inner.encode())
        elif lead:
            out = EndsWith(self.left, lit_)._match(c, inner.encode())
        else:
            nb = inner.encode()
            out = StartsWith(self.left, lit_)._match(c, nb) & (
                c.lengths == len(nb))
        return Column(out, c.validity, T.BOOLEAN)


# ---------------------------------------------------------------------- #
# Re-layout ops
# ---------------------------------------------------------------------- #

def _compact_rows(chars: jax.Array, keep: jax.Array
                  ) -> tuple[jax.Array, jax.Array]:
    """Left-pack kept bytes within each row (stable), zero the rest."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    packed = jnp.take_along_axis(chars, order, axis=1)
    new_len = jnp.sum(keep.astype(jnp.int32), axis=1)
    pos = jnp.arange(chars.shape[1], dtype=jnp.int32)[None, :]
    packed = jnp.where(pos < new_len[:, None], packed, 0)
    return packed, new_len


@dataclasses.dataclass(repr=False)
class Substring(Expression):
    """substring(str, pos, len) — 1-based, char-indexed, negative pos
    from the end (ref: GpuSubstring)."""

    child: Expression
    pos: int
    length: Optional[int] = None

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        nchars = char_length(c)
        pos = self.pos
        # Spark substringSQL: the length window counts from the
        # *unclamped* start (substring('abc', -5, 3) == 'a')
        if pos > 0:
            start = jnp.full_like(nchars, pos - 1)
        elif pos == 0:
            start = jnp.zeros_like(nchars)
        else:
            start = nchars + pos
        if self.length is None:
            end = nchars
        else:
            end = start + max(self.length, 0)
        start = jnp.maximum(start, 0)
        bpos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        in_str = bpos < c.lengths[:, None]
        char_idx = jnp.cumsum(
            (_is_char_start(c.chars) & in_str).astype(jnp.int32),
            axis=1) - 1
        keep = in_str & (char_idx >= start[:, None]) & \
            (char_idx < end[:, None])
        chars, lengths = _compact_rows(c.chars, keep)
        return StringColumn(chars, lengths, c.validity)


@dataclasses.dataclass(repr=False)
class StringTrim(Expression):
    """trim(str): strip leading+trailing spaces (0x20, Spark default)."""

    child: Expression

    _lead = True
    _trail = True

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        in_str = pos < c.lengths[:, None]
        sp = (c.chars == 32) & in_str
        keep = in_str
        if self._lead:
            lead_run = jnp.cumprod(sp.astype(jnp.int32), axis=1)
            keep = keep & (lead_run == 0)
        if self._trail:
            rev = (sp | ~in_str)[:, ::-1]
            trail_run = jnp.cumprod(rev.astype(jnp.int32), axis=1)[:, ::-1]
            keep = keep & (trail_run == 0)
        chars, lengths = _compact_rows(c.chars, keep)
        return StringColumn(chars, lengths, c.validity)


class StringTrimLeft(StringTrim):
    _trail = False


class StringTrimRight(StringTrim):
    _lead = False


@dataclasses.dataclass(repr=False)
class Concat(Expression):
    """concat(s1, s2, ...): NULL if any input NULL (Spark concat)."""

    exprs: tuple[Expression, ...]

    def __init__(self, *exprs: Expression):
        self.exprs = tuple(exprs)

    def with_children(self, children):
        return Concat(*children)

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cols = [e.eval(ctx) for e in self.exprs]
        total_w = pad_width(sum(c.width for c in cols))
        n = cols[0].capacity
        out_pos = jnp.arange(total_w, dtype=jnp.int32)[None, :]
        chars = jnp.zeros((n, total_w), jnp.uint8)
        offset = jnp.zeros((n,), jnp.int32)
        valid = None
        for c in cols:
            src_idx = out_pos - offset[:, None]
            in_src = (src_idx >= 0) & (src_idx < c.lengths[:, None])
            gathered = jnp.take_along_axis(
                c.chars, jnp.clip(src_idx, 0, c.width - 1), axis=1)
            chars = jnp.where(in_src, gathered, chars)
            offset = offset + c.lengths
            valid = c.validity if valid is None else (valid & c.validity)
        return StringColumn(chars, offset, valid)


# ---------------------------------------------------------------------- #
# Expression batch 3 (ref: stringFunctions.scala GpuStringReplace,
# GpuStringLPad/RPad, GpuStringLocate, GpuSubstringIndex, GpuInitCap,
# GpuConcatWs; regexp policy from GpuOverrides.scala:440-473)
# ---------------------------------------------------------------------- #

def _match_starts(c: StringColumn, nb: bytes) -> jax.Array:
    """(capacity, width) bool: a full needle match begins at this byte
    (within the row's length)."""
    m = len(nb)
    W = c.width
    out = jnp.zeros((c.capacity, W), bool)
    if m == 0 or m > W:
        return out
    needle = jnp.asarray(np.frombuffer(nb, np.uint8))
    for off in range(W - m + 1):
        w = c.chars[:, off:off + m]
        hit = (c.lengths >= off + m) & jnp.all(w == needle[None, :], axis=1)
        out = out.at[:, off].set(hit)
    return out


def _greedy_matches(starts: jax.Array, m: int) -> jax.Array:
    """Left-to-right non-overlapping match selection (the semantics of
    str.replace): a candidate is real only if no real match covers it.
    One lax.scan across the width (width is small and static)."""
    W = starts.shape[1]

    def step(next_allowed, j_col):
        j, cand = j_col
        real = cand & (j >= next_allowed)
        next_allowed = jnp.where(real, j + m, next_allowed)
        return next_allowed, real

    js = jnp.arange(W, dtype=jnp.int32)
    init = jnp.zeros((starts.shape[0],), jnp.int32)
    _, reals = jax.lax.scan(step, init, (js, starts.T))
    return reals.T


@dataclasses.dataclass(repr=False)
class StringReplace(Expression):
    """replace(str, search, replacement) with literal search/replacement
    (ref: GpuStringReplace, stringFunctions.scala).  Greedy
    left-to-right non-overlapping, like java String.replace."""

    child: Expression
    search: Expression  # literal, non-empty
    replacement: Expression  # literal

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    @property
    def name(self) -> str:
        return (f"replace({self.child.name}, {self.search.name}, "
                f"{self.replacement.name})")

    def check_supported(self) -> None:
        if not isinstance(self.search, Literal) \
                or not isinstance(self.replacement, Literal):
            raise TypeError("replace search/replacement must be literals")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        sb = _needle_bytes(self.search)
        rb = _needle_bytes(self.replacement)
        m, r = len(sb), len(rb)
        if m == 0 or m > c.width:
            return c  # Spark: empty search returns the input unchanged
        reals = _greedy_matches(_match_starts(c, sb), m)
        # covered = bytes inside a real match
        covered = jnp.zeros_like(reals)
        for k in range(m):
            covered = covered | jnp.pad(
                reals[:, : c.width - k], ((0, 0), (k, 0)))
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        in_len = pos < c.lengths[:, None]
        contrib = jnp.where(reals, r,
                            jnp.where(covered | ~in_len, 0, 1))
        out_end = jnp.cumsum(contrib, axis=1)
        out_start = out_end - contrib
        new_len = out_end[:, -1].astype(jnp.int32)
        # worst-case output width
        W_out = pad_width(max(1, (c.width // m) * max(r, m)
                              + (c.width % m)))
        n = c.capacity
        flat = jnp.zeros((n * W_out,), jnp.uint8)
        row_base = jnp.arange(n, dtype=jnp.int32)[:, None] * W_out
        # plain bytes
        plain = in_len & ~covered
        idx = jnp.where(plain, row_base + out_start, n * W_out)
        flat = flat.at[idx.reshape(-1)].set(
            c.chars.reshape(-1), mode="drop")
        # replacement bytes at real match starts
        for k in range(r):
            idx = jnp.where(reals, row_base + out_start + k, n * W_out)
            flat = flat.at[idx.reshape(-1)].set(
                jnp.full((n * c.width,), rb[k], jnp.uint8), mode="drop")
        chars = flat.reshape(n, W_out)
        opos = jnp.arange(W_out, dtype=jnp.int32)[None, :]
        chars = jnp.where(opos < new_len[:, None], chars, 0)
        return StringColumn(chars, new_len, c.validity)


@dataclasses.dataclass(repr=False)
class RegExpReplace(StringReplace):
    """regexp_replace restricted to patterns that are plain strings —
    the reference's policy (ref: GpuOverrides.scala:440-473
    canRegexpBeTreatedLikeARegularString + GpuStringReplace reuse);
    real regular expressions fall back to the CPU engine."""

    _META = set("\\^$.|?*+()[]{}")

    def check_supported(self) -> None:
        super().check_supported()
        pat = self.search.value  # type: ignore[union-attr]
        if pat is None or any(ch in self._META for ch in pat):
            raise TypeError(
                f"regexp pattern {pat!r} is a real regular expression; "
                "TPU runs only plain-string patterns (CPU fallback)")
        rep = self.replacement.value  # type: ignore[union-attr]
        if rep is not None and ("$" in rep or "\\" in rep):
            raise TypeError(
                "regexp replacement with backrefs is not supported")


@dataclasses.dataclass(repr=False)
class StringLPad(Expression):
    """lpad(str, len, pad) with literal len/pad (ref: GpuStringLPad).
    Character-based length, like Spark."""

    child: Expression
    length: Expression  # literal int
    pad: Expression  # literal string

    _left = True

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def check_supported(self) -> None:
        if not isinstance(self.length, Literal) \
                or not isinstance(self.pad, Literal):
            raise TypeError("pad length/fill must be literals")
        pb = (self.pad.value or "")
        if any(ord(ch) > 127 for ch in pb):
            raise TypeError("non-ASCII pad strings not supported on TPU")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        target = int(self.length.value)  # type: ignore[union-attr]
        pb = _needle_bytes(self.pad)
        if target <= 0:
            z = jnp.zeros((c.capacity, c.width), jnp.uint8)
            return StringColumn(z, jnp.zeros(c.capacity, jnp.int32),
                                c.validity)
        # character-count semantics: compute char length; byte targets
        # only coincide for ASCII, so refuse non-ASCII rows? Spark pads
        # by characters; with ASCII pad bytes the padded prefix/suffix is
        # ASCII, and we count the child's characters explicitly.
        nchars = char_length(c)
        if pb:
            npad = jnp.maximum(target - nchars, 0)  # chars==bytes for pad
        else:
            # empty pad: Spark returns the (truncated) input unpadded
            npad = jnp.zeros_like(nchars)
        W_out = pad_width(c.width + max(target, 0))
        pos = jnp.arange(W_out, dtype=jnp.int32)[None, :]
        padlen = max(len(pb), 1)
        pada = jnp.asarray(np.frombuffer(
            (pb * ((W_out // padlen) + 1))[:W_out], np.uint8)) \
            if pb else jnp.zeros((W_out,), jnp.uint8)
        if self._left:
            src = pos - npad[:, None]
            from_str = src >= 0
            gathered = jnp.take_along_axis(
                jnp.pad(c.chars, ((0, 0), (0, W_out - c.width))),
                jnp.clip(src, 0, W_out - 1), axis=1)
            chars = jnp.where(from_str, gathered, pada[None, :])
        else:
            in_str = pos < c.lengths[:, None]
            padsrc = pos - c.lengths[:, None]
            padbytes = jnp.take(
                pada, jnp.clip(padsrc, 0, W_out - 1))
            chars = jnp.where(
                in_str,
                jnp.pad(c.chars, ((0, 0), (0, W_out - c.width))),
                padbytes)
        # truncate to `target` characters (pad bytes are ASCII so the
        # full byte length is simply npad + string bytes)
        is_start = _is_char_start(chars)
        charidx = jnp.cumsum(is_start.astype(jnp.int32), axis=1)
        keep = charidx <= target
        byte_len_full = c.lengths + npad
        new_len = jnp.minimum(
            jnp.sum((keep & (pos < byte_len_full[:, None])).astype(
                jnp.int32), axis=1),
            byte_len_full)
        chars = jnp.where(pos < new_len[:, None], chars, 0)
        return StringColumn(chars, new_len.astype(jnp.int32), c.validity)


class StringRPad(StringLPad):
    _left = False


@dataclasses.dataclass(repr=False)
class StringLocate(Expression):
    """locate(substr, str, start) — 1-based character position, 0 when
    absent, literal substr/start (ref: GpuStringLocate)."""

    substr: Expression  # literal
    child: Expression
    start: Expression  # literal int, default 1

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    def check_supported(self) -> None:
        if not isinstance(self.substr, Literal) \
                or not isinstance(self.start, Literal):
            raise TypeError("locate substr/start must be literals")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        nb = _needle_bytes(self.substr)
        start = int(self.start.value)  # type: ignore[union-attr]
        valid = c.validity
        if start <= 0:
            # Spark: non-positive start returns 0
            return Column(jnp.zeros(c.capacity, jnp.int32), valid, T.INT)
        if len(nb) == 0:
            # Spark (java indexOf) semantics: min(pos, length + 1)
            nchars = char_length(c)
            out = jnp.minimum(jnp.int32(start), nchars + 1)
            return Column(out, valid, T.INT)
        starts = _match_starts(c, nb)
        is_cs = _is_char_start(c.chars)
        charpos = jnp.cumsum(is_cs.astype(jnp.int32), axis=1)  # 1-based
        cand = starts & (charpos >= start)
        pos_or_big = jnp.where(cand, charpos, jnp.int32(2**30))
        best = jnp.min(pos_or_big, axis=1)
        out = jnp.where(best < 2**30, best, 0).astype(jnp.int32)
        return Column(out, valid, T.INT)


@dataclasses.dataclass(repr=False)
class SubstringIndex(Expression):
    """substring_index(str, delim, count), literal delim/count
    (ref: GpuSubstringIndex)."""

    child: Expression
    delim: Expression  # literal, non-empty
    count: Expression  # literal int

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def check_supported(self) -> None:
        if not isinstance(self.delim, Literal) \
                or not isinstance(self.count, Literal):
            raise TypeError("substring_index delim/count must be literals")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        db = _needle_bytes(self.delim)
        count = int(self.count.value)  # type: ignore[union-attr]
        m = len(db)
        if count == 0 or m == 0:
            z = jnp.zeros((c.capacity, c.width), jnp.uint8)
            return StringColumn(z, jnp.zeros(c.capacity, jnp.int32),
                                c.validity)
        reals = _greedy_matches(_match_starts(c, db), m)
        occ = jnp.cumsum(reals.astype(jnp.int32), axis=1)
        total = occ[:, -1]
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        if count > 0:
            # prefix ending before the count-th delimiter
            cut = jnp.where(reals & (occ == count), pos, jnp.int32(2**30))
            first_cut = jnp.min(cut, axis=1)
            new_len = jnp.minimum(c.lengths,
                                  jnp.minimum(first_cut, c.lengths))
            chars = jnp.where(pos < new_len[:, None], c.chars, 0)
            return StringColumn(chars, new_len.astype(jnp.int32),
                                c.validity)
        # count < 0: suffix after the |count|-th delimiter from the right
        want = total + count  # 0-based index of the delimiter BEFORE out
        start_at = jnp.where(reals & (occ == (want + 1)[:, None]),
                             pos + m, jnp.int32(-1))
        start_byte = jnp.max(start_at, axis=1)
        take_all = want < 0
        start_byte = jnp.where(take_all, 0, jnp.maximum(start_byte, 0))
        new_len = (c.lengths - start_byte).astype(jnp.int32)
        src = pos + start_byte[:, None]
        chars = jnp.take_along_axis(
            c.chars, jnp.clip(src, 0, c.width - 1), axis=1)
        chars = jnp.where(pos < new_len[:, None], chars, 0)
        return StringColumn(chars, new_len, c.validity)


@dataclasses.dataclass(repr=False)
class InitCap(Expression):
    """initcap: first character of each space-separated word uppercased,
    the rest lowercased (ref: GpuInitCap; same byte-length-preserving
    mapping caveat as Upper/Lower)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        up = jnp.asarray(_case_table(True))
        lo = jnp.asarray(_case_table(False))
        cp, start = _decode_codepoints(c.chars)
        prev_byte = jnp.pad(c.chars[:, :-1], ((0, 0), (1, 0)))
        word_start = start & (
            (jnp.arange(c.width, dtype=jnp.int32)[None, :] == 0)
            | (prev_byte == 0x20))
        safe_cp = jnp.clip(cp, 0, 0xFFFF)
        mapped_up = jnp.take(up, safe_cp)
        mapped_lo = jnp.take(lo, safe_cp)
        mapped = jnp.where(word_start, mapped_up, mapped_lo)
        mapped = jnp.where((cp >= 0) & (cp < 0x10000), mapped, cp)
        chars = _encode_inplace(c.chars, mapped, start)
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        chars = jnp.where(pos < c.lengths[:, None], chars, 0)
        return StringColumn(chars, c.lengths, c.validity)


@dataclasses.dataclass(repr=False)
class ConcatWs(Expression):
    """concat_ws(sep, s1, s2, ...): literal separator, skips NULL inputs
    and never returns NULL itself (ref: GpuConcatWs semantics in
    stringFunctions.scala — note the difference from concat)."""

    sep: Expression  # literal
    exprs: tuple[Expression, ...]

    def __init__(self, sep: Expression, *exprs: Expression):
        self.sep = sep
        self.exprs = tuple(exprs)

    def with_children(self, children):
        children = list(children)
        return ConcatWs(children[0], *children[1:])

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    @property
    def nullable(self) -> bool:
        return self.sep.nullable

    @property
    def name(self) -> str:
        return "concat_ws(" + ", ".join(
            e.name for e in (self.sep,) + self.exprs) + ")"

    def check_supported(self) -> None:
        if not isinstance(self.sep, Literal):
            raise TypeError("concat_ws separator must be a literal")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        sep = _needle_bytes(self.sep)
        slen = len(sep)
        cols = [e.eval(ctx) for e in self.exprs]
        n = ctx.batch.capacity if not cols else cols[0].capacity
        total_w = pad_width(
            max(1, sum(c.width for c in cols) + slen * max(
                len(cols) - 1, 0)))
        out_pos = jnp.arange(total_w, dtype=jnp.int32)[None, :]
        chars = jnp.zeros((n, total_w), jnp.uint8)
        offset = jnp.zeros((n,), jnp.int32)
        any_prev = jnp.zeros((n,), bool)
        sepa = jnp.asarray(np.frombuffer(sep, np.uint8)) if slen \
            else jnp.zeros((0,), jnp.uint8)
        for c in cols:
            present = c.validity
            # separator before this part when a part already exists
            sep_here = present & any_prev
            if slen:
                for k in range(slen):
                    at = offset + k
                    put = sep_here[:, None] & (out_pos == at[:, None])
                    chars = jnp.where(put, sepa[k], chars)
                offset = offset + jnp.where(sep_here, slen, 0)
            src_idx = out_pos - offset[:, None]
            in_src = present[:, None] & (src_idx >= 0) \
                & (src_idx < c.lengths[:, None])
            gathered = jnp.take_along_axis(
                c.chars, jnp.clip(src_idx, 0, c.width - 1), axis=1)
            chars = jnp.where(in_src, gathered, chars)
            offset = offset + jnp.where(present, c.lengths, 0)
            any_prev = any_prev | present
        if isinstance(self.sep, Literal) and self.sep.value is None:
            valid = jnp.zeros((n,), bool)
        else:
            valid = jnp.ones((n,), bool)
        return StringColumn(chars, offset, valid & ctx.row_mask)


@dataclasses.dataclass(repr=False)
class StringSplit(Expression):
    """split(str, pattern[, limit]) (ref: GpuStringSplit,
    stringFunctions.scala) restricted to regex-free literal delimiters
    (the canRegexpBeTreatedLikeARegularString policy,
    GpuOverrides.scala:440-473).

    A bare split produces array<string>, which has no dense device
    layout — the planner rewrites the dominant `split(s, d)[i]` form
    (GetArrayItem over the split) into the device SplitPart kernel;
    other uses run on the CPU engine."""

    child: Expression
    delim: Expression  # Literal, plain string
    limit: int = -1

    _META = set("\\^$.|?*+()[]{}")

    @property
    def dtype(self) -> T.DataType:
        return T.ListType(T.STRING)

    @property
    def name(self) -> str:
        return f"split({self.child.name}, {self.delim.name})"

    @property
    def children(self) -> tuple:
        return (self.child, self.delim)

    def with_children(self, children):
        return StringSplit(children[0], children[1], self.limit)

    def check_supported(self) -> None:
        if not isinstance(self.delim, Literal) or not self.delim.value:
            raise TypeError("split delimiter must be a non-empty literal")
        if any(ch in self._META for ch in self.delim.value):
            raise TypeError(
                f"split pattern {self.delim.value!r} is a real regular "
                "expression; TPU runs only plain-string delimiters")
        if self.limit != -1:
            raise TypeError("split with an explicit limit falls back")
        raise TypeError(
            "bare split() produces array<string> (no dense device "
            "layout); only the split(s, d)[i] form runs on device — "
            "CPU fallback")

    def eval(self, ctx: EvalContext):
        raise AssertionError("rewritten by the planner or CPU-run")


@dataclasses.dataclass(repr=False)
class SplitPart(Expression):
    """split(str, delim)[idx] as one device kernel: the idx-th
    delimiter-separated piece, NULL when idx is out of range (Spark
    GetArrayItem semantics over GpuStringSplit's output; Java
    split(_, -1) keeps trailing empty pieces)."""

    child: Expression
    delim: Expression  # Literal, plain string, non-empty
    index: int         # 0-based

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    @property
    def name(self) -> str:
        return f"split({self.child.name}, {self.delim.name})[{self.index}]"

    @property
    def children(self) -> tuple:
        return (self.child, self.delim)

    def with_children(self, children):
        return SplitPart(children[0], children[1], self.index)

    def check_supported(self) -> None:
        if not isinstance(self.delim, Literal) or not self.delim.value:
            raise TypeError("split delimiter must be a non-empty literal")
        if any(ch in StringSplit._META for ch in self.delim.value):
            raise TypeError("regex delimiters fall back")
        if self.index < 0:
            raise TypeError("negative split index falls back")

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        db = _needle_bytes(self.delim)
        m = len(db)
        k = self.index
        if m > c.width:
            # delimiter longer than any value: piece 0 = whole string
            if k == 0:
                return c
            return StringColumn(
                jnp.zeros((c.capacity, c.width), jnp.uint8),
                jnp.zeros(c.capacity, jnp.int32),
                jnp.zeros(c.capacity, bool))
        reals = _greedy_matches(_match_starts(c, db), m)
        occ = jnp.cumsum(reals.astype(jnp.int32), axis=1)
        total = occ[:, -1]  # delimiter count -> total+1 pieces
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        # start: 0 for piece 0, else one past the k-th delimiter's end
        if k == 0:
            start = jnp.zeros(c.capacity, jnp.int32)
        else:
            s = jnp.where(reals & (occ == k), pos + m, jnp.int32(-1))
            start = jnp.max(s, axis=1).astype(jnp.int32)
        # end: position of the (k+1)-th delimiter, else the length
        e = jnp.where(reals & (occ == k + 1), pos, jnp.int32(2**30))
        end = jnp.minimum(jnp.min(e, axis=1),
                          c.lengths).astype(jnp.int32)
        in_range = (jnp.int32(k) <= total) & (start >= 0)
        start = jnp.maximum(start, 0)
        new_len = jnp.maximum(end - start, 0)
        src = pos + start[:, None]
        chars = jnp.take_along_axis(
            c.chars, jnp.clip(src, 0, c.width - 1), axis=1)
        chars = jnp.where(pos < new_len[:, None], chars, 0).astype(
            jnp.uint8)
        return StringColumn(chars, new_len, c.validity & in_range)


@dataclasses.dataclass(repr=False)
class GetJsonObject(Expression):
    """get_json_object(json, path) with a literal path (ref:
    GpuGetJsonObject.scala — the reference drives a native cudf JSON
    kernel; here JSON-path evaluation runs on the CPU engine, declared
    via check_supported so the planner routes the subtree there).
    Path grammar: $ root, .field / ['field'] access, [n] array index."""

    child: Expression
    path: Expression  # Literal string

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    @property
    def name(self) -> str:
        return f"get_json_object({self.child.name}, {self.path.name})"

    def check_supported(self) -> None:
        if not isinstance(self.path, Literal) or not self.path.value:
            raise TypeError("get_json_object path must be a literal")
        raise TypeError(
            "get_json_object evaluates JSON paths on the CPU engine "
            "(no device JSON kernel yet)")

    def eval(self, ctx: EvalContext):
        raise AssertionError("CPU-engine only")

    @staticmethod
    def parse_path(path: str):
        """'$.a.b[2]' -> ['a', 'b', 2]; None on malformed paths
        (Spark returns NULL for every row then)."""
        import re

        if not path.startswith("$"):
            return None
        steps = []
        rest = path[1:]
        token = re.compile(
            r"\.(\w+)|\[(\d+)\]|\['([^']*)'\]|\[\"([^\"]*)\"\]")
        pos = 0
        while pos < len(rest):
            m = token.match(rest, pos)
            if m is None:
                return None
            field, idx, q1, q2 = m.groups()
            if idx is not None:
                steps.append(int(idx))
            else:
                steps.append(field if field is not None
                             else (q1 if q1 is not None else q2))
            pos = m.end()
        return steps
