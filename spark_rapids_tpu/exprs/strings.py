"""String expressions over fixed-width byte matrices.

TPU counterparts of stringFunctions.scala (976 LoC).  cudf walks ragged
offset+chars buffers; here every op is a dense (rows, width) vectorized
program:

- char-indexed ops (length, substring) derive a per-byte *character
  index* from UTF-8 start-byte detection (one cumsum);
- byte re-layout ops (substring, concat, trim, pad) build output via
  take_along_axis index arithmetic or a stable per-row argsort on a
  drop flag — the row-local analog of the batch compaction trick;
- case mapping decodes UTF-8 to codepoints and maps through a BMP
  lookup table (built once from Python's casing rules).  Codepoints
  whose case-mapped UTF-8 byte length differs (e.g. 'ß' -> 'SS') map to
  themselves — a documented divergence, mirroring the reference's
  unicode caveats (docs/compatibility.md "unicode case-change edge
  cases"; the reference ships an incompatibleOps flag for the same
  reason).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    AnyColumn,
    Column,
    StringColumn,
    pad_width,
)
from spark_rapids_tpu.exprs.base import (
    EvalContext,
    Expression,
    Literal,
    broadcast_validity,
)


def _is_char_start(chars: jax.Array) -> jax.Array:
    """True for bytes that start a UTF-8 character (not 0b10xxxxxx)."""
    return (chars & 0xC0) != 0x80


def char_length(col: StringColumn) -> jax.Array:
    pos = jnp.arange(col.width, dtype=jnp.int32)[None, :]
    in_str = pos < col.lengths[:, None]
    return jnp.sum((_is_char_start(col.chars) & in_str).astype(jnp.int32),
                   axis=1)


@dataclasses.dataclass(repr=False)
class Length(Expression):
    """character_length (ref: GpuLength — char count, not bytes)."""

    child: Expression

    @property
    def dtype(self) -> T.DataType:
        return T.INT

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        return Column(char_length(c), c.validity, T.INT)


# ---------------------------------------------------------------------- #
# Case mapping
# ---------------------------------------------------------------------- #

@lru_cache(maxsize=2)
def _case_table(upper: bool) -> np.ndarray:
    """BMP codepoint -> cased codepoint, restricted to mappings that
    preserve UTF-8 byte length (others map to themselves)."""
    tbl = np.arange(0x10000, dtype=np.int32)
    for cp in range(0x10000):
        if 0xD800 <= cp <= 0xDFFF:  # surrogates are not characters
            continue
        ch = chr(cp)
        m = ch.upper() if upper else ch.lower()
        if len(m) == 1 and ord(m) < 0x10000:
            if len(m.encode("utf-8")) == len(ch.encode("utf-8")):
                tbl[cp] = ord(m)
    return tbl


def _decode_codepoints(chars: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-byte (codepoint_of_its_char, is_start).  3-byte max (BMP);
    4-byte sequences pass through unmapped."""
    c = chars.astype(jnp.int32)
    start = _is_char_start(chars)
    b0 = c
    b1 = jnp.pad(c[:, 1:], ((0, 0), (0, 1)))
    b2 = jnp.pad(c[:, 2:], ((0, 0), (0, 2)))
    cp1 = b0
    cp2 = ((b0 & 0x1F) << 6) | (b1 & 0x3F)
    cp3 = ((b0 & 0x0F) << 12) | ((b1 & 0x3F) << 6) | (b2 & 0x3F)
    cp = jnp.where(b0 < 0x80, cp1,
                   jnp.where(b0 < 0xE0, cp2,
                             jnp.where(b0 < 0xF0, cp3, -1)))
    return jnp.where(start, cp, -1), start


def _encode_inplace(chars: jax.Array, mapped_cp: jax.Array,
                    start: jax.Array) -> jax.Array:
    """Re-encode mapped codepoints over the same byte layout (same-length
    mappings only, enforced by the table)."""
    c = chars.astype(jnp.int32)
    one = (mapped_cp >= 0) & (mapped_cp < 0x80) & start
    two = (mapped_cp >= 0x80) & (mapped_cp < 0x800) & start
    three = (mapped_cp >= 0x800) & start
    out = c
    out = jnp.where(one, mapped_cp, out)
    out = jnp.where(two, 0xC0 | (mapped_cp >> 6), out)
    out = jnp.where(three, 0xE0 | (mapped_cp >> 12), out)
    # continuation bytes: recompute from *this* char's codepoint.  Chars
    # with no mapping (4-byte sequences, cp == -1) carry the -2 marker so
    # their continuation bytes pass through untouched — a plain
    # last-valid-value scan would leak the previous char's codepoint
    # into them and corrupt the UTF-8
    tag = jnp.where(start,
                    jnp.where(mapped_cp >= 0, mapped_cp, -2), -3)
    cp_here = jax.lax.associative_scan(
        lambda a, b: jnp.where(b != -3, b, a), tag, axis=1)
    pos = jnp.arange(chars.shape[1], dtype=jnp.int32)[None, :]
    start_pos = jax.lax.associative_scan(
        jnp.maximum, jnp.where(start, pos, -1), axis=1)
    off = pos - start_pos
    cont1 = (~start) & (off == 1)
    cont2 = (~start) & (off == 2)
    is3 = cp_here >= 0x800
    out = jnp.where(cont1 & is3, 0x80 | ((cp_here >> 6) & 0x3F), out)
    out = jnp.where(cont1 & ~is3 & (cp_here >= 0x80),
                    0x80 | (cp_here & 0x3F), out)
    out = jnp.where(cont2 & is3, 0x80 | (cp_here & 0x3F), out)
    return out.astype(jnp.uint8)


@dataclasses.dataclass(repr=False)
class Upper(Expression):
    child: Expression

    _upper = True

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        tbl = jnp.asarray(_case_table(self._upper))
        cp, start = _decode_codepoints(c.chars)
        safe_cp = jnp.clip(cp, 0, 0xFFFF)
        mapped = jnp.where((cp >= 0) & (cp < 0x10000),
                           jnp.take(tbl, safe_cp), cp)
        chars = _encode_inplace(c.chars, mapped, start)
        # zero out padding bytes again
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        chars = jnp.where(pos < c.lengths[:, None], chars, 0)
        return StringColumn(chars, c.lengths, c.validity)


class Lower(Upper):
    _upper = False


# ---------------------------------------------------------------------- #
# Search (literal needles, like the reference's lit-only TypeSigs)
# ---------------------------------------------------------------------- #

def _needle_bytes(e: Expression) -> bytes:
    assert isinstance(e, Literal), "needle must be a literal"
    return (e.value or "").encode("utf-8")


@dataclasses.dataclass(repr=False)
class StartsWith(Expression):
    left: Expression
    right: Expression  # literal

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def _match(self, c: StringColumn, nb: bytes) -> jax.Array:
        m = len(nb)
        if m == 0:
            return jnp.ones((c.capacity,), bool)
        if m > c.width:
            return jnp.zeros((c.capacity,), bool)
        needle = jnp.asarray(np.frombuffer(nb, np.uint8))
        return (c.lengths >= m) & jnp.all(
            c.chars[:, :m] == needle[None, :], axis=1)

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.left.eval(ctx)
        r = self.right.eval(ctx)
        out = self._match(c, _needle_bytes(self.right))
        return Column(out, broadcast_validity(c, r), T.BOOLEAN)


class EndsWith(StartsWith):
    def _match(self, c: StringColumn, nb: bytes) -> jax.Array:
        m = len(nb)
        if m == 0:
            return jnp.ones((c.capacity,), bool)
        if m > c.width:
            return jnp.zeros((c.capacity,), bool)
        needle = jnp.asarray(np.frombuffer(nb, np.uint8))
        # gather the last m bytes of each row
        start = jnp.maximum(c.lengths - m, 0)
        idx = start[:, None] + jnp.arange(m, dtype=jnp.int32)[None, :]
        tail = jnp.take_along_axis(
            c.chars, jnp.clip(idx, 0, c.width - 1), axis=1)
        return (c.lengths >= m) & jnp.all(tail == needle[None, :], axis=1)


class Contains(StartsWith):
    def _match(self, c: StringColumn, nb: bytes) -> jax.Array:
        m = len(nb)
        if m == 0:
            return jnp.ones((c.capacity,), bool)
        if m > c.width:
            return jnp.zeros((c.capacity,), bool)
        needle = jnp.asarray(np.frombuffer(nb, np.uint8))
        # compare all windows (W - m + 1 shifted equality tests, fused)
        hit = jnp.zeros((c.capacity,), bool)
        for off in range(c.width - m + 1):
            w = c.chars[:, off:off + m]
            hit = hit | ((c.lengths >= off + m)
                         & jnp.all(w == needle[None, :], axis=1))
        return hit


@dataclasses.dataclass(repr=False)
class Like(Expression):
    """SQL LIKE for simple patterns (%x, x%, %x%, exact, and
    'a%b' prefix+suffix).  Patterns with '_' or more embedded '%'s fail
    check_supported() and the planner falls back to the CPU engine's
    full match_like (the reference likewise refuses regex-like patterns,
    GpuOverrides.scala:440-473)."""

    left: Expression
    pattern: str

    def check_supported(self) -> None:
        p = self.pattern
        if "_" in p:
            raise TypeError("LIKE with '_' not supported on TPU")
        if "\\" in p:
            raise TypeError("LIKE with escapes not supported on TPU")
        inner = p.strip("%")
        if "%" in inner and len(inner.split("%")) != 2:
            raise TypeError(f"LIKE pattern {p!r} not supported on TPU")

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    def eval(self, ctx: EvalContext) -> AnyColumn:
        self.check_supported()
        c = self.left.eval(ctx)
        assert isinstance(c, StringColumn)
        p = self.pattern
        lead = p.startswith("%")
        trail = p.endswith("%")
        inner = p.strip("%")
        lit_ = Literal.of(inner, T.STRING)
        if "%" in inner:  # 'a%b': prefix + suffix, lengths must fit
            pre, suf = inner.split("%")
            m1 = StartsWith(self.left, Literal.of(pre, T.STRING))._match(
                c, pre.encode())
            m2 = EndsWith(self.left, Literal.of(suf, T.STRING))._match(
                c, suf.encode())
            fit = c.lengths >= len(pre.encode()) + len(suf.encode())
            out = m1 & m2 & fit
        elif lead and trail:
            out = Contains(self.left, lit_)._match(c, inner.encode())
        elif trail:
            out = StartsWith(self.left, lit_)._match(c, inner.encode())
        elif lead:
            out = EndsWith(self.left, lit_)._match(c, inner.encode())
        else:
            nb = inner.encode()
            out = StartsWith(self.left, lit_)._match(c, nb) & (
                c.lengths == len(nb))
        return Column(out, c.validity, T.BOOLEAN)


# ---------------------------------------------------------------------- #
# Re-layout ops
# ---------------------------------------------------------------------- #

def _compact_rows(chars: jax.Array, keep: jax.Array
                  ) -> tuple[jax.Array, jax.Array]:
    """Left-pack kept bytes within each row (stable), zero the rest."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    packed = jnp.take_along_axis(chars, order, axis=1)
    new_len = jnp.sum(keep.astype(jnp.int32), axis=1)
    pos = jnp.arange(chars.shape[1], dtype=jnp.int32)[None, :]
    packed = jnp.where(pos < new_len[:, None], packed, 0)
    return packed, new_len


@dataclasses.dataclass(repr=False)
class Substring(Expression):
    """substring(str, pos, len) — 1-based, char-indexed, negative pos
    from the end (ref: GpuSubstring)."""

    child: Expression
    pos: int
    length: Optional[int] = None

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        nchars = char_length(c)
        pos = self.pos
        # Spark substringSQL: the length window counts from the
        # *unclamped* start (substring('abc', -5, 3) == 'a')
        if pos > 0:
            start = jnp.full_like(nchars, pos - 1)
        elif pos == 0:
            start = jnp.zeros_like(nchars)
        else:
            start = nchars + pos
        if self.length is None:
            end = nchars
        else:
            end = start + max(self.length, 0)
        start = jnp.maximum(start, 0)
        bpos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        in_str = bpos < c.lengths[:, None]
        char_idx = jnp.cumsum(
            (_is_char_start(c.chars) & in_str).astype(jnp.int32),
            axis=1) - 1
        keep = in_str & (char_idx >= start[:, None]) & \
            (char_idx < end[:, None])
        chars, lengths = _compact_rows(c.chars, keep)
        return StringColumn(chars, lengths, c.validity)


@dataclasses.dataclass(repr=False)
class StringTrim(Expression):
    """trim(str): strip leading+trailing spaces (0x20, Spark default)."""

    child: Expression

    _lead = True
    _trail = True

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        c = self.child.eval(ctx)
        assert isinstance(c, StringColumn)
        pos = jnp.arange(c.width, dtype=jnp.int32)[None, :]
        in_str = pos < c.lengths[:, None]
        sp = (c.chars == 32) & in_str
        keep = in_str
        if self._lead:
            lead_run = jnp.cumprod(sp.astype(jnp.int32), axis=1)
            keep = keep & (lead_run == 0)
        if self._trail:
            rev = (sp | ~in_str)[:, ::-1]
            trail_run = jnp.cumprod(rev.astype(jnp.int32), axis=1)[:, ::-1]
            keep = keep & (trail_run == 0)
        chars, lengths = _compact_rows(c.chars, keep)
        return StringColumn(chars, lengths, c.validity)


class StringTrimLeft(StringTrim):
    _trail = False


class StringTrimRight(StringTrim):
    _lead = False


@dataclasses.dataclass(repr=False)
class Concat(Expression):
    """concat(s1, s2, ...): NULL if any input NULL (Spark concat)."""

    exprs: tuple[Expression, ...]

    def __init__(self, *exprs: Expression):
        self.exprs = tuple(exprs)

    def with_children(self, children):
        return Concat(*children)

    @property
    def dtype(self) -> T.DataType:
        return T.STRING

    def eval(self, ctx: EvalContext) -> AnyColumn:
        cols = [e.eval(ctx) for e in self.exprs]
        total_w = pad_width(sum(c.width for c in cols))
        n = cols[0].capacity
        out_pos = jnp.arange(total_w, dtype=jnp.int32)[None, :]
        chars = jnp.zeros((n, total_w), jnp.uint8)
        offset = jnp.zeros((n,), jnp.int32)
        valid = None
        for c in cols:
            src_idx = out_pos - offset[:, None]
            in_src = (src_idx >= 0) & (src_idx < c.lengths[:, None])
            gathered = jnp.take_along_axis(
                c.chars, jnp.clip(src_idx, 0, c.width - 1), axis=1)
            chars = jnp.where(in_src, gathered, chars)
            offset = offset + c.lengths
            valid = c.validity if valid is None else (valid & c.validity)
        return StringColumn(chars, offset, valid)
