"""Low-level event-log reading: JSONL (plain or gzip) -> records.

Forward-compat contract (see eventlog/schema.py): unknown fields are
preserved verbatim, records of unknown TYPE are skipped (a newer
writer may add record types), and a corrupt trailing line — a crash
mid-write — is dropped rather than failing the whole load.  Strict
schema validation is opt-in (the golden tests use it); operational
readers (tools/history) load permissively.
"""

from __future__ import annotations

import json
from typing import Iterator, Optional

from spark_rapids_tpu.eventlog.schema import (
    RECORD_TYPES,
    SchemaError,
    validate_record,
)


def _gunzip_prefix(raw: bytes) -> str:
    """Decode a sequence of gzip members, keeping everything that
    decompresses cleanly.  zlib's incremental decompressor RETURNS the
    partial output of a truncated member (GzipFile.read would raise
    EOFError and discard it), so a process killed mid-append costs at
    most the torn trailing line, never the whole final member."""
    import zlib

    out = bytearray()
    pos = 0
    while pos < len(raw):
        d = zlib.decompressobj(wbits=31)  # gzip-wrapped member
        try:
            out += d.decompress(raw[pos:])
            out += d.flush()
        except zlib.error:
            break  # corrupt member: keep the decoded prefix
        if not d.eof or not d.unused_data:
            break  # truncated final member / end of file
        pos = len(raw) - len(d.unused_data)
    return out.decode("utf-8", errors="replace")


def _read_lines(path: str) -> list[str]:
    """Whole-file read with crash tolerance: a truncated compressed
    tail yields its decoded prefix (the partial trailing line, if any,
    is then handled like a plain torn tail)."""
    if path.endswith(".gz"):
        with open(path, "rb") as f:
            return _gunzip_prefix(f.read()).splitlines()
    with open(path, "r", encoding="utf-8") as f:
        return f.read().splitlines()


def iter_records(path: str, strict: bool = False,
                 errors: Optional[list] = None) -> Iterator[dict]:
    """Yield decoded records from one event-log file.

    - unknown record types are skipped (forward compat);
    - an undecodable line is dropped (appended to `errors` when given)
      unless `strict`, where it raises — ONLY a final partial line is
      ever tolerated silently (crash-mid-write);
    - with `strict`, every record must validate against the schema.
    """
    lines = _read_lines(path)
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            if i == len(lines) - 1 and not strict:
                continue  # torn trailing write
            if strict:
                raise SchemaError(
                    f"{path}:{i + 1}: undecodable record: {exc}")
            if errors is not None:
                errors.append(f"{path}:{i + 1}: {exc}")
            continue
        if strict:
            validate_record(rec)
        elif not isinstance(rec, dict) \
                or rec.get("type") not in RECORD_TYPES:
            continue  # unknown record type: a newer writer's extension
        yield rec


def read_log(path: str, strict: bool = False
             ) -> tuple[Optional[dict], list[dict]]:
    """(header, query_records) for one log file."""
    header, queries, _telemetry, _slo = read_log_all(path,
                                                     strict=strict)
    return header, queries


def read_log_all(path: str, strict: bool = False
                 ) -> tuple[Optional[dict], list[dict], list[dict],
                            list[dict]]:
    """(header, query_records, telemetry_records, slo_records) for one
    log file — the full surface tools/history loads (telemetry records
    are the live sampler's gauge samples, trace/telemetry.py; slo
    records are the watchdog's budget breaches, obs/slo.py)."""
    header = None
    queries: list[dict] = []
    telemetry: list[dict] = []
    slo: list[dict] = []
    for rec in iter_records(path, strict=strict):
        if rec.get("type") == "header":
            header = rec
        elif rec.get("type") == "query":
            queries.append(rec)
        elif rec.get("type") == "telemetry":
            telemetry.append(rec)
        elif rec.get("type") == "slo":
            slo.append(rec)
    return header, queries, telemetry, slo
