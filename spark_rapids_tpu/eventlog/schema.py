"""Versioned record schema for the persistent event log.

The reference's tooling (ProfileMain / ApplicationInfo) works because
Spark's event-log format is a stable, versioned contract that readers
from a different release can still parse.  This module is that
contract for the TPU engine: every record the writer emits validates
against the field specs below, and the reader deliberately IGNORES
unknown fields so a newer engine's logs stay loadable by older tools
(forward compatibility is tested in tests/test_eventlog.py).

Rules of evolution:
- adding an OPTIONAL field: allowed within a schema version (readers
  must tolerate unknown fields);
- adding a REQUIRED field, renaming, or retyping: bump
  ``SCHEMA_VERSION`` and teach :func:`validate_record` both shapes.
"""

from __future__ import annotations

from typing import Any

#: bump on any backward-incompatible record-shape change (see module doc)
SCHEMA_VERSION = 1

#: record types the writer emits
RECORD_TYPES = ("header", "query", "telemetry", "slo")

#: required fields per record type: name -> allowed python types.
#: Anything NOT listed here is optional-by-construction; readers must
#: not choke on extras (the forward-compat contract).
REQUIRED_FIELDS: dict[str, dict[str, tuple]] = {
    "header": {
        "type": (str,),
        "schema_version": (int,),
        "ts": (int, float),
        "session": (str,),
        "pid": (int,),
        "env": (dict,),
        "conf": (dict,),
        "conf_hash": (str,),
    },
    "query": {
        "type": (str,),
        "schema_version": (int,),
        "query_id": (int,),
        "plan": (str,),
        "plan_hash": (str,),
        "engine": (str,),
        "wall_s": (int, float),
        "start_ts": (int, float),
        "end_ts": (int, float),
        "start_ns": (int,),
        "end_ns": (int,),
        "conf_hash": (str,),
        "counters": (dict,),
    },
    # one live-telemetry gauge sample (trace/telemetry.py): appended
    # by the sampler thread between query records; `counters` is the
    # flat sample_now() dict (store tiers, semaphore, admission queue,
    # pipeline occupancy)
    "telemetry": {
        "type": (str,),
        "schema_version": (int,),
        "ts": (int, float),
        "session": (str,),
        "counters": (dict,),
    },
    # one SLO breach (obs/slo.py): a tenant's rolling percentile went
    # over its spark.rapids.tpu.obs.slo.* budget — appended by the
    # watchdog thread; the HC016 health rule's input (tools/history)
    "slo": {
        "type": (str,),
        "schema_version": (int,),
        "ts": (int, float),
        "session": (str,),
        "tenant": (str,),
        "metric": (str,),
        "observed_ms": (int, float),
        "budget_ms": (int, float),
        "window": (int,),
    },
}

#: optional fields we still type-check WHEN present
OPTIONAL_FIELDS: dict[str, dict[str, tuple]] = {
    "header": {
        "mesh": (dict, type(None)),
    },
    "query": {
        "operators": (dict, type(None)),
        "spans": (dict, type(None)),
        "pipeline": (dict, type(None)),
        "faults": (dict, type(None)),
        "serving": (dict, type(None)),
        # cross-tenant work sharing (serving/work_share.py): the
        # result-cache verdict for this query plus its share.*
        # counter deltas — None when the sharing tier never engaged
        "sharing": (dict, type(None)),
        # wire-ingress provenance (connect/server.py): peer address,
        # request wire bytes and plan-translate ms — present only for
        # queries that arrived over the connect front door
        "connect": (dict, type(None)),
        # device-ledger attribution for this query (trace/ledger.py):
        # {"programs": {key: {...}}, "totals": {...}} — present only
        # when the ledger was enabled for the query
        "programs": (dict, type(None)),
        "result_digest": (str, type(None)),
        "trace_file": (str, type(None)),
        "rows": (int, type(None)),
    },
    "telemetry": {},
    "slo": {},
}


class SchemaError(ValueError):
    """An emitted/loaded record violates the versioned contract."""


def _check_operator_node(node: Any, where: str) -> None:
    if not isinstance(node, dict):
        raise SchemaError(f"{where}: operator node must be an object")
    if not isinstance(node.get("desc"), str):
        raise SchemaError(f"{where}: operator node missing 'desc'")
    if not isinstance(node.get("metrics"), dict):
        raise SchemaError(f"{where}: operator node missing 'metrics'")
    kids = node.get("children", [])
    if not isinstance(kids, list):
        raise SchemaError(f"{where}: operator children must be a list")
    for i, c in enumerate(kids):
        _check_operator_node(c, f"{where}.children[{i}]")


def validate_record(rec: Any) -> dict:
    """Validate one decoded JSONL record against the versioned schema;
    returns the record (for chaining).  Unknown EXTRA fields are
    explicitly allowed — only missing/mistyped required fields (and
    mistyped known-optional fields) raise :class:`SchemaError`."""
    if not isinstance(rec, dict):
        raise SchemaError("record must be a JSON object")
    rtype = rec.get("type")
    if rtype not in RECORD_TYPES:
        raise SchemaError(f"unknown record type {rtype!r}")
    ver = rec.get("schema_version")
    if not isinstance(ver, int) or ver < 1:
        raise SchemaError(f"bad schema_version {ver!r}")
    for name, types in REQUIRED_FIELDS[rtype].items():
        if name not in rec:
            raise SchemaError(f"{rtype} record missing required "
                              f"field {name!r}")
        if not isinstance(rec[name], types):
            raise SchemaError(
                f"{rtype}.{name}: expected {'/'.join(t.__name__ for t in types)}, "
                f"got {type(rec[name]).__name__}")
    for name, types in OPTIONAL_FIELDS[rtype].items():
        if name in rec and not isinstance(rec[name], types):
            raise SchemaError(
                f"{rtype}.{name}: expected {'/'.join(t.__name__ for t in types)}, "
                f"got {type(rec[name]).__name__}")
    if rtype == "query" and rec.get("operators") is not None:
        _check_operator_node(rec["operators"], "query.operators")
    return rec
