"""Persistent event log: the engine's signals, written to disk.

The reference ships a whole ops-tooling layer over PERSISTED event
logs: ``ProfileMain`` parses them into ``ApplicationInfo``, and
``CompareApplications`` / ``HealthCheck`` / ``GenerateDot`` operate on
that model (SURVEY §2.14).  This engine had rich in-process signals —
PR3 spans, settled operator metrics, speculation/runtime-filter/retry/
fault counters, jit-cache and spill accounting — but they all
evaporated at process exit.  This package persists them:

- an append-only JSONL log per session (optionally gzip), one
  ``header`` record (env/conf/mesh fingerprint) followed by one
  ``query`` record per TPU collect;
- each query record carries the annotated lowered plan (lint +
  runtime-filter sections), the settled per-operator metric tree,
  span-derived busy/self/overlap when tracing is on, the full counter
  surface as PER-QUERY deltas, a result digest, and a pointer to an
  optional sidecar Chrome-trace export;
- the reader/analysis layer (``ApplicationInfo``, ``compare``,
  ``health``, ``report``, ``dot``) lives in
  :mod:`spark_rapids_tpu.tools.history`.

Cost discipline: with ``spark.rapids.tpu.eventLog.enabled=false`` (the
default) a session holds ``_eventlog = None`` and the only per-query
cost is one attribute check in ``_collect_tpu`` — no writer thread
exists (enabled sessions piggyback on the QueryHistory snapshot
worker, which already settles the metrics the record needs), and
nothing touches the per-batch hot path either way.  Docs:
``docs/eventlog.md``.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import re
import threading
import time
from typing import Any, Optional

from spark_rapids_tpu.config import TpuConf, register

EVENTLOG_ENABLED = register(
    "spark.rapids.tpu.eventLog.enabled", False,
    "Persist one JSONL event-log record per collected query (header "
    "with env/conf/mesh fingerprint, then per-query plan + settled "
    "operator metrics + counter deltas), the input to "
    "`python -m spark_rapids_tpu.tools.history` "
    "(ref: spark.eventLog.enabled feeding the profiling tool's "
    "ApplicationInfo). Off by default: a disabled session performs "
    "one attribute check per query and starts no writer thread.")

EVENTLOG_DIR = register(
    "spark.rapids.tpu.eventLog.dir", "/tmp/spark_rapids_tpu_eventlog",
    "Directory for event-log files (one per session; ref: "
    "spark.eventLog.dir).")

EVENTLOG_COMPRESS = register(
    "spark.rapids.tpu.eventLog.compress", False,
    "Gzip the event-log file (ref: spark.eventLog.compress). Appended "
    "gzip members stay valid, so incremental per-query writes survive "
    "a crash mid-run.")

EVENTLOG_TRACE_SIDECAR = register(
    "spark.rapids.tpu.eventLog.traceSidecar", False,
    "When tracing is also enabled, export a per-query Chrome-trace "
    "JSON sidecar next to the event log and record its path in the "
    "query record (docs/observability.md).")

#: process-unique session-log discriminator (two sessions in one
#: process must not interleave into one file)
_SESSION_SEQ = itertools.count()

#: counter keys that are MONOTONIC cumulative process totals — the
#: writer records per-query deltas of exactly these
MONOTONIC_COUNTERS = (
    "jit.hits", "jit.misses", "jit.compiles",
    "persist.hits", "persist.misses", "persist.writes",
    "persist.evictions", "persist.errors",
    "persist.plan_hits", "persist.result_hits",
    "persist.fallback_compiles",
    "persist.deserialize_ms", "persist.serialize_ms",
    "retry.splits", "retry.spill_retries", "retry.task_retries",
    "retry.cpu_fallbacks",
    "faults.injected", "faults.recovered",
    "rf.filters_built", "rf.build_rows", "rf.build_ms",
    "rf.pruned_rows", "rf.row_groups_pruned",
    "speculation.hits", "speculation.overflows", "speculation.synced",
    "speculation.disabled",
    "placement.host_uploads", "placement.device_born",
    "placement.d2d_transfers",
    "pipeline.readbacks", "pipeline.async_readbacks", "pipeline.items",
    "spill.device_to_host_bytes", "spill.host_to_disk_bytes",
    "share.result_hits", "share.result_misses",
    "share.result_evictions", "share.result_invalidations",
    "share.scan_subscribes", "share.scan_units_shared",
    "share.scan_units_decoded", "share.scan_rows_decoded",
    "cancel.cancelled", "cancel.deadline_exceeded",
    "cancel.breaker_trips", "cancel.quarantined",
    "lock.acquisitions", "lock.contention_waits", "lock.cycles",
)


def counters_snapshot() -> dict[str, float]:
    """One flat snapshot of every process-global cumulative counter the
    engine exposes (the full counter surface the event log persists).
    Keys match :data:`MONOTONIC_COUNTERS` plus the two store GAUGES
    (``store.device_used`` / ``store.host_used``), which are recorded
    as-is rather than delta'd."""
    from spark_rapids_tpu.execs.jit_cache import cache_stats
    from spark_rapids_tpu.execs.retry import retry_stats
    from spark_rapids_tpu.memory import get_store
    from spark_rapids_tpu.parallel import speculation
    from spark_rapids_tpu.parallel.pipeline import stage_snapshot
    from spark_rapids_tpu.plan import runtime_filter
    from spark_rapids_tpu.robustness import faults

    out: dict[str, float] = {}
    jc = cache_stats()
    out["jit.hits"] = jc["hits"]
    out["jit.misses"] = jc["misses"]
    out["jit.compiles"] = jc["compiles"]
    from spark_rapids_tpu import persist as _persist

    ps = _persist.stats()
    out["persist.hits"] = ps["hits"]
    out["persist.misses"] = ps["misses"]
    out["persist.writes"] = ps["writes"]
    out["persist.evictions"] = ps["evictions"]
    out["persist.errors"] = ps["errors"]
    out["persist.plan_hits"] = ps["plan_hits"]
    out["persist.result_hits"] = ps["result_hits"]
    out["persist.fallback_compiles"] = ps["fallback_compiles"]
    out["persist.deserialize_ms"] = ps["deserialize_ms"]
    out["persist.serialize_ms"] = ps["serialize_ms"]
    # on-disk footprint GAUGE (0 without a dir walk when persistence
    # never activated in this process)
    out["persist_cache.bytes"] = _persist.cache_bytes()
    rs = retry_stats()
    out["retry.splits"] = rs["splits"]
    out["retry.spill_retries"] = rs["spill_retries"]
    out["retry.task_retries"] = rs["task_retries"]
    out["retry.cpu_fallbacks"] = rs["cpu_fallbacks"]
    out["faults.injected"] = faults.injected_total()
    out["faults.recovered"] = faults.recovered_total()
    rf = runtime_filter.stats()
    out["rf.filters_built"] = rf["filters_built"]
    out["rf.build_rows"] = rf["build_rows"]
    out["rf.build_ms"] = round(rf["build_ms"], 3)
    out["rf.pruned_rows"] = rf["pruned_rows"]
    out["rf.row_groups_pruned"] = rf["row_groups_pruned"]
    sp = speculation.stats()
    out["speculation.hits"] = sum(s["hits"] for s in sp.values())
    out["speculation.overflows"] = sum(
        s["overflows"] for s in sp.values())
    out["speculation.synced"] = sum(s["synced"] for s in sp.values())
    out["speculation.disabled"] = speculation.disabled_total()
    from spark_rapids_tpu.parallel import placement as _placement

    pl = _placement.stats()
    out["placement.host_uploads"] = pl["host_uploads"]
    out["placement.device_born"] = pl["device_born"]
    out["placement.d2d_transfers"] = pl["d2d_transfers"]
    st = stage_snapshot()
    out["pipeline.readbacks"] = sum(s["readbacks"] for s in st.values())
    out["pipeline.async_readbacks"] = sum(
        s["async_readbacks"] for s in st.values())
    out["pipeline.items"] = sum(s["items"] for s in st.values())
    ss = get_store().spill_stats()
    out["spill.device_to_host_bytes"] = ss["spilled_device_to_host"]
    out["spill.host_to_disk_bytes"] = ss["spilled_host_to_disk"]
    out["store.device_used"] = ss["device_used"]
    out["store.host_used"] = ss["host_used"]
    from spark_rapids_tpu.serving import work_share

    ws = work_share.stats()
    out["share.result_hits"] = ws["result_hits"]
    out["share.result_misses"] = ws["result_misses"]
    out["share.result_evictions"] = ws["result_evictions"]
    out["share.result_invalidations"] = ws["result_invalidations"]
    out["share.scan_subscribes"] = ws["scan_subscribes"]
    out["share.scan_units_shared"] = ws["scan_units_shared"]
    out["share.scan_units_decoded"] = ws["scan_units_decoded"]
    out["share.scan_rows_decoded"] = ws["scan_rows_decoded"]
    out["share.result_bytes"] = ws["result_bytes"]  # gauge
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.parallel.pipeline import live_stage_threads
    from spark_rapids_tpu.serving import cancel as _cancel

    cs = _cancel.stats()
    out["cancel.cancelled"] = cs["cancelled"]
    out["cancel.deadline_exceeded"] = cs["deadline_exceeded"]
    out["cancel.breaker_trips"] = cs["breaker_trips"]
    out["cancel.quarantined"] = cs["quarantined"]
    # residency GAUGES (recorded verbatim like store.*_used): the
    # snapshot taken at query END is the HC013 leak surface — a
    # cancelled query's record must show these back at baseline
    out["semaphore.in_use"] = TpuSemaphore.usage_now()["in_use"]
    out["pipeline.stage_threads"] = live_stage_threads()
    out["scan.inflight"] = work_share.SCAN_REGISTRY.inflight()
    from spark_rapids_tpu.robustness import lock_tracker

    ls = lock_tracker.aggregate_stats()
    out["lock.acquisitions"] = ls["acquisitions"]
    out["lock.contention_waits"] = ls["contention_waits"]
    out["lock.cycles"] = ls["cycles"]
    # hold-time high-water GAUGE (HC014 reads it against holdBudgetMs);
    # all-zero when the tracker is disarmed (the default)
    out["lock.max_hold_ms"] = ls["max_hold_ms"]
    return out


def counters_delta(before: dict, after: dict) -> dict[str, float]:
    """Per-query counter attribution: after - before for the monotonic
    keys (clamped at 0 — a concurrent ``reset_*`` between the two
    snapshots must not produce negative activity), gauges verbatim."""
    out: dict[str, float] = {}
    for k in MONOTONIC_COUNTERS:
        d = after.get(k, 0) - before.get(k, 0)
        out[k] = round(max(0, d), 3) if isinstance(d, float) else max(0, d)
    for k, v in after.items():
        if k not in MONOTONIC_COUNTERS:
            out[k] = v
    return out


# ------------------------------------------------------------------ #
# Fingerprints / hashes
# ------------------------------------------------------------------ #

_PATH_RE = re.compile(r"(?:/[\w.\-]+){2,}")
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def plan_fingerprint(plan_text: str) -> str:
    """A stable cross-run identity for a query template: the plan text
    with run-varying tokens (temp-dir paths, object addresses)
    normalized away, hashed.  `compare` matches queries across runs by
    this key, so the same bench query run against two different temp
    dirs still lines up."""
    norm = _ADDR_RE.sub("<addr>", plan_text)
    norm = _PATH_RE.sub("<path>", norm)
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


def conf_fingerprint(conf: TpuConf) -> str:
    """Hash of the conf's effective values — the conf-epoch key that
    lets cross-run compares align runs (two runs with different
    settings are not comparable apples-to-apples)."""
    payload = json.dumps(
        sorted((k, str(v)) for k, v in conf._values.items()))
    return hashlib.sha256(payload.encode()).hexdigest()[:12]


def table_digest(tbl) -> str:
    """Content digest of an Arrow result table (IPC stream bytes).
    Chaos-mode acceptance rests on this: a fault-injected run's record
    must carry the SAME digest as the fault-free run — recovery that
    changes the answer is not recovery."""
    import pyarrow as pa

    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        for b in tbl.combine_chunks().to_batches():
            w.write_batch(b)
    return hashlib.sha256(memoryview(sink.getvalue())).hexdigest()[:16]


def env_fingerprint() -> dict:
    """Host/runtime identity for the header record."""
    import platform as _plat

    out: dict[str, Any] = {
        "python": _plat.python_version(),
        "hostname": _plat.node(),
        "machine": _plat.machine(),
    }
    try:
        import jax

        out["jax"] = jax.__version__
        out["devices"] = [
            {"platform": d.platform,
             "kind": getattr(d, "device_kind", "")}
            for d in jax.devices()]
    except Exception:
        out["jax"] = None
        out["devices"] = []
    return out


def mesh_fingerprint() -> Optional[dict]:
    try:
        from spark_rapids_tpu.parallel.mesh import active_mesh

        mesh = active_mesh()
        if mesh is None:
            return None
        return {"axis_names": [str(a) for a in mesh.axis_names],
                "shape": {str(k): int(v)
                          for k, v in dict(mesh.shape).items()}}
    except Exception:
        return None


def render_plan_report(exec_, meta) -> str:
    """The lowered plan with its static annotation sections (lint
    findings, pipeline stages, runtime-filter sites) — exactly what
    ``DataFrame.explain()`` shows, shared so the persisted plan and the
    in-process view can never drift apart."""
    out = meta.explain()
    from spark_rapids_tpu.lint import lint_exec_tree

    diags = lint_exec_tree(exec_)
    if diags:
        out += "Lint:\n" + "\n".join(
            "  " + d.render() for d in diags) + "\n"
    stages = getattr(exec_, "_pipeline_stages", None)
    if stages:
        out += "Pipeline:\n" + "\n".join("  " + s for s in stages) + "\n"
    fusion = getattr(exec_, "_fusion_report", None)
    if fusion:
        # which per-batch chains compile into single XLA programs (and
        # why others don't) — docs/fusion.md
        out += "Fusion:\n" + "\n".join("  " + s for s in fusion) + "\n"
    from spark_rapids_tpu.plan.runtime_filter import (
        render_runtime_filters,
    )

    rf_lines = render_runtime_filters(exec_)
    if rf_lines:
        out += "RuntimeFilters:\n" + "\n".join(
            "  " + s for s in rf_lines) + "\n"
    return out


def _snapshot_to_dict(snap) -> dict:
    """NodeSnapshot tree -> the schema's operator-node shape."""
    return {"desc": snap.desc,
            "metrics": {k: v for k, v in snap.metrics.items()},
            "children": [_snapshot_to_dict(c) for c in snap.children]}


# ------------------------------------------------------------------ #
# Writer
# ------------------------------------------------------------------ #


class EventLogWriter:
    """Append-only JSONL event-log writer for one session.

    The file opens lazily on the first record (so a session that never
    collects writes nothing) and every ``append`` flushes — a crashed
    run keeps every completed query's record.  Query records are built
    and appended on the QueryHistory snapshot worker (which already
    waits for metric settlement), never on collect()'s critical path;
    the session's only synchronous work is the two
    :meth:`query_begin` / :meth:`query_end` counter snapshots, which
    MUST run at the query boundaries (a later reset/disarm would
    erase the attribution)."""

    def __init__(self, conf: TpuConf):
        self.directory = str(conf.get(EVENTLOG_DIR))
        self.compress = bool(conf.get(EVENTLOG_COMPRESS))
        self.trace_sidecar = bool(conf.get(EVENTLOG_TRACE_SIDECAR))
        self.session_id = (f"s{os.getpid()}-{int(time.time() * 1e3)}"
                           f"-{next(_SESSION_SEQ)}")
        ext = ".jsonl.gz" if self.compress else ".jsonl"
        self.path = os.path.join(
            self.directory, f"eventlog-{self.session_id}{ext}")
        self._conf = conf
        self._f = None
        self._wrote_header = False
        self._mu = threading.Lock()

    # -- low-level ------------------------------------------------- #

    def _write_locked(self, lines: list[str]) -> None:
        os.makedirs(self.directory, exist_ok=True)
        if self.compress:
            # one gzip MEMBER per append (open/write/close): the
            # member trailer lands with every record, so a crashed run
            # leaves a fully readable file — concatenated members are
            # valid gzip.  A held-open GzipFile only finalizes at
            # close, which would make the log unreadable mid-run.
            import gzip

            with gzip.open(self.path, "at", encoding="utf-8") as f:
                for line in lines:
                    f.write(line + "\n")
            return
        if self._f is None:
            self._f = open(self.path, "a", encoding="utf-8")
        for line in lines:
            self._f.write(line + "\n")
        self._f.flush()

    def append(self, rec: dict) -> None:
        """Validate + write one record (writer-side validation: an
        invalid record must fail HERE, in the session that can still
        see the bug, not in a reader weeks later)."""
        from spark_rapids_tpu.eventlog.schema import validate_record

        validate_record(rec)
        lines = [json.dumps(rec, default=str)]
        with self._mu:
            if not self._wrote_header:
                # under the same lock so two racing first queries emit
                # exactly one header, before either record
                hdr = self._header_record()
                validate_record(hdr)
                lines.insert(0, json.dumps(hdr, default=str))
            self._write_locked(lines)
            # only after the write SUCCEEDS: a failed first append
            # must retry the header next time, or the log would carry
            # query records with no env/conf fingerprint
            self._wrote_header = True

    def close(self) -> None:
        with self._mu:
            if self._f is not None:
                self._f.close()
                self._f = None

    # -- record builders ------------------------------------------- #

    def _header_record(self) -> dict:
        from spark_rapids_tpu.eventlog.schema import SCHEMA_VERSION

        conf_values = {k: str(v) for k, v in
                       sorted(self._conf._values.items())}
        return {
            "type": "header",
            "schema_version": SCHEMA_VERSION,
            "ts": time.time(),
            "session": self.session_id,
            "pid": os.getpid(),
            "env": env_fingerprint(),
            "conf": conf_values,
            "conf_hash": conf_fingerprint(self._conf),
            "mesh": mesh_fingerprint(),
        }

    def query_begin(self) -> dict:
        """Pre-query capture: the counter surface before execution (the
        record stores per-query deltas), plus the device-ledger
        snapshot when the ledger is on (the `programs` section is a
        per-query delta too)."""
        from spark_rapids_tpu.trace import ledger as _ledger

        pre = {"counters": counters_snapshot()}
        if _ledger.LEDGER.enabled:
            pre["ledger"] = _ledger.snapshot()
        return pre

    def query_end(self, pre: dict) -> dict:
        """End-of-query capture, ON THE CALLING THREAD: counter deltas,
        the pipeline stage snapshot, and per-site fault stats.  These
        must be read at query end, not later on the snapshot worker —
        by then a bench harness may have reset the counters or
        disarmed the fault schedule, and the record would lie.

        The serving tier's per-query facts (admission wait, tenant,
        plan-cache hit) ride the thread-local serving context rather
        than the counter deltas: admission and the plan-cache lookup
        happen BEFORE query_begin's snapshot, outside the delta
        window.  They land both as counters (serve.admit_wait_ms /
        serve.plan_cache_hit — the HC009 health-rule inputs) and as
        the structured `serving` record field."""
        from spark_rapids_tpu.robustness import faults
        from spark_rapids_tpu.serving import current_serving_context
        from spark_rapids_tpu.trace import ledger as _ledger

        programs = None
        if pre.get("ledger") is not None and _ledger.LEDGER.enabled:
            # bounded settle wait: the result fetch already forced the
            # device work, so in practice this returns immediately; a
            # wedged settle degrades the section, never the query
            _ledger.LEDGER.flush(timeout=2.0)
            d = _ledger.delta(pre["ledger"], _ledger.snapshot())
            if d:
                programs = _ledger.summarize(d)
        counters = counters_delta(pre["counters"], counters_snapshot())
        sctx = current_serving_context()
        # the wire-ingress section (docs/connect.md): the connect
        # server deposits peer/wire_bytes/translate_ms through the
        # serving facts; it is its own record section, not a serving
        # fact — in-process queries never carry one
        connect = sctx.pop("connect", None) if sctx else None
        if sctx:
            if "admit_wait_ms" in sctx:
                counters["serve.admit_wait_ms"] = sctx["admit_wait_ms"]
            if "plan_cache" in sctx:
                counters["serve.plan_cache_hit"] = \
                    1 if sctx["plan_cache"] == "hit" else 0
            if "result_cache" in sctx:
                counters["serve.result_cache_hit"] = \
                    1 if sctx["result_cache"] == "hit" else 0
        # the structured sharing section (docs/work_sharing.md): the
        # per-query result-cache verdict plus this query's share.*
        # counter deltas, None when the query never touched the
        # sharing tier (the common sharing-off fleet)
        share_delta = {k: v for k, v in counters.items()
                       if k.startswith("share.")}
        verdict = (sctx or {}).get("result_cache")
        sharing = None
        # the trigger reads the true per-query DELTAS only — the
        # result_bytes gauge reports the cache's current footprint,
        # which would mint a section for every query in the fleet
        # once anything is cached
        if verdict is not None or any(
                v for k, v in share_delta.items()
                if k != "share.result_bytes"):
            sharing = {"result_cache": verdict,
                       "counters": share_delta}
        return {
            "counters": counters,
            "pipeline": _pipeline_surface(),
            "faults": faults.fault_stats() or None,
            "serving": sctx,
            "sharing": sharing,
            "connect": connect,
            "programs": programs,
        }

    def build_query_record(self, ev, post: dict, plan_text: str,
                           engine: str,
                           result_digest: Optional[str] = None,
                           rows: Optional[int] = None) -> dict:
        """Build the per-query record from a settled QueryEvent plus
        the :meth:`query_end` capture (runs on the snapshot worker;
        `ev.root` metrics are already device-settled there)."""
        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.eventlog.schema import SCHEMA_VERSION

        spans = None
        trace_file = None
        if _trace.is_enabled():
            from spark_rapids_tpu.trace.export import (
                export_chrome_trace,
                span_stats,
            )

            events = _trace.snapshot()
            spans = span_stats(events, query_id=ev.query_id)
            if self.trace_sidecar:
                trace_file = os.path.join(
                    self.directory,
                    f"{self.session_id}-q{ev.query_id}.trace.json")
                try:
                    os.makedirs(self.directory, exist_ok=True)
                    export_chrome_trace(trace_file, events)
                except OSError:
                    trace_file = None
        return {
            "type": "query",
            "schema_version": SCHEMA_VERSION,
            "query_id": ev.query_id,
            "plan": plan_text,
            "plan_hash": plan_fingerprint(plan_text),
            "engine": engine,
            "wall_s": ev.wall_s,
            "start_ts": ev.start_ts,
            "end_ts": ev.end_ts,
            "start_ns": ev.start_ns,
            "end_ns": ev.end_ns,
            "conf_hash": ev.conf_hash,
            "counters": post["counters"],
            "operators": _snapshot_to_dict(ev.root),
            "spans": spans,
            "pipeline": post["pipeline"],
            "faults": post["faults"],
            "serving": post.get("serving"),
            "sharing": post.get("sharing"),
            "connect": post.get("connect"),
            "programs": post.get("programs"),
            "result_digest": result_digest,
            "rows": rows,
            "trace_file": trace_file,
        }

    def log_query(self, ev, post: dict, plan_text: str, engine: str,
                  result_digest: Optional[str] = None,
                  rows: Optional[int] = None) -> None:
        self.append(self.build_query_record(
            ev, post, plan_text, engine, result_digest, rows))

    def log_slo(self, breach: dict) -> None:
        """Append one SLO breach record (called by the obs/slo.py
        watchdog thread for every attached session — the HC016 health
        rule's input; `append` is lock-protected like log_telemetry)."""
        from spark_rapids_tpu.eventlog.schema import SCHEMA_VERSION

        self.append({
            "type": "slo",
            "schema_version": SCHEMA_VERSION,
            "ts": float(breach.get("ts") or time.time()),
            "session": self.session_id,
            "tenant": str(breach.get("tenant") or ""),
            "metric": str(breach["metric"]),
            "observed_ms": float(breach["observed_ms"]),
            "budget_ms": float(breach["budget_ms"]),
            "window": int(breach.get("window") or 0),
        })

    def log_telemetry(self, sample: dict) -> None:
        """Append one live-telemetry gauge sample (called by the
        trace/telemetry sampler thread for every attached session;
        `append` is lock-protected, so sampler and query records
        interleave without tearing)."""
        from spark_rapids_tpu.eventlog.schema import SCHEMA_VERSION

        self.append({
            "type": "telemetry",
            "schema_version": SCHEMA_VERSION,
            "ts": time.time(),
            "session": self.session_id,
            "counters": dict(sample),
        })


def _pipeline_surface() -> dict:
    from spark_rapids_tpu.parallel.pipeline import stage_snapshot

    return stage_snapshot()


def maybe_writer(conf: TpuConf) -> Optional[EventLogWriter]:
    """The session hook: a writer when the event log is enabled, else
    None (and the disabled session's whole per-query cost is the
    caller's ``is not None`` check)."""
    if not conf.get(EVENTLOG_ENABLED):
        return None
    return EventLogWriter(conf)
