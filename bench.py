"""Benchmark driver: TPC-H q6 + q1 end-to-end through the framework,
one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} —
headline = q6 (BASELINE.md config #1); q1 (config #2's shape: group-by
hash aggregate with 8 aggregates over string keys) rides as q1_*
diagnostic fields in the same object.

Unlike a kernel microbenchmark, this measures the REAL query path
(BASELINE.md config #1): `TpuSession.read_parquet -> where -> agg ->
collect`, which includes the host Parquet decode, plan tagging, H2D
upload, the jitted filter+project+aggregate programs, the partial->
exchange->final aggregation shape over multiple scan partitions, and the
D2H result materialization.  Every timed iteration is a full collect()
(the returned Arrow table forces a sync, so no async-dispatch artifact).

`vs_baseline` is measured IN-RUN: the same logical plan executed by the
CPU reference engine (pyarrow compute — the "CPU Spark" stand-in this
repo uses for differential testing), same files, same process.

A bytes/s figure against the chip's HBM roofline is included as a sanity
check (q6 input is ~28 B/row); rows/s claims that exceed the roofline
are physically impossible and mean the harness is broken.
"""

import json
import os
import statistics
import tempfile
import time

ROWS_PER_FILE = 1 << 20
N_FILES = 6  # ~6.3M rows ~ TPC-H SF1 lineitem
ROW_BYTES = 8 * 3 + 4  # three float64 columns + one int32 date
TPU_ITERS = 5
CPU_ITERS = 3
# HBM bandwidth of the bench chip (TPU v5e ~819 GB/s); only used for the
# roofline sanity fraction in the diagnostic fields.
HBM_BYTES_PER_S = 819e9


def make_lineitem(dirpath: str, n_files: int = N_FILES,
                  with_q1_cols: bool = False):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    paths = []
    for i in range(n_files):
        cols = {
            "l_quantity": rng.integers(1, 51, ROWS_PER_FILE).astype(
                np.float64),
            # TPC-H spec: l_extendedprice is a 2-decimal money value
            "l_extendedprice": np.round(
                rng.uniform(900, 105000, ROWS_PER_FILE), 2),
            "l_discount": rng.integers(0, 11, ROWS_PER_FILE) / 100.0,
            "l_shipdate": rng.integers(8766, 10957, ROWS_PER_FILE).astype(
                np.int32),
        }
        if with_q1_cols:
            cols["l_tax"] = rng.integers(0, 9, ROWS_PER_FILE) / 100.0
            cols["l_returnflag"] = np.array(["A", "N", "R"])[
                rng.integers(0, 3, ROWS_PER_FILE)]
            cols["l_linestatus"] = np.array(["F", "O"])[
                rng.integers(0, 2, ROWS_PER_FILE)]
        t = pa.table(cols)
        p = os.path.join(dirpath, f"lineitem-{i}.parquet")
        pq.write_table(t, p, row_group_size=ROWS_PER_FILE)
        paths.append(p)
    return paths


def q6_dataframe(session, paths):
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import col, sum_

    ship, disc, qty = col("l_shipdate"), col("l_discount"), col("l_quantity")
    price = col("l_extendedprice")
    cond = ((ship >= lit(8766)) & (ship < lit(9131))
            & (disc >= lit(0.05)) & (disc <= lit(0.07))
            & (qty < lit(24.0)))
    return (session.read_parquet(*paths)
            .where(cond)
            .agg((sum_(price * disc), "revenue")))


def q1_dataframe(session, paths):
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import avg, col, count_star, sum_

    qty, price = col("l_quantity"), col("l_extendedprice")
    disc, tax = col("l_discount"), col("l_tax")
    return (session.read_parquet(*paths)
            .where(col("l_shipdate") <= lit(10471))
            .group_by(col("l_returnflag"), col("l_linestatus"))
            .agg((sum_(qty), "sum_qty"),
                 (sum_(price), "sum_base_price"),
                 (sum_(price * (lit(1.0) - disc)), "sum_disc_price"),
                 (sum_(price * (lit(1.0) - disc) * (lit(1.0) + tax)),
                  "sum_charge"),
                 (avg(qty), "avg_qty"),
                 (avg(price), "avg_price"),
                 (avg(disc), "avg_disc"),
                 (count_star(), "count_order")))


def _time_collect(df, engine: str, iters: int) -> tuple[float, float]:
    """(median seconds per full collect, last result)."""
    times = []
    result = None
    for _ in range(iters):
        t0 = time.perf_counter()
        result = df.collect(engine=engine)
        times.append(time.perf_counter() - t0)
    return statistics.median(times), result


def _bench_q1(session, d: str) -> dict:
    """BASELINE config #2's SHAPE (grouped 8-aggregate q1) at a scale
    the bench host generates in seconds; full SF100 needs a real
    cluster-sized host.  Exchange width 1: on a single chip the
    8-way hash exchange is pure dispatch overhead, and on tunneled
    PJRT links every dispatch pays full round-trip latency."""
    from spark_rapids_tpu.config import get_conf

    conf = get_conf()
    key = "spark.rapids.tpu.sql.shuffle.partitions"
    old_sp = conf.get(key)
    conf.set(key, 1)
    try:
        q1_files = make_lineitem(os.path.join(d, "q1"), n_files=2,
                                 with_q1_cols=True)
        df = q1_dataframe(session, q1_files)
        df.collect(engine="tpu")  # warmup
        tpu_t, tpu_r = _time_collect(df, "tpu", 3)
        cpu_t, cpu_r = _time_collect(df, "cpu", 2)
    finally:
        conf.set(key, old_sp)
    got = sorted(zip(*tpu_r.to_pydict().values()))
    want = sorted(zip(*cpu_r.to_pydict().values()))
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1], (g[:2], w[:2])  # keys
        for gv, wv in zip(g[2:], w[2:]):  # 8 aggregates, float-tolerant
            assert abs(gv - wv) <= 1e-6 * max(1.0, abs(wv)), (gv, wv)
    return {
        "q1_tpu_s_per_query": round(tpu_t, 4),
        "q1_cpu_s_per_query": round(cpu_t, 4),
        "q1_vs_cpu": round(cpu_t / tpu_t, 3),
        "q1_rows": ROWS_PER_FILE * 2,
    }


def main() -> None:
    n_rows = ROWS_PER_FILE * N_FILES
    with tempfile.TemporaryDirectory(prefix="q6bench_") as d:
        paths = make_lineitem(d)
        os.makedirs(os.path.join(d, "q1"), exist_ok=True)

        from spark_rapids_tpu.session import TpuSession

        session = TpuSession()
        df = q6_dataframe(session, paths)

        df.collect(engine="tpu")  # warmup: compile cache, page cache
        tpu_t, tpu_result = _time_collect(df, "tpu", TPU_ITERS)
        cpu_t, cpu_result = _time_collect(df, "cpu", CPU_ITERS)

        # correctness gate: a fast wrong answer is not a benchmark
        got = tpu_result.to_pydict()["revenue"][0]
        want = cpu_result.to_pydict()["revenue"][0]
        assert abs(got - want) <= 1e-6 * max(1.0, abs(want)), (got, want)

        if tpu_t > 10.0:
            # degraded tunnel (per-dispatch latency in the seconds):
            # a q1 run would take tens of minutes and measure the
            # network, not the engine — record the skip instead
            q1_fields = {"q1_skipped": "slow device link "
                         f"(q6 took {tpu_t:.1f}s)"}
        else:
            q1_fields = _bench_q1(session, d)

    rows_per_s = n_rows / tpu_t
    bytes_per_s = rows_per_s * ROW_BYTES
    cpu_rows_per_s = n_rows / cpu_t
    out = {
        "metric": "tpch_q6_e2e_throughput",
        "value": round(rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_s / cpu_rows_per_s, 3),
        "rows": n_rows,
        "tpu_s_per_query": round(tpu_t, 4),
        "cpu_s_per_query": round(cpu_t, 4),
        "bytes_per_s": round(bytes_per_s, 1),
        "hbm_roofline_fraction": round(bytes_per_s / HBM_BYTES_PER_S, 4),
    }
    out.update(q1_fields)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
