"""Benchmark driver: TPC-H q6 + q1 + a q3-shaped join, end-to-end
through the framework, one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} —
headline = q6 (BASELINE.md config #1); q1 (config #2's shape: grouped
8-aggregate over string keys), q3 (config #3's shape: two-table hash
join -> grouped aggregate -> top-k) and q67 (config #4's shape:
grouped aggregate -> rank window -> rank filter -> sort) ride as
q1_*/q3_*/q67_* fields.

Unlike a kernel microbenchmark, this measures the REAL query path:
`TpuSession.read_parquet -> ... -> collect`, which includes the host
Parquet decode, plan tagging, wire encode + H2D upload, the fused jitted
programs, and the D2H result materialization.  Every timed iteration is
a full collect() (the returned Arrow table forces a sync, so no
async-dispatch artifact).

`vs_baseline` is measured IN-RUN: the same logical plan executed by the
CPU reference engine (pyarrow compute — the "CPU Spark" stand-in this
repo uses for differential testing), same files, same process.

Attribution fields (so round-over-round deltas are explainable):
- per-config min/median/max seconds (link weather varies ~100x between
  runs; a median alone cannot distinguish regression from weather);
- a link probe (scalar-fetch round-trip + upload bandwidth) taken right
  before timing;
- a q6 stage breakdown: host decode / wire encode+upload / the final
  fetch (which inlines the remaining device execution wait);
- per-query `q*_host_sync_count` (blocking device->host readbacks per
  collect — the number speculative output sizing drives to zero) and
  `q{1,3,67}_speculation_hit_rate` (fraction of speculative dispatches
  whose predicted capacity covered the true count), so the sync
  elimination is visible in the perf trajectory;
- `q3_rf_*` runtime-filter attribution (pruned rows, build ms, pruned
  row groups per collect) plus `q3_upload_rows` vs
  `q3_upload_rows_no_rf` — the probe-side wire-shrink runtime join
  filters buy (docs/runtime_filters.md);
- `q6_warm_*` / `q1_warm_*` + `hbm_roofline_fraction_warm`: a second
  pass against df.cache()-materialized DEVICE-resident batches, so
  actual device throughput is measured with the H2D wire out of the
  loop;
- per-query DEVICE-LEDGER attribution (trace/ledger.py,
  docs/device_ledger.md): `q*_device_busy_ms` (attributed device time
  per collect, vs the wall-clock numbers' host+wire+dispatch
  residual), `q*_roofline_attributed` (XLA-cost-model bytes over
  settled device time against the HBM peak — the honest counterpart
  of the coarse `hbm_roofline_fraction` quotients, same constant via
  trace/ledger.roofline_fraction), `q*_dispatches`/`q*_programs`
  (launch counts + distinct compiled programs: the ROADMAP #2
  fusion/bucketing scoreboard), `q*_live_capacity_ratio` (live rows
  over padded capacity across the window's dispatches — the occupancy
  scoreboard, docs/occupancy.md) and `q*_top_program` (+`_share`).
  Batch coalescing is ON by default for rounds (`--no-coalesce`
  reverts; results are bit-identical either way);
- `q*_fusion_chains` / `q*_fused_dispatch_savings` (docs/fusion.md):
  whole-stage fusion attribution per collect — chains the planner
  fused into single programs and the program launches those fused
  executions did not pay; the warm passes are additionally GATED by
  `spark.rapids.tpu.sql.fusion.warmDispatchBudget` (warm dispatches
  over budget, or any warm jit miss, fails the round — ROADMAP #2's
  dispatch-soup diagnosis as a regression gate).  Buffer donation is
  ON by default for rounds (`--no-donation` reverts);
- `q{1,3,6,67}_retry_splits` / `_spills_under_pressure` /
  `_recovered_faults` (reset per query like the pipeline/speculation
  counters): recovery activity in the timed window.  On a clean run
  all three are 0; under `--chaos` — which arms the deterministic
  fault schedule below for every query (robustness/faults.py,
  docs/robustness.md) — they record what the recovery ladder absorbed,
  so BENCH_r06+ measures recovery OVERHEAD, not just happy-path speed
  (the correctness gates still run, so a chaos round that survives is
  a chaos round that answered exactly);
- a persistent EVENT LOG per round (on by default; `--no-eventlog` to
  opt out, `--eventlog DIR` / $BENCH_EVENTLOG_DIR to place it): every
  collect's plan, settled operator metrics and counter deltas, so
  rounds are diffable offline via
  `python -m spark_rapids_tpu.tools.history report` instead of
  hand-diffing these JSON fields (docs/eventlog.md); the file path
  rides in the output as `eventlog`.

- `q{1,3,6}_upload_bytes_wire` / `_upload_bytes_raw` / `_upload_ratio`
  (+ `link_upload_mb_s_effective`): bytes actually crossing the H2D
  link over the tapped batched-upload counter, wire compression
  as-configured vs forced off — the multiplier the wire-codec
  subsystem (docs/wire_compression.md) buys on the tunneled link.
  Compression is ON by default for bench rounds
  (`--no-wire-compression` reverts to the raw wire; the correctness
  gates run either way).

`bench.py --scale-rows N` switches to the SCALING-CURVE round
(ROADMAP #1): q6 at N rows (~63M = SF10 lineitem) and q1 at
max(N // 3, 20M) rows with the full per-stage attribution, proving
the codec + OOC machinery under real pressure.

`bench.py --multichip N` switches to the MULTICHIP round (docs/spmd.md,
ROADMAP #3): the collective tier's agg/join/sort phases on the virtual
N-device CPU mesh — per-phase wall, exchange rounds, partitioned
program counts, ledger dispatches/device time, per-device wall — plus
the milestone comparison: single-device vs host-loop vs SPMD
whole-stage walls, bit-identical canonical digests, and
`speedup_vs_single_device`.  Known-noise XLA:CPU AOT stderr is
filtered out of the captured `tail`, so MULTICHIP_r*.json carries only
signal.

`bench.py --sessions N [--tenants K]` switches to the SERVING bench
(docs/serving.md): N concurrent sessions across K tenants drive
deterministic golden templates through admission control and the
prepared-plan cache, emitting `serving_qps`, `serving_p50_ms` /
`serving_p99_ms`, `admission_wait_p99_ms` and `plan_cache_hit_rate`,
with a bit-for-bit digest gate against serial execution and a
repeat-template pass asserting hit rate 1.0 with zero plan/tag/lower
spans and zero jit-cache misses.  Cross-tenant work sharing
(docs/work_sharing.md) is ON by default: the round runs the whole
concurrent pass twice — sharing off then on — and emits the A/B
(`serving_qps_sharing_{on,off}`, `shared_scan_dedup_ratio`,
`result_cache_hit_rate`, tapped upload-byte totals); `--no-sharing`
opts out, `--chaos` arms the deterministic fault schedule in both
arms, `--store-budget N` shrinks the spill-store budgets so cached
results take the host->disk spill/restore path mid-round.
`--cancel-rate P` (0..1) arms the CANCELLATION STORM on the measured
window: each repeat execution is perturbed with probability P
(seeded per session) — half get a mid-flight session.cancel(), half
a short per-query deadline — and one extra POISON tenant crash-loops
into the circuit breaker (serving.breaker.failureThreshold).  The
round then emits `cancelled_count` / `deadline_exceeded_count` /
`breaker_trips` / `quarantined_count`, every SURVIVING query's
digest stays bit-identical to serial, and the post-phase residency
gauges (semaphore permits, stage threads, in-flight scan shares,
admission queue) are asserted back at baseline — a cancelled query
is an outcome, not a leak (docs/robustness.md).

Every --sessions measured window additionally runs under the runtime
lock-order tracker (robustness/lock_tracker.py, docs/concurrency.md):
the phase asserts ZERO lock-order cycles across the storm's
interleavings and emits `lock_acquisitions` /
`lock_contention_waits` / `max_lock_hold_ms` — observed registry-mutex
contention, the HC014 health surface measured rather than inferred.

Every --sessions measured window also runs SCRAPED: the live ops
plane (spark_rapids_tpu/obs/, docs/ops_plane.md) is forced on and a
scraper thread hammers /metrics concurrently with the repeat pass.
The phase asserts every monotone eventlog counter only ever moves
forward across successive scrapes, and — because the serial reference
digests were computed with the plane off — the existing digest gate
doubles as the zero-impact proof: obs on vs off is bit-identical.
The round emits `obs_scrapes` / `obs_scrape_monotone`.

`bench.py --cold-start N` switches to the COLD-START bench
(docs/warm_start.md): after two unmeasured populate/prime children
fill one persist directory, N fresh subprocesses run the fusion-smoke
query against the WARM directory and N against EMPTY ones, emitting
`warm_cold_p50_ms` / `warm_cold_p99_ms` / `warm_cold_jit_misses` /
`warm_persist_hit_rate` and the `empty_*` mirror, a bit-identical
digest gate across every child, and `cold_p50_speedup` — the wall a
process restart re-pays with and without the warm-start cache.
"""

import json
import os
import statistics
import sys
import tempfile
import time

ROWS_PER_FILE = 1 << 20
N_FILES = 6  # ~6.3M rows ~ TPC-H SF1 lineitem
ROW_BYTES = 8 * 3 + 4  # three float64 columns + one int32 date
TPU_ITERS = 5
CPU_ITERS = 3


def _roofline(rows_per_s: float) -> float:
    """Coarse roofline fraction of a rows/s figure.  The formula AND
    the HBM-bandwidth constant live in trace/ledger.py (conf
    spark.rapids.tpu.trace.ledger.hbmBytesPerSec, default TPU v5e
    ~819 GB/s) — one definition shared by this coarse quotient, the
    warm-pass variant and the ledger's per-program attribution, so
    the three can never drift."""
    from spark_rapids_tpu.trace.ledger import roofline_fraction

    return round(roofline_fraction(rows_per_s * ROW_BYTES), 4)

#: --chaos schedule, re-armed (fresh counters, so the nth-call policies
#: re-fire) at every per-query counter reset: one device-alloc OOM
#: early, one upload fault, one compile fault, one stage fault, and a
#: two-deep batch fault that drives the ladder past the spill rung into
#: an actual bisection.
CHAOS_SPEC = ("alloc.device:nth=2;transfer.upload:nth=2;"
              "jit.compile:nth=1;pipeline.stage:nth=2;"
              "exec.batch:nth=3,times=2")
_CHAOS = False


def make_lineitem(dirpath: str, n_files: int = N_FILES,
                  with_q1_cols: bool = False,
                  with_orderkey: bool = False,
                  n_orders: int = 1 << 20):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    paths = []
    for i in range(n_files):
        cols = {
            "l_quantity": rng.integers(1, 51, ROWS_PER_FILE).astype(
                np.float64),
            # TPC-H spec: l_extendedprice is a 2-decimal money value
            "l_extendedprice": np.round(
                rng.uniform(900, 105000, ROWS_PER_FILE), 2),
            "l_discount": rng.integers(0, 11, ROWS_PER_FILE) / 100.0,
            "l_shipdate": rng.integers(8766, 10957, ROWS_PER_FILE).astype(
                np.int32),
        }
        if with_q1_cols:
            cols["l_tax"] = rng.integers(0, 9, ROWS_PER_FILE) / 100.0
            cols["l_returnflag"] = np.array(["A", "N", "R"])[
                rng.integers(0, 3, ROWS_PER_FILE)]
            cols["l_linestatus"] = np.array(["F", "O"])[
                rng.integers(0, 2, ROWS_PER_FILE)]
        if with_orderkey:
            cols["l_orderkey"] = rng.integers(
                0, n_orders, ROWS_PER_FILE).astype(np.int64)
        t = pa.table(cols)
        p = os.path.join(dirpath, f"lineitem-{i}.parquet")
        pq.write_table(t, p, row_group_size=ROWS_PER_FILE)
        paths.append(p)
    return paths


def make_orders(dirpath: str, n_orders: int = 1 << 20):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    t = pa.table({
        "o_orderkey": np.arange(n_orders, dtype=np.int64),
        "o_orderdate": rng.integers(8766, 10957, n_orders).astype(
            np.int32),
        "o_shippriority": rng.integers(0, 5, n_orders).astype(np.int32),
    })
    p = os.path.join(dirpath, "orders.parquet")
    pq.write_table(t, p, row_group_size=n_orders)
    return p


def q6_dataframe(session, paths):
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import col, sum_

    ship, disc, qty = col("l_shipdate"), col("l_discount"), col("l_quantity")
    price = col("l_extendedprice")
    cond = ((ship >= lit(8766)) & (ship < lit(9131))
            & (disc >= lit(0.05)) & (disc <= lit(0.07))
            & (qty < lit(24.0)))
    return (session.read_parquet(*paths)
            .where(cond)
            .agg((sum_(price * disc), "revenue")))


def q1_dataframe(session, paths):
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import avg, col, count_star, sum_

    qty, price = col("l_quantity"), col("l_extendedprice")
    disc, tax = col("l_discount"), col("l_tax")
    return (session.read_parquet(*paths)
            .where(col("l_shipdate") <= lit(10471))
            .group_by(col("l_returnflag"), col("l_linestatus"))
            .agg((sum_(qty), "sum_qty"),
                 (sum_(price), "sum_base_price"),
                 (sum_(price * (lit(1.0) - disc)), "sum_disc_price"),
                 (sum_(price * (lit(1.0) - disc) * (lit(1.0) + tax)),
                  "sum_charge"),
                 (avg(qty), "avg_qty"),
                 (avg(price), "avg_price"),
                 (avg(disc), "avg_disc"),
                 (count_star(), "count_order")))


def q3_dataframe(session, li_paths, orders_path):
    """TPC-H q3 shape on two tables: lineitem JOIN orders on orderkey,
    date filters on both sides, revenue per order, top-10 by revenue
    (exchange + shuffled hash join + high-cardinality group-by +
    sort/limit — BASELINE config #3's moving parts)."""
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import col, sum_

    li = (session.read_parquet(*li_paths)
          .where(col("l_shipdate") > lit(9500)))
    orders = (session.read_parquet(orders_path)
              .where(col("o_orderdate") < lit(9500)))
    joined = li.join(orders, left_on=[col("l_orderkey")],
                     right_on=[col("o_orderkey")])
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (joined
            .group_by(col("l_orderkey"), col("o_orderdate"),
                      col("o_shippriority"))
            .agg((sum_(rev), "revenue"))
            .order_by(col("revenue"), desc=True)
            .limit(10))


def make_store_sales(dirpath: str, n_rows: int = 1 << 21,
                     n_files: int = 2):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(67)
    per = n_rows // n_files
    paths = []
    for i in range(n_files):
        t = pa.table({
            "ss_store_sk": rng.integers(1, 9, per),
            "ss_item_sk": rng.integers(1, 2000, per),
            "ss_quantity": rng.integers(1, 20, per).astype(np.float64),
            "ss_sales_price": np.round(rng.uniform(1, 300, per), 2),
        })
        p = os.path.join(dirpath, f"ss-{i}.parquet")
        pq.write_table(t, p, row_group_size=per)
        paths.append(p)
    return paths


def q67_dataframe(session, paths):
    """TPC-DS q67 shape: grouped aggregate -> rank window partitioned
    by store -> rank filter -> ordered output (BASELINE config #4's
    sort + window moving parts)."""
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.exprs.window import Window, rank
    from spark_rapids_tpu.session import col, sum_

    agg = (session.read_parquet(*paths)
           .group_by(col("ss_store_sk"), col("ss_item_sk"))
           .agg((sum_(col("ss_sales_price") * col("ss_quantity")),
                 "sumsales")))
    spec = Window.partition_by("ss_store_sk").order_by(
        "sumsales", desc=True)
    ranked = agg.select(col("ss_store_sk"), col("ss_item_sk"),
                        col("sumsales"),
                        rank().over(spec).alias("rk"))
    return (ranked.where(col("rk") <= lit(10))
            .order_by(col("ss_store_sk"), col("rk"), col("ss_item_sk")))


def _time_collect(df, engine: str, iters: int):
    """([seconds per full collect...], last result)."""
    times = []
    result = None
    for _ in range(iters):
        t0 = time.perf_counter()
        result = df.collect(engine=engine)
        times.append(time.perf_counter() - t0)
    return times, result


def _stats(times, prefix: str) -> dict:
    return {
        f"{prefix}_s_min": round(min(times), 4),
        f"{prefix}_s_median": round(statistics.median(times), 4),
        f"{prefix}_s_max": round(max(times), 4),
    }


def _link_probe() -> dict:
    """Scalar-fetch round trips + one 8MB upload: the weather report.
    Taken AFTER the first result fetch, i.e. in the same degraded client
    mode the timed queries run in."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rtts = []
    x = jnp.asarray(1.0)
    for _ in range(5):
        t0 = time.perf_counter()
        float(jax.device_get(x + 1.0))
        rtts.append(time.perf_counter() - t0)
    a = np.random.default_rng(0).random(1 << 20)
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(a))
    up = time.perf_counter() - t0
    return {
        "link_rtt_ms_median": round(statistics.median(rtts) * 1e3, 1),
        "link_upload_mb_s": round(8.0 / max(up, 1e-9), 1),
    }


class _StageTaps:
    """Wall-clock accumulated in the scan host decode, the wire
    encode+upload, and the final result fetch, for ONE collect."""

    def __init__(self):
        import spark_rapids_tpu.io.scan as scan_mod
        import spark_rapids_tpu.plan.planner as planner_mod
        from spark_rapids_tpu.columnar.arrow import to_arrow
        from spark_rapids_tpu.io import fastpar

        self.host_s = 0.0
        self.wire_s = 0.0
        self.fetch_s = 0.0
        self._mods = (scan_mod, planner_mod, fastpar)
        self._orig = (scan_mod.ParquetScanExec._upload,
                      planner_mod.to_arrow, fastpar.read_file)

        taps = self

        def upload(inner_self, tables):
            t0 = time.perf_counter()
            try:
                return taps._orig[0](inner_self, tables)
            finally:
                taps.wire_s += time.perf_counter() - t0

        def fetch(b):
            t0 = time.perf_counter()
            try:
                return to_arrow(b)
            finally:
                taps.fetch_s += time.perf_counter() - t0

        def read_file(*a, **k):
            t0 = time.perf_counter()
            try:
                return taps._orig[2](*a, **k)
            finally:
                taps.host_s += time.perf_counter() - t0

        scan_mod.ParquetScanExec._upload = upload
        planner_mod.to_arrow = fetch
        fastpar.read_file = read_file

    def restore(self):
        scan_mod, planner_mod, fastpar = self._mods
        scan_mod.ParquetScanExec._upload = self._orig[0]
        planner_mod.to_arrow = self._orig[1]
        fastpar.read_file = self._orig[2]


def _stage_breakdown(df, prefix: str) -> dict:
    """One instrumented collect: where does an iteration of this query
    go?  host_decode / wire_upload / final_fetch accumulate wall time
    in the tapped stages; `other` is the residual — with the software
    pipeline on, stages OVERLAP, so the residual approximates the
    non-overlapped compute+dispatch and the four fields can sum past
    the total.  The final-fetch figure inlines the wait for any device
    execution still in flight (dispatch is async) — if the residual is
    dominated by fetch at near-zero decode/wire time, the bottleneck is
    the link, not the engine."""
    taps = _StageTaps()
    try:
        t0 = time.perf_counter()
        df.collect(engine="tpu")
        total = time.perf_counter() - t0
    finally:
        taps.restore()
    return {
        f"{prefix}_stage_host_decode_s": round(taps.host_s, 4),
        f"{prefix}_stage_wire_upload_s": round(taps.wire_s, 4),
        f"{prefix}_stage_final_fetch_s": round(taps.fetch_s, 4),
        f"{prefix}_stage_other_s": round(
            max(0.0, total - taps.host_s - taps.wire_s - taps.fetch_s),
            4),
    }


def _pipeline_occupancy(prefix: str = "pipeline") -> dict:
    """Aggregate the software pipeline's stage counters
    (parallel.pipeline.stage_snapshot) into one occupancy figure:
    item-weighted mean of each stage's queue-occupancy fraction.  ~1.0
    means producers stay ahead of consumers (the pipeline is full);
    ~0.0 means stages run starved/serial.  Per-stage detail rides as a
    sub-object so round-over-round deltas are attributable.

    Counters are RESET between benchmark configs
    (parallel.pipeline.reset_stage_counters), so each
    `{q}_pipeline_occupancy` reflects that query alone instead of
    accumulating across q6/q1/q3/q67."""
    from spark_rapids_tpu.parallel.pipeline import stage_snapshot

    snap = stage_snapshot()
    weighted = 0.0
    items = 0
    for s in snap.values():
        if s["items"]:
            weighted += s["occupancy_fraction"] * s["items"]
            items += s["items"]
    return {
        f"{prefix}_occupancy": round(weighted / items, 3)
        if items else 0.0,
        f"{prefix}_stages": snap,
    }


def reset_all_counters() -> None:
    """THE per-query counter reset: every process-global stat surface
    the q*_ attribution fields read — pipeline stage counters,
    speculation, runtime filters, retry ladder, device ledger, fusion
    chains, upload taps and the fault schedule — zeroed in ONE place
    so a new counter surface cannot be forgotten at one of the call
    sites (the warm-window choreography used to re-list them
    per site)."""
    from spark_rapids_tpu.columnar.transfer import reset_upload_stats
    from spark_rapids_tpu.execs.base import reset_fusion_stats
    from spark_rapids_tpu.execs.retry import reset_retry_stats
    from spark_rapids_tpu.parallel.pipeline import reset_stage_counters
    from spark_rapids_tpu.parallel.speculation import reset_stats
    from spark_rapids_tpu.plan import runtime_filter
    from spark_rapids_tpu.robustness import faults
    from spark_rapids_tpu.trace import ledger

    reset_stage_counters()
    reset_stats()  # per-query speculation hit rates, same discipline
    runtime_filter.reset_stats()  # per-query pruned-row counts too
    reset_retry_stats()  # per-query split/spill-retry attribution
    ledger.reset_stats()  # per-query program/roofline attribution
    reset_fusion_stats()  # per-query fused-chain/savings attribution
    reset_upload_stats()  # per-query H2D byte taps
    if _CHAOS:
        # fresh schedule per query: counters zero, nth policies re-fire
        faults.install(CHAOS_SPEC, forced=True)
    else:
        faults.reset_stats()


def _reset_ledger() -> None:
    """Zero ONLY the device ledger (warm passes call this so their
    attribution covers the warm runs alone, without the side effects
    of the full counter reset — which re-arms the --chaos schedule)."""
    from spark_rapids_tpu.trace import ledger

    ledger.reset_stats()


def _robustness_fields(prefix: str, spilled_before: int = 0) -> dict:
    """Recovery activity in the timed window (reset per query by
    reset_all_counters): ladder bisections, device->host bytes
    spilled under pressure, and recovered injected faults (nonzero
    only under --chaos)."""
    from spark_rapids_tpu.execs.retry import retry_stats
    from spark_rapids_tpu.memory import get_store
    from spark_rapids_tpu.robustness import faults

    st = retry_stats()
    return {
        f"{prefix}_retry_splits": st["splits"],
        f"{prefix}_spills_under_pressure":
            get_store().spilled_device_to_host - spilled_before,
        f"{prefix}_recovered_faults": faults.recovered_total(),
    }


def _spilled_now() -> int:
    from spark_rapids_tpu.memory import get_store

    return get_store().spilled_device_to_host


def _sync_spec_fields(prefix: str, iters: int,
                      with_hit_rate: bool = True) -> dict:
    """Host-sync + speculation attribution for the timed window:

    - `{prefix}_host_sync_count`: BLOCKING device->host readbacks per
      collect (stage-counter `readbacks`, which speculative sizing's
      async harvest does not tick) — the number the speculation layer
      exists to drive to zero; on a ~100ms-RTT link each unit is a
      stalled link round trip on the critical path;
    - `{prefix}_speculation_hit_rate`: fraction of speculative
      dispatches whose predicted capacity covered the true count
      (sized-output queries only — a grand aggregate never sizes)."""
    from spark_rapids_tpu.parallel import speculation
    from spark_rapids_tpu.parallel.pipeline import stage_snapshot

    snap = stage_snapshot()
    syncs = sum(s["readbacks"] for s in snap.values())
    out = {f"{prefix}_host_sync_count": round(syncs / max(iters, 1), 2)}
    if with_hit_rate:
        out[f"{prefix}_speculation_hit_rate"] = speculation.hit_rate()
        st = speculation.stats()
        out[f"{prefix}_speculation_overflows"] = sum(
            s["overflows"] for s in st.values())
        # adaptive kill-switch verdict for the window: tags whose
        # rolling hit rate fell below speculation.adaptive.minHitRate
        # and were auto-disabled (0 with the default threshold off)
        out[f"{prefix}_speculation_disabled"] = len(
            speculation.disabled_tags())
    return out


def _ledger_fields(prefix: str, iters: int) -> dict:
    """Per-query device-ledger attribution for the timed window (the
    ledger is reset per query by reset_all_counters, so the
    cumulative snapshot IS the window):

    - `{prefix}_device_busy_ms`: attributed device time per collect —
      summed dispatch-to-completion wall of every program the window
      dispatched (the DEVICE share of the coarse wall-clock numbers
      above; the gap is host decode/wire/dispatch overhead);
    - `{prefix}_roofline_attributed`: device-time-weighted roofline
      fraction from XLA's cost model (bytes accessed x dispatches /
      device time / HBM peak) — the honest per-program counterpart of
      the coarse `hbm_roofline_fraction`;
    - `{prefix}_dispatches` / `{prefix}_programs`: launch count per
      collect and distinct compiled programs in the window (the
      fusion/bucketing scoreboard of ROADMAP #2);
    - `{prefix}_live_capacity_ratio`: live rows over padded capacity
      across every dispatch in the window — the occupancy scoreboard
      (1.0 = every program ran full; docs/occupancy.md);
    - `{prefix}_top_program` (+ `_share`): where the device time went.
    """
    from spark_rapids_tpu.trace import ledger

    ledger.LEDGER.flush(timeout=10.0)
    s = ledger.summarize(ledger.snapshot())
    t = s["totals"]
    per = max(iters, 1)
    out = {
        f"{prefix}_device_busy_ms": round(t["device_ms"] / per, 2),
        f"{prefix}_dispatches": round(t["dispatches"] / per, 1),
        f"{prefix}_programs": t["programs"],
        f"{prefix}_roofline_attributed": t["roofline"],
    }
    if t.get("live_capacity_ratio") is not None:
        out[f"{prefix}_live_capacity_ratio"] = t["live_capacity_ratio"]
    top = t.get("top") or []
    if top:
        out[f"{prefix}_top_program"] = top[0]["key"]
        out[f"{prefix}_top_program_share"] = top[0]["share"]
    return out


def _fusion_fields(prefix: str, iters: int) -> dict:
    """Whole-stage fusion attribution for the timed window (reset per
    query by reset_all_counters; docs/fusion.md):

    - `{prefix}_fusion_chains`: fused chain programs planned per
      collect (the planner's _plan_fusion count — agrees with
      explain()'s "Fusion:" section by construction);
    - `{prefix}_fused_dispatch_savings`: program launches the fused
      executions did NOT pay per collect vs the unfused engine
      (chain length - 1 per execution, +1 when the wire decode rode
      inside) — the BENCH_r06+ scoreboard for ROADMAP #2's
      dispatch-soup diagnosis."""
    from spark_rapids_tpu.execs.base import fusion_stats

    st = fusion_stats()
    per = max(iters, 1)
    return {
        f"{prefix}_fusion_chains": round(st["chains"] / per, 1),
        f"{prefix}_fused_dispatch_savings": round(
            st["saved_dispatches"] / per, 1),
    }


def _assert_warm_budget(prefix: str, fields: dict) -> None:
    """The dispatch-budget regression GATE (ROADMAP #2): a warm
    (compile-cache-hot) milestone query must pay at most
    spark.rapids.tpu.sql.fusion.warmDispatchBudget program launches
    per collect and compile NOTHING — un-fusing a chain or
    destabilizing a jit key fails the round here instead of drifting
    in the diagnostics."""
    from spark_rapids_tpu.execs.base import warm_dispatch_budget

    budget = warm_dispatch_budget()
    if budget > 0:
        # budget 0 disables BOTH halves of the gate (the conf's
        # documented escape hatch for environments where warm
        # recompiles are expected, e.g. backend bring-up)
        misses = fields.get(f"{prefix}_jit_misses")
        assert misses == 0, (
            f"{prefix}: warm pass re-compiled {misses} program(s) — "
            "jit keys are unstable across identical collects")
        d = fields.get(f"{prefix}_dispatches")
        assert d is not None and d <= budget, (
            f"{prefix}: warm dispatch count {d} exceeds the budget "
            f"{budget} (spark.rapids.tpu.sql.fusion."
            f"warmDispatchBudget)")


def _wire_fields(df, prefix: str) -> dict:
    """Wire-compression attribution: bytes actually crossing the H2D
    link (the tapped batched-upload counter) with the codec subsystem
    as-configured vs forced off — `{prefix}_upload_ratio` is the
    multiplier the codecs buy on the ~13 MB/s tunneled link
    (docs/wire_compression.md)."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.tools.bench_smoke import count_upload_bytes

    key = "spark.rapids.tpu.sql.wireCompression.enabled"
    conf = get_conf()
    old = conf.get(key)
    try:
        # AS-CONFIGURED first (matches the timed windows — under
        # --no-wire-compression this honestly reports ratio 1.0
        # instead of attributing bytes the measured run never shipped)
        on_bytes = count_upload_bytes(df)
        conf.set(key, False)
        off_bytes = count_upload_bytes(df)
    finally:
        conf.set(key, old)
    return {
        f"{prefix}_upload_bytes_wire": on_bytes,
        f"{prefix}_upload_bytes_raw": off_bytes,
        f"{prefix}_upload_ratio": round(off_bytes / max(on_bytes, 1),
                                        3),
    }


def _rf_fields(df, iters: int) -> dict:
    """q3 runtime-filter attribution: pruned rows + build cost over the
    timed window (per collect), plus uploaded-row counts with filters
    on vs off — the wire-shrink the filters buy, measured."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.plan import runtime_filter
    from spark_rapids_tpu.tools.bench_smoke import count_upload_rows

    st = runtime_filter.stats()
    per = max(iters, 1)
    out = {
        "q3_rf_pruned_rows": round(st["pruned_rows"] / per, 1),
        "q3_rf_build_ms": round(st["build_ms"] / per, 2),
        "q3_rf_row_groups_pruned": round(
            st["row_groups_pruned"] / per, 1),
    }
    key = "spark.rapids.tpu.sql.runtimeFilter.enabled"
    conf = get_conf()
    old = conf.get(key)
    try:
        conf.set(key, True)
        out["q3_upload_rows"] = count_upload_rows(df)
        conf.set(key, False)
        out["q3_upload_rows_no_rf"] = count_upload_rows(df)
    finally:
        conf.set(key, old)
    return out


def _bench_warm(df, prefix: str, n_rows: int, iters: int = 3) -> dict:
    """Warm device-resident pass: `df` reads a df.cache()-materialized
    subtree, so timed collects run against batches already in HBM — the
    first measurement of actual DEVICE throughput, with the H2D wire
    out of the loop (VERDICT weak #3).  Caller collects once to fill
    the cache before timing.  `{prefix}_jit_misses` (compiles inside
    the warm window — budgeted to 0 by _assert_warm_budget) rides
    along for the dispatch-budget gate."""
    from spark_rapids_tpu.execs.jit_cache import cache_stats

    j0 = cache_stats()
    times, _r = _time_collect(df, "tpu", iters)
    j1 = cache_stats()
    t = statistics.median(times)
    rows_per_s = n_rows / t
    out = {
        f"{prefix}_s_median": round(t, 4),
        f"{prefix}_s_min": round(min(times), 4),
        f"{prefix}_s_max": round(max(times), 4),
        f"{prefix}_rows_per_s": round(rows_per_s, 1),
        f"{prefix}_jit_misses": j1["misses"] - j0["misses"],
    }
    return out


def _check_rows(tpu_tbl, cpu_tbl, float_from: int, key_cols: int):
    got = sorted(zip(*tpu_tbl.to_pydict().values()))
    want = sorted(zip(*cpu_tbl.to_pydict().values()))
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        assert g[:key_cols] == w[:key_cols], (g[:key_cols], w[:key_cols])
        for gv, wv in zip(g[float_from:], w[float_from:]):
            assert abs(gv - wv) <= 1e-6 * max(1.0, abs(wv)), (gv, wv)


def _bench_q1(session, d: str) -> dict:
    """BASELINE config #2's SHAPE (grouped 8-aggregate q1) at a scale
    the bench host generates in seconds; full SF100 needs a real
    cluster-sized host.  Exchange width 1: on a single chip the
    8-way hash exchange is pure dispatch overhead, and on tunneled
    PJRT links every dispatch pays full round-trip latency."""
    from spark_rapids_tpu.config import get_conf

    conf = get_conf()
    key = "spark.rapids.tpu.sql.shuffle.partitions"
    old_sp = conf.get(key)
    conf.set(key, 1)
    try:
        q1_files = make_lineitem(os.path.join(d, "q1"), n_files=2,
                                 with_q1_cols=True)
        df = q1_dataframe(session, q1_files)
        df.collect(engine="tpu")  # warmup
        reset_all_counters()  # per-query occupancy
        sp0 = _spilled_now()
        tpu_ts, tpu_r = _time_collect(df, "tpu", 3)
        # occupancy + sync/speculation counters read BEFORE the tapped
        # breakdown collect, so they reflect only the timed runs
        occ = _pipeline_occupancy("q1_pipeline")
        occ.update(_sync_spec_fields("q1", 3))
        occ.update(_robustness_fields("q1", sp0))
        occ.update(_ledger_fields("q1", 3))
        occ.update(_fusion_fields("q1", 3))
        occ.update(_wire_fields(df, "q1"))
        cpu_ts, cpu_r = _time_collect(df, "cpu", 2)
        breakdown = _stage_breakdown(df, "q1")
        breakdown.update(occ)
        # warm device-resident pass: cache the scan output, re-run the
        # aggregate against HBM-resident batches (no H2D in the loop)
        from spark_rapids_tpu.session import avg, col, count_star, sum_
        from spark_rapids_tpu.exprs.base import lit

        cached = session.read_parquet(*q1_files).cache()
        qty, price = col("l_quantity"), col("l_extendedprice")
        disc, tax = col("l_discount"), col("l_tax")
        warm_df = (cached.where(col("l_shipdate") <= lit(10471))
                   .group_by(col("l_returnflag"), col("l_linestatus"))
                   .agg((sum_(qty), "sum_qty"),
                        (sum_(price), "sum_base_price"),
                        (avg(disc), "avg_disc"),
                        (count_star(), "count_order")))
        try:
            warm_df.collect(engine="tpu")  # fills the cache slot
            # ledger-ONLY reset: the full counter reset would re-arm
            # the --chaos fault schedule inside the warm timed loop,
            # perturbing the steady-state numbers this pass exists for
            _reset_ledger()
            breakdown.update(_bench_warm(warm_df, "q1_warm",
                                         ROWS_PER_FILE * 2))
            # q1's own coarse roofline for the warm window (ISSUE 17
            # acceptance metric; q6's equivalent is the headline
            # hbm_roofline_fraction_warm)
            breakdown["q1_hbm_roofline_fraction_warm"] = _roofline(
                breakdown["q1_warm_rows_per_s"])
            breakdown.update(_ledger_fields("q1_warm", 3))
            # the dispatch-budget regression gate: warm q1 must stay
            # under the conf budget and compile nothing
            _assert_warm_budget("q1_warm", breakdown)
        finally:
            cached.unpersist()
    finally:
        conf.set(key, old_sp)
    _check_rows(tpu_r, cpu_r, float_from=2, key_cols=2)
    tpu_t = statistics.median(tpu_ts)
    cpu_t = statistics.median(cpu_ts)
    out = {
        "q1_tpu_s_per_query": round(tpu_t, 4),
        "q1_cpu_s_per_query": round(cpu_t, 4),
        "q1_vs_cpu": round(cpu_t / tpu_t, 3),
        "q1_rows": ROWS_PER_FILE * 2,
    }
    out.update(_stats(tpu_ts, "q1_tpu"))
    out.update(breakdown)
    return out


def _bench_q3(session, d: str) -> dict:
    """BASELINE config #3's shape: two-table shuffled hash join ->
    grouped aggregate -> top-k, correctness-gated against the CPU
    engine."""
    q3dir = os.path.join(d, "q3")
    os.makedirs(q3dir, exist_ok=True)
    li = make_lineitem(q3dir, n_files=2, with_orderkey=True)
    orders = make_orders(q3dir)
    df = q3_dataframe(session, li, orders)
    df.collect(engine="tpu")  # warmup
    reset_all_counters()  # per-query occupancy
    sp0 = _spilled_now()
    tpu_ts, tpu_r = _time_collect(df, "tpu", 3)
    occ = _pipeline_occupancy("q3_pipeline")  # timed runs only
    occ.update(_sync_spec_fields("q3", 3))
    occ.update(_robustness_fields("q3", sp0))
    occ.update(_ledger_fields("q3", 3))
    occ.update(_fusion_fields("q3", 3))
    # runtime-filter attribution for the timed window + the on/off
    # uploaded-row delta (the wire-shrink the filters buy)
    occ.update(_rf_fields(df, 3))
    occ.update(_wire_fields(df, "q3"))
    cpu_ts, cpu_r = _time_collect(df, "cpu", 2)
    # top-k by float revenue: compare the revenue VALUES (ties may order
    # differently) and the grouped rows' exactness via set inclusion
    got = sorted(tpu_r.to_pydict()["revenue"], reverse=True)
    want = sorted(cpu_r.to_pydict()["revenue"], reverse=True)
    assert len(got) == len(want) == 10, (len(got), len(want))
    for gv, wv in zip(got, want):
        assert abs(gv - wv) <= 1e-6 * max(1.0, abs(wv)), (gv, wv)
    tpu_t = statistics.median(tpu_ts)
    cpu_t = statistics.median(cpu_ts)
    out = {
        "q3_tpu_s_per_query": round(tpu_t, 4),
        "q3_cpu_s_per_query": round(cpu_t, 4),
        "q3_vs_cpu": round(cpu_t / tpu_t, 3),
        "q3_rows": ROWS_PER_FILE * 2 + (1 << 20),
    }
    out.update(_stats(tpu_ts, "q3_tpu"))
    out.update(_stage_breakdown(df, "q3"))
    out.update(occ)
    return out


def _bench_q67(session, d: str) -> dict:
    """BASELINE config #4's shape: grouped aggregate under a ranking
    window under a rank filter under a global sort, correctness-gated
    against the CPU engine."""
    q67dir = os.path.join(d, "q67")
    os.makedirs(q67dir, exist_ok=True)
    paths = make_store_sales(q67dir)
    df = q67_dataframe(session, paths)
    df.collect(engine="tpu")  # warmup
    reset_all_counters()  # per-query occupancy
    sp0 = _spilled_now()
    tpu_ts, tpu_r = _time_collect(df, "tpu", 3)
    occ = _pipeline_occupancy("q67_pipeline")  # timed runs only
    occ.update(_sync_spec_fields("q67", 3))
    occ.update(_robustness_fields("q67", sp0))
    occ.update(_ledger_fields("q67", 3))
    occ.update(_fusion_fields("q67", 3))
    cpu_ts, cpu_r = _time_collect(df, "cpu", 2)
    got = list(zip(*tpu_r.to_pydict().values()))
    want = list(zip(*cpu_r.to_pydict().values()))
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[3] == w[3], (g, w)  # store, rank
        assert abs(g[2] - w[2]) <= 1e-6 * max(1.0, abs(w[2])), (g, w)
    tpu_t = statistics.median(tpu_ts)
    cpu_t = statistics.median(cpu_ts)
    out = {
        "q67_tpu_s_per_query": round(tpu_t, 4),
        "q67_cpu_s_per_query": round(cpu_t, 4),
        "q67_vs_cpu": round(cpu_t / tpu_t, 3),
        "q67_rows": 1 << 21,
    }
    out.update(_stats(tpu_ts, "q67_tpu"))
    out.update(occ)
    return out


def _serving_queries(session, li_paths, orders_path):
    """The serving bench's golden templates.  Every one is
    DETERMINISTIC to the bit: aggregates are exact (sums of
    integer-valued doubles far below 2^53, counts, min/max) and output
    order is pinned by ORDER BY — so the concurrent-vs-serial digest
    gate can demand bit-for-bit equality, which thread-timing-dependent
    float aggregation order could not honor."""
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.session import (
        col,
        count_star,
        max_,
        min_,
        sum_,
    )

    qty = col("l_quantity")
    qa = (session.read_parquet(*li_paths)
          .where(col("l_shipdate") <= lit(10471))
          .group_by(col("l_returnflag"), col("l_linestatus"))
          .agg((sum_(qty), "sum_qty"), (count_star(), "n"),
               (min_(col("l_shipdate")), "d0"),
               (max_(col("l_shipdate")), "d1"))
          .order_by(col("l_returnflag"), col("l_linestatus")))
    li = (session.read_parquet(*li_paths)
          .where(col("l_shipdate") > lit(9500)))
    orders = (session.read_parquet(orders_path)
              .where(col("o_orderdate") < lit(9500)))
    qb = (li.join(orders, left_on=[col("l_orderkey")],
                  right_on=[col("o_orderkey")])
          .group_by(col("o_shippriority"))
          .agg((sum_(qty), "sum_qty"), (count_star(), "n"))
          .order_by(col("o_shippriority")))
    qc = (session.read_parquet(*li_paths)
          .agg((count_star(), "n"),
               (min_(col("l_shipdate")), "d0"),
               (max_(col("l_shipdate")), "d1")))
    return [("qa", qa), ("qb", qb), ("qc", qc)]


def _serving_phase(n_sessions: int, n_tenants: int, li, orders,
                   digests: dict, conf_factory, sharing: bool,
                   cancel_rate: float = 0.0) -> dict:
    """One full concurrent serving pass (warm + measured repeat) with
    cross-tenant sharing on or off: the A/B unit of the serving bench.
    Resets the scheduler/plan-cache/work-share/upload counters at
    phase start, runs every session's warm pass, arms the measured
    window at the barrier, and returns the phase's latency set plus
    every counter surface (docs/work_sharing.md).

    ``cancel_rate`` > 0 arms the cancellation storm on the measured
    window: each repeat execution is perturbed with probability P
    (seeded per session; half mid-flight session.cancel(), half a
    short per-query deadline) and one extra POISON tenant crash-loops
    into its circuit breaker concurrently — surviving digests stay
    gated, and the post-phase residency gauges are asserted back at
    baseline (docs/robustness.md)."""
    import random as _random
    import threading

    from spark_rapids_tpu import trace as _trace
    from spark_rapids_tpu.columnar.transfer import (
        reset_upload_stats,
        upload_stats,
    )
    from spark_rapids_tpu.config import set_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.execs.jit_cache import cache_stats
    from spark_rapids_tpu.robustness import faults
    from spark_rapids_tpu.robustness import lock_tracker as _locks
    from spark_rapids_tpu.serving import cancel as _cancel
    from spark_rapids_tpu.serving import plan_cache as _plan_cache
    from spark_rapids_tpu.serving import scheduler as _scheduler
    from spark_rapids_tpu.serving import work_share as _ws
    from spark_rapids_tpu.session import TpuSession

    repeat_iters = 3
    _scheduler.reset()
    _plan_cache.reset_stats()
    _ws.reset()
    _cancel.reset()
    reset_upload_stats()
    if _CHAOS:
        # fresh deterministic schedule per phase so the nth-call
        # policies fire in BOTH the sharing-off and sharing-on arms
        faults.install(CHAOS_SPEC, forced=True)
    lat_lock = threading.Lock()
    latencies: list = []
    mismatches: list = []
    prepared: list = []  # (session, {name: PreparedQuery})
    # the main thread is a barrier party: it arms the measured
    # window's instrumentation strictly AFTER every warm pass and
    # strictly BEFORE any repeat execution
    warm_done = threading.Barrier(n_sessions + 1)
    go_repeat = threading.Event()
    DEADLINE_KEY = "spark.rapids.tpu.serving.deadlineMs"

    def run_session(i: int) -> None:
        pqs = {}
        conf = None
        session = None
        try:
            conf = conf_factory(sharing=sharing)
            set_conf(conf)
            session = TpuSession(conf, tenant=f"t{i % n_tenants}")
            for name, df in _serving_queries(session, li, orders):
                pqs[name] = session.prepare(df)
            with lat_lock:
                prepared.append((session, pqs))
            # warm pass: every template once (prepare already
            # lowered; this compiles + validates), digest-gated
            for name, pq in pqs.items():
                r = pq.execute()
                if table_digest(r) != digests[name]:
                    with lat_lock:
                        mismatches.append((i, name, "warm"))
        except BaseException as e:  # noqa: BLE001 — reported below
            with lat_lock:
                mismatches.append((i, "session-error", repr(e)))
            pqs = {}
        finally:
            # ALWAYS reach the barrier: a dead party would leave
            # the main thread blocked in warm_done.wait() forever
            # instead of failing with the recorded error
            warm_done.wait()
        if not pqs:
            return
        go_repeat.wait()
        # measured REPEAT pass: pure cache hits, timed.  Under the
        # storm, a seeded per-session RNG perturbs executions; the
        # digest gate applies to every execution that SURVIVES.  The
        # deadline value is FIXED per session (and restored to the
        # constructed conf's explicit 0.0): the serving deadline is
        # conf-fingerprint-keyed like every conf, so each session pays
        # at most ONE plan-cache re-key per template (its single
        # deadline fingerprint) for the whole window — bounded below
        # by the scoped purity assert
        rng = _random.Random(9000 + i)
        dl_ms = round(rng.uniform(2.0, 20.0), 2)
        try:
            for _ in range(repeat_iters):
                for name, pq in pqs.items():
                    mode = None
                    if cancel_rate > 0:
                        roll = rng.random()
                        if roll < cancel_rate / 2:
                            mode = "deadline"
                        elif roll < cancel_rate:
                            mode = "cancel"
                    canceller = None
                    if mode == "deadline":
                        conf.set(DEADLINE_KEY, dl_ms)
                    elif mode == "cancel":
                        canceller = threading.Timer(
                            rng.uniform(0.0, 0.02), session.cancel)
                        canceller.start()
                    try:
                        t0 = time.perf_counter()
                        r = pq.execute()
                        dt = time.perf_counter() - t0
                        if table_digest(r) != digests[name]:
                            with lat_lock:
                                mismatches.append((i, name, "repeat"))
                        if mode is None:
                            # only unperturbed executions are latency
                            # samples — a shed query's 2ms would skew
                            # p50 optimistically
                            with lat_lock:
                                latencies.append(dt)
                    except _cancel.QueryCancelled:
                        pass  # counted process-wide by cancel.stats()
                    finally:
                        if mode == "deadline":
                            conf.set(DEADLINE_KEY, 0.0)
                        if canceller is not None:
                            # fired or defused, then joined: a late
                            # cancel must not bleed into the next
                            # execution's token
                            canceller.cancel()
                            canceller.join()
        except BaseException as e:  # noqa: BLE001 — reported below
            with lat_lock:
                mismatches.append((i, "repeat-error", repr(e)))

    poison_report: dict = {}

    def run_poison() -> None:
        """The crash-looping tenant: a prepared scan whose backing
        file is deleted, executed repeatedly under a 3-failure
        breaker — quarantine must engage within failureThreshold
        queries while the real tenants keep serving."""
        from spark_rapids_tpu.serving.cancel import TenantQuarantined

        conf = conf_factory(sharing=False)
        conf.set("spark.rapids.tpu.serving.breaker.failureThreshold",
                 3)
        conf.set("spark.rapids.tpu.serving.breaker.cooldownMs",
                 60_000.0)
        set_conf(conf)
        session = TpuSession(conf, tenant="poison")
        pdir = tempfile.mkdtemp(prefix="poison_")
        ppath = os.path.join(pdir, "p.parquet")
        import pyarrow as pa
        import pyarrow.parquet as pq_

        pq_.write_table(pa.table({"x": [1, 2, 3]}), ppath)
        df = session.read_parquet(ppath)
        os.remove(ppath)  # every execution now dies in the scan
        failures = quarantined = 0
        for _ in range(10):
            try:
                df.collect(engine="tpu")
            except TenantQuarantined:
                quarantined += 1
            except Exception:  # noqa: BLE001 — the poison crash
                failures += 1
        poison_report.update(
            {"failures": failures, "quarantined": quarantined})

    threads = [threading.Thread(target=run_session, args=(i,),
                                name=f"serve-bench-{i}")
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    warm_done.wait()
    # measured-window instrumentation, armed while every session
    # sits at go_repeat: plan-cache stats reset (repeats must show
    # hit rate 1.0), jit snapshot (zero misses on hits), tracer on
    # (zero query.plan/tag/lower spans on hits), work-share window
    # snapshot (repeats with sharing on must be pure result-cache
    # hits)
    _plan_cache.reset_stats()
    _scheduler.reset()  # fresh wait ring for the measured window
    # runtime lock-order tracker over the measured window: the N-way
    # repeat pass (and the cancellation storm's unwinds) is the most
    # contended interleaving the engine sees — a cycle here is a
    # deadlock a production fleet would eventually hit
    _locks.install(forced=True)
    jit0 = cache_stats()
    ws0 = _ws.stats()
    cancel0 = _cancel.stats()
    poison_thread = None
    if cancel_rate > 0:
        poison_thread = threading.Thread(target=run_poison,
                                         name="serve-bench-poison")
    # scrape-under-storm (docs/ops_plane.md): the ops plane is forced
    # on and a scraper hammers /metrics CONCURRENTLY with the measured
    # window.  Every monotone eventlog counter must never step
    # backwards across successive scrapes, and the digest gate below
    # doubles as the zero-impact proof — the serial reference digests
    # were computed with the plane off, so obs on vs off stays
    # bit-identical by the same assert
    from spark_rapids_tpu import obs as _obs
    from spark_rapids_tpu.eventlog import MONOTONIC_COUNTERS
    from spark_rapids_tpu.obs import metrics as _om

    obs_owned = not _obs.is_enabled()
    if obs_owned:
        _obs.start(port=0)  # forced: sessions' sync_conf can't stop it
    scrape_stop = threading.Event()
    scrape_report = {"scrapes": 0, "violations": [], "errors": 0}

    def run_scraper() -> None:
        import urllib.request

        base = f"http://127.0.0.1:{_obs.plane().port}"
        mono = tuple(MONOTONIC_COUNTERS)
        prev: dict = {}
        while True:
            try:
                body = urllib.request.urlopen(
                    base + "/metrics", timeout=5).read().decode()
                parsed = _om.parse_openmetrics(body)
                for key in mono:
                    v = _om.scrape_value(
                        parsed, _om.counter_metric_name(key))
                    if v is None:
                        continue
                    if key in prev and v < prev[key]:
                        scrape_report["violations"].append(
                            (key, prev[key], v))
                    prev[key] = v
                scrape_report["scrapes"] += 1
            except Exception:  # noqa: BLE001 — scrape, don't perturb
                scrape_report["errors"] += 1
            if scrape_stop.wait(0.02):
                return

    scraper = threading.Thread(target=run_scraper,
                               name="serve-bench-scraper")
    _trace.clear()
    _trace.enable()
    wall0 = time.perf_counter()
    go_repeat.set()
    scraper.start()
    if poison_thread is not None:
        poison_thread.start()
    for t in threads:
        t.join()
    if poison_thread is not None:
        poison_thread.join()
    wall = time.perf_counter() - wall0
    scrape_stop.set()
    scraper.join()
    if obs_owned:
        _obs.stop()
    assert scrape_report["scrapes"] >= 1, \
        "the storm scraper never completed a scrape"
    assert not scrape_report["violations"], (
        "monotone counter stepped backwards under concurrent "
        f"scraping: {scrape_report['violations']}")
    _trace.disable()
    spans = _trace.snapshot()
    _trace.clear()
    lock_agg = _locks.aggregate_stats()
    lock_graph = _locks.order_graph()
    _locks.disarm()
    assert lock_agg["cycles"] == 0, (
        f"lock-order cycle under the serving storm: {lock_graph}")
    jit1 = cache_stats()
    pc = _plan_cache.stats()
    sched = _scheduler.scheduler_stats()
    ws1 = _ws.stats()
    up = upload_stats()

    # -- streaming gate: stream == collect, to the bit ---------- #
    stream_ok = False
    if prepared and not mismatches:
        import pyarrow as pa

        _s_last, pqs_last = prepared[-1]
        batches = list(pqs_last["qa"].execute_stream())
        stream_tbl = pa.Table.from_batches(batches)
        stream_ok = table_digest(stream_tbl) == digests["qa"]

    # event logs hold every query before the dir is reported
    for session, _p in prepared:
        if session.event_log_path is not None:
            _ = session.history.events

    assert not mismatches, (
        f"serving results diverged from serial digests "
        f"(sharing={sharing}): {mismatches}")
    assert stream_ok, "streamed result digest != collect digest"
    plan_spans = sum(1 for e in spans
                     if e.name in ("query.plan", "query.tag",
                                   "query.lower"))
    n_execs = len(latencies)
    latencies.sort()

    def q(p: float) -> float:
        return latencies[min(n_execs - 1,
                             int(round(p * (n_execs - 1))))]

    cancel1 = _cancel.stats()
    storm = {k: cancel1[k] - cancel0[k] for k in cancel1}
    if cancel_rate > 0:
        # the storm must actually have shed something, quarantine must
        # have engaged within the failure threshold, and the unwinds
        # must leave NO residency behind: permits free, no live stage
        # threads, no in-flight scan shares, empty admission queue —
        # a cancelled query is an outcome, not a leak
        assert storm["cancelled"] + storm["deadline_exceeded"] >= 1, \
            storm
        assert poison_report.get("quarantined", 0) >= 1, poison_report
        assert poison_report.get("failures", 99) <= 3, poison_report
        from spark_rapids_tpu.trace.telemetry import sample_now

        gauges = sample_now()
        for g in ("semaphore.in_use", "pipeline.stage_threads",
                  "scan.inflight", "admission.running",
                  "admission.waiting"):
            assert gauges[g] == 0, (g, gauges)
    window = ws1["result_hits"] - ws0["result_hits"] \
        + ws1["result_misses"] - ws0["result_misses"]
    hits = ws1["result_hits"] - ws0["result_hits"]
    return {
        "qps": round(n_execs / wall, 2),
        "p50_ms": round(q(0.50) * 1e3, 1),
        "p99_ms": round(q(0.99) * 1e3, 1),
        "n_execs": n_execs,
        "sched": sched,
        "pc": pc,
        # the storm's plan-cache purity bound: each session's fixed
        # deadline fingerprint re-keys each of its prepared templates
        # at most once (set(0.0) restores the constructed conf's
        # explicit base fingerprint)
        "pc_miss_bound": sum(len(p) for _s, p in prepared),
        "plan_spans": plan_spans,
        "jit_misses": jit1["misses"] - jit0["misses"],
        # per-PHASE device-work evidence (warm + repeat): decoded
        # rows/units and tapped H2D wire bytes — the sub-linearity
        # story is these staying ~flat in sessions with sharing on
        "scan_rows_decoded": ws1["scan_rows_decoded"],
        "scan_units_decoded": ws1["scan_units_decoded"],
        "scan_units_shared": ws1["scan_units_shared"],
        "scan_subscribes": ws1["scan_subscribes"],
        "upload_bytes": up["wire_bytes"],
        # measured-WINDOW result-cache verdict: hit rate over the
        # repeat pass alone
        "result_cache_window_hits": hits,
        "result_cache_hit_rate":
            round(hits / window, 3) if window else 0.0,
        "result_inserts": ws1["result_inserts"],
        # cancellation-storm outcome counters (zero without
        # --cancel-rate): the serving tier's blast-radius story
        "cancelled_count": storm["cancelled"],
        "deadline_exceeded_count": storm["deadline_exceeded"],
        "breaker_trips": storm["breaker_trips"],
        "quarantined_count": storm["quarantined"],
        # measured-window lock health (runtime tracker, armed for the
        # repeat pass): real contention on the engine's registry
        # mutexes and the longest single hold — the HC014 surface,
        # observed under the storm instead of inferred
        "lock_acquisitions": lock_agg["acquisitions"],
        "lock_contention_waits": lock_agg["contention_waits"],
        "max_lock_hold_ms": lock_agg["max_hold_ms"],
        "admission_shed": sched.get("shed", 0),
        "poison": poison_report or None,
        # scrape-under-storm outcome: /metrics scrapes completed
        # concurrently with this measured window (monotonicity and
        # the digest gates asserted above)
        "obs_scrapes": scrape_report["scrapes"],
        "obs_scrape_errors": scrape_report["errors"],
    }


def _bench_serving(n_sessions: int, n_tenants: int) -> dict:
    """The multi-session serving bench (bench.py --sessions N
    [--tenants K]): N concurrent sessions across K tenants drive the
    deterministic golden templates through the serving tier — admission
    control + prepared-plan cache + cross-tenant work sharing +
    per-session event logs — and the output makes 'heavy traffic' a
    measured claim:

    - serving_qps, serving_p50_ms / serving_p99_ms over the measured
      window (all sessions, all templates);
    - admission_wait_p99_ms from the scheduler's wait ring;
    - plan_cache_hit_rate over the REPEAT-template pass, asserted 1.0,
      with serving_repeat_plan_spans (query.plan/tag/lower spans seen
      during that pass — asserted 0: hits skip lowering entirely) and
      serving_repeat_jit_misses (asserted 0: cached trees re-use their
      compiled programs);
    - the sharing A/B (docs/work_sharing.md): the whole concurrent
      pass runs TWICE, sharing off then on (skip the on-arm with
      --no-sharing), emitting serving_qps_sharing_{on,off},
      shared_scan_dedup_ratio (decoded rows off/on, tapped counter),
      result_cache_hit_rate (repeat window, asserted 1.0 with sharing
      on) and the upload-byte totals proving device work scales
      sub-linearly in sessions;
    - a bit-for-bit digest gate: every concurrent result in BOTH arms
      must hash identical to the serial sharing-off run's, and one
      streamed fetch must hash identical to its collect — under
      --chaos too (the deterministic fault schedule re-arms per arm).
    """
    from spark_rapids_tpu.config import TpuConf, set_conf
    from spark_rapids_tpu.eventlog import table_digest
    from spark_rapids_tpu.robustness import faults
    from spark_rapids_tpu.serving import work_share as _ws
    from spark_rapids_tpu.session import TpuSession

    sharing_on = "--no-sharing" not in sys.argv[1:]
    max_concurrent = max(1, min(2, n_sessions))
    store_budget = _int_flag("--store-budget")
    cancel_rate = _float_flag("--cancel-rate")
    if not 0.0 <= cancel_rate <= 1.0:
        raise SystemExit("bench.py: --cancel-rate takes 0..1")
    ev_dir = None
    if "--no-eventlog" not in sys.argv[1:]:
        ev_dir = _eventlog_dir()

    def _conf(extra=None, sharing=False) -> TpuConf:
        over = {
            "spark.rapids.tpu.serving.maxConcurrent": max_concurrent,
            "spark.rapids.tpu.serving.queueDepth": 4 * n_sessions + 8,
            # admission slots must not outnumber device permits, or the
            # scheduler clamp makes maxConcurrent a dead knob here
            "spark.rapids.tpu.sql.concurrentTpuTasks":
                max(2, max_concurrent),
            "spark.rapids.tpu.serving.sharing.enabled": sharing,
        }
        if store_budget:
            # --store-budget N: shrink the spill-store budgets so
            # cached shared results are forced through the host->disk
            # spill/restore path during the bench itself
            over["spark.rapids.tpu.memory.hbm.budgetBytes"] = \
                store_budget
            over["spark.rapids.tpu.memory.host.spillStorageSize"] = \
                store_budget
        if ev_dir is not None:
            over["spark.rapids.tpu.eventLog.enabled"] = True
            over["spark.rapids.tpu.eventLog.dir"] = ev_dir
        over.update(extra or {})
        return TpuConf(over)

    if store_budget:
        # the store snapshots budgets at construction: start fresh so
        # the serving sessions' shrunken budgets actually apply
        from spark_rapids_tpu.memory.store import reset_store

        reset_store()

    with tempfile.TemporaryDirectory(prefix="serve_bench_") as d:
        li = make_lineitem(d, n_files=2, with_q1_cols=True,
                           with_orderkey=True)
        orders = make_orders(d)

        # -- serial reference: digests + latency baseline (sharing
        # off, fault-free — THE ground truth both arms must match) -- #
        serial_conf = _conf(
            {"spark.rapids.tpu.serving.maxConcurrent": 0})
        set_conf(serial_conf)
        s0 = TpuSession(serial_conf)
        digests = {}
        serial_ts = []
        for name, df in _serving_queries(s0, li, orders):
            df.collect(engine="tpu")  # warm compile caches
            t0 = time.perf_counter()
            r = df.collect(engine="tpu")
            serial_ts.append(time.perf_counter() - t0)
            digests[name] = table_digest(r)

        try:
            off = _serving_phase(n_sessions, n_tenants, li, orders,
                                 digests, _conf, sharing=False,
                                 cancel_rate=cancel_rate)
            on = None
            if sharing_on:
                on = _serving_phase(n_sessions, n_tenants, li, orders,
                                    digests, _conf, sharing=True,
                                    cancel_rate=cancel_rate)
        finally:
            if _CHAOS:
                faults.disarm()
            _ws.reset()

    # headline fields come from the DEFAULT posture (sharing on unless
    # --no-sharing): the serving round measures the fleet as shipped
    head = on if on is not None else off
    out = {
        "metric": "serving_bench",
        "value": head["qps"],
        "unit": "qps",
        "serving_sessions": n_sessions,
        "serving_tenants": n_tenants,
        "serving_max_concurrent": max_concurrent,
        "serving_sharing": bool(on is not None),
        "serving_qps": head["qps"],
        "serving_p50_ms": head["p50_ms"],
        "serving_p99_ms": head["p99_ms"],
        "serving_executions": head["n_execs"],
        "serial_p50_ms": round(
            statistics.median(serial_ts) * 1e3, 1),
        "admission_wait_p99_ms": head["sched"]["wait_p99_ms"],
        "admission_total_wait_ms": head["sched"]["total_wait_ms"],
        "admitted": head["sched"]["admitted"],
        "rejected": head["sched"]["rejected"],
        "admission_coalesced": head["sched"]["coalesced"],
        "plan_cache_hit_rate": head["pc"]["hit_rate"],
        "plan_cache_hits": head["pc"]["hits"],
        "plan_cache_misses": head["pc"]["misses"],
        "serving_repeat_plan_spans": head["plan_spans"],
        "serving_repeat_jit_misses": head["jit_misses"],
        "serving_qps_sharing_off": off["qps"],
        "serving_upload_bytes_sharing_off": off["upload_bytes"],
        "serving_scan_rows_decoded_sharing_off":
            off["scan_rows_decoded"],
        "digests_match": True,
        "stream_matches_collect": True,
        # cancellation-storm counters (the headline phase's; zero
        # without --cancel-rate — docs/robustness.md)
        "cancelled_count": head["cancelled_count"],
        "deadline_exceeded_count": head["deadline_exceeded_count"],
        "breaker_trips": head["breaker_trips"],
        "quarantined_count": head["quarantined_count"],
        "admission_shed": head["admission_shed"],
        # lock-tracker surface (tracker armed for every measured
        # window; the phase already asserted zero cycles)
        "lock_acquisitions": head["lock_acquisitions"],
        "lock_contention_waits": head["lock_contention_waits"],
        "max_lock_hold_ms": head["max_lock_hold_ms"],
        # scrape-under-storm (docs/ops_plane.md): concurrent /metrics
        # scrapes over the measured window, monotone counters and the
        # obs-on digests bit-identical to the obs-off serial reference
        # — both asserted inside the phase
        "obs_scrapes": head["obs_scrapes"],
        "obs_scrape_monotone": True,
    }
    if cancel_rate > 0:
        out["cancel_rate"] = cancel_rate
        out["poison"] = head["poison"]
        if on is not None:
            # the off arm's storm outcome too: its ~N×-slower
            # executions absorb mid-flight cancels the on arm's
            # near-instant result-cache hits outrun (a completed
            # query always wins the cooperative race)
            for k in ("cancelled_count", "deadline_exceeded_count",
                      "breaker_trips", "quarantined_count"):
                out[f"{k}_sharing_off"] = off[k]
    if _CHAOS:
        out["chaos"] = CHAOS_SPEC
    if store_budget:
        out["store_budget_bytes"] = store_budget
    if on is not None:
        out.update({
            "serving_qps_sharing_on": on["qps"],
            "serving_upload_bytes_sharing_on": on["upload_bytes"],
            "serving_scan_rows_decoded_sharing_on":
                on["scan_rows_decoded"],
            "shared_scan_dedup_ratio": round(
                off["scan_rows_decoded"]
                / max(1, on["scan_rows_decoded"]), 2),
            "result_cache_hit_rate": on["result_cache_hit_rate"],
            "result_cache_window_hits":
                on["result_cache_window_hits"],
            "scan_units_shared": on["scan_units_shared"],
            "scan_subscribes": on["scan_subscribes"],
        })
    if ev_dir is not None:
        out["eventlog"] = ev_dir
    # the acceptance contract, enforced where it is measured: repeats
    # are pure hits that lowered nothing and compiled nothing — and
    # with sharing on, pure RESULT-cache hits that out-run and
    # out-dedup the sharing-off arm.  Under the storm the deadline
    # conf re-keys the plan cache (conf-fingerprint keying, by
    # design): each session pays at most ONE miss PER TEMPLATE — its
    # single fixed deadline fingerprint — so the purity gate becomes
    # that bound; programs are structural, so zero jit misses holds
    # regardless
    for phase in (off,) if on is None else (off, on):
        if cancel_rate > 0:
            assert phase["pc"]["misses"] <= phase["pc_miss_bound"], \
                (phase["pc"], phase["pc_miss_bound"])
        else:
            assert phase["pc"]["hit_rate"] == 1.0, phase["pc"]
            assert phase["plan_spans"] == 0, phase["plan_spans"]
        assert phase["jit_misses"] == 0, phase
    if on is not None:
        if cancel_rate == 0:
            assert on["result_cache_hit_rate"] == 1.0, on
            assert off["scan_rows_decoded"] >= \
                2 * max(1, on["scan_rows_decoded"]), (off, on)
            assert on["qps"] > off["qps"], (on["qps"], off["qps"])
        else:
            # under the storm both arms shed a seeded fraction of
            # their executions, deadline-fingerprint executions
            # bypass the result cache, and a shed query never offers
            # its result back — so the exact purity/2x/qps gates are
            # no longer stable claims.  Sharing must still ENGAGE:
            # hits present, strictly less device work than the off
            # arm (decoded rows AND upload bytes)
            assert on["result_cache_window_hits"] >= 1, on
            assert off["scan_rows_decoded"] > \
                on["scan_rows_decoded"], (off, on)
        assert off["upload_bytes"] > on["upload_bytes"], (off, on)
    return out


def _bench_scaled(scale_rows: int) -> dict:
    """The scaling-curve round (ROADMAP #1: bench scale was ~SF1
    against milestones specced SF10+): `bench.py --scale-rows N` runs
    q6 at N rows (~63M = SF10 lineitem) and q1 at max(N // 3, 20M)
    rows, each with the full per-stage attribution — stage breakdown,
    blocking syncs, spills under pressure, device-ledger programs and
    the wire-compression on/off byte delta — so BENCH_r06+ can prove
    the codec + OOC machinery under real pressure instead of unit
    tests.  Correctness stays gated against the CPU engine (one
    reference iteration; a fast wrong answer at scale is still not a
    benchmark)."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.session import TpuSession

    n_files6 = max(1, -(-scale_rows // ROWS_PER_FILE))
    q1_rows = max(scale_rows // 3, 20 * 10**6)
    n_files1 = max(1, -(-q1_rows // ROWS_PER_FILE))
    out: dict = {
        "metric": "scaling_curve",
        "value": scale_rows,
        "unit": "rows",
        "scale_rows": scale_rows,
        "q6_scaled_rows": n_files6 * ROWS_PER_FILE,
        "q1_scaled_rows": n_files1 * ROWS_PER_FILE,
    }
    conf = get_conf()
    conf.set("spark.rapids.tpu.trace.ledger.enabled", True)
    session = TpuSession()
    with tempfile.TemporaryDirectory(prefix="qscale_") as d:
        paths = make_lineitem(d, n_files=n_files6)
        df = q6_dataframe(session, paths)
        df.collect(engine="tpu")  # warmup
        link = _link_probe()
        reset_all_counters()
        sp0 = _spilled_now()
        tpu_ts, tpu_r = _time_collect(df, "tpu", 3)
        occ = _pipeline_occupancy("q6_scaled_pipeline")
        occ.update(_sync_spec_fields("q6_scaled", 3,
                                     with_hit_rate=False))
        occ.update(_robustness_fields("q6_scaled", sp0))
        occ.update(_ledger_fields("q6_scaled", 3))
        occ.update(_fusion_fields("q6_scaled", 3))
        occ.update(_wire_fields(df, "q6_scaled"))
        occ.update(_stage_breakdown(df, "q6_scaled"))
        cpu_ts, cpu_r = _time_collect(df, "cpu", 1)
        got = tpu_r.to_pydict()["revenue"][0]
        want = cpu_r.to_pydict()["revenue"][0]
        assert abs(got - want) <= 1e-6 * max(1.0, abs(want)), (got, want)
        tpu_t = statistics.median(tpu_ts)
        out.update(_stats(tpu_ts, "q6_scaled_tpu"))
        out.update({
            "q6_scaled_tpu_s_per_query": round(tpu_t, 4),
            "q6_scaled_cpu_s_per_query": round(cpu_ts[0], 4),
            "q6_scaled_vs_cpu": round(cpu_ts[0] / tpu_t, 3),
            "q6_scaled_rows_per_s": round(
                n_files6 * ROWS_PER_FILE / tpu_t, 1),
        })
        out.update(occ)
        out.update(link)

        # q1 at >= 20M rows: the grouped 8-aggregate under the same
        # exchange-width-1 discipline as the plain round
        key = "spark.rapids.tpu.sql.shuffle.partitions"
        old_sp = conf.get(key)
        conf.set(key, 1)
        try:
            os.makedirs(os.path.join(d, "q1"), exist_ok=True)
            q1_files = make_lineitem(os.path.join(d, "q1"),
                                     n_files=n_files1,
                                     with_q1_cols=True)
            df1 = q1_dataframe(session, q1_files)
            df1.collect(engine="tpu")  # warmup
            reset_all_counters()
            sp0 = _spilled_now()
            tpu_ts, tpu_r = _time_collect(df1, "tpu", 3)
            occ = _pipeline_occupancy("q1_scaled_pipeline")
            occ.update(_sync_spec_fields("q1_scaled", 3))
            occ.update(_robustness_fields("q1_scaled", sp0))
            occ.update(_ledger_fields("q1_scaled", 3))
            occ.update(_fusion_fields("q1_scaled", 3))
            occ.update(_wire_fields(df1, "q1_scaled"))
            occ.update(_stage_breakdown(df1, "q1_scaled"))
            cpu_ts, cpu_r = _time_collect(df1, "cpu", 1)
            _check_rows(tpu_r, cpu_r, float_from=2, key_cols=2)
            tpu_t = statistics.median(tpu_ts)
            out.update(_stats(tpu_ts, "q1_scaled_tpu"))
            out.update({
                "q1_scaled_tpu_s_per_query": round(tpu_t, 4),
                "q1_scaled_cpu_s_per_query": round(cpu_ts[0], 4),
                "q1_scaled_vs_cpu": round(cpu_ts[0] / tpu_t, 3),
            })
            out.update(occ)
        finally:
            conf.set(key, old_sp)
    return out


def _eventlog_dir() -> str:
    """Where this round's event log lands: --eventlog DIR, else
    $BENCH_EVENTLOG_DIR, else ./bench_eventlog.  On by default so
    every BENCH round is self-documenting — the per-query records
    (plan, settled operator metrics, counter deltas) reload via
    `python -m spark_rapids_tpu.tools.history report` for cross-round
    regression triage (docs/eventlog.md); --no-eventlog opts out."""
    argv = sys.argv[1:]
    if "--eventlog" in argv:
        i = argv.index("--eventlog")
        if i + 1 >= len(argv) or argv[i + 1].startswith("-"):
            # silently falling back would write the round's log
            # somewhere the operator didn't ask for
            raise SystemExit(
                "bench.py: --eventlog requires a directory operand")
        return argv[i + 1]
    return os.environ.get("BENCH_EVENTLOG_DIR", "bench_eventlog")


def _flag_operand(name: str, conv):
    """Parse `name VALUE` from argv through `conv` (int/float);
    absent flag -> conv's zero, malformed operand -> SystemExit."""
    argv = sys.argv[1:]
    if name not in argv:
        return conv(0)
    i = argv.index(name)
    try:
        return conv(argv[i + 1])
    except (IndexError, ValueError):
        raise SystemExit(
            f"bench.py: {name} requires a {conv.__name__} operand")


def _int_flag(name: str) -> int:
    return _flag_operand(name, int)


def _float_flag(name: str) -> float:
    return _flag_operand(name, float)


def _bench_cold_start(n: int) -> dict:
    """bench.py --cold-start N: the restart-cost artifact
    (docs/warm_start.md).  Two unmeasured children populate + prime
    one persist directory, then N measured WARM children run against
    it and N EMPTY children against fresh directories — cold wall
    p50/p99, jit misses, compile counts and persist hit rate both
    ways, a digest gate across every child, and the p50 speedup the
    warm-start cache buys a restarted fleet."""
    from spark_rapids_tpu.tools import cold_start as cs

    data = tempfile.mkdtemp(prefix="tpu-coldstart-data-")
    warm_dir = tempfile.mkdtemp(prefix="tpu-coldstart-warm-")
    cs.make_fixture(data)
    for _ in range(2):  # populate the program store, prime XLA cache
        cs.run_subprocess(data, warm_dir)
    warm = [cs.run_subprocess(data, warm_dir) for _ in range(n)]
    empty = [cs.run_subprocess(
        data, tempfile.mkdtemp(prefix="tpu-coldstart-empty-"))
        for _ in range(n)]

    def fold(runs, label):
        walls = sorted(r["wall_ms"] for r in runs)
        return {
            f"{label}_cold_p50_ms": round(
                statistics.median(walls), 3),
            f"{label}_cold_p99_ms": round(
                walls[min(len(walls) - 1,
                          int(0.99 * len(walls)))], 3),
            f"{label}_cold_jit_misses": max(
                r["jit_misses"] for r in runs),
            f"{label}_compiles": max(r["compiles"] for r in runs),
            f"{label}_persist_hit_rate": min(
                r["persist"]["hit_rate"] for r in runs),
        }

    digests = {r["digest"] for r in warm} | {r["digest"] for r in empty}
    out = {"metric": "cold_start_bench", "children": n,
           "digest_ok": len(digests) == 1}
    out.update(fold(warm, "warm"))
    out.update(fold(empty, "empty"))
    if out["warm_cold_p50_ms"]:
        out["cold_p50_speedup"] = round(
            out["empty_cold_p50_ms"] / out["warm_cold_p50_ms"], 2)
    return out


def _bench_multichip(n_devices: int) -> dict:
    """The MULTICHIP round: run dryrun_multichip on the virtual
    N-device CPU mesh with stderr captured at the fd level (XLA's AOT
    warnings are C-level glog lines Python redirection cannot see),
    then fold the bench fields + a noise-FILTERED tail into one
    artifact dict — the MULTICHIP_r*.json shape, now carrying signal
    instead of machine-feature spam."""
    import tempfile

    import __graft_entry__ as graft

    saved_fd = os.dup(2)
    tmp = tempfile.TemporaryFile(mode="w+b")
    ok = True
    err = None
    bench: dict = {"metric": "multichip_bench", "n_devices": n_devices}
    try:
        os.dup2(tmp.fileno(), 2)
        try:
            bench = graft.dryrun_multichip(n_devices)
        except Exception as e:
            # a failed gate still emits the artifact: rc=1 plus the
            # captured (filtered) stderr IS the diagnostic
            ok = False
            err = f"{type(e).__name__}: {e}"
            import traceback

            traceback.print_exc()  # lands in the captured tail
    finally:
        os.dup2(saved_fd, 2)
        os.close(saved_fd)
        tmp.seek(0)
        tail = tmp.read().decode(errors="replace")[-65536:]
        tmp.close()
    out = dict(bench)
    out.update({
        "n_devices": n_devices,
        "rc": 0 if ok else 1,
        "ok": ok,
        "skipped": False,
        "tail": graft.filter_stderr_noise(tail)[-4000:],
    })
    if err is not None:
        out["error"] = err
    return out


def _bench_mesh_serving(n_devices: int, n_sessions: int) -> dict:
    """bench.py --multichip N --sessions K: pod-scale serving — K
    concurrent sessions drive the milestone templates (agg / join /
    sort) through the serving tier ON an N-device virtual mesh with
    mesh-resident execution enabled (docs/pod_serving.md).  Emits
    `serving_qps_per_chip` and asserts the tentpole's contracts where
    they are measured:

    - every concurrent result hashes bit-identical (canonical digest)
      to the SERIAL SINGLE-DEVICE reference;
    - `serving.mesh.enabled=false` on the same mesh is asserted
      bit-for-bit identical too (the flag-off path is untouched);
    - steady state is device-born: the measured window's tapped
      `placement.host_uploads` counter is asserted ZERO (control-plane
      uploads tallied separately);
    - repeats are pure plan-cache hits (rate 1.0) that compile nothing
      (zero jit-cache misses).
    """
    import threading

    from spark_rapids_tpu.platform import pin_cpu_platform

    cpu_devs = pin_cpu_platform(n_devices)

    import __graft_entry__ as graft
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.config import TpuConf, set_conf
    from spark_rapids_tpu.execs.jit_cache import cache_stats
    from spark_rapids_tpu.parallel import make_mesh
    from spark_rapids_tpu.parallel import placement as _placement
    from spark_rapids_tpu.parallel.mesh import set_active_mesh
    from spark_rapids_tpu.serving import plan_cache as _plan_cache
    from spark_rapids_tpu.serving import scheduler as _scheduler
    from spark_rapids_tpu.session import TpuSession, col, count, sum_
    from spark_rapids_tpu.shuffle.transport import SHUFFLE_TRANSPORT

    mesh = make_mesh(n_devices, devices=cpu_devs)
    rows = int(os.environ.get("MESH_SERVING_ROWS", 1 << 14))
    rng = np.random.default_rng(7)
    fact = pa.table({
        "k": rng.integers(0, 1024, rows).astype(np.int64),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })
    dim = pa.table({
        "k": np.arange(1024, dtype=np.int64),
        "w": np.arange(1024, dtype=np.int64) * 3,
    })
    sort_t = pa.table({
        "k": rng.permutation(rows).astype(np.int64),
        "v": np.arange(rows, dtype=np.int64),
    })

    def templates(s):
        return [
            ("agg", s.create_dataframe(fact)
             .group_by(col("k"))
             .agg((sum_(col("v")), "s"), (count(col("v")), "c"))),
            ("join", s.create_dataframe(fact)
             .join(s.create_dataframe(dim), on="k", how="inner")),
            ("sort", s.create_dataframe(sort_t).order_by(col("k"))),
        ]

    def _conf(transport: str, mesh_serving: bool) -> TpuConf:
        return TpuConf({
            SHUFFLE_TRANSPORT.key: transport,
            "spark.rapids.tpu.shuffle.collective.spmd.enabled":
                transport == "collective",
            "spark.rapids.tpu.shuffle.collective.roundRows":
                max(1024, rows // (n_devices * 4)),
            "spark.rapids.tpu.sql.batchSizeRows":
                max(512, rows // (n_devices * 8)),
            "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes": -1,
            "spark.rapids.tpu.serving.mesh.enabled": mesh_serving,
            "spark.rapids.tpu.serving.maxConcurrent": 2,
            "spark.rapids.tpu.sql.concurrentTpuTasks": 2,
            "spark.rapids.tpu.serving.sharing.enabled": False,
        })

    set_active_mesh(mesh)
    out: dict = {"metric": "mesh_serving_bench",
                 "n_devices": n_devices,
                 "serving_sessions": n_sessions, "rows": rows}
    try:
        # -- serial single-device reference (the ground truth) ------ #
        serial_conf = _conf("local", False)
        serial_conf.set("spark.rapids.tpu.serving.maxConcurrent", 0)
        set_conf(serial_conf)
        s0 = TpuSession(serial_conf)
        digests = {}
        for name, df in templates(s0):
            df.collect(engine="tpu")  # warm
            digests[name] = graft._canon_digest(df.collect(engine="tpu"))

        # -- flag-off gate: collective SPMD on the mesh with
        # serving.mesh.enabled=false must be bit-for-bit the
        # pre-mesh-serving engine (every gated path dormant) -------- #
        off_conf = _conf("collective", False)
        set_conf(off_conf)
        s_off = TpuSession(off_conf)
        for name, df in templates(s_off):
            got = graft._canon_digest(df.collect(engine="tpu"))
            assert got == digests[name], \
                f"mesh.enabled=false diverged on {name}"
        out["mesh_off_identical"] = True

        # -- mesh-resident serving phase ---------------------------- #
        repeat_iters = 3
        _scheduler.reset()
        lock = threading.Lock()
        latencies: list = []
        mismatches: list = []
        warm_done = threading.Barrier(n_sessions + 1)
        go = threading.Event()

        def run_session(i: int) -> None:
            pqs = {}
            try:
                conf = _conf("collective", True)
                set_conf(conf)
                session = TpuSession(conf, tenant=f"t{i % 2}")
                for name, df in templates(session):
                    pqs[name] = session.prepare(df)
                for name, pq in pqs.items():
                    if graft._canon_digest(pq.execute()) \
                            != digests[name]:
                        with lock:
                            mismatches.append((i, name, "warm"))
            except BaseException as e:  # noqa: BLE001 — reported below
                with lock:
                    mismatches.append((i, "session-error", repr(e)))
                pqs = {}
            finally:
                warm_done.wait()
            if not pqs:
                return
            go.wait()
            try:
                for _ in range(repeat_iters):
                    for name, pq in pqs.items():
                        t0 = time.perf_counter()
                        r = pq.execute()
                        dt = time.perf_counter() - t0
                        if graft._canon_digest(r) != digests[name]:
                            with lock:
                                mismatches.append((i, name, "repeat"))
                        with lock:
                            latencies.append(dt)
            except BaseException as e:  # noqa: BLE001 — reported below
                with lock:
                    mismatches.append((i, "repeat-error", repr(e)))

        threads = [threading.Thread(target=run_session, args=(i,),
                                    name=f"mesh-serve-{i}")
                   for i in range(n_sessions)]
        for t in threads:
            t.start()
        warm_done.wait()
        # measured window armed strictly after every warm pass:
        # repeats must be pure plan-cache hits that compile nothing
        # and upload nothing on the data plane
        _plan_cache.reset_stats()
        _scheduler.reset()
        _placement.reset_stats()
        jit0 = cache_stats()
        wall0 = time.perf_counter()
        go.set()
        for t in threads:
            t.join()
        wall = time.perf_counter() - wall0
        assert not mismatches, (
            f"mesh serving diverged from the serial single-device "
            f"digests: {mismatches}")
        jit1 = cache_stats()
        pc = _plan_cache.stats()
        pl = _placement.stats()
        n_execs = len(latencies)
        latencies.sort()
        qps = n_execs / wall if wall else 0.0
        out.update({
            "serving_executions": n_execs,
            "serving_qps": round(qps, 2),
            "serving_qps_per_chip": round(qps / n_devices, 3),
            "serving_p50_ms": round(
                latencies[n_execs // 2] * 1e3, 1) if n_execs else 0.0,
            "plan_cache_hit_rate": pc["hit_rate"],
            "serving_repeat_jit_misses":
                jit1["misses"] - jit0["misses"],
            "placement_host_uploads": pl["host_uploads"],
            "placement_control_uploads": pl["control_uploads"],
            "placement_device_born": pl["device_born"],
            "placement_d2d_transfers": pl["d2d_transfers"],
            "placement_adoptions": pl["adoptions"],
            "digests_match": True,
        })
        assert pc["hit_rate"] == 1.0, pc
        assert out["serving_repeat_jit_misses"] == 0, (jit0, jit1)
        # the device-born contract, measured where it bites: the
        # steady-state window moved ZERO data-plane bytes host->device
        # through stage assembly
        assert pl["host_uploads"] == 0, pl
        out["ok"] = True
    finally:
        set_active_mesh(None)
    return out


def main() -> None:
    global _CHAOS
    multichip = _int_flag("--multichip")
    if multichip:
        # multichip mode FIRST: it must pin the virtual CPU platform
        # before any backend initialization below touches jax
        sessions = _int_flag("--sessions")
        if sessions:
            # pod-scale serving: K sessions on the N-device mesh with
            # mesh-resident execution (docs/pod_serving.md)
            print(json.dumps(_bench_mesh_serving(multichip, sessions)))
            return
        print(json.dumps(_bench_multichip(multichip)))
        return
    if "--chaos" in sys.argv[1:]:
        # chaos mode (parsed ahead of the mode dispatch so the serving
        # round honors it too): every query below runs under the
        # deterministic fault schedule — the correctness gates stay
        # on, so what gets measured is the cost of RECOVERING, not a
        # different answer
        _CHAOS = True
    sessions = _int_flag("--sessions")
    if sessions:
        # serving mode: the multi-session concurrency bench ONLY (the
        # single-session q6/q1/q3/q67 rounds are the plain invocation)
        tenants = _int_flag("--tenants") or min(2, sessions)
        print(json.dumps(_bench_serving(sessions, tenants)))
        return
    cold = _int_flag("--cold-start")
    if cold:
        # cold-start mode: fresh subprocesses only — this parent
        # process must not touch jax before forking the children
        print(json.dumps(_bench_cold_start(cold)))
        return
    # wire compression rides every bench round by default (the lever
    # for the upload-bound milestones; correctness gates stay on, and
    # the per-query _wire_fields still measure the on/off byte delta);
    # --no-wire-compression reverts to the raw wire
    if "--no-wire-compression" not in sys.argv[1:]:
        from spark_rapids_tpu.config import get_conf as _gc

        _gc().set("spark.rapids.tpu.sql.wireCompression.enabled", True)
    # buffer donation rides bench rounds by default (the fused
    # scan->agg programs reuse the wire components' HBM;
    # docs/fusion.md) — `--no-donation` reverts; the digest-gated
    # correctness checks run either way
    if "--no-donation" not in sys.argv[1:]:
        from spark_rapids_tpu.config import get_conf as _gc

        _gc().set("spark.rapids.tpu.sql.fusion.donation.enabled", True)
    # batch coalescing rides bench rounds by default (dense programs
    # under fused chains / joins / aggregates; docs/occupancy.md) —
    # `--no-coalesce` reverts; results are bit-identical either way
    # (coalescing only re-buckets rows) and the digest gates run anyway
    if "--no-coalesce" not in sys.argv[1:]:
        from spark_rapids_tpu.config import get_conf as _gc

        _gc().set("spark.rapids.tpu.sql.coalesce.enabled", True)
    scale = _int_flag("--scale-rows")
    if scale:
        # scaling-curve mode ONLY (ROADMAP #1): q6 at N rows, q1 at
        # >= 20M, full per-stage attribution, CPU-gated
        print(json.dumps(_bench_scaled(scale)))
        return
    n_rows = ROWS_PER_FILE * N_FILES
    with tempfile.TemporaryDirectory(prefix="q6bench_") as d:
        paths = make_lineitem(d)
        os.makedirs(os.path.join(d, "q1"), exist_ok=True)

        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.session import TpuSession

        ev_dir = None
        if "--no-eventlog" not in sys.argv[1:]:
            ev_dir = _eventlog_dir()
            get_conf().set("spark.rapids.tpu.eventLog.enabled", True)
            get_conf().set("spark.rapids.tpu.eventLog.dir", ev_dir)
        # device-ledger attribution rides every round: per-query
        # q*_device_busy_ms / q*_roofline_attributed / top-program
        # fields, and the event log's per-query `programs` section
        # (docs/device_ledger.md); per-dispatch cost is one counter
        # bump, settlement is off the timed path
        get_conf().set("spark.rapids.tpu.trace.ledger.enabled", True)
        session = TpuSession()
        df = q6_dataframe(session, paths)

        df.collect(engine="tpu")  # warmup: compile cache, page cache
        link = _link_probe()
        reset_all_counters()  # q6 occupancy = timed runs only
        sp0 = _spilled_now()
        tpu_ts, tpu_result = _time_collect(df, "tpu", TPU_ITERS)
        cpu_ts, cpu_result = _time_collect(df, "cpu", CPU_ITERS)
        tpu_t = statistics.median(tpu_ts)
        cpu_t = statistics.median(cpu_ts)

        # correctness gate: a fast wrong answer is not a benchmark
        got = tpu_result.to_pydict()["revenue"][0]
        want = cpu_result.to_pydict()["revenue"][0]
        assert abs(got - want) <= 1e-6 * max(1.0, abs(want)), (got, want)

        # headline occupancy is q6's own (counters reset per config),
        # read BEFORE the tapped breakdown collect
        occ = _pipeline_occupancy("pipeline")
        # q6 is a grand aggregate: its partials carry static counts, so
        # there is nothing to speculate — host_sync_count only
        occ.update(_sync_spec_fields("q6", TPU_ITERS,
                                     with_hit_rate=False))
        occ.update(_robustness_fields("q6", sp0))
        occ.update(_ledger_fields("q6", TPU_ITERS))
        occ.update(_fusion_fields("q6", TPU_ITERS))
        occ.update(_wire_fields(df, "q6"))
        breakdown = _stage_breakdown(df, "q6")
        breakdown.update(occ)
        # effective upload bandwidth: raw (uncompressed-equivalent)
        # bytes over the wall the wire stage actually spent moving the
        # compressed form — the codec's multiplier applied to the
        # physical link's weather-of-the-day figure
        wire_s = breakdown.get("q6_stage_wire_upload_s", 0.0)
        if wire_s > 0:
            breakdown["link_upload_mb_s_effective"] = round(
                occ["q6_upload_bytes_raw"] / wire_s / 1e6, 1)

        # warm device-resident q6: the same filter+aggregate against a
        # df.cache()-materialized scan — batches already in HBM, so
        # this finally measures DEVICE throughput instead of the wire
        # (VERDICT weak #3); roofline fraction rides along
        from spark_rapids_tpu.session import col as _col, sum_ as _sum
        from spark_rapids_tpu.exprs.base import lit as _lit

        cached = session.read_parquet(*paths).cache()
        ship, disc = _col("l_shipdate"), _col("l_discount")
        qty, price = _col("l_quantity"), _col("l_extendedprice")
        cond = ((ship >= _lit(8766)) & (ship < _lit(9131))
                & (disc >= _lit(0.05)) & (disc <= _lit(0.07))
                & (qty < _lit(24.0)))
        warm_df = cached.where(cond).agg((_sum(price * disc), "revenue"))
        try:
            warm_df.collect(engine="tpu")  # fills the cache slot
            # ledger-ONLY reset (see _bench_q1: the full reset would
            # re-arm the --chaos schedule inside the warm loop)
            _reset_ledger()
            warm = _bench_warm(warm_df, "q6_warm", n_rows)
            warm["hbm_roofline_fraction_warm"] = _roofline(
                warm["q6_warm_rows_per_s"])
            # the ATTRIBUTED counterpart: per-program device time +
            # cost-model roofline for the warm window — the number
            # ROADMAP #2's fusion/donation work moves
            warm.update(_ledger_fields("q6_warm", 3))
            # the dispatch-budget regression gate: warm q6 must stay
            # under the conf budget and compile nothing
            _assert_warm_budget("q6_warm", warm)
        finally:
            cached.unpersist()
        breakdown.update(warm)

        if tpu_t > 10.0:
            # degraded tunnel (per-dispatch latency in the seconds):
            # further configs would take tens of minutes and measure
            # the network, not the engine — record the skip instead
            extra = {"q1_skipped": f"slow device link (q6 {tpu_t:.1f}s)",
                     "q3_skipped": f"slow device link (q6 {tpu_t:.1f}s)"}
        else:
            extra = _bench_q1(session, d)
            extra.update(_bench_q3(session, d))
            extra.update(_bench_q67(session, d))

    rows_per_s = n_rows / tpu_t
    bytes_per_s = rows_per_s * ROW_BYTES
    cpu_rows_per_s = n_rows / cpu_t
    out = {
        "metric": "tpch_q6_e2e_throughput",
        "value": round(rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_s / cpu_rows_per_s, 3),
        "rows": n_rows,
        "tpu_s_per_query": round(tpu_t, 4),
        "cpu_s_per_query": round(cpu_t, 4),
        "bytes_per_s": round(bytes_per_s, 1),
        "hbm_roofline_fraction": _roofline(rows_per_s),
    }
    out.update(_stats(tpu_ts, "q6_tpu"))
    out.update(link)
    out.update(breakdown)
    out.update(extra)
    if _CHAOS:
        from spark_rapids_tpu.robustness import faults

        out["chaos"] = CHAOS_SPEC
        faults.disarm()
    if session.event_log_path is not None:
        # reading events drains the snapshot worker: the log holds
        # every query of this round before we report its path
        _ = session.history.events
        out["eventlog"] = session.event_log_path
    print(json.dumps(out))


if __name__ == "__main__":
    main()
