"""Benchmark driver: TPC-H q6-shaped pipeline throughput on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The metric is effective scan throughput (rows/s) of the fused
filter+project+aggregate program over device-resident batches — the
first milestone config in BASELINE.md (q6 @ single executor).
`vs_baseline` compares against a CPU-Spark-class single-core columnar
baseline of 100M rows/s for this pipeline shape (the reference claims
3-7x over CPU Spark for full-GPU plans, docs/FAQ.md:82-88; we measure,
not copy — this constant is our local CPU pyarrow-compute measurement
and is re-derived in tests/test_bench_baseline.py).
"""

import json
import time

import numpy as np

# Rows/s of the same q6 pipeline on one host CPU core via pyarrow.compute
# (measured locally; see scripts/measure_cpu_baseline.py).
CPU_BASELINE_ROWS_PER_S = 100e6


def main() -> None:
    import jax

    from __graft_entry__ import _example_batch, _q6_batch_fn

    n_rows = 1 << 22  # 4M rows per batch
    capacity = 1 << 22
    fn = jax.jit(_q6_batch_fn())
    batches = [_example_batch(n_rows, capacity, seed=s) for s in range(4)]

    # warmup/compile
    out = fn(batches[0])
    jax.block_until_ready(out.columns[0].data)

    iters = 8
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(batches[i % len(batches)])
    jax.block_until_ready(out.columns[0].data)
    dt = time.perf_counter() - t0

    rows_per_s = n_rows * iters / dt
    print(json.dumps({
        "metric": "q6_pipeline_throughput",
        "value": round(rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_s / CPU_BASELINE_ROWS_PER_S, 3),
    }))


if __name__ == "__main__":
    main()
