#!/usr/bin/env bash
# tpulint gate: static analysis over engine source, registries, the
# live planner's plan corpus, and — by default — the concurrency rules
# (CON*: guard discipline, lock-order cycles, CV hygiene; see
# docs/concurrency.md).  Mirrors
# tests/test_lint.py::test_repo_is_clean_or_baselined (the tier-1 hook);
# run it standalone for fast pre-commit feedback.
# `scripts/lint.sh --baseline-diff` audits baseline.json for stale
# suppressions.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m spark_rapids_tpu.tools.lint --strict "$@"
