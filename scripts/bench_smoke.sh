#!/usr/bin/env bash
# Bench smoke: one tiny query per hot exec (join, aggregate, exchange)
# with speculative output sizing on/off, asserting result equality —
# the cheap pre-merge check that the speculation layer stays a pure
# latency optimization.  The same check runs inside tier-1 as
# tests/test_speculation.py::test_bench_smoke_queries_match.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m spark_rapids_tpu.tools.bench_smoke "$@"
