"""Measure the single-core CPU (pyarrow.compute) throughput of the same
q6-shaped pipeline bench.py runs on the accelerator.  The printed rows/s
feeds bench.py's CPU_BASELINE_ROWS_PER_S (the stand-in for "CPU Spark"
until the differential engine runs full TPC-H)."""

import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


def main() -> None:
    n_rows = 1 << 22
    rng = np.random.default_rng(0)
    tbl = pa.table({
        "l_quantity": rng.integers(1, 51, n_rows).astype(np.float64),
        "l_extendedprice": rng.uniform(900, 105000, n_rows),
        "l_discount": rng.integers(0, 11, n_rows).astype(np.float64) / 100.0,
        "l_shipdate": rng.integers(8766, 10957, n_rows).astype(np.int32),
    })

    def q6(t):
        m = pc.and_(
            pc.and_(
                pc.and_(pc.greater_equal(t["l_shipdate"], 8766),
                        pc.less(t["l_shipdate"], 9131)),
                pc.and_(pc.greater_equal(t["l_discount"], 0.05),
                        pc.less_equal(t["l_discount"], 0.07))),
            pc.less(t["l_quantity"], 24.0))
        f = t.filter(m)
        return pc.sum(pc.multiply(f["l_extendedprice"], f["l_discount"]))

    q6(tbl)  # warmup
    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        out = q6(tbl)
    dt = time.perf_counter() - t0
    print(f"result={out}  rows/s={n_rows * iters / dt:,.0f}")


if __name__ == "__main__":
    main()
