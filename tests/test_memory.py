"""Buffer store / spill tier tests (mirrors RapidsDeviceMemoryStoreSuite,
RapidsHostMemoryStoreSuite, RapidsDiskStoreSuite, GpuSemaphoreSuite)."""

import threading

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory import (
    BufferStore,
    SpillPriorities,
    StorageTier,
    TpuSemaphore,
)

SCHEMA = T.Schema([T.Field("a", T.LONG), T.Field("s", T.STRING)])


def make_batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_numpy(
        {"a": rng.integers(0, 100, n).astype(np.int64),
         "s": np.array([f"row{i}-{'x' * (i % 7)}" for i in range(n)],
                       object)},
        SCHEMA)


def batch_rows(b):
    return b.to_pydict()


def test_register_acquire_roundtrip():
    store = BufferStore(device_budget=1 << 30, host_budget=1 << 30)
    b = make_batch(100)
    want = batch_rows(b)
    h = store.register(b)
    assert h.tier == StorageTier.DEVICE
    assert store.device_used > 0
    assert batch_rows(h.get()) == want
    h.close()
    assert store.device_used == 0
    store.close()


def test_spill_to_host_and_back():
    b1 = make_batch(200, 1)
    b2 = make_batch(200, 2)
    nbytes = None
    store = BufferStore(device_budget=1, host_budget=1 << 30)  # tiny
    # budget of 1 byte: the second register must evict the first
    want1 = batch_rows(b1)
    h1 = store.register(b1, SpillPriorities.COALESCE_PENDING)
    h2 = store.register(b2, SpillPriorities.ACTIVE_ON_DECK)
    assert h1.tier == StorageTier.HOST  # lower priority spilled first
    assert store.spilled_device_to_host > 0
    got = batch_rows(h1.get())  # re-materialize
    assert got == want1
    assert h1.tier == StorageTier.DEVICE
    store.close()


def test_spill_chain_to_disk(tmp_path):
    store = BufferStore(device_budget=1, host_budget=1,
                        spill_dir=str(tmp_path))
    b1 = make_batch(150, 3)
    want = batch_rows(b1)
    h1 = store.register(b1)
    _h2 = store.register(make_batch(150, 4))
    assert h1.tier == StorageTier.DISK
    assert store.spilled_host_to_disk > 0
    assert list(tmp_path.glob("spill-*.tpub"))
    assert batch_rows(h1.get()) == want
    store.close()
    assert not list(tmp_path.glob("spill-*"))


def test_spill_priority_order():
    store = BufferStore(device_budget=1, host_budget=1 << 30)
    hs = [store.register(make_batch(50, i), prio)
          for i, prio in enumerate([SpillPriorities.JOIN_BUILD,
                                    SpillPriorities.OUTPUT_FOR_SHUFFLE,
                                    SpillPriorities.ACTIVE_ON_DECK])]
    # every register spills what came before; shuffle output (lowest
    # priority) must be on host, the last registered stays on device
    assert hs[2].tier == StorageTier.DEVICE
    assert hs[0].tier == StorageTier.HOST
    assert hs[1].tier == StorageTier.HOST
    store.close()


def test_semaphore_caps_concurrency():
    TpuSemaphore.reset()
    sem = TpuSemaphore(2)
    order = []
    gate = threading.Barrier(2)

    def task(tid):
        sem.acquire_if_necessary(tid)
        sem.acquire_if_necessary(tid)  # idempotent
        order.append(tid)
        gate.wait(timeout=5)
        sem.release_if_necessary(tid)

    ts = [threading.Thread(target=task, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=5)
    assert sorted(order) == [0, 1]
    # all permits returned
    sem.acquire_if_necessary(99)
    sem.acquire_if_necessary(98)
    sem.release_if_necessary(99)
    sem.release_if_necessary(98)


def test_query_correct_under_forced_spill():
    """End-to-end: a sort+aggregate query stays correct when the store's
    device budget forces every pending batch through host/disk tiers."""
    import sys
    sys.path.insert(0, "tests")
    from differential import assert_tpu_cpu_equal, gen_table
    from spark_rapids_tpu.memory import reset_store
    from spark_rapids_tpu.session import TpuSession, col, sum_

    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, get_conf

    store = BufferStore(device_budget=1, host_budget=1 << 20)
    reset_store(store)
    conf = get_conf()
    old_rows = conf.get(BATCH_SIZE_ROWS)
    conf.set(BATCH_SIZE_ROWS.key, 100)  # many small batches -> spills
    try:
        spark = TpuSession()
        t = gen_table({"k": "smallint64", "v": "int64"}, 600, seed=30)
        q = (spark.create_dataframe(t)
             .group_by("k").agg((sum_("v"), "s")).order_by("k"))
        assert_tpu_cpu_equal(q, ignore_order=False)
        assert store.spilled_device_to_host > 0  # spills actually happened
    finally:
        conf.set(BATCH_SIZE_ROWS.key, old_rows)
        reset_store()


def test_store_leak_invariant():
    """SURVEY.md §5.2: a store-wide all-buffers-released check exists
    and reports leaked registrations precisely."""
    import numpy as np

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.memory.store import BufferStore, SpillPriorities

    store = BufferStore(device_budget=1 << 30)
    store.assert_all_released()  # fresh store is clean
    schema = T.Schema([T.Field("x", T.LONG)])
    b = ColumnarBatch.from_numpy(
        {"x": np.arange(10, dtype=np.int64)}, schema)
    h = store.register(b, SpillPriorities.ACTIVE_ON_DECK)
    leaks = store.leak_report()
    assert len(leaks) == 1 and "tier=DEVICE" in leaks[0]
    import pytest as _pytest

    with _pytest.raises(AssertionError, match="never released"):
        store.assert_all_released()
    h.close()
    store.assert_all_released()


def test_query_leaves_store_clean():
    """End-to-end query lifecycle releases every spill-store buffer
    (shuffle blocks, build sides, coalesce parking)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, get_conf
    from spark_rapids_tpu.memory import get_store
    from spark_rapids_tpu.session import TpuSession, col, sum_
    from spark_rapids_tpu.shuffle import reset_shuffle_manager

    session = TpuSession()
    conf = get_conf()
    old = conf.get(BATCH_SIZE_ROWS)
    conf.set(BATCH_SIZE_ROWS.key, 128)
    try:
        rng = np.random.default_rng(3)
        t = pa.table({"k": rng.integers(0, 5, 1000),
                      "v": rng.integers(0, 9, 1000)})
        df = (session.create_dataframe(t)
              .group_by(col("k")).agg((sum_(col("v")), "s")))
        df.collect(engine="tpu")
        # shuffle blocks live until their shuffle unregisters; reset
        # releases them — afterwards NOTHING may remain registered
        reset_shuffle_manager()
        leaks = get_store().leak_report()
        assert not leaks, leaks
    finally:
        conf.set(BATCH_SIZE_ROWS.key, old)


def test_spill_never_deletes_shared_dict_sidecar():
    """gather/compact/split pass the row-invariant dictionary through
    BY REFERENCE, so sibling batches share ONE device dict array.
    Spilling one registered sibling must not .delete() the shared
    dictionary out from under the others (pre-PR6 this crashed with
    'Array has been deleted' whenever a split/sliced dict-encoded
    batch spilled under a tight budget — exactly the OOC-under-
    pressure scenario)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import StringColumn

    schema = T.Schema([T.Field("s", T.STRING)])
    base = StringColumn(jnp.zeros((16, 4), jnp.uint8),
                        jnp.zeros(16, jnp.int32),
                        jnp.ones(16, bool), T.STRING,
                        codes=jnp.zeros(16, jnp.int32),
                        dict_chars=jnp.arange(32, dtype=jnp.uint8)
                        .reshape(8, 4),
                        dict_lens=jnp.full(8, 4, jnp.uint16),
                        dict_len=8)
    b1 = ColumnarBatch([base], 16, schema)
    # a gathered sibling: fresh per-row arrays, SAME dict arrays
    sib = ColumnarBatch(
        [base.gather(jnp.arange(16, dtype=jnp.int32))], 16, schema)
    assert sib.columns[0].dict_chars is base.dict_chars
    store = BufferStore(device_budget=1, host_budget=1 << 30)
    h1 = store.register(b1, SpillPriorities.COALESCE_PENDING)
    h2 = store.register(sib, SpillPriorities.COALESCE_PENDING)
    # spill BOTH (registration order spills b1 first): spilling b1
    # deleted its per-row arrays but must have left the shared
    # dictionary alive, so spilling + restoring the sibling still works
    store.spill_all_unpinned()
    assert h1.tier == StorageTier.HOST and h2.tier == StorageTier.HOST
    restored = h2.get()
    assert restored.columns[0].dict_len == 8
    np.testing.assert_array_equal(
        np.asarray(restored.columns[0].dict_chars),
        np.arange(32, dtype=np.uint8).reshape(8, 4))
    store.close()


def test_spill_preserves_dict_len_sidecar():
    """The dictionary entry-count bound (Column/StringColumn.dict_len)
    must survive a spill round trip with the rest of the dict sidecar —
    dropping it demotes restored group-by keys to padded-capacity
    domains and forks the pytree aux (recompiles)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import Column, StringColumn

    schema = T.Schema([T.Field("k", T.LONG), T.Field("s", T.STRING)])
    kcol = Column(jnp.arange(16, dtype=jnp.int64),
                  jnp.ones(16, bool), T.LONG,
                  codes=jnp.zeros(16, jnp.int32),
                  dict_values=jnp.zeros(8, jnp.int64), dict_len=3)
    scol = StringColumn(jnp.zeros((16, 4), jnp.uint8),
                        jnp.zeros(16, jnp.int32),
                        jnp.ones(16, bool), T.STRING,
                        codes=jnp.zeros(16, jnp.int32),
                        dict_chars=jnp.zeros((8, 4), jnp.uint8),
                        dict_lens=jnp.zeros(8, jnp.uint16), dict_len=5)
    b = ColumnarBatch([kcol, scol], 16, schema)
    store = BufferStore(device_budget=1, host_budget=1 << 30)
    h = store.register(b, SpillPriorities.COALESCE_PENDING)
    # a second registration under the 1-byte budget evicts the first
    h2 = store.register(make_batch(64), SpillPriorities.ACTIVE_ON_DECK)
    assert h.tier == StorageTier.HOST
    restored = h.get()
    assert restored.columns[0].dict_len == 3
    assert restored.columns[1].dict_len == 5
    assert restored.columns[0].codes is not None
    store.close()
