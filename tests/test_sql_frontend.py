"""SQL-text frontend: the ACTUAL text of TPC-H q1/q3/q6 and TPC-DS q3
(plus grammar corners) through `frontend("sql")`, differential against
the CPU reference engine.

The reference's contract is "the user's SQL, unmodified"
(ref: sql-plugin/src/main/scala/com/nvidia/spark/SQLPlugin.scala:26-31);
these tests paste the benchmark queries verbatim (schema-subset data)
and require TPU/CPU agreement.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.frontends.sql import SqlError, SqlSession

TPCH_Q6 = """
select
    sum(l_extendedprice * l_discount) as revenue
from
    lineitem
where
    l_shipdate >= date '1994-01-01'
    and l_shipdate < date '1994-01-01' + interval '1' year
    and l_discount between .06 - 0.01 and .06 + 0.01
    and l_quantity < 24
"""

TPCH_Q1 = """
select
    l_returnflag,
    l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from
    lineitem
where
    l_shipdate <= date '1998-12-01' - interval '90' day
group by
    l_returnflag,
    l_linestatus
order by
    l_returnflag,
    l_linestatus
"""

TPCH_Q3 = """
select
    l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate,
    o_shippriority
from
    customer,
    orders,
    lineitem
where
    c_mktsegment = 'BUILDING'
    and c_custkey = o_custkey
    and l_orderkey = o_orderkey
    and o_orderdate < date '1995-03-15'
    and l_shipdate > date '1995-03-15'
group by
    l_orderkey,
    o_orderdate,
    o_shippriority
order by
    revenue desc,
    o_orderdate
limit 10
"""

TPCDS_Q3 = """
select dt.d_year
       ,item.i_brand_id brand_id
       ,item.i_brand brand
       ,sum(ss_ext_sales_price) sum_agg
from date_dim dt
     ,store_sales
     ,item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = 128
  and dt.d_moy = 11
group by dt.d_year
        ,item.i_brand_id
        ,item.i_brand
order by dt.d_year
        ,sum_agg desc
        ,brand_id
limit 100
"""


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    d = tmp_path_factory.mktemp("sql_tpch")
    rng = np.random.default_rng(3)
    n = 20_000
    fe = SqlSession()
    fe.register_table("lineitem", pa.table({
        "l_orderkey": rng.integers(0, 3000, n),
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900, 105000, n), 2),
        "l_discount": rng.integers(0, 11, n) / 100.0,
        "l_tax": rng.integers(0, 9, n) / 100.0,
        "l_returnflag": pa.array(
            np.array(["A", "N", "R"])[rng.integers(0, 3, n)]),
        "l_linestatus": pa.array(
            np.array(["F", "O"])[rng.integers(0, 2, n)]),
        "l_shipdate": pa.array(
            rng.integers(8766, 10957, n).astype(np.int32),
            type=pa.date32()),
    }))
    fe.register_table("orders", pa.table({
        "o_orderkey": np.arange(3000),
        "o_custkey": rng.integers(0, 500, 3000),
        "o_orderdate": pa.array(
            rng.integers(8766, 10957, 3000).astype(np.int32),
            type=pa.date32()),
        "o_shippriority": rng.integers(0, 3, 3000).astype(np.int32),
    }))
    fe.register_table("customer", pa.table({
        "c_custkey": np.arange(500),
        "c_mktsegment": pa.array(
            np.array(["BUILDING", "AUTOMOBILE", "MACHINERY"])[
                rng.integers(0, 3, 500)]),
    }))
    return fe


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    rng = np.random.default_rng(11)
    n = 20_000
    fe = SqlSession()
    fe.register_table("store_sales", pa.table({
        "ss_sold_date_sk": rng.integers(0, 400, n),
        "ss_item_sk": rng.integers(0, 300, n),
        "ss_ext_sales_price": np.round(rng.uniform(1, 3000, n), 2),
    }))
    fe.register_table("date_dim", pa.table({
        "d_date_sk": np.arange(400),
        "d_year": (1998 + rng.integers(0, 3, 400)).astype(np.int32),
        "d_moy": rng.integers(1, 13, 400).astype(np.int32),
    }))
    fe.register_table("item", pa.table({
        "i_item_sk": np.arange(300),
        "i_brand_id": rng.integers(100, 120, 300).astype(np.int32),
        "i_brand": pa.array(
            np.array([f"brand#{i}" for i in range(20)])[
                rng.integers(0, 20, 300)]),
        "i_manufact_id": rng.integers(120, 140, 300).astype(np.int32),
    }))
    return fe


def _diff(df, expect_rows=None, ordered=False):
    t_tpu = df.collect(engine="tpu")
    t_cpu = df.collect(engine="cpu")
    a = list(zip(*t_tpu.to_pydict().values()))
    b = list(zip(*t_cpu.to_pydict().values()))
    if not ordered:
        a = sorted(a, key=repr)
        b = sorted(b, key=repr)
    assert len(a) == len(b), (len(a), len(b))
    if expect_rows is not None:
        assert len(a) == expect_rows
    for x, y in zip(a, b):
        for u, v in zip(x, y):
            if isinstance(u, float):
                assert abs(u - v) <= 1e-6 * max(1.0, abs(v)), (x, y)
            else:
                assert u == v, (x, y)
    return a


def test_tpch_q6_text(tpch):
    rows = _diff(tpch.sql(TPCH_Q6), expect_rows=1)
    assert rows[0][0] > 0


def test_tpch_q1_text(tpch):
    rows = _diff(tpch.sql(TPCH_Q1), expect_rows=6, ordered=True)
    # ORDER BY l_returnflag, l_linestatus honored
    assert [r[:2] for r in rows] == sorted(r[:2] for r in rows)


def test_tpch_q3_text(tpch):
    rows = _diff(tpch.sql(TPCH_Q3), expect_rows=10, ordered=True)
    revs = [r[1] for r in rows]
    assert revs == sorted(revs, reverse=True)


def test_tpcds_q3_text(tpcds):
    rows = _diff(tpcds.sql(TPCDS_Q3), ordered=True)
    assert rows, "manufact 128 rows expected"
    years = [r[0] for r in rows]
    assert years == sorted(years)


def test_case_in_like_having(tpch):
    q = """
    select l_linestatus,
           sum(case when l_discount > 0.05 then l_extendedprice
                    else 0 end) as disc_rev,
           count(*) as n
    from lineitem
    where l_returnflag in ('A', 'R') and l_linestatus like 'F%'
    group by l_linestatus
    having count(*) > 0
    order by 1
    """
    rows = _diff(tpch.sql(q), ordered=True)
    assert [r[0] for r in rows] == ["F"]


def test_scalar_fns_and_distinct(tpch):
    q = """
    select distinct upper(l_returnflag) as rf,
           substring(l_linestatus, 1, 1) ls
    from lineitem
    order by rf, ls
    """
    rows = _diff(tpch.sql(q), ordered=True)
    assert rows[0][0] in ("A", "N", "R")
    assert len(rows) == 6


def test_explicit_join_on(tpch):
    q = """
    select o_shippriority, count(*) as n
    from lineitem join orders on l_orderkey = o_orderkey
    where o_orderdate >= date '1995-01-01'
    group by o_shippriority
    order by o_shippriority
    """
    _diff(tpch.sql(q), ordered=True)


def test_extract_and_cast(tpch):
    q = """
    select extract(year from l_shipdate) as y,
           count(*) as n
    from lineitem
    where cast(l_quantity as int) >= 25
    group by extract(year from l_shipdate)
    order by y
    """
    rows = _diff(tpch.sql(q), ordered=True)
    assert all(1994 <= r[0] <= 2000 for r in rows)


def test_errors():
    fe = SqlSession()
    fe.register_table("t", pa.table({"a": [1, 2], "b": [3.0, 4.0]}))
    with pytest.raises(SqlError, match="not registered"):
        fe.sql("select * from missing")
    with pytest.raises(SqlError, match="GROUP BY"):
        fe.sql("select a, sum(b), b from t group by a")
    with pytest.raises(SqlError, match="unknown function"):
        fe.sql("select frobnicate(a) from t")
    with pytest.raises(SqlError, match="alias"):
        fe.sql("select x.a from t")


def test_star_and_ordinal_order_by():
    fe = SqlSession()
    fe.register_table("t", pa.table(
        {"a": [3, 1, 2], "b": ["x", "y", "z"]}))
    rows = _diff(fe.sql("select * from t order by 1 desc"), ordered=True)
    assert [r[0] for r in rows] == [3, 2, 1]


def test_outer_join_where_not_pushed():
    """WHERE over the null-producing side of a LEFT JOIN must filter
    POST-join rows (pre-join pushdown would resurrect unmatched rows
    with NULLs)."""
    fe = SqlSession()
    fe.register_table("l", pa.table({"lk": [1, 2, 3]}))
    fe.register_table("r", pa.table({"rk": [1, 2], "x": [0, 9]}))
    rows = _diff(fe.sql(
        "select lk, x from l left join r on lk = rk where x > 5"))
    assert rows == [(2, 9)], rows


def test_string_concat_operator():
    fe = SqlSession()
    fe.register_table("t", pa.table({"a": ["x", "y"], "b": ["1", "2"]}))
    rows = _diff(fe.sql("select a || '-' || b as c from t order by c"),
                 ordered=True)
    assert rows == [("x-1",), ("y-2",)]


def test_mixed_qualified_and_bare_refs():
    """Qualified and bare references to the same column must unify
    (TPC-DS queries mix them freely)."""
    fe = SqlSession()
    fe.register_table("t", pa.table({"a": [1, 1, 2], "v": [1.0, 2.0, 3.0]}))
    rows = _diff(fe.sql(
        "select t.a, sum(v) as s from t group by a order by t.a"),
        ordered=True)
    assert rows == [(1, 3.0), (2, 3.0)]


def test_distinct_over_aggregate():
    fe = SqlSession()
    fe.register_table("t", pa.table(
        {"g": [1, 1, 2, 2, 3], "v": [1, 1, 1, 1, 5]}))
    rows = _diff(fe.sql(
        "select distinct sum(v) as s from t group by g order by 1"))
    assert rows == [(2,), (5,)]


def test_presort_with_ordinal_key():
    """Mixed ordinal + dropped-column ORDER BY keys sort BEFORE the
    projection with the ordinal resolved to the select expression."""
    fe = SqlSession()
    fe.register_table("t", pa.table({
        "v": [3, 1, 2, 2], "w": ["a", "b", "c", "d"],
        "x": [9, 8, 7, 1]}))
    rows = _diff(fe.sql("select v, w from t order by 1, x"),
                 ordered=True)
    assert rows == [(1, "b"), (2, "d"), (2, "c"), (3, "a")]


def test_post_aggregate_arithmetic(tpch):
    """Arithmetic over aggregate results (the TPC-H q8/q14 shape):
    100 * sum(case..) / sum(x), avg ratios, shared aggregates."""
    q = """
    select l_linestatus,
           100.0 * sum(case when l_returnflag = 'A'
                            then l_extendedprice else 0 end)
                 / sum(l_extendedprice) as promo_pct,
           sum(l_quantity) / count(*) as avg_qty
    from lineitem
    group by l_linestatus
    order by l_linestatus
    """
    rows = _diff(tpch.sql(q), expect_rows=2, ordered=True)
    for _ls, pct, avg_qty in rows:
        assert 0 < pct < 100
        assert 20 < avg_qty < 30


# -- round-5 grammar: subqueries, unions, windows, rollup ------------- #

TPCDS_Q67 = """
select * from
    (select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
            d_moy, s_store_id, sumsales,
            rank() over (partition by i_category
                         order by sumsales desc) rk
     from (select i_category, i_class, i_brand, i_product_name, d_year,
                  d_qoy, d_moy, s_store_id,
                  sum(coalesce(ss_sales_price*ss_quantity, 0)) sumsales
           from store_sales, date_dim, store, item
           where ss_sold_date_sk = d_date_sk
             and ss_item_sk = i_item_sk
             and ss_store_sk = s_store_sk
             and d_month_seq between 1200 and 1200 + 11
           group by rollup(i_category, i_class, i_brand, i_product_name,
                           d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
where rk <= 100
order by i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales, rk
limit 100
"""


@pytest.fixture(scope="module")
def tpcds67(tmp_path_factory):
    """Schema-subset tables for the REAL q67 text (8 rollup keys)."""
    rng = np.random.default_rng(67)
    n = 6000
    fe = SqlSession()
    fe.register_table("store_sales", pa.table({
        "ss_sold_date_sk": rng.integers(0, 200, n),
        "ss_item_sk": rng.integers(0, 60, n),
        "ss_store_sk": rng.integers(0, 4, n),
        "ss_quantity": rng.integers(1, 20, n).astype(np.float64),
        "ss_sales_price": np.round(rng.uniform(1, 300, n), 2),
    }))
    fe.register_table("date_dim", pa.table({
        "d_date_sk": np.arange(200),
        "d_month_seq": rng.integers(1195, 1215, 200).astype(np.int32),
        "d_year": (1999 + rng.integers(0, 2, 200)).astype(np.int32),
        "d_qoy": rng.integers(1, 5, 200).astype(np.int32),
        "d_moy": rng.integers(1, 13, 200).astype(np.int32),
    }))
    fe.register_table("store", pa.table({
        "s_store_sk": np.arange(4),
        "s_store_id": pa.array([f"S{i:04d}" for i in range(4)]),
    }))
    fe.register_table("item", pa.table({
        "i_item_sk": np.arange(60),
        "i_category": pa.array(
            np.array(["Books", "Music", "Sports"])[
                rng.integers(0, 3, 60)]),
        "i_class": pa.array(
            np.array(["c1", "c2"])[rng.integers(0, 2, 60)]),
        "i_brand": pa.array(
            np.array(["b1", "b2", "b3"])[rng.integers(0, 3, 60)]),
        "i_product_name": pa.array([f"p{i}" for i in range(60)]),
    }))
    return fe


def test_tpcds_q67_text(tpcds67):
    """The ACTUAL TPC-DS q67: derived tables, 8-key rollup, rank()
    window, rank filter, 10-key ORDER BY + LIMIT."""
    _diff(tpcds67.sql(TPCDS_Q67), expect_rows=100)


def test_derived_table(tpch):
    q = """
    select f, q from
        (select l_returnflag f, l_quantity q from lineitem
         where l_quantity > 10) t
    where q < 20
    """
    rows = _diff(tpch.sql(q))
    assert rows and all(10 < r[1] < 20 for r in rows)


def test_scalar_subquery(tpch):
    q = """
    select sum(l_extendedprice) as s, count(*) as n from lineitem
    where l_quantity < (select avg(l_quantity) from lineitem)
    """
    rows = _diff(tpch.sql(q), expect_rows=1)
    assert rows[0][1] > 0


def test_in_subquery_semi_join(tpch):
    """TPC-H q18's signature shape: IN (grouped HAVING subquery)."""
    q = """
    select o_orderkey, sum(l_quantity) as total
    from orders, lineitem
    where o_orderkey in (select l_orderkey from lineitem
                         group by l_orderkey
                         having sum(l_quantity) > 250)
      and o_orderkey = l_orderkey
    group by o_orderkey
    order by total desc, o_orderkey
    limit 20
    """
    rows = _diff(tpch.sql(q), ordered=True)
    assert all(r[1] > 250 for r in rows)


def test_union_all_and_union_distinct(tpch):
    rows = _diff(tpch.sql("""
        select l_returnflag r, sum(l_quantity) q from lineitem
        group by l_returnflag
        union all
        select l_linestatus, sum(l_quantity) from lineitem
        group by l_linestatus
        order by 2 desc
    """), expect_rows=5, ordered=True)
    assert sorted(r[0] for r in rows) == ["A", "F", "N", "O", "R"]
    dedup = _diff(tpch.sql("""
        select l_returnflag r from lineitem
        union
        select l_linestatus from lineitem
        order by r
    """), expect_rows=5, ordered=True)
    assert [r[0] for r in dedup] == ["A", "F", "N", "O", "R"]


def test_window_functions_text(tpch):
    """row_number / window aggregate / lead over real window specs."""
    rows = _diff(tpch.sql("""
        select l_orderkey,
               row_number() over (partition by l_orderkey
                                  order by l_quantity desc,
                                           l_extendedprice) rn,
               sum(l_quantity) over (partition by l_orderkey) okq
        from lineitem
        where l_orderkey < 40
    """))
    assert rows and all(r[1] >= 1 for r in rows)
    rows = _diff(tpch.sql("""
        select l_orderkey,
               avg(l_extendedprice) over
                   (partition by l_returnflag
                    order by l_extendedprice
                    rows between 3 preceding and current row) m
        from lineitem where l_orderkey < 40
    """))
    assert rows


def test_rollup_text(tpch):
    rows = _diff(tpch.sql("""
        select l_returnflag, l_linestatus, sum(l_quantity) q
        from lineitem
        group by rollup(l_returnflag, l_linestatus)
        order by 1 nulls first, 2 nulls first
    """), expect_rows=3 * 2 + 3 + 1, ordered=True)
    assert rows[0][0] is None and rows[0][1] is None  # grand total


def test_not_in_subquery_null_aware(tpch):
    """NOT IN (subquery) lowers to the null-aware anti-join shape:
    TPU == CPU, and rows NOT in orders survive."""
    rows = _diff(tpch.sql(
        "select distinct l_orderkey from lineitem where l_orderkey "
        "not in (select o_orderkey from orders) order by 1"),
        ordered=True)
    # orders covers keys 0..2999; lineitem keys are within it, so the
    # complement is empty — the interesting assertions are in
    # test_not_in_null_semantics below
    assert rows == []


def test_not_in_subquery_kill_switch(tpch):
    """The sweep's fix probe: disabling the grammar fix restores the
    pre-fix rejection."""
    from spark_rapids_tpu.frontends import sql as sql_mod

    sql_mod.DISABLED_FEATURES.add("not_in_subquery")
    try:
        with pytest.raises(SqlError, match="NOT IN"):
            tpch.sql("select l_orderkey from lineitem where l_orderkey "
                     "not in (select o_orderkey from orders)")
    finally:
        sql_mod.DISABLED_FEATURES.discard("not_in_subquery")


# -- more verbatim TPC-H texts (multi-table joins, IN lists, CASE) ---- #

@pytest.fixture(scope="module")
def tpch_full():
    """Schema-subset synthetic TPC-H catalog for q5/q10/q12/q14/q19."""
    rng = np.random.default_rng(22)
    n_li = 12_000
    n_ord = 2500
    n_cust = 400
    n_supp = 50
    n_part = 300
    fe = SqlSession()
    nations = ["ALGERIA", "BRAZIL", "CANADA", "EGYPT", "FRANCE"]
    fe.register_table("region", pa.table({
        "r_regionkey": np.arange(3),
        "r_name": pa.array(["ASIA", "AMERICA", "AFRICA"]),
    }))
    fe.register_table("nation", pa.table({
        "n_nationkey": np.arange(5),
        "n_name": pa.array(nations),
        "n_regionkey": rng.integers(0, 3, 5),
    }))
    fe.register_table("customer", pa.table({
        "c_custkey": np.arange(n_cust),
        "c_name": pa.array([f"Customer#{i:09d}" for i in range(n_cust)]),
        "c_address": pa.array([f"addr{i}" for i in range(n_cust)]),
        "c_nationkey": rng.integers(0, 5, n_cust),
        "c_phone": pa.array([f"{rng.integers(10,35)}-555-{i:04d}"
                             for i in range(n_cust)]),
        "c_acctbal": np.round(rng.uniform(-999, 9999, n_cust), 2),
        "c_comment": pa.array([f"comment {i}" for i in range(n_cust)]),
    }))
    fe.register_table("supplier", pa.table({
        "s_suppkey": np.arange(n_supp),
        "s_nationkey": rng.integers(0, 5, n_supp),
    }))
    fe.register_table("part", pa.table({
        "p_partkey": np.arange(n_part),
        "p_type": pa.array(np.array(
            ["PROMO BRUSHED", "STANDARD POLISHED", "ECONOMY BURNISHED"]
        )[rng.integers(0, 3, n_part)]),
        "p_brand": pa.array(np.array(
            ["Brand#12", "Brand#23", "Brand#34"])[
                rng.integers(0, 3, n_part)]),
        "p_container": pa.array(np.array(
            ["SM CASE", "SM BOX", "MED BAG", "MED BOX", "LG CASE",
             "LG BOX"])[rng.integers(0, 6, n_part)]),
        "p_size": rng.integers(1, 16, n_part),
    }))
    fe.register_table("orders", pa.table({
        "o_orderkey": np.arange(n_ord),
        "o_custkey": rng.integers(0, n_cust, n_ord),
        "o_orderdate": pa.array(
            rng.integers(8766, 10957, n_ord).astype(np.int32),
            type=pa.date32()),
        "o_orderpriority": pa.array(np.array(
            ["1-URGENT", "2-HIGH", "3-MEDIUM"])[
                rng.integers(0, 3, n_ord)]),
    }))
    fe.register_table("lineitem", pa.table({
        "l_orderkey": rng.integers(0, n_ord, n_li),
        "l_partkey": rng.integers(0, n_part, n_li),
        "l_suppkey": rng.integers(0, n_supp, n_li),
        "l_quantity": rng.integers(1, 51, n_li).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900, 105000, n_li), 2),
        "l_discount": rng.integers(0, 11, n_li) / 100.0,
        "l_returnflag": pa.array(np.array(["A", "N", "R"])[
            rng.integers(0, 3, n_li)]),
        "l_shipdate": pa.array(rng.integers(8766, 10957, n_li)
                               .astype(np.int32), type=pa.date32()),
        "l_commitdate": pa.array(rng.integers(8766, 10957, n_li)
                                 .astype(np.int32), type=pa.date32()),
        "l_receiptdate": pa.array(rng.integers(8766, 10957, n_li)
                                  .astype(np.int32), type=pa.date32()),
        "l_shipmode": pa.array(np.array(
            ["MAIL", "SHIP", "AIR", "TRUCK"])[
                rng.integers(0, 4, n_li)]),
        "l_shipinstruct": pa.array(np.array(
            ["DELIVER IN PERSON", "COLLECT COD", "NONE"])[
                rng.integers(0, 3, n_li)]),
    }))
    return fe


def test_tpch_q5_text(tpch_full):
    """q5 verbatim: 6-table join chain with a region filter."""
    _diff(tpch_full.sql("""
select
    n_name,
    sum(l_extendedprice * (1 - l_discount)) as revenue
from
    customer, orders, lineitem, supplier, nation, region
where
    c_custkey = o_custkey
    and l_orderkey = o_orderkey
    and l_suppkey = s_suppkey
    and c_nationkey = s_nationkey
    and s_nationkey = n_nationkey
    and n_regionkey = r_regionkey
    and r_name = 'AMERICA'
    and o_orderdate >= date '1994-01-01'
    and o_orderdate < date '1994-01-01' + interval '1' year
group by n_name
order by revenue desc
"""))


def test_tpch_q10_text(tpch_full):
    """q10 verbatim: returned-item revenue per customer, top 20."""
    _diff(tpch_full.sql("""
select
    c_custkey, c_name,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    c_acctbal, n_name, c_address, c_phone, c_comment
from customer, orders, lineitem, nation
where
    c_custkey = o_custkey
    and l_orderkey = o_orderkey
    and o_orderdate >= date '1993-10-01'
    and o_orderdate < date '1993-10-01' + interval '3' month
    and l_returnflag = 'R'
    and c_nationkey = n_nationkey
group by c_custkey, c_name, c_acctbal, c_phone, n_name,
         c_address, c_comment
order by revenue desc, c_custkey
limit 20
""", ), ordered=True)


def test_tpch_q12_text(tpch_full):
    """q12 verbatim: IN list + multi-date comparisons + CASE counts."""
    _diff(tpch_full.sql("""
select
    l_shipmode,
    sum(case when o_orderpriority = '1-URGENT'
              or o_orderpriority = '2-HIGH'
         then 1 else 0 end) as high_line_count,
    sum(case when o_orderpriority <> '1-URGENT'
              and o_orderpriority <> '2-HIGH'
         then 1 else 0 end) as low_line_count
from orders, lineitem
where
    o_orderkey = l_orderkey
    and l_shipmode in ('MAIL', 'SHIP')
    and l_commitdate < l_receiptdate
    and l_shipdate < l_commitdate
    and l_receiptdate >= date '1994-01-01'
    and l_receiptdate < date '1994-01-01' + interval '1' year
group by l_shipmode
order by l_shipmode
"""), ordered=True)


def test_tpch_q14_text(tpch_full):
    """q14 verbatim: promo revenue ratio (CASE inside the aggregate,
    post-aggregate arithmetic)."""
    rows = _diff(tpch_full.sql("""
select
    100.00 * sum(case when p_type like 'PROMO%'
                  then l_extendedprice * (1 - l_discount)
                  else 0 end)
        / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
from lineitem, part
where
    l_partkey = p_partkey
    and l_shipdate >= date '1995-09-01'
    and l_shipdate < date '1995-09-01' + interval '1' month
"""), expect_rows=1)
    assert 0 < rows[0][0] < 100


def test_tpch_q19_text(tpch_full):
    """q19 verbatim: disjunction of conjunctive blocks with IN lists
    and BETWEEN over two tables."""
    _diff(tpch_full.sql("""
select
    sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem, part
where
    (
        p_partkey = l_partkey
        and p_brand = 'Brand#12'
        and p_container in ('SM CASE', 'SM BOX')
        and l_quantity >= 1 and l_quantity <= 1 + 10
        and p_size between 1 and 5
        and l_shipmode in ('AIR', 'TRUCK')
        and l_shipinstruct = 'DELIVER IN PERSON'
    )
    or
    (
        p_partkey = l_partkey
        and p_brand = 'Brand#23'
        and p_container in ('MED BAG', 'MED BOX')
        and l_quantity >= 10 and l_quantity <= 10 + 10
        and p_size between 1 and 10
        and l_shipmode in ('AIR', 'TRUCK')
        and l_shipinstruct = 'DELIVER IN PERSON'
    )
"""), expect_rows=1)


TPCH_Q4 = """
select
    o_orderpriority,
    count(*) as order_count
from
    orders
where
    o_orderdate >= date '1993-07-01'
    and o_orderdate < date '1993-07-01' + interval '3' month
    and exists (
        select * from lineitem
        where l_orderkey = o_orderkey
          and l_commitdate < l_receiptdate)
group by o_orderpriority
order by o_orderpriority
"""


def test_tpch_q4_text(tpch_full):
    """q4 verbatim: correlated EXISTS -> semi join."""
    rows = _diff(tpch_full.sql(TPCH_Q4), ordered=True)
    assert [r[0] for r in rows] == sorted(r[0] for r in rows)


def test_not_exists_anti_join(tpch_full):
    """NOT EXISTS keeps exactly the orders with no qualifying line."""
    both = _diff(tpch_full.sql("""
        select count(*) n from orders
        where exists (select * from lineitem
                      where l_orderkey = o_orderkey)"""))
    none = _diff(tpch_full.sql("""
        select count(*) n from orders
        where not exists (select * from lineitem
                          where l_orderkey = o_orderkey)"""))
    total = _diff(tpch_full.sql("select count(*) n from orders"))
    assert both[0][0] + none[0][0] == total[0][0]


def test_exists_errors(tpch_full):
    with pytest.raises(SqlError, match="correlate"):
        tpch_full.sql("select count(*) n from orders where exists "
                      "(select * from lineitem where l_quantity > 1)")
    with pytest.raises(SqlError, match="equality conjunct"):
        tpch_full.sql(
            "select count(*) n from orders where exists "
            "(select * from lineitem where l_orderkey < o_orderkey)")


# ------------------------------------------------------------------ #
# Named parameters (:name) — the prepared-template substrate
# ------------------------------------------------------------------ #


def test_named_params_match_inline_literals(tpch):
    """A parameterized template must lower to exactly what inline
    literals lower to: same rows, TPU/CPU differential on both."""
    inline = _diff(tpch.sql(
        "select l_returnflag, count(*) as n from lineitem "
        "where l_quantity < 24 and l_discount >= 0.05 "
        "group by l_returnflag"))
    bound = _diff(tpch.sql(
        "select l_returnflag, count(*) as n from lineitem "
        "where l_quantity < :qmax and l_discount >= :dmin "
        "group by l_returnflag",
        params={"qmax": 24, "dmin": 0.05}))
    assert inline == bound


def test_named_param_reused_binds_every_site(tpch):
    """One parameter referenced twice binds at every reference."""
    inline = _diff(tpch.sql(
        "select l_returnflag, count(*) as n from lineitem "
        "where l_quantity >= 20 and l_quantity < 20 + 10 "
        "group by l_returnflag"))
    bound = _diff(tpch.sql(
        "select l_returnflag, count(*) as n from lineitem "
        "where l_quantity >= :qmin and l_quantity < :qmin + 10 "
        "group by l_returnflag", params={"qmin": 20}))
    assert inline == bound


def test_named_param_date_binding(tpch):
    """datetime.date params bind as DATE literals (TPC-H predicates
    parameterize their date range)."""
    import datetime as dt

    inline = _diff(tpch.sql(
        "select count(*) as n from lineitem "
        "where l_shipdate >= date '1995-01-01'"))
    bound = _diff(tpch.sql(
        "select count(*) as n from lineitem where l_shipdate >= :d0",
        params={"d0": dt.date(1995, 1, 1)}))
    assert inline == bound and inline[0][0] > 0


def test_named_param_errors(tpch):
    with pytest.raises(SqlError, match=r"unbound parameter :qmax"):
        tpch.sql("select count(*) as n from lineitem "
                 "where l_quantity < :qmax")
    with pytest.raises(SqlError, match=r"unknown parameter\(s\) :typo"):
        tpch.sql("select count(*) as n from lineitem "
                 "where l_quantity < :qmax",
                 params={"qmax": 10, "typo": 1})


# -- PR15 grammar growth: NOT IN null semantics, month/year intervals,
# -- GROUPING SETS, CTEs, self-join disambiguation (tools/sweep.py
# -- exercises these against the full TPC-DS corpus) ----------------- #


@pytest.fixture(scope="module")
def nulls_fe():
    fe = SqlSession()
    fe.register_table("t", pa.table({
        "k": pa.array([1, 2, 3, 4, None], type=pa.int64()),
        "d": pa.array([10957, 11000, 11050, 11100, 11150],
                      type=pa.date32()),
        "g": ["a", "a", "b", "b", "c"],
        "v": [10.0, 20.0, 30.0, 40.0, 50.0],
    }))
    fe.register_table("s_plain", pa.table(
        {"sk": pa.array([2, 3], type=pa.int64())}))
    fe.register_table("s_null", pa.table(
        {"sk": pa.array([2, None], type=pa.int64())}))
    fe.register_table("s_empty", pa.table(
        {"sk": pa.array([], type=pa.int64())}))
    return fe


def test_not_in_null_semantics(nulls_fe):
    """Spark's NOT IN truth table: plain complement drops NULL probes;
    any NULL in the subquery empties the result; an EMPTY subquery
    keeps every row INCLUDING NULL probes."""
    q = "select k from t where k not in (select sk from {}) order by k"
    rows = _diff(nulls_fe.sql(q.format("s_plain")), ordered=True)
    assert [r[0] for r in rows] == [1, 4]
    assert _diff(nulls_fe.sql(q.format("s_null"))) == []
    rows = _diff(nulls_fe.sql(
        q.format("s_empty") + " nulls last"), ordered=True)
    assert [r[0] for r in rows] == [1, 2, 3, 4, None]


def test_month_year_interval_on_date_column(nulls_fe):
    """date COLUMN ± INTERVAL month/year lowers to AddMonths (device
    calendar shift with end-of-month clamping), TPU == CPU."""
    rows = _diff(nulls_fe.sql(
        "select d + interval '1' month as m, "
        "d - interval '2' year as y from t order by m"), ordered=True)
    import datetime as dt

    epoch = dt.date(1970, 1, 1)
    for (m, y), base_days in zip(
            rows, [10957, 11000, 11050, 11100, 11150]):
        d = epoch + dt.timedelta(days=base_days)
        mi = d.year * 12 + d.month  # +1 month
        yy, mm = divmod(mi, 12)
        import calendar

        want_m = dt.date(yy, mm + 1,
                         min(d.day, calendar.monthrange(yy, mm + 1)[1]))
        assert m == want_m
        assert y == dt.date(d.year - 2, d.month, d.day)


def test_add_months_pre_gregorian_edges():
    """Proleptic-Gregorian month shifts on pre-1582 dates match
    Python's datetime exactly (no Julian cutover), including leap-day
    clamping — the io/rebase.py edge family, now on the AddMonths
    path."""
    import calendar
    import datetime as dt

    epoch = dt.date(1970, 1, 1)
    cases = [dt.date(1582, 10, 4), dt.date(1500, 1, 31),
             dt.date(1600, 1, 31), dt.date(1212, 2, 29),
             dt.date(4, 2, 29), dt.date(2, 1, 31)]
    fe = SqlSession()
    fe.register_table("pg", pa.table({
        "d": pa.array([(c - epoch).days for c in cases],
                      type=pa.date32())}))
    for months, expr in ((1, "interval '1' month"),
                         (13, "interval '13' month"),
                         (-12, None)):
        sql_expr = (f"d + {expr}" if expr is not None
                    else "d - interval '1' year")
        rows = _diff(fe.sql(
            f"select d, {sql_expr} as shifted from pg order by d"),
            ordered=True)
        for d, shifted in rows:
            mi = d.year * 12 + (d.month - 1) + months
            yy, mm = divmod(mi, 12)
            want = dt.date(yy, mm + 1, min(
                d.day, calendar.monthrange(yy, mm + 1)[1]))
            assert shifted == want, (d, months, shifted, want)


def test_grouping_sets_general(nulls_fe):
    """GROUP BY GROUPING SETS beyond the rollup/cube sugar: mixed
    parenthesized/bare/empty sets, TPU == CPU, and the rollup
    equivalence (rollup(a) == grouping sets ((a), ()))."""
    rows = _diff(nulls_fe.sql(
        "select g, count(*) as n, sum(v) as sv from t "
        "group by grouping sets ((g), ()) "
        "order by g nulls last"), ordered=True)
    assert rows[-1][0] is None and rows[-1][1] == 5  # grand total
    roll = _diff(nulls_fe.sql(
        "select g, count(*) as n, sum(v) as sv from t "
        "group by rollup(g) order by g nulls last"), ordered=True)
    assert rows == roll
    # bare-expression member + duplicate-set semantics
    rows = _diff(nulls_fe.sql(
        "select g, count(*) as n from t "
        "group by grouping sets (g, ()) order by g nulls last"),
        ordered=True)
    assert rows[-1][1] == 5


def test_grouping_sets_kill_switch(nulls_fe):
    from spark_rapids_tpu.frontends import sql as sql_mod

    sql_mod.DISABLED_FEATURES.add("grouping_sets")
    try:
        with pytest.raises(SqlError):
            nulls_fe.sql("select g, count(*) as n from t "
                         "group by grouping sets ((g), ())")
    finally:
        sql_mod.DISABLED_FEATURES.discard("grouping_sets")


def test_month_interval_kill_switch(nulls_fe):
    from spark_rapids_tpu.frontends import sql as sql_mod

    sql_mod.DISABLED_FEATURES.add("month_year_interval")
    try:
        with pytest.raises(SqlError, match="month/year"):
            nulls_fe.sql("select d + interval '1' month as m from t")
    finally:
        sql_mod.DISABLED_FEATURES.discard("month_year_interval")


def test_cte_basic_and_chained(tpch):
    """WITH: one CTE, a later CTE referencing an earlier one, and two
    references to one CTE in a self-join with qualified filters (the
    TPC-DS year-over-year shape)."""
    q = """
    with big as (
      select l_orderkey, l_extendedprice from lineitem
      where l_quantity > 40),
    agg as (
      select l_orderkey, sum(l_extendedprice) rev, count(*) n
      from big group by l_orderkey)
    select count(*) as groups, sum(n) as rows_in
    from agg
    """
    rows = _diff(tpch.sql(q), expect_rows=1)
    assert rows[0][0] > 0 and rows[0][1] > 0


def test_cte_self_join_disambiguation(tpch):
    """Two references to one CTE: same-named columns disambiguate by
    qualifier; per-frame filters land on THEIR frame (the q4/q11/q74
    correctness trap: a qualifier-blind pushdown would send both
    year filters to the first frame)."""
    q = """
    with yearly as (
      select l_returnflag flag, extract(year from l_shipdate) yr,
             sum(l_extendedprice) total
      from lineitem group by l_returnflag,
           extract(year from l_shipdate))
    select a.flag, a.total, b.total
    from yearly a, yearly b
    where a.flag = b.flag and a.yr = 1994 and b.yr = 1995
    order by a.flag
    """
    rows = _diff(tpch.sql(q), ordered=True)
    assert rows, "both years exist in the fixture"
    for _flag, ta, tb in rows:
        assert ta != tb  # distinct per-frame values survived


def test_order_by_bare_aggregate(tpch):
    """ORDER BY sum(x) desc resolves against the aggregate output
    (Spark's ResolveAggregateFunctions for sort keys)."""
    q = """
    select l_returnflag, sum(l_extendedprice) as rev
    from lineitem group by l_returnflag
    order by sum(l_extendedprice) desc
    """
    rows = _diff(tpch.sql(q), ordered=True)
    revs = [r[1] for r in rows]
    assert revs == sorted(revs, reverse=True)


def test_union_parenthesized_members(tpch):
    q = """
    select l_returnflag x from lineitem where l_quantity < 2
    union all
    (select l_linestatus x from lineitem where l_quantity > 49)
    """
    a = tpch.sql(q).collect(engine="tpu")
    b = tpch.sql(q).collect(engine="cpu")
    assert sorted(a.column("x").to_pylist()) \
        == sorted(b.column("x").to_pylist())


def test_in_list_constant_fold(tpch):
    rows = _diff(tpch.sql(
        "select count(*) as n from lineitem "
        "where cast(l_quantity as int) in (10, 10 + 1, 2 * 6)"),
        expect_rows=1)
    assert rows[0][0] > 0
