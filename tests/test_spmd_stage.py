"""SPMD whole-stage execution tests (docs/spmd.md): a collective query
stage lowers to O(1) partitioned pjit programs over the 8-virtual-device
mesh — global sharded inputs (NamedSharding end-to-end), exchange rounds
as an in-program lax.scan, host syncs deferred to stage exit — with
results bit-identical to the legacy host-loop driver, plus the
`_CollectiveBase._shard_rounds` round-staging contracts the stage input
rides on."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.execs.collective  # noqa: F401  (register confs
# before any conf snapshot — they are lazily registered, like fusion's)
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.session import TpuSession, col, count, sum_

N_DEV = 8

ROUND_KEY = "spark.rapids.tpu.shuffle.collective.roundRows"
SPMD_KEY = "spark.rapids.tpu.shuffle.collective.spmd.enabled"
BUCKET_KEY = "spark.rapids.tpu.shuffle.collective.spmd.bucketRounds"
BATCH_KEY = "spark.rapids.tpu.sql.batchSizeRows"


@pytest.fixture
def collective_session():
    s = TpuSession()
    s.enable_collective_shuffle(N_DEV)
    yield s
    s.disable_collective_shuffle()


@pytest.fixture
def conf_sandbox():
    """Snapshot/restore the confs these tests tweak."""
    conf = get_conf()
    keys = (ROUND_KEY, SPMD_KEY, BUCKET_KEY, BATCH_KEY,
            "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes")
    old = {k: conf.get(k) for k in keys}
    yield conf
    for k, v in old.items():
        conf.set(k, v)


# ------------------------------------------------------------------ #
# _shard_rounds round-staging contracts
# ------------------------------------------------------------------ #


class _FakeChild:
    """Minimal child exec for driving _shard_rounds directly."""

    def __init__(self, schema: T.Schema, batches):
        self.schema = schema
        self._batches = list(batches)
        self.num_partitions = 1

    def execute_partition(self, p):
        assert p == 0
        yield from self._batches


def _int_schema():
    return T.Schema([T.Field("k", T.LONG), T.Field("v", T.LONG)])


def _batch(n_rows: int, seed: int = 0) -> ColumnarBatch:
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_numpy(
        {"k": rng.integers(0, 100, n_rows).astype(np.int64),
         "v": rng.integers(0, 100, n_rows).astype(np.int64)},
        _int_schema())


def _collective_base(mesh):
    from spark_rapids_tpu.execs.collective import _CollectiveBase

    schema = _int_schema()
    child = _FakeChild(schema, [])
    exec_ = _CollectiveBase(child)
    exec_.mesh = mesh
    exec_._init_stage(None, None)
    return exec_


@pytest.fixture
def mesh8():
    from spark_rapids_tpu.parallel.mesh import make_mesh

    return make_mesh(N_DEV)


def test_shard_rounds_least_loaded_balancing(mesh8, conf_sandbox):
    """Skewed batch sizes spread by LEAST-LOADED shard, not round
    robin: after a 900-row batch lands on one shard, the next batches
    fill the other shards before that one sees more rows."""
    exec_ = _collective_base(mesh8)
    conf_sandbox.set(ROUND_KEY, 1 << 20)  # one round
    batches = [_batch(900, seed=1)] + [_batch(100, seed=2 + i)
                                       for i in range(14)]
    child = _FakeChild(_int_schema(), batches)
    rounds = list(exec_._shard_rounds(child))
    assert len(rounds) == 1
    rows = [b.concrete_num_rows() for b in rounds[0]]
    assert sum(rows) == 900 + 14 * 100
    # the skewed batch's shard received nothing further: its load is
    # exactly 900, and every other shard got two 100-row batches
    assert sorted(rows) == [200] * 7 + [900]


def test_shard_rounds_always_yields_empties(mesh8):
    """An empty child still yields ONE round of schema-correct empty
    shard batches, so downstream stage programs emit schema-correct
    empty output."""
    exec_ = _collective_base(mesh8)
    child = _FakeChild(_int_schema(), [])
    rounds = list(exec_._shard_rounds(child))
    assert len(rounds) == 1
    assert len(rounds[0]) == N_DEV
    for b in rounds[0]:
        assert b.concrete_num_rows() == 0
        assert b.schema == _int_schema()


def test_shard_rounds_budget_boundary(mesh8, conf_sandbox):
    """A round closes exactly when SOME shard reaches the row budget
    (COLLECTIVE_ROUND_ROWS): one budget-sized batch per round when
    batches match the budget, and a trailing partial round flushes at
    end of input."""
    exec_ = _collective_base(mesh8)
    conf_sandbox.set(ROUND_KEY, 500)
    # 3 batches of exactly 500 -> each fills one shard to the budget
    # and closes a round; a final 10-row batch flushes as round 4
    child = _FakeChild(_int_schema(),
                       [_batch(500, seed=i) for i in range(3)]
                       + [_batch(10, seed=99)])
    rounds = list(exec_._shard_rounds(child))
    assert len(rounds) == 4
    for r in rounds[:3]:
        per_shard = [b.concrete_num_rows() for b in r]
        assert max(per_shard) == 500
        assert sum(per_shard) == 500
    assert sum(b.concrete_num_rows() for b in rounds[3]) == 10
    # one row under the budget does NOT close a round mid-stream
    conf_sandbox.set(ROUND_KEY, 501)
    child = _FakeChild(_int_schema(), [_batch(500, seed=5)])
    rounds = list(exec_._shard_rounds(child))
    assert len(rounds) == 1


def test_pad_rounds_pow2(mesh8):
    from spark_rapids_tpu.parallel import spmd as S

    schema = _int_schema()
    one = [[_batch(4)] * N_DEV]
    assert len(S.pad_rounds_pow2(list(one), schema, N_DEV)) == 1
    three = [[_batch(4)] * N_DEV] * 3
    padded = S.pad_rounds_pow2(list(three), schema, N_DEV)
    assert len(padded) == 4
    assert all(b.concrete_num_rows() == 0 for b in padded[-1])


# ------------------------------------------------------------------ #
# Global sharded input assembly
# ------------------------------------------------------------------ #


def test_shard_stack_rounds_is_global_and_sharded(mesh8):
    import jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_tpu.parallel import spmd as S

    rounds = [[_batch(16, seed=r * N_DEV + d) for d in range(N_DEV)]
              for r in range(2)]
    xs = S.shard_stack_rounds(rounds, mesh8)
    leaf = xs.columns[0].data
    assert leaf.shape[:2] == (2, N_DEV)
    assert leaf.sharding.spec == P(None, "data")
    assert leaf.sharding.mesh.shape["data"] == N_DEV
    # shard d's slice lives on mesh device d, not one host-stacked blob
    devices = {s.index[1].start: s.device
               for s in leaf.addressable_shards}
    assert len(devices) == N_DEV
    assert devices[0] != devices[1]
    counts = np.asarray(jax.device_get(xs.num_rows))
    assert counts.shape == (2, N_DEV)
    assert counts.sum() == 2 * N_DEV * 16


def test_mesh_key_identity(mesh8):
    from spark_rapids_tpu.parallel.mesh import make_mesh, mesh_key

    assert mesh_key(mesh8) == mesh_key(make_mesh(N_DEV))
    assert mesh_key(mesh8) != mesh_key(make_mesh(4))


def test_cached_jit_shardings_fold_into_key(mesh8):
    from spark_rapids_tpu.execs import jit_cache
    from spark_rapids_tpu.parallel import spmd as S

    key = ("spmdtestkey", 1)
    plain = jit_cache.cached_jit(key, lambda: (lambda x: x))
    sharded = jit_cache.cached_jit(
        key, lambda: (lambda x: x),
        in_shardings=(S.rounds_sharding(mesh8),),
        out_shardings=S.rounds_sharding(mesh8))
    assert plain is not sharded
    again = jit_cache.cached_jit(
        key, lambda: (lambda x: x),
        in_shardings=(S.rounds_sharding(mesh8),),
        out_shardings=S.rounds_sharding(mesh8))
    assert sharded is again


def test_choose_bounds_dynamic_matches_static():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.range_partition import (
        choose_bounds,
        choose_bounds_dynamic,
    )
    from spark_rapids_tpu.ops.sort import SortOrder

    rng = np.random.default_rng(7)
    vals = rng.integers(0, 1000, 96).astype(np.int64)
    schema = T.Schema([T.Field("k", T.LONG)])
    samples = ColumnarBatch.from_numpy({"k": vals}, schema)
    orders = [SortOrder(0)]
    static = choose_bounds(samples, orders, 8, 96).to_pydict()["k"]
    dyn = choose_bounds_dynamic(
        samples, orders, 8).to_pydict()["k"]
    assert dyn == static
    # and with a TRACED num_rows (the in-program form)
    traced = ColumnarBatch(samples.columns,
                           jnp.asarray(96, jnp.int32), schema)
    dyn2 = choose_bounds_dynamic(traced, orders, 8).to_pydict()["k"]
    assert dyn2 == static


# ------------------------------------------------------------------ #
# Whole-stage digest identity: SPMD on vs host loop off
# ------------------------------------------------------------------ #


def _canon(table: pa.Table) -> list:
    d = table.to_pydict()
    cols = sorted(d)
    return sorted(zip(*[d[c] for c in cols])) if cols else []


def _assert_same_result(session, make_df, conf):
    conf.set(SPMD_KEY, True)
    on = _canon(make_df(session).collect(engine="tpu"))
    conf.set(SPMD_KEY, False)
    off = _canon(make_df(session).collect(engine="tpu"))
    conf.set(SPMD_KEY, True)
    assert on == off
    return on


def test_spmd_agg_digest_identical_to_host_loop(collective_session,
                                                conf_sandbox):
    rng = np.random.default_rng(11)
    t = pa.table({"k": rng.integers(0, 40, 3000).astype(np.int64),
                  "v": rng.integers(0, 100, 3000).astype(np.int64)})
    conf_sandbox.set(ROUND_KEY, 256)
    conf_sandbox.set(BATCH_KEY, 128)

    def q(s):
        return (s.create_dataframe(t).group_by(col("k"))
                .agg((sum_(col("v")), "s"), (count(col("v")), "c")))

    rows = _assert_same_result(collective_session, q, conf_sandbox)
    wd = t.group_by("k").aggregate(
        [("v", "sum"), ("v", "count")]).to_pydict()
    # rows are (c, k, s) tuples (columns sorted by name)
    want = sorted(zip(wd["v_count"], wd["k"], wd["v_sum"]))
    assert rows == want


@pytest.mark.parametrize("how", [
    "inner",
    # the other types compile their own program pairs on BOTH paths —
    # covered, but in the slow tier to keep tier-1's wall bounded
    pytest.param("left_anti", marks=pytest.mark.slow),
    pytest.param("left_outer", marks=pytest.mark.slow),
    pytest.param("left_semi", marks=pytest.mark.slow),
])
def test_spmd_join_digest_identical_to_host_loop(collective_session,
                                                 conf_sandbox, how):
    rng = np.random.default_rng(13)
    lt = pa.table({"k": rng.integers(0, 30, 1200).astype(np.int64),
                   "lv": rng.integers(0, 9, 1200).astype(np.int64)})
    rt = pa.table({"k": rng.integers(0, 45, 300).astype(np.int64),
                   "rv": rng.integers(0, 9, 300).astype(np.int64)})
    conf_sandbox.set(
        "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes", -1)
    conf_sandbox.set(ROUND_KEY, 200)
    conf_sandbox.set(BATCH_KEY, 128)

    def q(s):
        return s.create_dataframe(lt).join(
            s.create_dataframe(rt), on="k", how=how)

    _assert_same_result(collective_session, q, conf_sandbox)


def test_spmd_sort_digest_identical_to_host_loop(collective_session,
                                                 conf_sandbox):
    rng = np.random.default_rng(17)
    t = pa.table({"k": rng.integers(0, 10_000, 2500).astype(np.int64),
                  "v": np.arange(2500, dtype=np.int64)})
    conf_sandbox.set(ROUND_KEY, 300)
    conf_sandbox.set(BATCH_KEY, 128)

    def run(spmd):
        conf_sandbox.set(SPMD_KEY, spmd)
        df = collective_session.create_dataframe(t).order_by(col("k"))
        d = df.collect(engine="tpu").to_pydict()
        return list(zip(d["k"], d["v"]))

    on, off = run(True), run(False)
    assert [k for k, _ in on] == sorted(t.column("k").to_pylist())
    assert on == off  # identical TOTAL order, not just sorted keys


def test_spmd_empty_input_stages(collective_session, conf_sandbox):
    conf_sandbox.set(
        "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes", -1)
    empty = pa.table({"k": pa.array([], pa.int64()),
                      "v": pa.array([], pa.int64())})
    s = collective_session
    agg = (s.create_dataframe(empty).group_by(col("k"))
           .agg((sum_(col("v")), "s"))).collect(engine="tpu")
    assert agg.num_rows == 0
    srt = s.create_dataframe(empty).order_by(col("k")) \
        .collect(engine="tpu")
    assert srt.num_rows == 0
    j = s.create_dataframe(empty).join(
        s.create_dataframe(empty), on="k", how="inner") \
        .collect(engine="tpu")
    assert j.num_rows == 0


# ------------------------------------------------------------------ #
# THE acceptance test: O(1) partitioned programs per stage
# ------------------------------------------------------------------ #


def _collective_programs(snap: dict) -> dict:
    return {k: v for k, v in snap.items()
            if v["tag"].startswith("spmd")}


def test_spmd_stage_dispatch_budget(collective_session, conf_sandbox):
    """Many exchange rounds, O(1) program dispatches: with the round
    budget forced tiny (16 rounds' worth of input), the warm agg stage
    still executes as at most bucket-chain + fold programs — the
    rounds run as an in-program scan, not a Python loop of dispatches
    — and the ledger attributes the partitioned programs with their
    mesh width and in-program round counts."""
    from spark_rapids_tpu.plan.planner import collect_exec, plan_query
    from spark_rapids_tpu.trace import ledger

    rng = np.random.default_rng(23)
    t = pa.table({"k": rng.integers(0, 64, 8192).astype(np.int64),
                  "v": rng.integers(0, 100, 8192).astype(np.int64)})
    # a round closes when one shard hits the budget; with least-loaded
    # filling that is ~8 shards x 128 rows = 1024 rows per round ->
    # 8192 rows = ~8 rounds of input in one bucket
    conf_sandbox.set(ROUND_KEY, 128)
    conf_sandbox.set(BATCH_KEY, 64)
    conf_sandbox.set(BUCKET_KEY, 8)
    df = (collective_session.create_dataframe(t).group_by(col("k"))
          .agg((sum_(col("v")), "s")))
    exec_, _ = plan_query(df._plan, collective_session.conf)
    assert "stage=spmd" in exec_.tree_string()
    rounds_seen = sum(
        node.metrics["collectiveRounds"].value
        for node in exec_._walk()
        if "collectiveRounds" in node.metrics)

    ledger.enable()
    ledger.reset_stats()
    try:
        got = collect_exec(exec_)
        ledger.LEDGER.flush(timeout=10.0)
        snap = _collective_programs(ledger.snapshot())
        dispatches = sum(p["dispatches"] for p in snap.values())
        # stage budget: bucketed scan programs + one fold — never one
        # dispatch per round
        assert 1 <= dispatches <= 4, snap
        assert all(p["devices"] == N_DEV for p in snap.values()), snap
        scan_rounds = max(p["rounds"] for p in snap.values())
        assert scan_rounds >= 8, snap  # rounds folded INTO a program
    finally:
        ledger.disable()
        ledger.reset_stats()
    want = t.group_by("k").aggregate([("v", "sum")])
    assert _canon(got) == _canon(want)


def test_spmd_explain_shows_stage_decision(collective_session,
                                           conf_sandbox):
    """The stage shape is decided by the planner seam at plan time and
    is visible in the plan report (and therefore the event log)."""
    from spark_rapids_tpu.plan.planner import plan_query

    t = pa.table({"k": pa.array([1, 2], pa.int64()),
                  "v": pa.array([3, 4], pa.int64())})
    df = (collective_session.create_dataframe(t).group_by(col("k"))
          .agg((sum_(col("v")), "s")))
    conf_sandbox.set(SPMD_KEY, False)
    exec_, _ = plan_query(df._plan, collective_session.conf)
    assert "stage=host-loop" in exec_.tree_string()
    conf_sandbox.set(SPMD_KEY, True)
    conf_sandbox.set(BUCKET_KEY, 4)
    exec_, _ = plan_query(df._plan, collective_session.conf)
    assert "stage=spmd(bucket=4)" in exec_.tree_string()
    # conf flips AFTER planning do not change the planned stage shape
    conf_sandbox.set(SPMD_KEY, False)
    assert "stage=spmd(bucket=4)" in exec_.tree_string()
