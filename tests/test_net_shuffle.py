"""Cross-process shuffle transport tests (ref: the reference's
mock-transport suites RapidsShuffleClientSuite/ServerSuite/
HeartbeatManagerTest, RapidsShuffleTestHelper.scala:53-259 — protocol
logic tested deterministically without a cluster; here a REAL second
process serves blocks over localhost TCP)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.execs.retry import is_retryable, with_task_retries
from spark_rapids_tpu.shuffle import (
    FetchFailedError,
    HeartbeatClient,
    HeartbeatManager,
    HeartbeatServer,
    ShuffleBlockServer,
    fetch_blocks,
    read_remote,
)

SCHEMA = T.Schema([T.Field("k", T.LONG), T.Field("v", T.DOUBLE)])

_SERVER_SCRIPT = r"""
import json, sys, time
from spark_rapids_tpu.platform import pin_cpu_platform
pin_cpu_platform(1)
import numpy as np
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.shuffle import ShuffleBlockServer, get_shuffle_manager

schema = T.Schema([T.Field("k", T.LONG), T.Field("v", T.DOUBLE)])
mgr = get_shuffle_manager()
sid = mgr.new_shuffle_id()
rng = np.random.default_rng(7)
expect = {}
for rid in range(3):
    tot = 0.0
    for _ in range(2):
        k = rng.integers(0, 100, 50).astype(np.int64)
        v = rng.random(50)
        mgr.write(sid, rid, ColumnarBatch.from_numpy(
            {"k": k, "v": v}, schema))
        tot += float(v.sum())
    expect[rid] = tot
srv = ShuffleBlockServer(mgr).start()
print(json.dumps({"port": srv.address[1], "shuffle_id": sid,
                  "expect": expect}), flush=True)
time.sleep(120)
"""


@pytest.fixture(scope="module")
def remote_server():
    env = dict(os.environ)
    proc = subprocess.Popen([sys.executable, "-c", _SERVER_SCRIPT],
                            stdout=subprocess.PIPE, env=env, text=True)
    line = proc.stdout.readline()
    try:
        info = json.loads(line)
    except json.JSONDecodeError:
        proc.kill()
        raise RuntimeError(f"server failed to start: {line!r}")
    yield proc, info
    if proc.poll() is None:
        proc.kill()
    proc.wait()


@pytest.mark.slow
def test_two_process_block_fetch(remote_server):
    """Real shuffle blocks cross a process boundary over localhost and
    reconstruct to device batches with the right contents."""
    proc, info = remote_server
    port, sid = info["port"], info["shuffle_id"]
    for rid in range(3):
        batches = list(read_remote("127.0.0.1", port, sid, rid, SCHEMA))
        assert len(batches) == 2  # two map writes per partition
        got = sum(float(np.asarray(b.columns[1].data)[
            : b.concrete_num_rows()].sum()) for b in batches)
        assert abs(got - info["expect"][str(rid)]) < 1e-9
    # a re-fetch works: serving is non-destructive (reducer retry)
    again = fetch_blocks("127.0.0.1", port, sid, 0)
    assert len(again) == 2


@pytest.mark.slow
def test_killed_server_triggers_retry(remote_server):
    """A dead peer surfaces FetchFailedError (retryable), and the
    retried attempt re-resolves to a live peer — the
    FetchFailedException -> task-retry contract."""
    proc, info = remote_server
    live_port, sid = info["port"], info["shuffle_id"]

    # a second server in THIS process with the same data shape
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    local_mgr = ShuffleManager()
    lsid = local_mgr.new_shuffle_id()
    local_mgr.write(lsid, 0, ColumnarBatch.from_numpy(
        {"k": np.arange(5, dtype=np.int64),
         "v": np.ones(5)}, SCHEMA))
    backup = ShuffleBlockServer(local_mgr).start()
    dead = ShuffleBlockServer(ShuffleManager()).start()
    dead_port = dead.address[1]
    dead.shutdown()  # now refuses connections

    err = None
    try:
        fetch_blocks("127.0.0.1", dead_port, lsid, 0, timeout=2.0)
    except FetchFailedError as e:
        err = e
    assert err is not None and is_retryable(err)

    peers = [("127.0.0.1", dead_port), ("127.0.0.1", backup.address[1])]
    attempt_no = [0]

    def attempt():
        # each attempt re-resolves a peer (dead first, then live)
        host, port = peers[min(attempt_no[0], len(peers) - 1)]
        attempt_no[0] += 1
        return fetch_blocks(host, port, lsid, 0, timeout=2.0)

    blocks = with_task_retries(attempt, desc="remote fetch")
    assert attempt_no[0] == 2  # first attempt failed, retry succeeded
    assert len(blocks) == 1
    backup.shutdown()


@pytest.mark.slow
def test_truncated_stream_is_fetch_failure(remote_server):
    """Killing the remote mid-exchange produces FetchFailedError, not
    a hang or partial result."""
    proc, info = remote_server
    port, sid = info["port"], info["shuffle_id"]
    # sanity fetch, then kill and observe the failure mode
    assert fetch_blocks("127.0.0.1", port, sid, 1)
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    time.sleep(0.2)
    with pytest.raises(FetchFailedError):
        fetch_blocks("127.0.0.1", port, sid, 1, timeout=2.0)


def test_fetch_reresolves_on_every_retry_after_first():
    """A moved peer is found EARLY: from the second retry on, every
    attempt re-resolves through the resolver (previously only the
    last-ditch attempt did), so with maxAttempts=4 a fetch against a
    dead address succeeds on the third attempt — one resolver call,
    not three wasted backoff rounds."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    mgr = ShuffleManager()
    sid = mgr.new_shuffle_id()
    mgr.write(sid, 0, ColumnarBatch.from_numpy(
        {"k": np.arange(4, dtype=np.int64),
         "v": np.ones(4)}, SCHEMA))
    live = ShuffleBlockServer(mgr).start()
    dead = ShuffleBlockServer(ShuffleManager()).start()
    dead_addr = dead.address
    dead.shutdown()  # refuses connections from here on

    calls = [0]

    def resolve():
        calls[0] += 1
        return live.address

    conf = get_conf()
    conf.set("spark.rapids.tpu.shuffle.fetch.maxAttempts", 4)
    conf.set("spark.rapids.tpu.shuffle.fetch.retryWaitSeconds", 0.01)
    try:
        blocks = fetch_blocks(dead_addr[0], dead_addr[1], sid, 0,
                              timeout=2.0, resolve_peer=resolve)
        # attempt 0 fails, retry 1 fails on the same dead address (no
        # resolution yet — transient resets on a live peer are the
        # common case), resolution fires, attempt 2 succeeds
        assert calls[0] == 1, calls
        assert len(blocks) == 1
    finally:
        live.shutdown()


def test_fetch_two_attempt_budget_still_reresolves():
    """maxAttempts=2 has exactly one retry — which IS the final
    attempt, so resolution must fire before it (the min clamp) rather
    than never: a moved peer is still found within the budget."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    mgr = ShuffleManager()
    sid = mgr.new_shuffle_id()
    mgr.write(sid, 0, ColumnarBatch.from_numpy(
        {"k": np.arange(4, dtype=np.int64),
         "v": np.ones(4)}, SCHEMA))
    live = ShuffleBlockServer(mgr).start()
    dead = ShuffleBlockServer(ShuffleManager()).start()
    dead_addr = dead.address
    dead.shutdown()

    calls = [0]

    def resolve():
        calls[0] += 1
        return live.address

    conf = get_conf()
    conf.set("spark.rapids.tpu.shuffle.fetch.maxAttempts", 2)
    conf.set("spark.rapids.tpu.shuffle.fetch.retryWaitSeconds", 0.01)
    try:
        blocks = fetch_blocks(dead_addr[0], dead_addr[1], sid, 0,
                              timeout=2.0, resolve_peer=resolve)
        assert calls[0] == 1, calls
        assert len(blocks) == 1
    finally:
        live.shutdown()


def test_fetch_honors_cancel_token_between_attempts():
    """A cancelled query stops reconnecting: the retry loop checks the
    cancel token between attempts, so the fetch raises QueryCancelled
    after the first failure instead of burning the whole backoff
    budget against a peer nobody will consume from."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.serving.cancel import (
        CancelToken,
        QueryCancelled,
        attach_token,
    )
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    dead = ShuffleBlockServer(ShuffleManager()).start()
    host, port = dead.address
    dead.shutdown()
    conf = get_conf()
    conf.set("spark.rapids.tpu.shuffle.fetch.maxAttempts", 5)
    conf.set("spark.rapids.tpu.shuffle.fetch.retryWaitSeconds", 0.01)
    tok = CancelToken("t0")
    tok.cancel()
    t0 = time.perf_counter()
    with attach_token(tok):
        with pytest.raises(QueryCancelled):
            fetch_blocks(host, port, 1, 0, timeout=2.0)
    # one failed connect, then the token check raised — nowhere near
    # the 5-attempt backoff budget
    assert time.perf_counter() - t0 < 2.0


def test_heartbeat_registry_peer_discovery():
    """register/heartbeat protocol (ref:
    RapidsShuffleHeartbeatManagerTest): registration returns existing
    peers, heartbeats surface only NEW peers, silence prunes."""
    mgr = HeartbeatManager(timeout_s=0.5)
    assert mgr.register("e1", "h1", 1) == []
    assert mgr.register("e2", "h2", 2) == [("e1", "h1", 1)]
    # e1's next heartbeat learns about e2, exactly once
    assert mgr.heartbeat("e1") == [("e2", "h2", 2)]
    assert mgr.heartbeat("e1") == []
    # e2 stays silent past the timeout; e1 keeps beating
    deadline = time.monotonic() + 0.8
    while time.monotonic() < deadline:
        mgr.heartbeat("e1")
        time.sleep(0.1)
    assert mgr.live_peers() == [("e1", "h1", 1)]
    with pytest.raises(KeyError):
        mgr.heartbeat("e2")  # pruned -> must re-register


def test_heartbeat_over_tcp():
    """The registry server + client round-trip over localhost."""
    srv = HeartbeatServer().start()
    try:
        host, port = srv.address
        c1 = HeartbeatClient(host, port, "ex1", "127.0.0.1", 1111)
        c2 = HeartbeatClient(host, port, "ex2", "127.0.0.1", 2222)
        c1.register()
        assert c1.peers == {}
        c2.register()
        assert c2.peers == {"ex1": ("127.0.0.1", 1111)}
        c1.heartbeat()
        assert c1.peers == {"ex2": ("127.0.0.1", 2222)}
    finally:
        srv.shutdown()


def test_plugin_lifecycle_starts_network_tier():
    """TpuPlugin with a registry address configured brings up the block
    server + heartbeat registration, and shutdown tears both down."""
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.plugin import TpuPlugin

    registry = HeartbeatServer().start()
    try:
        conf = TpuConf()
        conf.set("spark.rapids.tpu.shuffle.registry.address",
                 f"{registry.address[0]}:{registry.address[1]}")
        plugin = TpuPlugin(conf)
        try:
            assert plugin.block_server is not None
            assert plugin.heartbeat_client is not None
            assert registry.manager.live_peers(), "executor not registered"
        finally:
            plugin.shutdown()
        assert plugin.block_server is None
    finally:
        registry.shutdown()


def test_heartbeat_client_reregisters_after_prune():
    """A pruned executor (long stall) rejoins on its next beat instead
    of staying invisible forever."""
    srv = HeartbeatServer(HeartbeatManager(timeout_s=0.3)).start()
    try:
        host, port = srv.address
        c = HeartbeatClient(host, port, "ex1", "127.0.0.1", 1111)
        c.register()
        time.sleep(0.5)  # stall past the timeout -> pruned
        srv.manager.live_peers()  # trigger prune
        assert srv.manager.live_peers() == []
        c.start_background(interval_s=0.1)  # first tick re-registers
        try:
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                if srv.manager.live_peers():
                    break
                time.sleep(0.05)
            assert srv.manager.live_peers(), "client never re-registered"
        finally:
            c.stop()
    finally:
        srv.shutdown()


def test_lost_blocks_raise_not_empty():
    """Regression: a peer that never saw the shuffle (restart) must
    fail the fetch, not serve zero rows as a silently empty result."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    fresh = ShuffleBlockServer(ShuffleManager()).start()
    try:
        with pytest.raises(FetchFailedError, match="unknown shuffle"):
            fetch_blocks("127.0.0.1", fresh.address[1], 99, 0,
                         timeout=2.0)
        # ... but an EMPTY partition of a KNOWN shuffle is legit
        mgr = ShuffleManager()
        sid = mgr.new_shuffle_id()
        mgr.write(sid, 1, ColumnarBatch.from_numpy(
            {"k": np.arange(3, dtype=np.int64), "v": np.ones(3)},
            SCHEMA))
        srv2 = ShuffleBlockServer(mgr).start()
        try:
            assert fetch_blocks("127.0.0.1", srv2.address[1], sid, 0,
                                timeout=2.0) == []
        finally:
            srv2.shutdown()
    finally:
        fresh.shutdown()


def test_block_server_zlib_codec_roundtrip_and_bytes():
    """spark.rapids.tpu.shuffle.compression.codec honored on the TCP
    block tier: zlib-framed payloads round-trip exactly and the server
    accounts raw vs wire bytes (compressible data shrinks on the wire;
    ref: NvcompLZ4CompressionCodec.scala:25 compressing shuffle
    buffers)."""
    import numpy as np

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    from spark_rapids_tpu.shuffle.net import (
        ShuffleBlockServer,
        fetch_blocks,
    )

    schema = T.Schema([T.Field("k", T.LONG), T.Field("v", T.DOUBLE)])
    mgr = ShuffleManager()
    sid = mgr.new_shuffle_id()
    # highly compressible payload: constant key runs + repeated values
    k = np.repeat(np.arange(8, dtype=np.int64), 1024)
    v = np.tile(np.arange(16, dtype=np.float64), 512)
    mgr.write(sid, 0, ColumnarBatch.from_numpy({"k": k, "v": v}, schema))
    srv = ShuffleBlockServer(mgr, codec="zlib").start()
    try:
        host, port = srv.address
        blocks = fetch_blocks(host, port, sid, 0)
        assert blocks, "expected one block"
        got_k = np.concatenate([b["c0_data"] for b in blocks])
        got_v = np.concatenate([b["c1_data"] for b in blocks])
        n = int(blocks[0]["__num_rows"])
        assert n == len(k)
        np.testing.assert_array_equal(got_k[:n], k)
        np.testing.assert_array_equal(got_v[:n], v)
        stats = srv.bytes_stats()
        assert stats["raw"] > 0
        assert stats["wire"] < stats["raw"] // 4, stats  # compressed
    finally:
        srv.shutdown()
        mgr.unregister(sid)
