"""Fast Parquet decoder differential tests: io/fastpar.py (+ the native
snappy/RLE kernels) must reproduce pyarrow's read exactly for every
supported file shape, and must REFUSE (return None) anything outside
its envelope so the scan falls back (mirrors the reference's
GpuParquetScan fallback discipline)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.io import fastpar


@pytest.fixture
def session():
    from spark_rapids_tpu.session import TpuSession

    return TpuSession()


def _write(tmp_path, table, name="t.parquet", **kw):
    p = str(tmp_path / name)
    pq.write_table(table, p, **kw)
    return p


def _fast_read(path, columns=None):
    f = pq.ParquetFile(path)
    cols = columns or [c for c in f.schema_arrow.names]
    rgs = list(range(f.metadata.num_row_groups))
    return fastpar.read_file(path, rgs, cols, None, None)


def _assert_matches(path, columns=None):
    tables = _fast_read(path, columns)
    assert tables is not None, "fast path refused a supported file"
    got = pa.concat_tables(tables)
    want = pq.read_table(path, columns=columns)
    assert got.num_rows == want.num_rows
    for name in want.schema.names:
        gw, ww = got[name].combine_chunks(), want[name].combine_chunks()
        if pa.types.is_dictionary(gw.type):
            # fastpar deliberately keeps the Parquet dictionary (codes
            # ride to the device wire untouched); logical content must
            # still match the plain read
            gw = gw.cast(gw.type.value_type)
        assert gw.type == ww.type, (name, gw.type, ww.type)
        assert gw.equals(ww), name


@pytest.mark.parametrize("compression", ["snappy", "none"])
def test_low_cardinality_dict_columns(tmp_path, compression):
    rng = np.random.default_rng(7)
    t = pa.table({
        "i32": rng.integers(0, 50, 10_000).astype(np.int32),
        "i64": rng.integers(-100, 100, 10_000),
        "f32": rng.integers(0, 20, 10_000).astype(np.float32),
        "f64": rng.integers(0, 11, 10_000) / 100.0,
    })
    p = _write(tmp_path, t, compression=compression)
    _assert_matches(p)


def test_plain_fallback_pages_high_cardinality(tmp_path):
    """Dict overflow mid-chunk -> later pages PLAIN; both decode."""
    rng = np.random.default_rng(8)
    t = pa.table({
        "x": np.round(rng.uniform(0, 1e6, 300_000), 2),
        "y": rng.integers(0, 1 << 40, 300_000),
    })
    p = _write(tmp_path, t, row_group_size=150_000)
    _assert_matches(p)


def test_plain_only_no_dictionary(tmp_path):
    rng = np.random.default_rng(9)
    t = pa.table({"x": rng.random(50_000)})
    p = _write(tmp_path, t, use_dictionary=False)
    _assert_matches(p)


def test_multi_row_group_and_column_subset(tmp_path):
    rng = np.random.default_rng(10)
    t = pa.table({
        "a": rng.integers(0, 5, 40_000),
        "b": rng.random(40_000),
        "c": rng.integers(0, 3, 40_000).astype(np.int32),
    })
    p = _write(tmp_path, t, row_group_size=9_000)
    _assert_matches(p, columns=["b", "a"])


def test_dict_encoded_strings(tmp_path):
    rng = np.random.default_rng(11)
    vals = np.array(["N", "O", "F"])[rng.integers(0, 3, 20_000)]
    t = pa.table({"flag": vals, "v": rng.integers(0, 9, 20_000)})
    p = _write(tmp_path, t)
    _assert_matches(p)


def test_date_and_timestamp_logical_types(tmp_path):
    rng = np.random.default_rng(12)
    days = rng.integers(8766, 10957, 5_000).astype(np.int32)
    t = pa.table({
        "d": pa.array(days, pa.int32()).cast(pa.date32()),
        "ts": pa.array(rng.integers(0, 1 << 48, 5_000)).cast(
            pa.timestamp("us")),
    })
    p = _write(tmp_path, t)
    _assert_matches(p)


def test_nulls_now_decoded(tmp_path):
    x = pa.array([1.0, None, 3.0] * 1000)
    p = _write(tmp_path, pa.table({"x": x}))
    _assert_matches(p)  # null definition levels decode into validity


def test_nested_refused(tmp_path):
    x = pa.array([[1, 2], [3]] * 100)
    p = _write(tmp_path, pa.table({"x": x}))
    assert _fast_read(p) is None


def test_unsupported_codec_refused(tmp_path):
    t = pa.table({"x": np.arange(1000).astype(np.float64)})
    p = _write(tmp_path, t, compression="lz4")
    assert _fast_read(p) is None  # LZ4 framing stays out of scope


def test_filter_on_dictionary_lut(tmp_path):
    """Single-column pushed conjuncts evaluate on the dictionary."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.exprs.base import bind_references, lit
    from spark_rapids_tpu.session import col

    rng = np.random.default_rng(13)
    disc = rng.integers(0, 11, 30_000) / 100.0
    qty = rng.integers(1, 51, 30_000).astype(np.float64)
    t = pa.table({"disc": disc, "qty": qty})
    p = _write(tmp_path, t)

    schema = T.Schema([T.Field("disc", T.DOUBLE), T.Field("qty", T.DOUBLE)])
    conj = [bind_references(col("disc") >= lit(0.05), schema),
            bind_references(col("disc") <= lit(0.07), schema),
            bind_references(col("qty") < lit(24.0), schema)]
    tables = fastpar.read_file(p, [0], ["disc", "qty"], conj, schema)
    assert tables is not None
    got = pa.concat_tables(tables)
    mask = (disc >= 0.05) & (disc <= 0.07) & (qty < 24.0)
    assert got.num_rows == int(mask.sum())
    np.testing.assert_array_equal(
        np.asarray(got["disc"]), disc[mask])
    np.testing.assert_array_equal(np.asarray(got["qty"]), qty[mask])


def test_scan_exec_uses_fast_path_end_to_end(tmp_path, session):
    """Full session query over a fast-decodable file matches the CPU
    engine, and flipping the conf off gives the same answer."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.exprs.base import lit
    from spark_rapids_tpu.io.scan import FAST_DECODE
    from spark_rapids_tpu.session import col, sum_

    rng = np.random.default_rng(14)
    t = pa.table({
        "k": rng.integers(0, 9, 50_000),
        "price": np.round(rng.uniform(1, 1000, 50_000), 2),
        "disc": rng.integers(0, 11, 50_000) / 100.0,
    })
    p = _write(tmp_path, t)

    def q():
        return (session.read_parquet(p)
                .where((col("disc") >= lit(0.03)) & (col("disc") <= lit(0.08)))
                .agg((sum_(col("price") * col("disc")), "rev")))

    want = q().collect(engine="cpu").to_pydict()["rev"][0]
    got_fast = q().collect(engine="tpu").to_pydict()["rev"][0]
    try:
        get_conf().set("spark.rapids.tpu.sql.scan.fastDecode", False)
        got_slow = q().collect(engine="tpu").to_pydict()["rev"][0]
    finally:
        get_conf().set("spark.rapids.tpu.sql.scan.fastDecode", True)
    assert abs(got_fast - want) <= 1e-6 * max(1.0, abs(want))
    assert abs(got_fast - got_slow) <= 1e-9 * max(1.0, abs(got_slow))


def test_fast_decode_conf_actually_disables(tmp_path, session,
                                            monkeypatch):
    """Regression: the conf is read on the SESSION thread (thread-local
    conf does not exist on the prefetch producer thread), so setting it
    False must prevent any fastpar call."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.session import col, sum_

    t = pa.table({"x": np.arange(1000) / 7.0})
    p = _write(tmp_path, t)
    calls = []
    real = fastpar.read_file
    monkeypatch.setattr(fastpar, "read_file",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    df = session.read_parquet(p).agg((sum_(col("x")), "s"))
    try:
        get_conf().set("spark.rapids.tpu.sql.scan.fastDecode", False)
        df.collect(engine="tpu")
        assert not calls, "fast path ran with fastDecode=False"
        get_conf().set("spark.rapids.tpu.sql.scan.fastDecode", True)
        df.collect(engine="tpu")
        assert calls, "fast path did not run with fastDecode=True"
    finally:
        get_conf().set("spark.rapids.tpu.sql.scan.fastDecode", True)


def test_native_snappy_roundtrip():
    """Native snappy decode vs pyarrow's reference codec."""
    from spark_rapids_tpu import native

    lib = native.load()
    if lib is None:
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(15)
    for data in (
        rng.integers(0, 5, 100_000).astype(np.uint8).tobytes(),
        rng.integers(0, 256, 100_000).astype(np.uint8).tobytes(),
        b"a" * 70_000,
        b"",
        bytes(rng.integers(0, 3, 10).astype(np.uint8)) * 9000,
    ):
        comp = pa.Codec("snappy").compress(data)
        out = fastpar._snappy_decompress(
            comp.to_pybytes() if hasattr(comp, "to_pybytes") else comp,
            len(data))
        assert out is not None
        assert out.tobytes() == data


@pytest.mark.parametrize("compression", ["snappy", "gzip", "zstd",
                                         "none"])
def test_nulls_and_codecs(tmp_path, compression):
    """Definition levels with REAL nulls decode into validity; gzip and
    zstd pages decode; content matches the pyarrow read exactly."""
    rng = np.random.default_rng(3)
    n = 6000
    t = pa.table({
        "i": pa.array([None if rng.random() < 0.15 else int(v)
                       for v in rng.integers(0, 40, n)], pa.int64()),
        "f": pa.array([None if rng.random() < 0.05 else float(v)
                       for v in rng.integers(0, 9, n)], pa.float64()),
        "dense": rng.integers(0, 1000, n),
    })
    p = _write(tmp_path, t, compression=compression)
    _assert_matches(p)


def test_null_aware_filter_on_dict(tmp_path):
    """Predicates over null-carrying dict columns must keep SQL null
    semantics (null predicate result drops the row) in the host filter."""
    t = pa.table({
        "k": pa.array([1, None, 3, None, 1, 3] * 500, pa.int64()),
        "v": pa.array(np.arange(3000.0)),
    })
    p = _write(tmp_path, t)
    from spark_rapids_tpu.session import TpuSession, col
    from spark_rapids_tpu.exprs.base import lit

    s = TpuSession()
    df = s.read_parquet(p).where(col("k") >= lit(2))
    a = df.collect(engine="tpu")
    b = df.collect(engine="cpu")
    assert a.num_rows == b.num_rows == 1000
    assert sorted(a.to_pydict()["v"]) == sorted(b.to_pydict()["v"])


def test_is_null_predicate_on_null_dict_column(tmp_path, session):
    """IS NULL pushed onto a null-carrying dict column must KEEP the
    null rows in the host filter (null-input result is True)."""
    from spark_rapids_tpu.exprs.predicates import IsNull
    from spark_rapids_tpu.session import col

    t = pa.table({"x": pa.array([1, None, 2, None, 1] * 100,
                                pa.int64())})
    p = _write(tmp_path, t)
    df = session.read_parquet(p).where(IsNull(col("x")))
    a = df.collect(engine="tpu")
    b = df.collect(engine="cpu")
    assert a.num_rows == b.num_rows == 200
