"""The 99-query TPC-DS sweep harness (tools/sweep.py, SWEEP_r01.json).

Tier-1 keeps this LEAN: the full execute+oracle sweep over all 99
queries is the offline artifact run (`python -m
spark_rapids_tpu.tools.sweep`); here we assert the harness machinery —
classification stages, failure taxonomy, the satellite fix probes —
plus a full-corpus PARSE pass (cheap) and a 3-query end-to-end slice,
and that the committed artifact satisfies the coverage floors.
"""

import json
import os

import pytest

from spark_rapids_tpu.tools import sweep as SW
from spark_rapids_tpu.tools.tpcds_queries import QUERIES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_corpus_is_complete():
    assert sorted(QUERIES) == list(range(1, 100))
    assert all(q.strip().lower().startswith(("select", "with"))
               for q in QUERIES.values())


def test_full_corpus_parse_floor():
    """Cheap parse-only pass over ALL 99 texts: the grammar accepts at
    least the BASELINE floor (>= 40) — a parser regression that drops
    whole query families fails here, without paying execution."""
    from spark_rapids_tpu.frontends.sql import SqlError, _Parser

    parsed = 0
    for qid, text in QUERIES.items():
        try:
            _Parser(text).parse_select()
            parsed += 1
        except SqlError:
            pass
    assert parsed >= 40, f"only {parsed}/99 parsed"


def test_three_query_slice_end_to_end():
    """q3 (the anchor), q27 (GROUPING SETS satellite), q37 (month
    interval satellite) classify as correct vs the CPU oracle, and the
    fix probes attribute each satellite advance."""
    fe = SW.build_session()
    results = {}
    for qid in (3, 27, 37):
        results[f"q{qid}"] = SW.classify_query(fe, QUERIES[qid])
        assert results[f"q{qid}"]["status"] == "correct", \
            (qid, results[f"q{qid}"])
    adv = SW.fix_probes(fe, {q: QUERIES[q] for q in (3, 27, 37)},
                        results)
    assert "q27" in adv["grouping_sets"]
    assert "q37" in adv["month_year_interval"]
    assert "q3" not in adv["grouping_sets"]


def test_taxonomy_classifier():
    assert SW._classify_reason(
        "set-op INTERSECT blah") == "set-op INTERSECT not supported"
    assert SW._classify_reason("unknown function 'stddev_samp'") \
        == "unknown function"
    assert SW._classify_reason("no idea") == "other"


def test_committed_artifact_meets_floors():
    """SWEEP_r01.json (the committed artifact) satisfies the
    BASELINE #5 acceptance floors: >= 40 parsed, >= 20 executed AND
    correct vs the CPU oracle with q3/q67 among them, each satellite
    fix advancing >= 1 query, and the wire subset digest-matching."""
    path = os.path.join(REPO, "SWEEP_r01.json")
    if not os.path.exists(path):
        pytest.skip("SWEEP_r01.json not committed yet")
    with open(path) as f:
        rep = json.load(f)
    t = rep["totals"]
    assert t["queries"] == 99
    assert t["parsed"] >= 40
    assert t["correct"] >= 20
    for q in ("q3", "q67"):
        assert rep["queries"][q]["status"] == "correct", \
            rep["queries"][q]
    adv = rep["satellite_advances"]
    for feature in SW.FIX_FEATURES:
        assert len(adv[feature]) >= 1, (feature, adv)
    for name, v in rep["wire"].items():
        assert v["status"] == "ok" and v["digest_match"], (name, v)
