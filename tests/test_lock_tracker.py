"""Runtime lock-order/deadlock tracker tests
(robustness/lock_tracker.py, docs/concurrency.md): cycle detection at
formation time, per-name stats bookkeeping, the disarmed fast path,
the conf sync_conf ownership discipline (faults/tracer idiom), the
eventlog lock.* counter surface, and HC014."""

import threading

import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.robustness import lock_tracker as LT

ENABLED = "spark.rapids.tpu.robustness.lockTracker.enabled"


@pytest.fixture(autouse=True)
def _disarmed():
    LT.disarm()
    LT.reset_stats()  # disarm keeps counters; tests start from zero
    yield
    LT.disarm()
    LT.reset_stats()


# ------------------------------------------------------------------ #
# cycle detection
# ------------------------------------------------------------------ #


def test_two_lock_cycle_raises_at_formation():
    """THE acceptance behavior: a->b established, then b->a attempted
    on the SAME thread — the acquisition that would deadlock under the
    right interleaving raises right there, before any wait."""
    LT.install(forced=True)
    a = LT.tracked_lock("t.a")
    b = LT.tracked_lock("t.b")
    with a:
        with b:
            pass
    assert LT.order_graph() == {"t.a": ["t.b"]}
    with b:
        with pytest.raises(LT.LockCycleError) as ei:
            a.acquire()
    assert ei.value.edge == ("t.b", "t.a")
    assert ei.value.path == ["t.a", "t.b"]
    assert LT.cycle_count() == 1
    # the refused acquisition took nothing: both locks reacquirable
    with a:
        pass
    with b:
        pass


def test_transitive_cycle_detected_through_the_graph():
    """a->b and b->c observed on separate code paths; c->a is a cycle
    even though no single scope ever nested all three."""
    LT.install(forced=True)
    a, b, c = (LT.tracked_lock(n) for n in ("t3.a", "t3.b", "t3.c"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(LT.LockCycleError) as ei:
            a.acquire()
    assert ei.value.path == ["t3.a", "t3.b", "t3.c"]


def test_same_name_reacquisition_is_not_a_cycle():
    """Two INSTANCES sharing a name (two sessions' plan-cache mutexes)
    pool their identity; nesting one under the other neither edges nor
    raises (a self-edge would poison every per-instance lock class)."""
    LT.install(forced=True)
    a1 = LT.tracked_lock("pool.mu")
    a2 = LT.tracked_lock("pool.mu")
    with a1:
        with a2:
            pass
    assert LT.order_graph() == {}
    assert LT.cycle_count() == 0


def test_reentrant_lock_reentry_makes_no_edge():
    LT.install(forced=True)
    r = LT.tracked_lock("t.r", reentrant=True)
    with r:
        with r:   # owning-thread re-entry: no edge, no new frame
            pass
    assert LT.order_graph() == {}
    st = LT.lock_stats()["t.r"]
    assert st["acquisitions"] == 1  # outermost only


def test_nonblocking_acquire_never_raises_cycle():
    """acquire(blocking=False) gives up instead of waiting — not a
    deadlock hazard, so it records the acquisition but never refuses."""
    LT.install(forced=True)
    a = LT.tracked_lock("nb.a")
    b = LT.tracked_lock("nb.b")
    with a:
        with b:
            pass
    with b:
        assert a.acquire(blocking=False) is True
        a.release()
    assert LT.cycle_count() == 0


# ------------------------------------------------------------------ #
# stats bookkeeping
# ------------------------------------------------------------------ #


def test_lock_stats_exact_bookkeeping():
    LT.install(forced=True)
    a = LT.tracked_lock("s.a")
    b = LT.tracked_lock("s.b")
    for _ in range(3):
        with a:
            pass
    with b:
        pass
    st = LT.lock_stats()
    assert st["s.a"]["acquisitions"] == 3
    assert st["s.b"]["acquisitions"] == 1
    agg = LT.aggregate_stats()
    assert agg["acquisitions"] == 4
    assert agg["cycles"] == 0
    assert agg["max_hold_ms"] == max(
        v["max_hold_ms"] for v in st.values())


def test_contention_wait_is_counted():
    LT.install(forced=True)
    a = LT.tracked_lock("c.a")
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with a:
            entered.set()
            release.wait(5.0)

    th = threading.Thread(target=holder)
    th.start()
    entered.wait(5.0)
    got = []

    def contender():
        with a:
            got.append(True)

    th2 = threading.Thread(target=contender)
    th2.start()
    th2.join(0.2)
    release.set()
    th.join(5.0)
    th2.join(5.0)
    assert got == [True]
    st = LT.lock_stats()["c.a"]
    assert st["acquisitions"] == 2
    assert st["contention_waits"] == 1
    assert st["max_hold_ms"] > 0


def test_reset_stats_keeps_armed_state():
    LT.install(forced=True)
    a = LT.tracked_lock("rs.a")
    with a:
        pass
    assert LT.lock_stats()["rs.a"]["acquisitions"] == 1
    LT.reset_stats()
    assert LT.tracker_armed()
    assert LT.lock_stats() == {}
    with a:
        pass
    assert LT.lock_stats()["rs.a"]["acquisitions"] == 1


# ------------------------------------------------------------------ #
# disarmed fast path + arm/disarm transitions
# ------------------------------------------------------------------ #


def test_disarmed_records_nothing_and_passes_through():
    a = LT.tracked_lock("d.a")
    b = LT.tracked_lock("d.b")
    with a:
        with b:
            pass
    with b:
        with a:   # would be a cycle if tracked — disarmed, it is not
            pass
    assert LT.lock_stats() == {}
    assert LT.order_graph() == {}
    assert LT.cycle_count() == 0


def test_release_after_disarm_mid_hold_is_safe():
    """Arm state can flip between acquire and release (a query
    boundary disarms while a worker holds a lock): release must not
    corrupt the thread's stack or the inner lock either way."""
    a = LT.tracked_lock("flip.a")
    LT.install(forced=True)
    a.acquire()
    LT.disarm()
    a.release()   # armed-acquired, disarmed-released
    a.acquire()   # disarmed-acquired...
    LT.install(forced=True)
    a.release()   # ...armed-released: tolerated, no phantom frame
    with a:
        pass
    assert LT.lock_stats()["flip.a"]["acquisitions"] == 1


# ------------------------------------------------------------------ #
# conf ownership (faults/tracer sync_conf idiom)
# ------------------------------------------------------------------ #


def test_sync_conf_arms_and_only_owner_disarms():
    conf = get_conf()
    conf.set(ENABLED, True)
    LT.sync_conf(conf)
    assert LT.tracker_armed()
    other = type(conf)()   # a second session's default conf
    LT.sync_conf(other)    # non-owner default must NOT disarm
    assert LT.tracker_armed()
    conf.set(ENABLED, False)
    LT.sync_conf(conf)     # the owner's disable does
    assert not LT.tracker_armed()


def test_forced_install_survives_sync_conf():
    LT.install(forced=True)
    conf = get_conf()
    assert not conf.get(
        "spark.rapids.tpu.robustness.lockTracker.enabled")
    LT.sync_conf(conf)     # default conf, forced install: no disarm
    assert LT.tracker_armed()


# ------------------------------------------------------------------ #
# eventlog + HC014 surface
# ------------------------------------------------------------------ #


def test_eventlog_counters_carry_lock_surface():
    from spark_rapids_tpu.eventlog import (
        MONOTONIC_COUNTERS,
        counters_snapshot,
    )

    for k in ("lock.acquisitions", "lock.contention_waits",
              "lock.cycles"):
        assert k in MONOTONIC_COUNTERS
    assert "lock.max_hold_ms" not in MONOTONIC_COUNTERS  # gauge
    LT.install(forced=True)
    a = LT.tracked_lock("ev.a")
    with a:
        pass
    snap = counters_snapshot()
    assert snap["lock.acquisitions"] >= 1
    assert snap["lock.cycles"] == 0
    assert snap["lock.max_hold_ms"] >= 0


def test_hc014_lock_hold_over_budget():
    from spark_rapids_tpu.tools.history import (
        ApplicationInfo,
        _query_from_record,
        health_check,
    )

    def rules(counters):
        rec = _query_from_record({
            "query_id": 0, "plan": "", "plan_hash": "x",
            "engine": "tpu", "wall_s": 1.0, "counters": counters})
        app = ApplicationInfo("x", "eventlog", {}, [rec])
        return {f.rule for f in health_check(app)}

    # over budget (default 250ms) -> fires
    assert "HC014" in rules({"lock.max_hold_ms": 900.0})
    # under budget, or tracker-off all-zero record -> silent
    assert "HC014" not in rules({"lock.max_hold_ms": 3.0})
    assert "HC014" not in rules({})
    # conf moves the budget
    get_conf().set(
        "spark.rapids.tpu.robustness.lockTracker.holdBudgetMs", 2.0)
    assert "HC014" in rules({"lock.max_hold_ms": 3.0})
