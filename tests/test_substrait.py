"""Substrait frontend tests: a FOREIGN plan format executes through the
plugin seam (ref: Plugin.scala:45-52 — the reference intercepts plans
someone else built; here the someone else is any Substrait producer)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.frontends.substrait import (
    SubstraitError,
    SubstraitFrontend,
)
from spark_rapids_tpu.plugin import TpuPlugin, frontend


def _sel(i):
    return {"selection": {"directReference": {"structField": {"field": i}}}}


def _fn(anchor, *args):
    return {"scalarFunction": {"functionReference": anchor,
                               "arguments": [{"value": a} for a in args]}}


def _lit(key, v):
    return {"literal": {key: v}}


def _extensions(names):
    return [{"extensionFunction": {"functionAnchor": i, "name": n}}
            for i, n in enumerate(names, start=1)]


def _q6_plan():
    """TPC-H q6 as the Substrait JSON a producer would emit:
    read(lineitem) -> filter(shipdate/discount/quantity window) ->
    project(extendedprice * discount) -> aggregate(sum)."""
    fns = ["gte:fp64_fp64", "lt:fp64_fp64", "lte:fp64_fp64",
           "and:bool", "multiply:fp64_fp64", "sum:fp64"]
    GTE, LT, LTE, AND, MUL, _SUM = 1, 2, 3, 4, 5, 6
    cond = _fn(AND,
               _fn(GTE, _sel(3), _lit("i32", 8766)),
               _fn(LT, _sel(3), _lit("i32", 9131)),
               _fn(GTE, _sel(2), _lit("fp64", 0.05)),
               _fn(LTE, _sel(2), _lit("fp64", 0.07)),
               _fn(LT, _sel(0), _lit("fp64", 24.0)))
    return {
        "extensions": _extensions(fns),
        "relations": [{"root": {
            "names": ["revenue"],
            "input": {"aggregate": {
                "input": {"project": {
                    "common": {"emit": {"outputMapping": [4]}},
                    "input": {"filter": {
                        "input": {"read": {"namedTable": {
                            "names": ["lineitem"]}}},
                        "condition": cond,
                    }},
                    "expressions": [_fn(MUL, _sel(1), _sel(2))],
                }},
                "groupings": [],
                "measures": [{"measure": {
                    "functionReference": _SUM,
                    "arguments": [{"value": _sel(0)}]}}],
            }},
        }}],
    }


@pytest.fixture
def lineitem(tmp_path):
    rng = np.random.default_rng(42)
    n = 60_000
    t = pa.table({
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900, 105000, n), 2),
        "l_discount": rng.integers(0, 11, n) / 100.0,
        "l_shipdate": rng.integers(8766, 10957, n).astype(np.int32),
    })
    p = str(tmp_path / "lineitem.parquet")
    pq.write_table(t, p)
    return t, p


def test_q6_foreign_plan_runs_on_tpu(lineitem):
    """TPC-H q6 submitted as a Substrait plan executes on the TPU
    engine and matches the oracle computed directly from the data."""
    t, path = lineitem
    fe = TpuPlugin.get_or_create().session("substrait")
    assert isinstance(fe, SubstraitFrontend)
    fe.register_table("lineitem", path)

    df = fe.dataframe(_q6_plan())
    explain = df.explain()
    assert "Filter" in explain and "Aggregate" in explain, explain
    out = df.collect(engine="tpu").to_pydict()

    q = np.asarray(t["l_quantity"])
    price = np.asarray(t["l_extendedprice"])
    disc = np.asarray(t["l_discount"])
    ship = np.asarray(t["l_shipdate"])
    mask = ((ship >= 8766) & (ship < 9131) & (disc >= 0.05)
            & (disc <= 0.07) & (q < 24.0))
    want = float((price[mask] * disc[mask]).sum())
    assert abs(out["revenue"][0] - want) <= 1e-6 * max(1.0, abs(want))
    # the plan genuinely ran through the TPU planner
    cpu = df.collect(engine="cpu").to_pydict()
    assert abs(cpu["revenue"][0] - want) <= 1e-6 * max(1.0, abs(want))


def test_seam_resolves_by_name(lineitem):
    """plugin.frontend('substrait') resolves without manual imports."""
    factory = frontend("substrait")
    fe = factory(None)
    assert isinstance(fe, SubstraitFrontend)


def test_filter_project_sort_fetch(lineitem):
    t, path = lineitem
    fe = SubstraitFrontend()
    fe.register_table("lineitem", path)
    fns = ["lt:fp64_fp64"]
    plan = {
        "extensions": _extensions(fns),
        "relations": [{"root": {
            "names": ["qty", "disc"],
            "input": {"fetch": {
                "count": 5,
                "input": {"sort": {
                    "sorts": [{"expr": _sel(0),
                               "direction":
                               "SORT_DIRECTION_DESC_NULLS_LAST"}],
                    "input": {"project": {
                        "common": {"emit": {"outputMapping": [0, 2]}},
                        "input": {"filter": {
                            "input": {"read": {"namedTable": {
                                "names": ["lineitem"]}}},
                            "condition": _fn(1, _sel(0),
                                             _lit("fp64", 3.0)),
                        }},
                        "expressions": [],
                    }},
                }},
            }},
        }}],
    }
    out = fe.execute_plan(plan, engine="tpu")
    assert out.num_rows == 5
    assert out.column_names == ["qty", "disc"]
    assert all(v < 3.0 for v in out.to_pydict()["qty"])


def test_join_plan(tmp_path):
    fe = SubstraitFrontend()
    left = pa.table({"k": pa.array([1, 2, 3, 4], pa.int64()),
                     "v": pa.array([10.0, 20.0, 30.0, 40.0])})
    right = pa.table({"k2": pa.array([2, 4, 9], pa.int64()),
                      "w": pa.array([200, 400, 900], pa.int64())})
    fe.register_table("l", left)
    fe.register_table("r", right)
    fns = ["equal:any_any"]
    plan = {
        "extensions": _extensions(fns),
        "relations": [{"root": {
            "names": ["k", "v", "k2", "w"],
            "input": {"join": {
                "type": "JOIN_TYPE_INNER",
                "left": {"read": {"namedTable": {"names": ["l"]}}},
                "right": {"read": {"namedTable": {"names": ["r"]}}},
                "expression": _fn(1, _sel(0), _sel(2)),
            }},
        }}],
    }
    out = fe.execute_plan(plan, engine="tpu").to_pydict()
    assert sorted(zip(out["k"], out["w"])) == [(2, 200), (4, 400)]


def test_unsupported_rel_raises():
    fe = SubstraitFrontend()
    with pytest.raises(SubstraitError, match="not supported"):
        fe.dataframe({"relations": [{"root": {"input": {
            "exchange": {}}, "names": []}}]})


def test_unsupported_scalar_function_raises():
    fe = SubstraitFrontend()
    fe.register_table("t", pa.table({"x": pa.array([1.0])}))
    plan = {
        "extensions": _extensions(["sqrt_banana:fp64"]),
        "relations": [{"root": {
            "names": ["y"],
            "input": {"project": {
                "common": {"emit": {"outputMapping": [1]}},
                "input": {"read": {"namedTable": {"names": ["t"]}}},
                "expressions": [_fn(1, _sel(0))],
            }},
        }}],
    }
    with pytest.raises(SubstraitError, match="sqrt_banana"):
        fe.dataframe(plan)


def test_translatable_but_tpu_unsupported_falls_back(lineitem):
    """A foreign plan whose expression translates but is outside TPU
    support (decimal divide) runs via CPU fallback — correct answer,
    no crash, fallback visible in explain."""
    fe = SubstraitFrontend()
    import decimal

    fe.register_table("t", pa.table({
        "d": pa.array([decimal.Decimal("1.50"),
                       decimal.Decimal("2.25")]),
        "x": pa.array([1.0, 2.0])}))
    fns = ["divide:dec_dec"]
    plan = {
        "extensions": _extensions(fns),
        "relations": [{"root": {
            "names": ["q"],
            "input": {"project": {
                "common": {"emit": {"outputMapping": [2]}},
                "input": {"read": {"namedTable": {"names": ["t"]}}},
                "expressions": [_fn(1, _sel(0), _sel(0))],
            }},
        }}],
    }
    df = fe.dataframe(plan)
    explain = df.explain()
    assert "cannot run on TPU" in explain or "CPU" in explain, explain
    out = df.collect(engine="tpu").to_pydict()
    assert out["q"] == [1.0, 1.0]
