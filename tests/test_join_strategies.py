"""Join physical-strategy tests: broadcast vs partition-wise vs local,
plus keyless nested-loop joins — all differential vs the CPU oracle."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
from spark_rapids_tpu.execs.join import (
    TpuBroadcastHashJoinExec,
    TpuShuffledHashJoinExec,
)
from spark_rapids_tpu.plan.planner import BROADCAST_THRESHOLD, plan_query
from spark_rapids_tpu.session import TpuSession, col
from tests.differential import assert_tpu_cpu_equal


pytestmark = pytest.mark.slow  # TPC/fuzz/stress tier


@pytest.fixture
def session():
    return TpuSession()


def _fact(n=500, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "fk": rng.integers(0, 40, n),
        "x": rng.integers(0, 1000, n).astype(np.int64),
    })


def _dim(n=40):
    return pa.table({
        "id": np.arange(n, dtype=np.int64),
        "name": [f"dim-{i}" for i in range(n)],
    })


def _exec_types(df):
    exec_, _ = plan_query(df._plan, get_conf())
    out = set()

    def walk(e):
        out.add(type(e))
        for c in e.children:
            walk(c)

    walk(exec_)
    return out


def test_small_dim_side_broadcasts(session):
    fact = session.create_dataframe(_fact())
    dim = session.create_dataframe(_dim())
    df = fact.join(dim, left_on=[col("fk")], right_on=[col("id")])
    types = _exec_types(df)
    assert TpuBroadcastHashJoinExec in types
    assert TpuShuffleExchangeExec not in types  # neither side shuffles
    assert_tpu_cpu_equal(df)


def test_small_left_side_broadcasts_for_inner(session):
    dim = session.create_dataframe(_dim())
    fact = session.create_dataframe(_fact())
    df = dim.join(fact, left_on=[col("id")], right_on=[col("fk")])
    types = _exec_types(df)
    assert TpuBroadcastHashJoinExec in types
    assert_tpu_cpu_equal(df)


@pytest.mark.parametrize("how", ["left_outer", "left_semi", "left_anti"])
def test_broadcast_outer_semi_anti(session, how):
    rng = np.random.default_rng(4)
    fact = session.create_dataframe(pa.table({
        "fk": rng.integers(0, 60, 300),  # some keys miss the dim table
        "x": rng.integers(0, 9, 300).astype(np.int64)}))
    dim = session.create_dataframe(_dim())
    df = fact.join(dim, left_on=[col("fk")], right_on=[col("id")],
                   how=how)
    assert TpuBroadcastHashJoinExec in _exec_types(df)
    assert_tpu_cpu_equal(df)


def test_partition_wise_join_when_both_sides_large(session):
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS

    conf = get_conf()
    old = conf.get(BROADCAST_THRESHOLD)
    old_bs = conf.get(BATCH_SIZE_ROWS)
    conf.set(BROADCAST_THRESHOLD.key, 0)  # no side may broadcast
    conf.set(BATCH_SIZE_ROWS.key, 512)  # force multi-partition sources
    try:
        rng = np.random.default_rng(9)
        a = session.create_dataframe(pa.table({
            "k": rng.integers(0, 50, 4000),
            "va": rng.integers(0, 100, 4000).astype(np.int64)}))
        b = session.create_dataframe(pa.table({
            "k": rng.integers(0, 50, 4000),
            "vb": rng.integers(0, 100, 4000).astype(np.int64)}))
        df = a.join(b, on="k")
        # adaptive on (the default): the planner defers the
        # partition-wise shape behind an adaptive join over exchanges
        from spark_rapids_tpu.execs.adaptive import TpuAdaptiveJoinExec

        exec_, _ = plan_query(df._plan, conf)
        assert isinstance(exec_, TpuAdaptiveJoinExec)
        assert TpuShuffleExchangeExec in _exec_types(df)
        assert_tpu_cpu_equal(df)

        # adaptive off: the static partition-wise plan
        from spark_rapids_tpu.execs.adaptive import ADAPTIVE_ENABLED

        old_adaptive = conf.get(ADAPTIVE_ENABLED)
        conf.set(ADAPTIVE_ENABLED.key, False)
        try:
            exec_, _ = plan_query(df._plan, conf)
            assert isinstance(exec_, TpuShuffledHashJoinExec)
            assert exec_.partition_wise
            assert exec_.num_partitions > 1
            assert_tpu_cpu_equal(df)
        finally:
            conf.set(ADAPTIVE_ENABLED.key, old_adaptive)
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old)
        conf.set(BATCH_SIZE_ROWS.key, old_bs)


def test_partition_wise_full_outer(session):
    conf = get_conf()
    old = conf.get(BROADCAST_THRESHOLD)
    conf.set(BROADCAST_THRESHOLD.key, 0)
    try:
        rng = np.random.default_rng(2)
        a = session.create_dataframe(pa.table({
            "k": rng.integers(0, 30, 3000),
            "va": rng.integers(0, 100, 3000).astype(np.int64)}))
        b = session.create_dataframe(pa.table({
            "k": rng.integers(20, 60, 3000),
            "vb": rng.integers(0, 100, 3000).astype(np.int64)}))
        df = a.join(b, on="k", how="full_outer")
        assert_tpu_cpu_equal(df)
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old)


def test_join_reuses_aggregate_distribution(session):
    """EnsureRequirements: a final aggregate is already hash-partitioned
    on its group keys; joining on those keys must not re-shuffle it."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.session import sum_

    conf = get_conf()
    old = conf.get(BROADCAST_THRESHOLD)
    old_bs = conf.get(BATCH_SIZE_ROWS)
    conf.set(BROADCAST_THRESHOLD.key, 0)
    conf.set(BATCH_SIZE_ROWS.key, 512)
    try:
        rng = np.random.default_rng(6)
        a = session.create_dataframe(pa.table({
            "k": rng.integers(0, 30, 3000),
            "v": rng.integers(0, 100, 3000).astype(np.int64)}))
        agg = a.group_by("k").agg((sum_("v"), "s"))
        b = session.create_dataframe(pa.table({
            "k": rng.integers(0, 30, 3000),
            "w": rng.integers(0, 100, 3000).astype(np.int64)}))
        df = agg.join(b, on="k")
        exec_, _ = plan_query(df._plan, conf)
        assert isinstance(exec_, TpuShuffledHashJoinExec)
        assert exec_.partition_wise
        # left child is the final aggregate itself, not a fresh exchange
        assert not isinstance(exec_.children[0], TpuShuffleExchangeExec)
        assert_tpu_cpu_equal(df)
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old)
        conf.set(BATCH_SIZE_ROWS.key, old_bs)


def test_keyless_conditional_inner_join(session):
    # nested loop: inner join on an arbitrary range condition, no keys
    a = session.create_dataframe(pa.table(
        {"x": np.arange(30, dtype=np.int64)}))
    b = session.create_dataframe(pa.table(
        {"lo": np.array([0, 10, 25], np.int64),
         "hi": np.array([5, 12, 40], np.int64)}))
    df = a.join(b, condition=(col("x") >= col("lo"))
                & (col("x") < col("hi")))
    assert_tpu_cpu_equal(df)


def test_full_outer_never_broadcasts(session):
    fact = session.create_dataframe(_fact())
    dim = session.create_dataframe(_dim())
    df = fact.join(dim, left_on=[col("fk")], right_on=[col("id")],
                   how="full_outer")
    assert TpuBroadcastHashJoinExec not in _exec_types(df)
    assert_tpu_cpu_equal(df)


def test_broadcast_disabled_by_threshold(session):
    conf = get_conf()
    old = conf.get(BROADCAST_THRESHOLD)
    conf.set(BROADCAST_THRESHOLD.key, -1)
    try:
        fact = session.create_dataframe(_fact())
        dim = session.create_dataframe(_dim())
        df = fact.join(dim, left_on=[col("fk")], right_on=[col("id")])
        assert TpuBroadcastHashJoinExec not in _exec_types(df)
        assert_tpu_cpu_equal(df)
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old)
