"""Expression batch 5: CreateArray, ScalarSubquery, FromUnixTime,
DateFormatClass (ref: complexTypeCreator.scala GpuCreateArray,
GpuScalarSubquery, datetimeExpressions.scala)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.session import (
    TpuSession,
    array,
    avg,
    col,
    date_format,
    from_unixtime,
    scalar_subquery,
    sum_,
)
from tests.differential import assert_tpu_cpu_equal, gen_table


@pytest.fixture
def session():
    return TpuSession()


def test_create_array(session):
    t = pa.table({"a": pa.array([1, 2, None], pa.int64()),
                  "b": pa.array([10, None, 30], pa.int64())})
    q = session.create_dataframe(t).select(
        array(col("a"), col("b"), lit(7)).alias("arr"))
    got = q.collect().to_pydict()["arr"]
    assert got == [[1, 10, 7], [2, None, 7], [None, 30, 7]]
    assert q.collect(engine="cpu").to_pydict()["arr"] == got


def test_create_array_then_explode(session):
    from spark_rapids_tpu.session import explode

    t = pa.table({"a": pa.array([1, 2], pa.int64())})
    q = session.create_dataframe(t).select(
        explode(array(col("a"), col("a") * lit(10))).alias("e"))
    assert sorted(q.collect().to_pydict()["e"]) == [1, 2, 10, 20]


def test_scalar_subquery(session):
    t = gen_table({"v": "float64"}, 500, seed=1, null_prob=0.0)
    df = session.create_dataframe(t)
    mean = df.agg((avg(col("v")), "m"))
    q = df.where(col("v") > scalar_subquery(mean))
    got = q.collect()
    vals = t.column("v").to_numpy()
    expect = int((vals > vals.mean()).sum())
    assert got.num_rows == expect
    # CPU engine path evaluates the subquery too
    assert q.collect(engine="cpu").num_rows == expect


def test_scalar_subquery_shape_error(session):
    t = pa.table({"v": pa.array([1.0, 2.0])})
    df = session.create_dataframe(t)
    with pytest.raises(ValueError, match="1x1"):
        df.select(scalar_subquery(df).alias("x")).collect()


def test_from_unixtime(session):
    secs = [0, 86399, 86400, 1_600_000_000, -1, -2, -86400, -86401,
            -123456789]
    t = pa.table({"s": pa.array(secs, pa.int64())})
    q = session.create_dataframe(t).select(
        from_unixtime(col("s")).alias("f"))
    got = q.collect().to_pydict()["f"]
    import datetime as dt

    want = [dt.datetime.fromtimestamp(s, dt.timezone.utc)
            .strftime("%Y-%m-%d %H:%M:%S") for s in secs]
    assert got == want
    assert_tpu_cpu_equal(q)


def test_from_unixtime_date_only_format(session):
    t = pa.table({"s": pa.array([0, 1_600_000_000], pa.int64())})
    q = session.create_dataframe(t).select(
        from_unixtime(col("s"), "yyyy-MM-dd").alias("d"))
    assert q.collect().to_pydict()["d"] == ["1970-01-01", "2020-09-13"]


def test_from_unixtime_exotic_format_falls_back(session):
    t = pa.table({"s": pa.array([0], pa.int64())})
    q = session.create_dataframe(t).select(
        from_unixtime(col("s"), "yyyy/MM/dd").alias("d"))
    assert "!" in q.explain()  # refused at tagging, CPU fallback... but
    # the CPU mirror supports it, so the answer is still right
    assert q.collect().to_pydict()["d"] == ["1970/01/01"]


def test_date_format_on_date_and_timestamp(session):
    days = pa.array([0, 18262], pa.int32()).cast(pa.date32())
    ts = pa.array([0, 1_600_000_000_000_000], pa.int64()).cast(
        pa.timestamp("us", tz="UTC"))
    t = pa.table({"d": days, "t": ts})
    q = session.create_dataframe(t).select(
        date_format(col("d")).alias("fd"),
        date_format(col("t"), "yyyy-MM-dd HH:mm:ss").alias("ft"))
    got = q.collect().to_pydict()
    assert got["fd"] == ["1970-01-01", "2020-01-01"]
    assert got["ft"] == ["1970-01-01 00:00:00", "2020-09-13 12:26:40"]
    assert_tpu_cpu_equal(q)
