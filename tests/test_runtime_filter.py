"""Runtime join filters (plan/runtime_filter.py): host/device Bloom
parity, probe-side upload pruning, the join-type safety matrix, and the
enabled=false bit-for-bit contract."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.plan import runtime_filter as RF
from spark_rapids_tpu.session import TpuSession, col, sum_

RF_KEY = "spark.rapids.tpu.sql.runtimeFilter.enabled"
BCAST_KEY = "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes"


@pytest.fixture(autouse=True)
def _fresh_stats():
    RF.reset_stats()
    yield
    RF.reset_stats()


@pytest.fixture
def session():
    return TpuSession()


def _write(d, name, table, row_group_size=None):
    p = os.path.join(str(d), name)
    pq.write_table(table, p, row_group_size=row_group_size)
    return p


def _lineitem(d, n=8192, n_keys=512, seed=0, rg=2048):
    rng = np.random.default_rng(seed)
    t = pa.table({
        "l_orderkey": rng.integers(0, n_keys, n).astype(np.int64),
        "l_price": rng.random(n),
    })
    return _write(d, "li.parquet", t, rg)


def _orders(d, n_keys=512, seed=1):
    rng = np.random.default_rng(seed)
    t = pa.table({
        "o_orderkey": np.arange(n_keys, dtype=np.int64),
        "o_date": rng.integers(0, 100, n_keys).astype(np.int32),
    })
    return _write(d, "orders.parquet", t)


def _q3(session, li_path, o_path, date_lt=20, how="inner"):
    lidf = session.read_parquet(li_path)
    odf = session.read_parquet(o_path).where(col("o_date") < lit(date_lt))
    return lidf.join(odf, left_on=[col("l_orderkey")],
                     right_on=[col("o_orderkey")], how=how)


def _sorted_rows(tbl):
    return sorted(map(tuple, zip(*tbl.to_pydict().values())),
                  key=lambda t: tuple((x is None, x) for x in t))


def _assert_matches_cpu(df):
    got = _sorted_rows(df.collect(engine="tpu"))
    want = _sorted_rows(df.collect(engine="cpu"))
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        for x, y in zip(g, w):
            if isinstance(x, float):
                assert abs(x - y) <= 1e-9 * max(1.0, abs(y)), (g, w)
            else:
                assert x == y, (g, w)


def _count_upload_rows(df) -> int:
    from spark_rapids_tpu.tools.bench_smoke import count_upload_rows

    return count_upload_rows(df)


def _rf_nodes(df):
    """(build execs, scans-with-filters) in the lowered plan."""
    from spark_rapids_tpu.execs.join import TpuRuntimeFilterBuildExec
    from spark_rapids_tpu.plan.planner import plan_query

    root, _meta = plan_query(df._plan, get_conf())
    builds, scans = [], []
    for node in root._walk():
        if isinstance(node, TpuRuntimeFilterBuildExec):
            builds.append(node)
        if getattr(node, "runtime_filters", None):
            scans.append(node)
    return builds, scans


# -------------------------------------------------------------------- #
# Bloom bit-layout parity: host numpy probe vs device build
# -------------------------------------------------------------------- #


@pytest.mark.parametrize("is64", [False, True])
def test_host_device_bloom_parity_randomized(is64):
    """Every key inserted on DEVICE must probe positive on HOST, and
    the two murmur3 lanes must agree bit-for-bit — the layout contract
    the whole subsystem rests on."""
    import jax.numpy as jnp

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.exprs import hashing as H

    rng = np.random.default_rng(7)
    n = 1024
    if is64:
        keys = rng.integers(-2**62, 2**62, n, dtype=np.int64)
        dt = T.LONG
    else:
        keys = rng.integers(-2**31, 2**31, n).astype(np.int32)
        dt = T.INT
    # hash-lane parity
    d1 = np.asarray(H.hash_int64_blocks(jnp.asarray(keys), RF.BLOOM_SEED1)
                    if is64 else
                    H.hash_int32_block(jnp.asarray(keys), RF.BLOOM_SEED1))
    h1 = (H.np_hash_int64_blocks(keys, RF.BLOOM_SEED1) if is64
          else H.np_hash_int32_block(keys, RF.BLOOM_SEED1))
    assert (d1 == h1).all()

    m, k = RF.bloom_params(n, 0.01)
    rf = RF.RuntimeFilter("k", dt, "inner", m, k, True, True)
    col_ = Column(jnp.asarray(keys), jnp.ones(n, bool), dt)
    state = RF.device_init_state(m, True)
    state = RF.device_update(state, col_, jnp.ones(n, bool), m, k,
                             is64, True)
    RF.finalize(rf, state)
    assert rf.n_keys == n
    assert rf.min_val == int(keys.min())
    assert rf.max_val == int(keys.max())
    # inserted keys: no false negatives, ever
    assert rf.probe_host(keys.astype(np.int64)).all()
    # non-inserted keys: mostly rejected (fpp-bounded; generous margin)
    probe = rng.integers(-2**31 if not is64 else -2**62,
                         2**31 if not is64 else 2**62, 4096,
                         dtype=np.int64)
    fresh = probe[~np.isin(probe, keys.astype(np.int64))]
    rf_no_minmax = RF.RuntimeFilter("k", dt, "inner", m, k, False, True)
    rf_no_minmax.publish(rf.min_val, rf.max_val, rf.n_keys,
                         rf.bloom_words, 0.0)
    hits = rf_no_minmax.probe_host(fresh).mean()
    assert hits < 0.1, f"false-positive rate {hits} far above fpp"


def test_null_keys_never_probe_true():
    rf = RF.RuntimeFilter("k", __import__(
        "spark_rapids_tpu.types", fromlist=["LONG"]).LONG,
        "inner", 1 << 10, 3, True, True)
    rf.publish(0, 100, 5, np.zeros(32, np.uint32), 0.0)
    vals = np.array([1, 2, 3], np.int64)
    validity = np.array([True, False, True])
    mask = rf.probe_host(vals, validity)
    assert not mask[1]


# -------------------------------------------------------------------- #
# End-to-end pruning + correctness
# -------------------------------------------------------------------- #


def test_probe_upload_rows_drop_with_filters_on(tmp_path, session):
    """THE acceptance criterion: the q3-shaped join's probe-side
    uploaded row count drops when runtime filters are on, and results
    match the CPU oracle."""
    li = _lineitem(tmp_path)
    orders = _orders(tmp_path)
    conf = get_conf()
    df = (_q3(session, li, orders)
          .group_by(col("l_orderkey")).agg((sum_(col("l_price")), "rev")))
    conf.set(RF_KEY, True)
    RF.reset_stats()
    rows_on = _count_upload_rows(df)
    st = RF.stats()
    conf.set(RF_KEY, False)
    rows_off = _count_upload_rows(df)
    assert st["filters_built"] >= 1
    assert st["pruned_rows"] > 0
    assert rows_on < rows_off, (rows_on, rows_off)
    conf.set(RF_KEY, True)
    _assert_matches_cpu(df)


def test_adaptive_exchange_path_prunes(tmp_path, session):
    """Shuffled/adaptive shape (broadcast disabled): the build
    collector rides the build exchange's map stage, which must
    materialize BEFORE the probe side (rf_build_first ordering)."""
    li = _lineitem(tmp_path)
    orders = _orders(tmp_path)
    conf = get_conf()
    conf.set(BCAST_KEY, -1)
    df = (_q3(session, li, orders)
          .group_by(col("l_orderkey")).agg((sum_(col("l_price")), "rev")))
    RF.reset_stats()
    out = df.collect(engine="tpu")
    st = RF.stats()
    assert st["filters_built"] >= 1
    assert st["pruned_rows"] > 0
    want = df.collect(engine="cpu")
    assert out.num_rows == want.num_rows


def test_empty_build_prunes_everything(tmp_path, session):
    li = _lineitem(tmp_path)
    orders = _orders(tmp_path)
    df = _q3(session, li, orders, date_lt=-1)  # no order survives
    RF.reset_stats()
    rows = _count_upload_rows(df)
    st = RF.stats()
    assert st["filters_built"] >= 1 and st["build_rows"] == 0
    # every probe row group is pruned at the footer: zero uploads from
    # the probe scan (the build scan's rows still upload)
    assert st["row_groups_pruned"] >= 4
    assert rows <= 512  # only the (filtered-to-empty) orders side
    assert df.collect(engine="tpu").num_rows == 0


def test_rowgroup_minmax_pruning(tmp_path, session):
    """Sorted probe keys + a narrow build range: whole row groups must
    be skipped on the filter's [min, max] before decode."""
    t = pa.table({
        "l_orderkey": np.arange(8192, dtype=np.int64),  # sorted
        "l_price": np.random.default_rng(3).random(8192),
    })
    li = _write(tmp_path, "li_sorted.parquet", t, 2048)
    orders = pa.table({
        "o_orderkey": np.arange(100, dtype=np.int64),  # keys 0..99
        "o_date": np.zeros(100, np.int32),
    })
    op = _write(tmp_path, "orders_small.parquet", orders)
    lidf = session.read_parquet(li)
    odf = session.read_parquet(op).where(col("o_date") >= lit(0))
    df = lidf.join(odf, left_on=[col("l_orderkey")],
                   right_on=[col("o_orderkey")])
    RF.reset_stats()
    out = df.collect(engine="tpu")
    st = RF.stats()
    # keys 0..99 live in row group 0 of 4: three groups prune
    assert st["row_groups_pruned"] >= 3, st
    assert out.num_rows == 100


def test_bloom_false_positive_path_joins_correctly(tmp_path, session):
    """A deliberately tiny, collision-heavy Bloom (min/max off so the
    range can't rescue it) must still produce exact join results — the
    device join is the source of truth for FP rows."""
    rng = np.random.default_rng(5)
    li = _write(tmp_path, "li.parquet", pa.table({
        "l_orderkey": rng.integers(0, 4096, 4096).astype(np.int64),
        "l_price": rng.random(4096),
    }))
    # build keys interleaved across the probe range
    op = _write(tmp_path, "orders.parquet", pa.table({
        "o_orderkey": np.arange(0, 4096, 37, dtype=np.int64),
        "o_date": np.zeros(len(range(0, 4096, 37)), np.int32),
    }))
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.runtimeFilter.minMaxEnabled", False)
    conf.set("spark.rapids.tpu.sql.runtimeFilter.fpp", 0.5)
    df = _q3(session, li, op, date_lt=1)
    RF.reset_stats()
    _assert_matches_cpu(df)
    assert RF.stats()["filters_built"] >= 1


def test_null_probe_keys_pruned_and_correct(tmp_path, session):
    li_t = pa.table({
        "l_orderkey": pa.array([1, 2, None, 3, None, 2], pa.int64()),
        "l_price": pa.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
    })
    li = _write(tmp_path, "li_nulls.parquet", li_t)
    op = _write(tmp_path, "orders.parquet", pa.table({
        "o_orderkey": np.arange(3, dtype=np.int64),
        "o_date": np.zeros(3, np.int32),
    }))
    df = _q3(session, li, op, date_lt=1)
    _assert_matches_cpu(df)


@pytest.mark.parametrize("how", ["left_outer", "full_outer",
                                 "left_anti"])
def test_ineligible_join_types_never_inject(tmp_path, session, how):
    li = _lineitem(tmp_path, n=512, rg=None)
    orders = _orders(tmp_path)
    df = _q3(session, li, orders, how=how)
    builds, scans = _rf_nodes(df)
    assert not builds and not scans, how
    _assert_matches_cpu(df)


def test_left_semi_injects_and_matches(tmp_path, session):
    li = _lineitem(tmp_path)
    orders = _orders(tmp_path)
    df = _q3(session, li, orders, how="left_semi")
    builds, scans = _rf_nodes(df)
    assert builds and scans
    _assert_matches_cpu(df)


def test_disabled_reproduces_unfiltered_plan(tmp_path, session):
    """runtimeFilter.enabled=false: no build nodes, no scan filters —
    the PR4 plan, bit-for-bit — and identical results."""
    li = _lineitem(tmp_path)
    orders = _orders(tmp_path)
    conf = get_conf()
    df = (_q3(session, li, orders)
          .group_by(col("l_orderkey")).agg((sum_(col("l_price")), "rev")))
    conf.set(RF_KEY, False)
    builds, scans = _rf_nodes(df)
    assert not builds and not scans
    off_rows = _sorted_rows(df.collect(engine="tpu"))
    assert RF.stats()["filters_built"] == 0
    conf.set(RF_KEY, True)
    builds, scans = _rf_nodes(df)
    assert builds and scans
    on_rows = _sorted_rows(df.collect(engine="tpu"))
    assert len(on_rows) == len(off_rows)
    for a, b in zip(on_rows, off_rows):
        assert a[0] == b[0]
        assert abs(a[1] - b[1]) <= 1e-9 * max(1.0, abs(b[1]))


def test_unselective_build_skips_injection(tmp_path, session):
    li = _lineitem(tmp_path, n=512, rg=None)
    orders = _orders(tmp_path)
    get_conf().set("spark.rapids.tpu.sql.runtimeFilter.maxBuildRows", 10)
    df = _q3(session, li, orders)
    builds, scans = _rf_nodes(df)
    assert not builds and not scans


def test_explain_shows_runtime_filters(tmp_path, session):
    li = _lineitem(tmp_path, n=512, rg=None)
    orders = _orders(tmp_path)
    df = _q3(session, li, orders)
    out = df.explain()
    assert "RuntimeFilters:" in out
    assert "rf#" in out


def test_lint_pl005_flags_ineligible_filter(tmp_path, session):
    """The PL005 backstop: a hand-built plan attaching a runtime
    filter to an outer join is a plan ERROR."""
    from spark_rapids_tpu.lint.plan_rules import check_plan
    from spark_rapids_tpu.plan.planner import plan_query

    li = _lineitem(tmp_path, n=64, rg=None)
    orders = _orders(tmp_path, n_keys=16)
    df = _q3(session, li, orders, how="left_outer")
    root, _meta = plan_query(df._plan, get_conf())
    # no filter injected for left_outer; attach one by hand
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.io.scan import ParquetScanExec

    scan = next(n for n in root._walk()
                if isinstance(n, ParquetScanExec))
    bad = RF.RuntimeFilter("l_orderkey", T.LONG, "left_outer",
                           1 << 10, 3, True, True)
    scan.runtime_filters.append(("l_orderkey", bad))
    diags = check_plan(root)
    assert any(d.rule == "PL005" and d.severity == "error"
               for d in diags), [d.rule for d in diags]


def test_bench_smoke_rf_on_off_equality():
    """Tier-1 wiring of the bench_smoke runtime-filter contract."""
    from spark_rapids_tpu.tools.bench_smoke import run_rf_smoke

    out = run_rf_smoke()
    assert out["runtime_filter"] > 0
    assert out["runtime_filter_pruned_rows"] > 0


def test_date_key_rowgroup_stats(tmp_path, session):
    """date32 join keys: footer stats come back as datetime.date while
    the filter's min/max are epoch days — the coercion must prune."""
    days = np.arange(8192, dtype=np.int32)
    li_t = pa.table({
        "l_date": pa.array(days, pa.int32()).cast(pa.date32()),
        "l_price": np.random.default_rng(9).random(8192),
    })
    li = _write(tmp_path, "li_date.parquet", li_t, 2048)
    o_days = np.arange(50, dtype=np.int32)
    o_t = pa.table({
        "o_date_key": pa.array(o_days, pa.int32()).cast(pa.date32()),
        "o_flag": np.zeros(50, np.int32),
    })
    op = _write(tmp_path, "orders_date.parquet", o_t)
    lidf = session.read_parquet(li)
    odf = session.read_parquet(op).where(col("o_flag") >= lit(0))
    df = lidf.join(odf, left_on=[col("l_date")],
                   right_on=[col("o_date_key")])
    RF.reset_stats()
    out = df.collect(engine="tpu")
    st = RF.stats()
    assert out.num_rows == 50
    assert st["row_groups_pruned"] >= 3, st
