"""Persistent event log + tools/history analysis layer.

Covers the PR's acceptance surface:
- schema golden test: every emitted record validates strictly against
  the versioned schema, and the emitted field set is FROZEN (drift
  must be a conscious schema_version decision);
- forward compat: unknown fields and unknown record types from a
  newer writer load fine;
- compare/health round trip: a synthetic 2x slowdown and a
  CPU-fallback run are both flagged, end to end through the CLI
  `report` command;
- chaos: a fault-injected run's log records recovered-fault counts
  AND a result digest bit-identical to the fault-free run's;
- the default-off path adds zero per-query overhead beyond one
  attribute check (no writer thread, no counter snapshots);
- the bench_smoke eventlog contract (per-operator rows in the file ==
  the settled in-process metrics) wired into tier-1.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.session import TpuSession, col, sum_

ENABLED = "spark.rapids.tpu.eventLog.enabled"
DIR = "spark.rapids.tpu.eventLog.dir"
COMPRESS = "spark.rapids.tpu.eventLog.compress"
SIDECAR = "spark.rapids.tpu.eventLog.traceSidecar"


def _logging_session(tmp_path, **extra) -> TpuSession:
    conf = get_conf()
    conf.set(ENABLED, True)
    conf.set(DIR, str(tmp_path / "log"))
    for k, v in extra.items():
        conf.set(k, v)
    return TpuSession()


def _table(n: int = 512, seed: int = 7) -> pa.Table:
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 16, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _agg(session: TpuSession, t: pa.Table):
    return (session.create_dataframe(t)
            .group_by(col("k"))
            .agg((sum_(col("v")), "sv"))
            .order_by(col("k")))


def _drain(session: TpuSession) -> str:
    """Reading history.events drains the snapshot worker, which also
    appends the event-log records; the file is complete after."""
    _ = session.history.events
    return session.event_log_path


# ------------------------------------------------------------------ #
# Schema: golden + forward compat
# ------------------------------------------------------------------ #

#: THE emitted field sets.  Changing either is a schema decision:
#: removing/renaming a field (or retyping it) requires a
#: SCHEMA_VERSION bump; additions must stay optional for readers.
GOLDEN_HEADER_FIELDS = frozenset({
    "type", "schema_version", "ts", "session", "pid", "env", "conf",
    "conf_hash", "mesh"})
GOLDEN_QUERY_FIELDS = frozenset({
    "type", "schema_version", "query_id", "plan", "plan_hash",
    "engine", "wall_s", "start_ts", "end_ts", "start_ns", "end_ns",
    "conf_hash", "counters", "operators", "spans", "pipeline",
    "faults", "serving", "sharing", "connect", "programs",
    "result_digest", "rows", "trace_file"})


def test_schema_golden_every_record_validates(tmp_path):
    from spark_rapids_tpu.eventlog.reader import iter_records
    from spark_rapids_tpu.eventlog.schema import SCHEMA_VERSION

    session = _logging_session(tmp_path)
    t = _table()
    _agg(session, t).collect(engine="tpu")
    (session.create_dataframe(t).where(col("v") > 10)
     .select(col("k")).collect(engine="tpu"))
    path = _drain(session)
    recs = list(iter_records(path, strict=True))  # validates each
    assert [r["type"] for r in recs] == ["header", "query", "query"]
    hdr, q1, q2 = recs
    assert set(hdr) == GOLDEN_HEADER_FIELDS, set(hdr)
    assert set(q1) == set(q2) == GOLDEN_QUERY_FIELDS, set(q1)
    assert hdr["schema_version"] == SCHEMA_VERSION == 1
    assert hdr["conf"][ENABLED] == "True"
    assert q1["query_id"] != q2["query_id"]
    assert q1["plan_hash"] != q2["plan_hash"]  # different templates
    assert q1["conf_hash"] == hdr["conf_hash"]
    # the counter surface is complete
    from spark_rapids_tpu.eventlog import MONOTONIC_COUNTERS

    for key in MONOTONIC_COUNTERS:
        assert key in q1["counters"], key


def test_forward_compat_unknown_fields_and_types(tmp_path):
    from spark_rapids_tpu.eventlog.reader import iter_records, read_log
    from spark_rapids_tpu.eventlog.schema import validate_record

    session = _logging_session(tmp_path)
    _agg(session, _table()).collect(engine="tpu")
    path = _drain(session)
    future = str(tmp_path / "future.jsonl")
    with open(path) as f, open(future, "w") as out:
        for line in f:
            rec = json.loads(line)
            rec["future_field"] = {"from": "a newer writer"}
            out.write(json.dumps(rec) + "\n")
        out.write(json.dumps({"type": "gc_hint", "v": 1}) + "\n")
    # permissive read: unknown record type skipped, extras preserved
    recs = list(iter_records(future))
    assert [r["type"] for r in recs] == ["header", "query"]
    assert recs[1]["future_field"] == {"from": "a newer writer"}
    # strict validation tolerates unknown EXTRA fields by contract
    for r in recs:
        validate_record(r)
    header, queries = read_log(future)
    assert header is not None and len(queries) == 1


def test_corrupt_trailing_line_is_dropped(tmp_path):
    from spark_rapids_tpu.eventlog.reader import read_log

    session = _logging_session(tmp_path)
    _agg(session, _table()).collect(engine="tpu")
    path = _drain(session)
    with open(path, "a") as f:
        f.write('{"type": "query", "torn mid-')  # crash mid-write
    header, queries = read_log(path)
    assert header is not None and len(queries) == 1


def test_torn_trailing_gzip_member_keeps_prefix(tmp_path):
    """A process killed mid-append leaves a truncated final gzip
    member; the complete prefix members must still load (the whole
    point of one-member-per-append)."""
    from spark_rapids_tpu.eventlog.reader import read_log

    session = _logging_session(tmp_path, **{COMPRESS: True})
    _agg(session, _table()).collect(engine="tpu")
    path = _drain(session)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 7)  # tear the final member's trailer
    header, queries = read_log(path)
    assert header is not None
    # the header member decoded; the torn query record is dropped or
    # kept depending on where the tear landed — never an exception
    assert len(queries) <= 1


def test_failed_append_warns_but_does_not_poison_history(
        tmp_path, monkeypatch):
    """An event-log append failure (disk full, revoked dir) must not
    re-raise out of every later history read — the query succeeded."""
    from spark_rapids_tpu.eventlog import EventLogWriter

    session = _logging_session(tmp_path)

    def boom(self, rec):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(EventLogWriter, "append", boom)
    with pytest.warns(RuntimeWarning, match="on_event hook failed"):
        out = _agg(session, _table()).collect(engine="tpu")
        events = session.history.events  # drains without raising
    assert out.num_rows > 0
    assert len(events) == 1  # history itself intact
    # and the header retries on the next (now healthy) append
    monkeypatch.undo()
    _agg(session, _table()).collect(engine="tpu")
    from spark_rapids_tpu.eventlog.reader import read_log

    header, queries = read_log(_drain(session))
    assert header is not None and len(queries) == 1


def test_compressed_log_roundtrip(tmp_path):
    from spark_rapids_tpu.eventlog.reader import iter_records

    session = _logging_session(tmp_path, **{COMPRESS: True})
    assert session.event_log_path.endswith(".jsonl.gz")
    _agg(session, _table()).collect(engine="tpu")
    path = _drain(session)
    recs = list(iter_records(path, strict=True))
    assert [r["type"] for r in recs] == ["header", "query"]


# ------------------------------------------------------------------ #
# compare / health / report round trip
# ------------------------------------------------------------------ #


def _two_runs(tmp_path):
    """(logA, logB): a real two-query log and a doctored copy with a
    2.2x slowdown on every query plus one CPU-fallback record."""
    from spark_rapids_tpu.eventlog.reader import iter_records

    session = _logging_session(tmp_path)
    t = _table()
    _agg(session, t).collect(engine="tpu")
    (session.create_dataframe(t).where(col("v") > 10)
     .select(col("k")).collect(engine="tpu"))
    log_a = _drain(session)
    log_b = str(tmp_path / "runB.jsonl")
    recs = list(iter_records(log_a))
    last_qid = recs[-1]["query_id"]
    with open(log_b, "w") as f:
        for r in recs:
            if r["type"] == "query":
                r = dict(r)
                r["wall_s"] *= 2.2
                if r["query_id"] == last_qid:
                    r["engine"] = "cpu_fallback"
                    r["counters"] = dict(
                        r["counters"], **{"retry.cpu_fallbacks": 1})
            f.write(json.dumps(r) + "\n")
    return log_a, log_b


def test_compare_flags_synthetic_slowdown(tmp_path):
    from spark_rapids_tpu.tools.history import (
        compare_applications,
        load_application,
    )

    log_a, log_b = _two_runs(tmp_path)
    apps = [load_application(log_a), load_application(log_b)]
    result = compare_applications(apps, threshold=1.25)
    assert len(result["rows"]) == 2
    assert all(r["flag"] == "regression" for r in result["rows"])
    assert not result["unmatched"]  # plan hashes matched across runs
    # and below the threshold nothing is flagged
    calm = compare_applications(apps, threshold=3.0)
    assert not calm["regressions"]


def test_health_flags_cpu_fallback_run(tmp_path):
    from spark_rapids_tpu.tools.history import (
        health_check,
        load_application,
    )

    log_a, log_b = _two_runs(tmp_path)
    clean = health_check(load_application(log_a))
    assert not any(f.severity == "error" for f in clean), clean
    assert not any(f.rule == "HC001" for f in clean), clean
    findings = health_check(load_application(log_b))
    assert any(f.rule == "HC001" and f.severity == "error"
               for f in findings), findings


def test_health_rule_registry_thresholds():
    """Rules fire off the record's counters alone — build synthetic
    QueryRecords for each unhealthy pattern."""
    from spark_rapids_tpu.tools.history import (
        QueryRecord,
        _query_from_record,
        health_check,
        ApplicationInfo,
    )

    def q(counters, pipeline=None, engine="tpu") -> QueryRecord:
        return _query_from_record({
            "query_id": 0, "plan": "", "plan_hash": "x",
            "engine": engine, "wall_s": 1.0, "counters": counters,
            "pipeline": pipeline})

    cases = {
        "HC002": q({"retry.splits": 2, "retry.task_retries": 1}),
        "HC003": q({"spill.device_to_host_bytes": 64 << 20}),
        "HC004": q({"jit.misses": 40}),
        "HC005": q({"pipeline.readbacks": 50}),
        "HC006": q({}, pipeline={"s": {
            "items": 64, "occupancy_fraction": 0.01}}),
        "HC007": q({"rf.filters_built": 1, "rf.pruned_rows": 0}),
        "HC008": q({"faults.recovered": 2}),
    }
    for rule, rec in cases.items():
        app = ApplicationInfo("x", "eventlog", {}, [rec])
        got = {f.rule for f in health_check(app)}
        assert rule in got, (rule, got)
    healthy = ApplicationInfo("x", "eventlog", {}, [q({})])
    assert health_check(healthy) == []


def test_report_cli_flags_regression_and_fallback(tmp_path, capsys):
    """THE acceptance criterion: `history report` over two logs — one
    clean, one with an injected regression + CPU fallback — produces a
    markdown report whose compare section flags the >=threshold
    slowdown and whose health section flags the fallback run."""
    from spark_rapids_tpu.tools.history import main

    log_a, log_b = _two_runs(tmp_path)
    out = str(tmp_path / "report.md")
    rc = main(["report", log_a, log_b, "--threshold", "1.25",
               "-o", out])
    assert rc == 0
    text = open(out).read()
    assert text.startswith("# Fleet regression report")
    assert "REGRESSION" in text and "2.200x" in text
    assert "HC001" in text and "degraded to the CPU engine" in text
    # compare exits nonzero on regressions; health on error findings
    assert main(["compare", log_a, log_b]) == 1
    capsys.readouterr()
    assert main(["health", log_b]) == 1
    capsys.readouterr()


def test_dot_from_event_log(tmp_path, capsys):
    from spark_rapids_tpu.tools.history import (
        generate_dot,
        load_application,
        main,
    )

    session = _logging_session(tmp_path)
    _agg(session, _table()).collect(engine="tpu")
    path = _drain(session)
    app = load_application(path)
    dot = generate_dot(app.queries[0])
    assert dot.startswith("digraph plan {") and "->" in dot
    assert "TpuHashAggregateExec" in dot and "rows=" in dot
    assert main(["dot", path]) == 0
    assert "digraph plan {" in capsys.readouterr().out


def test_bench_round_ingest(tmp_path):
    """Committed BENCH_r0*.json artifacts load as pseudo-apps and
    compare against each other (the perf-trajectory use case)."""
    from spark_rapids_tpu.tools.history import (
        compare_applications,
        load_application,
    )

    r1 = {"metric": "tpch_q6_e2e_throughput",
          "tpu_s_per_query": 1.0, "q1_tpu_s_per_query": 4.0,
          "q1_retry_splits": 0, "rows": 100}
    r2 = dict(r1, tpu_s_per_query=2.0, q1_tpu_s_per_query=1.0)
    p1, p2 = str(tmp_path / "r1.json"), str(tmp_path / "r2.json")
    json.dump(r1, open(p1, "w"))
    json.dump(r2, open(p2, "w"))
    apps = [load_application(p1), load_application(p2)]
    assert [a.kind for a in apps] == ["bench", "bench"]
    result = compare_applications(apps, threshold=1.5)
    by_q = {r["query"]: r for r in result["rows"]}
    assert by_q["q6"]["flag"] == "regression"
    assert by_q["q1"]["flag"] == "improvement"


# ------------------------------------------------------------------ #
# Chaos: recovered faults + bit-identical results in the log
# ------------------------------------------------------------------ #


def test_chaos_run_records_recovery_with_identical_digest(tmp_path):
    from spark_rapids_tpu.robustness import faults
    from spark_rapids_tpu.tools.history import load_application

    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.batchSizeRows", 256)
    t = _table(n=2048, seed=11)

    # fault-free baseline run
    clean = _logging_session(tmp_path)
    _agg(clean, t).collect(engine="tpu")
    clean_path = _drain(clean)

    # chaos run of the SAME query under an injected upload fault
    conf.set(DIR, str(tmp_path / "chaos"))
    chaos = TpuSession()
    faults.install("transfer.upload:nth=1", forced=True)
    try:
        _agg(chaos, t).collect(engine="tpu")
    finally:
        faults.disarm()
    chaos_path = _drain(chaos)

    q_clean = load_application(clean_path).queries[0]
    q_chaos = load_application(chaos_path).queries[0]
    assert q_chaos.counter("faults.injected") >= 1
    assert q_chaos.counter("faults.recovered") >= 1
    assert q_chaos.faults["transfer.upload"]["recovered"] >= 1
    assert q_clean.counter("faults.injected") == 0
    # recovery changed NOTHING: integer sums, deterministic order
    assert q_clean.result_digest == q_chaos.result_digest
    assert q_clean.rows == q_chaos.rows
    assert q_clean.engine == q_chaos.engine == "tpu"


# ------------------------------------------------------------------ #
# Disabled path: zero overhead
# ------------------------------------------------------------------ #


def test_disabled_is_zero_overhead(tmp_path, monkeypatch):
    """eventLog.enabled=false (the default): no writer object, no
    writer thread, no counter snapshots during collect — the whole
    per-query cost is _collect_tpu's one `is not None` check."""
    import spark_rapids_tpu.eventlog as EL

    conf = get_conf()
    assert conf.get(ENABLED) is False  # default-off
    calls = {"n": 0}
    real = EL.counters_snapshot

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(EL, "counters_snapshot", counting)
    session = TpuSession()
    assert session._eventlog is None
    assert session.event_log_path is None
    out = _agg(session, _table()).collect(engine="tpu")
    assert out.num_rows > 0
    _ = session.history.events
    assert calls["n"] == 0, "disabled path took a counter snapshot"
    assert not any("eventlog" in th.name.lower()
                   for th in threading.enumerate())
    assert not (tmp_path / "log").exists()


# ------------------------------------------------------------------ #
# QueryHistory timestamps / conf-epoch (satellite)
# ------------------------------------------------------------------ #


def test_history_event_timestamps_roundtrip_into_log(tmp_path):
    """QueryEvent now carries start/end monotonic+epoch and the conf
    hash; the event-log record must round-trip all five exactly."""
    import time

    from spark_rapids_tpu.eventlog.reader import read_log

    session = _logging_session(tmp_path)
    df = _agg(session, _table())
    _out, qid = df._collect_tpu()
    path = _drain(session)
    ev = next(e for e in session.history.events if e.query_id == qid)
    assert ev.start_ns > 0 and ev.end_ns >= ev.start_ns
    assert 0 < ev.start_ts <= ev.end_ts
    assert abs(ev.end_ts - time.time()) < 300
    assert ev.conf_hash
    _hdr, queries = read_log(path, strict=True)
    rec = next(r for r in queries if r["query_id"] == qid)
    for field in ("start_ts", "end_ts", "start_ns", "end_ns",
                  "conf_hash"):
        assert rec[field] == getattr(ev, field), field


# ------------------------------------------------------------------ #
# Trace integration: spans + sidecar pointer
# ------------------------------------------------------------------ #


def test_spans_and_trace_sidecar_recorded(tmp_path):
    from spark_rapids_tpu import trace
    from spark_rapids_tpu.eventlog.reader import read_log

    session = _logging_session(tmp_path, **{SIDECAR: True})
    trace.enable()
    try:
        _agg(session, _table()).collect(engine="tpu")
        path = _drain(session)
    finally:
        trace.disable()
        trace.clear()
    _hdr, (rec,) = read_log(path, strict=True)
    assert rec["spans"], "span stats missing despite tracing on"
    assert any(op.startswith("Tpu") for op in rec["spans"]), rec["spans"]
    assert rec["trace_file"] and os.path.exists(rec["trace_file"])
    doc = json.load(open(rec["trace_file"]))
    assert doc["traceEvents"], "sidecar Chrome trace is empty"


# ------------------------------------------------------------------ #
# bench_smoke wiring (tier-1)
# ------------------------------------------------------------------ #


def test_bench_smoke_eventlog_matches_settled_metrics():
    """run_eventlog_smoke: reload-through-history per-operator metrics
    == the session's settled QueryHistory snapshot."""
    from spark_rapids_tpu.tools.bench_smoke import run_eventlog_smoke

    out = run_eventlog_smoke()
    assert out["eventlog"] > 0 and out["eventlog_operators"] >= 2


# ------------------------------------------------------------------ #
# analyze footer (satellite): PR6 + PR5 counters ride along
# ------------------------------------------------------------------ #


def test_explain_analyze_footer_has_recovery_and_rf_counters():
    session = TpuSession()
    out = _agg(session, _table()).explain("analyze")
    assert "jit cache:" in out
    assert "retry: splits=0" in out, out
    assert "cpu_fallbacks=0" in out
    assert "recovered_faults=0" in out
    assert "runtime filters: built=0" in out


def test_explain_analyze_footer_counts_recovered_faults():
    from spark_rapids_tpu.robustness import faults

    session = TpuSession()
    df = _agg(session, _table(seed=23))
    faults.install("transfer.upload:nth=1", forced=True)
    try:
        out = df.explain("analyze")
    finally:
        faults.disarm()
    assert "recovered_faults=1" in out, out
