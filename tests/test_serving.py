"""Serving-tier tests: admission control (weighted-fair scheduler over
the device semaphore), the prepared-plan cache, streaming result fetch,
and THE concurrent-session stress test (N sessions x M queries with
distinct confs, results bit-identical to serial execution).

Process-global state discipline: the scheduler, the plan-cache
counters and the semaphore singleton are reset around every test (the
tracer follows test_trace's rules)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import TpuConf, get_conf, set_conf
from spark_rapids_tpu.eventlog import table_digest
from spark_rapids_tpu.frontends.sql import SqlError, SqlSession
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.serving import (
    clear_serving_context,
    current_serving_context,
    plan_cache as plan_cache_mod,
)
from spark_rapids_tpu.serving.plan_cache import PlanCache
from spark_rapids_tpu.serving.scheduler import (
    AdmissionRejected,
    QueryScheduler,
    scheduler_stats,
)
from spark_rapids_tpu.serving import scheduler as scheduler_mod
from spark_rapids_tpu.session import TpuSession, col, count_star, sum_


@pytest.fixture(autouse=True)
def _isolate_serving():
    scheduler_mod.reset()
    plan_cache_mod.reset_stats()
    clear_serving_context()
    TpuSemaphore.reset()
    yield
    scheduler_mod.reset()
    plan_cache_mod.reset_stats()
    clear_serving_context()
    TpuSemaphore.reset()
    from spark_rapids_tpu import trace

    trace.disable()
    trace.clear()


@pytest.fixture(autouse=True)
def _no_leaks(leak_check):
    """Every serving test carries the suite-wide leak gauge: permits,
    store bytes per tier, stage threads and in-flight scan shares must
    return exactly to baseline (conftest.leak_check)."""
    yield


def _table(n=4096, keys=16, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, keys, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _agg_df(session, t):
    """Deterministic (integer sums, ordered output) grouped aggregate:
    digest-stable across runs and thread interleavings."""
    return (session.create_dataframe(t)
            .group_by(col("k"))
            .agg((sum_(col("v")), "sv"), (count_star(), "n"))
            .order_by(col("k")))


# ------------------------------------------------------------------ #
# Semaphore resize / sync_conf (the PR's satellite fix)
# ------------------------------------------------------------------ #


def test_semaphore_resize_wakes_waiters():
    sem = TpuSemaphore(1)
    sem.acquire_if_necessary("a")
    got = threading.Event()

    def waiter():
        sem.acquire_if_necessary("b")
        got.set()

    t = threading.Thread(target=waiter)
    t.start()
    assert not got.wait(0.1), "second task got a permit from a 1-permit pool"
    sem.resize(2)
    assert got.wait(2.0), "resize(2) did not wake the blocked waiter"
    t.join()
    sem.release_if_necessary("a")
    sem.release_if_necessary("b")
    assert sem._available == 2


def test_semaphore_shrink_blocks_new_admissions():
    sem = TpuSemaphore(2)
    sem.acquire_if_necessary("a")
    sem.acquire_if_necessary("b")
    sem.resize(1)
    assert sem._available == -1  # both holders finish first
    sem.release_if_necessary("a")
    got = threading.Event()

    def waiter():
        sem.acquire_if_necessary("c")
        got.set()

    t = threading.Thread(target=waiter)
    t.start()
    assert not got.wait(0.1)
    sem.release_if_necessary("b")  # now a permit is truly free
    assert got.wait(2.0)
    t.join()


def test_semaphore_sync_conf_resizes_without_restart():
    conf = get_conf()
    sem = TpuSemaphore.get()
    base = sem.permits
    conf.set("spark.rapids.tpu.sql.concurrentTpuTasks", base + 3)
    TpuSemaphore.sync_conf(conf)
    assert TpuSemaphore.get() is sem  # LIVE resize, not a new instance
    assert sem.permits == base + 3
    # the owner conf may move it back to the default
    conf.set("spark.rapids.tpu.sql.concurrentTpuTasks", base)
    TpuSemaphore.sync_conf(conf)
    assert sem.permits == base


def test_semaphore_sync_conf_default_conf_cannot_shrink_owner():
    from spark_rapids_tpu.config import CONCURRENT_TPU_TASKS

    owner = TpuConf({CONCURRENT_TPU_TASKS.key: 5})
    TpuSemaphore.get()
    TpuSemaphore.sync_conf(owner)
    assert TpuSemaphore.get().permits == 5
    other = TpuConf()  # carries the default
    TpuSemaphore.sync_conf(other)
    assert TpuSemaphore.get().permits == 5, \
        "a defaults-only conf shrank another session's explicit resize"
    # but the owner itself can restore the default
    owner.set(CONCURRENT_TPU_TASKS.key, CONCURRENT_TPU_TASKS.default)
    TpuSemaphore.sync_conf(owner)
    assert TpuSemaphore.get().permits == CONCURRENT_TPU_TASKS.default


# ------------------------------------------------------------------ #
# Scheduler semantics
# ------------------------------------------------------------------ #


def _fill_slot(sched):
    """Occupy every slot so later admits queue."""
    tickets = []
    for _ in range(sched.max_concurrent):
        tickets.append(sched.admit("filler"))
    return tickets


def _queue_async(sched, tenant, priority, order, name):
    done = threading.Event()

    def run():
        t = sched.admit(tenant, priority)
        order.append(name)
        sched.release(t)
        done.set()

    th = threading.Thread(target=run)
    th.start()
    return th, done


def test_scheduler_weighted_fair_share():
    """Priority-3 tenant should be admitted ~3x as often as a
    priority-1 tenant under contention (start-time WFQ: vtime advances
    1/3 vs 1 per grant)."""
    sched = QueryScheduler(max_concurrent=1, queue_depth=64)
    hold = _fill_slot(sched)
    order: list = []
    threads = []
    # interleave enqueues so both tenants always have queued work
    for i in range(4):
        threads.append(_queue_async(sched, "light", 1, order,
                                    f"L{i}")[0])
        for j in range(3):
            threads.append(_queue_async(sched, "heavy", 3, order,
                                        f"H{i * 3 + j}")[0])
    import time

    time.sleep(0.2)  # all 16 queued behind the held slot
    for t in hold:
        sched.release(t)
    for th in threads:
        th.join(5.0)
    assert len(order) == 16, order
    first8 = order[:8]
    heavy = sum(1 for n in first8 if n.startswith("H"))
    assert heavy >= 5, f"heavy tenant under-served: {order}"
    assert any(n.startswith("L") for n in first8), \
        f"light tenant starved: {order}"


def test_scheduler_rejects_past_queue_depth():
    sched = QueryScheduler(max_concurrent=1, queue_depth=1)
    hold = _fill_slot(sched)
    th, _done = _queue_async(sched, "t", 1, [], "q1")
    import time

    time.sleep(0.1)  # q1 parked in the queue
    with pytest.raises(AdmissionRejected, match="queue full"):
        sched.admit("t")
    assert sched.stats()["rejected"] == 1
    for t in hold:
        sched.release(t)
    th.join(5.0)


def test_scheduler_clamps_to_semaphore_permits():
    """maxConcurrent above the device semaphore's permit count clamps:
    admission rides the same budget that caps batch residency."""
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.concurrentTpuTasks", 2)
    TpuSemaphore.reset()
    TpuSemaphore.get()
    sched = QueryScheduler(max_concurrent=16, queue_depth=8)
    assert sched._limit() == 2
    TpuSemaphore.get().resize(5)
    assert sched._limit() == 5


def test_scheduler_records_wait_and_context():
    sched = QueryScheduler(max_concurrent=1, queue_depth=8)
    t1 = sched.admit("a")
    ctx = current_serving_context()
    assert ctx["tenant"] == "a" and ctx["admit_wait_ms"] == 0.0
    done = threading.Event()
    waited_ms = []

    def second():
        t2 = sched.admit("b", priority=2)
        waited_ms.append(current_serving_context()["admit_wait_ms"])
        sched.release(t2)
        done.set()

    th = threading.Thread(target=second)
    th.start()
    import time

    time.sleep(0.15)
    sched.release(t1)
    assert done.wait(5.0)
    th.join()
    assert waited_ms[0] >= 100.0, waited_ms  # really waited
    st = sched.stats()
    assert st["admitted"] == 2
    assert st["wait_p99_ms"] >= 100.0


def test_admission_disabled_is_inert_and_reentrant():
    conf = get_conf()
    assert int(conf.get("spark.rapids.tpu.serving.maxConcurrent")) == 0
    with scheduler_mod.admission(conf) as ticket:
        assert ticket is None
    assert scheduler_stats()["admitted"] == 0
    # enabled: nested admission on one thread must not self-deadlock
    conf.set("spark.rapids.tpu.serving.maxConcurrent", 1)
    with scheduler_mod.admission(conf, tenant="x") as t1:
        assert t1 is not None
        with scheduler_mod.admission(conf, tenant="x") as t2:
            assert t2 is None  # re-entrant passthrough
    assert scheduler_stats()["admitted"] == 1


# ------------------------------------------------------------------ #
# Prepared-plan cache
# ------------------------------------------------------------------ #


def test_exec_tree_is_redrainable():
    """The cache's load-bearing assumption: collect_exec on one lowered
    tree twice returns identical results (close() resets join builds /
    shuffle registrations)."""
    from spark_rapids_tpu.plan.planner import collect_exec, plan_query

    s = TpuSession()
    df = _agg_df(s, _table())
    exec_, _meta = plan_query(df._plan, s.conf)
    r1 = collect_exec(exec_)
    r2 = collect_exec(exec_)
    assert r1.equals(r2)


def test_prepared_hit_skips_lowering_and_matches():
    import spark_rapids_tpu.session as session_mod
    from spark_rapids_tpu.plan import planner as planner_mod

    s = TpuSession()
    pq = s.prepare(_agg_df(s, _table()))
    first = pq.execute()
    calls = [0]
    orig = planner_mod.plan_query

    def counting(*a, **kw):
        calls[0] += 1
        return orig(*a, **kw)

    # both import bindings: prepared.py resolves through the planner
    # module, session.py through its own module-level import
    planner_mod.plan_query = counting
    session_mod.plan_query = counting
    try:
        second = pq.execute()
    finally:
        planner_mod.plan_query = orig
        session_mod.plan_query = orig
    assert calls[0] == 0, "cache hit re-entered plan_query"
    assert table_digest(first) == table_digest(second)
    st = plan_cache_mod.stats()
    assert st["hits"] >= 2 and st["misses"] == 1, st


def test_prepared_distinct_templates_distinct_entries():
    s = TpuSession()
    t = _table()
    pq1 = s.prepare(_agg_df(s, t))
    pq2 = s.prepare(s.create_dataframe(t)
                    .group_by(col("k"))
                    .agg((sum_(col("v")), "other_name"))
                    .order_by(col("k")))
    assert len(s.plan_cache) == 2
    assert pq1.execute().column_names != pq2.execute().column_names


def test_prepared_conf_epoch_changes_key():
    """Lowering reads conf, so a conf change must not reuse the old
    lowered tree — the key includes the conf fingerprint."""
    s = TpuSession()
    pq = s.prepare(_agg_df(s, _table()))
    pq.execute()
    misses0 = plan_cache_mod.stats()["misses"]
    s.conf.set("spark.rapids.tpu.sql.batchSizeRows", 512)
    r = pq.execute()  # new conf epoch: re-lowered, not stale-hit
    assert plan_cache_mod.stats()["misses"] == misses0 + 1
    assert r.num_rows == 16


def test_plan_cache_lru_eviction_closes_and_recounts():
    s = TpuSession()
    s._plan_cache = PlanCache(capacity=2)
    t = _table()
    for i in range(3):
        s.prepare(s.create_dataframe(t)
                  .group_by(col("k"))
                  .agg((sum_(col("v")), f"sv{i}"))
                  .order_by(col("k")))
    st = plan_cache_mod.stats()
    assert st["evictions"] == 1 and len(s.plan_cache) == 2
    # evicted template still works — it just re-lowers
    pq = s.prepare(s.create_dataframe(t)
                   .group_by(col("k"))
                   .agg((sum_(col("v")), "sv0"))
                   .order_by(col("k")))
    assert pq.execute().num_rows == 16


def test_prepared_sql_template_params_and_bindings():
    t = _table()
    ss = SqlSession()
    ss.register_table("t", t)
    pq = ss.prepare("select k, sum(v) as sv from t where k < :kmax "
                    "group by k order by k")
    assert pq.param_names == frozenset({"kmax"})
    a8 = pq.execute(params={"kmax": 8})
    b8 = pq.execute(params={"kmax": 8})   # same binding: HIT
    a4 = pq.execute(params={"kmax": 4})   # new binding: its own entry
    assert a8.num_rows == 8 and a4.num_rows == 4
    assert table_digest(a8) == table_digest(b8)
    st = plan_cache_mod.stats()
    assert st["hits"] >= 1 and st["misses"] == 2, st
    with pytest.raises(SqlError, match="unbound parameter :kmax"):
        pq.execute()


def test_template_key_distinguishes_shared_subplans():
    """DAG-shaped plans that share repeated subplan OBJECTS must key by
    WHICH node repeats: union(a,b)+a and union(a,b)+b differ only in
    the shared leg, and colliding them would re-drain the wrong cached
    tree."""
    from spark_rapids_tpu.serving.plan_cache import template_key

    s = TpuSession()
    a = s.create_dataframe(_table(seed=1))
    b = s.create_dataframe(_table(seed=2))
    ab_a = a.union(b).union(s.create_dataframe(_table(seed=1)))
    # share the SAME plan objects for the repeat legs
    aa = a.union(b)
    aa._plan.children.append(a._plan)  # union(a, b, a) with shared a
    bb = a.union(b)
    bb._plan.children.append(b._plan)  # union(a, b, b) with shared b
    conf = get_conf()
    assert template_key(aa._plan, conf) != template_key(bb._plan, conf)
    assert template_key(ab_a._plan, conf)  # content-digested, no crash


def test_template_key_memoizes_table_content_digest():
    """Repeated prepare()s of one in-memory table hash its buffers
    ONCE (InMemoryRelation.content_digest memo), not once per
    structural-key build — counter-verified, and the memoized digest
    is the same content identity table_digest computes."""
    from spark_rapids_tpu.plan import logical
    from spark_rapids_tpu.serving.plan_cache import plan_structural_key

    s = TpuSession()
    df = s.create_dataframe(_table(seed=7))
    before = logical.digests_computed()
    k1 = plan_structural_key(df._plan)
    assert logical.digests_computed() == before + 1
    k2 = plan_structural_key(df._plan)  # re-prepare: memo, no re-hash
    assert k2 == k1
    assert logical.digests_computed() == before + 1
    rel = df._plan
    while not isinstance(rel, logical.InMemoryRelation):
        rel = rel.children[0]
    assert rel.content_digest() == table_digest(rel.table)
    assert logical.digests_computed() == before + 1


def test_sql_template_key_preserves_string_literal_whitespace():
    """Whitespace normalization must not reach inside string literals:
    'a  b' and 'a b' are different queries and must never share one
    cache entry (a stale hit would answer the wrong query)."""
    from spark_rapids_tpu.serving.plan_cache import sql_template_key

    conf = get_conf()
    k1 = sql_template_key("select * from t where s = 'a  b'", conf)
    k2 = sql_template_key("select * from t where s = 'a b'", conf)
    assert k1 != k2
    # benign formatting differences DO share a key
    k3 = sql_template_key("select *\n  from t\n where s = 'a  b'",
                          conf)
    assert k1 == k3


def test_nested_admission_does_not_inherit_serving_context():
    """A nested collect on an admitted thread (subquery prepass,
    CPU-compare) must not report the outer query's admission wait /
    tenant as its own; the outer context is restored afterwards."""
    from spark_rapids_tpu.serving import update_serving_context

    conf = get_conf()
    conf.set("spark.rapids.tpu.serving.maxConcurrent", 1)
    with scheduler_mod.admission(conf, tenant="outer"):
        update_serving_context(plan_cache="hit")
        outer = current_serving_context()
        assert outer["tenant"] == "outer"
        with scheduler_mod.admission(conf, tenant="ignored"):
            assert current_serving_context() is None
        restored = current_serving_context()
        assert restored["tenant"] == "outer"
        assert restored["plan_cache"] == "hit"


def test_sql_named_params_inline_and_errors():
    t = _table()
    ss = SqlSession()
    ss.register_table("t", t)
    r = ss.sql("select k, sum(v) as sv from t where k = :k group by k",
               params={"k": 3})
    out = r.collect(engine="tpu")
    assert out.num_rows == 1 and out.to_pydict()["k"] == [3]
    # typed literal binding: str / bool / date / None
    import datetime as dt

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.frontends.sql import _param_literal

    assert _param_literal("s", "abc", 0).dtype == T.STRING
    assert _param_literal("b", True, 0).dtype == T.BOOLEAN
    dlit = _param_literal("d", dt.date(1996, 1, 1), 0)
    assert dlit.dtype == T.DATE and dlit.value == 9496
    assert _param_literal("n", None, 0).value is None
    with pytest.raises(SqlError, match="unbound parameter :missing"):
        ss.sql("select * from t where k = :missing")
    with pytest.raises(SqlError, match="unknown parameter"):
        ss.sql("select * from t where k = :k",
               params={"k": 1, "typo": 2})
    with pytest.raises(SqlError, match="unsupported type"):
        ss.sql("select * from t where k = :k", params={"k": [1, 2]})


# ------------------------------------------------------------------ #
# Streaming result fetch
# ------------------------------------------------------------------ #


def test_execute_stream_matches_collect_multibatch():
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.batchSizeRows", 512)
    s = TpuSession()
    t = _table(n=4096)
    # projection+filter (no aggregate): output stays multi-batch, so
    # the stream actually streams
    df = (s.create_dataframe(t)
          .where(col("k") < col("v"))
          .select(col("k"), (col("v") + col("k")).alias("vk")))
    pq = s.prepare(df)
    collected = pq.execute()
    batches = list(pq.execute_stream())
    assert len(batches) > 1, "stream produced one giant batch"
    streamed = pa.Table.from_batches(batches, schema=collected.schema)
    assert table_digest(streamed) == table_digest(collected)
    # batch_rows re-chunks without changing content
    rechunked = list(pq.execute_stream(batch_rows=100))
    assert all(rb.num_rows <= 100 for rb in rechunked)
    assert table_digest(
        pa.Table.from_batches(rechunked, schema=collected.schema)) \
        == table_digest(collected)


def test_execute_stream_early_close_releases_everything():
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.batchSizeRows", 256)
    conf.set("spark.rapids.tpu.serving.maxConcurrent", 1)
    s = TpuSession()
    df = (s.create_dataframe(_table(n=4096))
          .where(col("k") >= 0)
          .select(col("k"), col("v")))
    pq = s.prepare(df)
    gen = pq.execute_stream()
    next(gen)
    gen.close()  # abandon mid-stream
    # entry lock AND the admission slot must be free again; run the
    # re-execute on a guard thread so a leak fails instead of hanging
    out: list = []
    th = threading.Thread(target=lambda: out.append(pq.execute()))
    th.start()
    th.join(60.0)
    assert out, "abandoned stream leaked its admission slot/entry lock"
    assert out[0].num_rows == 4096


def test_open_stream_same_thread_reexecute_raises_not_deadlocks():
    """A partially consumed stream holds the template's drain lock on
    the consumer thread; re-executing the same template there must
    raise immediately with an explanation (a plain lock would hang the
    thread forever — reproduced before the DrainLock owner check)."""
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.batchSizeRows", 256)
    s = TpuSession()
    df = (s.create_dataframe(_table(n=2048))
          .where(col("k") >= 0).select(col("k"), col("v")))
    pq = s.prepare(df)
    gen = pq.execute_stream()
    next(gen)
    with pytest.raises(RuntimeError, match="still draining"):
        pq.execute()
    with pytest.raises(RuntimeError, match="still draining"):
        next(pq.execute_stream())
    # drain the open stream: the lock releases and execution works
    for _ in gen:
        pass
    assert pq.execute().num_rows == 2048


def test_eviction_of_streaming_entry_does_not_block():
    """Evicting an entry whose drain lock is held (an open stream on
    THIS thread) must neither hang nor raise — the in-flight drain
    closes its own tree when it finishes."""
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.batchSizeRows", 256)
    s = TpuSession()
    s._plan_cache = PlanCache(capacity=1)
    t = _table(n=2048)
    pq1 = s.prepare(s.create_dataframe(t)
                    .where(col("k") >= 0).select(col("k")))
    gen = pq1.execute_stream()
    next(gen)  # pq1's entry lock held by this thread
    # preparing a second template evicts pq1's entry (capacity 1)
    pq2 = s.prepare(s.create_dataframe(t)
                    .where(col("v") >= 0).select(col("v")))
    assert plan_cache_mod.stats()["evictions"] == 1
    rest = sum(tbl.num_rows for tbl in gen)  # stream still drains
    assert rest > 0
    assert pq2.execute().num_rows == 2048


def test_stream_records_history_on_drain():
    s = TpuSession()
    pq = s.prepare(_agg_df(s, _table()))
    n_before = len(s.history.events)
    _ = list(pq.execute_stream())
    events = s.history.events
    assert len(events) == n_before + 1
    assert "Aggregate" in events[-1].explain


# ------------------------------------------------------------------ #
# Event log + health (HC009)
# ------------------------------------------------------------------ #


def test_eventlog_serving_record(tmp_path):
    from spark_rapids_tpu.tools.history import load_application

    conf = get_conf()
    conf.set("spark.rapids.tpu.eventLog.enabled", True)
    conf.set("spark.rapids.tpu.eventLog.dir", str(tmp_path))
    conf.set("spark.rapids.tpu.serving.maxConcurrent", 2)
    s = TpuSession(conf, tenant="acme", priority=3)
    pq = s.prepare(_agg_df(s, _table()))
    pq.execute()   # miss->insert happened at prepare; this is a hit
    _ = s.history.events  # drain: the log is complete
    app = load_application(s.event_log_path)
    q = app.queries[-1]
    assert "serve.admit_wait_ms" in q.counters
    assert q.counters["serve.plan_cache_hit"] == 1
    serving = q.raw.get("serving")
    assert serving["tenant"] == "acme" and serving["priority"] == 3
    assert serving["plan_cache"] == "hit"


def test_hc009_flags_admission_wait_over_budget():
    from spark_rapids_tpu.tools.history import (
        _query_from_record,
        health_check,
        ApplicationInfo,
    )

    def rec(wait_ms):
        return _query_from_record({
            "query_id": 1, "plan": "x", "plan_hash": "h",
            "engine": "tpu", "wall_s": 1.0, "start_ts": 0.0,
            "end_ts": 1.0, "conf_hash": "c",
            "counters": {"serve.admit_wait_ms": wait_ms},
            "serving": {"tenant": "acme", "admit_wait_ms": wait_ms},
        })

    get_conf().set(
        "spark.rapids.tpu.serving.health.admitWaitBudgetMs", 100.0)
    app = ApplicationInfo("log", "eventlog", {},
                          [rec(50.0), rec(5000.0)])
    findings = [f for f in health_check(app) if f.rule == "HC009"]
    assert len(findings) == 1
    assert "5000ms" in findings[0].message
    assert "acme" in findings[0].message


def test_serving_smoke():
    """tools/bench_smoke.run_serving_smoke wired into tier-1."""
    from spark_rapids_tpu.tools.bench_smoke import run_serving_smoke

    out = run_serving_smoke()
    assert out["serving_plan_cache_hits"] >= 1
    assert out["serving_admitted"] >= 6


# ------------------------------------------------------------------ #
# THE concurrent-session stress test
# ------------------------------------------------------------------ #


def test_concurrent_sessions_stress(tmp_path):
    """N sessions x M queries on distinct confs, concurrently:

    - results bit-identical to serial execution (integer aggregates +
      pinned order, so digests must match exactly);
    - conf isolation: each thread runs its own batchSizeRows without
      leaking into the others;
    - trace ownership (PR3 sync_conf rules): only session 0 traces;
      the other sessions' collects must not kill its capture;
    - eventlog ownership: each session's log holds exactly its own
      queries;
    - per-session query_id monotonicity."""
    from spark_rapids_tpu import trace
    from spark_rapids_tpu.tools.history import load_application

    n_sessions, m_iters = 4, 3
    t = _table(n=4096, keys=32)

    # serial reference digests, one per template variant
    s0 = TpuSession()
    serial = {}
    for i in range(n_sessions):
        df = (s0.create_dataframe(t)
              .where(col("v") >= i)
              .group_by(col("k"))
              .agg((sum_(col("v")), "sv"), (count_star(), "n"))
              .order_by(col("k")))
        serial[i] = table_digest(df.collect(engine="tpu"))

    errors: list = []
    sessions: list = [None] * n_sessions

    def run(i: int) -> None:
        try:
            conf = TpuConf({
                "spark.rapids.tpu.sql.batchSizeRows": 256 * (i + 1),
                "spark.rapids.tpu.serving.maxConcurrent": 2,
                "spark.rapids.tpu.eventLog.enabled": True,
                "spark.rapids.tpu.eventLog.dir":
                    str(tmp_path / f"s{i}"),
                "spark.rapids.tpu.trace.enabled": i == 0,
            })
            set_conf(conf)
            sess = TpuSession(conf, tenant=f"tenant{i % 2}",
                              priority=1 + (i % 2))
            sessions[i] = sess
            df = (sess.create_dataframe(t)
                  .where(col("v") >= i)
                  .group_by(col("k"))
                  .agg((sum_(col("v")), "sv"), (count_star(), "n"))
                  .order_by(col("k")))
            pq = sess.prepare(df)
            for _ in range(m_iters):
                d = table_digest(pq.execute())
                if d != serial[i]:
                    errors.append((i, "digest mismatch"))
        except BaseException as e:  # noqa: BLE001 — reported below
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(n_sessions)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(120.0)
    assert not errors, errors

    # trace ownership: session 0's spans survived 3 sessions' worth of
    # concurrent sync_conf(trace off) calls
    assert trace.is_enabled(), \
        "a non-tracing session's collect killed the tracing session"
    span_qids = {e.attrs.get("query_id")
                 for e in trace.snapshot() if e.name == "query.execute"}
    s0_qids = {ev.query_id for ev in sessions[0].history.events}
    assert s0_qids & span_qids, "tracing session captured no spans"

    for i, sess in enumerate(sessions):
        events = sess.history.events  # drains the eventlog too
        qids = [ev.query_id for ev in events]
        assert qids == sorted(qids) and len(set(qids)) == len(qids), \
            f"session {i} query ids not monotonic: {qids}"
        assert len(events) == m_iters
        app = load_application(sess.event_log_path)
        assert len(app.queries) == m_iters, \
            f"session {i} log holds foreign/missing queries"
        assert {q.query_id for q in app.queries} == set(qids)
        for q in app.queries:
            assert q.raw["serving"]["tenant"] == f"tenant{i % 2}"
