"""Expression engine tests: Spark SQL null semantics and arithmetic parity
against hand-computed expectations (model: the reference's CastOpSuite /
arithmetic unit suites, SURVEY.md section 4 tier 2)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.arrow import from_arrow
from spark_rapids_tpu.exprs.base import ColumnReference, EvalContext, Literal, bind_references, lit
from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.columnar.column import column_to_numpy


def col(name):
    return ColumnReference(name)


def eval_expr(expr, table):
    batch = from_arrow(table)
    bound = bind_references(expr, batch.schema)
    ctx = EvalContext.for_batch(batch)
    out = bound.eval(ctx)
    n = batch.concrete_num_rows()
    vals, valid = column_to_numpy(out, n)
    return [
        (vals[i].item() if hasattr(vals[i], "item") else vals[i])
        if valid[i] else None
        for i in range(n)
    ]


T1 = pa.table({
    "a": pa.array([1, 2, None, -7, 9], pa.int64()),
    "b": pa.array([3, 0, 5, 2, None], pa.int64()),
    "x": pa.array([1.5, -2.0, None, 0.0, float("nan")], pa.float64()),
    "p": pa.array([True, False, None, True, False], pa.bool_()),
    "q": pa.array([True, None, None, False, True], pa.bool_()),
    "s": pa.array(["apple", "banana", None, "", "apple"], pa.string()),
})


def test_add_nulls():
    assert eval_expr(col("a") + col("b"), T1) == [4, 2, None, -5, None]


def test_divide_by_zero_is_null():
    out = eval_expr(col("a") / col("b"), T1)
    assert out[0] == pytest.approx(1 / 3)
    assert out[1] is None  # 2/0 -> NULL (Spark non-ANSI)
    assert out[2] is None
    assert out[3] == pytest.approx(-3.5)
    assert out[4] is None


def test_integral_divide_truncates_toward_zero():
    t = pa.table({"a": pa.array([7, -7, 7, -7, 0], pa.int64()),
                  "b": pa.array([2, 2, -2, -2, 0], pa.int64())})
    assert eval_expr(A.IntegralDivide(col("a"), col("b")), t) == \
        [3, -3, -3, 3, None]


def test_remainder_java_sign():
    t = pa.table({"a": pa.array([7, -7, 7, -7], pa.int64()),
                  "b": pa.array([3, 3, -3, -3], pa.int64())})
    assert eval_expr(A.Remainder(col("a"), col("b")), t) == [1, -1, 1, -1]


def test_pmod_spark_semantics():
    # Spark pmod: r = a % n (Java %); if r < 0 then (r + n) % n else r
    # => pmod(-7, 3) = 2 but pmod(7, -3) = 1, pmod(-7, -3) = -1
    t = pa.table({"a": pa.array([7, -7, 7, -7], pa.int64()),
                  "b": pa.array([3, 3, -3, -3], pa.int64())})
    assert eval_expr(A.Pmod(col("a"), col("b")), t) == [1, 2, 1, -1]


def test_comparisons_null_propagate():
    assert eval_expr(col("a") > col("b"), T1) == \
        [False, True, None, False, None]
    assert eval_expr(col("a").eq(lit(2)), T1) == \
        [False, True, None, False, False]


def test_kleene_and_or():
    assert eval_expr(col("p") & col("q"), T1) == \
        [True, False, None, False, False]
    assert eval_expr(col("p") | col("q"), T1) == \
        [True, None, None, True, True]


def test_is_null_not_null():
    assert eval_expr(col("a").is_null(), T1) == \
        [False, False, True, False, False]
    assert eval_expr(col("x").is_not_null(), T1) == \
        [True, True, False, True, True]


def test_equal_null_safe():
    t = pa.table({"a": pa.array([1, None, None, 4], pa.int64()),
                  "b": pa.array([1, None, 3, 5], pa.int64())})
    assert eval_expr(P.EqualNullSafe(col("a"), col("b")), t) == \
        [True, True, False, False]


def test_string_compare():
    assert eval_expr(col("s").eq(lit("apple")), T1) == \
        [True, False, None, False, True]
    assert eval_expr(col("s") < lit("b"), T1) == \
        [True, False, None, True, True]


def test_string_embedded_nul():
    t = pa.table({"s": pa.array(["a", "a\x00", "a\x00b"], pa.string())})
    assert eval_expr(col("s").eq(lit("a")), t) == [True, False, False]
    assert eval_expr(col("s") < lit("a\x00"), t) == [True, False, False]


def test_in_set():
    assert eval_expr(P.In(col("a"), (1, 9)), T1) == \
        [True, False, None, False, True]
    # list containing NULL: no-match rows become NULL
    assert eval_expr(P.In(col("a"), (1, None)), T1) == \
        [True, None, None, None, None]
    assert eval_expr(P.In(col("s"), ("apple", "")), T1) == \
        [True, False, None, True, True]


def test_coalesce():
    assert eval_expr(P.Coalesce(col("a"), col("b")), T1) == [1, 2, 5, -7, 9]
    assert eval_expr(P.Coalesce(col("s"), lit("zz")), T1) == \
        ["apple", "banana", "zz", "", "apple"]


def test_if_case_when():
    e = P.If(col("a") > lit(0), col("a"), A.UnaryMinus(col("a")))
    assert eval_expr(e, T1) == [1, 2, None, 7, 9]
    cw = P.CaseWhen(
        (((col("a") > lit(5)), lit(100)), ((col("a") > lit(0)), lit(10))),
        lit(0))
    assert eval_expr(cw, T1) == [10, 10, 0, 0, 100]


def test_least_greatest():
    assert eval_expr(A.Least(col("a"), col("b")), T1) == [1, 0, 5, -7, 9]
    assert eval_expr(A.Greatest(col("a"), col("b")), T1) == [3, 2, 5, 2, 9]


def test_isnan():
    # Spark IsNaN is non-nullable: NULL input -> false
    assert eval_expr(P.IsNaN(col("x")), T1) == \
        [False, False, False, False, True]


def test_nan_total_order():
    t = pa.table({"x": pa.array([1.0, float("nan"), float("nan"), 5.0],
                                pa.float64()),
                  "y": pa.array([float("nan"), float("nan"), 2.0, 4.0],
                                pa.float64())})
    # Spark: NaN == NaN true, NaN greater than everything
    assert eval_expr(col("x").eq(col("y")), t) == \
        [False, True, False, False]
    assert eval_expr(col("x") > col("y"), t) == \
        [False, False, True, True]
    assert eval_expr(col("x") < col("y"), t) == \
        [True, False, False, False]
    assert eval_expr(col("x") >= col("y"), t) == \
        [False, True, True, True]


def test_if_widens_types():
    t = pa.table({"a": pa.array([1, 2], pa.int64()),
                  "x": pa.array([1.5, 2.5], pa.float64()),
                  "p": pa.array([True, False], pa.bool_())})
    assert eval_expr(P.If(col("p"), col("a"), col("x")), t) == [1.0, 2.5]
    assert eval_expr(A.Least(col("a"), col("x")), t) == [1.0, 2.0]


def test_abs_unary_minus():
    assert eval_expr(A.Abs(col("a")), T1) == [1, 2, None, 7, 9]
    assert eval_expr(A.UnaryMinus(col("a")), T1) == [-1, -2, None, 7, -9]


def test_literal_null():
    assert eval_expr(Literal.of(None, T.LONG) + col("a"), T1) == [None] * 5


def test_least_greatest_nan_and_inf_null():
    """Spark contract: NaN is the greatest value; +/-inf must survive
    alongside NULL slots (regression: sentinel collision)."""
    t = pa.table({
        "x": pa.array([float("nan"), float("inf"), float("-inf"), 1.0]),
        "y": pa.array([1.0, None, None, 2.0]),
    })
    l = eval_expr(A.Least(col("x"), col("y")), t)
    assert l == [1.0, float("inf"), float("-inf"), 1.0]
    g = eval_expr(A.Greatest(col("x"), col("y")), t)
    assert np.isnan(g[0])
    assert g[1:] == [float("inf"), float("-inf"), 2.0]


def test_case_when_dtype_widens_to_else_branch():
    """Regression: CaseWhen.dtype must match what eval returns (widened
    over all branches + else), or the projected schema mistypes data."""
    cw = P.CaseWhen(((lit(True), lit(100)),), lit(2.5))
    assert cw.dtype == T.DOUBLE
    t = pa.table({"a": pa.array([1, 2], pa.int64())})
    assert eval_expr(cw, t) == [100.0, 100.0]


def test_least_greatest_extreme_values_with_nulls():
    """Regression: a valid LONG_MAX/LONG_MIN must beat a NULL slot (no
    sentinel-key collision)."""
    t = pa.table({
        "a": pa.array([None, None], pa.int64()),
        "b": pa.array([2**63 - 1, -(2**63)], pa.int64()),
    })
    assert eval_expr(A.Least(col("a"), col("b")), t) == \
        [2**63 - 1, -(2**63)]
    assert eval_expr(A.Greatest(col("a"), col("b")), t) == \
        [2**63 - 1, -(2**63)]
