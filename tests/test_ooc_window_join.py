"""Out-of-core window + chunked join output (VERDICT r3 #5): window and
join must survive inputs far larger than one working batch — window via
the hash exchange on partition_by (per-reduce-partition windowing,
ref: GpuWindowExec's ClusteredDistribution requirement), join via
target-size output chunks (ref: JoinGatherer.scala:55,138)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.session import TpuSession, col
from tests.differential import assert_tpu_cpu_equal


@pytest.fixture
def session():
    return TpuSession()


def _multifile(tmp_path, t, n_files, stem):
    paths = []
    per = t.num_rows // n_files
    for i in range(n_files):
        p = str(tmp_path / f"{stem}{i}.parquet")
        pq.write_table(t.slice(i * per, per if i < n_files - 1
                               else t.num_rows - i * per), p)
        paths.append(p)
    return paths


def test_window_partitioned_streaming(session, tmp_path):
    """Multi-partition child: the planner exchanges on partition_by and
    windows per reduce partition — the plan shows the exchange and the
    result matches the CPU oracle."""
    from spark_rapids_tpu.exprs.window import Window, row_number
    from spark_rapids_tpu.plan.planner import plan_query

    rng = np.random.default_rng(3)
    n = 6000
    t = pa.table({
        "k": rng.integers(0, 40, n),
        "o": rng.integers(0, 1000, n),
        "v": rng.random(n),
    })
    paths = _multifile(tmp_path, t, 6, "w")
    get_conf().set("spark.rapids.tpu.sql.scan.taskTargetBytes", 1024)
    spec = Window.partition_by("k").order_by("o", "v")
    df = session.read_parquet(*paths).select(
        col("k"), col("o"), col("v"),
        row_number().over(spec).alias("rn"))
    exec_, _ = plan_query(df._plan, session.conf)
    tree = exec_.tree_string()
    assert "per-partition" in tree and "TpuShuffleExchangeExec" in tree, \
        tree
    assert_tpu_cpu_equal(df, approx_float=True)


def test_window_10x_budget(session, tmp_path):
    """Input ~10x one scan batch: per-partition windowing keeps every
    program bounded to a reduce partition."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.exprs.window import Window
    from spark_rapids_tpu.session import sum_

    rng = np.random.default_rng(5)
    n = 8000
    t = pa.table({
        "k": rng.integers(0, 16, n),
        "o": rng.integers(0, 100, n),
        "v": rng.integers(0, 50, n),
    })
    paths = _multifile(tmp_path, t, 8, "x")
    conf = get_conf()
    conf.set(BATCH_SIZE_ROWS.key, 800)  # ~10 batches of input
    conf.set("spark.rapids.tpu.sql.scan.taskTargetBytes", 1024)
    spec = Window.partition_by("k").order_by("o")
    df = session.read_parquet(*paths).select(
        col("k"),
        sum_(col("v")).over(spec).alias("s"))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_join_output_chunking_exact(session):
    """Join output larger than the chunk size arrives in multiple
    bounded batches with exactly the right rows (forced tiny chunks)."""
    rng = np.random.default_rng(7)
    left = pa.table({"k": rng.integers(0, 5, 400),
                     "lv": np.arange(400)})
    right = pa.table({"k": rng.integers(0, 5, 50),
                      "rv": np.arange(50)})
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.join.outputChunkRows", 256)
    df = session.create_dataframe(left).join(
        session.create_dataframe(right), on="k", how="inner")
    got = df.collect(engine="tpu")
    # expected join cardinality ~ 400*50/5 = 4000 rows >> 256-row chunks
    want = df.collect(engine="cpu")
    assert got.num_rows == want.num_rows
    assert sorted(zip(*got.to_pydict().values())) == \
        sorted(zip(*want.to_pydict().values()))


def test_join_chunking_with_condition_and_outer(session):
    rng = np.random.default_rng(9)
    from spark_rapids_tpu.exprs.base import lit

    left = pa.table({"k": rng.integers(0, 4, 300),
                     "lv": rng.integers(0, 100, 300)})
    right = pa.table({"k": rng.integers(0, 6, 60),
                      "rv": rng.integers(0, 100, 60)})
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.join.outputChunkRows", 128)
    ldf, rdf = (session.create_dataframe(x) for x in (left, right))
    df = ldf.join(rdf, on="k", how="left_outer")
    assert_tpu_cpu_equal(df)
    df2 = ldf.join(rdf, on="k", how="inner",
                   condition=col("lv") > col("rv"))
    assert_tpu_cpu_equal(df2)
