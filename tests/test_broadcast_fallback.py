"""Spillable broadcast builds (store pin counts) and batch-wise
streaming CPU fallback (ref: GpuBroadcastExchangeExec.scala:237,271
spillable broadcast catalog entries; the reference's fallback boundary
is row-iterator streaming)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.memory.store import BufferStore, StorageTier
from spark_rapids_tpu.session import TpuSession, col
from tests.differential import assert_tpu_cpu_equal, gen_table


def _batch(n, seed=0):
    from spark_rapids_tpu.columnar.arrow import from_arrow

    rng = np.random.default_rng(seed)
    return from_arrow(pa.table({"x": rng.integers(0, 100, n)}))


def test_pin_count_shared_entry():
    """Two concurrent acquires of one entry: the first unpin must not
    make it evictable under the second (broadcast-build sharing)."""
    store = BufferStore(device_budget=1 << 30, host_budget=1 << 30)
    h = store.register(_batch(100))
    e = store._entries[h.buffer_id]
    h.get()
    h.get()
    assert e.pins == 2 and e.pinned
    h.unpin()
    assert e.pins == 1 and e.pinned  # still in use elsewhere
    h.unpin()
    assert e.pins == 0 and not e.pinned
    h.unpin()  # over-unpin clamps at zero
    assert e.pins == 0
    h.close()
    store.close()


def test_broadcast_build_is_spillable_and_released():
    """The broadcast join registers its build with the store (spillable
    between partitions) and close() releases it."""
    from spark_rapids_tpu.execs.join import TpuBroadcastHashJoinExec
    from spark_rapids_tpu.memory import get_store
    from spark_rapids_tpu.plan.planner import collect_exec, plan_query

    session = TpuSession()
    build = gen_table({"k": "smallint64", "v": "float64"}, 30, seed=3,
                      null_prob=0.0)
    stream = gen_table({"k": "smallint64", "w": "float64"}, 500, seed=4,
                       null_prob=0.0)
    df = session.create_dataframe(stream).join(
        session.create_dataframe(build), on="k")
    exec_, _ = plan_query(df._plan, session.conf)
    joins = [n for n in exec_._walk()
             if isinstance(n, TpuBroadcastHashJoinExec)]
    assert joins, exec_.tree_string()
    store = get_store()
    before = len(store._entries)
    out = collect_exec(exec_)  # collect_exec closes the plan when done
    assert out.num_rows > 0
    assert len(store._entries) == before  # build entry released
    assert joins[0]._build_handle is None


def test_streaming_fallback_filter_project():
    """A CPU-fallback Filter/Project over multi-batch input streams
    batch-wise and matches the all-TPU answer."""
    conf = TpuConf()
    conf.set("spark.rapids.tpu.sql.exec.Filter", False)
    conf.set("spark.rapids.tpu.sql.batchSizeRows", 128)
    session = TpuSession(conf)
    t = gen_table({"a": "int64", "b": "float64"}, 1000, seed=7)
    q = session.create_dataframe(t).where(col("a") > lit(0)) \
        .select((col("a") + lit(1)).alias("a1"), col("b"))
    assert "! Filter" in q.explain()
    assert_tpu_cpu_equal(q, approx_float=True)


def test_streaming_fallback_emits_multiple_batches():
    from spark_rapids_tpu.plan.planner import CpuFallbackExec, plan_query

    session = TpuSession()  # shared thread-local conf (restored by the
    session.conf.set("spark.rapids.tpu.sql.exec.Filter", False)  # fixture)
    session.conf.set("spark.rapids.tpu.sql.batchSizeRows", 128)
    t = gen_table({"a": "int64"}, 1000, seed=8, null_prob=0.0)
    q = session.create_dataframe(t).where(col("a") >= lit(-(2 ** 62)))
    exec_, _ = plan_query(q._plan, session.conf)
    fb = [n for n in exec_._walk() if isinstance(n, CpuFallbackExec)]
    assert fb, exec_.tree_string()
    batches = list(fb[0].execute())
    assert len(batches) > 1  # streamed, not one materialized table
    assert sum(b.concrete_num_rows() for b in batches) == 1000
