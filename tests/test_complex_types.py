"""Struct/Map column plane + extractor expression tests (ref:
complexTypeExtractors.scala GpuGetStructField/GpuGetMapValue/
GpuElementAt, complexTypeCreator.scala GpuCreateNamedStruct,
TypeChecks.scala:129 nested signatures)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.session import TpuSession, col, sum_
from tests.differential import assert_tpu_cpu_equal


@pytest.fixture
def session():
    return TpuSession()


def _struct_table(n=500, seed=3):
    rng = np.random.default_rng(seed)
    nulls = rng.random(n) < 0.15
    inner_null = rng.random(n) < 0.2
    x = pa.array(rng.integers(0, 100, n), pa.int64(), mask=inner_null)
    y = pa.array(rng.random(n), pa.float64())
    s = pa.StructArray.from_arrays(
        [x, y], names=["x", "y"],
        mask=pa.array(nulls))
    return pa.table({"s": s, "w": pa.array(rng.integers(0, 9, n))})


def _map_table(n=400, seed=5):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        if rng.random() < 0.12:
            rows.append(None)
        else:
            k = rng.integers(0, 6, rng.integers(0, 4))
            rows.append([(int(kk), float(rng.random())) for kk in
                         dict.fromkeys(k.tolist())])
    m = pa.array(rows, pa.map_(pa.int64(), pa.float64()))
    return pa.table({"m": m, "v": pa.array(np.arange(n))})


def test_struct_roundtrip_arrow(session):
    """struct column H2D -> D2H is exact (incl. null parents)."""
    t = _struct_table()
    out = session.create_dataframe(t).collect(engine="tpu")
    assert out.column("s").combine_chunks().equals(
        t.column("s").combine_chunks())


def test_map_roundtrip_arrow(session):
    t = _map_table()
    got = session.create_dataframe(t).collect(engine="tpu")
    assert got.column("m").to_pylist() == t.column("m").to_pylist()


def test_get_struct_field_differential(session):
    t = _struct_table()
    df = (session.create_dataframe(t)
          .select(col("s").get_field("x").alias("sx"),
                  (col("s").get_field("y") * 2.0).alias("sy2"),
                  col("w")))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_struct_field_in_filter_and_agg(session):
    t = _struct_table()
    df = (session.create_dataframe(t)
          .where(col("s").get_field("x") > lit(50))
          .agg((sum_(col("s").get_field("y")), "total")))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_create_named_struct_differential(session):
    t = _struct_table()
    df = (session.create_dataframe(t)
          .select(col("w"),
                  col("s").get_field("x").alias("x")))
    # build a struct, then extract from it — round trip through the
    # constructor
    from spark_rapids_tpu.exprs.complex import CreateNamedStruct

    ns = CreateNamedStruct(("a", "b"), (col("w"), col("x")))
    df2 = df.select(ns.alias("st"))
    df3 = df2.select(col("st").get_field("a").alias("a"),
                     col("st").get_field("b").alias("b"))
    assert_tpu_cpu_equal(df3)


def test_get_map_value_differential(session):
    t = _map_table()
    df = (session.create_dataframe(t)
          .select(col("m").get_map_value(lit(2)).alias("m2"),
                  col("m").element_at(lit(4)).alias("m4"),
                  col("v")))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_element_at_array_differential(session):
    rng = np.random.default_rng(9)
    rows = [None if rng.random() < 0.1 else
            rng.integers(0, 50, rng.integers(0, 5)).tolist()
            for _ in range(300)]
    t = pa.table({"a": pa.array(rows, pa.list_(pa.int64()))})
    df = (session.create_dataframe(t)
          .select(col("a").element_at(lit(1)).alias("first"),
                  col("a").element_at(lit(-1)).alias("last"),
                  col("a").element_at(lit(3)).alias("third")))
    assert_tpu_cpu_equal(df)


def test_struct_parquet_scan(session, tmp_path):
    """Nested columns through the real Parquet scan (pyarrow decode
    path; fastpar refuses nested and falls back)."""
    t = _struct_table(300)
    p = str(tmp_path / "s.parquet")
    pq.write_table(t, p)
    df = (session.read_parquet(p)
          .select(col("s").get_field("y").alias("y"), col("w"))
          .where(col("w") > lit(3)))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_struct_survives_spill(session):
    """Struct batches spill to host/disk and re-materialize exactly."""
    from spark_rapids_tpu.columnar.arrow import from_arrow, to_arrow
    from spark_rapids_tpu.memory import SpillPriorities, get_store

    t = _struct_table(200)
    b = from_arrow(t)
    store = get_store()
    h = store.register(b, SpillPriorities.COALESCE_PENDING)
    h.unpin()
    store.spill_all_unpinned()
    back = h.get()
    assert to_arrow(back).column("s").combine_chunks().equals(
        t.column("s").combine_chunks())
    h.close()


def test_map_string_values_fall_back(session):
    """map<*, string> has no device layout: the query still answers
    (CPU engine) instead of crashing."""
    rows = [[("a", "x")], None, [("b", "y"), ("c", None)]] * 30
    t = pa.table({"m": pa.array(rows, pa.map_(pa.string(), pa.string())),
                  "v": pa.array(np.arange(90))})
    df = session.create_dataframe(t).select(col("v"))
    out = df.collect(engine="tpu")
    assert out.num_rows == 90


def test_concat_and_collect_struct_multibatch(session):
    """Struct columns across multiple batches (concat path)."""
    t = _struct_table(700, seed=11)
    df = session.create_dataframe(t) \
        .select(col("s").get_field("x").alias("x"))
    assert_tpu_cpu_equal(df)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_fuzz_nested_extract_pipeline(session, seed):
    """Seeded fuzz: random nested rows through extract/filter/project
    pipelines match the CPU oracle (the data_gen.py nested-row sweep)."""
    from tests.differential import gen_table

    t = gen_table({"s": "struct", "m": "map", "k": "smallint64"},
                  400, seed=seed)
    df = (session.create_dataframe(t)
          .select(col("s").get_field("a").alias("sa"),
                  col("s").get_field("b").alias("sb"),
                  col("m").element_at(lit(int(seed) % 8)).alias("mv"),
                  col("k"))
          .where(col("sa").is_not_null() | col("k").is_null()))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_sliced_map_array_decodes_correctly(session):
    """Regression: MapArray.keys/.items are the FULL child with
    absolute offsets — a sliced map must not decode shifted entries."""
    m = pa.array([[(1, 1.0)], [(2, 2.0), (3, 3.0)], [(4, 4.0)],
                  [(5, 5.0)]], pa.map_(pa.int64(), pa.float64()))
    rb = pa.record_batch([m.slice(2, 2)], names=["m"])
    from spark_rapids_tpu.columnar.arrow import from_arrow, to_arrow

    b = from_arrow(rb)
    assert to_arrow(b).column("m").to_pylist() == [[(4, 4.0)],
                                                   [(5, 5.0)]]


def test_list_of_struct_falls_back(session):
    """list<struct> has no dense device layout: CPU fallback, not a
    crash."""
    rows = [[{"a": 1}], None, [{"a": 2}, {"a": 3}]] * 20
    t = pa.table({
        "x": pa.array(rows, pa.list_(pa.struct([("a", pa.int64())]))),
        "v": pa.array(np.arange(60))})
    out = session.create_dataframe(t).select(col("v")).collect(
        engine="tpu")
    assert out.num_rows == 60


def test_get_host_on_device_struct_batch(session):
    """Regression: get_host() on a device-resident nested batch."""
    from spark_rapids_tpu.columnar.arrow import from_arrow
    from spark_rapids_tpu.memory import SpillPriorities, get_store

    b = from_arrow(_struct_table(50))
    h = get_store().register(b, SpillPriorities.ACTIVE_ON_DECK)
    arrays = h.get_host()
    assert any(k.startswith("c0_f0") for k in arrays)
    h.close()
