"""Collective (tier-2) shuffle transport tests on the 8-virtual-CPU-device
mesh: the planner lowers grouped aggregates to the fused all_to_all SPMD
program and results match the CPU oracle (the RapidsShuffleTransport SPI
coverage analog, ref: RapidsShuffleClientSuite et al. — here the fabric
is XLA collectives, so correctness is tested end-to-end through the
session instead of against a mocked wire protocol)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.session import TpuSession, avg, col, count, sum_
from tests.differential import assert_tpu_cpu_equal, gen_table


@pytest.fixture
def collective_session():
    s = TpuSession()
    s.enable_collective_shuffle(8)
    yield s
    s.disable_collective_shuffle()


def _multi_file(tmp_path, t: pa.Table, n_files: int):
    paths = []
    per = max(1, t.num_rows // n_files)
    for i in range(n_files):
        sl = t.slice(i * per, per if i < n_files - 1
                     else t.num_rows - i * per)
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(sl, p)
        paths.append(p)
    return paths


@pytest.mark.slow
def test_collective_groupby_through_session(collective_session, tmp_path):
    t = gen_table({"k": "smallint64", "v": "float64"}, 2000, seed=7)
    paths = _multi_file(tmp_path, t, 5)
    df = (collective_session.read_parquet(*paths)
          .group_by(col("k"))
          .agg((sum_(col("v")), "s"), (count(col("v")), "c"),
               (avg(col("v")), "a")))
    from spark_rapids_tpu.plan.planner import plan_query

    exec_, _ = plan_query(df._plan, collective_session.conf)
    tree = exec_.tree_string()
    assert "TpuCollectiveHashAggregateExec" in tree, tree
    assert "all_to_all" in tree
    assert_tpu_cpu_equal(df, approx_float=True)


@pytest.mark.slow
def test_collective_string_keys(collective_session):
    t = gen_table({"s": "string", "v": "int64"}, 600, seed=13)
    df = (collective_session.create_dataframe(t)
          .group_by(col("s")).agg((sum_(col("v")), "sv")))
    assert_tpu_cpu_equal(df)


def test_collective_fewer_partitions_than_devices(collective_session):
    t = pa.table({"k": pa.array([1, 2, 1], pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0], pa.float64())})
    df = (collective_session.create_dataframe(t)
          .group_by(col("k")).agg((sum_(col("v")), "s")))
    out = df.collect().to_pydict()
    assert dict(zip(out["k"], out["s"])) == {1: 4.0, 2: 2.0}


@pytest.mark.slow
def test_collective_composes_with_filter_project(collective_session,
                                                 tmp_path):
    from spark_rapids_tpu.exprs.base import lit

    t = gen_table({"k": "smallint64", "v": "float64", "w": "float64"},
                  1500, seed=23)
    paths = _multi_file(tmp_path, t, 4)
    df = (collective_session.read_parquet(*paths)
          .where(col("v") > lit(0.0))
          .select(col("k"), (col("v") * col("w")).alias("vw"))
          .group_by(col("k")).agg((sum_(col("vw")), "s")))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_local_transport_without_mesh_falls_back():
    """transport=collective with no active mesh degrades to local."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.shuffle.transport import (
        SHUFFLE_TRANSPORT,
        get_transport,
    )

    conf = get_conf()
    old = conf.get(SHUFFLE_TRANSPORT)
    conf.set(SHUFFLE_TRANSPORT.key, "collective")
    try:
        assert get_transport().kind == "local"
    finally:
        conf.set(SHUFFLE_TRANSPORT.key, old)


# ------------------------------------------------------------------ #
# Collective JOIN / SORT lowering (round 4: every exchange-bearing
# operator rides the fused all_to_all tier, not just aggregates)
# ------------------------------------------------------------------ #


@pytest.fixture
def no_broadcast():
    """Force the shuffled-join path (small test tables would otherwise
    take the broadcast strategy before the collective lowering)."""
    from spark_rapids_tpu.config import get_conf

    conf = get_conf()
    key = "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes"
    old = conf.get(key)
    conf.set(key, -1)
    yield
    conf.set(key, old)


def _join_tables(seed, n_left=900, n_right=300):
    lt = gen_table({"k": "smallint64", "lv": "float64"}, n_left, seed=seed)
    rt = gen_table({"k": "smallint64", "rv": "int64"}, n_right,
                   seed=seed + 1)
    return lt, rt


@pytest.mark.parametrize("how", ["inner", "left_outer", "left_semi",
                                 "left_anti"])
def test_collective_join_differential(collective_session, no_broadcast, how):
    lt, rt = _join_tables(31)
    ldf = collective_session.create_dataframe(lt)
    rdf = collective_session.create_dataframe(rt)
    df = ldf.join(rdf, on="k", how=how)
    from spark_rapids_tpu.plan.planner import plan_query

    exec_, _ = plan_query(df._plan, collective_session.conf)
    assert "TpuCollectiveHashJoinExec" in exec_.tree_string(), \
        exec_.tree_string()
    assert_tpu_cpu_equal(df, approx_float=True)


@pytest.mark.slow
def test_collective_join_multi_round(collective_session, no_broadcast, tmp_path):
    """Stream side larger than the round budget: bounded rounds, same
    answer (the streaming-shards discipline)."""
    from spark_rapids_tpu.config import get_conf

    lt, rt = _join_tables(37, n_left=4000, n_right=500)
    paths = _multi_file(tmp_path, lt, 6)
    ldf = collective_session.read_parquet(*paths)
    rdf = collective_session.create_dataframe(rt)
    df = ldf.join(rdf, on="k", how="inner")
    get_conf().set("spark.rapids.tpu.shuffle.collective.roundRows", 512)
    try:
        assert_tpu_cpu_equal(df, approx_float=True)
    finally:
        get_conf().set("spark.rapids.tpu.shuffle.collective.roundRows",
                       1 << 20)


def test_collective_join_string_keys(collective_session, no_broadcast):
    lt = gen_table({"s": "string", "lv": "int64"}, 500, seed=41)
    rt = gen_table({"s": "string", "rv": "int64"}, 200, seed=42)
    df = collective_session.create_dataframe(lt).join(
        collective_session.create_dataframe(rt), on="s", how="inner")
    assert_tpu_cpu_equal(df)


def test_collective_join_empty_build(collective_session, no_broadcast):
    lt, rt = _join_tables(43, n_left=100, n_right=0)
    df = collective_session.create_dataframe(lt).join(
        collective_session.create_dataframe(rt), on="k",
        how="left_outer")
    assert_tpu_cpu_equal(df, approx_float=True)


def test_collective_sort_differential(collective_session):
    t = gen_table({"k": "int64", "v": "float64"}, 1200, seed=51)
    df = collective_session.create_dataframe(t).order_by(col("k"),
                                                         col("v"))
    from spark_rapids_tpu.plan.planner import plan_query

    exec_, _ = plan_query(df._plan, collective_session.conf)
    assert "TpuCollectiveSortExec" in exec_.tree_string(), \
        exec_.tree_string()
    assert_tpu_cpu_equal(df, ignore_order=False, approx_float=True)


def test_collective_sort_desc_nulls(collective_session):
    t = gen_table({"k": "int64", "v": "float64"}, 800, seed=53)
    df = collective_session.create_dataframe(t).order_by(col("k"),
                                                         desc=True)
    assert_tpu_cpu_equal(df, ignore_order=False, approx_float=True)


@pytest.mark.slow
def test_collective_sort_multi_round(collective_session, tmp_path):
    from spark_rapids_tpu.config import get_conf

    t = gen_table({"k": "float64", "v": "int64"}, 5000, seed=57)
    paths = _multi_file(tmp_path, t, 5)
    df = collective_session.read_parquet(*paths).order_by(col("k"))
    get_conf().set("spark.rapids.tpu.shuffle.collective.roundRows", 600)
    try:
        assert_tpu_cpu_equal(df, ignore_order=False, approx_float=True)
    finally:
        get_conf().set("spark.rapids.tpu.shuffle.collective.roundRows",
                       1 << 20)


@pytest.mark.slow
def test_collective_agg_multi_round(collective_session, tmp_path):
    from spark_rapids_tpu.config import get_conf

    t = gen_table({"k": "smallint64", "v": "float64"}, 4000, seed=59)
    paths = _multi_file(tmp_path, t, 5)
    df = (collective_session.read_parquet(*paths)
          .group_by(col("k")).agg((sum_(col("v")), "s"),
                                  (count(col("v")), "c")))
    get_conf().set("spark.rapids.tpu.shuffle.collective.roundRows", 512)
    try:
        assert_tpu_cpu_equal(df, approx_float=True)
    finally:
        get_conf().set("spark.rapids.tpu.shuffle.collective.roundRows",
                       1 << 20)


def test_collective_execs_compose_per_partition(collective_session,
                                                no_broadcast):
    """Regression: collective execs report mesh-width num_partitions,
    so anything stacked above (sort, limit, another join) consumes
    them through execute_partition — that must serve per-shard
    output, not trip the single-partition assertion."""
    t = gen_table({"k": "smallint64", "v": "float64"}, 900, seed=61)
    df = (collective_session.create_dataframe(t)
          .group_by(col("k")).agg((sum_(col("v")), "s"))
          .order_by(col("k")))
    assert_tpu_cpu_equal(df, ignore_order=False, approx_float=True)

    rt = gen_table({"k": "smallint64", "rv": "int64"}, 300, seed=62)
    df2 = (collective_session.create_dataframe(t)
           .join(collective_session.create_dataframe(rt), on="k",
                 how="inner")
           .limit(7))
    out = df2.collect(engine="tpu")
    assert out.num_rows == 7
