"""Collective (tier-2) shuffle transport tests on the 8-virtual-CPU-device
mesh: the planner lowers grouped aggregates to the fused all_to_all SPMD
program and results match the CPU oracle (the RapidsShuffleTransport SPI
coverage analog, ref: RapidsShuffleClientSuite et al. — here the fabric
is XLA collectives, so correctness is tested end-to-end through the
session instead of against a mocked wire protocol)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.session import TpuSession, avg, col, count, sum_
from tests.differential import assert_tpu_cpu_equal, gen_table


@pytest.fixture
def collective_session():
    s = TpuSession()
    s.enable_collective_shuffle(8)
    yield s
    s.disable_collective_shuffle()


def _multi_file(tmp_path, t: pa.Table, n_files: int):
    paths = []
    per = max(1, t.num_rows // n_files)
    for i in range(n_files):
        sl = t.slice(i * per, per if i < n_files - 1
                     else t.num_rows - i * per)
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(sl, p)
        paths.append(p)
    return paths


@pytest.mark.slow
def test_collective_groupby_through_session(collective_session, tmp_path):
    t = gen_table({"k": "smallint64", "v": "float64"}, 2000, seed=7)
    paths = _multi_file(tmp_path, t, 5)
    df = (collective_session.read_parquet(*paths)
          .group_by(col("k"))
          .agg((sum_(col("v")), "s"), (count(col("v")), "c"),
               (avg(col("v")), "a")))
    from spark_rapids_tpu.plan.planner import plan_query

    exec_, _ = plan_query(df._plan, collective_session.conf)
    tree = exec_.tree_string()
    assert "TpuCollectiveHashAggregateExec" in tree, tree
    assert "all_to_all" in tree
    assert_tpu_cpu_equal(df, approx_float=True)


@pytest.mark.slow
def test_collective_string_keys(collective_session):
    t = gen_table({"s": "string", "v": "int64"}, 600, seed=13)
    df = (collective_session.create_dataframe(t)
          .group_by(col("s")).agg((sum_(col("v")), "sv")))
    assert_tpu_cpu_equal(df)


def test_collective_fewer_partitions_than_devices(collective_session):
    t = pa.table({"k": pa.array([1, 2, 1], pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0], pa.float64())})
    df = (collective_session.create_dataframe(t)
          .group_by(col("k")).agg((sum_(col("v")), "s")))
    out = df.collect().to_pydict()
    assert dict(zip(out["k"], out["s"])) == {1: 4.0, 2: 2.0}


@pytest.mark.slow
def test_collective_composes_with_filter_project(collective_session,
                                                 tmp_path):
    from spark_rapids_tpu.exprs.base import lit

    t = gen_table({"k": "smallint64", "v": "float64", "w": "float64"},
                  1500, seed=23)
    paths = _multi_file(tmp_path, t, 4)
    df = (collective_session.read_parquet(*paths)
          .where(col("v") > lit(0.0))
          .select(col("k"), (col("v") * col("w")).alias("vw"))
          .group_by(col("k")).agg((sum_(col("vw")), "s")))
    assert_tpu_cpu_equal(df, approx_float=True)


def test_local_transport_without_mesh_falls_back():
    """transport=collective with no active mesh degrades to local."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.shuffle.transport import (
        SHUFFLE_TRANSPORT,
        get_transport,
    )

    conf = get_conf()
    old = conf.get(SHUFFLE_TRANSPORT)
    conf.set(SHUFFLE_TRANSPORT.key, "collective")
    try:
        assert get_transport().kind == "local"
    finally:
        conf.set(SHUFFLE_TRANSPORT.key, old)
