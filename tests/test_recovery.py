"""Failure detection / recovery (SURVEY §5.3).

Deterministic lineage makes tasks re-runnable: a map task that fails
with a device/transient error re-executes and the query still answers
correctly; a failed attempt must leave no partial shuffle blocks
(atomic commit); a device lost for good degrades the query to the CPU
engine instead of failing it.
"""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.execs.retry import (
    CPU_FALLBACK_ON_DEVICE_ERROR,
    TASK_MAX_FAILURES,
    RETRY_BACKOFF_S,
    is_retryable,
    with_task_retries,
)
from spark_rapids_tpu.io.scan import ArrowSourceExec
from spark_rapids_tpu.session import TpuSession, col, sum_
from tests.differential import assert_tables_equal


class FakeDeviceOOM(RuntimeError):
    def __str__(self):
        return "RESOURCE_EXHAUSTED: out of memory allocating 1234 bytes"


@pytest.fixture(autouse=True)
def fast_backoff():
    conf = get_conf()
    old = conf.get(RETRY_BACKOFF_S)
    conf.set(RETRY_BACKOFF_S.key, 0.0)
    yield
    conf.set(RETRY_BACKOFF_S.key, old)


def test_is_retryable_classification():
    assert is_retryable(FakeDeviceOOM())
    assert is_retryable(MemoryError())
    assert is_retryable(RuntimeError("UNAVAILABLE: Socket closed"))
    assert not is_retryable(AssertionError("logic bug"))
    assert not is_retryable(RuntimeError("division by zero"))


def test_with_task_retries_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise FakeDeviceOOM()
        return "ok"

    assert with_task_retries(flaky) == "ok"
    assert len(calls) == 3


def test_with_task_retries_fails_fast_on_logic_error():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("user bug")

    with pytest.raises(ValueError):
        with_task_retries(broken)
    assert len(calls) == 1


def test_with_task_retries_exhausts():
    conf = get_conf()
    old = conf.get(TASK_MAX_FAILURES)
    conf.set(TASK_MAX_FAILURES.key, 2)
    calls = []
    try:
        with pytest.raises(FakeDeviceOOM):
            def always():
                calls.append(1)
                raise FakeDeviceOOM()
            with_task_retries(always)
        assert len(calls) == 2
    finally:
        conf.set(TASK_MAX_FAILURES.key, old)


class FlakyExec(TpuExec):
    """Wraps a child; each partition's FIRST attempt dies with a device
    error mid-stream (after yielding one batch), later attempts
    succeed — the retrying runner must discard the partial output."""

    def __init__(self, child, fail_attempts: int = 1):
        super().__init__(child)
        self.fail_attempts = fail_attempts
        self._attempts: dict[int, int] = {}
        self._lock = threading.Lock()

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        return "FlakyExec"

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def execute_partition(self, p: int):
        with self._lock:
            n = self._attempts.get(p, 0)
            self._attempts[p] = n + 1
        emitted = 0
        for b in self.children[0].execute_partition(p):
            yield b
            emitted += 1
            if n < self.fail_attempts and emitted >= 1:
                raise FakeDeviceOOM()

    def execute(self):
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)


def _table(n=4000, seed=23):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 16, n),
                     "v": rng.random(n)})


@pytest.mark.slow
def test_map_task_retry_no_duplicates():
    """A mid-stream map-task failure retries and the aggregate over the
    exchange is EXACT — duplicated partial writes would inflate it."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.exprs.aggregates import NamedAgg, Sum
    from spark_rapids_tpu.ops.partition import HashPartitioning
    from spark_rapids_tpu.plan.planner import collect_exec

    conf = get_conf()
    old = conf.get(BATCH_SIZE_ROWS)
    conf.set(BATCH_SIZE_ROWS.key, 500)
    try:
        t = _table()
        src = ArrowSourceExec(t)
        flaky = FlakyExec(src)
        keys = [B.BoundReference(0, T.LONG, False, "k")]
        ex = TpuShuffleExchangeExec(HashPartitioning(keys, 4), flaky)
        agg = TpuHashAggregateExec(
            keys, [NamedAgg(Sum(B.BoundReference(1, T.DOUBLE, False,
                                                 "v")), "s")], ex,
            mode="complete")
        got = collect_exec(agg)

        want = (TpuSession().create_dataframe(t)
                .group_by(col("k")).agg((sum_(col("v")), "s"))
                .collect(engine="cpu"))
        assert_tables_equal(got, want, n_keys=1, approx_float=True) \
            if _has_kw() else _fallback_compare(got, want)
    finally:
        conf.set(BATCH_SIZE_ROWS.key, old)


def _has_kw():
    import inspect

    from tests.differential import assert_tables_equal as f

    return "n_keys" in inspect.signature(f).parameters


def _fallback_compare(got, want):
    k = lambda tbl: sorted(  # noqa: E731
        (r["k"], round(r["s"], 9)) for r in tbl.to_pylist())
    assert k(got) == k(want)


@pytest.mark.slow
def test_failed_attempt_leaves_no_partial_blocks():
    """Exhausted retries must close every buffered handle (no leaked
    store entries, no partial shuffle blocks)."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.memory import get_store
    from spark_rapids_tpu.ops.partition import HashPartitioning

    conf = get_conf()
    old_bs = conf.get(BATCH_SIZE_ROWS)
    old_mf = conf.get(TASK_MAX_FAILURES)
    conf.set(BATCH_SIZE_ROWS.key, 500)
    conf.set(TASK_MAX_FAILURES.key, 2)
    try:
        store = get_store()
        before = set(store._entries)
        src = ArrowSourceExec(_table())
        flaky = FlakyExec(src, fail_attempts=99)  # never succeeds
        keys = [B.BoundReference(0, T.LONG, False, "k")]
        ex = TpuShuffleExchangeExec(HashPartitioning(keys, 4), flaky)
        with pytest.raises(FakeDeviceOOM):
            list(ex.execute())
        ex.close()
        leaked = set(store._entries) - before
        assert not leaked, f"{len(leaked)} leaked buffers"
    finally:
        conf.set(BATCH_SIZE_ROWS.key, old_bs)
        conf.set(TASK_MAX_FAILURES.key, old_mf)


# ------------------------------------------------------------------ #
# batch-granular OOM split-and-retry (ISSUE 6 acceptance)
# ------------------------------------------------------------------ #


@pytest.fixture
def disarm_faults():
    from spark_rapids_tpu.robustness import faults

    yield faults
    faults.disarm()


def test_bisect_batch_halves_rows_and_strings():
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.execs.retry import bisect_batch

    schema = T.Schema([T.Field("x", T.LONG), T.Field("s", T.STRING)])
    vals = list(range(1000))
    strs = [f"s{i}" for i in vals]
    b = ColumnarBatch.from_numpy(
        {"x": np.asarray(vals), "s": np.asarray(strs, object)}, schema)
    first, second = bisect_batch(b)
    assert first.concrete_num_rows() == 500
    assert second.concrete_num_rows() == 500
    got = first.to_pydict()
    assert got["x"] == vals[:500] and got["s"] == strs[:500]
    got2 = second.to_pydict()
    assert got2["x"] == vals[500:] and got2["s"] == strs[500:]


def test_with_split_retry_ladder_rungs(disarm_faults):
    """Rung order: spill+retry at full size first; a second failure
    bisects; sub-batches recurse; the split counter ticks."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.execs import retry as R

    schema = T.Schema([T.Field("x", T.LONG)])
    b = ColumnarBatch.from_numpy(
        {"x": np.arange(4096, dtype=np.int64)}, schema)
    conf = get_conf()
    conf.set(R.SPLIT_MIN_ROWS.key, 16)
    seen = []
    fails = {"n": 2}  # first two attempts die -> spill rung, then split

    def run(batch):
        if fails["n"]:
            fails["n"] -= 1
            raise FakeDeviceOOM()
        seen.append(batch.concrete_num_rows())
        yield batch

    R.reset_retry_stats()
    out = list(R.with_split_retry(run, b, desc="t"))
    # the split emits the two 2048-row halves
    assert seen == [2048, 2048] and len(out) == 2
    st = R.retry_stats()
    assert st["spill_retries"] == 1 and st["splits"] == 1


def test_with_split_retry_floor_escalates():
    """At the min-rows floor the ladder re-raises instead of splitting
    (whole-task retry / CPU fallback take over)."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.execs import retry as R

    schema = T.Schema([T.Field("x", T.LONG)])
    b = ColumnarBatch.from_numpy(
        {"x": np.arange(64, dtype=np.int64)}, schema)
    conf = get_conf()
    conf.set(R.SPLIT_MIN_ROWS.key, 1024)  # 64 rows is under the floor

    def always(batch):
        raise FakeDeviceOOM()
        yield  # pragma: no cover

    with pytest.raises(FakeDeviceOOM):
        list(R.with_split_retry(always, b, desc="t"))


def test_with_split_retry_never_duplicates_streamed_output():
    """Once a chunk streamed downstream, a re-run would duplicate rows:
    the ladder must escalate instead of retrying."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.execs import retry as R

    schema = T.Schema([T.Field("x", T.LONG)])
    b = ColumnarBatch.from_numpy(
        {"x": np.arange(256, dtype=np.int64)}, schema)

    def yields_then_dies(batch):
        yield batch
        raise FakeDeviceOOM()

    got = []
    with pytest.raises(FakeDeviceOOM):
        for out in R.with_split_retry(yields_then_dies, b, desc="t"):
            got.append(out)
    assert len(got) == 1  # the one real chunk, never re-emitted


def test_run_with_oom_retry_restartable_closure():
    from spark_rapids_tpu.execs import retry as R

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise FakeDeviceOOM()
        return "ok"

    assert R.run_with_oom_retry(flaky, desc="t") == "ok"
    with pytest.raises(ValueError):
        R.run_with_oom_retry(lambda: (_ for _ in ()).throw(
            ValueError("logic")), desc="t")


def test_classify_and_new_markers():
    from spark_rapids_tpu.execs.retry import classify

    assert classify(RuntimeError("DEADLINE_EXCEEDED: rpc")) \
        == "retryable"
    assert classify(RuntimeError("connection reset by peer")) \
        == "retryable"
    assert classify(RuntimeError("[Errno 104] ECONNRESET")) \
        == "retryable"
    assert classify(ValueError("user bug")) == "fatal"


def _split_acceptance(df, want, faults, spec, min_split=32):
    """Run df under an injected mid-stream RESOURCE_EXHAUSTED schedule:
    must complete via batch bisection — split counter > 0, zero CPU
    fallbacks — with speculation and pipelining at their (enabled)
    defaults."""
    import warnings

    from spark_rapids_tpu.execs import retry as R
    from spark_rapids_tpu.parallel.pipeline import stage_depth
    from spark_rapids_tpu.parallel.speculation import speculation_enabled

    assert stage_depth() > 0 and speculation_enabled()
    conf = get_conf()
    conf.set(R.SPLIT_MIN_ROWS.key, min_split)
    faults.install(spec, forced=True)
    R.reset_retry_stats()
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            got = df.collect(engine="tpu")
    finally:
        faults.disarm()
    st = R.retry_stats()
    assert st["splits"] > 0, st
    assert st["cpu_fallbacks"] == 0, st
    k = lambda tbl: sorted(  # noqa: E731
        tuple(round(v, 9) if isinstance(v, float) else v for v in row)
        for row in zip(*tbl.to_pydict().values()))
    assert k(got) == k(want)


def test_join_split_retry_acceptance(disarm_faults):
    """THE split acceptance: a join stream hit with RESOURCE_EXHAUSTED
    mid-stream (twice for the same batch, defeating the spill rung)
    completes via bisection with speculation + pipelining on."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS

    conf = get_conf()
    conf.set(BATCH_SIZE_ROWS.key, 500)
    rng = np.random.default_rng(31)
    facts = pa.table({"k": rng.integers(0, 64, 4000),
                      "v": rng.random(4000)})
    dims = pa.table({"k2": np.arange(64), "name": np.arange(64) * 7})
    s = TpuSession()
    df = (s.create_dataframe(facts)
          .join(s.create_dataframe(dims), how="inner",
                left_on=[col("k")], right_on=[col("k2")]))
    want = df.collect(engine="cpu")
    _split_acceptance(df, want, disarm_faults,
                      "exec.batch:nth=3,times=2")


def test_aggregate_split_retry_acceptance(disarm_faults):
    """Same ladder through the hash aggregate's update stream (driven
    as one exec so the fault schedule's call numbering is sequential —
    in a planned query, concurrent guarded loops each absorb injected
    faults at their own spill rung, which is also correct but does not
    pin the split rung)."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.execs import retry as R
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.exprs.aggregates import NamedAgg, Sum
    from spark_rapids_tpu.plan.planner import collect_exec

    conf = get_conf()
    conf.set(BATCH_SIZE_ROWS.key, 500)
    conf.set(R.SPLIT_MIN_ROWS.key, 32)
    rng = np.random.default_rng(33)
    t = pa.table({"k": rng.integers(0, 16, 4000),
                  "v": rng.random(4000)})
    s = TpuSession()
    want = (s.create_dataframe(t).group_by(col("k"))
            .agg((sum_(col("v")), "s")).collect(engine="cpu"))
    keys = [B.BoundReference(0, T.LONG, False, "k")]
    agg = TpuHashAggregateExec(
        keys, [NamedAgg(Sum(B.BoundReference(1, T.DOUBLE, False, "v")),
                        "s")],
        ArrowSourceExec(t), mode="complete")
    disarm_faults.install("exec.batch:nth=3,times=2", forced=True)
    R.reset_retry_stats()
    try:
        got = collect_exec(agg)
    finally:
        disarm_faults.disarm()
    st = R.retry_stats()
    assert st["splits"] > 0, st
    assert st["cpu_fallbacks"] == 0 and st["task_retries"] == 0, st
    k = lambda tbl: sorted(  # noqa: E731
        (r["k"], round(r["s"], 9)) for r in tbl.to_pylist())
    assert k(got) == k(want)


def test_exchange_map_split_retry(disarm_faults):
    """The exchange map task bisects too: injected OOM mid-map-stage
    splits the input batch into more (correct) reduce slices instead
    of burning a whole-task retry."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.execs import retry as R
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.ops.partition import HashPartitioning
    from spark_rapids_tpu.plan.planner import collect_exec

    conf = get_conf()
    conf.set(BATCH_SIZE_ROWS.key, 500)
    conf.set(R.SPLIT_MIN_ROWS.key, 32)
    # one map thread: concurrent map tasks interleave the fault
    # schedule's call numbering, which makes WHERE the two consecutive
    # failures land nondeterministic (each lands in a different unit's
    # spill rung — recovered, but no split to assert on)
    conf.set("spark.rapids.tpu.sql.taskThreads", 1)
    t = _table()
    src = ArrowSourceExec(t)
    keys = [B.BoundReference(0, T.LONG, False, "k")]
    ex = TpuShuffleExchangeExec(HashPartitioning(keys, 4), src)
    disarm_faults.install("exec.batch:nth=3,times=2", forced=True)
    R.reset_retry_stats()
    try:
        got = collect_exec(ex)
    finally:
        disarm_faults.disarm()
    st = R.retry_stats()
    assert st["splits"] > 0 and st["task_retries"] == 0, st
    assert got.num_rows == t.num_rows
    assert sorted(got.column("k").to_pylist()) \
        == sorted(t.column("k").to_pylist())


def test_query_level_cpu_fallback(monkeypatch):
    """Device errors surviving retries degrade collect() to the CPU
    engine (with a warning) instead of failing the query."""
    import spark_rapids_tpu.plan.planner as planner_mod

    session = TpuSession()
    df = (session.create_dataframe(_table())
          .group_by(col("k")).agg((sum_(col("v")), "s")))
    want = df.collect(engine="cpu")

    def boom(exec_):
        raise FakeDeviceOOM()

    monkeypatch.setattr("spark_rapids_tpu.session.collect_exec", boom)
    with pytest.warns(RuntimeWarning, match="CPU engine"):
        got = df.collect(engine="tpu")
    k = lambda tbl: sorted(  # noqa: E731
        (r["k"], round(r["s"], 9)) for r in tbl.to_pylist())
    assert k(got) == k(want)

    conf = get_conf()
    old = conf.get(CPU_FALLBACK_ON_DEVICE_ERROR)
    conf.set(CPU_FALLBACK_ON_DEVICE_ERROR.key, False)
    try:
        with pytest.raises(FakeDeviceOOM):
            df.collect(engine="tpu")
    finally:
        conf.set(CPU_FALLBACK_ON_DEVICE_ERROR.key, old)
