"""Failure detection / recovery (SURVEY §5.3).

Deterministic lineage makes tasks re-runnable: a map task that fails
with a device/transient error re-executes and the query still answers
correctly; a failed attempt must leave no partial shuffle blocks
(atomic commit); a device lost for good degrades the query to the CPU
engine instead of failing it.
"""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.execs.retry import (
    CPU_FALLBACK_ON_DEVICE_ERROR,
    TASK_MAX_FAILURES,
    RETRY_BACKOFF_S,
    is_retryable,
    with_task_retries,
)
from spark_rapids_tpu.io.scan import ArrowSourceExec
from spark_rapids_tpu.session import TpuSession, col, sum_
from tests.differential import assert_tables_equal


class FakeDeviceOOM(RuntimeError):
    def __str__(self):
        return "RESOURCE_EXHAUSTED: out of memory allocating 1234 bytes"


@pytest.fixture(autouse=True)
def fast_backoff():
    conf = get_conf()
    old = conf.get(RETRY_BACKOFF_S)
    conf.set(RETRY_BACKOFF_S.key, 0.0)
    yield
    conf.set(RETRY_BACKOFF_S.key, old)


def test_is_retryable_classification():
    assert is_retryable(FakeDeviceOOM())
    assert is_retryable(MemoryError())
    assert is_retryable(RuntimeError("UNAVAILABLE: Socket closed"))
    assert not is_retryable(AssertionError("logic bug"))
    assert not is_retryable(RuntimeError("division by zero"))


def test_with_task_retries_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise FakeDeviceOOM()
        return "ok"

    assert with_task_retries(flaky) == "ok"
    assert len(calls) == 3


def test_with_task_retries_fails_fast_on_logic_error():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("user bug")

    with pytest.raises(ValueError):
        with_task_retries(broken)
    assert len(calls) == 1


def test_with_task_retries_exhausts():
    conf = get_conf()
    old = conf.get(TASK_MAX_FAILURES)
    conf.set(TASK_MAX_FAILURES.key, 2)
    calls = []
    try:
        with pytest.raises(FakeDeviceOOM):
            def always():
                calls.append(1)
                raise FakeDeviceOOM()
            with_task_retries(always)
        assert len(calls) == 2
    finally:
        conf.set(TASK_MAX_FAILURES.key, old)


class FlakyExec(TpuExec):
    """Wraps a child; each partition's FIRST attempt dies with a device
    error mid-stream (after yielding one batch), later attempts
    succeed — the retrying runner must discard the partial output."""

    def __init__(self, child, fail_attempts: int = 1):
        super().__init__(child)
        self.fail_attempts = fail_attempts
        self._attempts: dict[int, int] = {}
        self._lock = threading.Lock()

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        return "FlakyExec"

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def execute_partition(self, p: int):
        with self._lock:
            n = self._attempts.get(p, 0)
            self._attempts[p] = n + 1
        emitted = 0
        for b in self.children[0].execute_partition(p):
            yield b
            emitted += 1
            if n < self.fail_attempts and emitted >= 1:
                raise FakeDeviceOOM()

    def execute(self):
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)


def _table(n=4000, seed=23):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 16, n),
                     "v": rng.random(n)})


@pytest.mark.slow
def test_map_task_retry_no_duplicates():
    """A mid-stream map-task failure retries and the aggregate over the
    exchange is EXACT — duplicated partial writes would inflate it."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.exprs.aggregates import NamedAgg, Sum
    from spark_rapids_tpu.ops.partition import HashPartitioning
    from spark_rapids_tpu.plan.planner import collect_exec

    conf = get_conf()
    old = conf.get(BATCH_SIZE_ROWS)
    conf.set(BATCH_SIZE_ROWS.key, 500)
    try:
        t = _table()
        src = ArrowSourceExec(t)
        flaky = FlakyExec(src)
        keys = [B.BoundReference(0, T.LONG, False, "k")]
        ex = TpuShuffleExchangeExec(HashPartitioning(keys, 4), flaky)
        agg = TpuHashAggregateExec(
            keys, [NamedAgg(Sum(B.BoundReference(1, T.DOUBLE, False,
                                                 "v")), "s")], ex,
            mode="complete")
        got = collect_exec(agg)

        want = (TpuSession().create_dataframe(t)
                .group_by(col("k")).agg((sum_(col("v")), "s"))
                .collect(engine="cpu"))
        assert_tables_equal(got, want, n_keys=1, approx_float=True) \
            if _has_kw() else _fallback_compare(got, want)
    finally:
        conf.set(BATCH_SIZE_ROWS.key, old)


def _has_kw():
    import inspect

    from tests.differential import assert_tables_equal as f

    return "n_keys" in inspect.signature(f).parameters


def _fallback_compare(got, want):
    k = lambda tbl: sorted(  # noqa: E731
        (r["k"], round(r["s"], 9)) for r in tbl.to_pylist())
    assert k(got) == k(want)


@pytest.mark.slow
def test_failed_attempt_leaves_no_partial_blocks():
    """Exhausted retries must close every buffered handle (no leaked
    store entries, no partial shuffle blocks)."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.memory import get_store
    from spark_rapids_tpu.ops.partition import HashPartitioning

    conf = get_conf()
    old_bs = conf.get(BATCH_SIZE_ROWS)
    old_mf = conf.get(TASK_MAX_FAILURES)
    conf.set(BATCH_SIZE_ROWS.key, 500)
    conf.set(TASK_MAX_FAILURES.key, 2)
    try:
        store = get_store()
        before = set(store._entries)
        src = ArrowSourceExec(_table())
        flaky = FlakyExec(src, fail_attempts=99)  # never succeeds
        keys = [B.BoundReference(0, T.LONG, False, "k")]
        ex = TpuShuffleExchangeExec(HashPartitioning(keys, 4), flaky)
        with pytest.raises(FakeDeviceOOM):
            list(ex.execute())
        ex.close()
        leaked = set(store._entries) - before
        assert not leaked, f"{len(leaked)} leaked buffers"
    finally:
        conf.set(BATCH_SIZE_ROWS.key, old_bs)
        conf.set(TASK_MAX_FAILURES.key, old_mf)


def test_query_level_cpu_fallback(monkeypatch):
    """Device errors surviving retries degrade collect() to the CPU
    engine (with a warning) instead of failing the query."""
    import spark_rapids_tpu.plan.planner as planner_mod

    session = TpuSession()
    df = (session.create_dataframe(_table())
          .group_by(col("k")).agg((sum_(col("v")), "s")))
    want = df.collect(engine="cpu")

    def boom(exec_):
        raise FakeDeviceOOM()

    monkeypatch.setattr("spark_rapids_tpu.session.collect_exec", boom)
    with pytest.warns(RuntimeWarning, match="CPU engine"):
        got = df.collect(engine="tpu")
    k = lambda tbl: sorted(  # noqa: E731
        (r["k"], round(r["s"], 9)) for r in tbl.to_pylist())
    assert k(got) == k(want)

    conf = get_conf()
    old = conf.get(CPU_FALLBACK_ON_DEVICE_ERROR)
    conf.set(CPU_FALLBACK_ON_DEVICE_ERROR.key, False)
    try:
        with pytest.raises(FakeDeviceOOM):
            df.collect(engine="tpu")
    finally:
        conf.set(CPU_FALLBACK_ON_DEVICE_ERROR.key, old)
