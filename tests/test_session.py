"""Session/DataFrame + planner tests: TPU-vs-CPU differential runs,
fallback behavior, explain output (mirrors the reference's pytest
integration tier + StringFallbackSuite-style fallback assertions)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import TpuConf, set_conf
from spark_rapids_tpu.session import (
    DataFrame,
    TpuSession,
    avg,
    col,
    count,
    count_star,
    max_,
    min_,
    sum_,
)
from spark_rapids_tpu.exprs.base import lit

from differential import assert_tpu_cpu_equal, gen_table


@pytest.fixture
def spark():
    return TpuSession()


def test_select_where_differential(spark):
    t = gen_table({"a": "int64", "b": "int64", "x": "float64"}, 500, seed=1)
    df = spark.create_dataframe(t)
    q = df.where((col("a") > lit(0)) & col("x").is_not_null()) \
          .select(col("a"), (col("a") + col("b")).alias("ab"),
                  (col("x") / lit(2.0)).alias("half"))
    assert_tpu_cpu_equal(q)


def test_groupby_differential(spark):
    t = gen_table({"k": "smallint64", "v": "int64", "x": "float64"},
                  800, seed=2)
    df = spark.create_dataframe(t)
    q = df.group_by("k").agg((sum_("v"), "s"), (count("v"), "c"),
                             (min_("v"), "mn"), (max_("v"), "mx"),
                             (count_star(), "n"))
    assert_tpu_cpu_equal(q)


def test_avg_differential_approx(spark):
    t = gen_table({"k": "smallint64", "v": "int64"}, 400, seed=3)
    q = spark.create_dataframe(t).group_by("k").agg((avg("v"), "a"))
    assert_tpu_cpu_equal(q, approx_float=True)


@pytest.mark.slow
def test_join_differential(spark):
    lt = gen_table({"k": "smallint64", "lv": "int64"}, 300, seed=4)
    rt = gen_table({"k": "smallint64", "rv": "string"}, 60, seed=5)
    left = spark.create_dataframe(lt)
    right = spark.create_dataframe(
        rt.rename_columns(["rk", "rv"]))
    for how in ("inner", "left_outer", "right_outer", "full_outer",
                "left_semi", "left_anti"):
        q = left.join(right, left_on=["k"], right_on=["rk"], how=how)
        assert_tpu_cpu_equal(q)


def test_sort_limit_differential(spark):
    t = gen_table({"a": "int64", "x": "float64"}, 300, seed=6)
    df = spark.create_dataframe(t)
    # total order (tie-break on both columns) so limit is deterministic
    q = df.order_by("a", "x").limit(17)
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_union_differential(spark):
    t1 = gen_table({"a": "int64", "s": "string"}, 100, seed=7)
    t2 = gen_table({"a": "int64", "s": "string"}, 80, seed=8)
    q = spark.create_dataframe(t1).union(spark.create_dataframe(t2))
    assert_tpu_cpu_equal(q)


def test_range(spark):
    q = spark.range(0, 1000, 7).select(
        col("id"), (col("id") * lit(2)).alias("dbl"))
    assert_tpu_cpu_equal(q)


def test_parquet_scan(spark, tmp_path):
    import pyarrow.parquet as pq

    t = gen_table({"a": "int64", "s": "string", "x": "float64"}, 400,
                  seed=9)
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path, row_group_size=100)
    q = spark.read_parquet(path).where(col("a").is_not_null())
    assert_tpu_cpu_equal(q)


def test_csv_scan(spark, tmp_path):
    import pyarrow.csv as pacsv

    t = pa.table({"a": pa.array([1, 2, 3], pa.int64()),
                  "b": pa.array([1.5, 2.5, 3.5])})
    path = str(tmp_path / "t.csv")
    pacsv.write_csv(t, path)
    q = spark.read_csv(path).select(
        (col("a") + lit(1)).alias("a1"), col("b"))
    assert_tpu_cpu_equal(q)


def test_explain_marks_everything_on_tpu(spark):
    df = spark.create_dataframe({"a": [1, 2, 3]})
    q = df.where(col("a") > lit(1)).select((col("a") * lit(2)).alias("d"))
    ex = q.explain()
    assert "!" not in ex
    assert ex.count("*") == 3  # project, filter, relation


def test_fallback_on_disabled_exec():
    conf = TpuConf()
    conf.set("spark.rapids.tpu.sql.exec.Filter", False)
    spark = TpuSession(conf)
    t = gen_table({"a": "int64"}, 100, seed=10)
    q = spark.create_dataframe(t).where(col("a") > lit(0)) \
             .select((col("a") + lit(1)).alias("a1"))
    ex = q.explain()
    assert "! Filter" in ex
    assert "disabled by spark.rapids.tpu.sql.exec.Filter" in ex
    assert "* Project" in ex
    # and the fallback still computes the right answer
    assert_tpu_cpu_equal(q)


def test_fallback_on_disabled_expression():
    conf = TpuConf()
    conf.set("spark.rapids.tpu.sql.expression.Divide", False)
    spark = TpuSession(conf)
    t = gen_table({"a": "int64", "b": "int64"}, 60, seed=11)
    q = spark.create_dataframe(t).select(
        (col("a") / col("b")).alias("q"))
    ex = q.explain()
    assert "expression Divide disabled" in ex
    assert_tpu_cpu_equal(q, approx_float=True)


def test_tpch_q6_shape(spark):
    """The BASELINE.md config-1 slice: scan+filter+project+sum."""
    n = 2000
    rng = np.random.default_rng(42)
    t = pa.table({
        "l_quantity": pa.array(
            rng.integers(1, 51, n).astype(np.float64)),
        "l_extendedprice": pa.array(rng.uniform(900, 105000, n)),
        "l_discount": pa.array(
            rng.integers(0, 11, n).astype(np.float64) / 100.0),
        "l_shipdate": pa.array(
            rng.integers(8000, 11000, n).astype(np.int32)),
    })
    df = spark.create_dataframe(t)
    q = df.where((col("l_shipdate") >= lit(8766))
                 & (col("l_shipdate") < lit(9131))
                 & (col("l_discount") >= lit(0.05))
                 & (col("l_discount") <= lit(0.07))
                 & (col("l_quantity") < lit(24.0))) \
          .select((col("l_extendedprice") * col("l_discount"))
                  .alias("rev")) \
          .agg((sum_("rev"), "revenue"))
    assert_tpu_cpu_equal(q, approx_float=True)


def test_tpch_q1_shape(spark):
    """BASELINE.md config-2 slice: multi-aggregate group-by."""
    n = 3000
    rng = np.random.default_rng(43)
    t = pa.table({
        "l_returnflag": pa.array(
            [["A", "N", "R"][i] for i in rng.integers(0, 3, n)]),
        "l_linestatus": pa.array(
            [["F", "O"][i] for i in rng.integers(0, 2, n)]),
        "l_quantity": pa.array(rng.integers(1, 51, n).astype(np.float64)),
        "l_extendedprice": pa.array(rng.uniform(900, 105000, n)),
        "l_discount": pa.array(
            rng.integers(0, 11, n).astype(np.float64) / 100.0),
        "l_tax": pa.array(rng.integers(0, 9, n).astype(np.float64) / 100.0),
    })
    df = spark.create_dataframe(t)
    disc_price = (col("l_extendedprice")
                  * (lit(1.0) - col("l_discount"))).alias("disc_price")
    charge = (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
              * (lit(1.0) + col("l_tax"))).alias("charge")
    q = (df.select(col("l_returnflag"), col("l_linestatus"),
                   col("l_quantity"), col("l_extendedprice"),
                   col("l_discount"), disc_price, charge)
           .group_by("l_returnflag", "l_linestatus")
           .agg((sum_("l_quantity"), "sum_qty"),
                (sum_("l_extendedprice"), "sum_base_price"),
                (sum_("disc_price"), "sum_disc_price"),
                (sum_("charge"), "sum_charge"),
                (avg("l_quantity"), "avg_qty"),
                (avg("l_extendedprice"), "avg_price"),
                (avg("l_discount"), "avg_disc"),
                (count_star(), "count_order"))
           .order_by("l_returnflag", "l_linestatus"))
    assert_tpu_cpu_equal(q, ignore_order=False, approx_float=True)


def test_to_device_arrays_zero_copy_into_jax():
    """ColumnarRdd-analog export (ref: ColumnarRdd.scala): SQL results
    stay on device and feed jax code directly."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.exprs.base import lit

    session = TpuSession()
    rng = np.random.default_rng(4)
    t = pa.table({"x": rng.random(500), "y": rng.random(500)})
    df = (session.create_dataframe(t)
          .where(col("x") > lit(0.5))
          .select(col("x"), (col("x") * col("y")).alias("xy")))
    batches = df.to_device_arrays()
    assert batches and all(isinstance(b["x"], jax.Array)
                           for b in batches)
    # consume straight from HBM: a jitted reduction over the batches
    total = sum(float(jnp.sum(jnp.where(b["xy__valid"], b["xy"], 0.0)))
                for b in batches)
    x, y = np.asarray(t["x"]), np.asarray(t["y"])
    want = float((x[x > 0.5] * y[x > 0.5]).sum())
    assert abs(total - want) < 1e-6 * max(1.0, abs(want))
