"""Test harness config: force JAX onto CPU with 8 virtual devices so the
multi-chip sharding paths are exercised without TPU hardware (the driver
separately dry-runs the multi-chip path; bench.py uses the real chip).

Note: this environment's sitecustomize registers a remote TPU PJRT plugin
and *forcibly* sets jax_platforms="axon,cpu" via jax.config.update, which
overrides the JAX_PLATFORMS env var.  We must win the override back with
another config.update before any backend initializes, otherwise every test
run rides a fragile remote-TPU tunnel.
"""

from spark_rapids_tpu.platform import pin_cpu_platform

pin_cpu_platform(8)

# Persistent XLA compilation cache: the suite's wall clock is dominated
# by per-test jit compiles of the same operator programs; caching them
# on disk makes repeat runs (the habitual pre-commit `-m "not slow"`
# tier) skip recompilation entirely.
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  "/tmp/spark_rapids_tpu_jitcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolate_conf():
    """Snapshot/restore the thread-local conf so a test's conf.set()
    can't leak into later tests (sessions share the thread-local)."""
    from spark_rapids_tpu.config import get_conf, set_conf

    conf = get_conf()
    saved = dict(conf._values)
    yield
    conf._values.clear()
    conf._values.update(saved)
    set_conf(conf)  # undo any set_conf() swap too
