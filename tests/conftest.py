"""Test harness config: force JAX onto CPU with 8 virtual devices so the
multi-chip sharding paths are exercised without TPU hardware (the driver
separately dry-runs the multi-chip path; bench.py uses the real chip).

Note: this environment's sitecustomize registers a remote TPU PJRT plugin
and *forcibly* sets jax_platforms="axon,cpu" via jax.config.update, which
overrides the JAX_PLATFORMS env var.  We must win the override back with
another config.update before any backend initializes, otherwise every test
run rides a fragile remote-TPU tunnel.
"""

from spark_rapids_tpu.platform import pin_cpu_platform

pin_cpu_platform(8)

# Persistent XLA compilation cache: the suite's wall clock is dominated
# by per-test jit compiles of the same operator programs; caching them
# on disk makes repeat runs (the habitual pre-commit `-m "not slow"`
# tier) skip recompilation entirely.
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  "/tmp/spark_rapids_tpu_jitcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import pytest  # noqa: E402


@pytest.fixture
def leak_check():
    """Reusable process-residency leak gauge (docs/robustness.md):
    snapshots semaphore permits in use, BufferStore bytes per tier,
    live prefetch stage threads and the in-flight shared-scan count at
    setup, and asserts at teardown that every gauge returned EXACTLY
    to baseline (with a bounded settle wait for stage threads still
    unwinding).  Yields the snapshot callable so tests can also diff
    mid-test.  Suite-wide usage: test_serving.py, test_work_share.py
    and test_cancellation.py wrap it in a module-level autouse
    fixture, turning "no leaks" from a one-off assert into coverage
    every test in those modules carries."""
    import time as _time

    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.memory.store import peek_store
    from spark_rapids_tpu.parallel.pipeline import live_stage_threads
    from spark_rapids_tpu.serving.work_share import SCAN_REGISTRY

    def snap() -> dict:
        store = peek_store()
        ss = store.spill_stats() if store is not None else {
            "device_used": 0, "host_used": 0, "disk_used": 0}
        return {
            "semaphore_in_use": TpuSemaphore.usage_now()["in_use"],
            "store_device_bytes": ss["device_used"],
            "store_host_bytes": ss["host_used"],
            "store_disk_bytes": ss["disk_used"],
            "stage_threads": live_stage_threads(),
            "scan_inflight": SCAN_REGISTRY.inflight(),
        }

    before = snap()
    yield snap
    deadline = _time.monotonic() + 5.0
    after = snap()
    while after != before and _time.monotonic() < deadline:
        _time.sleep(0.05)  # stage threads may still be joining
        after = snap()
    assert after == before, (
        f"process residency leaked: before={before} after={after}")


#: tier-1 modules that run with the runtime lock-order tracker ARMED:
#: the concurrency-heavy suites double as a continuous deadlock hunt —
#: any lock-order cycle the tests' interleavings ever exhibit raises
#: LockCycleError right there instead of hanging a future soak
#: (docs/concurrency.md)
_LOCK_TRACKED_MODULES = frozenset((
    "test_serving",
    "test_cancellation",
    "test_work_share",
    "test_chaos",
))


@pytest.fixture(autouse=True)
def _arm_lock_tracker(request):
    """Force-arm the lock tracker for the modules above (forced
    installs survive sync_conf, so in-test sessions carrying the
    default conf cannot disarm it mid-test); verify no cycle formed."""
    if request.module.__name__ not in _LOCK_TRACKED_MODULES:
        yield
        return
    from spark_rapids_tpu.robustness import lock_tracker

    lock_tracker.install(forced=True)
    yield
    cycles = lock_tracker.cycle_count()
    graph = lock_tracker.order_graph()
    lock_tracker.disarm()
    assert cycles == 0, (
        f"lock-order cycle detected during test: graph={graph}")


@pytest.fixture(autouse=True)
def _isolate_conf():
    """Snapshot/restore the thread-local conf so a test's conf.set()
    can't leak into later tests (sessions share the thread-local)."""
    from spark_rapids_tpu.config import get_conf, set_conf

    conf = get_conf()
    saved = dict(conf._values)
    yield
    conf._values.clear()
    conf._values.update(saved)
    set_conf(conf)  # undo any set_conf() swap too
