"""Device-utilization ledger + live telemetry sampler (trace/ledger.py,
trace/telemetry.py; docs/device_ledger.md).

The acceptance surface:
- an enabled ledger attributes >=1 program per query with nonzero
  cost-model bytes AND dispatch count, and the attributed device time
  never exceeds the query wall (run_ledger_smoke, wired into tier-1
  here and into the bench_smoke CLI);
- the per-query `programs` event-log section round-trips through
  tools/history EQUAL to the in-process snapshot;
- both features OFF are bit-identical and effectively free: the
  dispatch wrapper never touches ledger state, no sampler thread
  exists;
- the telemetry sampler starts/stops leak-free under concurrent
  sessions and its counter samples export as Chrome-trace ph="C"
  counter tracks (Perfetto counter tracks).
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import trace
from spark_rapids_tpu.config import TpuConf, get_conf
from spark_rapids_tpu.session import TpuSession, col, sum_
from spark_rapids_tpu.trace import ledger, telemetry

LEDGER_KEY = "spark.rapids.tpu.trace.ledger.enabled"
TELEMETRY_KEY = "spark.rapids.tpu.telemetry.enabled"


@pytest.fixture(autouse=True)
def _clean_ledger_and_sampler():
    """The ledger and the sampler are process-global: every test
    starts and ends with both disabled and empty."""
    ledger.disable()
    ledger.reset_stats()
    telemetry.SAMPLER.stop()
    yield
    ledger.disable()
    ledger.reset_stats()
    telemetry.SAMPLER.stop()
    trace.disable()
    trace.clear()


def _table(n: int = 4096, seed: int = 0x1ED) -> pa.Table:
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 32, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _agg(session: TpuSession, t: pa.Table):
    return (session.create_dataframe(t)
            .group_by(col("k"))
            .agg((sum_(col("v")), "sv"))
            .order_by(col("k")))


# -- attribution core --------------------------------------------------- #

def test_ledger_attributes_programs_with_cost_model():
    """THE core contract: an enabled ledger records every dispatched
    program with invocation count, settled device time, the XLA cost
    model (flops/bytes) and an op label for per-operator rollups."""
    ledger.enable()
    session = TpuSession()
    _agg(session, _table()).collect(engine="tpu")
    assert ledger.LEDGER.flush(timeout=30.0)
    snap = ledger.snapshot()
    assert snap, "no programs recorded"
    assert any(p["dispatches"] > 0 and p["bytes_accessed"] > 0
               for p in snap.values()), snap
    assert any(p["device_ms"] > 0 for p in snap.values()), snap
    ops = {p["op"] for p in snap.values() if p["op"]}
    assert "TpuHashAggregateExec" in ops, ops


def test_ledger_smoke():
    """The CI smoke (also a bench_smoke CLI stage): >=1 program with
    nonzero cost bytes + dispatches, attributed device time within the
    query wall."""
    from spark_rapids_tpu.tools.bench_smoke import run_ledger_smoke

    out = run_ledger_smoke()
    assert out["ledger_programs"] >= 1
    assert out["ledger_dispatches"] >= 1


def test_ledger_delta_isolates_query_window():
    ledger.enable()
    session = TpuSession()
    t = _table()
    _agg(session, t).collect(engine="tpu")
    ledger.LEDGER.flush(timeout=30.0)
    before = ledger.snapshot()
    # second run of the SAME template: cached programs, new dispatches
    _agg(session, t).collect(engine="tpu")
    ledger.LEDGER.flush(timeout=30.0)
    d = ledger.delta(before, ledger.snapshot())
    assert d, "second collect attributed nothing"
    for p in d.values():
        assert p["dispatches"] >= 1
    # a delta over an idle window is empty
    assert ledger.delta(ledger.snapshot(), ledger.snapshot()) == {}


def test_summarize_math_and_top_programs():
    """summarize() arithmetic on a synthetic delta: attributed
    bytes/s, roofline fractions against explicit peaks, dispatch
    overhead, totals and top-N shares."""
    programs = {
        "fused#aa": {"tag": "fused", "op": "A", "dispatches": 4,
                     "dispatch_ms": 2.0, "device_ms": 100.0,
                     "flops": 1e6, "bytes_accessed": 1e6},
        "sort#bb": {"tag": "sort", "op": "B", "dispatches": 1,
                    "dispatch_ms": 1.0, "device_ms": 300.0,
                    "flops": 0.0, "bytes_accessed": 0.0},
    }
    s = ledger.summarize(programs, top_n=1,
                         hbm_bytes_per_s=1e9, peak_flops=1e12)
    a = s["programs"]["fused#aa"]
    # 1e6 bytes x 4 dispatches over 0.1s = 4e7 B/s; /1e9 = 0.04
    assert a["bytes_per_s"] == pytest.approx(4e7)
    assert a["roofline"] == pytest.approx(0.04)
    assert a["flops_per_s"] == pytest.approx(4e7)
    assert a["dispatch_overhead"] == pytest.approx(0.02)
    b = s["programs"]["sort#bb"]
    assert b["roofline"] is None  # no cost model -> no attribution
    t = s["totals"]
    assert t["programs"] == 2 and t["dispatches"] == 5
    assert t["device_ms"] == pytest.approx(400.0)
    # device-time-weighted over programs with a KNOWN cost model only
    assert t["roofline"] == pytest.approx(0.04)
    assert len(t["top"]) == 1
    assert t["top"][0]["key"] == "sort#bb"  # most device time
    assert t["top"][0]["share"] == pytest.approx(0.75)


def test_per_op_aggregation():
    programs = {
        "x#1": {"tag": "x", "op": "A", "dispatches": 2,
                "dispatch_ms": 1.0, "device_ms": 50.0,
                "flops": 10.0, "bytes_accessed": 1e6},
        "x#2": {"tag": "x", "op": "A", "dispatches": 1,
                "dispatch_ms": 1.0, "device_ms": 50.0,
                "flops": 10.0, "bytes_accessed": 2e6},
        "y#1": {"tag": "y", "op": None, "dispatches": 9,
                "dispatch_ms": 1.0, "device_ms": 5.0,
                "flops": 0.0, "bytes_accessed": 0.0},
    }
    per = ledger.per_op(programs, hbm_bytes_per_s=1e9)
    assert set(per) == {"A"}  # op-less programs stay out
    # (1e6*2 + 2e6*1) bytes over 0.1s = 4e7 B/s over 1e9 peak
    assert per["A"]["roofline"] == pytest.approx(0.04)
    assert per["A"]["dispatches"] == 3


def test_program_key_str_is_stable_and_distinct():
    k1 = ("fused", ("a", "b"), True)
    assert ledger.program_key_str(k1) == ledger.program_key_str(k1)
    assert ledger.program_key_str(k1).startswith("fused#")
    assert ledger.program_key_str(k1) != \
        ledger.program_key_str(("fused", ("a", "c"), True))


def test_reset_rekeys_wrapper_cells():
    """reset() drops entries; live cached wrappers re-register on
    their next dispatch (the per-query bench discipline)."""
    ledger.enable()
    session = TpuSession()
    t = _table()
    _agg(session, t).collect(engine="tpu")
    ledger.LEDGER.flush(timeout=30.0)
    assert ledger.snapshot()
    ledger.reset_stats()
    assert ledger.snapshot() == {}
    _agg(session, t).collect(engine="tpu")  # same cached programs
    ledger.LEDGER.flush(timeout=30.0)
    snap = ledger.snapshot()
    assert snap and all(p["dispatches"] >= 1 for p in snap.values())


# -- donation-safe settlement ------------------------------------------- #

def test_derive_sentinels_retains_live_leaves():
    """THE donation-attribution regression (ISSUE 11 satellite): a
    program output mixing a dead (deleted/donated) leaf with live
    leaves must keep sentinels for the live ones — the old
    all-or-nothing derivation settled the whole dispatch 'as host',
    silently dropping a donated fused program's device-busy time."""
    import jax.numpy as jnp

    live = jnp.arange(16)
    dead = jnp.arange(8) + 1
    dead.block_until_ready()
    dead.delete()
    sentinels = ledger.derive_sentinels({"a": dead, "b": live,
                                         "n": 7})
    assert len(sentinels) == 1  # the live leaf survives the dead one
    assert sentinels[0].shape == (0,)
    # all-dead (or host-only) outputs degrade to no sentinels, never
    # raise
    assert ledger.derive_sentinels({"a": dead}) == []
    assert ledger.derive_sentinels(42) == []


def test_donated_program_settles_device_time():
    """End-to-end through the settle worker: a dispatch whose output
    pytree holds a DEAD leaf next to a live one still settles its
    exclusive busy interval via the retained sibling sentinel (and
    the entry carries the donated marker for the footer)."""
    import time as _time

    import jax.numpy as jnp

    ledger.enable()
    entry = ledger.LEDGER.entry(("fusedenc", "t"), "T", donated=True)
    live = jnp.arange(1 << 16) * 3
    dead = jnp.arange(8)
    dead.block_until_ready()
    dead.delete()
    # THE regression contract: per-leaf fault isolation.  The old
    # all-or-nothing derivation returned [] the moment any leaf was
    # dead, so the settle worker stamped completion at submit time
    # ("as host") and the fused program's busy time vanished.  The
    # live sibling must survive as a sentinel.
    sentinels = ledger.derive_sentinels({"a": dead, "b": live})
    assert len(sentinels) == 1 and sentinels[0].shape == (0,)
    t0 = _time.perf_counter_ns()
    ledger.LEDGER._settle.submit(entry, t0, {"a": dead, "b": live},
                                 None)
    assert ledger.LEDGER.flush(timeout=30.0)
    snap = ledger.snapshot()
    e = snap[ledger.program_key_str(("fusedenc", "t"))]
    assert e["donated"] is True
    assert e["device_ms"] >= 0.0  # settled through the live sentinel


# -- off = free and bit-identical --------------------------------------- #

def test_ledger_disabled_dispatches_touch_nothing(monkeypatch):
    """Disabled-path contract: the cached_jit wrapper's only cost is
    the enabled-flag read — it must never create or look up a ledger
    entry (asserted by making entry creation explode)."""
    assert not ledger.LEDGER.enabled

    def boom(*a, **k):  # pragma: no cover - failing is the assert
        raise AssertionError("ledger touched while disabled")

    monkeypatch.setattr(ledger.LEDGER, "entry", boom)
    session = TpuSession()
    _agg(session, _table()).collect(engine="tpu")
    assert ledger.snapshot() == {}


def test_ledger_off_on_results_bit_identical():
    """The ledger is observation only: integer-exact query digests
    match bit-for-bit with the feature off and on."""
    from spark_rapids_tpu.eventlog import table_digest

    t = _table()
    session = TpuSession()
    off = table_digest(_agg(session, t).collect(engine="tpu"))
    ledger.enable()
    on = table_digest(_agg(session, t).collect(engine="tpu"))
    assert off == on


def test_sync_conf_ownership():
    """Conf-driven enable follows the tracer's ownership rule: only
    the enabling conf's `off` disables; a forced enable() wins."""
    conf_a = TpuConf({LEDGER_KEY: True})
    conf_b = TpuConf()  # defaults: ledger off
    ledger.sync_conf(conf_a)
    assert ledger.LEDGER.enabled
    ledger.sync_conf(conf_b)  # another session's defaults: no-op
    assert ledger.LEDGER.enabled
    conf_a.set(LEDGER_KEY, False)
    ledger.sync_conf(conf_a)  # the owner turns it off
    assert not ledger.LEDGER.enabled
    ledger.enable()  # forced
    ledger.sync_conf(conf_a)
    assert ledger.LEDGER.enabled


# -- surfacing: analyze / eventlog / history ---------------------------- #

def test_analyze_shows_roofline_column_and_ledger_footer():
    conf = TpuConf({LEDGER_KEY: True})
    session = TpuSession(conf)
    out = _agg(session, _table()).explain("analyze")
    assert "roofline=" in out, out
    assert "device ledger:" in out, out
    assert "top:" in out, out


def test_eventlog_programs_roundtrip_equals_inprocess(tmp_path):
    """THE round-trip contract: the query record's `programs` section
    reloaded through tools/history equals the in-process ledger
    snapshot for that query's window."""
    conf = TpuConf({
        LEDGER_KEY: True,
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    })
    session = TpuSession(conf)
    _agg(session, _table()).collect(engine="tpu")
    _ = session.history.events  # drain the snapshot worker
    ledger.LEDGER.flush(timeout=30.0)
    in_process = ledger.summarize(ledger.snapshot())

    from spark_rapids_tpu.tools.history import load_application

    app = load_application(session.event_log_path)
    assert len(app.queries) == 1
    q = app.queries[0]
    assert q.programs == in_process, (q.programs, in_process)
    assert q.program_totals()["dispatches"] >= 1


def test_history_compare_reports_program_deltas():
    """Per-program device-time deltas in compare: a 3x slower program
    is pinned by its structural key; appeared/vanished programs read
    as churn."""
    from spark_rapids_tpu.tools.history import (
        ApplicationInfo,
        QueryRecord,
        compare_applications,
        render_compare_md,
    )

    def q(programs, wall):
        return QueryRecord(
            query_id=1, plan="p", plan_hash="h", engine="tpu",
            wall_s=wall, start_ts=0, end_ts=0, conf_hash="c",
            counters={}, operators=None, spans=None, pipeline=None,
            faults=None, result_digest=None, rows=1, raw={},
            programs={"programs": programs, "totals": {}})

    base_p = {"fused#aa": {"op": "A", "dispatches": 3,
                           "device_ms": 100.0},
              "sort#bb": {"op": "B", "dispatches": 1,
                          "device_ms": 50.0}}
    run_p = {"fused#aa": {"op": "A", "dispatches": 3,
                          "device_ms": 300.0},
             "agg#cc": {"op": "C", "dispatches": 2,
                        "device_ms": 10.0}}
    base = ApplicationInfo("base", "eventlog", {}, [q(base_p, 1.0)])
    run = ApplicationInfo("run", "eventlog", {}, [q(run_p, 1.1)])
    result = compare_applications([base, run], threshold=1.25)
    (row,) = result["rows"]
    pd = {d["program"]: d for d in row["program_deltas"]}
    assert pd["fused#aa"]["change"] == "ratio"
    assert pd["fused#aa"]["ratio"] == pytest.approx(3.0)
    assert pd["sort#bb"]["change"] == "vanished"
    assert pd["agg#cc"]["change"] == "appeared"
    md = render_compare_md(result)
    assert "fused#aa" in md and "vanished" in md


def _qrec(programs_totals, wall_s):
    from spark_rapids_tpu.tools.history import QueryRecord

    return QueryRecord(
        query_id=7, plan="p", plan_hash="h", engine="tpu",
        wall_s=wall_s, start_ts=0, end_ts=0, conf_hash="c",
        counters={}, operators=None, spans=None, pipeline=None,
        faults=None, result_digest=None, rows=1, raw={},
        programs={"programs": {}, "totals": programs_totals})


def test_hc010_dispatch_overhead_rule():
    from spark_rapids_tpu.tools.history import (
        _hc_dispatch_overhead,
    )

    # 100 dispatches, 50ms device in a 1s query: overhead-dominated
    assert _hc_dispatch_overhead(
        _qrec({"dispatches": 100, "device_ms": 50.0}, 1.0))
    # same dispatches but the chip was busy 80% of the wall: healthy
    assert _hc_dispatch_overhead(
        _qrec({"dispatches": 100, "device_ms": 800.0}, 1.0)) is None
    # few dispatches: not this rule's business
    assert _hc_dispatch_overhead(
        _qrec({"dispatches": 3, "device_ms": 1.0}, 1.0)) is None
    # no ledger section at all: silent
    from spark_rapids_tpu.tools.history import QueryRecord

    bare = QueryRecord(
        query_id=1, plan="p", plan_hash="h", engine="tpu", wall_s=1.0,
        start_ts=0, end_ts=0, conf_hash="", counters={},
        operators=None, spans=None, pipeline=None, faults=None,
        result_digest=None, rows=1, raw={})
    assert _hc_dispatch_overhead(bare) is None


def test_hc011_roofline_budget_rule():
    from spark_rapids_tpu.tools.history import _hc_roofline_budget

    get_conf().set(
        "spark.rapids.tpu.trace.ledger.health.rooflineFloor", 0.01)
    # real device time at 0.001 roofline, floor 0.01: flagged
    assert _hc_roofline_budget(
        _qrec({"device_ms": 200.0, "roofline": 0.001}, 1.0))
    # above the floor: healthy
    assert _hc_roofline_budget(
        _qrec({"device_ms": 200.0, "roofline": 0.02}, 1.0)) is None
    # unit-test-sized device time: silent by design
    assert _hc_roofline_budget(
        _qrec({"device_ms": 3.0, "roofline": 0.0001}, 1.0)) is None
    # no attribution: silent
    assert _hc_roofline_budget(
        _qrec({"device_ms": 200.0, "roofline": None}, 1.0)) is None


# -- telemetry sampler -------------------------------------------------- #

def _telemetry_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("tpu-telemetry")]


def test_telemetry_disabled_no_thread():
    assert not telemetry.SAMPLER.enabled
    assert _telemetry_threads() == []


def test_telemetry_counter_tracks_export_to_chrome_trace():
    """Sampler output is Perfetto-loadable: ph='C' counter events with
    numeric args on the telemetry.* tracks, riding the same trace
    export as spans."""
    from spark_rapids_tpu.trace.export import chrome_trace

    trace.enable()
    s0 = telemetry.SAMPLER.samples  # cumulative across starts
    telemetry.start(hz=200)
    deadline = time.monotonic() + 5.0
    while telemetry.SAMPLER.samples < s0 + 3 \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    telemetry.stop()
    doc = chrome_trace()
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert counters, "no counter events exported"
    names = {e["name"] for e in counters}
    assert "telemetry.store_bytes" in names
    assert "telemetry.admission" in names
    # the ops-plane gauges ride the same counter-track export
    assert "telemetry.queries" in names
    assert "telemetry.result_cache_bytes" in names
    for e in counters:
        assert "dur" not in e and "s" not in e
        assert all(isinstance(v, (int, float))
                   for v in e["args"].values()), e
    json.dumps(doc)  # serializable whole


def test_telemetry_sampler_leakfree_under_concurrent_sessions(
        tmp_path):
    """Start/stop discipline under many sessions: one thread ever, the
    owner's off stops it, repeated cycles leave nothing behind, and
    attached sessions' event logs receive telemetry records."""
    assert _telemetry_threads() == []
    confs = [TpuConf({
        TELEMETRY_KEY: True,
        "spark.rapids.tpu.telemetry.hz": 100,
        "spark.rapids.tpu.telemetry.eventLogEvery": 1,
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    }) for _ in range(4)]
    sessions = [TpuSession(c) for c in confs]
    s0 = telemetry.SAMPLER.samples  # cumulative across starts

    def run(s):
        _agg(s, _table(512)).collect(engine="tpu")

    threads = [threading.Thread(target=run, args=(s,))
               for s in sessions]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(_telemetry_threads()) == 1  # ONE process sampler
    # give it a few periods so every attached log receives records
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if telemetry.SAMPLER.samples >= s0 + 4:
            break
        time.sleep(0.01)
    # a non-owner conf's off is a no-op; the owner's off stops it
    owner = telemetry.SAMPLER._enabled_by()
    other = next(c for c in confs if c is not owner)
    other.set(TELEMETRY_KEY, False)
    telemetry.sync_conf(other)
    assert telemetry.SAMPLER.enabled
    owner.set(TELEMETRY_KEY, False)
    telemetry.sync_conf(owner)
    assert not telemetry.SAMPLER.enabled
    assert _telemetry_threads() == []
    # forced cycles do not accumulate threads
    for _ in range(3):
        telemetry.start(hz=200)
        assert len(_telemetry_threads()) == 1
        telemetry.stop()
    assert _telemetry_threads() == []
    # the attached sessions' logs carry validated telemetry records
    from spark_rapids_tpu.eventlog.reader import iter_records

    telem_total = 0
    for s in sessions:
        _ = s.history.events  # drain query records first
        recs = list(iter_records(s.event_log_path, strict=True))
        telem_total += sum(1 for r in recs
                           if r["type"] == "telemetry")
        for r in recs:
            if r["type"] == "telemetry":
                assert "store.device_bytes" in r["counters"]
                assert "admission.waiting" in r["counters"]
                assert "queries.in_flight" in r["counters"]
                assert "result_cache.bytes" in r["counters"]
    assert telem_total > 0, "no telemetry records landed in any log"


def test_telemetry_history_roundtrip(tmp_path):
    """tools/history loads telemetry records alongside queries."""
    conf = TpuConf({
        TELEMETRY_KEY: True,
        "spark.rapids.tpu.telemetry.hz": 200,
        "spark.rapids.tpu.telemetry.eventLogEvery": 1,
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    })
    session = TpuSession(conf)
    s0 = telemetry.SAMPLER.samples  # cumulative across starts
    _agg(session, _table(512)).collect(engine="tpu")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if telemetry.SAMPLER.samples >= s0 + 2:
            break
        time.sleep(0.01)
    conf.set(TELEMETRY_KEY, False)
    telemetry.sync_conf(conf)  # owner off: sampler stops, log settles
    _ = session.history.events

    from spark_rapids_tpu.tools.history import load_application

    app = load_application(session.event_log_path)
    assert len(app.queries) == 1
    assert app.telemetry, "history dropped the telemetry records"
    assert "pipeline.occupancy" in app.telemetry[0]["counters"]
    assert "queries.in_flight" in app.telemetry[0]["counters"]
