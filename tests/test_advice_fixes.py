"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession, col, first, last, sum_
from tests.differential import assert_tpu_cpu_equal


@pytest.fixture
def session():
    return TpuSession()


# -- Ceil/Floor on non-finite doubles (medium) -------------------------- #

def test_ceil_floor_nan_inf_saturate(session):
    from spark_rapids_tpu.exprs.math import Ceil, Floor

    data = {"x": [float("nan"), float("inf"), float("-inf"),
                  1.5, -1.5, 2.0 ** 70, -(2.0 ** 70), 0.0]}
    df = session.create_dataframe(pa.table(data)).select(
        Ceil(col("x")).alias("c"), Floor(col("x")).alias("f"))
    out = df.collect(engine="tpu").to_pydict()
    i64 = np.iinfo(np.int64)
    assert out["c"] == [0, i64.max, i64.min, 2, -1, i64.max, i64.min, 0]
    assert out["f"] == [0, i64.max, i64.min, 1, -2, i64.max, i64.min, 0]
    # CPU oracle must agree (it previously raised on these inputs)
    assert_tpu_cpu_equal(df)


# -- First/Last default ignoreNulls=false (low) ------------------------- #

def test_first_last_default_keeps_nulls(session):
    t = pa.table({"k": [1, 1, 2, 2], "v": [None, 10, 20, None]})
    df = session.create_dataframe(t).group_by("k").agg(
        (first("v"), "f"), (last("v"), "l"))
    out = {r["k"]: (r["f"], r["l"])
           for r in df.collect(engine="tpu").to_pylist()}
    # group 1 first value is NULL -> NULL; group 2 last value NULL -> NULL
    assert out[1] == (None, 10)
    assert out[2] == (20, None)
    assert_tpu_cpu_equal(df)


def test_first_last_ignore_nulls(session):
    t = pa.table({"k": [1, 1, 2, 2], "v": [None, 10, 20, None]})
    df = session.create_dataframe(t).group_by("k").agg(
        (first("v", ignore_nulls=True), "f"),
        (last("v", ignore_nulls=True), "l"))
    out = {r["k"]: (r["f"], r["l"])
           for r in df.collect(engine="tpu").to_pylist()}
    assert out[1] == (10, 10)
    assert out[2] == (20, 20)
    assert_tpu_cpu_equal(df)


def test_grand_first_last_null(session):
    t = pa.table({"v": [None, 7, None]}, schema=pa.schema(
        [pa.field("v", pa.int64())]))
    df = session.create_dataframe(t).agg((first("v"), "f"),
                                         (last("v"), "l"),
                                         (first("v", True), "fi"),
                                         (last("v", True), "li"))
    row = df.collect(engine="tpu").to_pylist()[0]
    assert (row["f"], row["l"], row["fi"], row["li"]) == (None, None, 7, 7)
    assert_tpu_cpu_equal(df)


# -- shuffle blocks released when a limit abandons partitions (low) ----- #

def test_shuffle_blocks_released_on_early_stop(session):
    from spark_rapids_tpu.memory import get_store, reset_store
    from spark_rapids_tpu.shuffle import reset_shuffle_manager

    reset_store()
    reset_shuffle_manager()
    t = pa.table({"k": list(range(100)), "v": list(range(100))})
    # multi-partition aggregate forces a shuffle; limit(3) stops early
    df = (session.create_dataframe(t).union(session.create_dataframe(t))
          .group_by("k").agg((sum_("v"), "s")).limit(3))
    out = df.collect(engine="tpu")
    assert out.num_rows == 3
    store = get_store()
    assert store._entries == {}, (
        f"leaked {len(store._entries)} spillable buffers after collect")


# -- semaphore: same task_id from two racing threads leaks no permit ---- #

def test_semaphore_same_task_race():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore

    sem = TpuSemaphore(1)
    n_threads = 8
    barrier = threading.Barrier(n_threads)

    def run():
        barrier.wait()
        sem.acquire_if_necessary(42)

    threads = [threading.Thread(target=run) for _ in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=10)
    assert not any(th.is_alive() for th in threads)
    sem.release_if_necessary(42)
    assert sem._available == sem.permits, "permit leaked"


# -- disk-tier acquire keeps the spill file until upload succeeds ------- #

def test_disk_acquire_survives_reserve_failure(monkeypatch):
    import spark_rapids_tpu.memory.store as store_mod
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.memory import reset_store
    from spark_rapids_tpu.memory.store import BufferStore, StorageTier

    reset_store()
    store = BufferStore(device_budget=10 ** 9, host_budget=0)
    schema = T.Schema([T.Field("x", T.LONG)])
    b = ColumnarBatch.from_numpy(
        {"x": np.arange(16, dtype=np.int64)}, schema)
    h = store.register(b)
    h.unpin()
    e = store._entries[h.buffer_id]
    store._spill_to_host(e)  # host_budget=0 cascades straight to disk
    assert e.tier == StorageTier.DISK

    # first acquire attempt dies mid-upload; the file must survive
    real = store_mod._host_to_batch
    calls = {"n": 0}

    def boom(arrays, schema):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("injected H2D failure")
        return real(arrays, schema)

    monkeypatch.setattr(store_mod, "_host_to_batch", boom)
    with pytest.raises(RuntimeError, match="injected"):
        store.acquire(h.buffer_id)
    assert e.pins == 0  # a failed acquire rolls its pin back
    got = store.acquire(h.buffer_id)  # retry succeeds from the same file
    assert np.asarray(got.columns[0].data)[:16].tolist() == list(range(16))
    h.close()


# -- Round-2 advisor findings ------------------------------------------- #

def test_window_orderby_grouping_is_structural(session):
    """Two window exprs whose order-by exprs differ structurally but share
    a display name must land in separate Window nodes (no crash, correct
    results); structurally identical specs must share one node."""
    from spark_rapids_tpu.exprs.window import Window, row_number

    t = pa.table({
        "g": pa.array([1, 1, 2, 2], pa.int64()),
        "a": pa.array([3.0, 1.0, 4.0, 2.0], pa.float64()),
    })
    df = session.create_dataframe(t)
    # order by a ascending vs a descending: same display name "a"
    asc = Window.partition_by("g").order_by("a")
    desc = Window.partition_by("g").order_by("a", desc=True)
    out = df.select(
        col("g"), col("a"),
        row_number().over(asc).alias("rn_asc"),
        row_number().over(desc).alias("rn_desc"),
    ).collect().to_pydict()
    by_pair = {(g, a): (x, y) for g, a, x, y in zip(
        out["g"], out["a"], out["rn_asc"], out["rn_desc"])}
    assert by_pair[(1, 1.0)] == (1, 2)
    assert by_pair[(1, 3.0)] == (2, 1)
    assert by_pair[(2, 2.0)] == (1, 2)
    assert by_pair[(2, 4.0)] == (2, 1)


def test_join_cache_key_covers_child_split():
    """Joins with identical output schema but different left/right child
    splits must not share compiled closures."""
    from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec
    from spark_rapids_tpu.io.scan import ArrowSourceExec
    from spark_rapids_tpu.exprs.base import ColumnReference

    l1 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64()),
                                   "x": pa.array([1.0], pa.float64())}))
    r1 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64())}))
    l2 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64())}))
    r2 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64()),
                                   "x": pa.array([1.0], pa.float64())}))
    j1 = TpuShuffledHashJoinExec([ColumnReference("k")],
                                 [ColumnReference("k")], "inner", l1, r1)
    j2 = TpuShuffledHashJoinExec([ColumnReference("k")],
                                 [ColumnReference("k")], "inner", l2, r2)
    assert j1._cache_key() != j2._cache_key()


def test_expr_key_rejects_non_dataclass_expression():
    from spark_rapids_tpu.execs.jit_cache import expr_key
    from spark_rapids_tpu.exprs.base import Expression

    class Sneaky(Expression):
        def __init__(self):
            self.state = 42

    with pytest.raises(TypeError, match="dataclass"):
        expr_key(Sneaky())
