"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession, col, first, last, sum_
from tests.differential import assert_tpu_cpu_equal


@pytest.fixture
def session():
    return TpuSession()


# -- Ceil/Floor on non-finite doubles (medium) -------------------------- #

def test_ceil_floor_nan_inf_saturate(session):
    from spark_rapids_tpu.exprs.math import Ceil, Floor

    data = {"x": [float("nan"), float("inf"), float("-inf"),
                  1.5, -1.5, 2.0 ** 70, -(2.0 ** 70), 0.0]}
    df = session.create_dataframe(pa.table(data)).select(
        Ceil(col("x")).alias("c"), Floor(col("x")).alias("f"))
    out = df.collect(engine="tpu").to_pydict()
    i64 = np.iinfo(np.int64)
    assert out["c"] == [0, i64.max, i64.min, 2, -1, i64.max, i64.min, 0]
    assert out["f"] == [0, i64.max, i64.min, 1, -2, i64.max, i64.min, 0]
    # CPU oracle must agree (it previously raised on these inputs)
    assert_tpu_cpu_equal(df)


# -- First/Last default ignoreNulls=false (low) ------------------------- #

def test_first_last_default_keeps_nulls(session):
    t = pa.table({"k": [1, 1, 2, 2], "v": [None, 10, 20, None]})
    df = session.create_dataframe(t).group_by("k").agg(
        (first("v"), "f"), (last("v"), "l"))
    out = {r["k"]: (r["f"], r["l"])
           for r in df.collect(engine="tpu").to_pylist()}
    # group 1 first value is NULL -> NULL; group 2 last value NULL -> NULL
    assert out[1] == (None, 10)
    assert out[2] == (20, None)
    assert_tpu_cpu_equal(df)


def test_first_last_ignore_nulls(session):
    t = pa.table({"k": [1, 1, 2, 2], "v": [None, 10, 20, None]})
    df = session.create_dataframe(t).group_by("k").agg(
        (first("v", ignore_nulls=True), "f"),
        (last("v", ignore_nulls=True), "l"))
    out = {r["k"]: (r["f"], r["l"])
           for r in df.collect(engine="tpu").to_pylist()}
    assert out[1] == (10, 10)
    assert out[2] == (20, 20)
    assert_tpu_cpu_equal(df)


def test_grand_first_last_null(session):
    t = pa.table({"v": [None, 7, None]}, schema=pa.schema(
        [pa.field("v", pa.int64())]))
    df = session.create_dataframe(t).agg((first("v"), "f"),
                                         (last("v"), "l"),
                                         (first("v", True), "fi"),
                                         (last("v", True), "li"))
    row = df.collect(engine="tpu").to_pylist()[0]
    assert (row["f"], row["l"], row["fi"], row["li"]) == (None, None, 7, 7)
    assert_tpu_cpu_equal(df)


# -- shuffle blocks released when a limit abandons partitions (low) ----- #

def test_shuffle_blocks_released_on_early_stop(session):
    from spark_rapids_tpu.memory import get_store, reset_store
    from spark_rapids_tpu.shuffle import reset_shuffle_manager

    reset_store()
    reset_shuffle_manager()
    t = pa.table({"k": list(range(100)), "v": list(range(100))})
    # multi-partition aggregate forces a shuffle; limit(3) stops early
    df = (session.create_dataframe(t).union(session.create_dataframe(t))
          .group_by("k").agg((sum_("v"), "s")).limit(3))
    out = df.collect(engine="tpu")
    assert out.num_rows == 3
    store = get_store()
    assert store._entries == {}, (
        f"leaked {len(store._entries)} spillable buffers after collect")


# -- semaphore: same task_id from two racing threads leaks no permit ---- #

def test_semaphore_same_task_race():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore

    sem = TpuSemaphore(1)
    n_threads = 8
    barrier = threading.Barrier(n_threads)

    def run():
        barrier.wait()
        sem.acquire_if_necessary(42)

    threads = [threading.Thread(target=run) for _ in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=10)
    assert not any(th.is_alive() for th in threads)
    sem.release_if_necessary(42)
    assert sem._available == sem.permits, "permit leaked"


# -- disk-tier acquire keeps the spill file until upload succeeds ------- #

def test_disk_acquire_survives_reserve_failure(monkeypatch):
    import spark_rapids_tpu.memory.store as store_mod
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.memory import reset_store
    from spark_rapids_tpu.memory.store import BufferStore, StorageTier

    reset_store()
    store = BufferStore(device_budget=10 ** 9, host_budget=0)
    schema = T.Schema([T.Field("x", T.LONG)])
    b = ColumnarBatch.from_numpy(
        {"x": np.arange(16, dtype=np.int64)}, schema)
    h = store.register(b)
    h.unpin()
    e = store._entries[h.buffer_id]
    store._spill_to_host_locked(e)  # host_budget=0 cascades straight to disk
    assert e.tier == StorageTier.DISK

    # first acquire attempt dies mid-upload; the file must survive
    real = store_mod._host_to_batch
    calls = {"n": 0}

    def boom(arrays, schema):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("injected H2D failure")
        return real(arrays, schema)

    monkeypatch.setattr(store_mod, "_host_to_batch", boom)
    with pytest.raises(RuntimeError, match="injected"):
        store.acquire(h.buffer_id)
    assert e.pins == 0  # a failed acquire rolls its pin back
    got = store.acquire(h.buffer_id)  # retry succeeds from the same file
    assert np.asarray(got.columns[0].data)[:16].tolist() == list(range(16))
    h.close()


# -- Round-2 advisor findings ------------------------------------------- #

def test_window_orderby_grouping_is_structural(session):
    """Two window exprs whose order-by exprs differ structurally but share
    a display name must land in separate Window nodes (no crash, correct
    results); structurally identical specs must share one node."""
    from spark_rapids_tpu.exprs.window import Window, row_number

    t = pa.table({
        "g": pa.array([1, 1, 2, 2], pa.int64()),
        "a": pa.array([3.0, 1.0, 4.0, 2.0], pa.float64()),
    })
    df = session.create_dataframe(t)
    # order by a ascending vs a descending: same display name "a"
    asc = Window.partition_by("g").order_by("a")
    desc = Window.partition_by("g").order_by("a", desc=True)
    out = df.select(
        col("g"), col("a"),
        row_number().over(asc).alias("rn_asc"),
        row_number().over(desc).alias("rn_desc"),
    ).collect().to_pydict()
    by_pair = {(g, a): (x, y) for g, a, x, y in zip(
        out["g"], out["a"], out["rn_asc"], out["rn_desc"])}
    assert by_pair[(1, 1.0)] == (1, 2)
    assert by_pair[(1, 3.0)] == (2, 1)
    assert by_pair[(2, 2.0)] == (1, 2)
    assert by_pair[(2, 4.0)] == (2, 1)


def test_join_cache_key_covers_child_split():
    """Joins with identical output schema but different left/right child
    splits must not share compiled closures."""
    from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec
    from spark_rapids_tpu.io.scan import ArrowSourceExec
    from spark_rapids_tpu.exprs.base import ColumnReference

    l1 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64()),
                                   "x": pa.array([1.0], pa.float64())}))
    r1 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64())}))
    l2 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64())}))
    r2 = ArrowSourceExec(pa.table({"k": pa.array([1], pa.int64()),
                                   "x": pa.array([1.0], pa.float64())}))
    j1 = TpuShuffledHashJoinExec([ColumnReference("k")],
                                 [ColumnReference("k")], "inner", l1, r1)
    j2 = TpuShuffledHashJoinExec([ColumnReference("k")],
                                 [ColumnReference("k")], "inner", l2, r2)
    assert j1._cache_key() != j2._cache_key()


def test_expr_key_rejects_non_dataclass_expression():
    from spark_rapids_tpu.execs.jit_cache import expr_key
    from spark_rapids_tpu.exprs.base import Expression

    class Sneaky(Expression):
        def __init__(self):
            self.state = 42

    with pytest.raises(TypeError, match="dataclass"):
        expr_key(Sneaky())


# ===================================================================== #
# Round-5 advisor findings
# ===================================================================== #

# -- SQL UNION dtype widening (medium) ---------------------------------- #

def _sql_session_ab():
    from spark_rapids_tpu.frontends.sql import SqlSession

    fe = SqlSession()
    fe.register_table("ta", pa.table(
        {"x": pa.array([1, 2], pa.int32())}))
    fe.register_table("tb", pa.table(
        {"x": pa.array([1.5, 2.5], pa.float64())}))
    fe.register_table("tc", pa.table({"x": ["a", "b"]}))
    return fe


def test_sql_union_widens_member_types():
    """Pre-fix, TpuUnionExec re-tagged the DOUBLE member's batches with
    the INT first-member schema, silently truncating 1.5 -> 1.  Now the
    lowering inserts widening casts (WidenSetOperationTypes)."""
    fe = _sql_session_ab()
    df = fe.sql("select x from ta union all select x from tb")
    import spark_rapids_tpu.types as T

    assert isinstance(df.schema.fields[0].dtype, T.DoubleType)
    out = sorted(df.collect(engine="tpu")["x"].to_pylist())
    assert out == [1.0, 1.5, 2.0, 2.5]
    assert_tpu_cpu_equal(df)


def test_sql_union_widens_first_member_too():
    """Widening must coerce the FIRST member as well (double comes
    second)."""
    fe = _sql_session_ab()
    df = fe.sql("select x from tb union all select x from ta")
    out = sorted(df.collect(engine="tpu")["x"].to_pylist())
    assert out == [1.0, 1.5, 2.0, 2.5]
    assert_tpu_cpu_equal(df)


def test_sql_union_widening_with_duplicate_output_names():
    """Coercion must be positional: name-based references would
    resolve both 'a' columns to the first one."""
    fe = _sql_session_ab()
    fe.register_table("td", pa.table({"p": [10, 20], "q": [30, 40]}))
    fe.register_table("te", pa.table({"r": [1.5], "s": [2.5]}))
    df = fe.sql("select p as a, q as a from td "
                "union all select r, s from te")
    out = df.collect(engine="tpu")
    # positional read: to_pylist() dicts would collapse the dup names
    rows = sorted(zip(*(c.to_pylist() for c in out.columns)))
    assert rows == [(1.5, 2.5), (10.0, 30.0), (20.0, 40.0)]


def test_sql_union_incompatible_types_fail_analysis():
    from spark_rapids_tpu.frontends.sql import SqlError

    fe = _sql_session_ab()
    with pytest.raises(SqlError, match="incompatible types"):
        fe.sql("select x from ta union all select x from tc")


def test_dtype_flow_checker_catches_prefix_union():
    """The lint regression demanded by the fix: a hand-built L.Union
    (bypassing DataFrame.union's widening) still produces the pre-fix
    plan shape, and the static dtype-flow checker flags it without
    execution."""
    from spark_rapids_tpu.lint import lint_exec_tree
    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.plan.planner import plan_query
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession()
    a = s.create_dataframe(pa.table({"x": pa.array([1], pa.int32())}))
    b = s.create_dataframe(pa.table({"x": pa.array([1.5], pa.float64())}))
    root, _ = plan_query(L.Union([a._plan, b._plan]), s.conf)
    assert any(d.rule == "DT001" and d.severity == "error"
               for d in lint_exec_tree(root))


def test_dataframe_union_widens_at_engine_layer(session):
    """DataFrame.union (the single producer of L.Union) must widen, so
    every frontend is protected — a SQL-only fix would leave the
    DataFrame surface collecting truncated values."""
    import spark_rapids_tpu.types as T

    a = session.create_dataframe(
        pa.table({"x": pa.array([1, 2], pa.int32())}))
    b = session.create_dataframe(pa.table({"x": [1.5, 2.5]}))
    df = a.union(b)
    assert isinstance(df.schema.fields[0].dtype, T.DoubleType)
    out = sorted(df.collect(engine="tpu")["x"].to_pylist())
    assert out == [1.0, 1.5, 2.0, 2.5]
    assert_tpu_cpu_equal(df)


def test_dataframe_union_incompatible_types_raise(session):
    from spark_rapids_tpu.session import AnalysisException

    a = session.create_dataframe(pa.table({"x": [1, 2]}))
    b = session.create_dataframe(pa.table({"x": ["a", "b"]}))
    with pytest.raises(AnalysisException, match="incompatible types"):
        a.union(b)


def test_dataframe_union_column_count_mismatch(session):
    from spark_rapids_tpu.session import AnalysisException

    a = session.create_dataframe(pa.table({"x": [1]}))
    b = session.create_dataframe(pa.table({"x": [1], "y": [2]}))
    with pytest.raises(AnalysisException, match="column count"):
        a.union(b)


def test_sql_union_decimal_members_widen():
    """decimal(10,2) union decimal(8,4) -> decimal(12,4): Spark's
    DecimalPrecision keeps the integral and fractional digits of both
    sides; the cast rescales the int64 unscaled values.  The pre-review
    widening rejected ALL decimal pairs, regressing same-scale unions
    that previously worked by benign re-tagging."""
    from decimal import Decimal

    import spark_rapids_tpu.types as T
    from spark_rapids_tpu.frontends.sql import SqlSession

    fe = SqlSession()
    fe.register_table("t1", pa.table(
        {"d": pa.array([Decimal("1.50"), Decimal("2.25")],
                       pa.decimal128(10, 2))}))
    fe.register_table("t2", pa.table(
        {"d": pa.array([Decimal("3.1234")], pa.decimal128(8, 4))}))
    df = fe.sql("select d from t1 union all select d from t2")
    assert df.schema.fields[0].dtype == T.DecimalType(12, 4)
    out = sorted(df.collect(engine="tpu")["d"].to_pylist())
    assert out == [Decimal("1.5000"), Decimal("2.2500"),
                   Decimal("3.1234")]


def test_sql_union_same_scale_decimals_widen():
    """Same scale, different precision — the exact pair the first
    widening cut regressed (it worked pre-widening because the int64
    unscaled payloads are identical)."""
    from decimal import Decimal

    import spark_rapids_tpu.types as T
    from spark_rapids_tpu.frontends.sql import SqlSession

    fe = SqlSession()
    fe.register_table("t1", pa.table(
        {"d": pa.array([Decimal("1.00")], pa.decimal128(10, 2))}))
    fe.register_table("t2", pa.table(
        {"d": pa.array([Decimal("2.00"), Decimal("3.00")],
                       pa.decimal128(12, 2))}))
    df = fe.sql("select d from t1 union all select d from t2")
    assert df.schema.fields[0].dtype == T.DecimalType(12, 2)
    out = sorted(df.collect(engine="tpu")["d"].to_pylist())
    assert out == [Decimal("1.00"), Decimal("2.00"), Decimal("3.00")]


def test_sql_union_int_decimal_promotes():
    """int union decimal(10,2) -> decimal(12,2) (Spark's
    DecimalPrecision via DecimalType.forType(int) = decimal(10,0));
    the int side rescales to unscaled*100."""
    from decimal import Decimal

    import spark_rapids_tpu.types as T
    from spark_rapids_tpu.frontends.sql import SqlSession

    fe = SqlSession()
    fe.register_table("ti", pa.table(
        {"v": pa.array([1, 2], pa.int32())}))
    fe.register_table("td", pa.table(
        {"v": pa.array([Decimal("3.25")], pa.decimal128(10, 2))}))
    df = fe.sql("select v from ti union all select v from td")
    assert df.schema.fields[0].dtype == T.DecimalType(12, 2)
    out = sorted(df.collect(engine="tpu")["v"].to_pylist())
    assert out == [Decimal("1.00"), Decimal("2.00"), Decimal("3.25")]


def test_dataframe_union_decimal_double_promotes(session):
    """decimal + fractional -> double (Spark's DecimalPrecision)."""
    from decimal import Decimal

    import spark_rapids_tpu.types as T

    a = session.create_dataframe(pa.table(
        {"v": pa.array([Decimal("1.25")], pa.decimal128(10, 2))}))
    b = session.create_dataframe(pa.table({"v": [2.5]}))
    df = a.union(b)
    assert isinstance(df.schema.fields[0].dtype, T.DoubleType)
    out = sorted(df.collect(engine="tpu")["v"].to_pylist())
    assert out == [1.25, 2.5]


def test_dataframe_union_long_decimal_has_no_common_type(session):
    """LONG needs 19 integral digits — past the int64-backed
    MAX_PRECISION — so decimal+long fails analysis instead of losing
    digits (Spark would widen to decimal(20,s) on 128-bit storage)."""
    from decimal import Decimal

    from spark_rapids_tpu.session import AnalysisException

    a = session.create_dataframe(pa.table({"v": pa.array([1], pa.int64())}))
    b = session.create_dataframe(pa.table(
        {"v": pa.array([Decimal("1.00")], pa.decimal128(10, 2))}))
    with pytest.raises(AnalysisException, match="incompatible types"):
        a.union(b)


def test_dataframe_union_date_timestamp_promotes(session):
    """date + timestamp members promote to timestamp (Spark's
    findWiderTypeForTwo); the date side casts to midnight UTC."""
    import datetime as dt

    import spark_rapids_tpu.types as T

    a = session.create_dataframe(
        pa.table({"t": pa.array([0, 1], pa.int32()).cast(pa.date32())}))
    b = session.create_dataframe(
        pa.table({"t": pa.array([1_000_000], pa.timestamp("us"))}))
    df = a.union(b)
    assert isinstance(df.schema.fields[0].dtype, T.TimestampType)
    out = sorted(t.replace(tzinfo=None)
                 for t in df.collect(engine="tpu")["t"].to_pylist())
    assert out == [dt.datetime(1970, 1, 1),
                   dt.datetime(1970, 1, 1, 0, 0, 1),
                   dt.datetime(1970, 1, 2)]


# -- EXISTS derived tables lowered once (low) --------------------------- #

def test_exists_over_derived_table_reuses_lowering(monkeypatch):
    """_lower_exists pre-lowers derived tables into ("__df__", df) refs;
    q2 must consume them (no double lowering, and _lower must accept
    the __df__ tag)."""
    from spark_rapids_tpu.frontends.sql import SqlSession

    fe = SqlSession()
    fe.register_table("t1", pa.table({"ok": [1, 2, 3, 4]}))
    fe.register_table("t2", pa.table({"k": [2, 4, 4]}))

    calls: list[int] = []
    orig = SqlSession._lower

    def spy(self, q, ctes=None):
        calls.append(id(q))
        return orig(self, q, ctes)

    monkeypatch.setattr(SqlSession, "_lower", spy)
    df = fe.sql("select ok from t1 where exists "
                "(select k from (select k from t2) d where k = ok)")
    # each parsed query dict is lowered at most once — pre-fix the
    # derived table's dict went through _lower twice
    assert len(calls) == len(set(calls))
    out = sorted(df.collect(engine="tpu")["ok"].to_pylist())
    assert out == [2, 4]
    assert_tpu_cpu_equal(df)


def test_not_exists_over_derived_table():
    from spark_rapids_tpu.frontends.sql import SqlSession

    fe = SqlSession()
    fe.register_table("t1", pa.table({"ok": [1, 2, 3, 4]}))
    fe.register_table("t2", pa.table({"k": [2, 4, 4]}))
    df = fe.sql("select ok from t1 where not exists "
                "(select k from (select k from t2) d where k = ok)")
    assert sorted(df.collect(engine="tpu")["ok"].to_pylist()) == [1, 3]


# -- groupby coded-key domains use the TRUE dictionary length (low) ----- #

def test_coded_key_domains_use_dict_len():
    import jax.numpy as jnp

    import spark_rapids_tpu.types as T
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.ops.groupby import _coded_key_domains

    def make(dict_len):
        return Column(jnp.zeros(16, jnp.int64), jnp.ones(16, bool),
                      T.LONG, codes=jnp.zeros(16, jnp.int32),
                      dict_values=jnp.zeros(8, jnp.int64),
                      dict_len=dict_len)

    # wire-padded capacity 8, true entry count 2: the domain product
    # must use 2 (pre-fix it used 8, compounding per key)
    assert _coded_key_domains([make(2)]) == [2]
    # decode paths that predate the sidecar still fall back to capacity
    assert _coded_key_domains([make(None)]) == [8]


def test_transfer_decode_carries_dict_len():
    """Parquet-style dictionary columns decode with a tight bucketed
    bound on the true entry count riding alongside the pow2-padded
    device dictionary.  130 entries: bound = 144 (multiple of 16),
    padded capacity = 256 — the domain product must use 144, while the
    bucketing keeps jit treedefs from fragmenting per exact
    cardinality."""
    import numpy as np

    n_dict = 130
    codes = pa.array(np.arange(400, dtype=np.int32) % n_dict)
    ints = pa.DictionaryArray.from_arrays(
        codes, pa.array((np.arange(n_dict) * 10**9).tolist()))
    strs = pa.DictionaryArray.from_arrays(
        codes, pa.array([f"v{i:03d}" for i in range(n_dict)]))
    t = pa.table({"i": ints, "s": strs})

    from spark_rapids_tpu.columnar import transfer
    from spark_rapids_tpu.columnar.arrow import schema_from_arrow

    schema = schema_from_arrow(t.schema)
    arrays = [c.combine_chunks() for c in t.columns]
    enc = transfer.encode_for_device(arrays, schema, t.num_rows)
    assert enc is not None
    cols = transfer.decode_on_device(*enc, schema)
    icol, scol = cols
    assert icol.dict_len == 144
    assert int(icol.dict_values.shape[0]) == 256  # pow2 pad
    assert scol.dict_len == 144
    assert int(scol.dict_chars.shape[0]) == 256


def test_groupby_on_dict_column_differential():
    """End-to-end: grouping on a dictionary-encoded key column stays
    correct with the dict_len-sized domains."""
    import numpy as np

    from spark_rapids_tpu.session import TpuSession, sum_

    rng = np.random.default_rng(7)
    keys = rng.integers(0, 3, 64)
    t = pa.table({
        "k": pa.DictionaryArray.from_arrays(
            pa.array(keys, pa.int32()),
            pa.array([10**9, 2 * 10**9, 3 * 10**9])),
        "v": rng.normal(size=64),
    })
    s = TpuSession()
    df = s.create_dataframe(t).group_by("k").agg((sum_("v"), "sv"))
    assert_tpu_cpu_equal(df)
