"""Murmur3 parity tests.

The vectorized XLA implementation is cross-checked against an independent
scalar Python implementation of Spark's Murmur3_x86_32 (translated from
the *spec* of spark-catalyst's Murmur3_x86_32 + HashExpression null/seed
chaining, not from the JAX code) so a vectorization bug cannot hide.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.exprs.hashing import (
    hash_columns,
    partition_ids,
)

M32 = 0xFFFFFFFF


def rotl(x, r):
    return ((x << r) | (x >> (32 - r))) & M32


def mix_k1(k1):
    k1 = (k1 * 0xCC9E2D51) & M32
    k1 = rotl(k1, 15)
    return (k1 * 0x1B873593) & M32


def mix_h1(h1, k1):
    h1 ^= k1
    h1 = rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & M32


def fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & M32
    h1 ^= h1 >> 16
    return h1


def spark_hash_int(x, seed):
    return fmix(mix_h1(seed & M32, mix_k1(x & M32)), 4)


def spark_hash_long(x, seed):
    low = x & M32
    high = (x >> 32) & M32
    h1 = mix_h1(seed & M32, mix_k1(low))
    h1 = mix_h1(h1, mix_k1(high))
    return fmix(h1, 8)


def spark_hash_bytes(bs: bytes, seed):
    h1 = seed & M32
    aligned = len(bs) - len(bs) % 4
    for i in range(0, aligned, 4):
        word = int.from_bytes(bs[i:i + 4], "little")
        h1 = mix_h1(h1, mix_k1(word))
    for i in range(aligned, len(bs)):
        b = bs[i]
        if b >= 128:
            b -= 256  # Platform.getByte is signed
        h1 = mix_h1(h1, mix_k1(b & M32))
    return fmix(h1, len(bs))


def as_i32(u):
    return u - (1 << 32) if u >= (1 << 31) else u


def one_col_batch(col, dtype):
    schema = T.Schema([T.Field("c", dtype)])
    return ColumnarBatch([col], col.capacity, schema)


def test_hash_int_types():
    vals = np.array([0, 1, -1, 42, 2**31 - 1, -(2**31)], np.int32)
    col = Column.from_numpy(vals, T.INT)
    got = np.asarray(hash_columns([col], col.capacity))[: len(vals)]
    want = [as_i32(spark_hash_int(int(v) & M32, 42)) for v in vals]
    assert list(got) == want


def test_hash_long():
    vals = np.array([0, 1, -1, 42, 2**63 - 1, -(2**63)], np.int64)
    col = Column.from_numpy(vals, T.LONG)
    got = np.asarray(hash_columns([col], col.capacity))[: len(vals)]
    want = [as_i32(spark_hash_long(int(v) & ((1 << 64) - 1), 42))
            for v in vals]
    assert list(got) == want


def test_hash_double():
    import struct

    vals = np.array([0.0, -0.0, 1.5, -3.25, 1e300, float("nan")], np.float64)
    col = Column.from_numpy(vals, T.DOUBLE)
    got = np.asarray(hash_columns([col], col.capacity))[: len(vals)]
    want = []
    for v in vals:
        vv = 0.0 if v == 0.0 else v  # -0.0 normalized
        if np.isnan(vv):
            bits = 0x7FF8000000000000
        else:
            bits = struct.unpack("<Q", struct.pack("<d", vv))[0]
        want.append(as_i32(spark_hash_long(bits, 42)))
    assert list(got) == want
    # -0.0 and 0.0 must collide (same partition)
    assert got[0] == got[1]


def test_hash_strings_various_lengths():
    vals = ["", "a", "ab", "abc", "abcd", "abcde", "héllo wörld",
            "exactly8", "0123456789abcdef0", None]
    col = StringColumn.from_list(vals)
    got = np.asarray(hash_columns([col], col.capacity))[: len(vals)]
    for i, v in enumerate(vals):
        if v is None:
            assert got[i] == 42  # null leaves seed untouched
        else:
            assert got[i] == as_i32(spark_hash_bytes(v.encode("utf-8"), 42))


def test_hash_multi_column_chaining_and_nulls():
    a = Column.from_numpy(np.array([1, 2, 3], np.int64), T.LONG,
                          validity=np.array([True, False, True]))
    s = StringColumn.from_list(["x", "y", None])
    got = np.asarray(hash_columns([a, s], a.capacity))[:3]
    want = []
    for i, (av, avalid, sv) in enumerate(
            [(1, True, "x"), (2, False, "y"), (3, True, None)]):
        h = 42
        if avalid:
            h = spark_hash_long(av, h)
        if sv is not None:
            h = spark_hash_bytes(sv.encode(), h)
        want.append(as_i32(h))
    assert list(got) == want


def test_partition_ids_range_and_pmod():
    rng = np.random.default_rng(0)
    vals = rng.integers(-(2**62), 2**62, size=100, dtype=np.int64)
    col = Column.from_numpy(vals, T.LONG)
    pids = np.asarray(partition_ids([col], col.capacity, 7))[:100]
    assert pids.min() >= 0 and pids.max() < 7
    for v, p in list(zip(vals, pids))[:20]:
        h = as_i32(spark_hash_long(int(v) & ((1 << 64) - 1), 42))
        assert p == h % 7 if h % 7 >= 0 else (h % 7) + 7


def test_hash_float32():
    import struct

    vals = np.array([0.0, -0.0, 2.5, float("nan")], np.float32)
    col = Column.from_numpy(vals, T.FLOAT)
    got = np.asarray(hash_columns([col], col.capacity))[: len(vals)]
    want = []
    for v in vals:
        vv = np.float32(0.0) if v == 0.0 else v
        if np.isnan(vv):
            bits = 0x7FC00000
        else:
            bits = struct.unpack("<I", struct.pack("<f", vv))[0]
        want.append(as_i32(spark_hash_int(bits, 42)))
    assert list(got) == want


def test_md5_matches_hashlib():
    """Device MD5 (lockstep block schedule on the VPU) vs hashlib, over
    varied lengths incl. the 55/56-byte padding boundary and nulls."""
    import hashlib

    import pyarrow as pa

    from spark_rapids_tpu.exprs.hashing import Md5
    from spark_rapids_tpu.session import TpuSession, col

    vals = ["", "a", "abc", "hello world", "é✓ünïcode",
            "x" * 55, "y" * 56, "z" * 63, "w" * 64, "q" * 100,
            None, "The quick brown fox jumps over the lazy dog"]
    t = pa.table({"s": pa.array(vals, pa.string())})
    session = TpuSession()
    df = session.create_dataframe(t).select(
        col("s"), Md5(col("s")).alias("h"))
    got = df.collect(engine="tpu").to_pydict()["h"]
    want = [None if v is None else hashlib.md5(v.encode()).hexdigest()
            for v in vals]
    assert got == want
    cpu = df.collect(engine="cpu").to_pydict()["h"]
    assert cpu == want
