"""Software-pipelined executor (parallel/pipeline.py): stage order /
error / cancellation contracts, the deferred-readback lookahead in the
join stream loop (ISSUE 2's acceptance test), and a CPU smoke run of
the whole scan->filter->aggregate->sort pipeline with stages on vs off.
"""

from __future__ import annotations

import threading
import time

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.parallel import pipeline as P
from spark_rapids_tpu.session import TpuSession, col, sum_
from tests.differential import assert_tables_equal, assert_tpu_cpu_equal


@pytest.fixture
def session():
    return TpuSession()


# -- prefetch: the bounded background stage ----------------------------- #

def test_prefetch_preserves_order():
    got = list(P.prefetch(iter(range(200)), depth=3, stage="t.order"))
    assert got == list(range(200))


def test_prefetch_propagates_producer_exception_in_stream_order():
    def gen():
        yield 1
        yield 2
        raise ValueError("decode failed")

    it = P.prefetch(gen(), depth=2, stage="t.err")
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(ValueError, match="decode failed"):
        next(it)


def test_prefetch_cancels_cleanly_on_early_consumer_exit():
    """Early consumer exit must close the producer's generator (its
    finally runs on the producer thread) and join the thread — the
    join-on-abort handshake that replaced the 10ms poll-drain."""
    closed = threading.Event()
    started = threading.Event()

    def gen():
        try:
            for i in range(10_000):
                started.set()
                yield i
        finally:
            closed.set()

    before = threading.active_count()
    it = P.prefetch(gen(), depth=2, stage="t.cancel")
    assert next(it) == 0
    assert started.wait(2)
    t0 = time.perf_counter()
    it.close()  # abort: wakes the blocked producer, joins it
    assert time.perf_counter() - t0 < 1.0, "abort took poll-drain time"
    assert closed.is_set(), "producer generator was not closed on abort"
    # the stage thread is gone (give the OS a beat to reap it)
    deadline = time.time() + 2
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_prefetch_propagates_thread_local_conf():
    """conf is THREAD-LOCAL; the stage must install the caller's
    snapshot on the producer thread (a bare thread would silently read
    defaults — the scan's old hand-rolled snapshot, generalized)."""
    key = "spark.rapids.tpu.sql.pipeline.depth"
    get_conf().set(key, 5)

    def gen():
        yield get_conf().get(key)

    assert list(P.prefetch(gen(), depth=1, stage="t.conf")) == [5]


def test_prefetch_depth_zero_runs_inline():
    main_thread = threading.current_thread()
    seen = []

    def gen():
        seen.append(threading.current_thread())
        yield 1

    assert list(P.prefetch(gen(), depth=0, stage="t.inline")) == [1]
    assert seen == [main_thread]


def test_stage_metrics_accumulate():
    name = "t.metrics"
    list(P.prefetch(iter(range(32)), depth=4, stage=name))
    snap = P.stage_snapshot()[name]
    assert snap["items"] == 32
    assert snap["depth"] == 4
    assert 0.0 <= snap["occupancy_fraction"] <= 1.0


# -- pipelined: the deferred-readback lookahead ------------------------- #

def test_pipelined_dispatches_ahead_of_readback():
    """The generic contract: with lookahead k>=1, dispatch(i+1) happens
    before retire(i)'s blocking readback."""
    def dispatch(i):
        return i, jnp.asarray(i * 10, jnp.int32)

    def retire(entry):
        i, x = entry
        yield (i, P.device_read_int(x, tag="t.look"))

    with P.trace_events() as events:
        got = list(P.pipelined(range(5), dispatch, retire, depth=1,
                               tag="t.look"))
    assert got == [(i, i * 10) for i in range(5)]
    ev = [k for k, tag in events if tag == "t.look"]
    assert ev == ["dispatch", "dispatch", "readback", "dispatch",
                  "readback", "dispatch", "readback", "dispatch",
                  "readback", "readback"]


def test_pipelined_depth_zero_is_serial():
    with P.trace_events() as events:
        list(P.pipelined(range(3), lambda i: i, lambda i: [i], depth=0,
                         tag="t.serial"))
    ev = [k for k, _ in events]
    assert ev == ["dispatch", "readback"] * 0 + [
        "dispatch", "dispatch", "dispatch"]


def test_device_read_passes_host_scalars_through():
    with P.trace_events() as events:
        assert P.device_read_int(7, tag="t.host") == 7
        assert P.device_read_many([1, 2], tag="t.host") == [1, 2]
    assert events == []  # no device traffic, no readback event


# -- the join stream loop (ISSUE 2 acceptance) -------------------------- #

def _join_exec(n_stream=200, batch_rows=32, dup=2):
    """A wide shuffled hash join whose stream side arrives in several
    batches: stream (left) k in [0, 50), build (right) each key
    repeated `dup` times."""
    from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec
    from spark_rapids_tpu.io.scan import ArrowSourceExec

    rng = np.random.default_rng(11)
    left = pa.table({
        "k": rng.integers(0, 50, n_stream).astype(np.int64),
        "v": rng.random(n_stream),
    })
    right = pa.table({
        "k": np.repeat(np.arange(50, dtype=np.int64), dup),
        "w": np.arange(50 * dup, dtype=np.int64),
    })
    lsrc = ArrowSourceExec(left, batch_rows=batch_rows)
    rsrc = ArrowSourceExec(right)
    join = TpuShuffledHashJoinExec([col("k")], [col("k")], "inner",
                                   lsrc, rsrc)
    n_batches = lsrc.num_partitions
    return join, left, right, n_batches


def _drain_to_table(exec_):
    from spark_rapids_tpu.columnar.arrow import to_arrow

    tables = [to_arrow(b) for b in exec_.execute()]
    return pa.concat_tables(tables)


def _got_rows(tbl: pa.Table):
    """Join output columns are [k, v, k, w] (stream ++ build, Spark
    keeps both key columns) — canonicalize to sorted (k, v, w)."""
    k = tbl.column(0).to_pylist()
    v = tbl.column(1).to_pylist()
    w = tbl.column(3).to_pylist()
    return sorted(zip(k, (round(x, 9) for x in v), w))


def _expected_rows(left: pa.Table, right: pa.Table):
    from collections import defaultdict

    m = defaultdict(list)
    for k, w in zip(right["k"].to_pylist(), right["w"].to_pylist()):
        m[k].append(w)
    out = []
    for k, v in zip(left["k"].to_pylist(), left["v"].to_pylist()):
        for w in m.get(k, ()):
            out.append((k, round(v, 9), w))
    return sorted(out)


def test_join_stream_loop_one_readback_per_batch_with_lookahead():
    """THE acceptance criterion (PR 2, the non-speculative pipelined
    contract): at most one blocking device->host readback per stream
    batch, and batch k's readback happens only after batch k+1's probe
    is already dispatched.  Speculative sizing (which removes the
    readback entirely; tests/test_speculation.py) is pinned OFF so the
    deferred-readback ordering stays covered on its own."""
    get_conf().set("spark.rapids.tpu.sql.speculation.enabled", False)
    join, left, right, n_batches = _join_exec()
    assert n_batches >= 4
    with P.trace_events() as events:
        got = _drain_to_table(join)
    ev = [kind for kind, tag in events if tag == "join.probe"]
    dispatches = ev.count("dispatch")
    readbacks = ev.count("readback")
    assert dispatches == n_batches
    assert readbacks <= n_batches, \
        "more than one blocking readback per stream batch"
    # ordering: before the k-th readback retires, k+2 probes must have
    # been dispatched (the lookahead window) — except at stream end
    seen_d = 0
    seen_r = 0
    for kind in ev:
        if kind == "dispatch":
            seen_d += 1
        else:
            seen_r += 1
            assert seen_d >= min(seen_r + 1, n_batches), (
                f"readback #{seen_r} before probe #{seen_r + 1} was "
                f"dispatched: {ev}")
    assert _got_rows(got) == _expected_rows(left, right)


def test_join_lookahead_disabled_still_correct():
    get_conf().set("spark.rapids.tpu.sql.pipeline.enabled", False)
    join, left, right, _ = _join_exec()
    got = _drain_to_table(join)
    assert _got_rows(got) == _expected_rows(left, right)


def test_join_output_chunk_boundary_with_lookahead():
    """Join output larger than JOIN_OUTPUT_CHUNK_ROWS per stream batch:
    the expansion must chunk correctly while the next probe is already
    in flight."""
    get_conf().set("spark.rapids.tpu.sql.join.outputChunkRows", 64)
    join, left, right, n_batches = _join_exec(
        n_stream=128, batch_rows=64, dup=8)
    # each stream batch matches ~64*8 = 512 pairs >> 64-row chunks
    got = _drain_to_table(join)
    want = _expected_rows(left, right)
    assert got.num_rows == len(want)
    assert _got_rows(got) == want


# -- whole-pipeline smoke (tier-1, CPU) --------------------------------- #

def _smoke_query(session, tmp_path):
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    for i in range(3):
        t = pa.table({
            "k": rng.integers(0, 9, 4000).astype(np.int64),
            "v": rng.random(4000),
        })
        pq.write_table(t, str(tmp_path / f"part-{i}.parquet"))
    paths = [str(tmp_path / f"part-{i}.parquet") for i in range(3)]
    from spark_rapids_tpu.exprs.base import lit

    return (session.read_parquet(*paths)
            .where(col("v") > lit(0.25))
            .group_by(col("k"))
            .agg((sum_(col("v")), "sv"))
            .order_by(col("k")))


def test_pipeline_smoke_scan_agg_sort(session, tmp_path):
    """Exercises every inserted stage on CPU: scan decode/upload
    prefetch, aggregate update lookahead, result-fetch stage."""
    df = _smoke_query(session, tmp_path)
    assert_tpu_cpu_equal(df, approx_float=True)
    snap = P.stage_snapshot()
    assert snap.get("scan.decode", {}).get("items", 0) > 0
    assert snap.get("result.fetch", {}).get("items", 0) > 0


def test_pipeline_disabled_same_results(session, tmp_path):
    df = _smoke_query(session, tmp_path)
    on = df.collect(engine="tpu")
    get_conf().set("spark.rapids.tpu.sql.pipeline.enabled", False)
    off = df.collect(engine="tpu")
    assert_tables_equal(on, off, approx_float=True)


def test_explain_shows_pipeline_stages(session, tmp_path):
    df = _smoke_query(session, tmp_path)
    out = df.explain()
    assert "Pipeline:" in out
    assert "scan->decode" in out
    assert "last-exec->fetch" in out
    get_conf().set("spark.rapids.tpu.sql.pipeline.enabled", False)
    assert "Pipeline:" not in df.explain()


def test_pipeline_kill_switch_holds_on_map_task_threads(session,
                                                        tmp_path):
    """conf is thread-local: with the pipeline DISABLED, execs running
    on exchange map-task pool threads must also see the kill switch
    (the exchange installs the session conf snapshot per task) — no
    stage queue may record a single pop."""
    import pyarrow.parquet as pq

    rng = np.random.default_rng(17)
    paths = []
    for i in range(3):
        t = pa.table({
            "k": rng.integers(0, 7, 2000).astype(np.int64),
            "v": rng.random(2000),
        })
        p = str(tmp_path / f"mt-{i}.parquet")
        pq.write_table(t, p)
        paths.append(p)
    # one scan task per file -> several concurrent map tasks
    get_conf().set("spark.rapids.tpu.sql.scan.taskTargetBytes", 1)
    get_conf().set("spark.rapids.tpu.sql.pipeline.enabled", False)
    df = (session.read_parquet(*paths)
          .group_by(col("k")).agg((sum_(col("v")), "sv")))

    def items(snap):
        return sum(v["items"] for v in snap.values())

    before = items(P.stage_snapshot())
    got = df.collect(engine="tpu")
    assert items(P.stage_snapshot()) == before, \
        "a pipeline stage ran on a pool thread despite enabled=False"
    get_conf().set("spark.rapids.tpu.sql.pipeline.enabled", True)
    assert_tables_equal(got, df.collect(engine="cpu"),
                        approx_float=True)


def test_exchange_map_pipeline_correct(session):
    """Hash exchange map tasks retire split counts one batch behind
    dispatch; the shuffle must still route every row exactly once."""
    get_conf().set("spark.rapids.tpu.sql.batchSizeRows", 256)
    rng = np.random.default_rng(5)
    t = pa.table({
        "k": rng.integers(0, 64, 2048).astype(np.int64),
        "v": rng.random(2048),
    })
    df = (session.create_dataframe(t)
          .group_by(col("k")).agg((sum_(col("v")), "sv")))
    assert_tpu_cpu_equal(df, approx_float=True)
