"""Pod-scale serving (docs/pod_serving.md): mesh-resident multi-tenant
execution with device-born stage inputs.

- THE tier-1 hook for tools/bench_smoke.run_mesh_serving_smoke (two
  sessions on a virtual 4-device mesh: shared partitioned program set
  via the jit-key census, zero steady-state data-plane host uploads
  via the tapped placement counter, digest gate vs the serial
  single-device reference);
- the SPMD x serving digest-identity storm: four concurrent sessions
  x three templates (agg / join / sort) on the virtual 8-device mesh,
  every result bit-identical (canonical row-sorted digest) to the
  serial single-device run;
- a cancellation storm ON the mesh whose unwinds leave every process
  residency gauge exactly at baseline (conftest.leak_check);
- mesh re-keying: a pod reshape (mesh shape change) changes
  mesh_cache_suffix and therefore every prepared-plan template key
  under an UNCHANGED conf fingerprint — and the default-off posture
  keeps the suffix empty (flag-off keying bit-identical to the
  pre-mesh engine);
- placement classification unit coverage (place_piece /adopt_batch:
  host vs control vs device-born vs d2d) and the scheduler's
  mesh-admission budget multiplier.
"""

from __future__ import annotations

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import TpuConf, get_conf, set_conf
from spark_rapids_tpu.parallel import make_mesh
from spark_rapids_tpu.parallel import placement
from spark_rapids_tpu.parallel.mesh import (
    active_mesh,
    mesh_key,
    set_active_mesh,
)
from spark_rapids_tpu.serving import (
    mesh_cache_suffix,
    mesh_serving_enabled,
    scheduler as scheduler_mod,
)
from spark_rapids_tpu.session import TpuSession, col, count_star, sum_
from spark_rapids_tpu.shuffle.transport import SHUFFLE_TRANSPORT

MESH_ENABLED = "spark.rapids.tpu.serving.mesh.enabled"
SPMD_ENABLED = "spark.rapids.tpu.shuffle.collective.spmd.enabled"
ROUND_ROWS = "spark.rapids.tpu.shuffle.collective.roundRows"


@pytest.fixture(autouse=True)
def _isolate_mesh():
    """Active mesh, scheduler ring and serving context are process
    state — every test leaves them as found (conf restore is
    conftest._isolate_conf's job)."""
    from spark_rapids_tpu.serving import clear_serving_context

    prev = active_mesh()
    scheduler_mod.reset()
    clear_serving_context()
    yield
    set_active_mesh(prev)
    scheduler_mod.reset()
    clear_serving_context()


def _canon_digest(tbl) -> str:
    import __graft_entry__ as graft

    return graft._canon_digest(tbl)


def _tables(rows: int = 2048, seed: int = 3):
    rng = np.random.default_rng(seed)
    fact = pa.table({
        "k": rng.integers(0, 256, rows).astype(np.int64),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })
    dim = pa.table({
        "k": np.arange(256, dtype=np.int64),
        "w": np.arange(256, dtype=np.int64) * 3,
    })
    sort_t = pa.table({
        "k": rng.permutation(rows).astype(np.int64),
        "v": np.arange(rows, dtype=np.int64),
    })
    return fact, dim, sort_t


def _templates(session, fact, dim, sort_t):
    return [
        ("agg", session.create_dataframe(fact)
         .group_by(col("k"))
         .agg((sum_(col("v")), "sv"), (count_star(), "n"))),
        ("join", session.create_dataframe(fact)
         .join(session.create_dataframe(dim), on="k", how="inner")),
        ("sort", session.create_dataframe(sort_t).order_by(col("k"))),
    ]


def _mesh_conf(rows: int, mesh_serving: bool = True) -> TpuConf:
    over = dict(get_conf()._values)
    over.update({
        SHUFFLE_TRANSPORT.key: "collective",
        SPMD_ENABLED: True,
        ROUND_ROWS: max(256, rows // 8),
        "spark.rapids.tpu.sql.batchSizeRows": max(256, rows // 8),
        "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes": -1,
        MESH_ENABLED: mesh_serving,
    })
    return TpuConf(over)


def _serial_digests(fact, dim, sort_t) -> dict:
    conf = TpuConf(dict(get_conf()._values))
    conf.set(SHUFFLE_TRANSPORT.key, "local")
    conf.set(MESH_ENABLED, False)
    conf.set("spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes",
             -1)
    set_conf(conf)
    s0 = TpuSession(conf)
    return {name: _canon_digest(df.collect(engine="tpu"))
            for name, df in _templates(s0, fact, dim, sort_t)}


# ------------------------------------------------------------------ #
# Placement classification (the device-born contract's unit layer)
# ------------------------------------------------------------------ #


def test_place_piece_classification():
    """place_piece classifies every move: host-born numpy counts
    host_uploads (or control_uploads under control=True), an exactly
    placed jax.Array is a zero-copy device_born adoption, and an
    array on ANOTHER device is a d2d transfer."""
    import jax

    devs = jax.devices()
    placement.reset_stats()
    a = placement.place_piece(np.arange(8), devs[0])
    assert placement.stats()["host_uploads"] == 1
    placement.place_piece(np.arange(4), devs[0], control=True)
    st = placement.stats()
    assert st["host_uploads"] == 1 and st["control_uploads"] == 1
    b = placement.place_piece(a, devs[0])
    assert b is a  # exactly placed: returned unchanged
    assert placement.stats()["device_born"] == 1
    c = placement.place_piece(a, devs[1])
    assert c.devices() == {devs[1]}
    st = placement.stats()
    assert st["d2d_transfers"] == 1
    placement.reset_stats()
    assert all(v == 0 for v in placement.stats().values())


def test_adopt_batch_idempotent_and_counted():
    """adopt_batch commits every column leaf onto the shard's device;
    already-resident leaves are untouched (idempotent, zero adoptions
    on the second call) and num_rows stays a host int."""
    import jax

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch

    devs = jax.devices()
    schema = T.Schema([T.Field("x", T.LONG)])
    batch = ColumnarBatch.from_numpy(
        {"x": np.arange(16, dtype=np.int64)}, schema, capacity=16)
    placement.reset_stats()
    moved = placement.adopt_batch(batch, devs[1])
    n_moved = placement.stats()["adoptions"]
    assert n_moved >= 1
    again = placement.adopt_batch(moved, devs[1])
    assert placement.stats()["adoptions"] == n_moved  # idempotent
    assert isinstance(again.num_rows, int)
    np.testing.assert_array_equal(
        np.asarray(again.columns[0].data), np.arange(16))


def test_src016_choke_point_is_clean():
    """The in-tree execs//parallel/ layers carry ZERO raw
    jax.device_put calls (SRC016): placement.py is the only mover."""
    from spark_rapids_tpu.lint.source_rules import check_sources

    hits = [d for d in check_sources() if d.rule == "SRC016"]
    assert hits == [], hits


# ------------------------------------------------------------------ #
# Mesh admission + cache re-keying
# ------------------------------------------------------------------ #


def test_mesh_cache_suffix_keys_on_mesh_shape():
    """A pod reshape changes mesh_cache_suffix (and so every
    mesh-keyed cache key) under an UNCHANGED conf fingerprint; the
    default-off posture and the no-mesh posture keep the suffix empty
    — flag-off cache keying is bit-identical to the pre-mesh
    engine."""
    conf = get_conf()
    assert not mesh_serving_enabled(conf)
    assert mesh_cache_suffix(conf) == ""
    conf.set(MESH_ENABLED, True)
    set_active_mesh(None)
    assert mesh_cache_suffix(conf) == ""  # enabled but no mesh yet
    m8 = make_mesh(8)
    set_active_mesh(m8)
    sfx8 = mesh_cache_suffix(conf)
    assert sfx8.startswith("|mesh:") and len(sfx8) == len("|mesh:") + 12
    m4 = make_mesh(4)
    set_active_mesh(m4)
    sfx4 = mesh_cache_suffix(conf)
    assert sfx4.startswith("|mesh:") and sfx4 != sfx8
    assert mesh_key(m4) != mesh_key(m8)
    # back to 8: the suffix is a pure function of the mesh identity
    set_active_mesh(m8)
    assert mesh_cache_suffix(conf) == sfx8
    conf.set(MESH_ENABLED, False)
    assert mesh_cache_suffix(conf) == ""


def test_template_key_rekeys_on_mesh_shape_change():
    """The prepared-plan template key folds the mesh identity under
    mesh serving: same plan, same conf -> different key after a pod
    reshape (stale partitioned entries can never serve the new mesh),
    and the same key again when the original shape returns."""
    from spark_rapids_tpu.serving.plan_cache import template_key

    conf = get_conf()
    conf.set(MESH_ENABLED, True)
    session = TpuSession(conf)
    fact, _dim, _sort = _tables(rows=64)
    df = (session.create_dataframe(fact)
          .group_by(col("k")).agg((sum_(col("v")), "sv")))
    set_active_mesh(make_mesh(8))
    k8 = template_key(df._plan, conf)
    set_active_mesh(make_mesh(4))
    k4 = template_key(df._plan, conf)
    assert k8 != k4
    set_active_mesh(make_mesh(8))
    assert template_key(df._plan, conf) == k8
    # flag off: mesh identity leaves the key entirely
    conf.set(MESH_ENABLED, False)
    koff = template_key(df._plan, conf)
    set_active_mesh(make_mesh(4))
    assert template_key(df._plan, conf) == koff


def test_scheduler_mesh_admission_budget():
    """Mesh admission: with an active mesh and mesh serving on, the
    admission limit scales by n_devices x deviceBudget (the whole pod
    serves); off — or with no mesh — the limit is the plain clamp."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore

    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.concurrentTpuTasks", 2)
    TpuSemaphore.reset()
    sched = scheduler_mod.QueryScheduler(max_concurrent=2,
                                         queue_depth=8)
    set_active_mesh(None)
    base = sched._limit()
    assert base == 2
    conf.set(MESH_ENABLED, True)
    assert sched._limit() == base  # enabled but no mesh
    set_active_mesh(make_mesh(4))
    assert sched._limit() == base * 4
    conf.set("spark.rapids.tpu.serving.mesh.deviceBudget", 2)
    assert sched._limit() == base * 8
    conf.set(MESH_ENABLED, False)
    assert sched._limit() == base
    TpuSemaphore.reset()


# ------------------------------------------------------------------ #
# The tier-1 smoke hook
# ------------------------------------------------------------------ #


def test_bench_smoke_mesh_serving():
    """tools/bench_smoke.run_mesh_serving_smoke: two sessions on a
    virtual 4-device mesh share one partitioned program set (flat
    census), move zero steady-state data-plane bytes host->device,
    and hash identical to the serial single-device reference."""
    from spark_rapids_tpu.tools.bench_smoke import (
        run_mesh_serving_smoke,
    )

    out = run_mesh_serving_smoke()
    assert out["mesh_serving_host_uploads"] == 0
    assert out["mesh_serving_programs"] >= 1
    assert out["mesh_serving_device_born"] >= 1


# ------------------------------------------------------------------ #
# SPMD x serving digest identity (the storm-shaped acceptance test)
# ------------------------------------------------------------------ #


def test_spmd_serving_digest_identity_four_sessions():
    """Four concurrent sessions x three templates on the virtual
    8-device mesh with mesh-resident serving: every result (warm and
    repeat) hashes bit-identical to the serial single-device
    reference, and the measured repeats compile nothing new."""
    from spark_rapids_tpu.execs.jit_cache import cache_stats

    fact, dim, sort_t = _tables(rows=2048)
    digests = _serial_digests(fact, dim, sort_t)
    set_active_mesh(make_mesh(8))
    n_sessions = 4
    errors: list = []
    mismatches: list = []
    lock = threading.Lock()
    warm_done = threading.Barrier(n_sessions + 1)
    go = threading.Event()

    def run(i: int) -> None:
        pqs = {}
        try:
            conf = _mesh_conf(rows=2048)
            set_conf(conf)
            session = TpuSession(conf, tenant=f"t{i % 2}")
            for name, df in _templates(session, fact, dim, sort_t):
                pqs[name] = session.prepare(df)
            for name, pq in pqs.items():
                if _canon_digest(pq.execute()) != digests[name]:
                    with lock:
                        mismatches.append((i, name, "warm"))
        except BaseException as e:  # noqa: BLE001 — reported below
            with lock:
                errors.append((i, repr(e)))
            pqs = {}
        finally:
            warm_done.wait()
        if not pqs:
            return
        go.wait()
        try:
            for name, pq in pqs.items():
                if _canon_digest(pq.execute()) != digests[name]:
                    with lock:
                        mismatches.append((i, name, "repeat"))
        except BaseException as e:  # noqa: BLE001 — reported below
            with lock:
                errors.append((i, repr(e)))

    threads = [threading.Thread(target=run, args=(i,),
                                name=f"pod-serve-{i}")
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    warm_done.wait()
    jit0 = cache_stats()
    go.set()
    for t in threads:
        t.join()
    jit1 = cache_stats()
    assert not errors, errors
    assert not mismatches, mismatches
    assert jit1["misses"] == jit0["misses"], (jit0, jit1)


# ------------------------------------------------------------------ #
# Cancellation storm on the mesh: unwinds leave no residency
# ------------------------------------------------------------------ #


def test_mesh_cancellation_storm_leaves_no_residency(leak_check):
    """session.cancel() fired mid-flight against mesh-resident
    executions: every surviving result stays digest-gated, cancelled
    ones unwind cleanly, and the process residency gauges (permits,
    store bytes, stage threads, scan shares — conftest.leak_check)
    return EXACTLY to baseline."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.serving import cancel as C

    C.reset()
    TpuSemaphore.reset()
    fact, dim, sort_t = _tables(rows=2048)
    digests = _serial_digests(fact, dim, sort_t)
    set_active_mesh(make_mesh(8))
    conf = _mesh_conf(rows=2048)
    set_conf(conf)
    session = TpuSession(conf, tenant="storm")
    pqs = {name: session.prepare(df)
           for name, df in _templates(session, fact, dim, sort_t)}
    for name, pq in pqs.items():  # warm: compile the program set
        assert _canon_digest(pq.execute()) == digests[name]
    survived = cancelled = 0
    for round_i in range(4):
        for name, pq in pqs.items():
            canceller = threading.Timer(0.005 * (round_i + 1),
                                        session.cancel)
            canceller.start()
            try:
                r = pq.execute()
                assert _canon_digest(r) == digests[name], name
                survived += 1
            except C.QueryCancelled:
                cancelled += 1
            finally:
                canceller.cancel()
                canceller.join()
    # the storm must have produced BOTH outcomes being meaningful is
    # timing-dependent; what is load-bearing is that every execution
    # either survived digest-gated or unwound cleanly
    assert survived + cancelled == 12
    C.reset()
    TpuSemaphore.reset()
