"""collect_list / collect_set — the two-phase dense-list exec vs the
CPU oracle (element ORDER is unspecified in Spark, so comparisons
canonicalize each list as a sorted multiset)."""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession, col, collect_list, collect_set


@pytest.fixture
def session():
    return TpuSession()


def _canon_cell(v):
    if v is None:
        return None
    return sorted("NaN" if isinstance(x, float) and math.isnan(x)
                  else str(x) for x in v)


def _canon(tbl, keys):
    rows = []
    for r in tbl.to_pylist():
        rows.append(tuple(
            _canon_cell(v) if isinstance(v, list) else str(v)
            for v in r.values()))
    return sorted(rows)


def test_grouped_collect_list_differential(session):
    rng = np.random.default_rng(61)
    n = 3000
    t = pa.table({
        "k": rng.integers(0, 12, n),
        "v": pa.array([None if rng.random() < 0.15 else int(x)
                       for x in rng.integers(0, 50, n)], pa.int64()),
    })
    df = (session.create_dataframe(t)
          .group_by(col("k")).agg((collect_list(col("v")), "vs")))
    got = df.collect(engine="tpu")
    want = df.collect(engine="cpu")
    assert _canon(got, 1) == _canon(want, 1)
    # TPU plan, not fallback
    from spark_rapids_tpu.execs.collect_agg import TpuCollectAggExec
    from spark_rapids_tpu.plan.planner import plan_query

    exec_, _ = plan_query(df._plan)
    assert isinstance(exec_, TpuCollectAggExec)


def test_grouped_collect_set_dedups(session):
    rng = np.random.default_rng(62)
    n = 2000
    vals = [None if rng.random() < 0.1
            else float(rng.integers(0, 5)) for _ in range(n)]
    for i in range(0, n, 37):
        vals[i] = float("nan")  # NaN == NaN must dedup
    t = pa.table({"k": rng.integers(0, 6, n),
                  "v": pa.array(vals, pa.float64())})
    df = (session.create_dataframe(t)
          .group_by(col("k")).agg((collect_set(col("v")), "vs")))
    got = df.collect(engine="tpu")
    want = df.collect(engine="cpu")
    assert _canon(got, 1) == _canon(want, 1)
    for r in got.to_pylist():
        nan_count = sum(1 for x in r["vs"]
                        if isinstance(x, float) and math.isnan(x))
        assert nan_count <= 1


def test_grand_collect_and_empty(session):
    t = pa.table({"v": pa.array([3, 1, None, 2], pa.int64())})
    df = session.create_dataframe(t).agg((collect_list(col("v")), "vs"))
    got = df.collect(engine="tpu").to_pydict()["vs"]
    want = df.collect(engine="cpu").to_pydict()["vs"]
    assert sorted(got[0]) == sorted(want[0]) == [1, 2, 3]

    empty = session.create_dataframe(
        pa.table({"v": pa.array([], pa.int64())}))
    dfe = empty.agg((collect_list(col("v")), "vs"))
    assert dfe.collect(engine="tpu").to_pydict()["vs"] == [[]]
    assert dfe.collect(engine="cpu").to_pydict()["vs"] == [[]]


def test_multipartition_collect_stays_on_device(session):
    """Round 4: multi-partition grouped collect no longer falls back —
    it hash-exchanges on the keys and collects per reduce partition."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, get_conf
    from spark_rapids_tpu.plan.planner import CpuFallbackExec, plan_query

    conf = get_conf()
    old = conf.get(BATCH_SIZE_ROWS)
    conf.set(BATCH_SIZE_ROWS.key, 100)
    try:
        rng = np.random.default_rng(63)
        t = pa.table({"k": rng.integers(0, 4, 1000),
                      "v": rng.integers(0, 9, 1000)})
        df = (session.create_dataframe(t)
              .group_by(col("k")).agg((collect_list(col("v")), "vs")))
        exec_, _ = plan_query(df._plan)
        assert not isinstance(exec_, CpuFallbackExec)
        tree = exec_.tree_string()
        assert "TpuShuffleExchangeExec" in tree, tree
        got = df.collect(engine="tpu")
        want = df.collect(engine="cpu")
        assert _canon(got, 1) == _canon(want, 1)
    finally:
        conf.set(BATCH_SIZE_ROWS.key, old)


def test_collect_over_strings_falls_back(session):
    from spark_rapids_tpu.plan.planner import CpuFallbackExec, plan_query

    t = pa.table({"k": [1, 1, 2], "s": ["a", "b", "a"]})
    df = (session.create_dataframe(t)
          .group_by(col("k")).agg((collect_list(col("s")), "vs")))
    exec_, meta = plan_query(df._plan)
    assert isinstance(exec_, CpuFallbackExec), meta.explain()
    got = df.collect(engine="tpu")
    want = df.collect(engine="cpu")
    assert _canon(got, 1) == _canon(want, 1)


def test_string_collect_below_tpu_parent(session):
    """A TPU project above a CPU collect_list(string) would crash at
    the upload boundary (list<string> has no device layout): the
    planner must push the CPU region up over the parent."""
    t = pa.table({"k": [1, 1, 2], "s": ["a", "b", "a"]})
    df = (session.create_dataframe(t)
          .group_by(col("k")).agg((collect_list(col("s")), "l"))
          .select(col("k")))
    from spark_rapids_tpu.plan.planner import CpuFallbackExec, plan_query

    exec_, meta = plan_query(df._plan)
    assert isinstance(exec_, CpuFallbackExec), meta.explain()
    got = sorted(df.collect(engine="tpu").to_pydict()["k"])
    assert got == sorted(df.collect(engine="cpu").to_pydict()["k"])


def test_collect_over_array_column_is_construction_error(session):
    t = pa.table({"k": [1, 1], "x": pa.array([[1, 2], [3]],
                                             pa.list_(pa.int64()))})
    with pytest.raises(TypeError, match="array column"):
        (session.create_dataframe(t)
         .group_by(col("k")).agg((collect_list(col("x")), "l")))


def test_multi_partition_grouped_collect(session, tmp_path):
    """Multi-partition input: hash exchange on keys, per-partition
    device collect, union output — no CPU fallback (VERDICT r3 #10)."""
    import pyarrow.parquet as pq

    from spark_rapids_tpu.plan.planner import plan_query
    from tests.differential import gen_table

    from spark_rapids_tpu.config import get_conf

    get_conf().set("spark.rapids.tpu.sql.scan.taskTargetBytes", 1024)
    t = gen_table({"k": "smallint64", "v": "int64"}, 3000, seed=77)
    paths = []
    for i in range(5):
        p = str(tmp_path / f"c{i}.parquet")
        pq.write_table(t.slice(i * 600, 600), p)
        paths.append(p)
    df = (session.read_parquet(*paths)
          .group_by(col("k"))
          .agg((collect_list(col("v")), "vs")))
    exec_, meta = plan_query(df._plan, session.conf)
    tree = exec_.tree_string()
    assert "TpuCollectAggExec" in tree, tree
    assert "CpuFallback" not in tree, tree
    assert "TpuShuffleExchangeExec" in tree, tree
    assert _canon(df.collect(engine="tpu"), 1) == \
        _canon(df.collect(engine="cpu"), 1)

    df2 = (session.read_parquet(*paths)
           .group_by(col("k"))
           .agg((collect_set(col("v")), "vs")))
    assert _canon(df2.collect(engine="tpu"), 1) == \
        _canon(df2.collect(engine="cpu"), 1)


def test_multi_partition_grand_collect(session, tmp_path):
    import pyarrow.parquet as pq

    from tests.differential import gen_table

    t = gen_table({"k": "smallint64", "v": "int64"}, 900, seed=78)
    paths = []
    for i in range(3):
        p = str(tmp_path / f"g{i}.parquet")
        pq.write_table(t.slice(i * 300, 300), p)
        paths.append(p)
    df = session.read_parquet(*paths).agg((collect_list(col("v")), "vs"))
    assert _canon(df.collect(engine="tpu"), 0) == \
        _canon(df.collect(engine="cpu"), 0)
