"""Chaos-mode acceptance: deterministic fault injection drives every
recovery path (robustness/faults.py + the execs/retry.py escalation
ladder) and the answers stay BIT-FOR-BIT identical to the fault-free
run — the reference proves its OOM machinery the same way
(RmmRapidsRetryIterator's forced-OOM/forced-split test harness).

Covers ISSUE 6's acceptance criteria:
- golden-query chaos parity: the full golden pack under a seeded fault
  schedule (device OOM on an early alloc, one upload fault, one
  compile fault, one pipeline-stage fault, one mid-stream batch fault)
  returns exactly the fault-free tables, with every injected fault
  recovered and at least one recovery per core site across the pack;
- shuffle-fetch chaos: an injected connection reset inside
  fetch_blocks recovers through the new bounded-retry/backoff path
  (and peer re-resolution picks up a moved server);
- OOC under real pressure: the BufferStore device budget shrinks
  mid-query and the sort/join still answer exactly;
- fully DISABLED, the robustness subsystem is behavior-identical:
  same table, same plan, same dispatch/readback pattern, zero
  counters."""

import json
import pathlib

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import BATCH_SIZE_ROWS, get_conf
from spark_rapids_tpu.execs import retry as R
from spark_rapids_tpu.robustness import faults
from spark_rapids_tpu.session import TpuSession, col, sum_

from tests.test_golden import FIXTURES, _column


def assert_bitwise_equal(got: pa.Table, want: pa.Table, ctx="") -> None:
    """BIT-FOR-BIT table parity: repr-level comparison distinguishes
    NaN (equal to itself here, unlike ==) and -0.0 from 0.0 — the
    float corners plain dict equality gets wrong in both directions."""
    assert got.schema == want.schema, ctx
    g, w = got.to_pydict(), want.to_pydict()
    for name in w:
        assert [repr(v) for v in g[name]] \
            == [repr(v) for v in w[name]], (ctx, name)


@pytest.fixture(autouse=True)
def _fast_and_disarmed():
    conf = get_conf()
    conf.set(R.RETRY_BACKOFF_S.key, 0.0)
    R.reset_retry_stats()
    yield
    faults.disarm()


# ------------------------------------------------------------------ #
# fault registry unit behavior
# ------------------------------------------------------------------ #


def test_spec_parsing_and_determinism():
    st = faults.parse_spec(
        "alloc.device:nth=3,times=2;shuffle.fetch:prob=0.5,seed=7;"
        "transfer.upload:latency=5,marker=UNAVAILABLE boom")
    assert st["alloc.device"].nth == 3
    assert st["alloc.device"].times == 2
    assert st["shuffle.fetch"].prob == 0.5
    assert st["transfer.upload"].latency_s == 0.005
    assert "UNAVAILABLE" in st["transfer.upload"].marker
    with pytest.raises(ValueError):
        faults.parse_spec("alloc.device")  # missing ':'
    with pytest.raises(ValueError):
        faults.parse_spec("alloc.device:bogus=1")
    with pytest.raises(ValueError):
        # a typo'd site would arm a schedule that never fires — the
        # chaos run would read green without testing anything
        faults.parse_spec("alloc.devices:nth=1")


def test_nth_and_every_policies_fire_deterministically():
    faults.install("exec.batch:nth=2,times=2;jit.compile:every=3",
                   forced=True)
    fired = []
    for i in range(1, 7):
        try:
            faults.fault_point("exec.batch")
            fired.append(False)
        except faults.InjectedFault as e:
            fired.append(True)
            assert R.is_retryable(e)  # default markers classify
            assert e.site == "exec.batch"
    assert fired == [False, True, True, False, False, False]
    fired = []
    for i in range(1, 7):
        try:
            faults.fault_point("jit.compile")
            fired.append(False)
        except faults.InjectedFault:
            fired.append(True)
    assert fired == [False, False, True, False, False, True]


def test_seeded_probability_is_reproducible():
    def run():
        faults.install("exec.batch:prob=0.5,seed=42", forced=True)
        out = []
        for _ in range(32):
            try:
                faults.fault_point("exec.batch")
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    a, b = run(), run()
    assert a == b and 0 < sum(a) < 32


def test_note_recovered_walks_cause_chain():
    faults.install("shuffle.fetch:nth=1", forced=True)
    try:
        faults.fault_point("shuffle.fetch")
    except faults.InjectedFault as inner:
        try:
            raise RuntimeError("wrapped") from inner
        except RuntimeError as outer:
            faults.note_recovered(outer, action="test")
    assert faults.fault_stats()["shuffle.fetch"]["recovered"] == 1


def test_disarmed_fault_point_is_noop():
    faults.disarm()
    for site in faults.SITES:
        faults.fault_point(site)  # never raises
    assert faults.fault_stats() == {}


# ------------------------------------------------------------------ #
# golden-query chaos parity (THE acceptance test)
# ------------------------------------------------------------------ #

#: one fault per core site: an early device-alloc OOM, one H2D upload
#: fault, one compile fault, one producer-stage fault, and one
#: batch fault for the split-retry ladder's spill rung.
#: times=1 everywhere keeps recovery on-device (spill+retry re-runs
#: the same programs), so parity is bit-for-bit by construction.
_GOLDEN_SCHEDULE = ("alloc.device:nth=1;transfer.upload:nth=1;"
                    "jit.compile:nth=1;pipeline.stage:nth=1;"
                    "exec.batch:nth=1")

_CORE_SITES = ("alloc.device", "transfer.upload", "jit.compile",
               "pipeline.stage", "exec.batch")


def test_golden_pack_chaos_parity():
    """Every golden query under the seeded fault schedule returns
    bit-for-bit the same table as its fault-free run; every injected
    fault is recovered; across the pack every core site records at
    least one recovery."""
    from spark_rapids_tpu.execs import jit_cache
    from spark_rapids_tpu.frontends.sql import SqlSession

    recovered_by_site = {s: 0 for s in _CORE_SITES}
    injected_total = 0
    for path in FIXTURES:
        fx = json.loads(pathlib.Path(path).read_text())
        fe = SqlSession()
        for name, cols in fx["tables"].items():
            fe.register_table(
                name, pa.table({c: _column(v)
                                for c, v in cols.items()}))
        df = fe.sql(fx["sql"])
        want = df.collect(engine="tpu")  # fault-free reference
        jit_cache.clear()  # force a compile miss for jit.compile
        faults.install(_GOLDEN_SCHEDULE, forced=True)
        try:
            got = df.collect(engine="tpu")
            stats = faults.fault_stats()
        finally:
            faults.disarm()
        assert_bitwise_equal(got, want, ctx=path.stem)
        for site, st in stats.items():
            # every injected fault was absorbed by a recovery path
            assert st["recovered"] == st["injected"], (path.stem, site,
                                                       stats)
            if site in recovered_by_site:
                recovered_by_site[site] += st["recovered"]
            injected_total += st["injected"]
    assert injected_total > 0
    for site in _CORE_SITES:
        assert recovered_by_site[site] > 0, (
            f"site {site} never exercised a recovery across the "
            f"golden pack: {recovered_by_site}")


def test_chaos_never_degrades_to_cpu():
    """The golden schedule recovers on-device: no query-level CPU
    fallback is part of the parity story (a degraded query would still
    be correct, but would not prove the TPU recovery paths)."""
    import warnings

    rng = np.random.default_rng(11)
    t = pa.table({"k": rng.integers(0, 8, 3000), "v": rng.random(3000)})
    s = TpuSession()
    df = (s.create_dataframe(t).group_by(col("k"))
          .agg((sum_(col("v")), "s")))
    want = df.collect(engine="tpu")
    faults.install(_GOLDEN_SCHEDULE, forced=True)
    R.reset_retry_stats()
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # no degrade
        got = df.collect(engine="tpu")
    assert_bitwise_equal(got, want)
    assert R.retry_stats()["cpu_fallbacks"] == 0
    assert faults.recovered_total() == faults.injected_total() > 0


# ------------------------------------------------------------------ #
# chaos under the runtime lock tracker (docs/concurrency.md)
# ------------------------------------------------------------------ #


def test_chaos_under_lock_tracker_zero_cycles_exact_bookkeeping():
    """Fault-driven recovery AND fault-driven cancellation unwinds,
    executed with the runtime lock-order tracker armed (the conftest
    arms it for this module): the perturbed interleavings must form
    ZERO lock-order cycles, and lock_stats() must balance exactly —
    every aggregate is precisely the per-name sum, every name is a
    known engine lock, and the locks the exercised paths own
    (pipeline stage metrics, the active-token gauge) show real
    acquisitions."""
    from spark_rapids_tpu.robustness import lock_tracker as LT
    from spark_rapids_tpu.serving import cancel as C

    conf = get_conf()
    conf.set(BATCH_SIZE_ROWS.key, 512)  # multi-batch: the prefetch
    # pipeline (and its stage fault seam) only runs a real stream
    rng = np.random.default_rng(23)
    # integer measure: exact sums independent of the accumulation
    # order a recovery re-split may choose
    t = pa.table({"k": rng.integers(0, 16, 4000),
                  "v": rng.integers(0, 1000, 4000).astype(np.int64)})
    s = TpuSession()
    df = (s.create_dataframe(t).group_by(col("k"))
          .agg((sum_(col("v")), "sv")))
    want = df.collect(engine="tpu")  # warm + fault-free reference

    LT.reset_stats()  # measure only the chaos runs below
    # recovery path: one injected producer-stage fault, recovered
    faults.install("pipeline.stage:nth=1", forced=True)
    try:
        got = df.collect(engine="tpu")
        stage_stats = faults.fault_stats()["pipeline.stage"]
    finally:
        faults.disarm()  # disarm drops the site state: read first
    assert_bitwise_equal(got, want)
    assert stage_stats["recovered"] == 1
    # cancellation path: an injected cancel.check hit unwinds the
    # query through the production teardown
    faults.install("cancel.check:nth=2", forced=True)
    try:
        with pytest.raises(C.QueryCancelled):
            df.collect(engine="tpu")
    finally:
        faults.disarm()

    assert LT.cycle_count() == 0, LT.order_graph()
    stats = LT.lock_stats()
    agg = LT.aggregate_stats()
    # exact bookkeeping: aggregates are the per-name sums, nothing
    # drops or double-counts
    assert agg["acquisitions"] == sum(
        v["acquisitions"] for v in stats.values())
    assert agg["contention_waits"] == sum(
        v["contention_waits"] for v in stats.values())
    assert agg["max_hold_ms"] == max(
        (v["max_hold_ms"] for v in stats.values()), default=0)
    assert agg["cycles"] == 0
    # only known engine locks appear
    assert set(stats) <= {
        "planCache.mu", "resultCache.mu", "scanShare.mu",
        "cancel.breakers", "cancel.active", "pipeline.stages",
        "scheduler.registry"}
    for name, v in stats.items():
        assert 0 <= v["contention_waits"] <= v["acquisitions"], name
        assert v["max_hold_ms"] >= 0, name
    # the exercised paths really own their locks: the pipelined agg
    # ticks stage metrics; every collect brackets the active-token
    # gauge (cancellation is on by default)
    assert stats["pipeline.stages"]["acquisitions"] > 0
    assert stats["cancel.active"]["acquisitions"] >= 4, \
        "begin+end per collect across the two chaos runs"


# ------------------------------------------------------------------ #
# shuffle-fetch chaos: bounded retries + peer re-resolution
# ------------------------------------------------------------------ #


def _serve_blocks():
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.shuffle import ShuffleBlockServer
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    schema = T.Schema([T.Field("k", T.LONG), T.Field("v", T.DOUBLE)])
    mgr = ShuffleManager()
    sid = mgr.new_shuffle_id()
    rng = np.random.default_rng(3)
    v = rng.random(64)
    mgr.write(sid, 0, ColumnarBatch.from_numpy(
        {"k": rng.integers(0, 9, 64).astype(np.int64), "v": v}, schema))
    srv = ShuffleBlockServer(mgr).start()
    return srv, sid, float(v.sum())


def test_fetch_blocks_retries_injected_reset():
    """An injected connection reset on the first attempt recovers
    inside fetch_blocks (bounded retries with backoff) — the task
    layer never sees it, and the recovery is credited to the site."""
    from spark_rapids_tpu.shuffle import fetch_blocks

    conf = get_conf()
    conf.set("spark.rapids.tpu.shuffle.fetch.retryWaitSeconds", 0.0)
    srv, sid, want = _serve_blocks()
    try:
        faults.install("shuffle.fetch:nth=1", forced=True)
        blocks = fetch_blocks("127.0.0.1", srv.address[1], sid, 0)
        assert len(blocks) == 1
        got = float(np.asarray(blocks[0]["c1_data"])[:64].sum())
        assert abs(got - want) < 1e-9
        st = faults.fault_stats()["shuffle.fetch"]
        assert st["injected"] == 1 and st["recovered"] == 1
    finally:
        faults.disarm()
        srv.shutdown()


def test_fetch_blocks_exhausts_then_raises():
    from spark_rapids_tpu.shuffle import FetchFailedError, fetch_blocks

    conf = get_conf()
    conf.set("spark.rapids.tpu.shuffle.fetch.retryWaitSeconds", 0.0)
    conf.set("spark.rapids.tpu.shuffle.fetch.maxAttempts", 3)
    srv, sid, _ = _serve_blocks()
    try:
        faults.install("shuffle.fetch:nth=1,times=3", forced=True)
        with pytest.raises(FetchFailedError):
            fetch_blocks("127.0.0.1", srv.address[1], sid, 0)
        assert faults.fault_stats()["shuffle.fetch"]["injected"] == 3
        assert faults.fault_stats()["shuffle.fetch"]["recovered"] == 0
    finally:
        faults.disarm()
        srv.shutdown()


def test_fetch_re_resolves_peer_before_last_attempt():
    """Persistent failure against a stale address re-resolves the peer
    through the heartbeat registry (live_peers) and the final attempt
    lands on the moved server."""
    from spark_rapids_tpu.shuffle import HeartbeatManager, fetch_blocks
    from spark_rapids_tpu.shuffle.net import peer_resolver

    conf = get_conf()
    conf.set("spark.rapids.tpu.shuffle.fetch.retryWaitSeconds", 0.0)
    conf.set("spark.rapids.tpu.shuffle.fetch.timeoutSeconds", 2.0)
    srv, sid, want = _serve_blocks()
    registry = HeartbeatManager()
    registry.register("exec-1", "127.0.0.1", srv.address[1])
    try:
        # a port nothing listens on: connect fails until re-resolution
        blocks = fetch_blocks(
            "127.0.0.1", 1, sid, 0,
            resolve_peer=peer_resolver(registry, "exec-1"))
        assert len(blocks) == 1
        got = float(np.asarray(blocks[0]["c1_data"])[:64].sum())
        assert abs(got - want) < 1e-9
    finally:
        srv.shutdown()


# ------------------------------------------------------------------ #
# OOC under real pressure: device budget shrunk mid-query
# ------------------------------------------------------------------ #


class BudgetShrinkExec:
    """Pass-through exec that collapses the BufferStore's device budget
    after the first batch flows by — everything registered afterwards
    spills immediately (the mid-query pressure drop a multi-tenant
    serving tier produces when a neighbor session lands)."""

    def __new__(cls, child, shrink_to):
        from spark_rapids_tpu.execs.base import TpuExec

        class _Shrink(TpuExec):
            def __init__(self):
                super().__init__(child)
                self._done = False

            @property
            def schema(self):
                return child.schema

            @property
            def num_partitions(self):
                return child.num_partitions

            def node_desc(self):
                return "BudgetShrinkExec"

            def execute_partition(self, p):
                from spark_rapids_tpu.memory import get_store

                for b in child.execute_partition(p):
                    yield b
                    if not self._done:
                        self._done = True
                        get_store().device_budget = shrink_to

            def execute(self):
                for p in range(self.num_partitions):
                    yield from self.execute_partition(p)

        return _Shrink()


def _mkstore(budget=None):
    from spark_rapids_tpu.memory.store import BufferStore, reset_store

    store = BufferStore(device_budget=budget or (12 << 30))
    reset_store(store)
    return store


def test_ooc_sort_with_budget_shrunk_mid_query():
    from spark_rapids_tpu.execs.sort import SortKey, TpuSortExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.io.scan import ArrowSourceExec
    from spark_rapids_tpu.plan.planner import collect_exec

    conf = get_conf()
    conf.set(BATCH_SIZE_ROWS.key, 512)
    conf.set("spark.rapids.tpu.sql.sort.singleBatchRows", 1024)
    rng = np.random.default_rng(5)
    vals = rng.integers(0, 1 << 30, 6000)
    t = pa.table({"x": vals})
    store = _mkstore()
    try:
        src = BudgetShrinkExec(ArrowSourceExec(t), shrink_to=1 << 16)
        keys = [SortKey(B.BoundReference(0, T.LONG, False, "x"))]
        got = collect_exec(TpuSortExec(keys, src, scope="global"))
        assert got.column("x").to_pylist() == sorted(vals.tolist())
        assert store.spilled_device_to_host > 0, \
            "shrunken budget never forced a spill"
    finally:
        _mkstore()  # fresh store for later tests


def test_ooc_join_with_budget_shrunk_mid_query():
    from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.io.scan import ArrowSourceExec
    from spark_rapids_tpu.plan.planner import collect_exec

    conf = get_conf()
    conf.set(BATCH_SIZE_ROWS.key, 512)
    rng = np.random.default_rng(9)
    left = pa.table({"k": rng.integers(0, 64, 4000),
                     "a": rng.integers(0, 1000, 4000)})
    right = pa.table({"k2": np.arange(64), "b": np.arange(64) * 10})
    store = _mkstore()
    try:
        lsrc = BudgetShrinkExec(ArrowSourceExec(left), shrink_to=1 << 16)
        rsrc = ArrowSourceExec(right)
        join = TpuShuffledHashJoinExec(
            [B.BoundReference(0, T.LONG, False, "k")],
            [B.BoundReference(0, T.LONG, False, "k2")],
            "inner", lsrc, rsrc)
        got = collect_exec(join)
        assert got.num_rows == 4000
        ks = got.column("k").to_pylist()
        bs = got.column("b").to_pylist()
        assert all(b == k * 10 for k, b in zip(ks, bs))
    finally:
        _mkstore()


# ------------------------------------------------------------------ #
# fully disabled = behavior-identical
# ------------------------------------------------------------------ #


def test_disabled_robustness_is_plan_and_readback_identical():
    """With robustness.faults fully disabled (the default), a query's
    plan, result AND dispatch/readback pattern are identical to the
    armed-but-empty-schedule run — the subsystem's off-state is
    asserted to be a no-op, not assumed."""
    from spark_rapids_tpu.parallel import pipeline as P
    from spark_rapids_tpu.robustness.faults import (
        FAULTS_ENABLED,
        FAULTS_SPEC,
    )

    rng = np.random.default_rng(13)
    t = pa.table({"v": rng.random(4000), "w": rng.random(4000)})
    s = TpuSession()
    df = (s.create_dataframe(t)
          .where(col("v") > col("w"))
          .agg((sum_(col("v")), "sv")))
    df.collect(engine="tpu")  # warm compile caches / predictors

    assert not faults._ARMED
    plan_off = df.explain()
    with P.trace_events() as ev_off:
        out_off = df.collect(engine="tpu")
    pattern_off = list(ev_off)
    assert faults.fault_stats() == {}

    conf = get_conf()
    conf.set(FAULTS_ENABLED.key, True)
    conf.set(FAULTS_SPEC.key, "")  # armed, zero policies
    try:
        plan_on = df.explain()
        with P.trace_events() as ev_on:
            out_on = df.collect(engine="tpu")
        pattern_on = list(ev_on)
        assert faults._ARMED
    finally:
        conf.set(FAULTS_ENABLED.key, False)
        df.collect(engine="tpu")  # boundary sync disarms (owner conf)
    assert not faults._ARMED
    assert plan_on == plan_off
    assert_bitwise_equal(out_on, out_off)
    assert pattern_on == pattern_off
    assert R.retry_stats()["splits"] == 0
