"""Serializer/compression, row<->columnar converters, plugin lifecycle
(ref: GpuColumnarBatchSerializer, GpuRowToColumnarExec/ColumnarToRow,
ColumnarRdd, SQLPlugin lifecycle)."""

import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.session import TpuSession, col, sum_
from tests.differential import gen_table


@pytest.fixture
def session():
    return TpuSession()


def test_serde_round_trip():
    from spark_rapids_tpu.columnar.serde import (
        deserialize_arrays,
        serialize_arrays,
    )

    arrays = {
        "a": np.arange(1000, dtype=np.int64),
        "b": np.random.default_rng(0).random(1000),
        "c": np.zeros((100, 8), np.uint8),
        "v": np.ones(1000, bool),
    }
    for codec in ("none", "zlib"):
        data = serialize_arrays(arrays, codec)
        back = deserialize_arrays(data)
        assert set(back) == set(arrays)
        for k in arrays:
            assert np.array_equal(back[k], arrays[k]), (codec, k)


def test_serde_zlib_compresses():
    from spark_rapids_tpu.columnar.serde import serialize_arrays

    arrays = {"a": np.zeros(100_000, np.int64)}  # highly compressible
    raw = serialize_arrays(arrays, "none")
    z = serialize_arrays(arrays, "zlib")
    assert len(z) < len(raw) // 10


def test_compressed_disk_spill_round_trip(session, tmp_path):
    """Force a spill chain to disk with zlib and read it back."""
    from spark_rapids_tpu.columnar.arrow import from_arrow
    from spark_rapids_tpu.memory.store import BufferStore, StorageTier

    session.conf.set(
        "spark.rapids.tpu.memory.spill.compression.codec", "zlib")
    store = BufferStore(device_budget=1 << 16, host_budget=1 << 16,
                        spill_dir=str(tmp_path))
    b1 = from_arrow(pa.table({"x": pa.array(np.arange(5000))}))
    b2 = from_arrow(pa.table({"x": pa.array(np.arange(5000) * 2)}))
    h1 = store.register(b1)
    h1.unpin()
    h2 = store.register(b2)  # evicts b1 to host, then disk
    h2.unpin()
    store.reserve(1 << 15)  # push the chain
    files = [f for f in os.listdir(tmp_path) if f.endswith(".tpub")]
    assert files, "expected a disk spill file"
    got = h1.get()
    assert np.asarray(got.columns[0].data)[:5000].tolist() \
        == list(range(5000))
    store.close()


def test_rows_and_batches_export(session):
    t = gen_table({"a": "int64", "s": "string"}, 300, seed=2)
    df = session.create_dataframe(t).where(col("a").is_not_null())
    rbs = list(df.to_batches(batch_rows=64))
    assert sum(rb.num_rows for rb in rbs) == df.collect().num_rows
    assert all(rb.num_rows <= 64 for rb in rbs)
    rows = list(df.rows())
    assert len(rows) == df.collect().num_rows
    assert all(isinstance(r, tuple) and len(r) == 2 for r in rows)


def test_rows_to_batch_round_trip():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.rows import (
        batch_to_rows,
        rows_to_batch,
    )

    schema = T.Schema([T.Field("i", T.LONG, True),
                       T.Field("s", T.STRING, True)])
    rows = [(1, "a"), (None, "β"), (3, None)]
    b = rows_to_batch(rows, schema)
    assert list(batch_to_rows(b)) == rows
    # dict form
    b2 = rows_to_batch([{"i": 5, "s": "x"}], schema)
    assert list(batch_to_rows(b2)) == [(5, "x")]


@pytest.mark.slow
def test_plugin_lifecycle():
    from spark_rapids_tpu.plugin import TpuPlugin, frontend

    p = TpuPlugin.get_or_create()
    s = p.session()
    out = s.create_dataframe(pa.table({"x": pa.array([1, 2, 3])})) \
        .agg((sum_(col("x")), "s")).collect()
    assert out.to_pydict()["s"] == [6]
    p.shutdown()
    assert p._closed
    # a new plugin instance comes up cleanly after shutdown
    p2 = TpuPlugin.get_or_create()
    assert p2 is not p
    s2 = p2.session("native")
    assert s2.create_dataframe(pa.table({"x": pa.array([4])})) \
        .collect().num_rows == 1
    with pytest.raises(KeyError):
        frontend("no-such-frontend")
