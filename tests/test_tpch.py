"""TPC-H milestone queries as differential tests (BASELINE.md configs
#1-#3): q1 (wide aggregate), q3 (3-way join + agg + top-k), q6 (filter +
grand agg), q17 (agg-subquery join).  These exercise the
join+exchange+agg compositions the engine must keep correct at every
commit (ref: integration_tests tpch/tpcds suites,
src/main/python/tpch_test.py)."""

from __future__ import annotations

import math
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.execs.sort import SortKey
from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.session import (
    TpuSession,
    avg,
    col,
    count_star,
    sum_,
)

pytestmark = pytest.mark.slow  # TPC/fuzz/stress tier


SF = 0.002  # ~12k lineitem rows: fast but multi-batch when batch conf drops
N_LINE = int(6_000_000 * SF)
N_ORDERS = int(1_500_000 * SF)
N_CUST = int(150_000 * SF)
N_PART = int(200_000 * SF)


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    """Tiny TPC-H-shaped dataset written as Parquet (no nulls, like the
    real spec) with enough key skew to make joins/groups non-trivial."""
    d = tmp_path_factory.mktemp("tpch")
    rng = np.random.default_rng(1234)

    lineitem = pa.table({
        "l_orderkey": rng.integers(1, N_ORDERS + 1, N_LINE),
        "l_partkey": rng.integers(1, N_PART + 1, N_LINE),
        "l_quantity": rng.integers(1, 51, N_LINE).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900, 105000, N_LINE), 2),
        "l_discount": rng.integers(0, 11, N_LINE) / 100.0,
        "l_tax": rng.integers(0, 9, N_LINE) / 100.0,
        "l_returnflag": pa.array(
            [["A", "N", "R"][i] for i in rng.integers(0, 3, N_LINE)]),
        "l_linestatus": pa.array(
            [["O", "F"][i] for i in rng.integers(0, 2, N_LINE)]),
        "l_shipdate": rng.integers(8000, 11000, N_LINE),
    })
    orders = pa.table({
        "o_orderkey": np.arange(1, N_ORDERS + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, N_CUST + 1, N_ORDERS),
        "o_orderdate": rng.integers(8000, 11000, N_ORDERS),
        "o_shippriority": rng.integers(0, 2, N_ORDERS),
    })
    customer = pa.table({
        "c_custkey": np.arange(1, N_CUST + 1, dtype=np.int64),
        "c_mktsegment": pa.array(
            [["BUILDING", "MACHINERY", "HOUSEHOLD"][i]
             for i in rng.integers(0, 3, N_CUST)]),
    })
    part = pa.table({
        "p_partkey": np.arange(1, N_PART + 1, dtype=np.int64),
        "p_brand": pa.array(
            [f"Brand#{i}" for i in rng.integers(1, 6, N_PART)]),
        "p_container": pa.array(
            [["JUMBO BOX", "MED BAG", "SM PKG"][i]
             for i in rng.integers(0, 3, N_PART)]),
    })
    paths = {}
    for name, t in [("lineitem", lineitem), ("orders", orders),
                    ("customer", customer), ("part", part)]:
        p = str(d / f"{name}.parquet")
        pq.write_table(t, p, row_group_size=max(N_LINE // 4, 1024))
        paths[name] = p
    return paths


@pytest.fixture
def session():
    return TpuSession()


def assert_rows_close(got: pa.Table, want: pa.Table, n_keys: int,
                      rel: float = 1e-9) -> None:
    """Match rows on the first n_keys columns (must be exact), then
    require floats close to `rel` — float aggregates legitimately differ
    in the last bits between reduction orders."""
    assert got.schema.names == want.schema.names, \
        (got.schema.names, want.schema.names)
    assert got.num_rows == want.num_rows, (got.num_rows, want.num_rows)

    def keyed(t):
        rows = list(zip(*[c.to_pylist() for c in t.columns])) \
            if t.num_columns else []
        return sorted(rows, key=lambda r: tuple(map(repr, r[:n_keys])))

    for g, w in zip(keyed(got), keyed(want)):
        assert g[:n_keys] == w[:n_keys], (g, w)
        for a, b in zip(g[n_keys:], w[n_keys:]):
            if isinstance(a, float) and isinstance(b, float):
                assert math.isclose(a, b, rel_tol=rel, abs_tol=1e-6), \
                    (g, w)
            else:
                assert a == b, (g, w)


def q1(session, paths):
    qty, price = col("l_quantity"), col("l_extendedprice")
    disc, tax = col("l_discount"), col("l_tax")
    return (session.read_parquet(paths["lineitem"])
            .where(col("l_shipdate") <= lit(10000))
            .group_by(col("l_returnflag"), col("l_linestatus"))
            .agg((sum_(qty), "sum_qty"),
                 (sum_(price), "sum_base_price"),
                 (sum_(price * (lit(1.0) - disc)), "sum_disc_price"),
                 (sum_(price * (lit(1.0) - disc) * (lit(1.0) + tax)),
                  "sum_charge"),
                 (avg(qty), "avg_qty"),
                 (avg(price), "avg_price"),
                 (avg(disc), "avg_disc"),
                 (count_star(), "count_order")))


def test_q1(session, tpch):
    df = q1(session, tpch)
    got = df.collect(engine="tpu")
    want = df.collect(engine="cpu")
    assert want.num_rows == 6  # 3 flags x 2 statuses
    assert_rows_close(got, want, n_keys=2)


def test_q1_small_batches(session, tpch):
    # multi-batch per partition: the partial->exchange->final agg path
    session.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1 << 10)
    df = q1(session, tpch)
    assert_rows_close(df.collect(engine="tpu"),
                      df.collect(engine="cpu"), n_keys=2)


def q3(session, paths):
    price, disc = col("l_extendedprice"), col("l_discount")
    cust = (session.read_parquet(paths["customer"])
            .where(col("c_mktsegment").eq(lit("BUILDING"))))
    orders = (session.read_parquet(paths["orders"])
              .where(col("o_orderdate") < lit(9200)))
    li = (session.read_parquet(paths["lineitem"])
          .where(col("l_shipdate") > lit(9200)))
    j = (cust.join(orders, left_on=[col("c_custkey")],
                   right_on=[col("o_custkey")])
         .join(li, left_on=[col("o_orderkey")],
               right_on=[col("l_orderkey")]))
    return (j.group_by(col("l_orderkey"), col("o_orderdate"),
                       col("o_shippriority"))
            .agg((sum_(price * (lit(1.0) - disc)), "revenue")))


def test_q3(session, tpch):
    df = q3(session, tpch)
    got = df.collect(engine="tpu")
    want = df.collect(engine="cpu")
    assert want.num_rows > 50  # non-trivial join survivors
    assert_rows_close(got, want, n_keys=3)


def test_q3_topk(session, tpch):
    # revenue desc, orderdate asc, limit 10 — the classic q3 tail;
    # random float revenues are distinct so the order is deterministic
    df = q3(session, tpch).order_by(
        SortKey(col("revenue"), descending=True, nulls_last=True),
        SortKey(col("o_orderdate"), descending=False)).limit(10)
    got = df.collect(engine="tpu").to_pydict()
    want = df.collect(engine="cpu").to_pydict()
    assert got["l_orderkey"] == want["l_orderkey"]
    for a, b in zip(got["revenue"], want["revenue"]):
        assert math.isclose(a, b, rel_tol=1e-9)


def test_q6(session, tpch):
    ship, disc = col("l_shipdate"), col("l_discount")
    qty, price = col("l_quantity"), col("l_extendedprice")
    df = (session.read_parquet(tpch["lineitem"])
          .where((ship >= lit(8766)) & (ship < lit(9131))
                 & (disc >= lit(0.05)) & (disc <= lit(0.07))
                 & (qty < lit(24.0)))
          .agg((sum_(price * disc), "revenue")))
    got = df.collect(engine="tpu").to_pydict()["revenue"][0]
    want = df.collect(engine="cpu").to_pydict()["revenue"][0]
    assert math.isclose(got, want, rel_tol=1e-9)


def test_q17(session, tpch):
    """Correlated avg-quantity subquery as an aggregate self-join."""
    li = session.read_parquet(tpch["lineitem"])
    part = (session.read_parquet(tpch["part"])
            .where(col("p_brand").eq(lit("Brand#2"))
                   & col("p_container").eq(lit("JUMBO BOX"))))
    per_part_avg = (li.group_by(col("l_partkey"))
                    .agg((avg(col("l_quantity")), "aq"))
                    .select(col("l_partkey").alias("ap_key"), col("aq")))
    j = (li.join(part, left_on=[col("l_partkey")],
                 right_on=[col("p_partkey")])
         .join(per_part_avg, left_on=[col("l_partkey")],
               right_on=[col("ap_key")])
         .where(col("l_quantity") < col("aq") * lit(0.2))
         .agg((sum_(col("l_extendedprice")), "s")))
    df = j.select((col("s") / lit(7.0)).alias("avg_yearly"))
    got = df.collect(engine="tpu").to_pydict()["avg_yearly"][0]
    want = df.collect(engine="cpu").to_pydict()["avg_yearly"][0]
    # the filter must actually select something for this to mean much
    assert want is not None and want > 0
    assert math.isclose(got, want, rel_tol=1e-9), (got, want)


def test_q1_explain_all_tpu(session, tpch):
    """The whole q1 plan should run on the TPU engine — no fallbacks."""
    df = q1(session, tpch)
    tree = df.explain()
    assert "CpuFallback" not in tree, tree
