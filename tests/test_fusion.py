"""Whole-stage program fusion + buffer donation (docs/fusion.md).

The contract under test:

- FUSED-CHAIN PARITY: scan->filter->project->agg chains compiled into
  single XLA programs answer bit-for-bit like the unfused engine
  (`spark.rapids.tpu.sql.fusion.enabled=false`), across encoded and
  plain batches, ANSI on/off, and null-heavy data;
- DONATION IDENTITY: `fusion.donation.enabled` is a pure HBM
  optimization — digests identical on/off, and the consumed-state
  bookkeeping (EncodedBatch.consumed, SpillableBatch.mark_consumed)
  never lets a donated buffer be re-parked, re-split or re-spilled,
  including under a --chaos-style exec.batch fault inside the
  split-retry ladder;
- JIT-KEY STABILITY: identical collects mint no new programs
  (re-key stability), and the warm pass of the q1-shaped smoke stays
  within the conf dispatch budget with zero jit misses — THE
  warm-dispatch-budget acceptance test
  (tools/bench_smoke.run_fusion_smoke, wired into tier-1 here).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.eventlog import table_digest
from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.session import TpuSession, col, count_star, sum_

FUSION_KEY = "spark.rapids.tpu.sql.fusion.enabled"
DONATE_KEY = "spark.rapids.tpu.sql.fusion.donation.enabled"


def _quiet_conf(conf, batch_rows=2048):
    """Deterministic dispatch accounting: pipeline + speculation off,
    small batches so streams actually stream."""
    conf.set("spark.rapids.tpu.sql.pipeline.enabled", False)
    conf.set("spark.rapids.tpu.sql.speculation.enabled", False)
    conf.set("spark.rapids.tpu.sql.batchSizeRows", batch_rows)
    conf.set("spark.rapids.tpu.sql.shuffle.partitions", 1)


def _write_lineitem(d, n=8192, null_heavy=False):
    rng = np.random.default_rng(0xF0510)
    ship = rng.integers(8766, 10957, n).astype(np.int32)
    qty = rng.integers(1, 51, n).astype(np.int64)
    key = rng.integers(0, 6, n).astype(np.int64)
    cols = {
        "l_shipdate": ship,
        "l_key": key,
        "l_quantity": qty,
        "l_price": rng.integers(900, 105000, n).astype(np.int64),
    }
    t = pa.table(cols)
    if null_heavy:
        mask = rng.random(n) < 0.6
        arrs = dict(cols)
        arrs["l_quantity"] = pa.array(
            [None if m else int(v) for m, v in zip(mask, qty)],
            type=pa.int64())
        t = pa.table(arrs)
    p = os.path.join(d, "li.parquet")
    pq.write_table(t, p, row_group_size=max(n // 4, 1))
    return p


def _q(session, path):
    """scan -> filter -> project -> agg: the whole-stage chain."""
    return (session.read_parquet(path)
            .where(col("l_shipdate") <= lit(10471))
            .select(col("l_key"),
                    (col("l_quantity") * lit(2)).alias("q2"),
                    col("l_price"))
            .group_by(col("l_key"))
            .agg((sum_(col("q2")), "sq"),
                 (sum_(col("l_price")), "sp"),
                 (count_star(), "n"))
            .order_by(col("l_key")))


def _collect_digest(path, **conf_over):
    conf = get_conf()
    _quiet_conf(conf)
    for k, v in conf_over.items():
        conf.set(k, v)
    return table_digest(_q(TpuSession(), path).collect(engine="tpu"))


# ------------------------------------------------------------------ #
# fused-chain parity across shapes
# ------------------------------------------------------------------ #


@pytest.mark.parametrize("null_heavy", [False, True],
                         ids=["dense", "null-heavy"])
def test_fusion_on_off_digest_identity(tmp_path, null_heavy):
    """Encoded scan batches through the fused decode+filter+project+
    update program answer exactly like the unfused per-exec engine."""
    p = _write_lineitem(str(tmp_path), null_heavy=null_heavy)
    on = _collect_digest(p, **{FUSION_KEY: True})
    off = _collect_digest(p, **{FUSION_KEY: False})
    assert on == off


def test_fusion_parity_plain_batches():
    """In-memory (non-parquet) sources feed PLAIN device batches into
    the same chain — parity must hold without the wire decode."""
    conf = get_conf()
    _quiet_conf(conf, batch_rows=512)
    rng = np.random.default_rng(7)
    n = 2048
    t = pa.table({
        "k": rng.integers(0, 5, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })

    def run():
        s = TpuSession()
        return table_digest(
            (s.create_dataframe(t)
             .where(col("v") > lit(10))
             .select(col("k"), (col("v") + lit(1)).alias("v1"))
             .group_by(col("k")).agg((sum_(col("v1")), "sv"),
                                     (count_star(), "n"))
             .order_by(col("k"))).collect(engine="tpu"))

    conf.set(FUSION_KEY, True)
    on = run()
    conf.set(FUSION_KEY, False)
    assert on == run()


def test_fusion_parity_ansi(tmp_path):
    """ANSI mode blocks the agg absorption (error polling needs its
    own driver) but the standalone chains still fuse — results and
    ANSI error behavior must match the unfused engine."""
    p = _write_lineitem(str(tmp_path))
    ansi = "spark.rapids.tpu.sql.ansi.enabled"
    on = _collect_digest(p, **{FUSION_KEY: True, ansi: True})
    off = _collect_digest(p, **{FUSION_KEY: False, ansi: True})
    assert on == off


def test_donation_digest_identity(tmp_path):
    """Donation is a pure HBM optimization: digests identical with
    fusion.donation.enabled on and off."""
    p = _write_lineitem(str(tmp_path))
    base = _collect_digest(p, **{FUSION_KEY: True, DONATE_KEY: False})
    donated = _collect_digest(p, **{FUSION_KEY: True,
                                    DONATE_KEY: True})
    assert base == donated


# ------------------------------------------------------------------ #
# consumed-state bookkeeping
# ------------------------------------------------------------------ #


def test_encoded_batch_consumed_state():
    """A consumed wire batch refuses decode_now/bisection and the
    memoized output resumes re-runs (run_consuming)."""
    from spark_rapids_tpu.columnar.transfer import (
        ConsumedBatchError,
        EncodedBatch,
        encode_batch,
        run_consuming,
    )
    from spark_rapids_tpu.execs.retry import _batch_rows, is_retryable

    t = pa.table({"a": np.arange(64, dtype=np.int64)})
    from spark_rapids_tpu import types as T

    schema = T.Schema([T.Field("a", T.LONG, True)])
    eb = encode_batch(list(t.columns), schema, 64)
    assert isinstance(eb, EncodedBatch) and not eb.consumed

    calls = []

    def fake_program(b):
        calls.append(b)
        return "OUT"

    assert run_consuming(fake_program, eb) == "OUT"
    assert eb.consumed
    # re-run RESUMES from the memoized output, no re-execution
    assert run_consuming(fake_program, eb) == "OUT"
    assert len(calls) == 1
    # the ladder refuses to size/split a consumed batch...
    assert _batch_rows(eb) is None
    # ...and an eager decode fails fast, non-retryably
    with pytest.raises(ConsumedBatchError) as ei:
        eb.decode_now()
    assert not is_retryable(ei.value)


def test_run_consuming_program_death_is_fatal():
    """A donated program dying mid-execution leaves the input gone and
    nothing memoized: the re-run must fail fast (non-retryable), not
    burn the spill/split ladder on freed HBM."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.transfer import (
        ConsumedBatchError,
        encode_batch,
        run_consuming,
    )
    from spark_rapids_tpu.execs.retry import is_retryable

    t = pa.table({"a": np.arange(16, dtype=np.int64)})
    schema = T.Schema([T.Field("a", T.LONG, True)])
    eb = encode_batch(list(t.columns), schema, 16)

    def dying(b):
        raise RuntimeError("RESOURCE_EXHAUSTED: boom")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        run_consuming(dying, eb)
    assert eb.consumed and eb.donated_out is None
    with pytest.raises(ConsumedBatchError) as ei:
        run_consuming(dying, eb)
    assert not is_retryable(ei.value)


def test_cached_jit_donate_spec_validation():
    """Malformed donate= specs fail loud AT the chokepoint — in
    particular donate=True (bool IS int in Python) must not silently
    normalize to argnum 1 and donate the wrong buffer."""
    from spark_rapids_tpu.execs.jit_cache import _validate_donate

    assert _validate_donate((0,)) == (0,)
    assert _validate_donate(0) == (0,)
    assert _validate_donate(()) == ()
    for bad in (True, (True,), (-1,), (0, 0), ("0",)):
        with pytest.raises(TypeError):
            _validate_donate(bad)


def test_spillable_batch_mark_consumed():
    """mark_consumed un-registers WITHOUT deleting: the store can
    never spill (use-after-free) a donated buffer, rollback sweeps
    (unpin/close) become no-ops, and get() fails fast."""
    import jax.numpy as jnp

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.columnar.transfer import ConsumedBatchError
    from spark_rapids_tpu.memory.store import BufferStore

    store = BufferStore(device_budget=1 << 30, host_budget=1 << 30)
    data = jnp.arange(8, dtype=jnp.int64)
    valid = jnp.ones(8, jnp.bool_)
    b = ColumnarBatch([Column(data, valid, T.LONG)], 8,
                      T.Schema([T.Field("a", T.LONG, False)]))
    h = store.register(b)
    used = store.device_used
    assert used > 0
    h.mark_consumed()
    assert h.consumed
    assert store.device_used == 0  # un-registered, accounting settled
    assert store.spill_all_unpinned() == 0  # nothing left to spill
    h.unpin()  # rollback-sweep no-ops
    h.close()
    h.mark_consumed()  # idempotent
    with pytest.raises(ConsumedBatchError):
        h.get()
    # every handle surface fails TYPED on a consumed buffer (a raw
    # KeyError would dodge the retry classifier's fail-fast contract)
    with pytest.raises(ConsumedBatchError):
        h.get_host()
    with pytest.raises(ConsumedBatchError):
        _ = h.tier
    with pytest.raises(ConsumedBatchError):
        _ = h.nbytes
    # the donated arrays themselves are untouched (XLA owns them now;
    # on CPU donation is a no-op so they are simply still alive)
    assert int(data.sum()) == 28
    store.close()


def test_spilled_donated_memo_repair_and_fail_fast():
    """A donated update output registered UNPINNED with the spill
    store may be spilled by pressure — the spill deletes the device
    arrays the memoized `donated_out` references (the store restores
    into a NEW batch object, never the memo).  Two contracts: (1) a
    rollback about to drop the registration repairs the memo through
    the handle, so the re-run's resume hands downstream a LIVE batch
    with the same data; (2) a dead memo with no surviving copy fails
    fast with ConsumedBatchError, not an opaque deleted-array crash
    deep in the next merge."""
    import jax.numpy as jnp

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.columnar.transfer import (
        ConsumedBatchError,
        EncodedBatch,
        memo_is_dead,
        repair_donated_memo,
        run_consuming,
    )
    from spark_rapids_tpu.execs.retry import is_retryable
    from spark_rapids_tpu.memory.store import BufferStore

    schema = T.Schema([T.Field("a", T.LONG, False)])

    def _part():
        return ColumnarBatch(
            [Column(jnp.arange(8, dtype=jnp.int64),
                    jnp.ones(8, jnp.bool_), T.LONG)], 8, schema)

    store = BufferStore(device_budget=1 << 30, host_budget=1 << 30)
    eb = EncodedBatch([], ("p",), schema, num_rows=8)
    out = _part()
    assert run_consuming(lambda _: out, eb) is out
    h = store.register(out)  # retire's unpinned registration
    assert not memo_is_dead(eb.donated_out)
    assert repair_donated_memo(eb, h) is False  # live memo: no-op
    assert store.spill_all_unpinned() == 1  # pressure strikes
    assert memo_is_dead(eb.donated_out)
    # (1) rollback repair: restore through the handle, re-memoize
    assert repair_donated_memo(eb, h) is True
    assert not memo_is_dead(eb.donated_out)
    resumed = run_consuming(lambda _: None, eb)
    assert resumed is eb.donated_out
    assert [int(x) for x in
            np.asarray(resumed.columns[0].data)] == list(range(8))
    # the rollback sweep then drops the registration: the restored
    # arrays survive (store.remove never deletes device buffers)
    h.close()
    assert not memo_is_dead(eb.donated_out)
    # (2) dead memo, host copy already dropped: fail fast, typed,
    # non-retryable — never hand freed buffers downstream
    eb2 = EncodedBatch([], ("p",), schema, num_rows=8)
    out2 = _part()
    run_consuming(lambda _: out2, eb2)
    h2 = store.register(out2)
    assert store.spill_all_unpinned() == 1
    h2.close()
    with pytest.raises(ConsumedBatchError, match="spilled") as ei:
        run_consuming(lambda _: None, eb2)
    assert not is_retryable(ei.value)
    store.close()


# ------------------------------------------------------------------ #
# split-retry under chaos with donation on
# ------------------------------------------------------------------ #


def test_chaos_exec_batch_with_donation(tmp_path):
    """THE donated-ladder acceptance: an exec.batch fault firing
    inside the fused+donated scan->agg unit must recover to a
    bit-identical answer without ever touching a consumed buffer —
    pre-consumption faults bisect the intact batch, post-consumption
    re-runs resume from the memoized update output."""
    from spark_rapids_tpu.robustness import faults

    p = _write_lineitem(str(tmp_path))
    clean = _collect_digest(p, **{FUSION_KEY: True, DONATE_KEY: True})
    conf = get_conf()
    conf.set(DONATE_KEY, True)
    try:
        # nth=1,times=2: the FIRST ladder unit eats two faults — the
        # first re-run happens with the dispatch-side update already
        # consumed (memoized-resume path), the second drives the
        # bisection decision against a consumed batch (must skip the
        # split, not decode freed buffers)
        faults.install("exec.batch:nth=1,times=2", forced=True)
        chaotic = table_digest(
            _q(TpuSession(), p).collect(engine="tpu"))
    finally:
        faults.disarm()
    assert chaotic == clean

    # and a fault schedule that also bisects an INTACT batch
    # (initial_error path: dispatch-time failure before consumption)
    try:
        faults.install("exec.batch:nth=2,times=2", forced=True)
        chaotic2 = table_digest(
            _q(TpuSession(), p).collect(engine="tpu"))
    finally:
        faults.disarm()
    assert chaotic2 == clean


# ------------------------------------------------------------------ #
# jit-key bucketing stability
# ------------------------------------------------------------------ #


def test_rekey_stability_identical_collects(tmp_path):
    """Two identical collects mint ZERO new compiled programs: the
    program census (per-tag distinct-program counts) is unchanged and
    the second collect has no jit-cache misses — per-batch offsets,
    live counts and dictionary cardinalities must ride as runtime
    args / bucketed aux, never in the keys."""
    from spark_rapids_tpu.execs import jit_cache

    p = _write_lineitem(str(tmp_path))
    conf = get_conf()
    _quiet_conf(conf)
    conf.set(FUSION_KEY, True)
    session = TpuSession()
    _q(session, p).collect(engine="tpu")
    census0 = jit_cache.program_census()
    j0 = jit_cache.cache_stats()
    r = _q(session, p).collect(engine="tpu")
    j1 = jit_cache.cache_stats()
    census1 = jit_cache.program_census()
    assert j1["misses"] - j0["misses"] == 0, (
        f"identical collect re-compiled: census {census0} -> "
        f"{census1}")
    assert census1 == census0
    assert r.num_rows > 0


def test_capacity_buckets_share_programs(tmp_path):
    """Different row counts in the same capacity bucket share one
    compiled program; a different bucket compiles, a repeat of the
    first bucket hits (capacity bucketing = the jax shape key)."""
    from spark_rapids_tpu.execs import jit_cache

    conf = get_conf()
    _quiet_conf(conf, batch_rows=1 << 20)
    conf.set(FUSION_KEY, True)
    rng = np.random.default_rng(3)

    def run(n):
        t = pa.table({
            "k": rng.integers(0, 4, n).astype(np.int64),
            "v": rng.integers(0, 9, n).astype(np.int64),
        })
        s = TpuSession()
        return (s.create_dataframe(t)
                .where(col("v") > lit(2))
                .group_by(col("k")).agg((sum_(col("v")), "sv"))
                .order_by(col("k"))).collect(engine="tpu")

    run(1000)  # capacity bucket 1024
    j0 = jit_cache.cache_stats()
    run(900)  # same bucket: different live count, same programs
    j1 = jit_cache.cache_stats()
    assert j1["misses"] == j0["misses"], \
        "same capacity bucket re-compiled"


# ------------------------------------------------------------------ #
# the fusion smoke: dispatch budget + savings, wired into tier-1
# ------------------------------------------------------------------ #


def test_fusion_smoke():
    """THE warm-dispatch-budget acceptance test: the q1-shaped smoke's
    warm pass has 0 jit misses, strictly fewer ledger dispatches than
    the unfused baseline, digest equality across fusion/donation
    on/off, and a warm dispatch count within the conf budget."""
    from spark_rapids_tpu.tools.bench_smoke import run_fusion_smoke

    out = run_fusion_smoke()
    assert out["fusion_warm_jit_misses"] == 0
    assert out["fusion_warm_dispatches"] \
        < out["fusion_unfused_dispatches"]
    assert out["fusion_chains"] >= 1
    assert out["fusion_saved_dispatches"] >= 1
    # the budget gate ran inside the smoke; re-assert the headroom
    # here so budget regressions name this test
    from spark_rapids_tpu.execs.base import warm_dispatch_budget

    assert out["fusion_warm_dispatches"] <= warm_dispatch_budget()


def test_warm_dispatch_budget_gate_trips():
    """The budget gate has teeth: an absurdly tight budget makes the
    smoke fail with the budget assertion (not some other error)."""
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.fusion.warmDispatchBudget", 1)
    from spark_rapids_tpu.tools.bench_smoke import run_fusion_smoke

    with pytest.raises(AssertionError, match="warmDispatchBudget"):
        run_fusion_smoke()


def test_warm_budget_zero_disables_gate():
    """warmDispatchBudget=0 is the documented escape hatch ('0
    disables the gate'): BOTH halves of the bench gate — the dispatch
    count AND the warm jit-miss assert — are off, so environments
    where warm recompiles are expected (backend bring-up) can still
    run rounds."""
    import bench

    conf = get_conf()
    bad = {"q1_jit_misses": 3, "q1_dispatches": 10_000}
    conf.set("spark.rapids.tpu.sql.fusion.warmDispatchBudget", 0)
    bench._assert_warm_budget("q1", bad)  # disabled: no assert
    conf.set("spark.rapids.tpu.sql.fusion.warmDispatchBudget", 8)
    with pytest.raises(AssertionError, match="re-compiled"):
        bench._assert_warm_budget("q1", bad)
    with pytest.raises(AssertionError, match="warmDispatchBudget"):
        bench._assert_warm_budget("q1", {"q1_jit_misses": 0,
                                         "q1_dispatches": 10_000})


# ------------------------------------------------------------------ #
# explain() integration
# ------------------------------------------------------------------ #


def test_explain_fusion_section(tmp_path):
    """explain() gains a "Fusion:" section naming the fused chains;
    with fusion disabled it says so instead."""
    p = _write_lineitem(str(tmp_path))
    conf = get_conf()
    _quiet_conf(conf)
    conf.set(FUSION_KEY, True)
    s = TpuSession()
    text = _q(s, p).explain()
    assert "Fusion:" in text
    assert "one program" in text
    conf.set(FUSION_KEY, False)
    text_off = _q(TpuSession(), p).explain()
    assert "Fusion:" in text_off and "disabled" in text_off


def test_explain_fusion_donation_annotated(tmp_path):
    p = _write_lineitem(str(tmp_path))
    conf = get_conf()
    _quiet_conf(conf)
    conf.set(FUSION_KEY, True)
    conf.set(DONATE_KEY, True)
    text = _q(TpuSession(), p).explain()
    assert "inputs donated" in text
