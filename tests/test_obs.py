"""Live ops plane (spark_rapids_tpu/obs/, docs/ops_plane.md).

Covers the PR's acceptance surface:
- disabled by default: no thread, no socket, no registry entry — a
  collect under the default conf pays one conf read and nothing else;
- the bench_smoke ops contract wired into tier-1: a real HTTP scrape
  of /metrics parses as OpenMetrics and parity-matches the in-process
  eventlog counters_snapshot, /queries empties after the query, and
  the owning conf's off leaves no tpu-obs-* thread and a refused
  socket;
- live registry mid-stream: an in-flight streamed query is visible
  under /queries and /queries/<id> (rendered plan, batches-so-far)
  while the stream is being drained, and deregisters on exhaustion;
- the SLO watchdog loop end to end: a breached wall budget emits
  `slo` event-log records (strict-schema validated), loads back
  through tools/history into ApplicationInfo.slo, raises the HC016
  health finding, and serves at /slo.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import obs
from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.obs.slo import WATCHDOG
from spark_rapids_tpu.session import TpuSession, col, sum_

OBS_ENABLED = "spark.rapids.tpu.obs.enabled"
OBS_PORT = "spark.rapids.tpu.obs.port"
SLO_WALL = "spark.rapids.tpu.obs.slo.wallBudgetMs"
SLO_INTERVAL = "spark.rapids.tpu.obs.slo.checkIntervalMs"
EL_ENABLED = "spark.rapids.tpu.eventLog.enabled"
EL_DIR = "spark.rapids.tpu.eventLog.dir"


def _obs_threads() -> list[str]:
    return [t.name for t in threading.enumerate()
            if t.name.startswith("tpu-obs")]


def _table(n: int = 4096, seed: int = 0x0B5) -> pa.Table:
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 16, n).astype(np.int64),
        "v": rng.random(n),
    })


def _agg(session: TpuSession, t: pa.Table):
    return (session.create_dataframe(t)
            .group_by(col("k"))
            .agg((sum_(col("v")), "sv"))
            .order_by(col("k")))


def _get_json(url: str):
    return json.loads(
        urllib.request.urlopen(url, timeout=10).read().decode())


def test_disabled_by_default_no_thread_no_registry():
    """The whole disabled-path cost is one conf read: a collect under
    the default conf must leave the plane off, the registry empty and
    no tpu-obs-* thread alive."""
    session = TpuSession()
    result = _agg(session, _table()).collect(engine="tpu")
    assert result.num_rows == 16
    assert not obs.is_enabled()
    assert obs.plane().port is None
    assert obs.REGISTRY.count() == 0
    assert not obs.REGISTRY.enabled
    assert _obs_threads() == []


def test_ops_smoke_tier1():
    """The bench_smoke contract in the fast tier: scrape == snapshot
    parity, registry empties, conf off leaves no thread/socket."""
    from spark_rapids_tpu.tools.bench_smoke import run_ops_smoke

    out = run_ops_smoke()
    assert out["ops_rows"] == 16
    assert out["ops_scrape_families"] > 0
    assert out["ops_parity_counters"] > 0
    assert out["ops_stopped_clean"] is True


def test_live_registry_visible_mid_stream():
    """An in-flight streamed query shows under /queries with its
    rendered plan and batches-so-far, then deregisters when the
    stream drains (the /queries/<id> 404 afterwards)."""
    conf = get_conf()
    saved_batch = conf.get("spark.rapids.tpu.sql.batchSizeRows")
    obs.start(port=0)  # forced: survives the sessions' sync_conf
    try:
        conf.set("spark.rapids.tpu.sql.batchSizeRows", 512)
        session = TpuSession(tenant="streamer")
        pq = session.prepare(_agg(session, _table()))
        gen = pq.execute_stream()
        first = next(gen)  # at least one batch retired, still in flight
        assert first.num_rows > 0
        assert obs.REGISTRY.count() == 1
        snap = obs.REGISTRY.snapshot()
        assert len(snap) == 1
        entry = snap[0]
        qid = entry["query_id"]
        assert entry["tenant"] == "streamer"
        assert entry["batches"] >= 1
        assert entry["elapsed_ms"] >= 0
        assert "plan" not in entry  # list view elides plans

        base = f"http://127.0.0.1:{obs.plane().port}"
        wire = _get_json(base + "/queries")
        assert [e["query_id"] for e in wire] == [qid]
        one = _get_json(base + f"/queries/{qid}")
        assert one["query_id"] == qid
        assert one["plan"], "detail view is missing the rendered plan"
        assert one["plan_hash"]

        rest = list(gen)  # drain: the epilogue deregisters
        assert first.num_rows + sum(b.num_rows for b in rest) == 16
        assert obs.REGISTRY.count() == 0
        assert _get_json(base + "/queries") == []
        try:
            urllib.request.urlopen(base + f"/queries/{qid}",
                                   timeout=10)
            raise AssertionError("finished query still served")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # liveness probe while we're here
        body = urllib.request.urlopen(
            base + "/healthz", timeout=10).read().decode()
        assert body == "ok\n"
    finally:
        conf.set("spark.rapids.tpu.sql.batchSizeRows", saved_batch)
        obs.stop()
    assert _obs_threads() == []


def test_scrape_under_storm_monotone_and_zero_impact():
    """The bench.py --sessions scrape arm's contract in the fast
    tier: /metrics scraped concurrently with running queries never
    shows a monotone counter stepping backwards, and the scraped
    queries' digests stay bit-identical to the obs-off reference."""
    from spark_rapids_tpu.eventlog import MONOTONIC_COUNTERS, \
        table_digest
    from spark_rapids_tpu.obs import metrics as om

    t = _table()
    ref = table_digest(
        _agg(TpuSession(), t).collect(engine="tpu"))  # plane off
    assert not obs.is_enabled()

    obs.start(port=0)
    try:
        stop = threading.Event()
        violations: list = []
        scrapes = [0]
        digests: list = []
        errors: list = []

        def scraper() -> None:
            base = f"http://127.0.0.1:{obs.plane().port}"
            prev: dict = {}
            while True:
                try:
                    parsed = om.parse_openmetrics(
                        urllib.request.urlopen(
                            base + "/metrics",
                            timeout=10).read().decode())
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
                    return
                for key in MONOTONIC_COUNTERS:
                    v = om.scrape_value(
                        parsed, om.counter_metric_name(key))
                    if v is None:
                        continue
                    if key in prev and v < prev[key]:
                        violations.append((key, prev[key], v))
                    prev[key] = v
                scrapes[0] += 1
                if stop.wait(0.005):
                    return

        def worker() -> None:
            try:
                s = TpuSession()
                for _ in range(2):
                    digests.append(table_digest(
                        _agg(s, t).collect(engine="tpu")))
            except BaseException as e:  # noqa: BLE001 — reported
                errors.append(repr(e))

        ths = [threading.Thread(target=worker) for _ in range(2)]
        sth = threading.Thread(target=scraper)
        sth.start()
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        stop.set()
        sth.join()
        assert not errors, errors
        assert scrapes[0] >= 1, "scraper never completed a scrape"
        assert not violations, (
            f"monotone counter stepped backwards: {violations}")
        assert set(digests) == {ref}, \
            "scraping changed query results vs the obs-off reference"
    finally:
        obs.stop()
    assert _obs_threads() == []


def test_slo_breach_lands_in_eventlog_and_hc016(tmp_path):
    """The watchdog loop end to end: an impossible wall budget
    (0.001ms) must breach on the first completed query; the breach is
    returned by evaluate_now(), appended to the session event log as a
    strict-schema-valid `slo` record, served at /slo, loaded back by
    tools/history and flagged by the HC016 health rule."""
    from spark_rapids_tpu.eventlog.reader import iter_records
    from spark_rapids_tpu.tools.history import (
        health_check,
        load_application,
    )

    conf = get_conf()
    keys = (OBS_ENABLED, OBS_PORT, SLO_WALL, SLO_INTERVAL,
            EL_ENABLED, EL_DIR)
    saved = {k: conf.get(k) for k in keys}
    try:
        conf.set(OBS_ENABLED, True)
        conf.set(OBS_PORT, 0)
        conf.set(SLO_WALL, 0.001)  # every real query breaches
        # park the watchdog thread: the test drives evaluate_now()
        # itself, so breach counts stay deterministic
        conf.set(SLO_INTERVAL, 600000.0)
        conf.set(EL_ENABLED, True)
        conf.set(EL_DIR, str(tmp_path / "log"))
        session = TpuSession(tenant="slower")
        _agg(session, _table()).collect(engine="tpu")
        # reading events drains the snapshot worker (query record is
        # in the file before the breach record we emit next)
        assert session.history.events[-1].query_id is not None

        breaches = WATCHDOG.evaluate_now()
        assert breaches, "0.001ms budget did not breach"
        b = breaches[0]
        assert b["tenant"] == "slower"
        assert b["metric"] == "wall_p99_ms"
        assert b["observed_ms"] > b["budget_ms"] == 0.001

        snap = WATCHDOG.snapshot()
        assert snap["budgets"]["wall_p99_ms"] == 0.001
        assert snap["breach_count"] >= 1
        assert snap["tenants"]["slower"]["n"] >= 1
        wire = _get_json(
            f"http://127.0.0.1:{obs.plane().port}/slo")
        assert wire["breach_count"] >= 1
        assert wire["budgets"]["wall_p99_ms"] == 0.001

        # file surface: strict schema + history + HC016
        path = session.event_log_path
        recs = list(iter_records(path, strict=True))
        slo_recs = [r for r in recs if r["type"] == "slo"]
        assert slo_recs, "no slo record in the event log"
        assert slo_recs[0]["tenant"] == "slower"
        assert slo_recs[0]["metric"] == "wall_p99_ms"
        assert slo_recs[0]["observed_ms"] > 0.001

        app = load_application(path)
        assert app.slo, "history did not load the slo records"
        hc016 = [f for f in health_check(app) if f.rule == "HC016"]
        assert hc016, "HC016 did not fire on a breached run"
        assert hc016[0].severity == "warning"
        assert "tenant:slower" in hc016[0].query
    finally:
        for k, v in saved.items():
            conf.set(k, v)
        obs.sync_conf(conf)  # the owning conf's off stops the plane
        obs.stop()
        WATCHDOG.reset()
    assert _obs_threads() == []
