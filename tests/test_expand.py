"""Expand exec + grouping sets (rollup/cube) + count-distinct rewrite.

Coverage analog of the reference's Expand/distinct tests
(ref: GpuExpandExec.scala:67, hash_aggregate_test.py distinct cases)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import (
    TpuSession,
    col,
    count,
    count_distinct,
    sum_,
)
from tests.differential import assert_tpu_cpu_equal


@pytest.fixture
def session():
    return TpuSession()


@pytest.fixture
def sales(session):
    t = pa.table({
        "region": pa.array(["e", "e", "w", "w", "w", None], pa.string()),
        "product": pa.array(["a", "b", "a", "a", "b", "a"], pa.string()),
        "amount": pa.array([1.0, 2.0, 4.0, 8.0, 16.0, 32.0], pa.float64()),
    })
    return session.create_dataframe(t)


def test_rollup_hand_checked(sales):
    out = sales.rollup("region", "product").agg(
        (sum_(col("amount")), "s")).collect().to_pydict()
    rows = {(r, p): s for r, p, s in zip(out["region"], out["product"],
                                         out["s"])}
    # full detail
    assert rows[("e", "a")] == 1.0 and rows[("e", "b")] == 2.0
    assert rows[("w", "a")] == 12.0 and rows[("w", "b")] == 16.0
    assert rows[(None, "a")] == 32.0  # real NULL region, product level
    # region subtotals (product rolled up)
    assert rows[("e", None)] == 3.0
    assert rows[("w", None)] == 28.0
    # grand total
    assert rows[(None, None)] == 63.0 or (None, None) in rows
    # 5 detail groups + 3 region subtotals (e, w, NULL) + 1 grand = 9
    assert len(out["s"]) == 9


def test_rollup_matches_cpu(sales):
    assert_tpu_cpu_equal(sales.rollup("region", "product").agg(
        (sum_(col("amount")), "s"), (count(col("amount")), "c")))


def test_cube_matches_cpu(sales):
    df = sales.cube("region", "product").agg((sum_(col("amount")), "s"))
    assert_tpu_cpu_equal(df)
    out = df.collect().to_pydict()
    rows = list(zip(out["region"], out["product"], out["s"]))
    # cube adds product-only subtotals
    assert (None, "a", 45.0) in rows
    assert (None, "b", 18.0) in rows


def test_grouping_sets_explicit(session):
    t = pa.table({"a": pa.array([1, 1, 2], pa.int64()),
                  "b": pa.array([10, 20, 10], pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0], pa.float64())})
    df = session.create_dataframe(t).grouping_sets(
        [["a"], ["b"]], keys=["a", "b"]).agg((sum_(col("v")), "s"))
    out = df.collect().to_pydict()
    rows = set(zip(out["a"], out["b"], out["s"]))
    assert (1, None, 3.0) in rows and (2, None, 3.0) in rows
    assert (None, 10, 4.0) in rows and (None, 20, 2.0) in rows
    assert_tpu_cpu_equal(df)


def test_count_distinct_grouped(session):
    t = pa.table({
        "g": pa.array([1, 1, 1, 2, 2, 2, 2], pa.int64()),
        "x": pa.array([5, 5, 7, 1, None, 1, 2], pa.int64()),
    })
    df = session.create_dataframe(t).group_by(col("g")).agg(
        (count_distinct(col("x")), "d"))
    out = df.collect().to_pydict()
    assert dict(zip(out["g"], out["d"])) == {1: 2, 2: 2}
    assert_tpu_cpu_equal(df)


def test_count_distinct_grand(session):
    t = pa.table({"x": pa.array([1, 1, 2, None, 3, 3], pa.int64())})
    df = session.create_dataframe(t).agg((count_distinct(col("x")), "d"))
    assert df.collect().to_pydict() == {"d": [3]}
    assert_tpu_cpu_equal(df)


def test_count_distinct_mixed_rejected(session):
    t = pa.table({"x": pa.array([1], pa.int64())})
    with pytest.raises(ValueError, match="mixing count_distinct"):
        session.create_dataframe(t).agg(
            (count_distinct(col("x")), "d"), (sum_(col("x")), "s"))


def test_rollup_multi_partition(session, tmp_path):
    """Grouping sets compose with the partial/exchange/final aggregate
    shape over a multi-file scan."""
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    for i in range(3):
        t = pa.table({
            "k": pa.array(rng.integers(0, 4, 500), pa.int64()),
            "v": pa.array(rng.random(500), pa.float64()),
        })
        pq.write_table(t, str(tmp_path / f"f{i}.parquet"))
    df = session.read_parquet(str(tmp_path)).rollup("k").agg(
        (sum_(col("v")), "s"), (count(col("v")), "c"))
    assert_tpu_cpu_equal(df, approx_float=True)
