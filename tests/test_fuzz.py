"""Differential fuzz sweep: random plans over random data, TPU vs CPU.

The reference's fuzz layer (SURVEY.md §4: integration_tests' data_gen
randomized columns + qa_nightly sweeps) distilled to a seeded,
time-bounded property test: every case builds a random table (mixed
dtypes, nulls, NaN, +-0.0, unicode, empty strings), composes a random
plan from the supported surface (project/filter/group-by/sort/limit/
join), and requires exact row-set equality between engines.  Failures
reproduce from the printed seed alone.
"""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.session import TpuSession, avg, col, count, max_, min_, sum_

pytestmark = pytest.mark.slow  # TPC/fuzz/stress tier


N_CASES = 25  # per shape family; seeds 0..N-1 reproduce failures


def _rand_table(rng: np.random.Generator, n: int) -> pa.Table:
    def floats():
        v = rng.uniform(-1e4, 1e4, n)
        v[rng.random(n) < 0.05] = np.nan
        v[rng.random(n) < 0.05] = 0.0
        v[rng.random(n) < 0.05] = -0.0
        return [None if rng.random() < 0.1 else float(x) for x in v]

    def ints(lo, hi):
        v = rng.integers(lo, hi, n)
        return [None if rng.random() < 0.1 else int(x) for x in v]

    def strings():
        pool = ["", "a", "émoji✓", "SHIP", "ship", "  pad  ",
                "long-" + "x" * 50, "NULLish", "0"]
        return [None if rng.random() < 0.1
                else pool[rng.integers(0, len(pool))] for _ in range(n)]

    return pa.table({
        "i": pa.array(ints(-100, 100), pa.int64()),
        "j": pa.array(ints(0, 10), pa.int64()),
        "f": pa.array(floats(), pa.float64()),
        "s": pa.array(strings(), pa.string()),
        "b": pa.array([None if rng.random() < 0.1
                       else bool(x) for x in rng.integers(0, 2, n)],
                      pa.bool_()),
    })


def _rand_scalar_expr(rng, depth=0):
    """A random numeric expression over columns i/j/f."""
    leaves = [col("i"), col("j"), col("f"),
              lit(float(rng.integers(-5, 6))), lit(int(rng.integers(-5, 6)))]
    if depth >= 2:
        return leaves[rng.integers(0, len(leaves))]
    a = _rand_scalar_expr(rng, depth + 1)
    b = _rand_scalar_expr(rng, depth + 1)
    ops = [lambda: a + b, lambda: a - b, lambda: a * b,
           lambda: leaves[rng.integers(0, 3)]]
    return ops[rng.integers(0, len(ops))]()


def _rand_predicate(rng):
    a = _rand_scalar_expr(rng, depth=1)
    b = _rand_scalar_expr(rng, depth=1)
    cmps = [lambda: a > b, lambda: a < b, lambda: a >= b,
            lambda: a <= b,
            lambda: col("s").is_null(),
            lambda: col("b") & (col("i") > lit(0)),
            ]
    p = cmps[rng.integers(0, len(cmps))]()
    if rng.random() < 0.3:
        q = cmps[rng.integers(0, 4)]()
        p = (p | q) if rng.random() < 0.5 else (p & q)
    return p


def _canon(v):
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if v == 0.0:
            return 0.0  # -0.0 == 0.0 in SQL; either spelling is right
        return round(v, 6)
    return v


def _rows(tbl: pa.Table):
    return sorted(
        tuple(str(_canon(x)) for x in r.values())
        for r in tbl.to_pylist())


def _check(df):
    got = df.collect(engine="tpu")
    want = df.collect(engine="cpu")
    assert _rows(got) == _rows(want)


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_project_filter(seed):
    rng = np.random.default_rng(1000 + seed)
    t = _rand_table(rng, int(rng.integers(1, 400)))
    session = TpuSession()
    df = (session.create_dataframe(t)
          .where(_rand_predicate(rng))
          .select(col("s"), col("b"),
                  _rand_scalar_expr(rng).alias("e1"),
                  _rand_scalar_expr(rng).alias("e2")))
    _check(df)


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_groupby(seed):
    rng = np.random.default_rng(2000 + seed)
    t = _rand_table(rng, int(rng.integers(1, 400)))
    session = TpuSession()
    keys = [col("j")] if rng.random() < 0.5 else [col("j"), col("s")]
    df = (session.create_dataframe(t)
          .where(_rand_predicate(rng))
          .group_by(*keys)
          .agg((sum_(col("f")), "sf"), (count(col("i")), "ci"),
               (min_(col("i")), "mi"), (max_(col("f")), "mf"),
               (min_(col("f")), "mnf"), (avg(col("i")), "ai")))
    _check(df)


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_sort_limit(seed):
    rng = np.random.default_rng(3000 + seed)
    t = _rand_table(rng, int(rng.integers(1, 300)))
    session = TpuSession()
    from spark_rapids_tpu.execs.sort import SortKey

    # total order (every column) so exact ordered comparison is fair
    sks = [SortKey(col(c), descending=bool(rng.integers(0, 2)),
                   nulls_last=bool(rng.integers(0, 2)))
           for c in ("i", "f", "s", "b", "j")]
    df = session.create_dataframe(t).order_by(*sks)
    if rng.random() < 0.5:
        df = df.limit(int(rng.integers(1, 50)))
    got = df.collect(engine="tpu")
    want = df.collect(engine="cpu")
    assert _rows(got) == _rows(want)  # set equality
    # and ordered equality (total order makes it deterministic)
    g = [tuple(str(_canon(x)) for x in r.values()) for r in got.to_pylist()]
    w = [tuple(str(_canon(x)) for x in r.values())
         for r in want.to_pylist()]
    assert g == w


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_join(seed):
    rng = np.random.default_rng(4000 + seed)
    n1, n2 = int(rng.integers(1, 250)), int(rng.integers(1, 250))
    t1 = _rand_table(rng, n1).select(["i", "j", "f"])
    t2 = pa.table({
        "j": pa.array([None if rng.random() < 0.1 else int(x)
                       for x in rng.integers(0, 10, n2)], pa.int64()),
        "g": pa.array(rng.random(n2)),
    })
    session = TpuSession()
    how = ["inner", "left_outer", "left_semi", "left_anti"][
        rng.integers(0, 4)]
    df = (session.create_dataframe(t1)
          .join(session.create_dataframe(t2), on="j", how=how))
    _check(df)
