"""Adaptive execution: runtime join re-planning + partition coalescing.

The AQE analog (ref: GpuCustomShuffleReaderExec coalesced reads,
Spark's AdaptiveSparkPlanExec): static estimates are upper bounds, so a
selective filter leaves the scan-time estimate too big to broadcast —
the adaptive join must discover the real (small) size after the map
stage materializes and switch strategy, while results stay identical to
the CPU oracle.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.execs.adaptive import (
    ADAPTIVE_ENABLED,
    ADVISORY_PARTITION_BYTES,
    TpuAdaptiveJoinExec,
    plan_coalesced_groups,
)
from spark_rapids_tpu.plan.planner import BROADCAST_THRESHOLD
from spark_rapids_tpu.session import TpuSession, col, count_star
from tests.differential import assert_tables_equal


def test_plan_coalesced_groups():
    # groups close when they reach the target; empties merge for free
    assert plan_coalesced_groups([10, 10, 10, 10], 20) == [[0, 1], [2, 3]]
    assert plan_coalesced_groups([0, 0, 0, 50], 20) == [[0, 1, 2, 3]]
    assert plan_coalesced_groups([100, 0, 0, 0], 20) == [[0], [1, 2, 3]]
    # an oversized partition stays alone (no skew split)
    assert plan_coalesced_groups([5, 99, 5], 20) == [[0, 1], [2]]
    assert plan_coalesced_groups([], 20) == [[0]]


@pytest.fixture(autouse=True)
def small_batches():
    """Multi-partition sources so joins take the exchange path."""
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS

    conf = get_conf()
    old = conf.get(BATCH_SIZE_ROWS)
    conf.set(BATCH_SIZE_ROWS.key, 1000)
    yield
    conf.set(BATCH_SIZE_ROWS.key, old)


@pytest.fixture
def joined_tables():
    rng = np.random.default_rng(21)
    n = 5000
    fact = pa.table({
        "k": rng.integers(0, 200, n),
        "v": rng.random(n),
        "sel": rng.integers(0, 100, n),
    })
    dim = pa.table({
        "k": np.arange(200, dtype=np.int64),
        "name": pa.array([f"name-{i}" for i in range(200)]),
        "sel2": rng.integers(0, 100, 200),
    })
    return fact, dim


def _adaptive_nodes(exec_root):
    out = []

    def walk(e):
        if isinstance(e, TpuAdaptiveJoinExec):
            out.append(e)
        for c in e.children:
            walk(c)
    walk(exec_root)
    return out


@pytest.mark.slow
def test_adaptive_broadcast_switch(joined_tables):
    """Estimates say both sides are big (filters keep the child's upper
    bound); measured map output of the filtered dim side is tiny, so the
    join must execute as a runtime broadcast."""
    fact, dim = joined_tables
    conf = get_conf()
    old_thr = conf.get(BROADCAST_THRESHOLD)
    try:
        conf.set(BROADCAST_THRESHOLD.key, 4 << 10)  # 4KiB: est never fits
        session = TpuSession()
        f = session.create_dataframe(fact)
        # selective filter: ~10 of 200 dim rows survive -> tiny map output
        d = session.create_dataframe(dim).where(col("sel2") < 5)
        df = f.join(d, on="k")
        tpu = df.collect(engine="tpu")
        cpu = df.collect(engine="cpu")
        assert_tables_equal(tpu, cpu)
        # the decision is visible on the executed tree
        from spark_rapids_tpu.plan.planner import collect_exec, plan_query

        exec_, _ = plan_query(df._plan)
        nodes = _adaptive_nodes(exec_)
        assert nodes, "planner did not emit an adaptive join"
        collect_exec(exec_)
        assert "broadcast" in nodes[0]._decision, nodes[0]._decision
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old_thr)


@pytest.mark.slow
def test_adaptive_partition_coalescing(joined_tables):
    """With broadcast impossible and a large advisory target, the 8
    shuffle partitions must execute as one coalesced reduce group."""
    fact, dim = joined_tables
    conf = get_conf()
    old_thr = conf.get(BROADCAST_THRESHOLD)
    old_adv = conf.get(ADVISORY_PARTITION_BYTES)
    try:
        conf.set(BROADCAST_THRESHOLD.key, -1)  # broadcast disabled
        conf.set(ADVISORY_PARTITION_BYTES.key, 1 << 30)
        session = TpuSession()
        df = session.create_dataframe(fact).join(
            session.create_dataframe(dim), on="k")
        from spark_rapids_tpu.plan.planner import collect_exec, plan_query

        exec_, _ = plan_query(df._plan)
        nodes = _adaptive_nodes(exec_)
        assert nodes
        tpu = collect_exec(exec_)
        assert "->1 parts" in nodes[0]._decision, nodes[0]._decision
        cpu = df.collect(engine="cpu")
        assert_tables_equal(tpu, cpu)
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old_thr)
        conf.set(ADVISORY_PARTITION_BYTES.key, old_adv)


def test_adaptive_disabled_keeps_static_plan(joined_tables):
    fact, dim = joined_tables
    conf = get_conf()
    old = conf.get(ADAPTIVE_ENABLED)
    old_thr = conf.get(BROADCAST_THRESHOLD)
    try:
        conf.set(ADAPTIVE_ENABLED.key, False)
        conf.set(BROADCAST_THRESHOLD.key, -1)
        session = TpuSession()
        df = session.create_dataframe(fact).join(
            session.create_dataframe(dim), on="k")
        from spark_rapids_tpu.plan.planner import plan_query

        exec_, _ = plan_query(df._plan)
        assert not _adaptive_nodes(exec_)
        tpu = df.collect(engine="tpu")
        cpu = df.collect(engine="cpu")
        assert_tables_equal(tpu, cpu)
    finally:
        conf.set(ADAPTIVE_ENABLED.key, old)
        conf.set(BROADCAST_THRESHOLD.key, old_thr)


def test_plan_query_does_not_materialize(joined_tables):
    """Planning (and explain) must be side-effect free: building the
    exec tree — including parents that read num_partitions — must not
    run the adaptive join's map stages."""
    fact, dim = joined_tables
    conf = get_conf()
    old_thr = conf.get(BROADCAST_THRESHOLD)
    try:
        conf.set(BROADCAST_THRESHOLD.key, -1)  # force the exchange path
        session = TpuSession()
        from spark_rapids_tpu.plan.planner import plan_query
        from spark_rapids_tpu.session import sum_

        df = (session.create_dataframe(fact)
              .join(session.create_dataframe(dim), on="k")
              .group_by(col("name")).agg((sum_(col("v")), "s")))
        exec_, _ = plan_query(df._plan)
        nodes = _adaptive_nodes(exec_)
        assert nodes
        assert all(n._decided is None for n in nodes), \
            "plan_query materialized a shuffle stage"
        assert all(n.num_partitions > 0 for n in nodes)  # still undecided
        assert all(n._decided is None for n in nodes)
        exec_.close()
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old_thr)


def test_adaptive_broadcast_releases_build(joined_tables):
    """The runtime-decided broadcast join is not a child of the adaptive
    node; close() must still release its spillable build handle."""
    fact, dim = joined_tables
    conf = get_conf()
    old_thr = conf.get(BROADCAST_THRESHOLD)
    try:
        conf.set(BROADCAST_THRESHOLD.key, 4 << 10)
        session = TpuSession()
        d = session.create_dataframe(dim).where(col("sel2") < 5)
        df = session.create_dataframe(fact).join(d, on="k")
        from spark_rapids_tpu.memory import get_store
        from spark_rapids_tpu.plan.planner import collect_exec, plan_query

        store = get_store()
        before = set(store._entries)
        exec_, _ = plan_query(df._plan)
        nodes = _adaptive_nodes(exec_)
        collect_exec(exec_)  # drains AND closes
        assert nodes and "broadcast" in nodes[0]._decision
        leaked = set(store._entries) - before
        assert not leaked, f"leaked {len(leaked)} buffers after close"
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old_thr)


@pytest.mark.slow
def test_adaptive_left_outer_differential(joined_tables):
    """Strategy switches must not change join semantics: left_outer with
    unmatched rows, both adaptive strategies vs the CPU oracle."""
    fact, dim = joined_tables
    conf = get_conf()
    old_thr = conf.get(BROADCAST_THRESHOLD)
    try:
        session = TpuSession()
        half = session.create_dataframe(dim.slice(0, 100))
        f = session.create_dataframe(fact)
        for thr in (4 << 10, 1 << 30):
            conf.set(BROADCAST_THRESHOLD.key, thr)
            df = f.join(half, on="k", how="left_outer")
            assert_tables_equal(df.collect(engine="tpu"),
                                df.collect(engine="cpu"))
    finally:
        conf.set(BROADCAST_THRESHOLD.key, old_thr)


def test_plan_skew_groups_unit():
    from spark_rapids_tpu.execs.adaptive import plan_skew_groups

    # partition 1 is 100x the median and above threshold: split side=left
    lb = [10, 1000, 10, 10]
    rb = [10, 10, 10, 10]
    out = plan_skew_groups(lb, rb, target=300, factor=5.0, threshold=100,
                           join_type="inner")
    assert out is not None
    lg, rg, n = out
    assert n >= 2 and len(lg) == len(rg)
    # skewed partition appears as k slices on the left, full reads right
    slices = [g for g in lg if any(k > 1 for (_r, _i, k) in g)]
    assert slices and all(r == 1 for g in slices for (r, _i, _k) in g)
    for li, ri in zip(lg, rg):
        if any(k > 1 for (_r, _i, k) in li):
            assert ri == [(1, 0, 1)]
    # full_outer: no sound split
    assert plan_skew_groups(lb, rb, 300, 5.0, 100, "full_outer") is None
    # left_outer: only the left side may split
    assert plan_skew_groups(rb, lb, 300, 5.0, 100,
                            "left_outer") is None


@pytest.mark.slow
def test_adaptive_skew_split_differential(joined_tables):
    """A heavily skewed join key: the adaptive reader slices the skewed
    reduce partition (plan shows split groups) and results still match
    the oracle (ref: GpuCustomShuffleReaderExec's
    PartialReducerPartitionSpec / Spark's OptimizeSkewedJoin)."""
    from spark_rapids_tpu.execs.adaptive import (
        SKEW_FACTOR,
        SKEW_THRESHOLD_BYTES,
        ADVISORY_PARTITION_BYTES,
    )

    rng = np.random.default_rng(99)
    n = 20_000
    # 85% of fact rows share ONE key -> one giant reduce partition
    keys = np.where(rng.random(n) < 0.85, 7,
                    rng.integers(0, 200, n)).astype(np.int64)
    fact = pa.table({"k": keys, "v": rng.random(n)})
    dim = pa.table({"k": np.arange(200, dtype=np.int64),
                    "name": pa.array([f"n{i}" for i in range(200)])})
    conf = get_conf()
    old = {k.key: conf.get(k) for k in
           (BROADCAST_THRESHOLD, SKEW_FACTOR, SKEW_THRESHOLD_BYTES,
            ADVISORY_PARTITION_BYTES)}
    try:
        conf.set(BROADCAST_THRESHOLD.key, 1)       # no broadcast escape
        conf.set(SKEW_THRESHOLD_BYTES.key, 8 << 10)
        conf.set(SKEW_FACTOR.key, 3.0)
        conf.set(ADVISORY_PARTITION_BYTES.key, 32 << 10)
        session = TpuSession()
        f = session.create_dataframe(fact)
        d = session.create_dataframe(dim)
        df = f.join(d, on="k")
        from spark_rapids_tpu.plan.planner import collect_exec, plan_query

        exec_, _ = plan_query(df._plan)
        nodes = _adaptive_nodes(exec_)
        assert nodes
        tpu = collect_exec(exec_)
        assert "skew" in nodes[0]._decision, nodes[0]._decision
        cpu = df.collect(engine="cpu")
        assert_tables_equal(tpu, cpu)
    finally:
        for k, v in old.items():
            conf.set(k, v)


@pytest.mark.slow
def test_skew_split_wider_than_static_width(joined_tables):
    """Skew splitting may produce MORE join tasks than the static
    partition width the parent iterates; the overflow must drain (rows
    were silently dropped before the last-partition overflow drain)."""
    from spark_rapids_tpu.config import SHUFFLE_PARTITIONS
    from spark_rapids_tpu.execs.adaptive import (
        ADVISORY_PARTITION_BYTES,
        SKEW_FACTOR,
        SKEW_THRESHOLD_BYTES,
    )

    rng = np.random.default_rng(7)
    n = 12_000
    keys = np.where(rng.random(n) < 0.9, 1,
                    rng.integers(0, 40, n)).astype(np.int64)
    fact = pa.table({"k": keys, "v": rng.random(n)})
    dim = pa.table({"k": np.arange(40, dtype=np.int64),
                    "name": pa.array([f"n{i}" for i in range(40)])})
    conf = get_conf()
    old = {k.key: conf.get(k) for k in
           (BROADCAST_THRESHOLD, SKEW_FACTOR, SKEW_THRESHOLD_BYTES,
            ADVISORY_PARTITION_BYTES, SHUFFLE_PARTITIONS)}
    try:
        conf.set(SHUFFLE_PARTITIONS.key, 2)  # narrow static width
        conf.set(BROADCAST_THRESHOLD.key, 1)
        conf.set(SKEW_THRESHOLD_BYTES.key, 4 << 10)
        conf.set(SKEW_FACTOR.key, 2.0)
        conf.set(ADVISORY_PARTITION_BYTES.key, 16 << 10)
        session = TpuSession()
        df = (session.create_dataframe(fact)
              .join(session.create_dataframe(dim), on="k"))
        # drive through a PARENT that iterates child.num_partitions
        total = df.agg((count_star(), "n"))
        got = total.collect(engine="tpu").to_pydict()["n"][0]
        want = total.collect(engine="cpu").to_pydict()["n"][0]
        assert got == want == n, (got, want)
    finally:
        for k, v in old.items():
            conf.set(k, v)
