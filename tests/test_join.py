"""Join exec tests, diffed against a pure-Python nested-loop oracle
(mirrors the role of the reference's join_test.py differential suite)."""

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.basic import TpuBatchSourceExec
from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec
from spark_rapids_tpu.exprs.base import ColumnReference as C

L_SCHEMA = T.Schema([T.Field("lk", T.LONG), T.Field("lv", T.LONG)])
R_SCHEMA = T.Schema([T.Field("rk", T.LONG), T.Field("rv", T.STRING)])


def src(schema, rows, n_batches=1):
    """rows: list of dicts; split into n_batches."""
    per = max(1, -(-len(rows) // n_batches)) if rows else 1
    batches = []
    for i in range(0, max(len(rows), 1), per):
        chunk = rows[i:i + per]
        if not chunk and i > 0:
            break
        data, valid = {}, {}
        for f in schema.fields:
            vals = [r[f.name] for r in chunk]
            valid[f.name] = np.array([v is not None for v in vals])
            if isinstance(f.dtype, T.StringType):
                data[f.name] = np.array(
                    [v if v is not None else "" for v in vals], object)
            else:
                data[f.name] = np.array(
                    [v if v is not None else 0 for v in vals],
                    T.to_numpy_dtype(f.dtype))
        batches.append(ColumnarBatch.from_numpy(data, schema, valid))
    return TpuBatchSourceExec(batches, schema)


def rows_of(exec_):
    out = []
    for b in exec_.execute():
        d = b.to_pydict()
        names = list(d)
        for i in range(len(d[names[0]])):
            out.append(tuple(d[n][i] for n in names))
    return sorted(out, key=lambda t: tuple((x is None, x) for x in t))


def oracle(left, right, join_type):
    out = []
    matched_r = [False] * len(right)
    for l in left:
        hits = [r for r in right
                if l["lk"] is not None and l["lk"] == r["rk"]]
        for r in hits:
            matched_r[right.index(r)] = True
        if join_type in ("inner", "left_outer", "full_outer",
                         "right_outer"):
            for r in hits:
                out.append((l["lk"], l["lv"], r["rk"], r["rv"]))
            if not hits and join_type in ("left_outer", "full_outer"):
                out.append((l["lk"], l["lv"], None, None))
        elif join_type == "left_semi" and hits:
            out.append((l["lk"], l["lv"]))
        elif join_type == "left_anti" and not hits:
            out.append((l["lk"], l["lv"]))
    if join_type in ("right_outer", "full_outer"):
        for i, r in enumerate(right):
            if not matched_r[i]:
                out.append((None, None, r["rk"], r["rv"]))
    return sorted(out, key=lambda t: tuple((x is None, x) for x in t))


LEFT = [
    {"lk": 1, "lv": 10}, {"lk": 2, "lv": 20}, {"lk": 2, "lv": 21},
    {"lk": None, "lv": 30}, {"lk": 5, "lv": 50}, {"lk": 7, "lv": 70},
]
RIGHT = [
    {"rk": 1, "rv": "one"}, {"rk": 2, "rv": "two"}, {"rk": 2, "rv": "TWO"},
    {"rk": None, "rv": "null"}, {"rk": 5, "rv": "five"},
    {"rk": 9, "rv": "nine"},
]


@pytest.mark.parametrize("join_type", ["inner", "left_outer", "left_semi",
                                       "left_anti", "full_outer"])
@pytest.mark.parametrize("n_batches", [1, 3])
def test_join_vs_oracle(join_type, n_batches):
    ex = TpuShuffledHashJoinExec(
        [C("lk")], [C("rk")], join_type,
        src(L_SCHEMA, LEFT, n_batches), src(R_SCHEMA, RIGHT))
    assert rows_of(ex) == oracle(LEFT, RIGHT, join_type)


def test_right_outer():
    """right_outer: all right rows preserved, build side = left."""
    ex = TpuShuffledHashJoinExec(
        [C("lk")], [C("rk")], "right_outer",
        src(L_SCHEMA, LEFT), src(R_SCHEMA, RIGHT, 2))
    want = [t for t in oracle(LEFT, RIGHT, "full_outer")
            if t[2] is not None or (t[0] is None and t[1] is None)]
    # full_outer minus left-unmatched rows == right_outer
    want = [t for t in want if not (t[2] is None and t[3] is None)]
    assert rows_of(ex) == sorted(
        want, key=lambda t: tuple((x is None, x) for x in t))


def test_inner_with_condition():
    ex = TpuShuffledHashJoinExec(
        [C("lk")], [C("rk")], "inner",
        src(L_SCHEMA, LEFT), src(R_SCHEMA, RIGHT),
        condition=C("lv") > 20)
    assert rows_of(ex) == [(2, 21, 2, "TWO"), (2, 21, 2, "two"),
                           (5, 50, 5, "five")]


def test_cross_join():
    l = [{"lk": 1, "lv": 10}, {"lk": 2, "lv": 20}]
    r = [{"rk": 7, "rv": "a"}, {"rk": 8, "rv": "b"}, {"rk": 9, "rv": "c"}]
    ex = TpuShuffledHashJoinExec([], [], "cross",
                                 src(L_SCHEMA, l), src(R_SCHEMA, r))
    assert len(rows_of(ex)) == 6


def test_join_empty_build_side():
    for jt, want_rows in [("inner", 0), ("left_outer", len(LEFT)),
                          ("left_anti", len(LEFT)), ("left_semi", 0)]:
        ex = TpuShuffledHashJoinExec(
            [C("lk")], [C("rk")], jt, src(L_SCHEMA, LEFT),
            src(R_SCHEMA, []))
        assert len(rows_of(ex)) == want_rows, jt


def test_join_string_keys():
    ls = T.Schema([T.Field("lk", T.STRING), T.Field("lv", T.LONG)])
    rs = T.Schema([T.Field("rk", T.STRING), T.Field("rv", T.LONG)])
    l = [{"lk": "aa", "lv": 1}, {"lk": "bb", "lv": 2},
         {"lk": "日本", "lv": 3}, {"lk": None, "lv": 4}]
    r = [{"rk": "aa", "rv": 10}, {"rk": "日本", "rv": 30},
         {"rk": "cc", "rv": 40}]
    ex = TpuShuffledHashJoinExec([C("lk")], [C("rk")], "inner",
                                 src(ls, l), src(rs, r))
    assert rows_of(ex) == [("aa", 1, "aa", 10), ("日本", 3, "日本", 30)]
