"""The Connect-style wire front door (spark_rapids_tpu/connect/,
docs/connect.md):

- THE wire-parity acceptance test: an EXTERNAL CLIENT PROCESS (engine
  modules never imported) submits a plan over TCP and the Arrow
  batches it reassembles digest bit-identical to an in-process
  collect;
- multi-batch round trip with strings + NULLs, equality vs collect;
- a wire deadline expiring in the admission queue sheds with ZERO
  device work (no ledger programs, no jit compiles, no tapped upload
  bytes) and records engine="deadline_exceeded";
- a dropped client connection cancels the in-flight query via its
  CancelToken — the engine unwinds cooperatively and every residency
  gauge returns to baseline (conftest.leak_check, module-wide);
- malformed and oversized frames are rejected without killing the
  server (the SRC014 clamp contract);
- two tenants over two sockets share the process-wide result cache;
- the per-query event-log record carries the `connect` section
  (peer, wire_bytes, translate_ms) — INCLUDING queue-shed
  deadline_exceeded records (the facts are deposited before the
  shed outcome is logged);
- wire trace propagation (docs/ops_plane.md): a client-minted trace
  id rides the request frame, every server-side span of that query
  carries it, and trace/export.merge_wire_trace folds the client's
  send/first-byte/last-byte spans onto the SAME Chrome-trace
  timeline;
- the tier-1 hook for tools/bench_smoke.run_connect_smoke.
"""

import json
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import TpuConf, get_conf
from spark_rapids_tpu.connect.client import (
    ConnectClient,
    ConnectError,
    table_digest,
)
from spark_rapids_tpu.connect.server import ConnectServer
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.serving import cancel as C
from spark_rapids_tpu.serving import clear_serving_context
from spark_rapids_tpu.serving import scheduler as scheduler_mod


@pytest.fixture(autouse=True)
def _isolate_connect():
    from spark_rapids_tpu.memory.store import reset_store
    from spark_rapids_tpu.serving import work_share

    scheduler_mod.reset()
    C.reset()
    clear_serving_context()
    TpuSemaphore.reset()
    work_share.reset()
    reset_store()
    yield
    scheduler_mod.reset()
    C.reset()
    clear_serving_context()
    TpuSemaphore.reset()
    work_share.reset()


@pytest.fixture(autouse=True)
def _no_leaks(leak_check):
    """Every wire test proves its unwind leaked nothing.  The shared
    caches are dropped FIRST — retained result-cache entries hold
    store bytes by design; everything else must return to baseline."""
    yield
    from spark_rapids_tpu.serving import work_share

    work_share.reset()


def _table(n=6000, seed=5):
    rng = np.random.default_rng(seed)
    strs = np.array(["alpha", "beta", "gamma", "delta", None],
                    dtype=object)
    return pa.table({
        "k": rng.integers(0, 23, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "s": pa.array([strs[i % 5] for i in rng.integers(0, 5, n)]),
    })


def _server(conf=None, table=None):
    srv = ConnectServer(conf=conf)
    srv.register_table("t", table if table is not None else _table())
    return srv.start()


SQL = ("select k, s, count(*) as n, sum(v) as sv from t "
       "group by k, s order by k, s nulls last")


# ------------------------------------------------------------------ #
# Round trip parity
# ------------------------------------------------------------------ #


def test_multibatch_roundtrip_equals_collect():
    """Strings + NULLs over several wire frames reassemble to the
    exact in-process collect table (bit-identical digest)."""
    from spark_rapids_tpu.frontends.sql import SqlSession

    t = _table()
    srv = _server(table=t)
    try:
        host, port = srv.address
        with ConnectClient(host, port, tenant="t1") as cli:
            got = cli.execute_sql(SQL, batch_rows=16)
        assert got.num_rows > 16  # several frames
        fe = SqlSession()
        fe.register_table("t", t)
        want = fe.sql(SQL).collect(engine="tpu").combine_chunks()
        assert table_digest(got) == table_digest(want)
        # and the digest helper agrees with the engine's
        from spark_rapids_tpu.eventlog import table_digest as engine_td

        assert table_digest(want) == engine_td(want)
    finally:
        srv.shutdown()


def test_external_client_process_wire_parity(tmp_path):
    """THE acceptance test: a separate client PROCESS that never
    imports the engine submits a Substrait plan over TCP and gets
    batches digest-identical to the same plan collected in-process."""
    from spark_rapids_tpu.frontends.substrait import SubstraitFrontend

    t = _table()
    plan = {
        "relations": [{"root": {
            "names": ["k", "v", "s"],
            "input": {"read": {"namedTable": {"names": ["t"]},
                               "baseSchema":
                                   {"names": ["k", "v", "s"]}}}}}],
    }
    srv = _server(table=t)
    try:
        host, port = srv.address
        plan_file = tmp_path / "plan.json"
        plan_file.write_text(json.dumps(plan))
        code = (
            "import sys, json\n"
            "from spark_rapids_tpu.connect.client import "
            "ConnectClient, table_digest\n"
            f"plan = json.load(open({str(plan_file)!r}))\n"
            f"with ConnectClient({host!r}, {port}, tenant='ext') "
            "as cli:\n"
            "    t = cli.execute_plan(plan)\n"
            "print('DIGEST', table_digest(t), t.num_rows)\n"
            "engine = [m for m in sys.modules"
            " if m.startswith('spark_rapids_tpu.')"
            " and m.split('.')[1] in ('session', 'plan', 'execs',"
            " 'ops', 'io', 'memory', 'parallel', 'serving',"
            " 'frontends', 'columnar')]\n"
            "print('ENGINE_MODULES', engine)\n")
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        lines = dict(
            line.split(" ", 1) for line in out.stdout.splitlines())
        assert lines["ENGINE_MODULES"] == "[]", (
            "client process imported the engine: "
            + lines["ENGINE_MODULES"])
        fe = SubstraitFrontend()
        fe.register_table("t", t)
        want = fe.execute_plan(plan).combine_chunks()
        digest, rows = lines["DIGEST"].split()
        assert int(rows) == want.num_rows
        assert digest == table_digest(want)
    finally:
        srv.shutdown()


def test_connect_client_cli(tmp_path):
    """python -m spark_rapids_tpu.tools.connect_client --digest-only"""
    t = _table(n=500)
    srv = _server(table=t)
    try:
        host, port = srv.address
        out = subprocess.run(
            [sys.executable, "-m",
             "spark_rapids_tpu.tools.connect_client",
             "--host", host, "--port", str(port),
             "--sql", "select k, sum(v) as sv from t group by k "
                      "order by k",
             "--digest-only"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        from spark_rapids_tpu.frontends.sql import SqlSession

        fe = SqlSession()
        fe.register_table("t", t)
        want = fe.sql("select k, sum(v) as sv from t group by k "
                      "order by k").collect(engine="tpu")
        assert out.stdout.strip() == table_digest(
            want.combine_chunks())
    finally:
        srv.shutdown()


def test_bench_smoke_connect():
    """tier-1 hook: the packaged connect smoke passes."""
    from spark_rapids_tpu.tools.bench_smoke import run_connect_smoke

    out = run_connect_smoke()
    assert out["connect_smoke_rows"] > 0


# ------------------------------------------------------------------ #
# Deadline from the wire: shed in queue, zero device work
# ------------------------------------------------------------------ #


def test_wire_deadline_sheds_in_queue_zero_device_work(tmp_path):
    from spark_rapids_tpu.columnar.transfer import upload_stats
    from spark_rapids_tpu.execs.jit_cache import cache_stats
    from spark_rapids_tpu.trace import ledger as _ledger

    conf = TpuConf({
        "spark.rapids.tpu.serving.maxConcurrent": 1,
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.trace.ledger.enabled": True,
    })
    srv = _server(conf=conf)
    sched = scheduler_mod.get_scheduler(conf)
    hog = sched.admit("hog")  # occupy the only admission slot
    try:
        _ledger.sync_conf(conf)
        led0 = _ledger.LEDGER.snapshot()
        jit0 = cache_stats()
        up0 = upload_stats()
        host, port = srv.address
        t0 = time.perf_counter()
        with ConnectClient(host, port, tenant="dl") as cli:
            with pytest.raises(ConnectError) as ei:
                cli.execute_sql(SQL, deadline_ms=40.0)
        waited = time.perf_counter() - t0
        assert ei.value.kind == "deadline_exceeded"
        assert waited < 10.0
        # the zero-DEVICE-work contract over the wire: no ledger
        # program activity, no byte uploaded, no program DISPATCHED.
        # (Translate + prepared-plan resolve legitimately run before
        # admission — that is the plan-cache design, same as an
        # in-process PreparedQuery — so plan-time compiles are not
        # device work; what must be zero is execution.)
        assert _ledger.delta(led0, _ledger.LEDGER.snapshot()) == {}
        assert upload_stats() == up0
    finally:
        sched.release(hog)
        srv.shutdown()
        _ledger.disable()
        _ledger.sync_conf(get_conf())
    # the shed query is an observable outcome in the event log
    rec = _wait_for_record(tmp_path, "deadline_exceeded")
    assert rec["engine"] == "deadline_exceeded"


def test_queue_shed_record_keeps_connect_section(tmp_path):
    """Regression: a wire query shed IN THE ADMISSION QUEUE
    (deadline_exceeded before admit) must still record its `connect`
    section.  The facts are deposited into the serving context only
    after admission on the happy path, so the shed path used to drop
    peer/wire_bytes from the event-log record — the cancelled-outcome
    recorder now deposits them itself before logging."""
    conf = TpuConf({
        "spark.rapids.tpu.serving.maxConcurrent": 1,
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    })
    srv = _server(conf=conf)
    sched = scheduler_mod.get_scheduler(conf)
    hog = sched.admit("hog")  # occupy the only admission slot
    try:
        host, port = srv.address
        with ConnectClient(host, port, tenant="shed") as cli:
            with pytest.raises(ConnectError) as ei:
                cli.execute_sql(SQL, deadline_ms=40.0)
        assert ei.value.kind == "deadline_exceeded"
    finally:
        sched.release(hog)
        srv.shutdown()
    rec = _wait_for_record(tmp_path, "deadline_exceeded")
    conn = rec.get("connect")
    assert conn is not None, \
        "queue-shed record dropped its connect section"
    assert conn["peer"].startswith("127.0.0.1:")
    assert conn["wire_bytes"] > 0
    assert conn["translate_ms"] >= 0


def _wait_for_record(log_dir, engine: str, timeout=10.0):
    from spark_rapids_tpu.eventlog.reader import iter_records

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for path in sorted(log_dir.glob("*.jsonl")):
            for rec in iter_records(str(path)):
                if rec.get("type") == "query" \
                        and rec.get("engine") == engine:
                    return rec
        time.sleep(0.1)
    raise AssertionError(f"no {engine!r} query record in {log_dir}")


# ------------------------------------------------------------------ #
# Client disconnect cancels mid-stream
# ------------------------------------------------------------------ #


def test_client_disconnect_cancels_inflight(tmp_path):
    """Closing the socket mid-stream cancels the query via its
    CancelToken: the engine records a cancelled outcome and (via the
    module-wide leak_check) every residency gauge returns to
    baseline."""
    conf = TpuConf({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.sql.batchSizeRows": 256,
        # tight server send buffer: the stream BLOCKS as soon as this
        # client stops reading, so the drop is detected mid-stream
        # instead of after the whole result fit in kernel buffers
        "spark.rapids.tpu.connect.sendBufferBytes": 8192,
    })
    srv = _server(conf=conf, table=_table(n=60000))
    try:
        host, port = srv.address
        cli = ConnectClient(host, port, tenant="dropper")
        cli._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                             8192)
        stream = cli.execute_plan_stream(
            None, sql="select k, v, s from t", batch_rows=16)
        first = next(stream)  # at least one frame arrived
        assert first.num_rows > 0
        time.sleep(0.5)  # let the producer run into the full buffer
        cli.close()  # drop the connection mid-stream
        rec = _wait_for_record(tmp_path, "cancelled", timeout=20.0)
        assert rec["engine"] == "cancelled"
    finally:
        srv.shutdown()


# ------------------------------------------------------------------ #
# Framing robustness
# ------------------------------------------------------------------ #


def test_malformed_and_oversized_frames_rejected():
    srv = _server(table=_table(n=100))
    try:
        host, port = srv.address
        # oversized length: rejected BEFORE allocation, with an error
        # frame, and only this connection dies
        with socket.create_connection((host, port), timeout=10) as s:
            s.sendall(struct.pack("<Q", 1 << 60))
            s.sendall(b"JXXXX")
            from spark_rapids_tpu.connect.client import recv_json

            resp = recv_json(s)
            assert not resp["ok"] and resp["kind"] == "bad_frame"
        # malformed JSON: same contract
        with socket.create_connection((host, port), timeout=10) as s:
            payload = b"Jnot-json"
            s.sendall(struct.pack("<Q", len(payload)) + payload)
            resp = recv_json(s)
            assert not resp["ok"] and resp["kind"] == "bad_frame"
        # unknown op: error frame, connection stays usable
        with ConnectClient(host, port) as cli:
            from spark_rapids_tpu.connect.client import (
                TAG_JSON,
                send_frame,
                recv_json as rj,
            )

            send_frame(cli._sock, TAG_JSON,
                       json.dumps({"op": "nope"}).encode())
            resp = rj(cli._sock)
            assert not resp["ok"] and resp["kind"] == "bad_request"
            assert cli.ping()  # same connection still serves
        # and the server survived all of it
        with ConnectClient(host, port) as cli:
            out = cli.execute_sql("select count(*) as n from t")
            assert out.column("n")[0].as_py() == 100
    finally:
        srv.shutdown()


def test_translate_error_keeps_connection():
    srv = _server(table=_table(n=50))
    try:
        host, port = srv.address
        with ConnectClient(host, port) as cli:
            with pytest.raises(ConnectError) as ei:
                cli.execute_sql("select frobnicate(k) from t")
            assert ei.value.kind == "translate_error"
            # same connection executes the next query fine
            out = cli.execute_sql("select count(*) as n from t")
            assert out.column("n")[0].as_py() == 50
    finally:
        srv.shutdown()


# ------------------------------------------------------------------ #
# Cross-tenant result sharing over the wire
# ------------------------------------------------------------------ #


def test_two_tenants_two_sockets_share_result_cache():
    from spark_rapids_tpu.serving import work_share

    conf = TpuConf({
        "spark.rapids.tpu.serving.sharing.enabled": True,
    })
    srv = _server(conf=conf)
    try:
        host, port = srv.address
        s0 = work_share.stats()
        with ConnectClient(host, port, tenant="tenant_a") as a:
            ra = a.execute_sql(SQL)
        with ConnectClient(host, port, tenant="tenant_b") as b:
            rb = b.execute_sql(SQL)
        s1 = work_share.stats()
        assert table_digest(ra) == table_digest(rb)
        assert s1["result_hits"] - s0["result_hits"] >= 1, (
            "second tenant's wire query did not hit the shared "
            f"result cache: {s0} -> {s1}")
    finally:
        srv.shutdown()


# ------------------------------------------------------------------ #
# Wire trace propagation (docs/ops_plane.md)
# ------------------------------------------------------------------ #


def test_wire_trace_propagates_and_merges_one_timeline():
    """THE trace-propagation acceptance test: a wire query submitted
    with a client-minted trace id produces server-side spans tagged
    with that exact id, and merge_wire_trace folds the client's
    send/first-byte/last-byte spans into the same Chrome-trace
    document — both sides stamp perf_counter_ns, so for this
    in-process loopback every tagged server span lands INSIDE the
    client's wire window on one timeline."""
    from spark_rapids_tpu import trace as _trace
    from spark_rapids_tpu.trace.export import (
        chrome_trace,
        merge_wire_trace,
    )

    srv = _server(table=_table(n=2000))
    _trace.enable()
    try:
        host, port = srv.address
        with ConnectClient(host, port, tenant="traced",
                           trace=True) as cli:
            got = cli.execute_sql(SQL, batch_rows=256)
        assert got.num_rows > 0
        # the client minted one 16-hex id and recorded its wire spans
        assert cli.trace_id and len(cli.trace_id) == 16
        assert [s["name"] for s in cli.trace_spans] == [
            "connect.client.send", "connect.client.first_byte",
            "connect.client.last_byte"]
        assert all(s["attrs"]["trace_id"] == cli.trace_id
                   for s in cli.trace_spans)
        # server-side spans of the query carry the INBOUND id — the
        # correlation context survives the drain loop's per-pull
        # re-attach and the pipeline threads
        tagged = [e for e in _trace.snapshot()
                  if e.attrs.get("trace_id") == cli.trace_id]
        assert tagged, "no server span carries the client trace id"
        assert any(e.name == "query.execute" for e in tagged)
        # one timeline: every tagged server span starts inside the
        # client's send..last_byte window (shared clock in-process)
        send = cli.trace_spans[0]
        last = cli.trace_spans[-1]
        lo = send["ts_ns"]
        hi = last["ts_ns"] + last["dur_ns"]
        for e in tagged:
            assert lo <= e.ts_ns <= hi, (e.name, e.ts_ns, lo, hi)
        # merged export: both sides in ONE document, client spans on
        # their own named track
        doc = merge_wire_trace(chrome_trace(tagged), cli.trace_spans)
        names = {e["name"] for e in doc["traceEvents"]}
        assert "connect.client.send" in names
        assert "connect.client.last_byte" in names
        assert "query.execute" in names
        assert any(e.get("ph") == "M"
                   and e.get("args", {}).get("name") ==
                   "connect-client"
                   for e in doc["traceEvents"])
        json.dumps(doc)  # serializable whole
    finally:
        _trace.disable()
        _trace.clear()
        srv.shutdown()


def test_wire_trace_off_by_default():
    """Without trace=True no trace field is minted and no span is
    recorded — the wire contract is unchanged for existing clients."""
    srv = _server(table=_table(n=200))
    try:
        host, port = srv.address
        with ConnectClient(host, port) as cli:
            out = cli.execute_sql("select count(*) as n from t")
        assert out.column("n")[0].as_py() == 200
        assert cli.trace_id is None
        assert cli.trace_spans == []
    finally:
        srv.shutdown()


# ------------------------------------------------------------------ #
# Event-log connect section
# ------------------------------------------------------------------ #


def test_eventlog_connect_section(tmp_path):
    conf = TpuConf({
        "spark.rapids.tpu.eventLog.enabled": True,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
    })
    srv = _server(conf=conf, table=_table(n=300))
    try:
        host, port = srv.address
        with ConnectClient(host, port, tenant="logged") as cli:
            cli.execute_sql("select count(*) as n from t")
        rec = _wait_for_record(tmp_path, "tpu")
        conn = rec["connect"]
        assert conn is not None
        assert conn["peer"].startswith("127.0.0.1:")
        assert conn["wire_bytes"] > 0
        assert conn["translate_ms"] >= 0
        # the serving facts rode the same deposit (plan-cache verdict)
        assert rec["serving"]["plan_cache"] in ("hit", "miss")
    finally:
        srv.shutdown()
