"""TPC-DS q67/q93-shaped differential tests (BASELINE.md config #4:
sort + window workloads; ref: the reference validates these shapes via
its NDS runs).  Small-scale data, full plan shapes: rollup aggregate ->
ranking window -> rank filter -> order by (q67), and join + window +
conditional arithmetic -> grouped sum -> top-N (q93)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.exprs.base import lit
from spark_rapids_tpu.exprs.window import Window, rank
from spark_rapids_tpu.session import TpuSession, col, sum_
from tests.differential import assert_tpu_cpu_equal

pytestmark = pytest.mark.slow  # TPC tier


@pytest.fixture
def session():
    return TpuSession()


def _store_sales(tmp_path, n=20_000, seed=67):
    rng = np.random.default_rng(seed)
    t = pa.table({
        "ss_item_sk": rng.integers(1, 40, n),
        "ss_store_sk": rng.integers(1, 6, n),
        "ss_quantity": rng.integers(1, 20, n),
        "ss_sales_price": np.round(rng.uniform(1, 300, n), 2),
        "ss_ticket_number": rng.integers(1, n // 2, n),
        "ss_customer_sk": pa.array(
            [None if rng.random() < 0.08 else int(x)
             for x in rng.integers(1, 500, n)], pa.int64()),
    })
    paths = []
    for i in range(4):
        p = str(tmp_path / f"ss{i}.parquet")
        pq.write_table(t.slice(i * (n // 4), n // 4), p)
        paths.append(p)
    return paths


def test_q67_shape_rollup_window_rank(session, tmp_path):
    """q67: aggregate sales, rank items within each store by revenue,
    keep the top ranks, order the output — grouped aggregate under a
    ranking window under a filter under a global sort."""
    paths = _store_sales(tmp_path)
    agg = (session.read_parquet(*paths)
           .group_by(col("ss_store_sk"), col("ss_item_sk"))
           .agg((sum_(col("ss_sales_price") * col("ss_quantity")),
                 "sumsales")))
    spec = Window.partition_by("ss_store_sk").order_by(
        "sumsales", desc=True)
    ranked = agg.select(col("ss_store_sk"), col("ss_item_sk"),
                        col("sumsales"),
                        rank().over(spec).alias("rk"))
    out = (ranked.where(col("rk") <= lit(5))
           .order_by(col("ss_store_sk"), col("rk"),
                     col("ss_item_sk")))
    assert_tpu_cpu_equal(out, ignore_order=False, approx_float=True)
    got = out.collect(engine="tpu").to_pydict()
    assert got["rk"] and max(got["rk"]) <= 5


def test_q93_shape_join_conditional_topn(session, tmp_path):
    """q93: sales joined to returns on (item, ticket), refunded
    quantity subtracted conditionally, summed per customer, top-N by
    total — shuffled join + conditional arithmetic + grouped sum +
    TakeOrdered."""
    from spark_rapids_tpu.exprs.predicates import If, IsNotNull

    rng = np.random.default_rng(93)
    paths = _store_sales(tmp_path, seed=93)
    nr = 3_000
    returns = pa.table({
        "sr_item_sk": rng.integers(1, 40, nr),
        "sr_ticket_number": rng.integers(1, 10_000, nr),
        "sr_return_quantity": rng.integers(1, 10, nr),
        "sr_reason_sk": rng.integers(1, 5, nr),
    })
    sales = session.read_parquet(*paths)
    rdf = session.create_dataframe(returns).where(
        col("sr_reason_sk").eq(lit(3)))
    joined = sales.join(
        rdf, how="left_outer",
        left_on=[col("ss_item_sk"), col("ss_ticket_number")],
        right_on=[col("sr_item_sk"), col("sr_ticket_number")])
    act_qty = If(IsNotNull(col("sr_ticket_number")),
                 col("ss_quantity") - col("sr_return_quantity"),
                 col("ss_quantity"))
    out = (joined.select(col("ss_customer_sk"),
                         (act_qty * col("ss_sales_price")).alias("act"))
           .group_by(col("ss_customer_sk"))
           .agg((sum_(col("act")), "sumsales"))
           .order_by(col("sumsales"), col("ss_customer_sk"))
           .limit(50))
    assert_tpu_cpu_equal(out, ignore_order=False, approx_float=True)


def test_q67_shape_on_collective_mesh(tmp_path):
    """The q67 shape through the collective tier: rollup aggregate +
    window + sort all lower onto the 8-device mesh programs."""
    session = TpuSession()
    session.enable_collective_shuffle(8)
    try:
        paths = _store_sales(tmp_path, n=8_000, seed=68)
        agg = (session.read_parquet(*paths)
               .group_by(col("ss_store_sk"), col("ss_item_sk"))
               .agg((sum_(col("ss_sales_price")), "s")))
        spec = Window.partition_by("ss_store_sk").order_by(
            "s", desc=True)
        out = (agg.select(col("ss_store_sk"), col("ss_item_sk"),
                          col("s"), rank().over(spec).alias("rk"))
               .where(col("rk") <= lit(3))
               .order_by(col("ss_store_sk"), col("rk"),
                         col("ss_item_sk")))
        assert_tpu_cpu_equal(out, ignore_order=False,
                             approx_float=True)
    finally:
        session.disable_collective_shuffle()


def test_q93_shape_sql_text(tmp_path):
    """The q93 moving parts driven from SQL TEXT through
    frontend("sql"): join on (item, ticket), CASE'd refund arithmetic,
    grouped sum, top-N — the user's query string, unmodified."""
    from spark_rapids_tpu.frontends.sql import SqlSession

    rng = np.random.default_rng(93)
    n = 8_000
    fe = SqlSession()
    fe.register_table("store_sales", pa.table({
        "ss_item_sk": rng.integers(1, 40, n),
        "ss_ticket_number": rng.integers(1, n // 2, n),
        "ss_customer_sk": rng.integers(1, 300, n),
        "ss_quantity": rng.integers(1, 20, n).astype(np.int64),
        "ss_sales_price": np.round(rng.uniform(1, 300, n), 2),
    }))
    m = 2_000
    fe.register_table("store_returns", pa.table({
        "sr_item_sk": rng.integers(1, 40, m),
        "sr_ticket_number": rng.integers(1, n // 2, m),
        "sr_return_quantity": rng.integers(1, 10, m).astype(np.int64),
    }))
    df = fe.sql("""
        select ss_customer_sk,
               sum(case when sr_return_quantity is not null
                        then (ss_quantity - sr_return_quantity)
                             * ss_sales_price
                        else ss_quantity * ss_sales_price end) as sumsales
        from store_sales
             left join store_returns
               on ss_item_sk = sr_item_sk
              and ss_ticket_number = sr_ticket_number
        group by ss_customer_sk
        order by sumsales, ss_customer_sk
        limit 25
    """)
    t_tpu = df.collect(engine="tpu")
    t_cpu = df.collect(engine="cpu")
    a = list(zip(*t_tpu.to_pydict().values()))
    b = list(zip(*t_cpu.to_pydict().values()))
    assert len(a) == len(b) == 25
    # revenue ordering is the contract; customer tiebreak may differ on
    # equal sums, so compare the sorted value columns
    for (ac, av), (bc, bv) in zip(a, b):
        assert abs(av - bv) <= 1e-6 * max(1.0, abs(bv)), ((ac, av),
                                                          (bc, bv))
