"""Collective exchange tests on an 8-virtual-device CPU mesh (the model
for testing the distributed path without a pod — mirrors the reference's
in-process mock-transport shuffle suites, SURVEY.md §4.3)."""

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exprs.hashing import partition_ids
from spark_rapids_tpu.ops.groupby import AggSpec, groupby_aggregate
from spark_rapids_tpu.parallel import (
    make_hash_exchange_step,
    make_mesh,
    stack_batches,
    unstack_batch,
)

N_DEV = 8


def make_shards(schema, n_rows_per_shard, seed=0):
    rng = np.random.default_rng(seed)
    shards = []
    for _ in range(N_DEV):
        data = {
            "k": rng.integers(0, 20, n_rows_per_shard).astype(np.int64),
            "v": rng.integers(0, 100, n_rows_per_shard).astype(np.int64),
        }
        shards.append(ColumnarBatch.from_numpy(data, schema, capacity=32))
    return shards


def test_exchange_routes_rows_to_hash_owner():
    mesh = make_mesh(N_DEV)
    schema = T.Schema([T.Field("k", T.LONG), T.Field("v", T.LONG)])
    shards = make_shards(schema, 20)
    step = make_hash_exchange_step(mesh, key_ordinals=[0])
    out = step(stack_batches(shards))
    outs = unstack_batch(out)

    # every input row lands on exactly the device that owns its hash bucket
    all_in = []
    for s in shards:
        d = s.to_pydict()
        all_in.extend(zip(d["k"], d["v"]))
    all_out = []
    for dev, o in enumerate(outs):
        d = o.to_pydict()
        for k, v in zip(d["k"], d["v"]):
            kcol = ColumnarBatch.from_numpy(
                {"k": np.array([k])}, T.Schema([T.Field("k", T.LONG)]))
            want_dev = int(np.asarray(
                partition_ids([kcol.columns[0]], kcol.capacity, N_DEV))[0])
            assert want_dev == dev, f"row k={k} on wrong device"
            all_out.append((k, v))
    assert sorted(all_in) == sorted(all_out)


def test_exchange_with_fused_partial_and_merge_agg():
    """Map-side partial agg -> exchange -> reduce-side merge, all one
    program: the TPU analog of the reference's partial/final aggregate
    around a shuffle (aggregate.scala modes)."""
    mesh = make_mesh(N_DEV)
    schema = T.Schema([T.Field("k", T.LONG), T.Field("v", T.LONG)])
    partial_schema = T.Schema([T.Field("k", T.LONG), T.Field("s", T.LONG)])
    shards = make_shards(schema, 24, seed=3)

    def pre(b):
        return groupby_aggregate(b, [0], [AggSpec("sum", 1)], partial_schema)

    def post(b):
        return groupby_aggregate(b, [0], [AggSpec("sum", 1)], partial_schema)

    step = make_hash_exchange_step(mesh, key_ordinals=[0], pre=pre, post=post)
    outs = unstack_batch(step(stack_batches(shards)))

    got = {}
    for o in outs:
        d = o.to_pydict()
        for k, s in zip(d["k"], d["s"]):
            assert k not in got, "key owned by two devices"
            got[k] = s
    want = {}
    for sh in shards:
        d = sh.to_pydict()
        for k, v in zip(d["k"], d["v"]):
            want[k] = want.get(k, 0) + v
    assert got == want
