"""ANSI mode tests (ref: AnsiCastOpSuite + the ANSI arithmetic gating
in arithmetic.scala / GpuCast.scala:166): with
spark.rapids.tpu.sql.ansi.enabled, overflowing arithmetic and
invalid/overflowing casts RAISE on BOTH engines; with it off, legacy
wrap/NULL semantics are unchanged."""

import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import get_conf
from spark_rapids_tpu.exprs.base import AnsiError
from spark_rapids_tpu.exprs.cast import Cast
from spark_rapids_tpu.session import TpuSession, col
from tests.differential import assert_tpu_cpu_equal

I64MAX = (1 << 63) - 1
I64MIN = -(1 << 63)


@pytest.fixture
def session():
    return TpuSession()


@pytest.fixture
def ansi():
    conf = get_conf()
    conf.set("spark.rapids.tpu.sql.ansi.enabled", True)
    yield
    conf.set("spark.rapids.tpu.sql.ansi.enabled", False)


def _df(session, **cols):
    return session.create_dataframe(pa.table(
        {k: pa.array(v) for k, v in cols.items()}))


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_add_overflow_raises(session, ansi, engine):
    df = _df(session, a=[1, I64MAX], b=[1, 1])
    with pytest.raises(AnsiError, match="long overflow"):
        df.select((col("a") + col("b")).alias("s")).collect(
            engine=engine)


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_subtract_overflow_raises(session, ansi, engine):
    df = _df(session, a=[0, I64MIN], b=[5, 1])
    with pytest.raises(AnsiError, match="long overflow"):
        df.select((col("a") - col("b")).alias("s")).collect(
            engine=engine)


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_multiply_overflow_raises(session, ansi, engine):
    df = _df(session, a=[2, 1 << 62], b=[3, 4])
    with pytest.raises(AnsiError, match="long overflow"):
        df.select((col("a") * col("b")).alias("s")).collect(
            engine=engine)


def test_no_overflow_passes_in_ansi(session, ansi):
    df = _df(session, a=[1, 2, None], b=[10, 20, 30])
    out = df.select((col("a") + col("b")).alias("s"),
                    (col("a") * col("b")).alias("p"))
    assert_tpu_cpu_equal(out)


def test_overflow_wraps_when_ansi_off(session):
    """Legacy mode: Java wrap-around semantics, both engines agree."""
    df = _df(session, a=[I64MAX], b=[1])
    out = df.select((col("a") + col("b")).alias("s"))
    assert_tpu_cpu_equal(out)
    got = out.collect(engine="tpu").to_pydict()["s"]
    assert got == [I64MIN]  # wrapped


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_ansi_cast_float_to_int_overflow_raises(session, ansi, engine):
    df = _df(session, x=[1.5, 3.1e9])
    with pytest.raises(AnsiError, match="overflow"):
        df.select(Cast(col("x"), T.INT).alias("i")).collect(
            engine=engine)


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_ansi_cast_nan_to_int_raises(session, ansi, engine):
    df = _df(session, x=[1.0, float("nan")])
    with pytest.raises(AnsiError):
        df.select(Cast(col("x"), T.LONG).alias("i")).collect(
            engine=engine)


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_ansi_cast_long_to_int_narrowing_raises(session, ansi, engine):
    df = _df(session, x=[5, 1 << 40])
    with pytest.raises(AnsiError, match="overflow"):
        df.select(Cast(col("x"), T.INT).alias("i")).collect(
            engine=engine)


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_ansi_cast_malformed_string_raises(session, ansi, engine):
    df = session.create_dataframe(pa.table(
        {"s": pa.array(["12", "x9", "34"])}))
    with pytest.raises(AnsiError, match="invalid input"):
        df.select(Cast(col("s"), T.LONG).alias("i")).collect(
            engine=engine)


def test_legacy_cast_matches_across_engines(session):
    """ANSI off: saturation + NULL-on-malformed, engines agree."""
    df = _df(session, x=[1.5, 3.1e9, float("nan"), -2.9])
    out = df.select(Cast(col("x"), T.INT).alias("i"))
    assert_tpu_cpu_equal(out)
    df2 = session.create_dataframe(pa.table(
        {"s": pa.array(["12", "x9", None, "-7"])}))
    out2 = df2.select(Cast(col("s"), T.LONG).alias("i"))
    assert_tpu_cpu_equal(out2)


def test_ansi_valid_casts_still_work(session, ansi):
    df = _df(session, x=[1.0, -3.7, 2000000.2])
    out = df.select(Cast(col("x"), T.INT).alias("i"))
    assert_tpu_cpu_equal(out)
    df2 = session.create_dataframe(pa.table(
        {"s": pa.array([" 12 ", "-7", None])}))
    out2 = df2.select(Cast(col("s"), T.LONG).alias("i"))
    assert_tpu_cpu_equal(out2)


def test_null_rows_never_trigger_ansi_errors(session, ansi):
    """Error conditions on NULL inputs must not raise (valid-row
    gating)."""
    df = session.create_dataframe(pa.table({
        "a": pa.array([None, 5], pa.int64()),
        "b": pa.array([I64MAX, 7], pa.int64())}))
    out = df.select((col("a") + col("b")).alias("s"))
    assert_tpu_cpu_equal(out)
    df2 = session.create_dataframe(pa.table(
        {"s": pa.array([None, "33"])}))
    out2 = df2.select(Cast(col("s"), T.LONG).alias("i"))
    assert_tpu_cpu_equal(out2)


@pytest.mark.parametrize("engine", ["tpu", "cpu"])
def test_ansi_divide_by_zero_raises(session, ansi, engine):
    df = _df(session, a=[10, 7], b=[2, 0])
    with pytest.raises(AnsiError, match="Division by zero"):
        df.select((col("a") / col("b")).alias("q")).collect(
            engine=engine)


def test_divide_by_zero_nulls_when_ansi_off(session):
    df = _df(session, a=[10, 7], b=[2, 0])
    out = df.select((col("a") / col("b")).alias("q"))
    assert_tpu_cpu_equal(out)
    assert out.collect(engine="tpu").to_pydict()["q"] == [5.0, None]


def test_ansi_risky_expr_outside_fused_positions_falls_back(session,
                                                            ansi):
    """Sort keys (etc.) can't capture ANSI flags on device: the
    planner must route such plans to the CPU engine, which raises —
    the engines never silently diverge."""
    df = _df(session, a=[1, I64MAX], b=[3, 1])
    q = df.order_by(col("a") + col("b"))
    from spark_rapids_tpu.plan.planner import plan_query

    exec_, meta = plan_query(q._plan, session.conf)
    assert not meta.can_replace or "CpuFallback" in exec_.tree_string()
    with pytest.raises(AnsiError):
        q.collect(engine="tpu")


def test_ansi_long_to_int_pure_integer_check(session, ansi):
    """Regression: a long beyond 2^53 must raise AnsiError, not a raw
    pyarrow error from a float64 round-trip."""
    from spark_rapids_tpu import types as T2

    df = _df(session, x=[1 << 62])
    with pytest.raises(AnsiError, match="overflow"):
        df.select(Cast(col("x"), T2.INT).alias("i")).collect(
            engine="cpu")
