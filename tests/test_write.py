"""Write-path tests: round trips, save modes, dynamic partitioning.

Mirrors the reference's ParquetWriterSuite / partitioned-write coverage
(ref: tests/.../ParquetWriterSuite.scala, GpuFileFormatDataWriter)."""

import os

import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession, col, sum_


@pytest.fixture
def session():
    return TpuSession()


def _sample_table(n=100):
    import numpy as np

    rng = np.random.default_rng(5)
    return pa.table({
        "i": pa.array(rng.integers(0, 1000, n), pa.int64()),
        "f": pa.array(rng.random(n), pa.float64()),
        "s": pa.array([None if x % 7 == 0 else f"s✓{x % 13}"
                       for x in range(n)], pa.string()),
    })


def _sorted(t: pa.Table) -> list:
    return sorted(t.to_pylist(),
                  key=lambda r: (str(r.get("i")), str(r.get("f"))))


def test_parquet_round_trip(session, tmp_path):
    t = _sample_table()
    df = session.create_dataframe(t)
    stats = df.write_parquet(str(tmp_path / "out"))
    assert stats.num_rows == t.num_rows
    assert stats.num_files >= 1 and stats.num_bytes > 0
    assert (tmp_path / "out" / "_SUCCESS").exists()
    back = session.read_parquet(str(tmp_path / "out")).collect()
    assert _sorted(back) == _sorted(t)


def test_csv_round_trip(session, tmp_path):
    t = pa.table({"i": pa.array([1, 2, 3], pa.int64()),
                  "f": pa.array([0.5, 1.5, -2.0], pa.float64())})
    session.create_dataframe(t).write_csv(str(tmp_path / "out"))
    back = session.read_csv(str(tmp_path / "out")).collect()
    assert _sorted(back) == _sorted(t)


def test_write_query_result_multi_partition(session, tmp_path):
    """Write the OUTPUT of a query over a multi-file scan: one part file
    per scan partition, all rows preserved."""
    import pyarrow.parquet as pq

    src = tmp_path / "src"
    os.makedirs(src)
    # defeat small-file coalescing: this test wants one task per file
    session.conf.set("spark.rapids.tpu.sql.scan.taskTargetBytes", 1)
    tables = []
    for i in range(3):
        t = _sample_table(50)
        pq.write_table(t, str(src / f"f{i}.parquet"))
        tables.append(t)
    full = pa.concat_tables(tables)
    df = session.read_parquet(str(src)).where(col("i") >= col("i"))
    stats = df.write_parquet(str(tmp_path / "out"))
    assert stats.num_rows == full.num_rows
    assert stats.num_files == 3  # one per scan partition
    back = session.read_parquet(str(tmp_path / "out")).collect()
    assert _sorted(back) == _sorted(full)


def test_save_modes(session, tmp_path):
    t = pa.table({"x": pa.array([1, 2], pa.int64())})
    df = session.create_dataframe(t)
    p = str(tmp_path / "out")
    df.write_parquet(p)
    with pytest.raises(FileExistsError):
        df.write_parquet(p)
    assert df.write.mode("ignore").parquet(p) is None
    df.write.mode("append").parquet(p)
    assert session.read_parquet(p).collect().num_rows == 4
    df.write.mode("overwrite").parquet(p)
    assert session.read_parquet(p).collect().num_rows == 2


def test_partitioned_write_and_discovery(session, tmp_path):
    t = pa.table({
        "k": pa.array([1, 1, 2, 2, 3], pa.int64()),
        "name": pa.array(["a", "b", "a", "c", None], pa.string()),
        "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0], pa.float64()),
    })
    p = str(tmp_path / "out")
    stats = session.create_dataframe(t).write.partition_by("k").parquet(p)
    assert stats.partitions == 3
    assert os.path.isdir(os.path.join(p, "k=1"))
    # partition columns come back as trailing columns with inferred type
    back = session.read_parquet(p).collect()
    assert back.schema.names[-1] == "k"
    assert back.schema.field("k").type == pa.int64()
    assert sorted(zip(back.to_pydict()["v"], back.to_pydict()["k"])) == \
        [(1.0, 1), (2.0, 1), (3.0, 2), (4.0, 2), (5.0, 3)]
    # differential: CPU engine sees the same partitioned relation
    df = session.read_parquet(p)
    cpu = df.collect(engine="cpu")
    assert _sorted(back) == _sorted(cpu)
    # query over partition column incl. pruned projection
    agg = (session.read_parquet(p).group_by(col("k"))
           .agg((sum_(col("v")), "sv")).collect().to_pydict())
    got = dict(zip(agg["k"], agg["sv"]))
    assert got == {1: 3.0, 2: 7.0, 3: 5.0}
    only_k = session.read_parquet(p, columns=["k"]).collect()
    assert sorted(only_k.to_pydict()["k"]) == [1, 1, 2, 2, 3]


def test_partitioned_write_null_and_string_values(session, tmp_path):
    t = pa.table({
        "cat": pa.array(["x/y", None, "plain"], pa.string()),
        "v": pa.array([1, 2, 3], pa.int64()),
    })
    p = str(tmp_path / "out")
    session.create_dataframe(t).write.partition_by("cat").parquet(p)
    back = session.read_parquet(p).collect().to_pydict()
    assert sorted(zip(back["v"], [c for c in back["cat"]]),
                  key=lambda x: x[0]) == [
        (1, "x/y"), (2, None), (3, "plain")]


def test_empty_write_round_trip(session, tmp_path):
    t = pa.table({"x": pa.array([], pa.float64())})
    p = str(tmp_path / "out")
    session.create_dataframe(t).write_parquet(p)
    back = session.read_parquet(p).collect()
    assert back.num_rows == 0
    assert back.schema.names == ["x"]


def test_csv_partitioned_round_trip(session, tmp_path):
    t = pa.table({"k": pa.array([1, 1, 2], pa.int64()),
                  "v": pa.array([1.5, 2.5, 3.5], pa.float64())})
    p = str(tmp_path / "out")
    session.create_dataframe(t).write.partition_by("k").csv(p)
    back = session.read_csv(p).collect()
    assert sorted(zip(back.to_pydict()["v"], back.to_pydict()["k"])) == [
        (1.5, 1), (2.5, 1), (3.5, 2)]
    cpu = session.read_csv(p).collect(engine="cpu")
    assert _sorted(back) == _sorted(cpu)


def test_partitioned_write_nan_value(session, tmp_path):
    import math

    t = pa.table({"k": pa.array([1.0, float("nan"), 2.0], pa.float64()),
                  "v": pa.array([1, 2, 3], pa.int64())})
    p = str(tmp_path / "out")
    stats = session.create_dataframe(t).write.partition_by("k").parquet(p)
    assert stats.num_rows == 3
    back = session.read_parquet(p).collect().to_pydict()
    assert sorted(back["v"]) == [1, 2, 3]  # the NaN row must survive
    kv = dict(zip(back["v"], back["k"]))
    assert math.isnan(float(kv[2]))
